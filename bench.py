#!/usr/bin/env python
"""Benchmark: chisq-grid fit throughput at the reference's baseline scale.

Headline workload (reference ``profiling/bench_chisq_grid.py:14-34`` /
BASELINE.md): a GLS fitter refit per grid point over an M2 x SINI grid on the
NANOGrav B1855+09 9-yr dataset — 4,005 TOAs, DD binary, 120+ DMX windows,
EFAC/EQUAD/ECORR per backend, power-law red noise (90 Fourier basis columns).
The reference takes ~19.6 s per grid-point fit on an i7-6700K core
(0.057 fits/s, BASELINE.md "Derived headline").

TOAs are *simulated at the real tim file's epochs/frequencies/errors/flags*
(``make_fake_toas_fromtim``) because this image ships no JPL ephemeris kernel
— with the built-in analytic ephemeris the real TOAs are dominated by ~ms
Earth-position systematics that push the fit nonphysical (SINI > 1).  The
workload shape (TOA count, mask structure, noise bases, free parameters) is
identical to the reference benchmark's; per-fit cost does not depend on the
residual values.

Prints ONE JSON line:

    {"metric": "gls_chisq_grid_evals_per_sec", "value": N, "unit": "fits/s",
     "vs_baseline": N / 0.057}

plus a per-stage timing table on stderr (ingest, simulate, fit, compile,
grid) and a secondary NGC6440E WLS-grid number for continuity with r01/r02.
"""

import json
import os
import platform as _platform_mod
import sys
import time

import numpy as np

BASELINE_FITS_PER_SEC = 0.057
DATADIR = "/root/reference/tests/datafile"
B1855_PAR = f"{DATADIR}/B1855+09_NANOGrav_9yv1.gls.par"
B1855_TIM = f"{DATADIR}/B1855+09_NANOGrav_9yv1.tim"
NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"

FALLBACK_PAR = """\
PSR              BENCH6440E
RAJ       17:48:52.75  1
DECJ      -20:21:29.0  1
F0       61.485476554  1
F1         -1.181D-15  1
PEPOCH        53750.000000
POSEPOCH      53750.000000
DM              223.9  1
EPHEM               DE421
CLK              TT(BIPM2019)
UNITS               TDB
TZRMJD  53801.38605120074849
TZRFRQ            1949.609
TZRSITE                  1
"""


#: lazily-built Stages class (bench keeps ALL pint_tpu/jax imports out of
#: module scope so the fast error-emit paths never pay the package import)
_STAGES_CLS = None


def Stages():
    """Bench stage table: telemetry-backed StageTimer (one shared
    mark/stage clock, rows mirrored into the span tree when telemetry is
    on) with the bench's historical table format kept byte-identical so
    BENCH_NOTES.md comparisons still line up."""
    global _STAGES_CLS
    if _STAGES_CLS is None:
        from pint_tpu.profiling import StageTimer

        class _Stages(StageTimer):
            def table(self, title):
                lines = [f"# --- {title} stage timings ---"]
                for name, dt in self.rows:
                    lines.append(f"#   {name:<28s} {dt:8.2f} s")
                return "\n".join(lines)

        _STAGES_CLS = _Stages
    return _STAGES_CLS()


def cache_key(backend: str) -> str:
    """Persistent-cache directory key.  CPU entries are keyed by the host's
    actual CPU feature set: AOT artifacts compiled on another
    microarchitecture must never replay locally (SIGILL hazard seen in r03
    — and every container reports hostname 'vm', so the old hostname key
    isolated nothing).  TPU entries are NOT host-keyed: they are compiled
    for (and by) the accelerator behind the tunnel, and a per-container key
    would cold-start every session (~7-10 min remote recompile, r04)."""
    key = f"{backend}-{_platform_mod.machine()}"
    if backend not in ("tpu", "axon"):
        import hashlib

        try:
            with open("/proc/cpuinfo") as f:
                # x86 spells the ISA line 'flags'; aarch64 'Features'
                flags = next(ln for ln in f
                             if ln.startswith(("flags", "Features")))
            key += "-" + hashlib.sha1(flags.encode()).hexdigest()[:8]
        except (OSError, StopIteration):
            key += f"-{_platform_mod.node()}"
    return key


#: cache dir whose enabling is deferred until the CPU-pinned simulation is
#: done (TPU-backend runs only; see main())
_PENDING_CACHE_DIR = []


def _enable_persistent_cache():
    if not _PENDING_CACHE_DIR:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", _PENDING_CACHE_DIR[0])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    del _PENDING_CACHE_DIR[:]


def bench_b1855_gls():
    """Headline: GLS chisq grid on the 4k-TOA correlated-noise workload."""
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.grid import grid_chisq
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromtim

    st = Stages()
    model = get_model(B1855_PAR)
    st.mark("parse par (91 free params)")
    rng = np.random.default_rng(20260729)
    # simulate on the host CPU backend: zero_residuals iterates phase evals
    # whose compiles/dispatches cost minutes through the remote-TPU tunnel;
    # a throwaway model copy keeps CPU-placed device buffers out of the
    # timed model's cache (TOAs themselves are host numpy either way)
    import copy as _copy

    import jax as _jax

    try:
        _cpu = _jax.devices("cpu")[0]
    except RuntimeError:
        _cpu = None
    if _cpu is not None and _jax.default_backend() != "cpu":
        with _jax.default_device(_cpu):
            toas = make_fake_toas_fromtim(B1855_TIM, _copy.deepcopy(model),
                                          add_noise=True, rng=rng)
    else:
        toas = make_fake_toas_fromtim(B1855_TIM, model, add_noise=True,
                                      rng=rng)
    st.mark("ingest tim + simulate TOAs")
    # the simulation above compiled CPU executables (host-pinned); only now
    # is it safe to turn on the un-hostnamed TPU cache dir (see main())
    _enable_persistent_cache()

    f = GLSFitter(toas, model)
    chi2_fit = f.fit_toas(maxiter=2)
    st.mark("initial GLS fit (2 iter)")

    npts = 16  # 16x16 = 256 grid fits
    dm2 = 3 * (float(model.M2.uncertainty or 0.011))
    dsini = 3 * (float(model.SINI.uncertainty or 1.8e-4))
    g_m2 = np.linspace(model.M2.value - dm2, model.M2.value + dm2, npts)
    g_sini = np.linspace(model.SINI.value - dsini,
                         min(0.999999, model.SINI.value + dsini), npts)

    # niter=1 Gauss-Newton per point == the reference benchmark's per-point
    # work exactly (its per-point GLSFitter does one linearized solve,
    # profiling/bench_chisq_grid.py).  One solve is also CONVERGED here:
    # every fit column classifies linear on this workload, so the GN step
    # is the exact linear-system solution — measured on the v5e, niter=1
    # and niter=2 give the same argmin and grid-min chi2 to 2e-5 relative
    # (3965.978 / 3965.994 vs converged fit 3965.962).  The linearity
    # assumption is NOT trusted blindly: the sanity check below uses a
    # convergence-grade ~5-chi2-unit tolerance that an under-converged
    # surface (tens of units) cannot pass.  (Runs before 2026-08-01 used
    # niter=2; the r05 progression up to 195.3 fits/s is on that basis.)
    niter = 1
    # chunk 256 = one executable invocation for the whole 16x16 grid: the
    # round-5 on-TPU sweep measured 106.9 fits/s vs 101.5 (128) / 96.3 (64)
    # at exactly this workload; must match between the warm and timed calls
    # (the chunk is part of the executable cache key)
    chunk = 256
    # warmup grid: 2 corner points spanning the FULL grid range, so both the
    # chunked executable and the linear-column classification (cached by
    # span) are reused verbatim inside the timed region
    warm = (g_m2[[0, -1]], g_sini[[0, -1]])
    t_c = time.time()
    grid_chisq(f, ("M2", "SINI"), warm, niter=niter, chunk=chunk)
    compile_s = time.time() - t_c
    st.mark("compile (chunked grid fn)")

    t0 = time.time()
    chi2, _ = grid_chisq(f, ("M2", "SINI"), (g_m2, g_sini), niter=niter,
                         chunk=chunk)
    chi2 = np.asarray(chi2)
    elapsed = time.time() - t0
    st.mark("grid 16x16 (256 GLS fits)")

    # AOT cost attribution for the grid executable just measured.  The
    # analysis lower/compile does NOT hit jit's dispatch cache — only
    # the persistent compilation cache (enabled above for every backend,
    # min_compile_time 1 s) keeps this from being a second full grid
    # compile; it runs AFTER the timed region either way, with the
    # jaxevents accounting paused so the telemetry block's compile
    # counters describe the workload, not the analysis.  The result
    # degrades to explicit nulls where the backend reports nothing.
    from pint_tpu.telemetry import costs as _costs

    cost = _costs.profile_grid(f).to_dict()

    # warm-serving measurement (ROADMAP item 2): pre-warm the production
    # executables through the AOT cache (populating it when enabled, so
    # the NEXT process loads instead of compiling), then serve a steady-
    # state batch of fit requests through the shape-bucketed batcher and
    # report throughput + latency percentiles.  Never fatal: a broken
    # serving layer degrades to an errored-but-present warm block.
    try:
        warm = warm_serving_block(f)
    except Exception as e:
        # the degraded block carries the same key set as a successful
        # one (explicit nulls) — consumers never branch on shape
        warm = {"cache_hits": 0, "cold_compiles": 0,
                "warm_fits_per_s": None, "p50_ms": None, "p99_ms": None,
                "steady_state_compiles": None, "bucket": None,
                "chi2": None, "aot_cache": None,
                "error": f"{type(e).__name__}: {e}"}
    st.mark("warm-serving measurement")

    # cost-model autotune measurement (ROADMAP item 5): rank a small
    # chunk-candidate set by AOT cost analysis, measure-confirm the
    # winner AND the headline's static chunk on this same grid, and
    # stamp tuned fits/s + the tuned/static ratio perfwatch gates.
    # Never fatal: a broken tuner degrades to an errored-but-present
    # tuned block (the warm{} discipline).
    try:
        tuned = tuned_block(f, g_m2, g_sini, niter=niter,
                            static_chunk=chunk)
    except Exception as e:
        tuned = {"chunk": None, "static_chunk": chunk,
                 "tuned_fits_per_s": None, "static_fits_per_s": None,
                 "tuned_vs_static": None, "basis": None,
                 "decisions": None,
                 "error": f"{type(e).__name__}: {e}"}
    st.mark("autotune measurement")

    # mixed-precision measurement (ROADMAP item 4): resolve the active
    # precision policy per segment, serve the same linearized-fit batch
    # under a forced-f64 override and under the active policy, and
    # stamp throughput for both + the measured mixed-vs-f64
    # disagreement.  Default (no manifest) is bit-identical f64:
    # reduced_count 0, max_rel_err 0.0.  Never fatal: a broken
    # precision layer degrades to an errored-but-present block.
    try:
        prec = precision_block(f)
    except Exception as e:
        prec = {"segments": None, "reduced_count": None,
                "f64_count": None, "mixed_fits_per_s": None,
                "f64_fits_per_s": None, "mixed_vs_f64": None,
                "max_rel_err": None,
                "error": f"{type(e).__name__}: {e}"}
    st.mark("precision measurement")

    # PTA catalog measurement (ROADMAP item 1): fit a ragged synthetic
    # multi-pulsar catalog as one batched program per bucket and
    # evaluate the joint Hellings-Downs lnlikelihood over a walker
    # batch.  Never fatal: a broken catalog engine degrades to an
    # errored-but-present catalog block (the warm{}/tuned{} discipline).
    try:
        catalog = catalog_block()
    except Exception as e:
        catalog = {"n_pulsars": None, "buckets": None,
                   "pad_waste_frac": None, "catalog_fits_per_s": None,
                   "joint_lnlike_per_s": None,
                   "steady_state_compiles": None,
                   "error": f"{type(e).__name__}: {e}"}
    st.mark("catalog measurement")

    # amortized-inference measurement (ROADMAP item 3): train a small
    # normalizing flow against a vectorizable Bayesian timing
    # posterior and serve draws + log-prob queries through the
    # TimingService posterior door.  Never fatal: a broken amortized
    # engine degrades to an errored-but-present posterior block.
    try:
        posterior = posterior_block()
    except Exception as e:
        posterior = {"train_steps": None, "elbo_final": None,
                     "draws_per_s": None, "logprob_per_s": None,
                     "p50_ms": None, "p99_ms": None,
                     "steady_state_compiles": None,
                     "error": f"{type(e).__name__}: {e}"}
    st.mark("posterior measurement")

    # phase-prediction measurement (ROADMAP serving item): a warmed
    # PredictorCache served through the TimingService predict door —
    # coalesced batches for throughput, single-request probes for the
    # latency distribution, with the settle pass paying every lazy
    # window generation outside the measured window.  Never fatal,
    # same degraded-block discipline.
    try:
        predict = predict_block()
    except Exception as e:
        predict = {"windows": None, "predicts_per_s": None,
                   "cache_hit_rate": None,
                   "p50_ms": None, "p99_ms": None,
                   "steady_state_compiles": None,
                   "error": f"{type(e).__name__}: {e}"}
    st.mark("predict measurement")

    # work-per-byte scaling accounting (ROADMAP item 2): fused-dispatch
    # rate measured live, efficiency/scatter bytes restamped from the
    # newest committed scalewatch series.  Never fatal, same degraded-
    # block discipline.
    try:
        scaling = scaling_block()
    except Exception as e:
        scaling = {"efficiency_at_max": None, "dispatch_per_s": None,
                   "scatter_bytes": None,
                   "error": f"{type(e).__name__}: {e}"}
    st.mark("scaling measurement")

    # streaming-update measurement (ROADMAP item 5): appended TOA
    # blocks served through the TimingService update door as rank-k
    # factor updates + warm-started refits, against the warm
    # full-refit path on the same final set.  Never fatal, same
    # degraded-block discipline.
    try:
        streaming = streaming_block()
    except Exception as e:
        streaming = {"appends": None, "update_p50_ms": None,
                     "update_p99_ms": None, "updates_per_s": None,
                     "refit_p50_ms": None, "speedup_vs_refit": None,
                     "steady_state_compiles": None,
                     "error": f"{type(e).__name__}: {e}"}
    st.mark("streaming measurement")

    # traffic-engineering measurement (ROADMAP item 3): the closed-loop
    # load harness drives the live service to saturation under a 4:1
    # fit:posterior overload mix and proves the SLO / shed / fairness
    # contract.  Never fatal, same degraded-block discipline.
    try:
        load = load_block()
    except Exception as e:
        load = {"arrival": None, "offered": None, "capacity_rps": None,
                "offered_rps": None, "fit_rps": None,
                "posterior_rps": None, "fit_p99_ms": None,
                "posterior_p99_ms": None, "posterior_slo_ms": None,
                "shed_rate": None, "fairness": None,
                "steady_state_compiles": None,
                "error": f"{type(e).__name__}: {e}"}
    st.mark("load measurement")

    # request-lifecycle observatory measurement: trace overhead on the
    # warm path, per-class SLO compliance + burn from the service's
    # own health snapshot, and the breaker-open -> postmortem-bundle
    # contract.  Never fatal, same degraded-block discipline.
    try:
        slo = slo_block()
    except Exception as e:
        slo = {"untraced_fits_per_s": None, "traced_fits_per_s": None,
               "trace_overhead_frac": None, "fit_compliance": None,
               "posterior_compliance": None, "worst_burn_rate": None,
               "postmortems_emitted": None,
               "steady_state_compiles": None,
               "error": f"{type(e).__name__}: {e}"}
    st.mark("slo measurement")

    # durability measurement (ROADMAP robustness item): crash
    # mid-stream with the update journal live, recover a fresh
    # service bitwise from the journal tail, then drill it under
    # open-loop load with injected device loss.  Never fatal, same
    # degraded-block discipline.
    try:
        recovery = recovery_block()
    except Exception as e:
        recovery = {"ops_journaled": None, "time_to_recover_s": None,
                    "replay_ops_per_s": None, "bitwise_match": None,
                    "rps_under_fault": None,
                    "p99_under_fault_ms": None,
                    "stranded_futures": None, "drill_recovery_s": None,
                    "scenario": None,
                    "error": f"{type(e).__name__}: {e}"}
    st.mark("recovery measurement")

    imin = np.unravel_index(np.argmin(chi2), chi2.shape)
    # convergence-grade sanity, not just order-of-magnitude: the measured
    # grid-min-vs-fit gap is ~0.02 chi2 units (pure grid discretization);
    # an under-converged niter=1 surface (a fit column going nonlinear)
    # would miss by tens of units, so the tolerance is an absolute ~5
    # units (1e-3 relative floor for scale changes), 250x the measured
    # gap and far below any under-convergence signature
    tol = max(5.0, 1e-3 * chi2_fit)
    ok = bool(np.isfinite(chi2).all()) and abs(chi2.min() - chi2_fit) < tol
    return {
        "fits_per_sec": chi2.size / elapsed,
        "elapsed": elapsed,
        "ntoas": len(toas),
        "nfree": len(model.free_params),
        "grid_points": int(chi2.size),
        "compile_s": compile_s,
        "chi2_fit": chi2_fit,
        "chi2_min": float(chi2.min()),
        "imin": tuple(int(i) for i in imin),
        "ok": ok,
        "stages": st,
        "cost": cost,
        "warm": warm,
        "tuned": tuned,
        "precision": prec,
        "catalog": catalog,
        "posterior": posterior,
        "predict": predict,
        "scaling": scaling,
        "streaming": streaming,
        "load": load,
        "slo": slo,
        "recovery": recovery,
    }


#: steady-state serve batch: 8 requests coalesce onto one padded batched
#: executable at the default batch ladder (8x4096xK f64 operands stay
#: well under device memory at B1855 scale)
WARM_SERVE_REQUESTS = 8
#: additional single-request passes so p50/p99 are percentiles of a real
#: per-dispatch latency DISTRIBUTION (one coalesced pass alone records
#: the identical wall for every member — p99 would just mirror fits/s)
WARM_LATENCY_PROBES = 12


def warm_serving_block(f):
    """The headline's ``warm{}`` block: pre-warm the fit-step /
    GLS-solve / grid-chunk executables through the AOT cache
    (:mod:`pint_tpu.serving`), then serve a coalesced batch of
    linearized fit requests and measure warm-start throughput and
    latency percentiles.

    ``cache_hits`` / ``cold_compiles`` count the warm pool's per-
    executable provenance: on the first run with
    ``PINT_TPU_AOT_CACHE_DIR`` set everything is a cold compile (and is
    stored), on the next process-equivalent run the same executables
    load from the cache.  ``steady_state_compiles`` is the JAX
    accounting delta over the timed serving pass — the ``compiles=0``
    proof the ROADMAP asks for, measured, not asserted."""
    from pint_tpu.serving import (FitRequest, TimingService, WarmPool,
                                  warm_fitter)
    from pint_tpu.serving import aotcache as _aotcache
    from pint_tpu.telemetry import jaxevents

    cache = None
    try:
        cache = _aotcache.cache()
    except Exception as e:
        print(f"# AOT cache unusable, serving uncached: {e}",
              file=sys.stderr)
    pool = WarmPool(cache=cache)
    # production executables: populate/load the cache for the expensive
    # cold-start stages (fit step, GLS solve, the chunked grid program)
    _, prod_report = warm_fitter(f, pool=pool)

    svc = TimingService(pool=pool)
    req = FitRequest.from_fitter(f)
    bn, bk = svc.batcher.bucket_for(req)
    # both serve executables: the coalesced throughput batch AND the
    # single-request shape the latency probes dispatch
    serve_report = svc.warm([(WARM_SERVE_REQUESTS, bn, bk), (1, bn, bk)])

    def _req(i):
        return FitRequest(M=req.M, r=req.r, w=req.w, phiinv=req.phiinv,
                          params=req.params, norm=req.norm,
                          request_id=f"bench-{i}")

    before = jaxevents.counts()
    t0 = time.time()
    results = svc.serve([_req(i) for i in range(WARM_SERVE_REQUESTS)])
    elapsed = time.time() - t0
    # per-dispatch latency distribution: repeated single-request passes,
    # each its own wall clock, so p99 is a tail signal independent of
    # the coalesced-batch throughput above
    for i in range(WARM_LATENCY_PROBES):
        svc.serve([_req(f"lat-{i}")])
    steady = jaxevents.counts() - before
    lat = svc.latency_summary()
    return {
        "cache_hits": prod_report.cache_hits + serve_report.cache_hits,
        "cold_compiles": prod_report.cold_compiles
        + serve_report.cold_compiles,
        "warm_fits_per_s": round(len(results) / elapsed, 3)
        if elapsed > 0 else None,
        "p50_ms": round(lat["p50_ms"], 3),
        "p99_ms": round(lat["p99_ms"], 3),
        "steady_state_compiles": int(steady.compiles),
        "bucket": [WARM_SERVE_REQUESTS, bn, bk],
        "chi2": round(float(results[0].chi2), 3),
        "aot_cache": cache.stats.to_dict() if cache is not None else None,
    }


#: chunk candidates the bench's tuned block cost-ranks: the static
#: per-backend default and the headline's hand-picked chunk always
#: participate; one extra rung below keeps the ranking honest without
#: paying a long ladder of AOT analysis compiles inside the bench
TUNED_EXTRA_CHUNKS = (64,)


def tuned_block(f, g_m2, g_sini, niter, static_chunk):
    """The headline's ``tuned{}`` block: run the cost-model autotuner's
    chunk search on the measured grid (cost-rank a small candidate set,
    measure-confirm the top candidate and the static chunk) and report
    tuned fits/s next to the static number.

    ``tuned_vs_static`` >= 1.0 is structural: the static chunk is
    always in the measured-confirmation set and the winner is the
    measured argmax, so the tuned configuration can tie the static one
    but never lose to it — perfwatch still gates the ratio so a broken
    search cannot ship a slower decision silently.  The decision
    persists into the tuning manifest when ``PINT_TPU_TUNE_DIR`` is
    configured (the cross-process ``chunk="auto"`` source)."""
    from pint_tpu import autotune
    from pint_tpu.grid import default_gls_chunk

    pts = np.stack([g.ravel() for g in
                    np.meshgrid(g_m2, g_sini, indexing="ij")], axis=-1)
    chunks = sorted({default_gls_chunk(), int(static_chunk),
                     *TUNED_EXTRA_CHUNKS})
    manifest = autotune.manifest()  # None when tuning is unconfigured
    dec = autotune.tune_grid_chunk(
        f, ("M2", "SINI"), pts, chunks=chunks, niter=niter, top_k=1,
        static=int(static_chunk), tuning_manifest=manifest)
    measured = {int(k): v for k, v in dec.measured.items()
                if isinstance(v, (int, float))}
    tuned_fps = measured.get(int(dec.value))
    static_fps = measured.get(int(static_chunk))
    if tuned_fps is None or not static_fps:
        # the never-slower gate needs BOTH numbers: a block without
        # the ratio must be a loud degraded block (perfwatch fails it
        # when prior rounds measured tuning), never a silent skip
        raise RuntimeError(
            f"measured confirmation incomplete: tuned chunk "
            f"{dec.value} -> {tuned_fps}, static chunk {static_chunk} "
            f"-> {static_fps} (confirmed: {sorted(measured)})")
    ratio = tuned_fps / static_fps
    decisions = manifest.digest() if manifest is not None else None
    if decisions is None:
        import hashlib

        decisions = hashlib.sha256(json.dumps(
            dec.to_dict(), sort_keys=True, default=str
        ).encode()).hexdigest()[:12]
    return {
        "chunk": int(dec.value),
        "static_chunk": int(static_chunk),
        "tuned_fits_per_s": round(tuned_fps, 3),
        "static_fits_per_s": round(static_fps, 3),
        "tuned_vs_static": round(ratio, 4),
        "basis": dec.basis,
        "decisions": decisions,
    }


#: precision-block serve batch: same coalesced shape as the warm block,
#: measured twice (forced f64 vs the active policy)
PRECISION_SERVE_REQUESTS = 8


def precision_block(f):
    """The headline's ``precision{}`` block: resolve the active
    mixed-precision policy per segment (:mod:`pint_tpu.precision`),
    then serve one coalesced linearized-fit batch under a forced-f64
    override and again under the active policy, stamping both
    throughputs, their ratio, and the measured worst mixed-vs-f64
    relative disagreement across the batch's chi2 and steps.

    With no tuning manifest and no override the active policy IS f64 —
    ``reduced_count`` 0, ``max_rel_err`` exactly 0.0 (bit-identical
    executables) — and ``tools/perfwatch.py`` gates
    ``mixed_fits_per_s`` drops and ``max_rel_err`` rises (zero-
    baseline opt-in: the first nonzero disagreement in a bit-identical
    history fails the gate rather than slipping in silently)."""
    from pint_tpu import precision
    from pint_tpu.serving.batcher import FitRequest, ShapeBatcher

    segs = precision.describe_segments(f.model, f.toas)
    reduced = {n: s["tag"] for n, s in segs.items()
               if s["compute_dtype"] != "float64"}
    base = FitRequest.from_fitter(f)

    def _reqs():
        return [FitRequest(M=base.M, r=base.r, w=base.w,
                           phiinv=base.phiinv, params=base.params,
                           norm=base.norm, request_id=f"prec-{i}")
                for i in range(PRECISION_SERVE_REQUESTS)]

    batcher = ShapeBatcher()

    def _timed_pass():
        batcher.run(_reqs())           # settle: compile out of the clock
        t0 = time.time()
        results = batcher.run(_reqs())
        return results, time.time() - t0

    with precision.use_policy(precision.PrecisionPolicy.f64()):
        res64, f64_el = _timed_pass()
    resmix, mix_el = _timed_pass()     # the active policy
    if f64_el <= 0 or mix_el <= 0:
        raise RuntimeError(
            f"precision timing degenerate: f64 {f64_el}s, "
            f"mixed {mix_el}s")
    chi64 = np.array([r.chi2 for r in res64])
    chimix = np.array([r.chi2 for r in resmix])
    rel_chi = float(np.max(np.abs(chimix - chi64))
                    / max(float(np.max(np.abs(chi64))), 1e-300))
    dx64 = np.stack([r.dx for r in res64])
    dxmix = np.stack([r.dx for r in resmix])
    dx_scale = max(float(np.max(np.abs(dx64))), 1e-300)
    rel_dx = float(np.max(np.abs(dxmix - dx64)) / dx_scale)
    if not (np.all(np.isfinite(chimix)) and np.all(np.isfinite(dxmix))):
        raise RuntimeError("mixed-precision pass produced non-finite "
                           "results")
    f64_fps = len(res64) / f64_el
    mix_fps = len(resmix) / mix_el
    return {
        "segments": {n: s["tag"] for n, s in segs.items()},
        "reduced_count": len(reduced),
        "f64_count": len(segs) - len(reduced),
        "mixed_fits_per_s": round(mix_fps, 3),
        "f64_fits_per_s": round(f64_fps, 3),
        "mixed_vs_f64": round(mix_fps / f64_fps, 4),
        "max_rel_err": max(rel_chi, rel_dx),
    }


#: catalog-block knobs: pulsar count (env-overridable so the contract
#: test stays fast), timed fit passes, and the joint-lnlike walker batch
CATALOG_BENCH_PULSARS = 16
CATALOG_FIT_PASSES = 4
CATALOG_LNLIKE_WALKERS = 32
CATALOG_LNLIKE_REPS = 8


def catalog_block():
    """The headline's ``catalog{}`` block: ingest a ragged synthetic
    multi-pulsar catalog through the quarantine gate, fit it as one
    vmapped batched GLS program per learned bucket
    (:mod:`pint_tpu.catalog`), and evaluate the jitted joint
    Hellings-Downs lnlikelihood over a walker batch.

    ``catalog_fits_per_s`` counts whole-pulsar fits per second across
    the timed end-to-end passes (host relinearization included — this
    is the serving-shaped number); ``pad_waste_frac`` is the learned
    ladder's padding overhead; ``steady_state_compiles`` proves the
    warm buckets (0 after the settle pass).  ``tools/perfwatch.py``
    gates ``catalog_fits_per_s`` drops and ``pad_waste_frac`` rises."""
    from pint_tpu.catalog import CatalogFitter, JointLikelihood, ingest_catalog
    from pint_tpu.catalog.ingest import make_synthetic_catalog

    n = int(os.environ.get("BENCH_CATALOG_PULSARS",
                           str(CATALOG_BENCH_PULSARS)))
    report = ingest_catalog(make_synthetic_catalog(
        n_pulsars=max(2, n), seed=20260804, ntoa_range=(24, 64)))
    cf = CatalogFitter(report)
    cf.fit(maxiter=1)                      # compile + settle the state
    t0 = time.time()
    for _ in range(CATALOG_FIT_PASSES):
        res = cf.fit(maxiter=1)
    fit_elapsed = time.time() - t0

    jl = JointLikelihood(cf, n_modes=5)
    pts = np.column_stack([
        np.linspace(-16.0, -13.0, CATALOG_LNLIKE_WALKERS),
        np.full(CATALOG_LNLIKE_WALKERS, 13.0 / 3.0)])
    jl.lnlike_batch(pts)                   # compile
    t0 = time.time()
    for _ in range(CATALOG_LNLIKE_REPS):
        lnl = jl.lnlike_batch(pts)
    lnl_elapsed = time.time() - t0
    if not np.all(np.isfinite(lnl)):
        raise RuntimeError("joint lnlikelihood produced non-finite "
                           "values on the bench catalog")
    if fit_elapsed <= 0 or lnl_elapsed <= 0:
        # both throughputs or a loud degraded block: a present-but-None
        # number would slip past perfwatch's missing-quantity skip (the
        # tuned{} silent-skip hole, closed the same way)
        raise RuntimeError(
            f"catalog timing degenerate: fit {fit_elapsed}s, "
            f"lnlike {lnl_elapsed}s")
    return {
        "n_pulsars": report.n_pulsars,
        "buckets": res.n_buckets,
        "pad_waste_frac": round(float(res.pad_waste_frac), 4),
        "catalog_fits_per_s": round(
            report.n_pulsars * CATALOG_FIT_PASSES / fit_elapsed, 3),
        "joint_lnlike_per_s": round(
            CATALOG_LNLIKE_WALKERS * CATALOG_LNLIKE_REPS / lnl_elapsed,
            3),
        "steady_state_compiles": int(res.compiles),
    }


#: scaling-block knobs: the live fused-dispatch probe's catalog size
#: and scanned depth (small — the probe times dispatch rate, not
#: compute; env-overridable so the contract test stays fast)
SCALING_PROBE_PULSARS = 4
SCALING_PROBE_STEPS = 8
SCALING_PROBE_REPS = 8


def scaling_block():
    """The headline's ``scaling{}`` block — work-per-byte execution-plan
    accounting ``tools/perfwatch.py`` gates:

    * ``dispatch_per_s``: measured live — back-to-back dispatch rate of
      the scan-fused catalog executable (one bucket, ``steps`` fused
      fit steps per dispatch; a slower fused executable is a dispatch-
      amortization regression);
    * ``efficiency_at_max`` / ``scatter_bytes``: restamped from the
      newest committed ``SCALING_r*.json`` series (catalog-workload
      parallel efficiency at the top device count; the grid workload's
      reduce-scatter payload bytes) — provenance from
      ``tools/scalewatch.py``, so perfwatch trends the same numbers the
      scalewatch gate protects and a PR that commits a worse series
      trips BOTH gates."""
    from pint_tpu.catalog import CatalogFitter, ingest_catalog
    from pint_tpu.catalog.ingest import make_synthetic_catalog

    import jax

    n = int(os.environ.get("BENCH_SCALING_PULSARS",
                           str(SCALING_PROBE_PULSARS)))
    report = ingest_catalog(make_synthetic_catalog(
        n_pulsars=max(2, n), seed=20260804, ntoa_range=(24, 64)))
    cf = CatalogFitter(report)
    handles = cf.fused_bucket_executables(steps=SCALING_PROBE_STEPS,
                                          reweight="huber")
    for fn, ops in handles.values():
        jax.block_until_ready(fn(*ops))    # warm: compile outside timing
    t0 = time.time()
    out = None
    for _ in range(SCALING_PROBE_REPS):
        for fn, ops in handles.values():
            out = fn(*ops)
    jax.block_until_ready(out)
    elapsed = time.time() - t0
    dispatches = SCALING_PROBE_REPS * len(handles)
    if elapsed <= 0:
        raise RuntimeError(f"scaling probe degenerate: {elapsed}s")

    # committed-series provenance: newest catalog-workload efficiency,
    # newest grid-workload reduce-scatter bytes at the top device count
    from tools.scalewatch import collect_history

    errors: list = []
    history = collect_history([], os.path.dirname(
        os.path.abspath(__file__)), errors)
    eff = None
    scatter = None
    for doc in history:
        wl = str(doc.get("workload", ""))
        if wl == "catalog_batched_fit":
            eff = doc.get("efficiency_at_max")
        else:
            series = doc.get("series") or [{}]
            scatter = series[-1].get("collective_bytes")
    if errors:
        raise RuntimeError("scaling history unreadable: "
                           + "; ".join(errors[:2]))
    return {
        "efficiency_at_max": eff,
        "dispatch_per_s": round(dispatches / elapsed, 3),
        "scatter_bytes": scatter,
        "fused_steps": SCALING_PROBE_STEPS,
    }


#: streaming-block stand-in: a B1855-class spin + span-pinned red-noise
#: model (TNREDTSPAN keeps the Fourier basis identical across appended
#: blocks — the frame-consistency requirement; ECORR-style epoch
#: columns would grow the frame and route every append through the
#: refactor fallback, which is exactly what the streaming engine is
#: NOT for)
STREAM_PAR = """\
PSR STREAMBENCH
RAJ 04:37:15.0
DECJ -47:15:09.0
F0 173.6879 1
F1 -1.7e-15 1
PEPOCH 55000
DM 2.64
EFAC mjd 50000 60000 1.1
TNRedAmp -13.5
TNRedGam 3.5
TNRedC 8
TNREDTSPAN 6.0
UNITS TDB
"""

#: streaming-block knobs (env-overridable so the contract test stays
#: fast): base-set size, appended-block rows, timed appends, and the
#: refit repetitions the p50 comes from
STREAM_BENCH_TOAS = 1024
STREAM_BENCH_BLOCK = 16
STREAM_BENCH_APPENDS = 8
STREAM_BENCH_REFITS = 3


def streaming_block():
    """The headline's ``streaming{}`` block: serve appended TOA blocks
    through the :class:`~pint_tpu.serving.service.TimingService` update
    door (rank-k factor update + warm-started Gauss-Newton, kernels
    pre-warmed at the append-block-size ladder) and measure update
    latency percentiles against the warm full-refit path — a fresh
    :class:`~pint_tpu.gls_fitter.GLSFitter` fit of the same final
    certified set in the same warm process (new data invalidates every
    data-keyed cache, which is exactly what an append does to the
    refit path).  ``steady_state_compiles`` is the JAX accounting
    delta over the timed appends after the settle pass — the
    ``compiles=0`` proof.  ``tools/perfwatch.py`` gates
    ``updates_per_s`` drops, ``update_p99_ms`` rises, and
    ``speedup_vs_refit`` drops."""
    import copy

    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.serving import TimingService
    from pint_tpu.simulation import make_fake_toas_uniform
    from pint_tpu.streaming import UpdateRequest
    from pint_tpu.telemetry import jaxevents

    n = int(os.environ.get("BENCH_STREAM_TOAS", str(STREAM_BENCH_TOAS)))
    bs = int(os.environ.get("BENCH_STREAM_BLOCK",
                            str(STREAM_BENCH_BLOCK)))
    appends = int(os.environ.get("BENCH_STREAM_APPENDS",
                                 str(STREAM_BENCH_APPENDS)))
    model = get_model([ln + "\n" for ln in STREAM_PAR.splitlines()])
    rng = np.random.default_rng(20260804)
    toas = make_fake_toas_uniform(
        53400, 54800, n, model, freq=np.array([800.0, 1400.0]),
        error_us=1.0, add_noise=True, rng=rng)
    nbase = n - (appends + 1) * bs
    if nbase < 4 * bs:
        raise RuntimeError(
            f"streaming bench needs a base set; {n} TOAs cannot hold "
            f"{appends + 1} blocks of {bs}")
    base = toas[np.arange(nbase)]
    blocks = [toas[np.arange(nbase + bs * i, nbase + bs * (i + 1))]
              for i in range(appends + 1)]

    f = GLSFitter(base, copy.deepcopy(model))
    f.fit_toas(maxiter=2)
    svc = TimingService()
    svc.register_stream(f, block_sizes=[bs])
    # settle pass: the first append of this block shape pays the
    # per-shape ingestion compiles (phase eval at the block size);
    # steady state is everything after it
    svc.serve_updates([UpdateRequest(new_toas=blocks[0],
                                     request_id="settle")])
    before = jaxevents.counts()
    t0 = time.time()
    results = []
    for i, b in enumerate(blocks[1:]):
        results += svc.serve_updates([UpdateRequest(new_toas=b,
                                                    request_id=f"u{i}")])
    elapsed = time.time() - t0
    steady = jaxevents.counts().compiles - before.compiles
    fallbacks = sum(1 for r in results if r.fallback is not None)
    if fallbacks:
        raise RuntimeError(
            f"{fallbacks}/{len(results)} appends fell back to a full "
            "refactor on the stand-in — the rank-k path is broken")
    # percentiles over the TIMED appends only (the door's ring also
    # holds the settle pass, whose per-shape compiles would pollute
    # the steady-state p99)
    lat_ms = sorted(float(r.latency_ms) for r in results)
    lat = {"p50_ms": float(np.percentile(lat_ms, 50)),
           "p99_ms": float(np.percentile(lat_ms, 99))}

    # the warm full-refit comparison: fresh fitter per refit (appended
    # data invalidates the design/Gram/Schur caches), measured AFTER
    # one unmeasured warm pass settles the union-shape executables
    final = svc.stream.cache.toas
    refits = int(os.environ.get("BENCH_STREAM_REFITS",
                                str(STREAM_BENCH_REFITS)))
    fr = GLSFitter(final, copy.deepcopy(f.model))
    fr.fit_toas(maxiter=1)
    refit_ms = []
    for _ in range(max(1, refits)):
        t0 = time.time()
        fr = GLSFitter(final, copy.deepcopy(f.model))
        fr.fit_toas(maxiter=1)
        refit_ms.append(1e3 * (time.time() - t0))
    refit_p50 = float(np.percentile(refit_ms, 50))
    if elapsed <= 0 or lat["p50_ms"] <= 0:
        raise RuntimeError(
            f"streaming timing degenerate: {elapsed}s for "
            f"{appends} appends")
    return {
        "appends": appends,
        "update_p50_ms": round(lat["p50_ms"], 3),
        "update_p99_ms": round(lat["p99_ms"], 3),
        "updates_per_s": round(appends / elapsed, 3),
        "refit_p50_ms": round(refit_p50, 3),
        "speedup_vs_refit": round(refit_p50 / lat["p50_ms"], 2),
        "steady_state_compiles": int(steady),
        "block": bs,
        "ntoas_final": len(final),
    }


#: durably journaled update ops before the simulated crash (appends
#: interleaved with quarantine/release row ops — the replay must
#: reconstruct the provenance, not just the factor)
RECOVERY_BENCH_OPS = 8
#: open-loop requests offered during the chaos drill under fault
RECOVERY_BENCH_REQUESTS = 48
#: offered rate during the drill: modest, so the run outlasts the
#: breaker's open window (requests/rps must exceed the breaker's
#: reset_s with real margin — at 48/100 the offered window is 0.48 s
#: vs the 0.2 s reset, so completions resume UNDER fault even on a
#: loaded machine instead of every request landing inside the open
#: window and starving rps_under_fault)
RECOVERY_BENCH_RPS = 100.0


def recovery_block():
    """The headline's ``recovery{}`` block: the durability measurement
    — journal interleaved update ops (appends + quarantine/release)
    through the :class:`~pint_tpu.serving.service.TimingService`
    update door's write-ahead journal, crash mid-stream (the
    ``crash_at_op`` seam tears the process between the factor apply
    and the journal ack), then :meth:`~pint_tpu.serving.service.
    TimingService.recover` a FRESH service from the journal and prove
    the landing is **bitwise** (every ``state_dict`` array
    ``array_equal`` against the pre-crash reference).  The recovered
    service then takes a scripted chaos drill (``device_loss``) under
    open-loop load with a drill-tuned circuit breaker: the block FAILS
    (degraded twin) unless the replay landed bitwise, the drill
    stranded zero futures, every shed was typed, and the service
    returned to steady state.  ``tools/perfwatch.py`` gates
    ``time_to_recover_s`` rises, ``replay_ops_per_s`` drops,
    ``rps_under_fault`` drops, and nonzero ``stranded_futures``."""
    import copy
    import shutil
    import tempfile

    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.runtime import chaos
    from pint_tpu.runtime.faultinject import SimulatedCrash, crash_at_op
    from pint_tpu.serving import ServeConfig, TimingService
    from pint_tpu.serving.admission import BreakerConfig
    from pint_tpu.serving.loadgen import ShapePopulation
    from pint_tpu.streaming import UpdateRequest

    n_ops = int(os.environ.get("BENCH_RECOVERY_OPS",
                               str(RECOVERY_BENCH_OPS)))
    n_requests = int(os.environ.get("BENCH_RECOVERY_REQUESTS",
                                    str(RECOVERY_BENCH_REQUESTS)))
    rps = float(os.environ.get("BENCH_RECOVERY_RPS",
                               str(RECOVERY_BENCH_RPS)))
    bs = 8
    model = get_model([ln + "\n" for ln in STREAM_PAR.splitlines()])
    rng = np.random.default_rng(20260806)
    ntoa = 100 + (n_ops + 1) * bs
    from pint_tpu.simulation import make_fake_toas_uniform

    toas = make_fake_toas_uniform(
        53400, 54800, ntoa, model, freq=np.array([800.0, 1400.0]),
        error_us=1.0, add_noise=True, rng=rng)
    base = toas[np.arange(100)]
    blocks = [toas[np.arange(100 + bs * i, 100 + bs * (i + 1))]
              for i in range(n_ops + 1)]

    def fresh_service():
        f = GLSFitter(base, copy.deepcopy(model))
        f.fit_toas(maxiter=2)
        svc = TimingService(ServeConfig(
            ntoa_buckets=(64,), nfree_buckets=(8,),
            batch_buckets=(1, 4, 16), draw_buckets=(32,),
            window_ms=1.0,
            breaker=BreakerConfig(failures=2, reset_s=0.2)))
        svc.register_stream(f, block_sizes=[bs])
        return svc

    tmp = tempfile.mkdtemp(prefix="pint_tpu_recovery_bench_")
    jdir = os.path.join(tmp, "journal")
    try:
        # phase 1: journal interleaved ops, then crash mid-stream —
        # the LAST op's journal write dies between apply and ack, so
        # the pre-crash reference is the state before it
        svc = fresh_service()
        svc.attach_journal(jdir)
        for i in range(n_ops - 1):
            reqs = [UpdateRequest(new_toas=copy.deepcopy(blocks[i]),
                                  request_id=f"a{i}")]
            if i == 1:
                reqs.append(UpdateRequest(kind="quarantine",
                                          block_id=0, rows=[0, 2],
                                          request_id="q0"))
            if i == 2:
                reqs.append(UpdateRequest(kind="release", block_id=0,
                                          rows=[2], request_id="r0"))
            svc.serve_updates(reqs)
        ref = {k: np.asarray(v)
               for k, v in svc.stream.cache.state_dict().items()}
        ops_journaled = int(svc.journal.ops_journaled)
        try:
            with crash_at_op(0):
                svc.serve_updates([UpdateRequest(
                    new_toas=copy.deepcopy(blocks[n_ops - 1]),
                    request_id="crashed")])
            raise RuntimeError("crash_at_op(0) never fired — the "
                               "journal fault seam is dead")
        except SimulatedCrash:
            pass
        svc.journal.close()

        # phase 2: recover a FRESH service from the journal alone
        # (full-tail replay — the honest replay_ops_per_s)
        svc2 = fresh_service()
        rep = svc2.recover(jdir)
        got = {k: np.asarray(v)
               for k, v in svc2.stream.cache.state_dict().items()}
        bitwise = set(got) == set(ref) and all(
            np.array_equal(ref[k], got[k], equal_nan=True)
            for k in ref)
        if not bitwise:
            bad = [k for k in ref
                   if k not in got
                   or not np.array_equal(ref[k], got[k],
                                         equal_nan=True)]
            raise RuntimeError(
                f"recovery landed off-bitwise on {bad[:4]} — the "
                "journal replay is not crash-consistent")
        t_rec = float(rep["time_to_recover_s"])
        if t_rec <= 0 or rep["ops_replayed"] != ops_journaled:
            raise RuntimeError(
                f"recovery accounting degenerate: {rep} vs "
                f"{ops_journaled} journaled ops")

        # phase 3: the recovered service takes a chaos drill under
        # open-loop load — the drill contract is the degraded-twin
        # gate (zero stranded futures, typed sheds, steady state)
        drill = chaos.run_drill(
            svc2, "device_loss", rps=rps, n_requests=n_requests,
            times=2, seed=14,
            shapes=ShapePopulation.synthetic(n=4, seed=14),
            recovery_timeout_s=20.0)
        if not drill.contract_ok:
            raise RuntimeError(
                "chaos drill broke the contract: "
                + "; ".join(drill.violations))
        if drill.completed < 1:
            raise RuntimeError(
                "drill completed zero requests under fault — "
                "rps_under_fault would be vacuous")
        p99 = drill.per_class.get("fit", {}).get("p99_ms")
        return {
            "ops_journaled": ops_journaled,
            "time_to_recover_s": round(t_rec, 4),
            "replay_ops_per_s": round(rep["ops_replayed"] / t_rec, 3),
            "bitwise_match": bool(bitwise),
            "rps_under_fault": round(
                drill.completed / drill.duration_s, 3),
            "p99_under_fault_ms": round(float(p99), 3)
                if p99 == p99 and p99 is not None else None,
            "stranded_futures": int(drill.stranded),
            "drill_recovery_s": round(float(drill.recovery_s), 4),
            "scenario": "device_loss",
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


#: closed-loop calibration requests (fit:posterior 4:1) whose measured
#: completion rate sets the overload offered rate
LOAD_CALIB_REQUESTS = 48
#: measured open-loop requests under the 4:1 overload mix
LOAD_BENCH_REQUESTS = 240
#: offered rate = this multiple of the calibrated closed-loop capacity
#: (past 1.0 the excess MUST shed — queueing it would grow without
#: bound)
LOAD_OVERLOAD_FACTOR = 3.0
#: the posterior door's p99 SLO budget the block holds under overload
LOAD_POSTERIOR_SLO_MS = 250.0


def load_block():
    """The headline's ``load{}`` block: the traffic-engineering
    measurement — the seeded closed-loop harness
    (:mod:`pint_tpu.serving.loadgen`) drives the real
    :class:`~pint_tpu.serving.service.TimingService` (fit + posterior
    doors, pre-warmed) to saturation on the CPU stand-in.  A
    closed-loop calibration pass measures capacity, then an open-loop
    Poisson run offers ``LOAD_OVERLOAD_FACTOR``x that rate in a 4:1
    fit:posterior mix.  The block FAILS (degraded twin) unless the
    overload actually shed (admission control, not unbounded queueing),
    posterior p99 held its SLO budget while fit absorbed the
    degradation, accounting balanced (no request lost — a shed never
    fails a coalesced batch-mate), and the JAX accounting delta over
    the measured window shows zero steady-state recompiles.
    ``tools/perfwatch.py`` gates per-class RPS drops, per-class p99
    rises, shed-rate rises, and fairness drops."""
    from pint_tpu.amortized import (AmortizedPosterior, AmortizedVI,
                                    TrainConfig, train_flow)
    from pint_tpu.bayesian import BayesianTiming, apply_prior_info
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.serving import (AdmissionConfig, LoadConfig,
                                  LoadGenerator, ServeConfig,
                                  ShapePopulation, TimingService)
    from pint_tpu.telemetry import jaxevents

    # the posterior door needs a trained flow: a deliberately tiny one
    # (the harness measures contention, not posterior quality)
    model, toas = _ngc_or_fallback(np.random.default_rng(20260806))
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=3)
    f.model.free_params = ["F0", "F1"]
    info = {}
    for p in f.model.free_params:
        par = getattr(f.model, p)
        half = 10.0 * float(par.uncertainty or abs(par.value or 1.0) * 1e-8)
        v = float(par.value or 0.0)
        info[p] = {"distr": "uniform", "pmin": v - half, "pmax": v + half}
    apply_prior_info(f.model, info)
    bt = BayesianTiming(f.model, f.toas)
    vi = AmortizedVI.from_bayesian(bt, n_layers=2, hidden=8, seed=4)
    steps = int(os.environ.get("BENCH_LOAD_TRAIN_STEPS", "40"))
    res = train_flow(vi, TrainConfig(steps=max(1, steps), n_samples=16,
                                     lr=1e-2, seed=5))
    ap = AmortizedPosterior.from_training(vi, res)

    draws = 32
    batch_buckets = (1, 4, 16)
    svc = TimingService(ServeConfig(
        ntoa_buckets=(64,), nfree_buckets=(8,),
        batch_buckets=batch_buckets, draw_buckets=(draws,),
        max_queue=32,
        admission=AdmissionConfig(high_watermark=0.75,
                                  low_watermark=0.375)))
    svc.register_posterior(ap, seed=6)
    svc.warm([(b, 64, 8) for b in batch_buckets])
    svc.warm_posterior([(b, draws) for b in batch_buckets])

    shapes = ShapePopulation.synthetic(n=6, seed=11,
                                       ntoa_range=(24, 64),
                                       nfree_range=(3, 8))
    slo_ms = float(os.environ.get("BENCH_LOAD_SLO_MS",
                                  str(LOAD_POSTERIOR_SLO_MS)))
    mix = {"fit": 4.0, "posterior": 1.0}
    slos = {"posterior": slo_ms, "fit": 4000.0}

    # calibration (doubles as the settle pass: any first-touch compile
    # left after warm-up is paid here, outside the measured window)
    calib = LoadGenerator(svc, LoadConfig(
        arrival="closed", concurrency=8,
        n_requests=int(os.environ.get("BENCH_LOAD_CALIB",
                                      str(LOAD_CALIB_REQUESTS))),
        mix=mix, seed=12, slo_ms=slos), shapes=shapes).run()
    capacity_rps = calib.completed / calib.duration_s
    if capacity_rps <= 0 or calib.completed < 1:
        raise RuntimeError(
            f"load calibration degenerate: {calib.completed} completed "
            f"in {calib.duration_s}s")

    # overload search: the closed-loop calibration floor understates
    # what open-loop batching can absorb (bigger coalitions amortize
    # better), so the offered rate escalates geometrically from
    # LOAD_OVERLOAD_FACTOR x capacity until admission actually sheds —
    # the measured run is the first genuinely saturating one
    n_requests = int(os.environ.get("BENCH_LOAD_REQUESTS",
                                    str(LOAD_BENCH_REQUESTS)))
    rps = LOAD_OVERLOAD_FACTOR * capacity_rps
    rep = steady = None
    for attempt in range(8):
        overload = LoadConfig(arrival="open", rps=rps,
                              n_requests=n_requests, mix=mix,
                              seed=13 + attempt, slo_ms=slos)
        before = jaxevents.counts()
        rep = LoadGenerator(svc, overload, shapes=shapes).run()
        steady = jaxevents.counts().compiles - before.compiles
        if rep.shed >= 1:
            break
        rps *= 4.0
    pc = rep.per_class
    if rep.completed + rep.shed != rep.offered:
        raise RuntimeError(
            f"load accounting lost requests: offered {rep.offered}, "
            f"completed {rep.completed}, shed {rep.shed}")
    if rep.shed < 1:
        raise RuntimeError(
            f"no shedding up to {rps:.0f} offered rps "
            f"({rps / capacity_rps:.0f}x the calibrated capacity) — "
            "admission control queued the excess")
    if pc["posterior"]["completed"] < 1:
        raise RuntimeError("overload starved the posterior class to "
                           "zero completions")
    post_p99 = pc["posterior"]["p99_ms"]
    if not post_p99 == post_p99 or post_p99 > slo_ms:
        raise RuntimeError(
            f"posterior p99 {post_p99} ms past its {slo_ms} ms SLO "
            "under the 4:1 overload mix")
    if steady:
        raise RuntimeError(
            f"{steady} steady-state recompile(s) under load — the "
            "warmed bucket ladder missed a dispatch shape")
    return {
        "arrival": "open",
        "offered": int(rep.offered),
        "capacity_rps": round(capacity_rps, 3),
        "offered_rps": round(rps, 3),
        "fit_rps": round(pc["fit"]["rps"], 3),
        "posterior_rps": round(pc["posterior"]["rps"], 3),
        "fit_p99_ms": round(pc["fit"]["p99_ms"], 3),
        "posterior_p99_ms": round(post_p99, 3),
        "posterior_slo_ms": slo_ms,
        "shed_rate": round(rep.shed_rate, 4),
        "fairness": round(rep.fairness, 4),
        "steady_state_compiles": int(steady),
    }


#: coalesced batch + repeat count for the trace-overhead measurement:
#: enough dispatches that the traced/untraced ratio is a throughput
#: signal, small enough to stay a minor slice of the bench wall
SLO_SERVE_REQUESTS = 8
SLO_OVERHEAD_REPEATS = 6


def slo_block():
    """The headline's ``slo{}`` block: the request-lifecycle
    observatory measurement (DESIGN.md "Request-lifecycle
    observability").

    Three sub-measurements on one warmed fit+posterior service:

    * **trace overhead** — warm coalesced fit throughput through the
      async door with sampling disabled vs every request traced (both
      in ``basic`` mode, so the ratio isolates the tracer itself);
      ``trace_overhead_frac`` = 1 - traced/untraced, gated by
      perfwatch so the observatory can never silently tax the hot
      path.  Both passes must show zero steady-state compiles —
      tracing lives entirely on the host.
    * **SLO compliance** — a short closed-loop fit+posterior load pass,
      then per-class deadline compliance and the worst multi-window
      burn rate straight from ``TimingService.health()``.
    * **flight recorder** — a ``door_fault`` raise storm trips the fit
      breaker open, which must dump at least one validating
      ``postmortem/1`` bundle (``postmortems_emitted``)."""
    from pint_tpu import config as _config
    from pint_tpu import telemetry as _telemetry
    from pint_tpu.amortized import (AmortizedPosterior, AmortizedVI,
                                    TrainConfig, train_flow)
    from pint_tpu.bayesian import BayesianTiming, apply_prior_info
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.runtime.chaos import door_fault
    from pint_tpu.serving import (BreakerConfig, FitRequest, LoadConfig,
                                  LoadGenerator, ServeConfig,
                                  ShapePopulation, TimingService)
    from pint_tpu.telemetry import jaxevents
    from pint_tpu.telemetry.flightrec import validate_bundle

    # a tiny trained flow so the posterior door has real compliance to
    # report (the block measures the observatory, not posterior
    # quality)
    model, toas = _ngc_or_fallback(np.random.default_rng(20260807))
    pf = WLSFitter(toas, model)
    pf.fit_toas(maxiter=3)
    pf.model.free_params = ["F0", "F1"]
    info = {}
    for p in pf.model.free_params:
        par = getattr(pf.model, p)
        half = 10.0 * float(par.uncertainty
                            or abs(par.value or 1.0) * 1e-8)
        v = float(par.value or 0.0)
        info[p] = {"distr": "uniform", "pmin": v - half, "pmax": v + half}
    apply_prior_info(pf.model, info)
    bt = BayesianTiming(pf.model, pf.toas)
    vi = AmortizedVI.from_bayesian(bt, n_layers=2, hidden=8, seed=7)
    steps = int(os.environ.get("BENCH_SLO_TRAIN_STEPS", "30"))
    res = train_flow(vi, TrainConfig(steps=max(1, steps), n_samples=16,
                                     lr=1e-2, seed=8))
    ap = AmortizedPosterior.from_training(vi, res)

    draws = 32
    svc = TimingService(ServeConfig(
        ntoa_buckets=(64,), nfree_buckets=(8,),
        batch_buckets=(1, SLO_SERVE_REQUESTS), draw_buckets=(draws,),
        max_queue=64, trace_sample=1,
        breaker=BreakerConfig(failures=3, reset_s=60.0)))
    svc.register_posterior(ap, seed=9)
    svc.warm([(b, 64, 8) for b in (1, SLO_SERVE_REQUESTS)])
    svc.warm_posterior([(b, draws) for b in (1, SLO_SERVE_REQUESTS)])

    rng = np.random.default_rng(20260808)
    base = FitRequest(M=rng.normal(size=(37, 5)),
                      r=rng.normal(size=37), w=np.full(37, 4.0),
                      phiinv=np.zeros(5))

    def _req(i):
        return FitRequest(M=base.M, r=base.r, w=base.w,
                          phiinv=base.phiinv, request_id=f"slo-{i}")

    def _submit_batch(reqs):
        """Drive the ASYNC fit door (the traced/breaker-fed path —
        the sync ``serve`` bypass sees neither)."""
        import asyncio

        async def _run():
            return await asyncio.gather(*[svc.submit(q) for q in reqs])

        return asyncio.run(_run())

    def throughput():
        """Settle one pass, then measure repeats of the coalesced
        batch; returns (fits/s, steady-state compile delta)."""
        _submit_batch([_req("settle")])
        before = jaxevents.counts()
        t0 = time.time()
        for r in range(SLO_OVERHEAD_REPEATS):
            _submit_batch([_req(f"{r}-{i}")
                           for i in range(SLO_SERVE_REQUESTS)])
        elapsed = time.time() - t0
        steady = jaxevents.counts().compiles - before.compiles
        n = SLO_OVERHEAD_REPEATS * SLO_SERVE_REQUESTS
        return (n / elapsed if elapsed > 0 else float("nan"),
                int(steady))

    # both passes in BASIC mode so the comparison isolates the tracer
    # (mark stamps, per-request allocation, the batch record) from the
    # rest of telemetry: untraced = sampling effectively disabled,
    # traced = every request sampled.  Same service, same executables.
    prev_mode = _config.telemetry_mode()
    try:
        _telemetry.activate("basic")
        svc.tracer.sample_every = 1 << 30
        untraced_fps, steady_off = throughput()
        svc.tracer.sample_every = 1
        traced_fps, steady_full = throughput()
    finally:
        svc.tracer.sample_every = 1
        _config.set_telemetry_mode(prev_mode)
    overhead = 1.0 - traced_fps / untraced_fps
    steady = steady_off + steady_full
    if steady:
        raise RuntimeError(
            f"{steady} steady-state recompile(s) during the trace-"
            "overhead passes — tracing must not perturb executables")

    # compliance: a light closed-loop fit+posterior pass, then the
    # service's own health snapshot (the same numbers the slo_status
    # alerting consumes)
    shapes = ShapePopulation.synthetic(n=4, seed=23,
                                       ntoa_range=(24, 64),
                                       nfree_range=(3, 8))
    n_req = int(os.environ.get("BENCH_SLO_REQUESTS", "48"))
    rep = LoadGenerator(svc, LoadConfig(
        arrival="closed", concurrency=6, n_requests=n_req,
        mix={"fit": 2.0, "posterior": 1.0}, seed=24),
        shapes=shapes).run()
    if rep.completed < 1:
        raise RuntimeError("slo load pass completed zero requests")
    health = svc.health()
    classes = health["slo"]["classes"]

    def _compliance(klass):
        sli = classes.get(klass, {})
        c = sli.get("compliance_fast")
        return round(float(c), 4) if c is not None else None

    # flight recorder: a raise storm trips the fit breaker -> the
    # breaker-open hook must dump a validating postmortem bundle
    dumps_before = svc.flight_recorder.dumps
    with door_fault(svc, "raise", times=3):
        for i in range(3):
            try:
                _submit_batch([_req(f"fault-{i}")])
            except Exception:
                pass
    postmortems = svc.flight_recorder.dumps - dumps_before
    if postmortems < 1:
        raise RuntimeError(
            "breaker opened without a postmortem dump — the flight "
            "recorder missed its trigger")
    bundle_errors = []
    for b in svc.flight_recorder.bundles:
        validate_bundle(b, errors=bundle_errors)
    if bundle_errors:
        raise RuntimeError(
            f"postmortem bundle failed validation: {bundle_errors[:3]}")
    return {
        "untraced_fits_per_s": round(untraced_fps, 3),
        "traced_fits_per_s": round(traced_fps, 3),
        "trace_overhead_frac": round(overhead, 4),
        "fit_compliance": _compliance("fit"),
        "posterior_compliance": _compliance("posterior"),
        "worst_burn_rate": round(float(health["slo"]["worst_burn"]), 4),
        "postmortems_emitted": int(postmortems),
        "steady_state_compiles": int(steady),
    }


def _ngc_or_fallback(rng):
    """The NGC6440E workload when the reference data exists, else the
    FALLBACK_PAR model with simulated TOAs at the same scale — ONE
    loader shared by the secondary WLS grid and the posterior block."""
    from pint_tpu.models import get_model, get_model_and_toas
    from pint_tpu.simulation import make_fake_toas_uniform

    if os.path.exists(NGC_PAR) and os.path.exists(NGC_TIM):
        return get_model_and_toas(NGC_PAR, NGC_TIM)
    model = get_model([ln + "\n" for ln in FALLBACK_PAR.splitlines()])
    toas = make_fake_toas_uniform(53400, 54800, 62, model,
                                  error_us=20.0, add_noise=True,
                                  rng=rng)
    return model, toas


#: posterior-block knobs: flow training schedule (env-overridable so
#: the contract test stays fast), draw-request fan, and per-request
#: draw count for the coalesced throughput pass
POSTERIOR_TRAIN_STEPS = 80
POSTERIOR_MC_SAMPLES = 32
POSTERIOR_DRAW_REQUESTS = 4
POSTERIOR_DRAWS_PER_REQUEST = 256
POSTERIOR_LATENCY_PROBES = 8


def posterior_block():
    """The headline's ``posterior{}`` block: train a normalizing flow
    (:mod:`pint_tpu.amortized`) against a small vectorizable Bayesian
    timing posterior — white-noise F0/F1/DM with basic uniform priors,
    the MCMC-able surface (the full correlated-noise GLS likelihood is
    outside ``BayesianTiming``'s vectorized families, exactly as it is
    for ``MCMCFitter``) — then serve coalesced draw and log-prob
    requests through the :class:`~pint_tpu.serving.service.
    TimingService` posterior door and stamp training depth, final
    ELBO, draw/log-prob throughput, latency percentiles, and the
    steady-state compile proof.  ``tools/perfwatch.py`` gates
    ``draws_per_s`` drops and ``p99_ms`` rises."""
    from pint_tpu.amortized import (AmortizedPosterior, AmortizedVI,
                                    TrainConfig, train_flow)
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.bayesian import BayesianTiming, apply_prior_info
    from pint_tpu.serving import PosteriorRequest, ServeConfig, TimingService
    from pint_tpu.telemetry import jaxevents

    model, toas = _ngc_or_fallback(np.random.default_rng(20260804))
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=3)
    # amortize the well-conditioned spin subspace: single-band fake
    # TOAs leave DM (and the bench's astrometry) degenerate, and a
    # box prior built on a junk uncertainty destabilizes a
    # fixed-budget training run
    f.model.free_params = ["F0", "F1"]
    info = {}
    for p in f.model.free_params:
        par = getattr(f.model, p)
        half = 10.0 * float(par.uncertainty or abs(par.value or 1.0) * 1e-8)
        v = float(par.value or 0.0)
        info[p] = {"distr": "uniform", "pmin": v - half, "pmax": v + half}
    apply_prior_info(f.model, info)
    bt = BayesianTiming(f.model, f.toas)
    vi = AmortizedVI.from_bayesian(bt, n_layers=4, hidden=16, seed=1)
    steps = int(os.environ.get("BENCH_POSTERIOR_STEPS",
                               str(POSTERIOR_TRAIN_STEPS)))
    res = train_flow(vi, TrainConfig(steps=max(1, steps),
                                     n_samples=POSTERIOR_MC_SAMPLES,
                                     lr=1e-2, seed=2))
    if not np.isfinite(res.elbo_final):
        raise RuntimeError(
            f"flow training diverged: final ELBO {res.elbo_final}")
    ap = AmortizedPosterior.from_training(vi, res)
    svc = TimingService(ServeConfig(
        draw_buckets=(POSTERIOR_DRAWS_PER_REQUEST,)))
    svc.register_posterior(ap, seed=3)
    n, k = POSTERIOR_DRAWS_PER_REQUEST, POSTERIOR_DRAW_REQUESTS
    svc.warm_posterior([(k, n), (1, n)])

    before = jaxevents.counts()
    t0 = time.time()
    out = svc.serve_posterior([PosteriorRequest(n_draws=n,
                                                request_id=f"bench-{i}")
                               for i in range(k)])
    draw_elapsed = time.time() - t0
    pts = np.concatenate([o.draws for o in out])[:n]
    t0 = time.time()
    lout = svc.serve_posterior([PosteriorRequest(points=pts,
                                                 request_id="bench-lp")])
    lp_elapsed = time.time() - t0
    for i in range(POSTERIOR_LATENCY_PROBES):
        svc.serve_posterior([PosteriorRequest(n_draws=n,
                                              request_id=f"lat-{i}")])
    steady = jaxevents.counts() - before
    if draw_elapsed <= 0 or lp_elapsed <= 0:
        raise RuntimeError(
            f"posterior timing degenerate: draws {draw_elapsed}s, "
            f"logprob {lp_elapsed}s")
    if not np.all(np.isfinite(lout[0].log_probs)):
        raise RuntimeError("posterior log-prob produced non-finite "
                           "values on its own draws")
    lat = svc.posterior_latency_summary()
    return {
        "train_steps": res.steps,
        "elbo_final": round(res.elbo_final, 3),
        "draws_per_s": round(n * k / draw_elapsed, 3),
        "logprob_per_s": round(n / lp_elapsed, 3),
        "p50_ms": round(lat["p50_ms"], 3),
        "p99_ms": round(lat["p99_ms"], 3),
        "steady_state_compiles": int(steady.compiles),
    }


#: predict-block knobs: coverage span / polyco grid for the predictor
#: cache, request fan + per-request epoch count for the coalesced
#: throughput pass, and the single-request latency probes
PREDICT_SPAN_DAYS = 2.0
PREDICT_SEGLENGTH_MIN = 60.0
PREDICT_NCOEFF = 12
PREDICT_REQUESTS = 8
PREDICT_TIMES_PER_REQUEST = 48
PREDICT_LATENCY_PROBES = 12


def predict_block():
    """The headline's ``predict{}`` block: the phase-prediction read
    path — a :class:`~pint_tpu.predict.cache.PredictorCache` over a
    barycentric polyco grid, registered (and warmed) on a
    :class:`~pint_tpu.serving.service.TimingService`, then coalesced
    predict batches plus single-request latency probes served through
    the predict door.  A settle pass pays every lazy window
    generation outside the measured window, so the measured cache-hit
    rate is the steady state and the JAX accounting delta proves zero
    steady-state recompiles.  ``tools/perfwatch.py`` gates
    ``predicts_per_s`` drops, ``p99_ms`` rises, and
    ``cache_hit_rate`` drops."""
    from pint_tpu.predict import PredictorCache, PredictRequest
    from pint_tpu.serving import ServeConfig, TimingService
    from pint_tpu.telemetry import jaxevents

    model, _ = _ngc_or_fallback(np.random.default_rng(20260807))
    mjd0 = float(model.PEPOCH.value)
    cache = PredictorCache(model, mjd0, mjd0 + PREDICT_SPAN_DAYS,
                           obs="@", segLength=PREDICT_SEGLENGTH_MIN,
                           ncoeff=PREDICT_NCOEFF)
    n, k = PREDICT_TIMES_PER_REQUEST, PREDICT_REQUESTS
    svc = TimingService(ServeConfig(time_buckets=(n,),
                                    batch_buckets=(1, k)))
    svc.register_predictor(cache, warm=True)

    lo, hi = cache.coverage()
    rng = np.random.default_rng(20260808)

    def batch(tag):
        return [PredictRequest(
            times_mjd=np.sort(rng.uniform(lo, hi, size=n)),
            request_id=f"{tag}-{i}") for i in range(k)]

    # settle: every lazy window regenerates through build() — outside
    # the door, so the latency ring never sees generation walls — and
    # one served batch absorbs any first-dispatch overhead before the
    # measured window (the load block's calibration-pass discipline)
    cache.build()
    svc.serve_predicts(batch("settle"))
    h0, m0 = cache.hits, cache.misses

    before = jaxevents.counts()
    t0 = time.time()
    out = svc.serve_predicts(batch("bench"))
    elapsed = time.time() - t0
    for i in range(PREDICT_LATENCY_PROBES):
        svc.serve_predicts([PredictRequest(
            times_mjd=np.sort(rng.uniform(lo, hi, size=n)),
            request_id=f"lat-{i}")])
    steady = jaxevents.counts().compiles - before.compiles

    if elapsed <= 0:
        raise RuntimeError(f"predict timing degenerate: {elapsed}s")
    for r in out:
        if not (np.all(np.isfinite(r.phase_frac))
                and np.all(np.isfinite(r.freq))):
            raise RuntimeError("predict door produced non-finite "
                               "phases/frequencies")
    dh, dm = cache.hits - h0, cache.misses - m0
    if dm:
        raise RuntimeError(
            f"{dm} predictor-cache miss(es) after the settle pass — "
            "lazy generation leaked into the measured window")
    if steady:
        raise RuntimeError(
            f"{steady} steady-state recompile(s) on the predict "
            "path — the warmed ladder missed a dispatch shape")
    lat = svc.predict_latency_summary()
    return {
        "windows": int(cache.n_windows),
        "predicts_per_s": round(n * k / elapsed, 3),
        "cache_hit_rate": round(dh / (dh + dm), 4) if (dh + dm) else 0.0,
        "p50_ms": round(lat["p50_ms"], 3),
        "p99_ms": round(lat["p99_ms"], 3),
        "steady_state_compiles": int(steady),
    }


def bench_ngc6440e_wls():
    """Secondary: the r01/r02 NGC6440E WLS grid (continuity metric)."""
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.grid import grid_chisq

    model, toas = _ngc_or_fallback(np.random.default_rng(12345))
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=3)
    npts = 16
    escale = max(1.0, np.sqrt(f.resids.reduced_chi2))
    dF0 = 3 * escale * f.errors.get("F0", 1e-10)
    dF1 = 3 * escale * f.errors.get("F1", 1e-18)
    g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, npts)
    g1 = np.linspace(f.model.F1.value - dF1, f.model.F1.value + dF1, npts)
    grid_chisq(f, ("F0", "F1"), (g0, g1))  # warmup/compile
    t0 = time.time()
    chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
    chi2 = np.asarray(chi2)
    elapsed = time.time() - t0
    return {"fits_per_sec": chi2.size / elapsed, "ntoas": len(toas)}


def emit(out):
    """Print the headline JSON line on stdout (the bench contract)."""
    print(json.dumps(out))
    sys.stdout.flush()


def telemetry_summary(stages=None):
    """The ``telemetry`` block stamped into the bench artifact: JAX
    accounting (compiles / cache hits / transfers), a name->seconds span
    table, and the live-buffer / HBM watermark.  The bench self-activates
    ``basic`` collection in main() when the env left telemetry off, so
    the block is always present and populated."""
    from pint_tpu import telemetry
    from pint_tpu.telemetry import jaxevents, spans

    table = {}
    for root in spans.finished_roots():
        stack = [root]
        while stack:
            sp = stack.pop()
            table[sp.name] = round(table.get(sp.name, 0.0) + sp.duration, 3)
            stack.extend(sp.children)
    if stages is not None:
        for name, dt in stages.rows:
            table.setdefault(f"stage.{name}", round(dt, 3))
    return {
        "mode": telemetry.mode(),
        "jax": jaxevents.counts().to_dict(),
        "spans": dict(sorted(table.items())),
        "memory": jaxevents.memory_snapshot(),
    }


def _probe_tpu(timeout_s: int = 240):
    """Default backend platform probed in a subprocess, or None.

    A wedged axon tunnel hangs ``jax.devices()`` in make_c_api_client
    rather than raising; the subprocess bounds that hang so the parent
    never inherits it.  The child holds the (exclusive) lease only for
    the probe's duration.  Shutdown discipline matters: a timed-out
    child is sent SIGTERM to its whole session (kill -9 of a lease
    holder wedges the tunnel — the same rule the shell probes follow
    with ``timeout``), with SIGKILL only as a last resort; output goes
    to a tempfile, not pipes, so a surviving grandchild can never block
    the parent on pipe EOF."""
    import signal
    import subprocess
    import tempfile

    with tempfile.TemporaryFile("w+") as out:
        p = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            stdout=out, stderr=subprocess.DEVNULL, start_new_session=True)
        try:
            rc = p.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                p.terminate()
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                try:  # truly stuck: reap it rather than leak a zombie
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    p.kill()
                p.wait()
            return None
        if rc != 0:
            return None
        out.seek(0)
        lines = out.read().strip().splitlines()
        # last line only: plugin/log chatter may precede the platform
        return lines[-1].strip() if lines else None


def main():
    t_all = time.time()
    import jax

    if not (os.path.exists(B1855_PAR) and os.path.exists(B1855_TIM)):
        emit({"metric": "gls_chisq_grid_evals_per_sec", "value": 0.0,
              "unit": "fits/s", "vs_baseline": 0.0,
              "error": "B1855 datafiles unavailable"})
        return

    if os.environ.get("BENCH_FORCE_CPU"):
        if os.environ.get("BENCH_REQUIRE_TPU"):
            emit({"metric": "gls_chisq_grid_evals_per_sec", "value": 0.0,
                  "unit": "fits/s", "vs_baseline": 0.0,
                  "error": "BENCH_FORCE_CPU and BENCH_REQUIRE_TPU are "
                           "contradictory; unset one"})
            return
        # env vars don't work: axon.register force-sets jax_platforms at
        # interpreter startup, so a config.update is the only reliable way
        # to keep a validation run off the (exclusive, flaky) TPU lease
        jax.config.update("jax_platforms", "cpu")

    # the axon TPU tunnel is intermittently unavailable (see BENCH_NOTES.md)
    # and a WEDGED tunnel HANGS jax.devices() for ~25 min instead of
    # raising — probe in a short-lived subprocess first so this process
    # can still fall back to CPU (or fail fast under BENCH_REQUIRE_TPU)
    # rather than hanging past the caller's patience.
    if not os.environ.get("BENCH_FORCE_CPU") \
            and not os.environ.get("BENCH_SKIP_PROBE"):
        try:
            probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
        except ValueError:  # the one-JSON-line contract survives bad env
            probe_timeout = 240
        probed = _probe_tpu(probe_timeout)
        if probed not in ("tpu", "axon"):
            if os.environ.get("BENCH_REQUIRE_TPU"):
                emit({"metric": "gls_chisq_grid_evals_per_sec", "value": 0.0,
                      "unit": "fits/s", "vs_baseline": 0.0,
                      "error": "TPU probe found no live tunnel "
                               f"(got {probed!r})"})
                return
            print(f"# TPU probe found no live tunnel (got {probed!r}); "
                  "running on CPU", file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")

    try:
        backend = jax.devices()[0].platform
        if os.environ.get("BENCH_REQUIRE_TPU") and backend not in ("tpu", "axon"):
            # devices() can succeed on CPU (axon plugin not registered on
            # this host); a require-TPU run must not record that silently
            emit({"metric": "gls_chisq_grid_evals_per_sec", "value": 0.0,
                  "unit": "fits/s", "vs_baseline": 0.0,
                  "error": f"TPU required but backend is {backend!r}"})
            return
    except Exception as e:
        if os.environ.get("BENCH_REQUIRE_TPU"):
            # retry loops probe for a live TPU; a CPU fallback run would
            # just burn 15 minutes producing a number they will discard
            emit({"metric": "gls_chisq_grid_evals_per_sec", "value": 0.0,
                  "unit": "fits/s", "vs_baseline": 0.0,
                  "error": f"TPU unavailable: {type(e).__name__}"})
            return
        print(f"# TPU backend unavailable ({type(e).__name__}: {e}); "
              "falling back to CPU for this run", file=sys.stderr)
        try:
            jax.config.update("jax_platforms", "cpu")
            backend = jax.devices()[0].platform
        except Exception as e2:
            # the bench contract is one JSON line no matter what
            emit({"metric": "gls_chisq_grid_evals_per_sec", "value": 0.0,
                  "unit": "fits/s", "vs_baseline": 0.0,
                  "error": f"no usable backend: {type(e2).__name__}: {e2}"})
            return
    print(f"# platform: {backend}", file=sys.stderr)

    # device-health preflight (runtime guardrail): probe the platform that
    # actually executes jitted arrays and its f64 regime.  This permanently
    # closes the r03/r04 artifact hole — a silent CPU fallback can no
    # longer produce a JSON that claims a TPU measurement.
    from pint_tpu.runtime.preflight import device_profile, platform_matches

    requested = "cpu" if os.environ.get("BENCH_FORCE_CPU") else (
        "tpu" if os.environ.get("BENCH_REQUIRE_TPU") else backend)
    prof = device_profile(refresh=True)
    platform_ok = platform_matches(prof.platform, requested)
    if not platform_ok and os.environ.get("BENCH_REQUIRE_TPU"):
        # refuse outright: a require-TPU artifact from another device is
        # exactly the r03/r04 failure mode
        emit({"metric": "gls_chisq_grid_evals_per_sec", "value": 0.0,
              "unit": "fits/s", "vs_baseline": 0.0, "sanity_ok": False,
              "error": f"preflight: traces execute on {prof.platform!r} "
                       f"but {requested!r} was required",
              "device_profile": prof.to_dict()})
        return
    if not platform_ok:
        print(f"# PREFLIGHT MISMATCH: requested {requested!r}, executing "
              f"on {prof.platform!r} — sanity_ok will be stamped false",
              file=sys.stderr)

    # observability: the bench always collects at least basic telemetry
    # (compile counts prove warm-cache claims; span table attributes the
    # wall time); an explicit VALID PINT_TPU_TELEMETRY choice wins — an
    # invalid spelling (which config coerces to off) must not silently
    # produce an empty telemetry block, so it falls back to basic too.
    # Activated only now, AFTER every early error-emit return above: the
    # fast error paths keep paying only `import jax`.
    from pint_tpu import config as _ptconfig
    from pint_tpu import telemetry

    _env_mode = os.environ.get("PINT_TPU_TELEMETRY")
    telemetry.activate(None if _env_mode in _ptconfig.TELEMETRY_MODES
                       else "basic")

    # persistent-cache root: the AOT cache's fingerprint-keyed XLA dir
    # when warm serving is configured (so the initial fit and the grid
    # compile are disk-served on the next process — the cold-start
    # double-pay fix), else the bench's historical .jax_cache dir
    cache_dir = None
    if _ptconfig.aot_cache_dir():
        try:
            from pint_tpu.serving import aotcache as _aotcache

            cache_dir = _aotcache.cache().xla_cache_dir()
            print(f"# AOT cache: XLA persistent cache at {cache_dir}",
                  file=sys.stderr)
        except Exception as e:
            print(f"# AOT cache dir unusable ({e}); falling back to "
                  "the local .jax_cache", file=sys.stderr)
    if cache_dir is None:
        machine = cache_key(backend)
        cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_cache", machine)
    if backend in ("tpu", "axon"):
        # DEFER enabling: the TOA simulation pins to the host CPU device,
        # and its CPU artifacts must not land in the un-hostnamed TPU dir
        # (cross-host CPU AOT replay = the r03 SIGILL hazard).
        # bench_b1855_gls() enables the cache once the simulation is done.
        _PENDING_CACHE_DIR.append(cache_dir)
    else:
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass

    r = bench_b1855_gls()
    # structural guarantee: whatever the bench internals did, the deferred
    # cache is enabled from here on (idempotent; secondary benches and any
    # future reordering cannot silently run uncached)
    _enable_persistent_cache()
    fits_per_sec = r["fits_per_sec"]
    out = {
        "metric": "gls_chisq_grid_evals_per_sec",
        "value": round(fits_per_sec, 3),
        "unit": "fits/s",
        "vs_baseline": round(fits_per_sec / BASELINE_FITS_PER_SEC, 1),
        "platform": backend,  # cpu here flags a fallback measurement
        "ntoas": r["ntoas"],
        "nfree": r["nfree"],
        "grid_points": r["grid_points"],
        "compile_s": round(r["compile_s"], 1),
        # finite grid + min within 5% of the fitter's chi2 + the preflight
        # confirming the requested platform actually executed: a broken or
        # misattributed number must be visibly broken in the artifact
        # (plain bool: np.bool_ is not JSON-serializable)
        "sanity_ok": bool(r["ok"]) and platform_ok,
        "requested_platform": requested,
        "device_profile": prof.to_dict(),
        "telemetry": telemetry_summary(stages=r["stages"]),
        # normalized XLA cost/memory analysis of the grid executable
        # (FLOPs, bytes accessed, HBM footprint; explicit nulls where the
        # backend reports nothing) — what tools/perfwatch trends
        "cost": r["cost"],
        # warm-serving layer: AOT-cache provenance + steady-state
        # throughput/latency of the shape-bucketed batcher (perfwatch
        # gates warm_fits_per_s drops and p99_ms rises)
        "warm": r["warm"],
        # cost-model autotuner: tuned chunk, tuned fits/s, tuned/static
        # ratio and the decisions fingerprint (perfwatch gates the
        # ratio — a tuned configuration may tie the static default but
        # never ship slower)
        "tuned": r["tuned"],
        # mixed-precision layer: resolved per-segment policy, forced-f64
        # vs active-policy serve throughput, and the measured
        # disagreement (perfwatch gates mixed_fits_per_s drops and
        # max_rel_err rises; default policy is bit-identical f64)
        "precision": r["precision"],
        # PTA catalog engine: batched multi-pulsar fit throughput,
        # bucket-ladder padding waste, and joint Hellings-Downs
        # lnlikelihood throughput (perfwatch gates catalog_fits_per_s
        # drops and pad_waste_frac rises)
        "catalog": r["catalog"],
        # amortized inference engine: flow training depth/ELBO plus
        # warm-served posterior draw/log-prob throughput and latency
        # (perfwatch gates draws_per_s drops and p99_ms rises)
        "posterior": r["posterior"],
        # phase-prediction read path: predictor-cache window count,
        # warm-served epoch throughput, steady-state cache-hit rate,
        # and per-request latency through the predict door (perfwatch
        # gates predicts_per_s drops, p99_ms rises, and cache_hit_rate
        # drops)
        "predict": r["predict"],
        # work-per-byte scaling: fused-dispatch rate (live) plus the
        # committed scalewatch series' efficiency / scatter bytes
        # (perfwatch gates efficiency/dispatch drops and scatter-byte
        # rises)
        "scaling": r["scaling"],
        # streaming updates: rank-k append latency/throughput through
        # the update door vs the warm full-refit path (perfwatch gates
        # updates_per_s drops, update_p99_ms rises, speedup_vs_refit
        # drops)
        "streaming": r["streaming"],
        # traffic engineering: sustained per-class RPS / p99 under the
        # 4:1 overload mix from the closed-loop load harness (perfwatch
        # gates per-class RPS drops, p99 rises, shed-rate rises, and
        # fairness drops)
        "load": r["load"],
        # request-lifecycle observatory: traced-vs-untraced warm
        # throughput, per-class deadline compliance + worst burn rate,
        # and the breaker-open -> postmortem contract (perfwatch gates
        # trace_overhead_frac rises and compliance drops)
        "slo": r["slo"],
        # durability: crash mid-stream -> bitwise journal replay ->
        # chaos drill under load (perfwatch gates time_to_recover_s
        # rises, replay_ops_per_s / rps_under_fault drops, and nonzero
        # stranded_futures)
        "recovery": r["recovery"],
    }
    if not platform_ok:
        out["platform_mismatch"] = True
    print(r["stages"].table("B1855+09 9yv1 GLS (4005 TOAs)"), file=sys.stderr)
    print(
        f"# 256 GLS grid fits in {r['elapsed']:.3f}s on "
        f"{backend} ({r['ntoas']} TOAs; fit chi2 "
        f"{r['chi2_fit']:.1f}, grid min {r['chi2_min']:.1f} at {r['imin']}; "
        f"sanity {'OK' if r['ok'] else 'FAILED'})",
        file=sys.stderr,
    )
    if not os.environ.get("BENCH_SKIP_SECONDARY"):
        try:
            n = bench_ngc6440e_wls()
            print(f"# secondary NGC6440E WLS grid: {n['fits_per_sec']:.1f} fits/s "
                  f"({n['ntoas']} TOAs)", file=sys.stderr)
        except Exception as e:  # secondary metric must not kill the headline
            print(f"# secondary NGC6440E bench failed: {e}", file=sys.stderr)
    print(f"# total bench wall time {time.time() - t_all:.1f}s", file=sys.stderr)
    # the headline is emitted EXACTLY ONCE, as the FINAL stdout line: the
    # driver tails output (r03's number once scrolled away behind chatter),
    # and a duplicate mid-run emit made every artifact's tail carry the
    # line twice — one JSON line per run is the bench contract
    emit(out)


if __name__ == "__main__":
    main()
