#!/usr/bin/env python
"""Benchmark: chisq-grid fit throughput (the reference's headline workload).

Reproduces the semantics of reference ``profiling/bench_chisq_grid_WLSFitter.py``
(NGC6440E, WLS fit per grid point over an F0 x F1 grid; see BASELINE.md) and
prints ONE JSON line:

    {"metric": "chisq_grid_evals_per_sec", "value": N, "unit": "fits/s",
     "vs_baseline": N / 0.057}

Baseline: 0.057 fits/s (i7-6700K single core, BASELINE.md "Derived headline").
Runs on whatever accelerator jax's default backend exposes (TPU under axon).
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_FITS_PER_SEC = 0.057
NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"

FALLBACK_PAR = """\
PSR              BENCH6440E
RAJ       17:48:52.75  1
DECJ      -20:21:29.0  1
F0       61.485476554  1
F1         -1.181D-15  1
PEPOCH        53750.000000
POSEPOCH      53750.000000
DM              223.9  1
EPHEM               DE421
CLK              TT(BIPM2019)
UNITS               TDB
TZRMJD  53801.38605120074849
TZRFRQ            1949.609
TZRSITE                  1
"""


def main():
    t_setup = time.time()
    import jax

    # persistent XLA compilation cache: repeat bench runs skip the (slow,
    # possibly remote) TPU compile
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from pint_tpu.fitter import WLSFitter
    from pint_tpu.grid import grid_chisq
    from pint_tpu.models import get_model, get_model_and_toas
    from pint_tpu.simulation import make_fake_toas_uniform

    rng = np.random.default_rng(12345)
    if os.path.exists(NGC_PAR) and os.path.exists(NGC_TIM):
        model, toas = get_model_and_toas(NGC_PAR, NGC_TIM)
    else:
        model = get_model([ln + "\n" for ln in FALLBACK_PAR.splitlines()])
        toas = make_fake_toas_uniform(53400, 54800, 62, model, error_us=20.0,
                                      add_noise=True, rng=rng)

    # initial WLS fit (as the reference benchmark does before the grid)
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=3)

    npts = 16  # 16x16 = 256 grid fits
    # scale the grid span by sqrt(reduced chi2): with the built-in analytic
    # ephemeris real-data residuals are systematics-dominated and formal
    # errors understate the chi2 surface's scale
    escale = max(1.0, np.sqrt(f.resids.reduced_chi2))
    dF0 = 3 * escale * f.errors.get("F0", 1e-10)
    dF1 = 3 * escale * f.errors.get("F1", 1e-18)
    g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, npts)
    g1 = np.linspace(f.model.F1.value - dF1, f.model.F1.value + dF1, npts)

    # compile warmup at the full batch shape (vmap retraces per point count)
    chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
    setup_s = time.time() - t_setup

    t0 = time.time()
    chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
    chi2 = np.asarray(chi2)
    elapsed = time.time() - t0

    # sanity: the grid minimum should be interior and near the fitted point
    imin = np.unravel_index(np.argmin(chi2), chi2.shape)
    ok = bool(np.isfinite(chi2).all()) and 0 < imin[0] < npts - 1 and 0 < imin[1] < npts - 1

    fits_per_sec = chi2.size / elapsed
    result = {
        "metric": "chisq_grid_evals_per_sec",
        "value": round(fits_per_sec, 3),
        "unit": "fits/s",
        "vs_baseline": round(fits_per_sec / BASELINE_FITS_PER_SEC, 1),
    }
    print(json.dumps(result))
    if not ok:
        print(f"WARNING: grid sanity check failed (argmin {imin})", file=sys.stderr)
    print(
        f"# {chi2.size} grid fits in {elapsed:.3f}s on {jax.devices()[0].platform} "
        f"({len(toas)} TOAs; setup+compile {setup_s:.1f}s; "
        f"min chi2 {chi2.min():.1f} at {imin})",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
