"""The exception taxonomy under fire: each typed failure is actually
raised (or warned) by the fitters on crafted degenerate inputs — not just
importable from :mod:`pint_tpu.exceptions`.
"""

import io
import warnings

import numpy as np
import pytest

PAR = """
PSR  J0000+0000
RAJ  04:37:00.0
DECJ -47:15:00.0
POSEPOCH 55000
F0   173.6879489990983 1
F1   -1.728e-15 1
PEPOCH 55000
DM   2.64476 1
EPHEM DE440
UNITS TDB
"""

#: two JUMPs selecting the same MJD range: exactly duplicate design
#: columns, the canonical degenerate direction
DUP_JUMPS = "JUMP mjd 54000 54700 0 1\nJUMP mjd 54000 54700 0 1\n"

RED_NOISE = "TNREDAMP -13.0\nTNREDGAM 3.0\nTNREDC 5\n"


def _model(extra=""):
    from pint_tpu.models import get_model

    return get_model(io.StringIO(PAR + extra))


def _toas(m, n=40, seed=3):
    from pint_tpu.simulation import make_fake_toas_uniform

    return make_fake_toas_uniform(54000, 55500, n, m, error_us=1.0,
                                  add_noise=True,
                                  rng=np.random.default_rng(seed))


class TestHierarchy:
    def test_subclass_relations(self):
        from pint_tpu import exceptions as e

        assert issubclass(e.MaxiterReached, e.ConvergenceFailure)
        assert issubclass(e.StepProblem, e.ConvergenceFailure)
        assert issubclass(e.SingularMatrixError, e.ConvergenceFailure)
        assert issubclass(e.NonFiniteSystemError, e.ConvergenceFailure)
        assert issubclass(e.ConvergenceFailure, e.PintError)
        assert issubclass(e.DeviceMismatchError, e.DeviceError)
        assert issubclass(e.DeviceLostError, e.DeviceError)
        assert issubclass(e.DegeneracyWarning, UserWarning)


class TestStepProblem:
    def test_wls_raised_at_converged_point(self):
        """At an already-converged point with a negative chi2-increase
        tolerance, the first step cannot 'improve' — the state machine
        must raise StepProblem, not loop or return a stale chi2."""
        from pint_tpu.exceptions import StepProblem
        from pint_tpu.fitter import DownhillWLSFitter

        m = _model()
        t = _toas(m)
        f = DownhillWLSFitter(t, m)
        f.fit_toas(maxiter=10)
        f2 = DownhillWLSFitter(t, f.model)
        with pytest.raises(StepProblem):
            f2.fit_toas(maxiter=5, max_chi2_increase=-1.0)

    def test_gls_raised_at_converged_point(self):
        from pint_tpu.exceptions import ConvergenceFailure, StepProblem
        from pint_tpu.gls_fitter import DownhillGLSFitter

        m = _model(RED_NOISE)
        t = _toas(m)
        f = DownhillGLSFitter(t, m)
        f.fit_toas(maxiter=10)
        f2 = DownhillGLSFitter(t, f.model)
        with pytest.raises(StepProblem) as exc:
            f2.fit_toas(maxiter=5, max_chi2_increase=-1.0)
        # StepProblem IS a ConvergenceFailure: callers catching the base
        # class see every flavor of non-convergence
        assert isinstance(exc.value, ConvergenceFailure)


class TestMaxiterReached:
    def _perturbed(self):
        from pint_tpu.fitter import DownhillWLSFitter

        m = _model()
        t = _toas(m)
        f = DownhillWLSFitter(t, m)
        f.fit_toas(maxiter=10)
        err = f.errors.get("F0", 1e-10)
        f2 = DownhillWLSFitter(t, f.model)
        f2.model.F0.value = f.model.F0.value + 50 * err
        f2.update_resids()
        return f2

    def test_raised_when_requested(self):
        from pint_tpu.exceptions import MaxiterReached

        f = self._perturbed()
        with pytest.raises(MaxiterReached):
            f.fit_toas(maxiter=1, raise_on_maxiter=True)

    def test_warned_by_default(self):
        """Default behavior stays a log warning (non-fatal): the fit
        returns its best chi2 with converged=False."""
        f = self._perturbed()
        chi2 = f.fit_toas(maxiter=1)
        assert np.isfinite(chi2)
        assert not f.converged


class TestDegeneracyWarning:
    def test_wls_duplicate_jumps_warn(self):
        from pint_tpu.exceptions import DegeneracyWarning
        from pint_tpu.fitter import WLSFitter

        m = _model(DUP_JUMPS)
        t = _toas(m)
        f = WLSFitter(t, m)
        with pytest.warns(DegeneracyWarning):
            f.fit_toas(maxiter=1)

    def test_downhill_wls_duplicate_jumps_warn(self):
        from pint_tpu.exceptions import DegeneracyWarning
        from pint_tpu.fitter import DownhillWLSFitter

        m = _model(DUP_JUMPS)
        t = _toas(m)
        f = DownhillWLSFitter(t, m)
        with pytest.warns(DegeneracyWarning):
            f.fit_toas(maxiter=3)

    def test_gls_threshold_svd_warns(self):
        """The GLS SVD path (threshold > 0) names the degenerate
        direction instead of silently zeroing it."""
        from pint_tpu.exceptions import DegeneracyWarning
        from pint_tpu.gls_fitter import DownhillGLSFitter

        m = _model(DUP_JUMPS + RED_NOISE)
        t = _toas(m)
        f = DownhillGLSFitter(t, m)
        with pytest.warns(DegeneracyWarning):
            f.fit_toas(maxiter=2, threshold=1e-12)

    def test_gls_cholesky_path_survives_duplicates(self):
        """The default (threshold=0) hardened ladder survives the same
        degeneracy — finite chi2 plus recorded diagnostics, the
        'degrade gracefully' leg of the guardrail contract."""
        from pint_tpu.gls_fitter import DownhillGLSFitter

        m = _model(DUP_JUMPS + RED_NOISE)
        t = _toas(m)
        f = DownhillGLSFitter(t, m)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # degeneracy may also warn
            chi2 = f.fit_toas(maxiter=2)
        assert np.isfinite(chi2)
        assert f.solve_diagnostics is not None


class TestTypedSolveFailures:
    def test_hardened_cholesky_nonfinite_typed(self):
        from pint_tpu.exceptions import NonFiniteSystemError
        from pint_tpu.runtime.solve import hardened_cholesky

        A = np.eye(3)
        A[1, 1] = np.nan
        with pytest.raises(NonFiniteSystemError):
            hardened_cholesky(A)

    def test_hardened_cholesky_indefinite_typed(self):
        """A matrix no jitter rung can rescue raises the typed ladder
        exhaustion, signalling the caller to escalate to SVD."""
        from pint_tpu.exceptions import SingularMatrixError
        from pint_tpu.runtime.solve import hardened_cholesky

        A = -np.eye(3)  # negative definite: every rung fails
        with pytest.raises(SingularMatrixError):
            hardened_cholesky(A)

    def test_correlated_errors_typed(self):
        from pint_tpu.exceptions import CorrelatedErrors
        from pint_tpu.fitter import WLSFitter

        m = _model(RED_NOISE)
        t = _toas(m)
        with pytest.raises(CorrelatedErrors):
            WLSFitter(t, m)
