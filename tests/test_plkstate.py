"""Headless pintk interaction tests (VERDICT r4 item 8): the GUI state
functions — axis choice, rectangle/point selection, per-point delete,
stash, phase wrap, fit checkboxes — exercised without a display, including
the select -> delete -> refit flow changing TOA count and chi2."""

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"


@pytest.fixture(scope="module")
def state():
    import os

    if not (os.path.exists(NGC_PAR) and os.path.exists(NGC_TIM)):
        pytest.skip("NGC6440E datafiles unavailable")
    from pint_tpu.pintk.plkstate import PlkState
    from pint_tpu.pintk.pulsar import Pulsar

    return PlkState(Pulsar(NGC_PAR, NGC_TIM))


class TestAxes:
    def test_axis_choices_all_finite(self, state):
        from pint_tpu.pintk.plkstate import XIDS, YIDS

        n = len(state.psr.all_toas)
        for xid in XIDS:
            state.set_choice(xid=xid)
            x = state.xvals()
            assert x.shape == (n,), xid
            assert np.all(np.isfinite(x)), xid
        for yid in YIDS:
            state.set_choice(yid=yid)
            y, yerr = state.yvals()
            assert y.shape == yerr.shape == (n,), yid
            assert np.all(np.isfinite(y)), yid
        state.set_choice(xid="mjd", yid="pre-fit")
        with pytest.raises(ValueError):
            state.set_choice(xid="nope")

    def test_serial_and_rounded(self, state):
        state.set_choice(xid="serial")
        assert state.xvals()[3] == 3.0
        state.set_choice(xid="rounded MJD")
        mjds = np.asarray(state.psr.all_toas.get_mjds(), float)
        np.testing.assert_array_equal(state.xvals(), np.floor(mjds + 0.5))
        state.set_choice(xid="mjd")


class TestSelectDeleteRefit:
    def test_full_interaction_flow(self):
        """select (rect + point) -> delete -> refit: TOA count and chi2
        both change; then fit-checkbox toggling changes the free set."""
        from pint_tpu.pintk.plkstate import PlkState
        from pint_tpu.pintk.pulsar import Pulsar

        st = PlkState(Pulsar(NGC_PAR, NGC_TIM))
        n0 = len(st.psr.all_toas)
        chi2_before = st.fit()
        free_before = list(st.psr.model.free_params)

        # rectangle selection in the current axes
        x = st.xvals()
        y, _ = st.yvals()
        nsel = st.select_rect(x.min(), x[np.argsort(x)[4]],
                              y.min() - 1, y.max() + 1)
        assert nsel >= 5

        # point toggle: nearest point selected, toggling again deselects
        i = st.toggle_point(x[7], y[7])
        assert i is not None and st.selected[i]
        st.toggle_point(x[7], y[7])

        # delete the selection; count drops, mask resets
        ndel = st.delete_selected()
        assert ndel == nsel
        assert len(st.psr.all_toas) == n0 - ndel
        assert st.selected.shape == (n0 - ndel,)

        chi2_after = st.fit()
        assert chi2_after != chi2_before
        assert np.isfinite(chi2_after)

        # per-point delete (right click)
        x = st.xvals()
        y, _ = st.yvals()
        j = st.delete_point(x[0], y[0])
        assert j is not None
        assert len(st.psr.all_toas) == n0 - ndel - 1

        # fit checkboxes are live state functions over the model
        boxes = dict(st.fit_checkboxes())
        assert boxes["F0"] is True
        st.set_fit("F0", False)
        assert st.get_fit("F0") is False
        assert "F0" not in st.psr.model.free_params
        st.set_fit("F0", True)
        assert list(st.psr.model.free_params) == free_before

    def test_point_delete_preserves_selection_and_last_toa_guard(self):
        from pint_tpu.pintk.plkstate import PlkState
        from pint_tpu.pintk.pulsar import Pulsar

        st = PlkState(Pulsar(NGC_PAR, NGC_TIM))
        n0 = len(st.psr.all_toas)
        x = st.xvals()
        y, _ = st.yvals()
        # select the 5 highest-x points, then right-click-delete the lowest
        order = np.argsort(x)
        st.selected[order[-5:]] = True
        i = st.delete_point(x[order[0]], y[order[0]])
        assert i is not None and len(st.psr.all_toas) == n0 - 1
        # the selection survives, shifted past the removed index
        assert int(st.selected.sum()) == 5
        assert st.delete_selected() == 5
        # refuse to delete every TOA
        st.selected[:] = True
        assert st.delete_selected() == 0
        assert len(st.psr.all_toas) == n0 - 6

    def test_stash_round_trip(self):
        from pint_tpu.pintk.plkstate import PlkState
        from pint_tpu.pintk.pulsar import Pulsar

        st = PlkState(Pulsar(NGC_PAR, NGC_TIM))
        n0 = len(st.psr.all_toas)
        st.selected[:6] = True
        assert st.stash_selected() == 6
        assert len(st.psr.all_toas) == n0 - 6
        # empty selection + existing stash -> un-stash (reference 't')
        assert st.stash_selected() == -6
        assert len(st.psr.all_toas) == n0

    def test_phase_wrap_changes_residuals(self):
        from pint_tpu.pintk.plkstate import PlkState
        from pint_tpu.pintk.pulsar import Pulsar
        from pint_tpu.residuals import Residuals

        st = PlkState(Pulsar(NGC_PAR, NGC_TIM))
        r0 = Residuals(st.psr.all_toas, st.psr.model,
                       track_mode="use_pulse_numbers") \
            if st.psr.all_toas.pulse_number is not None \
            else st.psr.resids()
        chi0 = r0.chi2
        st.selected[:10] = True
        st.phase_wrap(1)
        r1 = st.psr.resids()
        assert r1.chi2 != pytest.approx(chi0)

    def test_jump_selected_adds_param(self):
        from pint_tpu.pintk.plkstate import PlkState
        from pint_tpu.pintk.pulsar import Pulsar

        st = PlkState(Pulsar(NGC_PAR, NGC_TIM))
        st.selected[:8] = True
        name = st.jump_selected()
        assert name is not None and name.startswith("JUMP")
        assert "PhaseJump" in st.psr.model.components
        assert name in st.psr.model.components["PhaseJump"].params

    def test_prefit_stays_prefit_after_fit(self):
        """After a fit, the 'pre-fit' y view must still show residuals of
        the INITIAL model, distinct from 'post-fit'."""
        from pint_tpu.pintk.plkstate import PlkState
        from pint_tpu.pintk.pulsar import Pulsar

        st = PlkState(Pulsar(NGC_PAR, NGC_TIM))
        st.set_choice(yid="pre-fit")
        y_pre0, _ = st.yvals()
        st.fit()
        y_pre1, _ = st.yvals()
        st.set_choice(yid="post-fit")
        y_post, _ = st.yvals()
        np.testing.assert_allclose(y_pre0, y_pre1)  # unchanged by the fit
        assert not np.allclose(y_pre1, y_post)
        assert st.last_resids is not None

    def test_loglevel(self, state):
        import logging

        state.set_loglevel("DEBUG")
        from pint_tpu.logging import log

        assert log.level == logging.DEBUG
        state.set_loglevel("INFO")
