"""Noise components + GLS fitter tests.

Mirrors the reference test strategy (SURVEY §4): simulation-as-fixture with
known injected noise, cross-fitter chi2 agreement (WLS vs GLS, Woodbury vs
full-covariance), and hand-checked basis/weight formulas.
"""

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


@pytest.fixture(scope="module")
def model():
    from pint_tpu.models import get_model

    return get_model(NGC_PAR)


@pytest.fixture(scope="module")
def toas(model):
    from pint_tpu.simulation import make_fake_toas_uniform

    return make_fake_toas_uniform(53000, 54800, 60, model, error_us=2.0,
                                  add_noise=True, rng=np.random.default_rng(3))


def _model_with_lines(extra_lines):
    from pint_tpu.io.par import parse_parfile
    from pint_tpu.models import get_model

    with open(NGC_PAR) as f:
        text = f.read()
    return get_model(parse_parfile(text + "\n" + "\n".join(extra_lines) + "\n"))


class TestScaleToaError:
    def test_efac_equad(self, toas):
        m = _model_with_lines(["EFAC mjd 52000 60000 1.5",
                               "EQUAD mjd 52000 60000 3.0"])
        sig = m.scaled_toa_uncertainty(toas)
        raw = toas.get_errors() * 1e-6
        expect = 1.5 * np.hypot(raw, 3.0e-6)
        assert np.allclose(sig, expect, rtol=1e-12)

    def test_tneq_converts_to_equad(self, toas):
        # TNEQ in log10-seconds: -5.52 -> ~3.02 us equad
        m = _model_with_lines(["TNEQ mjd 52000 60000 -5.52"])
        comp = m.components["ScaleToaError"]
        eq = comp._params_dict["EQUAD1"]
        assert eq.value == pytest.approx(10 ** -5.52 * 1e6)
        sig = m.scaled_toa_uncertainty(toas)
        raw = toas.get_errors() * 1e-6
        assert np.allclose(sig, np.hypot(raw, 10 ** -5.52), rtol=1e-12)

    def test_t2efac_alias(self, toas):
        """tempo2-style T2EFAC lines must set a real EFAC (not be ignored)."""
        m = _model_with_lines(["T2EFAC mjd 52000 60000 1.7"])
        sig = m.scaled_toa_uncertainty(toas)
        raw = toas.get_errors() * 1e-6
        assert np.allclose(sig, 1.7 * raw, rtol=1e-12)

    def test_tneq_with_unrelated_equad(self, toas):
        """A TNEQ must not clobber an EQUAD with a different selection."""
        m = _model_with_lines(["EQUAD mjd 52000 53500 0.5",
                               "TNEQ mjd 53500 60000 -7"])
        comp = m.components["ScaleToaError"]
        assert comp._params_dict["EQUAD1"].value == 0.5
        assert comp._params_dict["EQUAD2"].value == pytest.approx(1e-7 * 1e6)
        assert comp._params_dict["EQUAD2"].key_value == ["53500", "60000"] or \
            [float(v) for v in comp._params_dict["EQUAD2"].key_value] == [53500.0, 60000.0]

    def test_free_noise_param_not_in_designmatrix(self, toas):
        """A fit-flagged noise parameter gets no design column and does not
        inflate ntmpar (noise-amplitude slicing depends on this)."""
        m = _model_with_lines(["TNREDAMP -13.5 1", "TNREDGAM 3.0", "TNREDC 5"])
        assert "TNREDAMP" in m.free_params
        M, names, _ = m.designmatrix(toas)
        assert "TNREDAMP" not in names
        assert m.ntmpar == M.shape[1]

    def test_duplicate_selection_rejected(self):
        with pytest.raises(ValueError, match="[Dd]uplicate"):
            _model_with_lines(["EFAC mjd 52000 60000 1.5",
                               "EFAC mjd 52000 60000 1.2"])


class TestEcorr:
    def test_quantization_matrix(self):
        from pint_tpu.models.noise_model import ecorr_quantization_matrix

        # two clusters within 1s, one singleton (dropped)
        t = np.array([0.0, 0.3, 100.0, 200.0, 200.4, 200.9])
        U = ecorr_quantization_matrix(t)
        assert U.shape == (6, 2)
        assert U[:, 0].tolist() == [1, 1, 0, 0, 0, 0]
        assert U[:, 1].tolist() == [0, 0, 0, 1, 1, 1]

    def test_basis_weight_and_chi2_consistency(self, toas):
        """Sherman-Morrison chi2 equals dense covariance chi2."""
        from pint_tpu.residuals import Residuals
        import copy

        # cluster TOAs: duplicate each epoch (within <1s) so ECORR applies
        t2 = copy.deepcopy(toas)
        t2.utc_mjd = np.concatenate([t2.utc_mjd, t2.utc_mjd + 0.5 / 86400])
        t2.error_us = np.concatenate([t2.error_us] * 2)
        t2.freq_mhz = np.concatenate([t2.freq_mhz] * 2)
        t2.obs = np.concatenate([t2.obs] * 2)
        t2.flags = t2.flags * 2
        t2.clock_corr_s = None
        t2.tdb = None
        t2.apply_clock_corrections()
        t2.compute_TDBs()
        t2.compute_posvels()

        m = _model_with_lines(["ECORR mjd 52000 60000 1.2"])
        U, w = m.noise_model_basis_weight(t2)
        assert U.shape[0] == len(t2) and U.shape[1] == len(w) > 0
        assert np.allclose(w, (1.2e-6) ** 2)

        r = Residuals(t2, m)
        chi2_sm = r.calc_chi2()
        # dense check: r^T C^-1 r
        res = r.time_resids
        C = m.toa_covariance_matrix(t2)
        chi2_dense = float(res @ np.linalg.solve(C, res))
        assert chi2_sm == pytest.approx(chi2_dense, rel=1e-8)


class TestPLRedNoise:
    def test_weights_formula(self, toas):
        from pint_tpu.models.noise_model import FYR

        m = _model_with_lines(["TNREDAMP -13.5", "TNREDGAM 3.1", "TNREDC 10"])
        U, w = m.noise_model_basis_weight(toas)
        assert U.shape == (len(toas), 20)
        t = np.asarray(toas.tdb, dtype=float) * 86400.0
        T = t.max() - t.min()
        f = np.arange(1, 11) / T
        A, gam = 10 ** -13.5, 3.1
        psd = A**2 / 12 / np.pi**2 * FYR ** (gam - 3) * f ** -gam
        expect = np.repeat(psd, 2) * np.repeat(np.diff(np.r_[0.0, f]), 2)
        assert np.allclose(w, expect, rtol=1e-10)

    def test_rnamp_conversion(self, toas):
        m1 = _model_with_lines(["TNREDAMP -13.0", "TNREDGAM 4.0", "TNREDC 5"])
        fac = (86400.0 * 365.24 * 1e6) / (2.0 * np.pi * np.sqrt(3.0))
        m2 = _model_with_lines([f"RNAMP {1e-13 * fac:.10e}", "RNIDX -4.0",
                                "TNREDC 5"])
        _, w1 = m1.noise_model_basis_weight(toas)
        _, w2 = m2.noise_model_basis_weight(toas)
        assert np.allclose(w1, w2, rtol=1e-6)

    def test_log_spaced_modes(self, toas):
        m = _model_with_lines(["TNREDAMP -13.5", "TNREDGAM 3.1", "TNREDC 4",
                               "TNREDFLOG 3", "TNREDFLOG_FACTOR 2"])
        U, w = m.noise_model_basis_weight(toas)
        assert U.shape[1] == 2 * (4 + 3)
        t = np.asarray(toas.tdb, dtype=float) * 86400.0
        T = t.max() - t.min()
        # first log mode at 1/(2^3 T)
        comp = m.components["PLRedNoise"]
        _, f = comp.get_time_frequencies(toas)
        assert f[0] == pytest.approx(1 / (8 * T))
        assert f[3] == pytest.approx(1 / T)


class TestPLChromaticFamilies:
    def test_pldm_scaling(self, toas):
        m = _model_with_lines(["TNDMAMP -13.2", "TNDMGAM 2.5", "TNDMC 6"])
        mr = _model_with_lines(["TNREDAMP -13.2", "TNREDGAM 2.5", "TNREDC 6"])
        Udm, wdm = m.noise_model_basis_weight(toas)
        Ur, wr = mr.noise_model_basis_weight(toas)
        assert np.allclose(wdm, wr, rtol=1e-10)
        # DM basis is the achromatic basis scaled by (1400/f_bary)^2 per TOA
        ratio = Udm / Ur
        assert np.allclose(ratio, ratio[:, :1], rtol=1e-9)


class TestGLSFitter:
    def test_gls_matches_wls_when_diagonal(self, toas):
        """With only EFAC/EQUAD (no correlated noise), GLS == WLS."""
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.gls_fitter import GLSFitter

        m = _model_with_lines(["EFAC mjd 52000 60000 1.3"])
        f1 = WLSFitter(toas, m)
        c1 = f1.fit_toas()
        f2 = GLSFitter(toas, m)
        c2 = f2.fit_toas()
        assert c2 == pytest.approx(c1, rel=1e-6)
        for p in m.free_params:
            v1 = getattr(f1.model, p).value
            v2 = getattr(f2.model, p).value
            # agreement well inside the parameter uncertainty (DM is nearly
            # degenerate for single-frequency fake TOAs)
            assert abs(v2 - v1) < 1e-3 * f1.errors[p]
            assert f2.errors[p] == pytest.approx(f1.errors[p], rel=1e-4)

    def test_gls_full_cov_agrees_with_woodbury(self, toas):
        from pint_tpu.gls_fitter import GLSFitter

        m = _model_with_lines(["TNREDAMP -12.5", "TNREDGAM 3.0", "TNREDC 8"])
        f1 = GLSFitter(toas, m)
        c1 = f1.fit_toas(full_cov=False)
        f2 = GLSFitter(toas, m)
        c2 = f2.fit_toas(full_cov=True)
        assert c2 == pytest.approx(c1, rel=1e-6)
        for p in m.free_params:
            assert abs(getattr(f2.model, p).value
                       - getattr(f1.model, p).value) < 1e-3 * f1.errors[p]

    def test_gls_recovers_injected_offset(self, model, toas):
        """Perturb F0/F1; GLS with red noise still recovers them."""
        import copy
        from pint_tpu.gls_fitter import GLSFitter

        m = _model_with_lines(["TNREDAMP -13.0", "TNREDGAM 3.0", "TNREDC 5"])
        m2 = copy.deepcopy(m)
        m2.F0.value = m2.F0.value + 1e-9
        m2.F1.value = m2.F1.value * 1.001
        f = GLSFitter(toas, m2)
        f.fit_toas(maxiter=3)
        assert f.model.F0.value == pytest.approx(model.F0.value, abs=5e-10)
        assert f.resids.noise_ampls["PLRedNoise"].shape == (10,)

    def test_downhill_gls(self, toas):
        import copy
        from pint_tpu.gls_fitter import DownhillGLSFitter

        m = _model_with_lines(["TNREDAMP -13.0", "TNREDGAM 3.0", "TNREDC 5"])
        m.F0.value = m.F0.value + 5e-10
        f = DownhillGLSFitter(toas, m)
        chi2 = f.fit_toas()
        assert np.isfinite(chi2)
        assert f.converged

    def test_auto_dispatch(self, toas):
        from pint_tpu.fitter import Fitter
        from pint_tpu.gls_fitter import DownhillGLSFitter

        m = _model_with_lines(["TNREDAMP -13.0", "TNREDGAM 3.0", "TNREDC 5"])
        f = Fitter.auto(toas, m)
        assert isinstance(f, DownhillGLSFitter)


class TestWoodburyRangeSafety:
    """The scaled-basis Woodbury form (V = U sqrt(phi), Sigma = I + V^T
    N^-1 V) must stay finite across the full prior dynamic range.  The
    textbook diag(1/phi) + U^T N^-1 U form evaluates 1/phi and log(phi),
    which overflow TPU f64 emulation's float32 RANGE at the 1e40 offset
    prior (measured round 5, logdet NaN on device) — and go inf even on
    CPU for subnormal phi, which is what this CPU-runnable test uses to
    distinguish the forms."""

    def test_subnormal_phi_finite_and_correct(self):
        import jax
        import jax.numpy as jnp

        from pint_tpu.utils import woodbury_dot

        rng = np.random.default_rng(11)
        n, m = 40, 6
        U = rng.standard_normal((n, m))
        sigma2 = rng.uniform(0.5, 2.0, n) * 1e-12
        r = rng.standard_normal(n) * 1e-6
        # phi so small that 1/phi == inf in ANY IEEE f64 path: the unscaled
        # form would poison Sigma with inf; the scaled form must reduce to
        # the pure white-noise answer
        phi = np.full(m, 1e-310)
        dot, logdet = jax.jit(woodbury_dot)(
            jnp.asarray(sigma2), jnp.asarray(U), jnp.asarray(phi),
            jnp.asarray(r), jnp.asarray(r))
        assert np.isfinite(float(dot)) and np.isfinite(float(logdet))
        np.testing.assert_allclose(float(dot), float(np.sum(r * r / sigma2)),
                                   rtol=1e-9)
        np.testing.assert_allclose(float(logdet),
                                   float(np.sum(np.log(sigma2))), rtol=1e-9)

    def test_huge_prior_matches_dense(self):
        """Large (offset-scale 1e10) and tiny weights together, checked
        against a dense-covariance solve.  C spans ~22 decades, far past
        f64 dense-solve conditioning, so the reference is a 50-digit
        mpmath LU (same technique as tests/test_gls_oracle.py)."""
        import jax
        import jax.numpy as jnp

        mp = pytest.importorskip("mpmath")
        from pint_tpu.utils import woodbury_dot

        rng = np.random.default_rng(12)
        n, m = 30, 4
        U = np.hstack([rng.standard_normal((n, m - 1)), np.ones((n, 1))])
        sigma2 = rng.uniform(0.5, 2.0, n) * 1e-12
        r = rng.standard_normal(n) * 1e-6
        phi = np.array([1e-18, 1e-14, 1e-12, 1e10])
        with mp.workdps(50):
            C = mp.zeros(n)
            for i in range(n):
                C[i, i] = mp.mpf(sigma2[i])
                for j in range(n):
                    for k in range(m):
                        C[i, j] += mp.mpf(phi[k]) * mp.mpf(U[i, k]) \
                            * mp.mpf(U[j, k])
            rv = mp.matrix([mp.mpf(x) for x in r])
            x = mp.lu_solve(C, rv)
            dot_ref = float(sum(rv[i] * x[i] for i in range(n)))
            P, L, Umat = mp.lu(C)
            logdet_ref = float(sum(mp.log(abs(Umat[i, i]))
                                   for i in range(n)))
        dot, logdet = jax.jit(woodbury_dot)(
            jnp.asarray(sigma2), jnp.asarray(U), jnp.asarray(phi),
            jnp.asarray(r), jnp.asarray(r))
        np.testing.assert_allclose(float(dot), dot_ref, rtol=1e-7)
        np.testing.assert_allclose(float(logdet), logdet_ref, rtol=1e-9)
