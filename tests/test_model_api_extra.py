"""TimingModel user-API long tail: orbital kinematics, parameter dicts,
mask hygiene, jump deletion (reference ``timing_model.py:853-1100`` and
dict/mask helpers)."""

import io

import numpy as np
import pytest

BINARY_PAR = """
PSR  J9999+9999
RAJ  09:00:00
DECJ 09:00:00
POSEPOCH 55000
F0   300.0 1
PEPOCH 55000
DM   10.0
BINARY DD
PB   10.0 1
A1   20.0 1
T0   55000.0 1
ECC  0.3
OM   90.0
UNITS TDB
"""


@pytest.fixture(scope="module")
def bmodel():
    from pint_tpu.models import get_model

    return get_model(io.StringIO(BINARY_PAR))


class TestOrbitalKinematics:
    def test_is_binary(self, bmodel):
        from pint_tpu.models import get_model

        assert bmodel.is_binary is True
        m = get_model(["PSR X\n", "RAJ 1:00:00\n", "DECJ 2:00:00\n",
                       "F0 1.0\n", "PEPOCH 55000\n", "UNITS TDB\n"])
        assert m.is_binary is False

    def test_orbital_phase_anomalies(self, bmodel):
        # at T0 (periastron) every anomaly is zero
        for anom in ("mean", "ecc", "true"):
            assert bmodel.orbital_phase(55000.0, anom=anom)[0] == \
                pytest.approx(0.0, abs=1e-8)
        # half a period later the mean anomaly is pi
        assert bmodel.orbital_phase(55005.0, anom="mean")[0] == \
            pytest.approx(np.pi, rel=1e-10)
        # eccentric orbit: at M=pi, E=pi and nu=pi exactly
        assert bmodel.orbital_phase(55005.0, anom="true")[0] == \
            pytest.approx(np.pi, rel=1e-8)
        # quarter period: E and nu differ from M in the expected direction
        M = bmodel.orbital_phase(55002.5, anom="mean")[0]
        E = bmodel.orbital_phase(55002.5, anom="ecc")[0]
        nu = bmodel.orbital_phase(55002.5, anom="true")[0]
        assert M == pytest.approx(np.pi / 2, rel=1e-10)
        assert E > M and nu > E  # ecc=0.3 pushes later anomalies ahead
        # Kepler's equation holds
        assert E - 0.3 * np.sin(E) == pytest.approx(M, abs=1e-10)
        # cycles form
        assert bmodel.orbital_phase(55005.0, anom="mean", radians=False)[0] \
            == pytest.approx(0.5)
        with pytest.raises(ValueError):
            bmodel.orbital_phase(55000.0, anom="weird")

    def test_radial_velocity(self, bmodel):
        from pint_tpu import c as C

        # amplitude K = 2 pi a1 / (pb sqrt(1-e^2)) in light-s/s times c
        K = 2 * np.pi * 20.0 / (10 * 86400 * np.sqrt(1 - 0.09)) * C
        ts = 55000.0 + np.linspace(0, 10, 400)
        v = bmodel.pulsar_radial_velocity(ts)
        assert np.max(np.abs(v)) <= K * (1 + 0.3) * 1.001
        assert np.max(np.abs(v)) > K * 0.9
        vc = bmodel.companion_radial_velocity(ts, massratio=0.5)
        np.testing.assert_allclose(vc, -0.5 * v)

    def test_conjunction(self, bmodel):
        # OM=90 deg puts superior conjunction (nu + omega = pi/2) exactly at
        # periastron, so from T0+0.5 d the next one is T0+PB
        tc = bmodel.conjunction(55000.5)
        assert tc == pytest.approx(55010.0, abs=1e-6)
        nu = bmodel.orbital_phase(tc, anom="true")[0]
        om = np.deg2rad(90.0)
        assert np.remainder(nu + om + 1e-12, 2 * np.pi) == pytest.approx(
            np.pi / 2, abs=1e-5)
        # vector input
        tcs = bmodel.conjunction(np.array([55000.5, 55012.0]))
        assert len(tcs) == 2
        assert tcs[1] == pytest.approx(55020.0, abs=1e-6)


class TestParamDicts:
    def test_get_params_dict_and_mapping(self, bmodel):
        d = bmodel.get_params_dict("free", "value")
        assert set(d) == set(bmodel.free_params)
        u = bmodel.get_params_dict("all", "uncertainty")
        assert "ECC" in u
        m = bmodel.get_params_mapping()
        assert m["F0"] == "Spindown" and m["PB"] == "BinaryDD"
        with pytest.raises(ValueError):
            bmodel.get_params_dict("free", "nope")

    def test_set_values_and_uncertainties(self, bmodel):
        import copy

        m = copy.deepcopy(bmodel)
        m.set_param_values({"F0": 300.5, "ECC": 0.25})
        assert m.F0.value == 300.5 and m.ECC.value == 0.25
        m.set_param_uncertainties({"F0": 1e-9})
        assert m.F0.uncertainty == 1e-9

    def test_keys_items_ordered(self, bmodel):
        assert bmodel.params_ordered == bmodel.params
        assert "F0" in bmodel.keys()
        items = dict(bmodel.items())
        assert items["F0"].value == bmodel.F0.value


class TestMaskAndJumpHygiene:
    def test_find_empty_masks(self):
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        par = ["PSR M\n", "RAJ 03:00:00\n", "DECJ 3:00:00\n", "F0 99.0 1\n",
               "PEPOCH 55100\n", "DM 10\n", "UNITS TDB\n",
               "JUMP MJD 60000 60010 0.0 1\n"]  # range with no TOAs
        m = get_model(par)
        t = make_fake_toas_uniform(55000, 55200, 20, m, error_us=1.0)
        empty = m.find_empty_masks(t)
        assert empty == ["JUMP1"]
        assert not m.JUMP1.frozen
        m.find_empty_masks(t, freeze=True)
        assert m.JUMP1.frozen

    def test_delete_jump_and_flags(self):
        from pint_tpu.models import get_model
        from pint_tpu.pintk.pulsar import Pulsar
        from pint_tpu.simulation import make_fake_toas_uniform

        par = ["PSR D\n", "RAJ 03:00:00\n", "DECJ 3:00:00\n", "F0 99.0 1\n",
               "PEPOCH 55100\n", "DM 10\n", "UNITS TDB\n"]
        m = get_model(par)
        t = make_fake_toas_uniform(55000, 55200, 10, m, error_us=1.0)
        # stamp a gui jump the pintk way: flags + JUMP2 param
        from pint_tpu.models.jump import PhaseJump
        from pint_tpu.models.parameter import maskParameter

        m.add_component(PhaseJump(), validate=False)
        comp = m.components["PhaseJump"]
        for i in range(5):
            t.flags[i]["gui_jump"] = "2"
        comp.add_param(maskParameter("JUMP", index=2, key="-gui_jump",
                                     key_value=["2"], units="s", value=0.0,
                                     frozen=False), setup=True)
        m.setup()
        m.delete_jump_and_flags(t, 2)
        assert "JUMP2" not in m.params
        assert all("gui_jump" not in fl for fl in t.flags)
        with pytest.raises(ValueError):
            m.delete_jump_and_flags(t, 9)

    def test_add_tzr_toa_and_dispersion_slope(self):
        from pint_tpu import DMconst
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        par = ["PSR T\n", "RAJ 03:00:00\n", "DECJ 3:00:00\n", "F0 99.0 1\n",
               "PEPOCH 55100\n", "DM 10\n", "UNITS TDB\n"]
        m = get_model(par)
        t = make_fake_toas_uniform(55000, 55200, 5, m, error_us=1.0)
        assert "AbsPhase" not in m.components
        m.add_tzr_toa(t)
        assert "AbsPhase" in m.components
        assert float(m.TZRMJD.value) == pytest.approx(
            float(np.asarray(t.get_mjds())[0]), abs=1e-6)
        slope = m.total_dispersion_slope(t)
        np.testing.assert_allclose(slope, 10.0 * DMconst)

    def test_conjunction_eccentric_fast_sweep(self):
        """Regression: high-eccentricity orbit whose conjunction sits in the
        rapid periastron sweep must still be found, and the root must
        satisfy the defining equation (no wrap-discontinuity root)."""
        from pint_tpu.models import get_model

        par = ["PSR E\n", "RAJ 09:00:00\n", "DECJ 09:00:00\n",
               "POSEPOCH 55000\n", "F0 300.0\n", "PEPOCH 55000\n",
               "DM 10.0\n", "BINARY DD\n", "PB 10.0\n", "A1 20.0\n",
               "T0 55000.0\n", "ECC 0.85\n", "OM 250.0\n", "UNITS TDB\n"]
        m = get_model(par)
        for start in (55000.3, 55004.0, 55009.9):
            tc = m.conjunction(start)
            assert start < tc <= start + 10.0 + 1e-6
            nu = m.orbital_phase(tc, anom="true")[0]
            om = np.deg2rad(250.0)
            d = np.remainder(nu + om - np.pi / 2 + np.pi, 2 * np.pi) - np.pi
            assert abs(d) < 1e-6

    def test_delete_jump_strips_both_flag_conventions(self):
        from pint_tpu.models import get_model
        from pint_tpu.models.jump import PhaseJump
        from pint_tpu.models.parameter import maskParameter
        from pint_tpu.simulation import make_fake_toas_uniform

        par = ["PSR D2\n", "RAJ 03:00:00\n", "DECJ 3:00:00\n", "F0 99.0 1\n",
               "PEPOCH 55100\n", "DM 10\n", "UNITS TDB\n"]
        m = get_model(par)
        t = make_fake_toas_uniform(55000, 55200, 10, m, error_us=1.0)
        m.add_component(PhaseJump(), validate=False)
        comp = m.components["PhaseJump"]
        comp.add_param(maskParameter("JUMP", index=2, key="-gui_jump",
                                     key_value=["2"], units="s", value=0.0,
                                     frozen=False), setup=True)
        for i in range(4):
            t.flags[i]["gui_jump"] = "2"
            t.flags[i]["jump"] = "2"  # jump_params_to_flags convention
        m.setup()
        m.delete_jump_and_flags(t, 2)
        assert all("gui_jump" not in fl and "jump" not in fl
                   for fl in t.flags)

    def test_ell1_kinematics_semantics(self):
        """ELL1 has no periastron: ecc/true anomalies raise; mean phase is
        from TASC; conjunction is at Phi = pi/2; RV is the circular limit."""
        from pint_tpu import c as C
        from pint_tpu.models import get_model

        par = ["PSR L\n", "RAJ 09:00:00\n", "DECJ 09:00:00\n",
               "POSEPOCH 55000\n", "F0 300.0\n", "PEPOCH 55000\n",
               "DM 10.0\n", "BINARY ELL1\n", "PB 1.5\n", "A1 5.0\n",
               "TASC 55000.0\n", "EPS1 1e-3\n", "EPS2 2e-3\n",
               "UNITS TDB\n"]
        m = get_model(par)
        with pytest.raises(ValueError):
            m.orbital_phase(55000.5, anom="true")
        with pytest.raises(ValueError):
            m.orbital_phase(55000.5, anom="ecc")
        assert m.orbital_phase(55000.75, anom="mean", radians=False)[0] == \
            pytest.approx(0.5)
        assert m.conjunction(55000.1) == pytest.approx(55000.375, abs=1e-9)
        v = m.pulsar_radial_velocity(55000.0 + np.linspace(0, 1.5, 300))
        K = 2 * np.pi * 5.0 / (1.5 * 86400) * C
        assert np.max(np.abs(v)) == pytest.approx(K, rel=1e-3)

    def test_get_params_dict_bad_which(self, bmodel):
        with pytest.raises(ValueError):
            bmodel.get_params_dict("typo", "value")
