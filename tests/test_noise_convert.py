"""WaveX <-> power-law noise conversions (reference ``utils.py:1449,3216,3370``)."""

import numpy as np
import pytest

BASE_PAR = ["PSR NC\n", "RAJ 01:00:00 1\n", "DECJ 10:00:00 1\n",
            "F0 100.0 1\n", "F1 -1e-14 1\n", "PEPOCH 55000\n", "DM 10\n",
            "UNITS TDB\n"]


def _model():
    from pint_tpu.models import get_model

    return get_model(BASE_PAR)


class TestWavexSetup:
    def test_n_freqs(self):
        from pint_tpu.noise_convert import wavex_setup

        m = _model()
        idx = wavex_setup(m, 1000.0, n_freqs=5)
        assert idx == [1, 2, 3, 4, 5]
        assert "WaveX" in m.components
        fs = [float(getattr(m, f"WXFREQ_{i:04d}").value) for i in idx]
        assert np.allclose(fs, np.arange(1, 6) / 1000.0)

    def test_explicit_freqs_and_errors(self):
        from pint_tpu.noise_convert import wavex_setup

        m = _model()
        idx = wavex_setup(m, 1000.0, freqs=[0.003, 0.001])
        fs = [float(getattr(m, f"WXFREQ_{i:04d}").value) for i in idx]
        assert fs == [0.001, 0.003]  # sorted
        with pytest.raises(ValueError):
            wavex_setup(_model(), 1000.0)
        with pytest.raises(ValueError):
            wavex_setup(_model(), 1000.0, freqs=[0.1], n_freqs=3)


class TestPLFromWavex:
    def test_exact_recovery(self):
        """Amplitudes placed exactly at the power-law sigma must recover the
        spectral parameters (the ML estimator is exact there)."""
        from pint_tpu.models.noise_model import powerlaw
        from pint_tpu.noise_convert import plrednoise_from_wavex, wavex_setup

        m = _model()
        idx = wavex_setup(m, 1000.0, n_freqs=12)
        A, g = 10**-13.2, 3.7
        fs = np.array([float(getattr(m, f"WXFREQ_{i:04d}").value)
                       for i in idx]) / 86400.0
        sig = np.sqrt(powerlaw(fs, A, g) * fs.min())
        for i, s in zip(idx, sig):
            getattr(m, f"WXSIN_{i:04d}").value = float(s)
            getattr(m, f"WXCOS_{i:04d}").value = float(s)
        m2 = plrednoise_from_wavex(m, ignore_fyr=False)
        assert "WaveX" not in m2.components
        assert "PLRedNoise" in m2.components
        assert float(m2.TNREDAMP.value) == pytest.approx(-13.2, abs=1e-3)
        assert float(m2.TNREDGAM.value) == pytest.approx(3.7, abs=1e-3)
        assert int(m2.TNREDC.value) == 12

    def test_dmwavex_roundtrip(self):
        from pint_tpu import DMconst
        from pint_tpu.models.noise_model import powerlaw
        from pint_tpu.noise_convert import (dmwavex_setup,
                                            pldmnoise_from_dmwavex)

        m = _model()
        idx = dmwavex_setup(m, 1200.0, n_freqs=8)
        A, g = 10**-13.8, 2.5
        fs = np.array([float(getattr(m, f"DMWXFREQ_{i:04d}").value)
                       for i in idx]) / 86400.0
        sig = np.sqrt(powerlaw(fs, A, g) * fs.min()) / (DMconst / 1400.0**2)
        for i, s in zip(idx, sig):
            getattr(m, f"DMWXSIN_{i:04d}").value = float(s)
            getattr(m, f"DMWXCOS_{i:04d}").value = float(s)
        m2 = pldmnoise_from_dmwavex(m, ignore_fyr=False)
        assert "PLDMNoise" in m2.components
        assert float(m2.TNDMAMP.value) == pytest.approx(-13.8, abs=1e-3)
        assert float(m2.TNDMGAM.value) == pytest.approx(2.5, abs=1e-3)


class TestOptimalNharms:
    def test_flat_data_prefers_zero(self):
        """White-noise-only data: AIC must pick 0 harmonics."""
        from pint_tpu.noise_convert import find_optimal_nharms
        from pint_tpu.simulation import make_fake_toas_uniform

        m = _model()
        t = make_fake_toas_uniform(54500, 55500, 40, m, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(3))
        n, aics = find_optimal_nharms(m, t, nharms_max=3)
        assert n == 0
        assert aics[0] == 0.0
        assert len(aics) == 4


class TestWaveWavexTranslation:
    WAVE_PAR = BASE_PAR + [
        "WAVEEPOCH 55000\n", "WAVE_OM 0.00423 1\n",
        "WAVE1 0.0021 -0.0013\n", "WAVE2 -0.0008 0.0004\n",
        "WAVE3 0.0003 0.0002\n",
    ]

    def test_roundtrip_preserves_residuals(self):
        """Wave -> WaveX -> Wave keeps the model's residuals to sub-ns
        (the two representations are algebraically equivalent)."""
        from pint_tpu.models import get_model
        from pint_tpu.noise_convert import (translate_wave_to_wavex,
                                            translate_wavex_to_wave)
        from pint_tpu.residuals import Residuals
        from pint_tpu.simulation import make_fake_toas_uniform

        m = get_model(self.WAVE_PAR)
        t = make_fake_toas_uniform(54500, 55500, 50, m, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(11))
        r0 = np.asarray(Residuals(t, m).time_resids)
        mx = translate_wave_to_wavex(m)
        assert "Wave" not in mx.components and "WaveX" in mx.components
        rx = np.asarray(Residuals(t, mx).time_resids)
        assert np.max(np.abs(rx - r0)) < 1e-9
        mw = translate_wavex_to_wave(mx)
        assert "WaveX" not in mw.components and "Wave" in mw.components
        rw = np.asarray(Residuals(t, mw).time_resids)
        assert np.max(np.abs(rw - r0)) < 1e-9
        assert float(mw.WAVE_OM.value) == pytest.approx(0.00423, rel=1e-12)

    def test_non_harmonic_wavex_rejected(self):
        from pint_tpu.models import get_model
        from pint_tpu.noise_convert import (translate_wavex_to_wave,
                                            wavex_setup)

        m = get_model(BASE_PAR)
        wavex_setup(m, 1000.0, freqs=[0.001, 0.0025])  # not harmonics
        with pytest.raises(ValueError, match="harmonics"):
            translate_wavex_to_wave(m)
