"""Labeled matrices (pint_matrix) and the LM/Powell fitter family —
cross-fitter consistency in the reference's style
(``tests/test_fitter_compare.py``, SURVEY §4)."""

import io

import numpy as np
import pytest

PAR = """
PSR  J0000+0000
RAJ  04:37:00.0
DECJ -47:15:00.0
POSEPOCH 55000
F0   173.6879489990983 1
F1   -1.728e-15 1
PEPOCH 55000
DM   2.64476 1
EPHEM DE440
UNITS TDB
"""


def _model(extra=""):
    from pint_tpu.models import get_model

    return get_model(io.StringIO(PAR + extra))


@pytest.fixture(scope="module")
def sim():
    from pint_tpu.simulation import make_fake_toas_uniform

    m = _model()
    t = make_fake_toas_uniform(54000, 55500, 60, m, freq=1400.0, error_us=1.0,
                               add_noise=True, rng=np.random.default_rng(3))
    return m, t


class TestPintMatrix:
    def test_design_matrix_labels(self, sim):
        from pint_tpu.pint_matrix import DesignMatrixMaker

        m, t = sim
        d = DesignMatrixMaker("toa", "s")(t, m, m.free_params)
        # RAJ/DECJ carry no fit flag in PAR, so they are frozen
        assert d.derivative_params == ["Offset", "F0", "F1", "DM"]
        assert d.shape == (len(t), 4)
        assert d.get_label_size("F0", axis=1) == 1
        assert d.derivative_quantity == ["toa"]

    def test_label_matrix_subset(self, sim):
        from pint_tpu.pint_matrix import DesignMatrixMaker

        m, t = sim
        d = DesignMatrixMaker("toa", "s")(t, m, m.free_params)
        sub = d.get_label_matrix(["F0", "F1"])
        assert sub.matrix.shape[1] == 2
        i0 = d.derivative_params.index("F0")
        np.testing.assert_array_equal(sub.matrix[:, 0], d.matrix[:, i0])

    def test_combine_by_quantity(self, sim):
        from pint_tpu.pint_matrix import (DesignMatrixMaker,
                                          combine_design_matrices_by_quantity)

        m, t = sim
        dt = DesignMatrixMaker("toa", "s")(t, m, m.free_params)
        # make a dm-quantity matrix with matching columns
        t.update_dms(m.total_dm(t), np.full(len(t), 1e-4))
        dd = DesignMatrixMaker("dm", "pc/cm3")(t, m, m.free_params)
        c = combine_design_matrices_by_quantity([dt, dd])
        assert c.shape == (2 * len(t), 4)
        assert c.get_label("toa", 0)[0][2:4] == (0, len(t))
        assert c.get_label("dm", 0)[0][2:4] == (len(t), 2 * len(t))

    def test_combine_by_param_and_covariance(self, sim):
        from pint_tpu.pint_matrix import (CovarianceMatrixMaker,
                                          DesignMatrixMaker,
                                          combine_covariance_matrix,
                                          combine_design_matrices_by_param)

        m, t = sim
        d1 = DesignMatrixMaker("toa", "s")(t, m, m.free_params)
        d2 = DesignMatrixMaker("toa", "s")(t, m, m.free_params)
        # rename columns of d2 to avoid collision
        d2.axis_labels[1] = {f"B_{k}": v for k, v in d2.axis_labels[1].items()}
        c = combine_design_matrices_by_param(d1, d2)
        assert c.shape == (len(t), 8)
        cov = CovarianceMatrixMaker("toa", "s")(t, m)
        cc = combine_covariance_matrix([cov, cov])
        assert cc.shape == (2 * len(t), 2 * len(t))
        corr = cov.to_correlation_matrix()
        np.testing.assert_allclose(np.diag(corr.matrix), 1.0)

    def test_overlap_rejected(self):
        from pint_tpu.pint_matrix import PintMatrix

        with pytest.raises(ValueError):
            PintMatrix(np.zeros((4, 2)),
                       [{"a": (0, 3, "s"), "b": (2, 4, "s")},
                        {"x": (0, 2, "")}])

    def test_covariance_prettyprint(self, sim):
        m, t = sim
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.pint_matrix import CovarianceMatrix

        f = WLSFitter(t, m)
        f.fit_toas()
        # fitters now hand back the labeled matrix directly (reference
        # fitter.py parameter_covariance_matrix)
        cm = f.parameter_covariance_matrix
        assert isinstance(cm, CovarianceMatrix)
        assert "F0" in cm.get_label_names(axis=0)
        s = cm.prettyprint()
        assert "F0" in s and "Offset" not in s


class TestLMFitter:
    def test_lm_matches_wls(self, sim):
        from pint_tpu.fitter import LMFitter, WLSFitter

        m, t = sim
        m1 = _model(); m1.F0.value += 3e-10
        m2 = _model(); m2.F0.value += 3e-10
        f1 = WLSFitter(t, m1); c1 = f1.fit_toas(maxiter=3)
        f2 = LMFitter(t, m2); c2 = f2.fit_toas()
        assert f2.converged
        assert abs(c1 - c2) / c1 < 1e-6
        assert abs(f1.model.F0.value - f2.model.F0.value) < 1e-13
        # uncertainties agree at the few-percent level
        assert f2.errors["F0"] == pytest.approx(f1.errors["F0"], rel=0.05)

    def test_lm_with_noise_model(self, sim):
        from pint_tpu.fitter import LMFitter
        from pint_tpu.gls_fitter import GLSFitter

        _, t = sim
        extra = "EFAC -fe 430 1.3\nECORR -fe 430 0.5\n"
        for fl in t.flags:
            fl.setdefault("fe", "430")
        t._version += 1
        m1 = _model(extra)
        m2 = _model(extra)
        f1 = GLSFitter(t, m1); c1 = f1.fit_toas(maxiter=2)
        f2 = LMFitter(t, m2); c2 = f2.fit_toas()
        assert abs(c1 - c2) / c1 < 1e-3
        assert abs(f1.model.F0.value - f2.model.F0.value) < 5e-13

    def test_wideband_lm(self, sim):
        from pint_tpu.wideband import WidebandLMFitter, WidebandTOAFitter

        m, t = sim
        t.update_dms(m.total_dm(t) + 1e-4 * np.random.default_rng(0).standard_normal(len(t)),
                     np.full(len(t), 1e-4))
        m1 = _model(); m1.DM.value += 2e-3
        m2 = _model(); m2.DM.value += 2e-3
        f1 = WidebandTOAFitter(t, m1); c1 = f1.fit_toas(maxiter=3)
        f2 = WidebandLMFitter(t, m2); c2 = f2.fit_toas()
        assert abs(c1 - c2) / c1 < 1e-4
        assert abs(f1.model.DM.value - f2.model.DM.value) < 1e-7


class TestPowellFitter:
    def test_powell_refines_f0(self, sim):
        from pint_tpu.fitter import PowellFitter, WLSFitter

        _, t = sim
        # seed Powell from a WLS fit (uncertainty-scaled steps), nudge F0
        m0 = _model(); m0.F0.value += 2e-10
        w = WLSFitter(t, m0)
        cw = w.fit_toas(maxiter=2)
        m1 = w.model
        m1.F0.value += 5e-11  # perturb after fit; Powell should pull it back
        f = PowellFitter(t, m1)
        c = f.fit_toas(maxiter=8)
        assert c <= WLSFitter(t, m1).resids.chi2 + 1e-9
        assert abs(c - cw) / cw < 0.05
