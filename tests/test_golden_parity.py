"""Golden-file parity against tempo2 residual dumps (reference test
strategy pillar (a), SURVEY §4: ``tests/test_B1855.py:34-46``).

Exact parity (3e-8 s) requires a numerical JPL ephemeris kernel and clock
files, neither of which ship in this zero-egress image — 1 arcsec of Earth
position is already 2.4 ms of Roemer delay, so no analytic series can reach
it.  The exact-parity tests therefore skip unless a ``.bsp`` kernel is found
on the ephemeris search path; the structural smoke tests (real NANOGrav
par/tim at scale) always run.
"""

import glob
import os

import numpy as np
import pytest

DATADIR = "/root/reference/tests/datafile"
B1855_PAR = f"{DATADIR}/B1855+09_NANOGrav_dfg+12_TAI_FB90.par"
B1855_TIM = f"{DATADIR}/B1855+09_NANOGrav_dfg+12.tim"


def _kernel_available() -> bool:
    from pint_tpu.ephemeris import _search_paths

    return any(glob.glob(os.path.join(d, "*.bsp")) for d in _search_paths()
               if os.path.isdir(d))


analytic_only = pytest.mark.skipif(
    _kernel_available(),
    reason="asserts calibrated to the analytic-ephemeris error budget; a "
    "real kernel changes the residual scale entirely")

needs_kernel = pytest.mark.skipif(
    not _kernel_available(),
    reason="no JPL .bsp kernel on the ephemeris search path; analytic "
    "fallback is ~2 ms (1 arcsec at 1 AU), far above the 3e-8 s golden bar")


@pytest.fixture(scope="module")
def b1855():
    from pint_tpu.models import get_model_and_toas

    if not os.path.exists(B1855_TIM):
        pytest.skip("reference datafiles unavailable")
    return get_model_and_toas(B1855_PAR, B1855_TIM)


class TestRealDataSmoke:
    """Full pipeline on real NANOGrav data (no kernel needed): parse,
    evaluate, design matrix — structure and finiteness, not absolute ns."""

    @analytic_only
    def test_load_and_residuals(self, b1855):
        from pint_tpu.residuals import Residuals

        model, toas = b1855
        assert len(toas) > 600  # dfg+12 dataset: 702 TOAs
        r = Residuals(toas, model)
        res = np.asarray(r.time_resids)
        assert np.all(np.isfinite(res))
        # with the analytic ephemeris the error budget is dominated by
        # ~1 arcsec of Earth position = up to ~2.4 ms of Roemer delay; a
        # *badly* wrong ephemeris (or a broken delay chain) blows well past
        # this, and a correct one cannot sit below the real data scatter
        assert 1e-6 < np.sqrt(np.mean(res**2)) < 2.5e-3
        P = 1.0 / float(model.F0.value)
        assert np.max(np.abs(res)) <= P

    @analytic_only
    def test_fit_reduces_chi2(self, b1855):
        """A WLS fit on the real data must substantially reduce chi2 and
        converge to a stationary point (catches broken design matrices that
        finiteness checks miss)."""
        import copy

        from pint_tpu.fitter import WLSFitter

        model, toas = b1855
        m = copy.deepcopy(model)
        # the ~2 ms analytic-ephemeris systematics alias into the binary and
        # parallax parameters (SINI walks past 1); freeze them and fit the
        # spin/astrometry/DM subspace, which is what this smoke test pins
        for p in m.free_params:
            if p not in ("F0", "F1", "RAJ", "DECJ", "ELONG", "ELAT", "DM"):
                getattr(m, p).frozen = True
        f = WLSFitter(toas, m)
        chi2_pre = f.resids_init.calc_chi2()
        chi2_post = f.fit_toas(maxiter=4)
        assert np.isfinite(chi2_post)
        assert chi2_post < 0.9 * chi2_pre
        # another iteration changes chi2 only marginally (stationarity)
        chi2_again = f.fit_toas(maxiter=1)
        assert abs(chi2_again - chi2_post) < 0.05 * chi2_post

    def test_designmatrix_scales(self, b1855):
        model, toas = b1855
        M, names, units = model.designmatrix(toas)
        assert M.shape[0] == len(toas)
        assert M.shape[1] == len(names)
        assert np.all(np.isfinite(M))
        # no degenerate (zero) columns and a usable normalized condition
        from pint_tpu.utils import normalize_designmatrix

        Mn, norms = normalize_designmatrix(M, names)
        assert np.all(np.asarray(norms)[1:] > 0)  # [0] is the Offset column
        s = np.linalg.svd(np.asarray(Mn), compute_uv=False)
        assert s[-1] > 1e-12 * s[0]

    def test_binary_component_present(self, b1855):
        model, _ = b1855
        assert model.BINARY.value is not None


#: (par, tim) pairs covering different model families: FB90 binaries
#: (B1953), GLS + ELL1 (J0023), GLS + ELL1H (J0613) — same smoke contract
MORE_PULSARS = [
    ("B1953+29_NANOGrav_dfg+12_TAI_FB90.par", "B1953+29_NANOGrav_dfg+12.tim"),
    ("J0023+0923_NANOGrav_11yv0.gls.par", "J0023+0923_NANOGrav_11yv0.tim"),
    ("J0613-0200_NANOGrav_9yv1.gls.par", "J0613-0200_NANOGrav_9yv1.tim"),
    # DDK (Kopeikin annual/secular parallax terms) on real data
    ("J1713+0747_NANOGrav_11yv0_short.gls.par",
     "J1713+0747_NANOGrav_11yv0_short.tim"),
    # ELL1H (orthometric H3 Shapiro) on real data
    ("J1853+1303_NANOGrav_11yv0.gls.par", "J1853+1303_NANOGrav_11yv0.tim"),
    # DD + solar wind on real data
    ("J1643-1224_NANOGrav_9yv1.gls.par", "J1643-1224_NANOGrav_9yv1.tim"),
    # ELL1 narrowband from the 12.5-yr release (modern tim conventions)
    ("J1909-3744.NB.par", "J1909-3744.NB.tim"),
    # isolated MSP observed with CHIME (exercises the CHIME site entry)
    ("B1937+21.basic.par", "B1937+21.CHIME.CHIME.NG.N.tim"),
]


class TestMorePulsarsSmoke:
    """The full pipeline contract across model families on real NANOGrav
    data: parse, evaluate, residual bounds, finite design matrix."""

    @pytest.mark.parametrize("par,tim", MORE_PULSARS,
                             ids=[p.split("_")[0].split(".")[0] for p, _ in MORE_PULSARS])
    def test_pipeline_smoke(self, par, tim):
        from pint_tpu.models import get_model_and_toas
        from pint_tpu.residuals import Residuals

        parf, timf = f"{DATADIR}/{par}", f"{DATADIR}/{tim}"
        if not os.path.exists(timf):
            pytest.skip("datafile unavailable")
        model, toas = get_model_and_toas(parf, timf)
        assert len(toas) > 100
        res = np.asarray(Residuals(toas, model).time_resids)
        assert np.all(np.isfinite(res))
        P = 1.0 / float(model.F0.value)
        assert np.max(np.abs(res)) <= P
        if not _kernel_available():
            # analytic-ephemeris error budget (see TestRealDataSmoke)
            assert np.sqrt(np.mean(res**2)) < 2.5e-3
        M, names, units = model.designmatrix(toas)
        assert np.all(np.isfinite(M))
        assert M.shape == (len(toas), len(names))


class TestGoldenParity:
    @needs_kernel
    def test_b1855_tempo2_residuals(self, b1855):
        """Reference asserts |pint - tempo2| < 3e-8 s
        (``tests/test_B1855.py:43-46``)."""
        from pint_tpu.residuals import Residuals

        model, toas = b1855
        ltres = np.genfromtxt(f"{B1855_PAR}.tempo2_test", skip_header=1,
                              unpack=True)
        res = Residuals(toas, model, use_weighted_mean=False).time_resids
        assert np.all(np.abs(res - ltres) < 3e-8)
