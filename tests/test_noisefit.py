"""ML noise-parameter fitting (reference ``fitter.py:1179 _fit_noise``).

Test strategy (SURVEY §4 simulation-as-fixture): inject known noise
parameters into simulated TOAs, recover them by maximizing the autodiff
lnlikelihood, and check the recovered values against the injected truth
within the Hessian-derived uncertainties.  Plus exactness pillars: the
jitted lnlikelihood must equal ``Residuals.lnlikelihood`` at the current
values, and its gradient must match central finite differences.
"""

import copy

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


def _model_with_lines(extra_lines):
    from pint_tpu.io.par import parse_parfile
    from pint_tpu.models import get_model

    with open(NGC_PAR) as f:
        text = f.read()
    return get_model(parse_parfile(text + "\n" + "\n".join(extra_lines) + "\n"))


def _clustered_mjds(nepoch=60, perepoch=4, start=53005.0, end=54795.0):
    """Epochs of several TOAs within <1 s so ECORR groups form."""
    epochs = np.linspace(start, end, nepoch)
    return (epochs[:, None] + np.arange(perepoch)[None, :] * 0.4 / 86400.0).ravel()


def _sim(model, mjds, error_us=2.0, seed=1, corr=False, freq=1400.0):
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    return make_fake_toas_fromMJDs(
        np.asarray(mjds), model, freq=freq, error_us=error_us, add_noise=True,
        add_correlated_noise=corr, rng=np.random.default_rng(seed))


class TestLnlikeExactness:
    def test_matches_residuals_lnlikelihood_white(self):
        from pint_tpu.noisefit import build_noise_lnlikelihood
        from pint_tpu.residuals import Residuals

        m = _model_with_lines(["EFAC mjd 52000 53900 1.3 1",
                               "EQUAD mjd 53900 60000 2.5 1"])
        t = _sim(m, np.linspace(53005, 54795, 80), seed=2)
        res = Residuals(t, m)
        lnl, x0, names = build_noise_lnlikelihood(m, t)
        assert set(names) == {"EFAC1", "EQUAD1"}
        got = float(lnl(x0, np.asarray(res.time_resids)))
        assert got == pytest.approx(res.lnlikelihood(), rel=1e-10)

    def test_matches_residuals_lnlikelihood_correlated(self):
        from pint_tpu.noisefit import build_noise_lnlikelihood
        from pint_tpu.residuals import Residuals

        m = _model_with_lines(["EFAC mjd 52000 60000 1.2 1",
                               "ECORR mjd 52000 60000 1.5 1",
                               "TNREDAMP -12.8 1", "TNREDGAM 3.0 1",
                               "TNREDC 5"])
        t = _sim(m, _clustered_mjds(30, 3), seed=3, corr=True)
        res = Residuals(t, m)
        lnl, x0, names = build_noise_lnlikelihood(m, t)
        assert set(names) == {"EFAC1", "ECORR1", "TNREDAMP", "TNREDGAM"}
        got = float(lnl(x0, np.asarray(res.time_resids)))
        assert got == pytest.approx(res.lnlikelihood(), rel=1e-9)

    def test_gradient_matches_finite_differences(self):
        import jax

        from pint_tpu.noisefit import build_noise_lnlikelihood
        from pint_tpu.residuals import Residuals

        m = _model_with_lines(["EFAC mjd 52000 60000 1.2 1",
                               "ECORR mjd 52000 60000 1.5 1",
                               "TNREDAMP -12.8 1", "TNREDGAM 3.0 1",
                               "TNREDC 4"])
        t = _sim(m, _clustered_mjds(25, 3), seed=4, corr=True)
        r = np.asarray(Residuals(t, m).time_resids)
        lnl, x0, names = build_noise_lnlikelihood(m, t)
        g = np.asarray(jax.grad(lnl)(x0, r))
        for i in range(len(x0)):
            h = 1e-6 * max(abs(x0[i]), 1.0)
            xp, xm = x0.copy(), x0.copy()
            xp[i] += h
            xm[i] -= h
            fd = (float(lnl(xp, r)) - float(lnl(xm, r))) / (2 * h)
            assert g[i] == pytest.approx(fd, rel=2e-5, abs=1e-7), names[i]

    def test_wideband_and_tneq_params_excluded(self):
        """DM-noise and TNEQ free params are excluded (with a warning)
        rather than crashing the fit path: a wideband par with a free
        DMEFAC must still fit its timing parameters."""
        from pint_tpu.noisefit import free_noise_params

        m = _model_with_lines(["DMEFAC mjd 52000 60000 1.3 1",
                               "EFAC mjd 52000 60000 1.2 1"])
        assert free_noise_params(m) == ["EFAC1"]
        m2 = _model_with_lines(["TNEQ mjd 52000 60000 -5.5 1"])
        assert free_noise_params(m2) == []


class TestLnlikePropertySweep:
    """Hypothesis sweep: for RANDOM noise parameter values the jitted
    likelihood must track ``Residuals.lnlikelihood`` evaluated on a model
    carrying those same values — the traced weight/variance builders are
    exact reparameterizations, not approximations."""

    @pytest.fixture(scope="class")
    def setup(self):
        m = _model_with_lines(["EFAC mjd 52000 53900 1.2 1",
                               "EQUAD mjd 53900 60000 2.0 1",
                               "ECORR mjd 52000 60000 1.5 1",
                               "TNREDAMP -12.8 1", "TNREDGAM 3.0 1",
                               "TNREDC 4"])
        t = _sim(m, _clustered_mjds(20, 3), seed=21, corr=True)
        from pint_tpu.noisefit import build_noise_lnlikelihood
        from pint_tpu.residuals import Residuals

        r = np.asarray(Residuals(t, m).time_resids)
        lnl, x0, names = build_noise_lnlikelihood(m, t)
        return m, t, r, lnl, names

    def test_random_values_match_residuals(self, setup):
        from hypothesis import given, settings, strategies as st

        m, t, r, lnl, names = setup
        from pint_tpu.residuals import Residuals

        @settings(max_examples=25, deadline=None)
        @given(efac=st.floats(0.3, 3.0), equad=st.floats(0.1, 10.0),
               ecorr=st.floats(0.1, 8.0), amp=st.floats(-14.5, -11.5),
               gam=st.floats(0.5, 6.0))
        def sweep(efac, equad, ecorr, amp, gam):
            vals = {"EFAC1": efac, "EQUAD1": equad, "ECORR1": ecorr,
                    "TNREDAMP": amp, "TNREDGAM": gam}
            x = np.array([vals[n] for n in names])
            got = float(lnl(x, r))
            m2 = copy.deepcopy(m)
            for n, v in vals.items():
                getattr(m2, n).value = v
            want = Residuals(t, m2).lnlikelihood()
            assert got == pytest.approx(want, rel=1e-9, abs=1e-6), vals

        sweep()


class TestPowerlawRangeSafety:
    """The traced power-law phi builder must keep every intermediate
    within float32 RANGE: TPU f64 emulation stores a float64 as a
    float32 pair, so anything past ~3.4e38 lands on device as inf.
    Measured on a v5e (round 5): the naive ``FYR**(gam-3) * f**(-gam)``
    form hits ~1e44 at f ~ 1/span, gam ~ 5 — inf — and NaN-poisoned the
    on-device ML noise fit and its gradient.  The builder therefore
    factors the law as ``FYR**-3 * (f/FYR)**-gam`` (algebraically
    identical, intermediates <= ~1e23).  Evaluating the builder in TRUE
    float32 distinguishes the forms on CPU: the naive one overflows,
    the factored one must not."""

    def _builder_and_x(self):
        from pint_tpu.noisefit import _corr_weight_builders, _value_getter

        m = _model_with_lines(["TNREDAMP -13.5 1", "TNREDGAM 4.9 1",
                               "TNREDC 30"])
        t = _sim(m, np.linspace(53005, 54795, 80), seed=31)
        builders = _corr_weight_builders(m, t)
        assert len(builders) == 1
        getv = _value_getter(m, ["TNREDAMP", "TNREDGAM"])
        return m, t, builders[0], getv, np.array([-13.5, 4.9])

    def test_naive_form_would_overflow_f32(self):
        """Guard that the scenario is actually discriminating: at this
        span and gamma the un-factored power overflows float32."""
        import jax
        import jax.numpy as jnp

        from pint_tpu.models.noise_model import _PLNoiseBase

        m, t, _, _, _ = self._builder_and_x()
        c = next(c for c in m.noise_components
                 if isinstance(c, _PLNoiseBase))
        _, f = c.get_time_frequencies(t)
        with jax.enable_x64(False):
            naive = jnp.asarray(f) ** jnp.float32(-4.9)
        assert not np.all(np.isfinite(np.asarray(naive)))

    def test_phi_builder_finite_in_f32(self):
        import jax
        import jax.numpy as jnp

        m, t, w_pl, getv, x = self._builder_and_x()
        phi64 = np.asarray(w_pl(jnp.asarray(x), getv))
        assert np.all(np.isfinite(phi64)) and np.all(phi64 > 0)
        with jax.enable_x64(False):
            # closures rebuilt under f32 so every array and op in the
            # builder runs at float32 range, as on the TPU
            from pint_tpu.noisefit import _corr_weight_builders

            w32 = _corr_weight_builders(m, t)[0]
            phi32 = np.asarray(w32(jnp.asarray(x, dtype=jnp.float32), getv))
        assert phi32.dtype == np.float32
        assert np.all(np.isfinite(phi32))
        np.testing.assert_allclose(phi32, phi64, rtol=2e-3)


class TestRecovery:
    def test_efac_equad_recovery(self):
        from pint_tpu.noisefit import fit_noise_ml
        from pint_tpu.residuals import Residuals

        truth = _model_with_lines(["EFAC mjd 52000 53900 1.5 1",
                                   "EQUAD mjd 53900 60000 4.0 1"])
        t = _sim(truth, np.linspace(53005, 54795, 500), error_us=2.0, seed=6)
        start = _model_with_lines(["EFAC mjd 52000 53900 1.0 1",
                                   "EQUAD mjd 53900 60000 1.0 1"])
        r = np.asarray(Residuals(t, start).time_resids)
        res = fit_noise_ml(start, t, r, uncertainty=True)
        vals = dict(zip(res.names, res.values))
        errs = dict(zip(res.names, res.errors))
        assert abs(vals["EFAC1"] - 1.5) < 3 * errs["EFAC1"]
        assert abs(abs(vals["EQUAD1"]) - 4.0) < 3 * errs["EQUAD1"]
        # sanity on the scale of the uncertainties themselves
        assert 0.01 < errs["EFAC1"] < 0.3
        assert 0.05 < errs["EQUAD1"] < 2.0

    def test_ecorr_recovery(self):
        from pint_tpu.noisefit import fit_noise_ml
        from pint_tpu.residuals import Residuals

        truth = _model_with_lines(["ECORR mjd 52000 60000 5.0 1"])
        t = _sim(truth, _clustered_mjds(80, 4), error_us=2.0, seed=7,
                 corr=True)
        start = _model_with_lines(["ECORR mjd 52000 60000 1.0 1"])
        r = np.asarray(Residuals(t, start).time_resids)
        res = fit_noise_ml(start, t, r, uncertainty=True)
        vals = dict(zip(res.names, res.values))
        errs = dict(zip(res.names, res.errors))
        assert abs(abs(vals["ECORR1"]) - 5.0) < 3 * errs["ECORR1"]
        assert res.lnlike > float(Residuals(t, start).lnlikelihood())

    def test_rednoise_amplitude_recovery(self):
        from pint_tpu.noisefit import fit_noise_ml
        from pint_tpu.residuals import Residuals

        truth = _model_with_lines(["TNREDAMP -12.3 1", "TNREDGAM 3.5 1",
                                   "TNREDC 10"])
        t = _sim(truth, np.linspace(53005, 54795, 300), error_us=1.0, seed=8,
                 corr=True)
        start = _model_with_lines(["TNREDAMP -13.0 1", "TNREDGAM 2.0 1",
                                   "TNREDC 10"])
        r = np.asarray(Residuals(t, start).time_resids)
        res = fit_noise_ml(start, t, r, uncertainty=True)
        vals = dict(zip(res.names, res.values))
        errs = dict(zip(res.names, res.errors))
        # one GP realization constrains log10-amplitude to a few tenths
        assert abs(vals["TNREDAMP"] - (-12.3)) < 3 * max(errs["TNREDAMP"], 0.1)
        assert abs(vals["TNREDGAM"] - 3.5) < 3 * max(errs["TNREDGAM"], 0.5)
        assert res.lnlike > float(Residuals(t, start).lnlikelihood())


class TestChromaticPLNoiseFit:
    def test_pldm_amplitude_recovery(self):
        """The chromatic PL classes ride the same traced weight builder:
        a free TNDMAMP (DM-noise amplitude, 1/f^2-scaled Fourier basis)
        is recovered from two-band data."""
        from pint_tpu.noisefit import fit_noise_ml
        from pint_tpu.residuals import Residuals

        truth = _model_with_lines(["TNDMAMP -12.4 1", "TNDMGAM 3.0",
                                   "TNDMC 8"])
        mjds = np.repeat(np.linspace(53005, 54795, 150), 2)
        freqs = np.tile([430.0, 1400.0], 150)
        t = _sim(truth, mjds, error_us=1.0, seed=14, corr=True, freq=freqs)
        start = _model_with_lines(["TNDMAMP -13.2 1", "TNDMGAM 3.0",
                                   "TNDMC 8"])
        r = np.asarray(Residuals(t, start).time_resids)
        res = fit_noise_ml(start, t, r, uncertainty=True)
        vals = dict(zip(res.names, res.values))
        errs = dict(zip(res.names, res.errors))
        assert set(vals) == {"TNDMAMP"}
        assert abs(vals["TNDMAMP"] - (-12.4)) < 3 * max(errs["TNDMAMP"], 0.15)
        assert res.lnlike > float(Residuals(t, start).lnlikelihood())


class TestWidebandNoiseFit:
    def test_dmefac_dmequad_recovery(self):
        """Joint TOA+DM likelihood recovers injected DMEFAC/DMEQUAD
        (reference fits these through WidebandTOAResiduals lnlikelihood)."""
        from pint_tpu.noisefit import build_noise_lnlikelihood, fit_noise_ml
        from pint_tpu.wideband import WidebandTOAResiduals

        rng = np.random.default_rng(12)
        truth = _model_with_lines(["DMEFAC mjd 52000 60000 1.6 1",
                                   "DMEQUAD mjd 52000 60000 4e-4 1"])
        t = _sim(truth, np.linspace(53005, 54795, 400), seed=12)
        # wideband DM measurements with noise drawn at the SCALED errors
        dme = np.full(len(t), 2e-4)
        dm_model = np.asarray(truth.total_dm(t))
        t.update_dms(dm_model, dme)  # sets the raw measurement errors
        scaled = np.asarray(truth.scaled_dm_uncertainty(t))
        t.update_dms(dm_model + rng.standard_normal(len(t)) * scaled, dme)
        start = _model_with_lines(["DMEFAC mjd 52000 60000 1.0 1",
                                   "DMEQUAD mjd 52000 60000 1e-5 1"])
        wr = WidebandTOAResiduals(t, start)
        res = fit_noise_ml(start, t, np.asarray(wr.toa.time_resids),
                           dm_resids=np.asarray(wr.dm.resids),
                           uncertainty=True)
        vals = dict(zip(res.names, np.abs(res.values)))
        errs = dict(zip(res.names, res.errors))
        assert set(vals) == {"DMEFAC1", "DMEQUAD1"}
        assert abs(vals["DMEFAC1"] - 1.6) < 3 * max(errs["DMEFAC1"], 0.03)
        assert abs(vals["DMEQUAD1"] - 4e-4) < 3 * max(errs["DMEQUAD1"], 8e-6)

    def test_wideband_downhill_fit_toas_alternates(self):
        from pint_tpu.wideband import WidebandDownhillFitter

        rng = np.random.default_rng(13)
        truth = _model_with_lines(["DMEFAC mjd 52000 60000 1.5 1"])
        t = _sim(truth, np.linspace(53005, 54795, 200), seed=13)
        dme = np.full(len(t), 2e-4)
        dm_model = np.asarray(truth.total_dm(t))
        t.update_dms(dm_model + rng.standard_normal(len(t)) * dme * 1.5, dme)
        start = _model_with_lines(["DMEFAC mjd 52000 60000 1.0 1"])
        f = WidebandDownhillFitter(t, start)
        f.fit_toas(maxiter=5, noise_fit_niter=1)
        assert abs(float(f.model.DMEFAC1.value) - 1.5) < 0.3
        assert f.model.DMEFAC1.uncertainty is not None


class TestB1855Shaped:
    """VERDICT-r3 acceptance shape: recovery on the real B1855+09 9-yr
    structure — 4005 TOAs at the real epochs/flags, per-backend
    EFAC/EQUAD/ECORR masks, 90-mode power-law red noise (RNAMP tempo1
    convention)."""

    B_PAR = "/root/reference/tests/datafile/B1855+09_NANOGrav_9yv1.gls.par"
    B_TIM = "/root/reference/tests/datafile/B1855+09_NANOGrav_9yv1.tim"

    def test_b1855_noise_recovery(self):
        from pint_tpu.models import get_model
        from pint_tpu.noisefit import fit_noise_ml, free_noise_params
        from pint_tpu.residuals import Residuals
        from pint_tpu.simulation import make_fake_toas_fromtim

        truth = get_model(self.B_PAR)
        # the catalogue red noise (TNRedAmp -14.23; the par carries both
        # conventions and TNREDAMP takes precedence) is too weak to
        # constrain from one realization; amplify so recovery is a real test
        truth.TNREDAMP.value = float(truth.TNREDAMP.value) + np.log10(20.0)
        t = make_fake_toas_fromtim(self.B_TIM, truth, add_noise=True,
                                   add_correlated_noise=True,
                                   rng=np.random.default_rng(77))
        tv = {"EFAC1": float(truth.EFAC1.value),
              "EQUAD2": float(truth.EQUAD2.value),
              "ECORR2": float(truth.ECORR2.value),
              "TNREDAMP": float(truth.TNREDAMP.value)}
        start = copy.deepcopy(truth)
        start.EFAC1.frozen = False
        start.EFAC1.value = 1.0
        start.EQUAD2.frozen = False
        start.EQUAD2.value = 1.0
        start.ECORR2.frozen = False
        start.ECORR2.value = 1.0
        start.TNREDAMP.frozen = False
        start.TNREDAMP.value = tv["TNREDAMP"] - 0.5
        assert set(free_noise_params(start)) == set(tv)
        r = np.asarray(Residuals(t, start).time_resids)
        res = fit_noise_ml(start, t, r, uncertainty=True)
        # us-scale white params enter the likelihood squared: fold the
        # sign-degenerate branch; log10 amplitudes keep their sign
        vals = {n: (abs(v) if n.startswith(("EFAC", "EQUAD", "ECORR")) else v)
                for n, v in zip(res.names, res.values)}
        errs = dict(zip(res.names, res.errors))
        for p in tv:
            # 3-sigma with a small absolute floor against a lucky-seed
            # over-tight Hessian
            floor = 0.02 * abs(tv[p])
            assert abs(vals[p] - tv[p]) < 3 * max(errs[p], floor), \
                (p, vals[p], errs[p], tv[p])
        lnl_start = float(Residuals(t, start).lnlikelihood())
        assert res.lnlike > lnl_start


class TestB1855JointNoiseFit:
    def test_all_noise_params_jointly(self):
        """The reference's real noisefit workflow: EVERY per-backend
        EFAC/EQUAD/ECORR plus the red-noise amplitude and index free at
        once (14 parameters) on the full 4005-TOA B1855 structure — one
        L-BFGS run over the jitted autodiff likelihood recovers all of
        them within 3 sigma."""
        import copy

        from pint_tpu.models import get_model
        from pint_tpu.noisefit import fit_noise_ml, free_noise_params
        from pint_tpu.residuals import Residuals
        from pint_tpu.simulation import make_fake_toas_fromtim

        truth = get_model(TestB1855Shaped.B_PAR)
        truth.TNREDAMP.value = float(truth.TNREDAMP.value) + np.log10(20.0)
        t = make_fake_toas_fromtim(TestB1855Shaped.B_TIM, truth,
                                   add_noise=True, add_correlated_noise=True,
                                   rng=np.random.default_rng(123))
        start = copy.deepcopy(truth)
        for c in start.noise_components:
            for p in c.params:
                par = c._params_dict[p]
                if par.value is not None and p[:4] in ("EFAC", "EQUA",
                                                       "ECOR"):
                    par.frozen = False
        start.TNREDAMP.frozen = False
        start.TNREDGAM.frozen = False
        free = free_noise_params(start)
        assert len(free) == 14
        r = np.asarray(Residuals(t, start).time_resids)
        res = fit_noise_ml(start, t, r, uncertainty=True)
        bad = []
        for n, v, e in zip(res.names, res.values, res.errors):
            tv = float(getattr(truth, n).value)
            # abs-fold ONLY the squared-entry (sign-degenerate) params;
            # a sign flip on TNREDAMP/TNREDGAM would be a real failure
            if n.startswith(("EFAC", "EQUAD", "ECORR")):
                v, tv = abs(v), abs(tv)
            # floor guards near-zero truths (ns-level EQUADs/ECORRs) and
            # lucky-seed over-tight Hessians
            tol = 3 * max(e, 0.02 * abs(tv), 0.02)
            if abs(v - tv) > tol:
                bad.append((n, v, e, tv))
        assert not bad, bad


class TestFitterIntegration:
    def test_downhill_gls_alternating_noisefit(self):
        from pint_tpu.gls_fitter import DownhillGLSFitter
        from pint_tpu.residuals import Residuals

        truth = _model_with_lines(["EFAC mjd 52000 60000 1.4 1",
                                   "ECORR mjd 52000 60000 4.0 1"])
        t = _sim(truth, _clustered_mjds(60, 4), error_us=2.0, seed=9,
                 corr=True)
        start = _model_with_lines(["EFAC mjd 52000 60000 1.0 1",
                                   "ECORR mjd 52000 60000 1.0 1"])
        # timing params slightly off so the timing fit has real work
        start.F0.value = float(start.F0.value) + 2e-10
        f = DownhillGLSFitter(t, start)
        lnl_before = float(Residuals(t, start).lnlikelihood())
        f.fit_toas(maxiter=6, noise_fit_niter=2)
        efac = float(f.model.EFAC1.value)
        ecorr = float(f.model.ECORR1.value)
        assert abs(efac - 1.4) < 0.35
        assert abs(ecorr - 4.0) < 2.0
        assert f.model.EFAC1.uncertainty is not None
        assert "EFAC1" in f.errors and f.errors["EFAC1"] > 0
        assert float(f.resids.lnlikelihood()) > lnl_before

    def test_downhill_wls_white_noisefit(self):
        from pint_tpu.fitter import DownhillWLSFitter

        truth = _model_with_lines(["EFAC mjd 52000 60000 1.6 1"])
        t = _sim(truth, np.linspace(53005, 54795, 300), error_us=2.0, seed=10)
        start = _model_with_lines(["EFAC mjd 52000 60000 1.0 1"])
        f = DownhillWLSFitter(t, start)
        f.fit_toas(maxiter=6, noise_fit_niter=1)
        assert abs(float(f.model.EFAC1.value) - 1.6) < 0.25

    def test_no_free_noise_params_unchanged_path(self):
        """Without free noise params fit_toas must take the plain timing
        path (fit_noise returns None, no alternation)."""
        from pint_tpu.fitter import DownhillWLSFitter
        from pint_tpu.models import get_model

        m = get_model(NGC_PAR)
        t = _sim(m, np.linspace(53005, 54795, 60), seed=11)
        f = DownhillWLSFitter(t, copy.deepcopy(m))
        assert f._get_free_noise_params() == []
        assert f.fit_noise() is None
        chi2 = f.fit_toas(maxiter=4)
        assert np.isfinite(chi2)
