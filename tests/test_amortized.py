"""Amortized inference engine under test (pint_tpu/amortized/).

The contracts tier-1 (CPU) pins:

* **flow primitives** — affine couplings invert exactly (forward o
  inverse == id, log-determinants cancel), the identity init IS the
  prior-transformed base, fixed permutations are seed-deterministic;
* **prior alignment** — the :class:`PriorTransform` keeps every flow
  sample strictly in-support, inverts analytically, and reports
  out-of-support log-prob queries as exactly ``-inf``;
* **the deduped entry point** — ``BayesianTiming.batched_posterior``
  is the ONE lnposterior construction: its values pin against the
  scalar path and against ``lnposterior_batch`` / ``MCMCFitter`` on
  the B1855-shaped DD-binary stand-in;
* **training discipline** — a fixed seed reproduces the ELBO trace
  and trained weights bitwise; a crash mid-run resumes from the
  SweepCheckpoint bit-identically; a foreign checkpoint refuses with
  the typed CheckpointError;
* **the posterior door** — coalesced requests never share a PRNG
  key, results unpad in request order, ``posterior_serve`` events
  validate, and the AOT round trip (populate -> clear_caches ->
  fresh pool -> all-hit re-warm -> serve) reaches ``compiles=0``
  with bit-identical draws;
* **the slow acceptance pin** — the flow posterior matches
  ``MCMCFitter`` marginals (KS + first two moments) on the stand-in
  workload, with the amortized draw path >= 10x faster wall-clock
  than the MCMC chain.
"""

import io
import json
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.amortized

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pint_tpu.amortized import (  # noqa: E402
    AmortizedPosterior,
    AmortizedVI,
    Flow,
    FlowConfig,
    PriorTransform,
    TrainConfig,
    train_flow,
)
from pint_tpu.exceptions import CheckpointError, UsageError  # noqa: E402
from pint_tpu.serving import (  # noqa: E402
    PosteriorRequest,
    ServeConfig,
    TimingService,
    WarmPool,
)

# the B1855-shaped stand-in of the precision/autotune suites with the
# correlated-noise components dropped: BayesianTiming's vectorized
# likelihood (like MCMCFitter's) is the white-noise chi2 — the DD
# binary + EFAC structure is what makes it B1855-shaped
STANDIN_PAR = [
    "PSR TSTAMORT\n", "RAJ 04:37:15.0\n", "DECJ -47:15:09.0\n",
    "F0 173.6879 1\n", "F1 -1.7e-15 1\n", "PEPOCH 55000\n",
    "DM 2.64\n", "BINARY DD\n", "PB 5.7410\n", "A1 3.3667\n",
    "T0 55000.0\n", "OM 1.35\n", "ECC 1.9e-5\n", "M2 0.3\n",
    "SINI 0.95\n", "EFAC mjd 50000 60000 1.1\n", "UNITS TDB\n",
]


def _gauss_lnpost(mu, sig):
    """A synthetic Gaussian posterior for unit-level flow tests."""
    mu = np.asarray(mu, dtype=np.float64)
    sig = np.asarray(sig, dtype=np.float64)

    def lnpost(x):
        import jax.numpy as jnp

        return -0.5 * jnp.sum(((x - mu) / sig) ** 2, axis=-1)

    return lnpost


@pytest.fixture(scope="module")
def standin():
    """WLS-fitted F0/F1 stand-in + its BayesianTiming with +-10 sigma
    uniform priors (the MCMC-able posterior surface)."""
    from pint_tpu.bayesian import BayesianTiming, apply_prior_info
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    model = get_model(list(STANDIN_PAR))
    rng = np.random.default_rng(7)
    mjds = np.linspace(54000, 56000, 60)
    toas = make_fake_toas_fromMJDs(mjds, model, error_us=2.0,
                                   add_noise=True, rng=rng)
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=3)
    info = {}
    for p in f.model.free_params:
        par = getattr(f.model, p)
        half = 10.0 * float(par.uncertainty)
        info[p] = {"distr": "uniform",
                   "pmin": float(par.value) - half,
                   "pmax": float(par.value) + half}
    apply_prior_info(f.model, info)
    return f, BayesianTiming(f.model, toas)


@pytest.fixture
def aot_dir(tmp_path):
    from pint_tpu import config
    from pint_tpu.serving import aotcache

    d = str(tmp_path / "aot")
    config.set_aot_cache_dir(d)
    yield d
    config.set_aot_cache_dir(None)
    aotcache.reset_cache_singleton()


@pytest.fixture
def basic_telemetry():
    from pint_tpu import telemetry

    telemetry.activate("basic")
    yield telemetry
    telemetry.deactivate()


# ---------------------------------------------------------------------------
# flow primitives
# ---------------------------------------------------------------------------

class TestFlowPrimitives:
    def test_config_validation(self):
        with pytest.raises(UsageError):
            FlowConfig(ndim=0)
        with pytest.raises(UsageError):
            FlowConfig(ndim=2, n_layers=-1)
        with pytest.raises(UsageError):
            FlowConfig(ndim=2, hidden=0)
        assert FlowConfig(ndim=3).digest() != \
            FlowConfig(ndim=3, seed=1).digest()

    def test_identity_init_is_the_base(self):
        import jax.numpy as jnp

        flow = Flow(FlowConfig(ndim=3, n_layers=4, hidden=8, seed=2))
        params = flow.init()
        z = np.random.default_rng(0).normal(size=(11, 3))
        u, logdet = flow.forward(params, jnp.asarray(z))
        np.testing.assert_array_equal(np.asarray(u), z)
        np.testing.assert_array_equal(np.asarray(logdet), np.zeros(11))

    def test_forward_inverse_round_trip(self):
        """After real training steps (non-trivial weights) the
        coupling stack still inverts exactly and the log-dets
        cancel."""
        import jax.numpy as jnp

        vi = AmortizedVI(_gauss_lnpost([0.2, -0.1, 0.4], [0.1] * 3),
                         [("uniform", -2.0, 2.0)] * 3,
                         n_layers=4, hidden=8, seed=3)
        res = train_flow(vi, TrainConfig(steps=30, n_samples=16))
        z = np.random.default_rng(1).normal(size=(17, 3))
        u, ld_f = vi.flow.forward(res.params, jnp.asarray(z))
        z2, ld_i = vi.flow.inverse(res.params, u)
        np.testing.assert_allclose(np.asarray(z2), z, atol=1e-12)
        np.testing.assert_allclose(np.asarray(ld_f + ld_i),
                                   np.zeros(17), atol=1e-12)

    def test_ndim1_is_diagonal_affine_only(self):
        flow = Flow(FlowConfig(ndim=1, n_layers=4, hidden=8))
        assert flow.n_coupling_layers == 0
        vi = AmortizedVI(_gauss_lnpost([0.5], [0.1]),
                         [("uniform", -2.0, 2.0)], n_layers=4, seed=0)
        res = train_flow(vi, TrainConfig(steps=120, n_samples=32,
                                         lr=5e-2))
        ap = AmortizedPosterior.from_training(vi, res)
        d = ap.draw(2000, seed=4)
        assert abs(float(d.mean()) - 0.5) < 0.05

    def test_fixed_permutations_are_seed_deterministic(self):
        cfg = FlowConfig(ndim=6, n_layers=3, seed=5)
        a, b = Flow(cfg), Flow(cfg)
        for (ia, ib), (ja, jb) in zip(a._splits, b._splits):
            np.testing.assert_array_equal(ia, ja)
            np.testing.assert_array_equal(ib, jb)
        other = Flow(FlowConfig(ndim=6, n_layers=3, seed=6))
        assert any(not np.array_equal(x[0], y[0])
                   for x, y in zip(a._splits, other._splits))

    def test_base_logpdf_is_standard_normal(self):
        from scipy.stats import norm

        z = np.random.default_rng(2).normal(size=(9, 4))
        want = norm.logpdf(z).sum(axis=1)
        got = np.asarray(Flow.base_logpdf(z))
        np.testing.assert_allclose(got, want, rtol=1e-12)


class TestPriorTransform:
    def test_round_trip_and_jacobians_cancel(self):
        import jax.numpy as jnp

        tr = PriorTransform([("uniform", -1.0, 3.0),
                             ("normal", 2.0, 0.5)])
        u = np.random.default_rng(3).normal(size=(13, 2))
        x, lj = tr.constrain(jnp.asarray(u))
        u2, lji, inb = tr.unconstrain(x)
        np.testing.assert_allclose(np.asarray(u2), u, atol=1e-9)
        np.testing.assert_allclose(np.asarray(lj + lji), np.zeros(13),
                                   atol=1e-9)
        assert bool(np.all(np.asarray(inb)))

    def test_constrained_samples_stay_in_support(self):
        import jax.numpy as jnp

        tr = PriorTransform([("uniform", 10.0, 11.0)])
        u = np.linspace(-50, 50, 101)[:, None]
        x, _ = tr.constrain(jnp.asarray(u))
        x = np.asarray(x)
        assert np.all(x >= 10.0) and np.all(x <= 11.0)

    def test_out_of_support_is_minus_inf(self):
        vi = AmortizedVI(_gauss_lnpost([0.0], [0.5]),
                         [("uniform", -1.0, 1.0)], n_layers=0)
        ap = AmortizedPosterior.from_training(
            vi, train_flow(vi, TrainConfig(steps=2, n_samples=8)))
        lp = ap.log_prob(np.array([[0.0], [1.5], [-2.0]]))
        assert np.isfinite(lp[0])
        assert lp[1] == -np.inf and lp[2] == -np.inf

    def test_narrow_box_never_overshoots_in_fp(self):
        """A box narrow relative to its center (the F0-prior shape):
        fl(lo + width*sigmoid(u)) could exceed hi by an ulp — the
        clamp keeps every constrained sample inside the ORIGINAL
        bounds, and the inverse reports it in-support."""
        import jax.numpy as jnp

        lo, hi = 61.485476554 - 1e-9, 61.485476554 + 1e-9
        tr = PriorTransform([("uniform", lo, hi)])
        u = np.linspace(-45.0, 45.0, 4001)[:, None]
        x, _ = tr.constrain(jnp.asarray(u))
        x = np.asarray(x)
        assert np.all(x >= lo) and np.all(x <= hi)
        _, _, inb = tr.unconstrain(jnp.asarray(x))
        assert bool(np.all(np.asarray(inb)))

    def test_malformed_specs_raise_typed(self):
        with pytest.raises(UsageError):
            PriorTransform([])
        with pytest.raises(UsageError):
            PriorTransform([None])
        with pytest.raises(UsageError):
            PriorTransform([("cauchy", 0.0, 1.0)])
        with pytest.raises(UsageError):
            PriorTransform([("uniform", 2.0, 1.0)])
        with pytest.raises(UsageError):
            PriorTransform([("normal", 0.0, 0.0)])


# ---------------------------------------------------------------------------
# ELBO + training
# ---------------------------------------------------------------------------

class TestTraining:
    def test_elbo_improves_and_recovers_moments(self):
        mu, sig = [0.3, -0.5], [0.1, 0.2]
        vi = AmortizedVI(_gauss_lnpost(mu, sig),
                         [("uniform", -2.0, 2.0)] * 2,
                         n_layers=4, hidden=16, seed=1)
        res = train_flow(vi, TrainConfig(steps=300, n_samples=64,
                                         lr=2e-2, seed=3))
        assert res.elbo_final > res.elbo_trace[0]
        d = AmortizedPosterior.from_training(vi, res).draw(4000, seed=5)
        np.testing.assert_allclose(d.mean(axis=0), mu, atol=0.08)
        np.testing.assert_allclose(d.std(axis=0), sig, rtol=0.35)

    def test_training_is_bitwise_deterministic(self):
        """Satellite: a fixed jax.random seed reproduces the ELBO
        trace (and the trained weights) bitwise on CPU."""
        import jax

        def run():
            vi = AmortizedVI(_gauss_lnpost([0.1], [0.3]),
                             [("uniform", -1.0, 1.0)],
                             n_layers=2, hidden=8, seed=2)
            return vi, train_flow(vi, TrainConfig(steps=20,
                                                  n_samples=16, seed=9))

        _, a = run()
        _, b = run()
        np.testing.assert_array_equal(a.elbo_trace, b.elbo_trace)
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))

    def test_crash_resume_is_bitwise_identical(self, tmp_path,
                                               monkeypatch):
        """A run killed mid-chunk resumes from the SweepCheckpoint and
        finishes bit-identically to an uninterrupted run."""
        import jax

        from pint_tpu.amortized import train as train_mod

        cfg = TrainConfig(steps=40, n_samples=16, seed=4,
                          checkpoint_chunk=10)

        def make_vi():
            return AmortizedVI(_gauss_lnpost([0.2, 0.1], [0.2, 0.3]),
                               [("uniform", -1.0, 1.0)] * 2,
                               n_layers=2, hidden=8, seed=1)

        vi = make_vi()
        unfaulted = train_flow(vi, cfg,
                               checkpoint=str(tmp_path / "clean"))

        # crash at step 25 (mid third chunk): chunks 0-1 persist
        real_step_fn = train_mod._adam_step_fn
        calls = {"n": 0}

        def crashing(vi_, cfg_):
            step = real_step_fn(vi_, cfg_)

            def wrapped(*args):
                calls["n"] += 1
                if calls["n"] > 25:
                    raise RuntimeError("injected crash")
                return step(*args)

            return wrapped

        monkeypatch.setattr(train_mod, "_adam_step_fn", crashing)
        with pytest.raises(RuntimeError, match="injected crash"):
            train_flow(make_vi(), cfg,
                       checkpoint=str(tmp_path / "crashed"))
        monkeypatch.setattr(train_mod, "_adam_step_fn", real_step_fn)
        resumed = train_flow(make_vi(), cfg,
                             checkpoint=str(tmp_path / "crashed"))
        assert resumed.resumed_steps == 20
        np.testing.assert_array_equal(resumed.elbo_trace,
                                      unfaulted.elbo_trace)
        for la, lb in zip(jax.tree_util.tree_leaves(resumed.params),
                          jax.tree_util.tree_leaves(unfaulted.params)):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))

    def test_foreign_checkpoint_refuses(self, tmp_path):
        vi = AmortizedVI(_gauss_lnpost([0.0], [0.5]),
                         [("uniform", -1.0, 1.0)], n_layers=1,
                         hidden=4)
        d = str(tmp_path / "ck")
        train_flow(vi, TrainConfig(steps=10, n_samples=8, seed=1,
                                   checkpoint_chunk=5), checkpoint=d)
        with pytest.raises(CheckpointError):
            train_flow(vi, TrainConfig(steps=10, n_samples=8, seed=2,
                                       checkpoint_chunk=5),
                       checkpoint=d)

    def test_walker_plan_shards_the_sample_axis(self, eight_devices):
        from pint_tpu.runtime.plan import select_plan

        plan = select_plan("walker", devices=eight_devices)
        vi = AmortizedVI(_gauss_lnpost([0.1, 0.2], [0.2, 0.2]),
                         [("uniform", -1.0, 1.0)] * 2,
                         n_layers=2, hidden=8, seed=3)
        res = train_flow(vi, TrainConfig(steps=15, n_samples=30),
                         plan=plan)
        # 30 samples pad to 32 (8 shards); training stays finite
        assert np.all(np.isfinite(res.elbo_trace))

    def test_flow_train_events_validate(self, tmp_path):
        from pint_tpu import telemetry
        from pint_tpu.telemetry import runlog
        from tools.telemetry_report import validate_run_dir

        run_dir = str(tmp_path / "run")
        telemetry.activate("full")
        try:
            runlog.start_run(run_dir, name="amortized-test",
                             probe_device=False)
            vi = AmortizedVI(_gauss_lnpost([0.0], [0.5]),
                             [("uniform", -1.0, 1.0)], n_layers=1,
                             hidden=4)
            train_flow(vi, TrainConfig(steps=10, n_samples=8,
                                       log_every=5))
            runlog.end_run()
        finally:
            telemetry.deactivate()
        errors = []
        validate_run_dir(run_dir, errors)
        assert not errors, errors
        recs = [json.loads(ln) for ln in
                open(os.path.join(run_dir, "events.jsonl"))]
        ticks = [r for r in recs if r.get("type") == "event"
                 and r["event"]["name"] == "flow_train"]
        assert len(ticks) >= 2
        assert ticks[0]["event"]["attrs"]["lr"] > 0


# ---------------------------------------------------------------------------
# the deduped lnposterior entry point (satellite)
# ---------------------------------------------------------------------------

class TestBatchedPosteriorEntryPoint:
    def test_entry_point_pins_scalar_and_batch_paths(self, standin):
        """The regression pin: one lnposterior construction — the
        typed entry point, lnposterior_batch, and the scalar path
        agree on the B1855-shaped stand-in."""
        import jax.numpy as jnp

        _, bt = standin
        bp = bt.batched_posterior()
        assert bp.param_labels == tuple(bt.param_labels)
        assert bp.ndim == bt.nparams
        assert all(s is not None for s in bp.prior_specs)
        rng = np.random.default_rng(11)
        vals = np.array([float(getattr(bt.model, p).value)
                         for p in bp.param_labels])
        errs = np.array([float(getattr(bt.model, p).uncertainty)
                         for p in bp.param_labels])
        pts = vals + errs * rng.standard_normal((6, bp.ndim))
        via_entry = np.asarray(bp.fn(jnp.asarray(pts)))
        via_batch = bt.lnposterior_batch(pts)
        # the SAME built graph: identical, not merely close
        np.testing.assert_array_equal(via_entry, via_batch)
        scalar = np.array([bt.lnposterior(p) for p in pts])
        np.testing.assert_allclose(via_entry, scalar, rtol=1e-9,
                                   atol=1e-9)

    def test_mcmc_fitter_shares_the_entry_point(self, standin):
        from pint_tpu.mcmc_fitter import MCMCFitter

        f, bt = standin
        mf = MCMCFitter(bt.toas, bt.model, nwalkers=8)
        bp = mf.batched_posterior()
        assert bp.param_labels == tuple(bt.param_labels)
        pts = np.array([[float(getattr(bt.model, p).value)
                         for p in bp.param_labels]])
        # the fitter deep-copies the model, so its compiled graph is a
        # separate build: pinned to fp-envelope, not bitwise (the
        # bitwise pin above covers the shared-construction contract)
        np.testing.assert_allclose(
            np.asarray(bp.fn(pts)), bt.lnposterior_batch(pts),
            rtol=1e-12)

    def test_unvectorizable_posterior_raises_typed(self):
        from pint_tpu.bayesian import BayesianTiming
        from pint_tpu.models import get_model
        from pint_tpu.models.priors import GaussianBoundedRV, Prior
        from pint_tpu.simulation import make_fake_toas_uniform

        par = io.StringIO(
            "PSR TST\nF0 10.0 1\nPEPOCH 55000\nRAJ 1:00:00\n"
            "DECJ 1:00:00\nUNITS TDB\n")
        m = get_model(par)
        t = make_fake_toas_uniform(54000, 55000, 20, m, error_us=5.0)
        # a truncnorm prior has no jax_spec: host path only
        m.F0.prior = Prior(GaussianBoundedRV(10.0, 1e-6, 9.0, 11.0))
        bt = BayesianTiming(m, t)
        with pytest.raises(UsageError):
            bt.batched_posterior()

    def test_amortized_vi_builds_from_the_entry_point(self, standin):
        _, bt = standin
        vi = AmortizedVI.from_bayesian(bt, n_layers=2, hidden=8)
        assert vi.param_labels == tuple(bt.param_labels)
        assert vi.ndim == bt.nparams
        assert vi.vkey  # model signature + TOA version rode along

    def test_amortized_vi_over_the_joint_likelihood(self):
        """The catalog surface: the ELBO differentiates through the
        jitted cross-pulsar Hellings-Downs kernel and training stays
        finite on the (log10_A, gamma) box."""
        from pint_tpu.catalog import (CatalogFitter, JointLikelihood,
                                      ingest_catalog,
                                      make_synthetic_catalog)

        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=3, seed=5, ntoa_range=(20, 28)))
        cf = CatalogFitter(report)
        cf.fit(maxiter=1)
        jl = JointLikelihood(cf, n_modes=2)
        vi = AmortizedVI.from_joint_likelihood(
            jl, log10_A_bounds=(-16.0, -12.0), gamma_bounds=(1.0, 6.0),
            n_layers=2, hidden=8, seed=3)
        assert vi.param_labels == ("log10_A", "gamma")
        res = train_flow(vi, TrainConfig(steps=10, n_samples=8))
        assert np.all(np.isfinite(res.elbo_trace))
        d = AmortizedPosterior.from_training(vi, res).draw(100, seed=1)
        assert np.all(d[:, 0] >= -16.0) and np.all(d[:, 0] <= -12.0)
        assert np.all(d[:, 1] >= 1.0) and np.all(d[:, 1] <= 6.0)


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------

class TestPersistence:
    def _trained(self, tmp_path):
        vi = AmortizedVI(_gauss_lnpost([0.2, -0.3], [0.15, 0.1]),
                         [("uniform", -1.0, 1.0)] * 2,
                         n_layers=2, hidden=8, seed=1,
                         vkey=("standin", 3))
        res = train_flow(vi, TrainConfig(steps=25, n_samples=16))
        return AmortizedPosterior.from_training(vi, res)

    def test_save_load_round_trip_bitwise(self, tmp_path):
        ap = self._trained(tmp_path)
        path = str(tmp_path / "flow")
        ap.save(path)
        ap2 = AmortizedPosterior.load(path)
        assert ap2.serve_vkey() == ap.serve_vkey()
        assert ap2.param_labels == ap.param_labels
        np.testing.assert_array_equal(ap.draw(50, seed=3),
                                      ap2.draw(50, seed=3))

    def test_vkey_verification(self, tmp_path):
        ap = self._trained(tmp_path)
        path = str(tmp_path / "flow")
        ap.save(path)
        AmortizedPosterior.load(path, expect_vkey=("standin", 3))
        with pytest.raises(CheckpointError):
            AmortizedPosterior.load(path, expect_vkey=("other", 9))

    def test_load_pins_the_stored_precision_spec(self, tmp_path):
        """A flow saved under the f64 default must load at f64 even
        when the ambient policy has since flipped — the sidecar's
        verified identity wins over re-resolution."""
        from pint_tpu import precision

        ap = self._trained(tmp_path)
        assert not ap.flow.spec.reduced
        path = str(tmp_path / "flow")
        ap.save(path)
        with precision.use_policy(
                precision.PrecisionPolicy.forced("float32")):
            ap2 = AmortizedPosterior.load(path)
        assert not ap2.flow.spec.reduced
        assert ap2.serve_vkey() == ap.serve_vkey()

    def test_tampered_sidecar_refuses(self, tmp_path):
        ap = self._trained(tmp_path)
        path = str(tmp_path / "flow")
        ap.save(path)
        man = json.load(open(path + ".json"))
        man["config"]["hidden"] = 999
        json.dump(man, open(path + ".json", "w"))
        with pytest.raises(CheckpointError):
            AmortizedPosterior.load(path)

    def test_missing_field_and_schema_refuse(self, tmp_path):
        ap = self._trained(tmp_path)
        path = str(tmp_path / "flow")
        ap.save(path)
        man = json.load(open(path + ".json"))
        man["schema"] = "wrong/0"
        json.dump(man, open(path + ".json", "w"))
        with pytest.raises(CheckpointError):
            AmortizedPosterior.load(path)
        man = json.load(open(path + ".json"))
        man["schema"] = "pint_tpu.amortized.flow/1"
        del man["leaves"]
        json.dump(man, open(path + ".json", "w"))
        with pytest.raises(CheckpointError):
            AmortizedPosterior.load(path)


# ---------------------------------------------------------------------------
# the posterior door
# ---------------------------------------------------------------------------

def _tiny_posterior(seed=1):
    vi = AmortizedVI(_gauss_lnpost([0.3, -0.2], [0.1, 0.15]),
                     [("uniform", -1.0, 1.0)] * 2,
                     n_layers=2, hidden=8, seed=seed)
    res = train_flow(vi, TrainConfig(steps=30, n_samples=16))
    return AmortizedPosterior.from_training(vi, res)


class TestPosteriorDoor:
    def _svc(self, ap=None, **kw):
        svc = TimingService(ServeConfig(draw_buckets=(64, 256),
                                        batch_buckets=(1, 2, 4), **kw))
        svc.register_posterior(ap or _tiny_posterior(), seed=5)
        return svc

    def test_unregistered_door_raises_typed(self):
        svc = TimingService(ServeConfig())
        with pytest.raises(UsageError):
            svc.serve_posterior([PosteriorRequest(n_draws=8)])

    def test_request_validation(self):
        with pytest.raises(UsageError):
            PosteriorRequest()
        with pytest.raises(UsageError):
            PosteriorRequest(n_draws=4, points=np.zeros((2, 2)))

    def test_sync_serve_orders_and_unpads(self, basic_telemetry):
        svc = self._svc()
        reqs = [PosteriorRequest(n_draws=10, request_id="a"),
                PosteriorRequest(points=np.zeros((3, 2)),
                                 request_id="b"),
                PosteriorRequest(n_draws=40, request_id="c")]
        out = svc.serve_posterior(reqs)
        assert [o.request_id for o in out] == ["a", "b", "c"]
        assert out[0].draws.shape == (10, 2)
        assert out[1].log_probs.shape == (3,)
        assert out[2].draws.shape == (40, 2)
        # both draw requests fit the 64-bucket and coalesced there
        assert out[0].bucket == out[2].bucket == 64
        assert out[0].batch == out[2].batch == 2
        assert svc.posterior_served == 3
        lat = svc.posterior_latency_summary()
        assert lat["n"] == 3 and lat["p99_ms"] >= lat["p50_ms"] > 0
        # the fit door's ring is untouched — separate SLO surfaces
        assert svc.latency_summary()["n"] == 0

    def test_coalesced_requests_never_share_a_key(self):
        """Satellite: coalesced draw requests get distinct PRNG
        folds — within a batch AND across passes."""
        svc = self._svc()
        out = svc.serve_posterior(
            [PosteriorRequest(n_draws=30, request_id=f"r{i}")
             for i in range(4)])
        draws = [o.draws for o in out]
        for i in range(4):
            for j in range(i + 1, 4):
                assert not np.array_equal(draws[i], draws[j])
        again = svc.serve_posterior([PosteriorRequest(n_draws=30)])
        for d in draws:
            assert not np.array_equal(again[0].draws, d)

    def test_same_seed_fresh_service_reproduces(self):
        """The key chain is deterministic: a fresh service with the
        same seed and request order reproduces draws bitwise (the
        resumable-serving contract), while a different seed moves
        them."""
        ap = _tiny_posterior()
        a = self._svc(ap).serve_posterior([PosteriorRequest(n_draws=16)])
        b = self._svc(ap).serve_posterior([PosteriorRequest(n_draws=16)])
        np.testing.assert_array_equal(a[0].draws, b[0].draws)
        svc = TimingService(ServeConfig(draw_buckets=(64, 256),
                                        batch_buckets=(1, 2, 4)))
        svc.register_posterior(ap, seed=99)
        c = svc.serve_posterior([PosteriorRequest(n_draws=16)])
        assert not np.array_equal(a[0].draws, c[0].draws)

    def test_warm_rounds_through_the_dispatch_ladders(self,
                                                      basic_telemetry):
        """Warming non-rung shapes must warm the executables the
        dispatch path actually looks up (bucketed batch + draw
        count) — the serve after a rounded warm pays zero compiles."""
        from pint_tpu.telemetry import jaxevents

        svc = self._svc()
        rep = svc.warm_posterior([(3, 100)])  # rounds to (4, 256)
        ident = svc.posterior.ident()
        names = {e.name for e in rep.entries}
        assert names == {f"posterior.draw[4x256x2@{ident}]",
                         f"posterior.logprob[4x256x2@{ident}]"}
        before = jaxevents.counts()
        out = svc.serve_posterior(
            [PosteriorRequest(n_draws=100) for _ in range(3)])
        assert jaxevents.counts().compiles - before.compiles == 0
        assert all(o.draws.shape == (100, 2) for o in out)

    def test_same_shape_posteriors_never_share_a_kernel(self):
        """Regression: the draw/log-prob kernels bake the prior
        transform in as constants — two posteriors with identical
        architecture but different boxes must not alias through the
        module-jit registry OR the warm pool."""
        def trained(lo, hi):
            vi = AmortizedVI(_gauss_lnpost([0.5 * (lo + hi)] * 2,
                                           [0.1 * (hi - lo)] * 2),
                             [("uniform", lo, hi)] * 2,
                             n_layers=2, hidden=8, seed=1)
            res = train_flow(vi, TrainConfig(steps=5, n_samples=8))
            return AmortizedPosterior.from_training(vi, res)

        a = trained(0.0, 1.0)
        b = trained(100.0, 200.0)
        da = a.draw(50, seed=2)
        db = b.draw(50, seed=2)
        assert np.all(da >= 0.0) and np.all(da <= 1.0)
        assert np.all(db >= 100.0) and np.all(db <= 200.0)
        assert np.all(np.isfinite(b.log_prob(db[:10])))
        # and through one service: re-registering a same-shaped
        # posterior after warming must not replay the first's handle
        svc = self._svc(a)
        svc.warm_posterior([(1, 64)])
        svc.register_posterior(b, seed=5)
        out = svc.serve_posterior([PosteriorRequest(n_draws=16)])
        assert np.all(out[0].draws >= 100.0)
        assert np.all(out[0].draws <= 200.0)

    def test_logprob_pads_exactly(self):
        """Padded query rows are sliced away and do not perturb the
        served rows (vmapped kernel: lanes are independent)."""
        ap = _tiny_posterior()
        svc = self._svc(ap)
        pts = np.random.default_rng(3).uniform(-0.9, 0.9, size=(5, 2))
        served = svc.serve_posterior(
            [PosteriorRequest(points=pts)])[0].log_probs
        direct = ap.log_prob(pts)
        np.testing.assert_allclose(served, direct, rtol=1e-12)

    def test_async_door_coalesces(self):
        import asyncio

        svc = self._svc()

        async def run():
            return await asyncio.gather(*[
                svc.submit_posterior(PosteriorRequest(n_draws=20))
                for _ in range(3)])

        out = asyncio.run(run())
        assert all(o.draws.shape == (20, 2) for o in out)
        assert {o.batch for o in out} == {4}  # coalesced (3 -> rung 4)
        assert svc.posterior_served == 3

    def test_malformed_submit_fails_only_its_own_awaiter(self):
        """A wrong-ndim request raises at submit time — its coalesced
        batch-mates are served normally."""
        import asyncio

        svc = self._svc()

        async def run():
            ok = asyncio.ensure_future(
                svc.submit_posterior(PosteriorRequest(n_draws=8)))
            with pytest.raises(UsageError):
                await svc.submit_posterior(
                    PosteriorRequest(points=np.zeros((4, 5))))
            return await ok

        res = asyncio.run(run())
        assert res.draws.shape == (8, 2)

    def test_warm_caps_at_the_dispatch_top_rung(self,
                                                basic_telemetry):
        """A warm shape past the batch ladder's top warms the TOP rung
        (dispatch chunks there — bucket_of's doubling would warm a
        shape no dispatch ever reaches)."""
        from pint_tpu.telemetry import jaxevents

        svc = self._svc()   # batch ladder (1, 2, 4)
        rep = svc.warm_posterior([(100, 64)])
        ident = svc.posterior.ident()
        assert {e.name for e in rep.entries} == {
            f"posterior.draw[4x64x2@{ident}]",
            f"posterior.logprob[4x64x2@{ident}]"}
        before = jaxevents.counts()
        out = svc.serve_posterior(
            [PosteriorRequest(n_draws=10) for _ in range(8)])
        assert jaxevents.counts().compiles - before.compiles == 0
        assert {o.batch for o in out} == {4}

    def test_posterior_serve_events_validate(self, tmp_path):
        from pint_tpu import telemetry
        from pint_tpu.telemetry import runlog
        from tools.telemetry_report import validate_run_dir

        run_dir = str(tmp_path / "run")
        telemetry.activate("full")
        try:
            runlog.start_run(run_dir, name="posterior-test",
                             probe_device=False)
            svc = self._svc()
            svc.serve_posterior([PosteriorRequest(n_draws=8),
                                 PosteriorRequest(
                                     points=np.zeros((2, 2)))])
            runlog.end_run()
        finally:
            telemetry.deactivate()
        errors = []
        validate_run_dir(run_dir, errors)
        assert not errors, errors
        recs = [json.loads(ln) for ln in
                open(os.path.join(run_dir, "events.jsonl"))]
        served = [r["event"]["attrs"] for r in recs
                  if r.get("type") == "event"
                  and r["event"]["name"] == "posterior_serve"]
        assert {a["kind"] for a in served} == {"draw", "logprob"}
        assert all(a["latency_ms"] >= 0 and a["compiles"] >= 0
                   for a in served)


class TestWarmPathAcceptance:
    def test_aot_round_trip_compiles_zero_identical(self, aot_dir,
                                                    basic_telemetry):
        """The PR acceptance pin: populate the AOT cache with the
        posterior executables, simulate a new process (cache clear +
        fresh pool), re-warm all-hit, and serve with compiles == 0
        and bit-identical draws."""
        import jax

        from pint_tpu.telemetry import jaxevents

        ap = _tiny_posterior()
        cfg = ServeConfig(draw_buckets=(64,), batch_buckets=(1, 2, 4))
        svc = TimingService(cfg)
        svc.register_posterior(ap, seed=7)
        rep = svc.warm_posterior([(4, 64), (1, 64)])
        assert rep.cold_compiles == len(rep.entries) == 4
        cold = svc.serve_posterior(
            [PosteriorRequest(n_draws=20, request_id=f"r{i}")
             for i in range(4)])

        # --- process-equivalent warm start ---------------------------
        jax.clear_caches()
        svc2 = TimingService(cfg, pool=WarmPool())
        svc2.register_posterior(ap, seed=7)
        rep2 = svc2.warm_posterior([(4, 64), (1, 64)])
        assert rep2.cache_hits == len(rep2.entries) == 4, \
            f"expected all-hit re-warm, got {rep2.to_dict()}"
        assert rep2.cold_compiles == 0
        before = jaxevents.counts()
        warm = svc2.serve_posterior(
            [PosteriorRequest(n_draws=20, request_id=f"r{i}")
             for i in range(4)])
        delta = jaxevents.counts() - before
        assert delta.compiles == 0, \
            "steady-state posterior serving must pay zero compiles"
        for a, b in zip(cold, warm):
            np.testing.assert_array_equal(a.draws, b.draws)

    def test_no_registration_builds_no_executables(self):
        """The default-unchanged acceptance pin: a service without a
        registered flow holds no posterior executables and its warm
        pool stays exactly the fit-kernel surface."""
        svc = TimingService(ServeConfig())
        assert svc.posterior is None
        assert svc.pool.entries() == []
        assert svc.posterior_latency_summary()["n"] == 0


# ---------------------------------------------------------------------------
# slow acceptance: flow vs MCMC on the stand-in workload
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestMCMCAgreement:
    def test_flow_matches_mcmc_marginals_and_is_10x_faster(
            self, standin):
        """The ISSUE's acceptance criterion, at the stand-in scale the
        test image supports: the flow posterior's marginals match the
        MCMCFitter chain (KS < 0.1, means within 0.2 pooled sigma,
        stds within 30%) and the amortized draw path is >= 10x faster
        wall-clock than the MCMC sampling it replaces."""
        import time as _time

        from scipy.stats import ks_2samp

        from pint_tpu.mcmc_fitter import MCMCFitter

        _, bt = standin
        mf = MCMCFitter(bt.toas, bt.model, nwalkers=32)
        t0 = _time.perf_counter()
        mf.fit_toas(maxiter=400, seed=12)
        mcmc_s = _time.perf_counter() - t0
        chain = mf.get_posterior_samples(burn_frac=0.5)

        vi = AmortizedVI.from_fitter(mf, n_layers=4, hidden=16, seed=2)
        res = train_flow(vi, TrainConfig(steps=400, n_samples=64,
                                         lr=1e-2, seed=6))
        assert res.elbo_final > res.elbo_trace[0]
        ap = AmortizedPosterior.from_training(vi, res)
        ap.draw(len(chain), seed=8)          # settle the compile
        t0 = _time.perf_counter()
        draws = ap.draw(len(chain), seed=9)
        flow_s = _time.perf_counter() - t0

        for i, p in enumerate(vi.param_labels):
            ks = ks_2samp(chain[:, i], draws[:, i]).statistic
            sig = 0.5 * (chain[:, i].std() + draws[:, i].std())
            dmean = abs(chain[:, i].mean() - draws[:, i].mean())
            assert ks < 0.1, (p, ks)
            assert dmean < 0.2 * sig, (p, dmean, sig)
            ratio = draws[:, i].std() / chain[:, i].std()
            assert 0.7 < ratio < 1.3, (p, ratio)
        assert flow_s * 10 <= mcmc_s, \
            f"amortized draw {flow_s:.3f}s vs MCMC {mcmc_s:.3f}s"
