"""Long-tail completeness (VERDICT r2 directive #9): ORBWAVES orbital-phase
Fourier modulation, ITOA tim-format refusal, T2SpacecraftObs flag positions.

Reference: ``binary_orbits.py:243 OrbitWaves`` (+ ``pulsar_binary.py:62-72``
published formula), ``toa.py:557`` (ITOA raises), ``special_locations.py:161``.
"""

import numpy as np
import pytest


class TestORBWAVES:
    PAR_BASE = [
        "PSR ORBW\n", "RAJ 07:00:00 1\n", "DECJ 12:00:00 1\n",
        "F0 300.5 1\n", "PEPOCH 55400\n", "DM 9.0\n",
        "BINARY ELL1\n", "PB 0.4 1\n", "A1 2.1 1\n", "TASC 55399.5 1\n",
        "EPS1 1e-6\n", "EPS2 -2e-6\n", "UNITS TDB\n",
    ]
    WAVES = [
        "ORBWAVE_OM 1.5e-7\n", "ORBWAVE_EPOCH 55400\n",
        "ORBWAVEC0 2e-4 1\n", "ORBWAVES0 -1e-4 1\n",
        "ORBWAVEC1 5e-5 1\n", "ORBWAVES1 3e-5 1\n",
    ]

    def _delay(self, par_lines, mjds):
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        m = get_model(par_lines)
        t = make_fake_toas_fromMJDs(mjds, m, obs="bat", error_us=1.0)
        comp = next(c for n, c in m.components.items()
                    if n.startswith("Binary"))
        m._get_compiled(t, tuple(m.free_params))
        entry = m._cache["data"][t]
        batch, ctx = entry[1], entry[2]
        pv = dict(m._const_pv())
        for nm in m.free_params:
            pv[nm] = float(getattr(m, nm).value or 0.0)
        import jax.numpy as jnp

        name = next(n for n, c in m.components.items() if c is comp)
        return m, np.asarray(comp.delay_func(pv, batch, ctx[name],
                                             jnp.zeros(batch.ntoas)))

    def test_waves_modulate_orbital_phase(self):
        """delay(waves) == delay evaluated at a time shifted so the base
        orbital phase equals base-phase + dphi (published formula,
        reference pulsar_binary.py:71-72)."""
        mjds = np.linspace(55350, 55450, 40)
        m0, d0 = self._delay(self.PAR_BASE, mjds)
        mw, dw = self._delay(self.PAR_BASE + self.WAVES, mjds)
        assert not np.allclose(d0, dw)
        # clean-room oracle: shift each TOA's time by dphi * PB so the
        # unmodulated model lands on the same orbital phase
        om = 1.5e-7
        pb_d = 0.4
        tw = (mjds - 55400.0) * 86400.0  # TASC/epoch offsets cancel? no:
        tw = tw + (55399.5 - 55400.0) * 86400.0  # t - ORBWAVE_EPOCH, t ~ tasc
        # tw must be (t - ORBWAVE_EPOCH); t here = mjd (barycentric site)
        tw = (mjds - 55400.0) * 86400.0
        dphi = (2e-4 * np.cos(om * tw) + -1e-4 * np.sin(om * tw)
                + 5e-5 * np.cos(2 * om * tw) + 3e-5 * np.sin(2 * om * tw))
        mjds_shift = mjds + dphi * pb_d
        _, d0_shifted = self._delay(self.PAR_BASE, mjds_shift)
        # the Roemer delay at the shifted phase matches the waves delay to
        # the size of second-order terms (dphi ~ 2e-4 orbits)
        assert np.allclose(dw, d0_shifted, atol=5e-7)
        assert np.max(np.abs(dw - d0)) > 1e-4  # modulation is resolvable

    def test_zero_amplitude_waves_match_base(self):
        mjds = np.linspace(55350, 55450, 16)
        _, d0 = self._delay(self.PAR_BASE, mjds)
        zero = ["ORBWAVE_OM 1.5e-7\n", "ORBWAVE_EPOCH 55400\n",
                "ORBWAVEC0 0.0 1\n", "ORBWAVES0 0.0 1\n"]
        _, dz = self._delay(self.PAR_BASE + zero, mjds)
        assert np.allclose(d0, dz, atol=1e-12)

    def test_waves_params_roundtrip_parfile(self):
        from pint_tpu.models import get_model

        m = get_model(self.PAR_BASE + self.WAVES)
        text = m.as_parfile()
        m2 = get_model(text.splitlines(keepends=True))
        assert float(m2.ORBWAVEC1.value) == 5e-5
        assert float(m2.ORBWAVE_OM.value) == 1.5e-7

    def test_gapped_indices_rejected(self):
        from pint_tpu.exceptions import TimingModelError
        from pint_tpu.models import get_model

        bad = self.PAR_BASE + ["ORBWAVE_OM 1e-7\n", "ORBWAVE_EPOCH 55400\n",
                               "ORBWAVEC0 1e-4\n", "ORBWAVES0 1e-4\n",
                               "ORBWAVEC2 1e-5\n", "ORBWAVES2 1e-5\n"]
        with pytest.raises(TimingModelError, match="without gaps"):
            get_model(bad)


class TestITOA:
    def test_itoa_line_raises(self, tmp_path):
        from pint_tpu.exceptions import PintFileError
        from pint_tpu.io.tim import read_tim_file

        # two-char site code, decimal point in column 15 (0-based 14)
        line = "AO 1400.00 500.1234567890123  1.00\n"
        assert line[14] == "."
        p = tmp_path / "itoa.tim"
        p.write_text(line)
        with pytest.raises(PintFileError, match="ITOA"):
            read_tim_file(str(p))


class TestT2SpacecraftObs:
    def test_flag_positions_flow_to_posvel(self, tmp_path):
        from pint_tpu.toa import get_TOAs

        lines = ["FORMAT 1\n"]
        tel = [(1234.5, -2345.6, 3456.7), (2000.0, 1000.0, -500.0)]
        vel = [(1.5, -2.5, 0.5), (-1.0, 0.25, 2.0)]
        for i, m in enumerate((55000.25, 55001.75)):
            tx, ty, tz = tel[i]
            vx, vy, vz = vel[i]
            lines.append(
                f"sc{i} 1400.0 {m:.13f} 1.0 stl_geo -telx {tx} -tely {ty} "
                f"-telz {tz} -vx {vx} -vy {vy} -vz {vz}\n")
        p = tmp_path / "sc.tim"
        p.write_text("".join(lines))
        t = get_TOAs(str(p), include_bipm=False)
        from pint_tpu.ephemeris import load_ephemeris

        eph = load_ephemeris(t.ephem)
        epos, evel = eph.posvel_ssb("earth", np.asarray(t.tdb, np.float64))
        assert np.allclose(t.ssb_obs_pos_km - epos, np.asarray(tel),
                           atol=1e-9)
        assert np.allclose(t.ssb_obs_vel_kms - evel, np.asarray(vel),
                           atol=1e-12)

    def test_missing_flags_raise(self, tmp_path):
        from pint_tpu.toa import get_TOAs

        p = tmp_path / "bad.tim"
        p.write_text("FORMAT 1\nsc 1400.0 55000.2500000000000 1.0 stl_geo\n")
        with pytest.raises(ValueError, match="telx"):
            get_TOAs(str(p), include_bipm=False)

    def test_no_gps_correction(self, tmp_path, monkeypatch):
        """Even when the pipeline asks for GPS corrections (its default),
        the spacecraft site's policy wins (reference
        ``special_locations.py:170`` apply_gps2utc=False)."""
        import numpy as np

        from pint_tpu.observatory import clock_file as cfmod
        from pint_tpu.observatory import get_observatory

        ob = get_observatory("stl_geo")
        assert ob.include_gps is False
        assert get_observatory("spacecraft") is ob
        # plant a gps2utc.clk with a huge correction; spacecraft must ignore
        (tmp_path / "gps2utc.clk").write_text(
            "# UTC(GPS) UTC\n40000 1.0\n60000 1.0\n")
        monkeypatch.setenv("PINT_CLOCK_DIR", str(tmp_path))
        saved = dict(cfmod._cache)
        cfmod._cache.clear()
        try:
            mjd = np.array([55000.5])
            assert ob.clock_corrections(mjd, include_gps=True)[0] == 0.0
            gbt = get_observatory("gbt")
            assert gbt.clock_corrections(mjd, include_gps=True)[0] == 1.0
        finally:
            cfmod._cache.clear()
            cfmod._cache.update(saved)


class TestClockWarnDedup:
    """Clock diagnostics are deduplicated to once per (filename, kind)
    per process: out-of-range text varies per TOA batch (different MJD
    ranges), so without module-level dedup a bench tail fills with the
    same missing-file story and drowns real diagnostics."""

    @pytest.fixture
    def warn_counter(self):
        import logging

        from pint_tpu.logging import log

        records = []

        class Grab(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        h = Grab(level=logging.WARNING)
        log.addHandler(h)
        yield records
        log.removeHandler(h)

    @pytest.fixture(autouse=True)
    def fresh_warned(self):
        from pint_tpu.observatory import clock_file as cfmod

        saved_cache = dict(cfmod._cache)
        saved_warned = set(cfmod._warned)
        cfmod._cache.clear()
        cfmod._warned.clear()
        yield
        cfmod._cache.clear()
        cfmod._cache.update(saved_cache)
        cfmod._warned.clear()
        cfmod._warned.update(saved_warned)

    def test_missing_file_warns_once(self, warn_counter):
        from pint_tpu.observatory.clock_file import find_clock_file

        for _ in range(4):
            assert find_clock_file("definitely_absent_dedup.clk",
                                   fmt="tempo2") is None
        hits = [m for m in warn_counter if "definitely_absent_dedup" in m]
        assert len(hits) == 1
        assert "assuming zero correction" in hits[0]

    def test_out_of_range_warns_once_despite_varying_text(self,
                                                          warn_counter):
        """Each evaluate() call covers a DIFFERENT out-of-range window,
        so the logging layer's exact-message dedup can never catch it —
        the per-filename dedup must."""
        from pint_tpu.observatory.clock_file import ClockFile

        cf = ClockFile([50000.0, 50010.0], [1.0, 2.0],
                       filename="dedup_probe.clk")
        cf.evaluate([50020.0])
        cf.evaluate([50035.0])   # different MJD -> different message
        cf.evaluate([49990.0])
        hits = [m for m in warn_counter if "dedup_probe" in m]
        assert len(hits) == 1

    def test_distinct_files_each_warn(self, warn_counter):
        from pint_tpu.observatory.clock_file import find_clock_file

        assert find_clock_file("dedup_a.clk") is None
        assert find_clock_file("dedup_b.clk") is None
        assert len([m for m in warn_counter if "dedup_a" in m]) == 1
        assert len([m for m in warn_counter if "dedup_b" in m]) == 1

    def test_error_policy_still_raises_every_time(self, warn_counter):
        """Dedup silences REPEAT warnings only — the limits='error'
        escalation path must keep raising on every call."""
        from pint_tpu.exceptions import ClockCorrectionOutOfRange
        from pint_tpu.observatory.clock_file import ClockFile

        cf = ClockFile([50000.0, 50010.0], [1.0, 2.0],
                       filename="dedup_err.clk")
        for _ in range(2):
            with pytest.raises(ClockCorrectionOutOfRange):
                cf.evaluate([50020.0], limits="error")
        assert [m for m in warn_counter if "dedup_err" in m] == []
