"""Corrupt-corpus suite for the input-integrity layer.

Every validator the ingestion path grew (par syntax, tim syntax,
NaN/zero-error/duplicate TOA, coverage gap) is proven to *fire*: a healthy
fixture is corrupted via :mod:`pint_tpu.runtime.faultinject` contexts (or
targeted mutation), the strict policy must raise the typed error, and the
lenient policy must quarantine/record diagnostics while round-tripping the
good rows.  The outlier-robust fit is proven on a 5%-contaminated
synthetic dataset: Huber IRLS recovers F0/F1 within 3 sigma while plain
WLS does not.
"""

import io
import os
import pickle

import numpy as np
import pytest

from pint_tpu.exceptions import (
    ParSyntaxError,
    PintFileError,
    TimSyntaxError,
    TOAIntegrityError,
    UsageError,
)

PAR = """
PSR  J0000+0000
RAJ  04:37:00.0
DECJ -47:15:00.0
POSEPOCH 55000
F0   173.6879489990983 1
F1   -1.728e-15 1
PEPOCH 55000
DM   2.64476
EPHEM DE440
UNITS TDB
"""

F0_TRUE, F1_TRUE = 173.6879489990983, -1.728e-15


def _model(extra=""):
    from pint_tpu.models import get_model

    return get_model(io.StringIO(PAR + extra))


def _healthy_tim(path, n=8, start=55000.0):
    lines = ["FORMAT 1\n"]
    for i in range(n):
        lines.append(f"fake{i} 1400.0 {start + 10.0 * i:.13f} 1.0 gbt\n")
    path.write_text("".join(lines))
    return str(path)


def _healthy_par(path):
    path.write_text(PAR)
    return str(path)


def _fake_toas(n=40, seed=3, error_us=1.0):
    from pint_tpu.simulation import make_fake_toas_uniform

    m = _model()
    t = make_fake_toas_uniform(54000, 55500, n, m, error_us=error_us,
                               add_noise=True,
                               rng=np.random.default_rng(seed))
    return m, t


# ---------------------------------------------------------------------------
# par syntax
# ---------------------------------------------------------------------------

class TestParSyntax:
    def test_fortran_float_d_exponents(self):
        from pint_tpu.io.par import fortran_float

        assert fortran_float("-1.181D-15") == pytest.approx(-1.181e-15)
        assert fortran_float("2.5d3") == 2500.0
        assert fortran_float("1.0E2") == 100.0
        assert fortran_float("173.6879489990983") == 173.6879489990983

    def test_fortran_float_garbage_typed(self):
        from pint_tpu.io.par import fortran_float

        with pytest.raises(ParSyntaxError, match="1.2.3"):
            fortran_float("1.2.3")
        with pytest.raises(ParSyntaxError):
            fortran_float("12D")  # bare exponent marker
        # typed AND backwards compatible
        with pytest.raises(ValueError):
            fortran_float("not-a-number")
        with pytest.raises(PintFileError):
            fortran_float("--5")

    def test_garbled_par_strict_raises(self, tmp_path):
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.runtime import faultinject as fi

        src = _healthy_par(tmp_path / "good.par")
        # garble the F0 line's KEY so the failure is a par-syntax one
        with fi.garbled_copy(src, lines=[5],
                             mutate=lambda ln: "0@#" + ln) as bad:
            with pytest.raises(ParSyntaxError, match="invalid par-file key"):
                parse_parfile(bad, policy="strict")

    def test_garbled_par_lenient_roundtrips_good_rows(self, tmp_path):
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.runtime import faultinject as fi

        src = _healthy_par(tmp_path / "good.par")
        with fi.garbled_copy(src, lines=[5],
                             mutate=lambda ln: "0@#" + ln) as bad:
            d = parse_parfile(bad, policy="lenient")
        assert "par-invalid-key" in d.diagnostics.codes()
        assert len(d.diagnostics.errors) == 1
        # every other key survived
        for key in ("PSR", "RAJ", "DECJ", "F1", "PEPOCH", "DM"):
            assert key in d
        # the garbled F0 line is gone, not half-parsed
        assert "0@#F0" not in d and "F0" not in d

    def test_par_error_carries_location(self, tmp_path):
        from pint_tpu.io.par import parse_parfile

        p = tmp_path / "loc.par"
        p.write_text("PSR J1\nF0 10 1\n2BAD xx\n")
        with pytest.raises(ParSyntaxError) as ei:
            parse_parfile(str(p), policy="strict")
        assert ei.value.line == 3
        assert ei.value.file == str(p)
        assert ei.value.token == "2BAD"
        assert f"{p}:3" in str(ei.value)

    def test_duplicate_key_warning(self):
        from pint_tpu.io.par import parse_parfile

        d = parse_parfile("F0 10 1\nF0 11\nJUMP -fe A 0.1\nJUMP -fe B 0.2\n",
                          policy="collect")
        assert "par-duplicate-key" in d.diagnostics.codes()
        # mask families (JUMP) repeat legally: exactly one duplicate record
        assert len([c for c in d.diagnostics.codes()
                    if c == "par-duplicate-key"]) == 1
        assert len(d["JUMP"]) == 2

    def test_truncated_par_keeps_parsing(self, tmp_path):
        """A half-transferred par file parses to its surviving keys (no
        crash, no silent total loss)."""
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.runtime import faultinject as fi

        src = _healthy_par(tmp_path / "good.par")
        with fi.truncated_copy(src, fraction=0.5) as bad:
            d = parse_parfile(bad, policy="lenient")
        assert "PSR" in d and len(d) >= 2


# ---------------------------------------------------------------------------
# tim syntax
# ---------------------------------------------------------------------------

class TestTimSyntax:
    def test_garbled_tim_strict_raises_with_location(self, tmp_path):
        from pint_tpu.io.tim import read_tim_file
        from pint_tpu.runtime import faultinject as fi

        src = _healthy_tim(tmp_path / "good.tim")
        with fi.garbled_copy(src, lines=[3], seed=1) as bad:
            with pytest.raises(TimSyntaxError) as ei:
                read_tim_file(bad, policy="strict")
            assert ei.value.line == 4  # 1-based
            assert ei.value.file == bad

    def test_garbled_tim_lenient_roundtrips_good_rows(self, tmp_path):
        from pint_tpu.integrity import Diagnostics
        from pint_tpu.io.tim import read_tim_file
        from pint_tpu.runtime import faultinject as fi

        src = _healthy_tim(tmp_path / "good.tim", n=8)
        good, _ = read_tim_file(src)
        with fi.garbled_copy(src, lines=[3], seed=1) as bad:
            diags = Diagnostics(bad)
            toas, _ = read_tim_file(bad, policy="lenient", diagnostics=diags)
        assert len(toas) == len(good) - 1
        assert "tim-bad-toa-line" in diags.codes()
        # surviving rows parse identically to the uncorrupted read
        good_mjds = {(t.mjd_int, t.mjd_frac_str) for t in good}
        assert {(t.mjd_int, t.mjd_frac_str) for t in toas} < good_mjds

    def test_unknown_format_directive(self, tmp_path):
        from pint_tpu.integrity import Diagnostics
        from pint_tpu.io.tim import read_tim_file

        p = tmp_path / "fmt.tim"
        p.write_text("FORMAT 7\nfake 1400.0 55000.1 1.0 gbt\n")
        with pytest.raises(TimSyntaxError, match="FORMAT") as ei:
            read_tim_file(str(p), policy="strict")
        assert ei.value.line == 1
        # the typed error must not be re-wrapped as a generic bad-command
        # failure: the offending token survives
        assert ei.value.token == "7"
        diags = Diagnostics(str(p))
        read_tim_file(str(p), policy="lenient", diagnostics=diags)
        assert "tim-unknown-format" in diags.codes()

    def test_modeless_line(self, tmp_path):
        """A line no layout heuristic matches: typed error in strict,
        diagnostic + skip in lenient."""
        from pint_tpu.integrity import Diagnostics
        from pint_tpu.io.tim import read_tim_file

        p = tmp_path / "modeless.tim"
        # no FORMAT 1, short line, padded first cols: no layout matches
        p.write_text("  x y z\n")
        with pytest.raises(TimSyntaxError, match="unrecognized TOA line"):
            read_tim_file(str(p), policy="strict")
        diags = Diagnostics(str(p))
        toas, _ = read_tim_file(str(p), policy="lenient", diagnostics=diags)
        assert toas == []
        assert "tim-unknown-line" in diags.codes()

    def test_skip_region_garbage_is_not_flagged(self, tmp_path):
        from pint_tpu.integrity import Diagnostics
        from pint_tpu.io.tim import read_tim_file

        p = tmp_path / "skip.tim"
        p.write_text("FORMAT 1\nSKIP\ntotal garbage here\nNOSKIP\n"
                     "fake 1400.0 55000.1 1.0 gbt\n")
        diags = Diagnostics(str(p))
        toas, _ = read_tim_file(str(p), policy="strict", diagnostics=diags)
        assert len(toas) == 1

    def test_bad_command_argument(self, tmp_path):
        from pint_tpu.integrity import Diagnostics
        from pint_tpu.io.tim import read_tim_file

        p = tmp_path / "cmd.tim"
        p.write_text("FORMAT 1\nEFAC banana\nfake 1400.0 55000.1 1.0 gbt\n")
        with pytest.raises(TimSyntaxError, match="EFAC"):
            read_tim_file(str(p), policy="strict")
        diags = Diagnostics(str(p))
        toas, _ = read_tim_file(str(p), policy="lenient", diagnostics=diags)
        assert len(toas) == 1
        assert "tim-bad-command" in diags.codes()

    def test_collect_policy_is_silent_but_complete(self, tmp_path):
        from pint_tpu.integrity import Diagnostics
        from pint_tpu.io.tim import read_tim_file

        p = tmp_path / "multi.tim"
        p.write_text("FORMAT 1\nbad line one\nfake 1400.0 55000.1 1.0 gbt\n"
                     "another bad\n")
        diags = Diagnostics(str(p))
        toas, _ = read_tim_file(str(p), policy="collect", diagnostics=diags)
        assert len(toas) == 1
        assert len(diags.errors) == 2

    def test_get_toas_attaches_diagnostics(self, tmp_path):
        from pint_tpu.toa import get_TOAs

        src = _healthy_tim(tmp_path / "good.tim")
        t = get_TOAs(src, ephem="DE440", include_gps=False,
                     include_bipm=False, policy="lenient")
        assert hasattr(t, "ingest_diagnostics")
        assert len(t.ingest_diagnostics.errors) == 0


# ---------------------------------------------------------------------------
# TOA quarantine
# ---------------------------------------------------------------------------

class TestTOAQuarantine:
    def test_nan_mjd(self):
        m, t = _fake_toas()
        t.utc_mjd[5] = np.nan
        with pytest.raises(TOAIntegrityError, match="non-finite MJD"):
            t.validate(policy="strict", check_coverage=False)
        rep = t.validate(policy="lenient", check_coverage=False)
        assert rep.codes() == ["toa-nonfinite-mjd"]
        assert t.n_quarantined == 1
        assert t.quarantine_mask[5]
        assert "non-finite MJD" in t.quarantine_reasons[5][0]

    def test_zero_and_absurd_errors(self):
        m, t = _fake_toas()
        t.error_us[0] = 0.0
        t.error_us[1] = -2.0
        t.error_us[2] = 1e12
        t.error_us[3] = np.inf
        with pytest.raises(TOAIntegrityError, match="uncertainty"):
            t.validate(policy="strict", check_coverage=False)
        rep = t.validate(policy="collect", check_coverage=False)
        assert rep.codes() == ["toa-bad-error"]
        assert t.n_quarantined == 4

    def test_duplicate_rows(self):
        m, t = _fake_toas()
        t.utc_mjd[7] = t.utc_mjd[6]
        with pytest.raises(TOAIntegrityError, match="duplicate"):
            t.validate(policy="strict", check_coverage=False)
        rep = t.validate(policy="lenient", check_coverage=False)
        assert rep.codes() == ["toa-duplicate"]
        # only the second occurrence is quarantined
        assert t.quarantine_mask[7] and not t.quarantine_mask[6]

    @pytest.mark.skipif(np.finfo(np.longdouble).eps > 2e-19,
                        reason="needs x87 longdouble to place sub-us TOAs")
    def test_submicrosecond_neighbors_are_not_duplicates(self):
        """Two genuine TOAs ~0.4 us apart collide in float64 (ulp at MJD
        55000 is ~0.6 us) but are distinct measurements — the duplicate
        check keys on the full (hi, lo) time and must not merge them."""
        m, t = _fake_toas()
        t.utc_mjd[7] = t.utc_mjd[6] + np.longdouble(0.4e-6 / 86400.0)
        rep = t.validate(policy="collect", check_coverage=False)
        assert "toa-duplicate" not in rep.codes()

    def test_revalidation_after_repair_releases_rows(self):
        """A quarantined row whose data is fixed in place is released by
        the next validate() — a stale mask must not silently keep
        excluding repaired rows from fits."""
        m, t = _fake_toas()
        t.error_us[3] = 0.0
        t.validate(policy="collect", check_coverage=False)
        assert t.n_quarantined == 1
        t.error_us[3] = 1.0  # repair
        rep = t.validate(policy="collect", check_coverage=False)
        assert not rep
        assert t.n_quarantined == 0
        assert t.quarantine_mask is None

    def test_corrupted_tim_fixture_quarantine_end_to_end(self, tmp_path):
        """Corrupt a healthy tim (zero error column + duplicated row) via
        a faultinject mutator; strict load raises, lenient load
        quarantines and the fit sees only certified rows."""
        from pint_tpu.runtime import faultinject as fi
        from pint_tpu.toa import get_TOAs

        src = _healthy_tim(tmp_path / "good.tim", n=8)

        def zero_error(ln):
            return ln.replace(" 1.0 gbt", " 0.0 gbt")

        with fi.garbled_copy(src, lines=[2], mutate=zero_error,
                             dst=str(tmp_path / "zero.tim")) as bad:
            with pytest.raises(TOAIntegrityError):
                get_TOAs(bad, ephem="DE440", include_gps=False,
                         include_bipm=False, policy="strict")
            t = get_TOAs(bad, ephem="DE440", include_gps=False,
                         include_bipm=False, policy="lenient")
        assert t.n_quarantined == 1
        assert len(t.certified()) == 7

    def test_ephem_coverage_gap(self, monkeypatch):
        import pint_tpu.ephemeris as em

        class FakeEph:
            def coverage_mjd(self):
                return (53000.0, 55000.0)

        monkeypatch.setitem(em._loaded, "de_fake", FakeEph())
        m, t = _fake_toas()  # spans 54000-55500: tail is out of coverage
        with pytest.raises(TOAIntegrityError, match="coverage"):
            t.validate(policy="strict", ephem="DE_FAKE")
        rep = t.validate(policy="collect", ephem="DE_FAKE")
        assert "toa-ephem-coverage" in rep.codes()
        mjds = np.asarray(t.get_mjds(), np.float64)
        assert np.array_equal(t.quarantine_mask, mjds > 55000.0)

    def test_clock_coverage_gap(self, monkeypatch):
        from pint_tpu.observatory import get_observatory

        m, t = _fake_toas()
        ob = get_observatory("gbt")
        monkeypatch.setattr(ob, "last_clock_correction_mjd",
                            lambda limits="warn": 54750.0, raising=False)
        with pytest.raises(TOAIntegrityError, match="clock"):
            t.validate(policy="strict", check_coverage=True, ephem=None)
        rep = t.validate(policy="collect", check_coverage=True, ephem=None)
        assert "toa-clock-coverage" in rep.codes()
        mjds = np.asarray(t.get_mjds(), np.float64)
        assert t.n_quarantined == int(np.sum(mjds > 54750.0))

    def test_mask_carried_through_getitem_and_pickle(self):
        m, t = _fake_toas()
        t.error_us[4] = 0.0
        t.validate(policy="collect", check_coverage=False)
        sl = t[2:10]
        assert sl.quarantine_mask is not None
        assert sl.quarantine_mask[2]  # row 4 of parent
        assert "uncertainty" in sl.quarantine_reasons[2][0]
        # pickling round-trips the quarantine state
        t2 = pickle.loads(pickle.dumps(t))
        assert np.array_equal(t2.quarantine_mask, t.quarantine_mask)
        assert t2.quarantine_reasons == t.quarantine_reasons
        # adjust_TOAs keeps it
        t.adjust_TOAs(np.zeros(len(t)))
        assert t.n_quarantined == 1

    def test_mask_carried_through_merge(self):
        m, t = _fake_toas(n=10)
        t.error_us[1] = 0.0
        t.validate(policy="collect", check_coverage=False)
        m2, u = _fake_toas(n=5, seed=11)
        from pint_tpu.toa import merge_TOAs

        merged = merge_TOAs([t, u])
        assert merged.quarantine_mask is not None
        assert merged.n_quarantined == 1
        assert merged.quarantine_mask[1]
        assert not merged.quarantine_mask[10:].any()

    def test_fitter_and_grid_see_certified_rows_only(self):
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.grid import grid_chisq

        m, t = _fake_toas()
        t.error_us[3] = 0.0
        t.validate(policy="collect", check_coverage=False)
        f = WLSFitter(t, m)
        assert len(f.toas) == len(t) - 1
        assert f.toas_full is t
        chi2 = f.fit_toas(maxiter=2)
        assert np.isfinite(chi2)  # a zero-error row would make chi2 inf
        f0 = float(f.model.F0.value)
        chi2grid, _extra = grid_chisq(f, ["F0"],
                                      [np.linspace(f0 - 1e-9, f0 + 1e-9, 3)])
        assert np.all(np.isfinite(np.asarray(chi2grid)))

    def test_pickle_cache_respects_policy_key(self, tmp_path):
        import pint_tpu.config as config
        from pint_tpu.toa import get_TOAs

        src = _healthy_tim(tmp_path / "good.tim", n=4)
        # corrupt one row so lenient and strict genuinely differ
        body = (tmp_path / "good.tim").read_text()
        (tmp_path / "good.tim").write_text(
            body.replace(" 1.0 gbt", " 0.0 gbt", 1))
        t1 = get_TOAs(src, ephem="DE440", include_gps=False,
                      include_bipm=False, usepickle=True, policy="lenient")
        assert t1.n_quarantined == 1
        # the process-wide policy resolves at call time: flipping it to
        # strict must MISS the lenient cache and raise, not serve it
        old = config.ingestion_policy()
        config.set_ingestion_policy("strict")
        try:
            with pytest.raises(TOAIntegrityError):
                get_TOAs(src, ephem="DE440", include_gps=False,
                         include_bipm=False, usepickle=True)
        finally:
            config.set_ingestion_policy(old)

    def test_wideband_fitter_consumes_quarantine(self):
        """The wideband fitters' bespoke __init__ routes TOAs through the
        same quarantine consumption as every other fitter."""
        from pint_tpu.simulation import make_fake_toas_uniform
        from pint_tpu.wideband import WidebandTOAFitter

        m = _model()
        t = make_fake_toas_uniform(54000, 55500, 20, m, error_us=1.0,
                                   add_noise=True, wideband=True,
                                   rng=np.random.default_rng(9))
        t.error_us[5] = 0.0
        t.validate(policy="collect", check_coverage=False)
        f = WidebandTOAFitter(t, m)
        assert len(f.toas) == 19
        assert f.toas_full is t
        assert np.isfinite(f.fit_toas(maxiter=2))


# ---------------------------------------------------------------------------
# get_clusters guards (satellite)
# ---------------------------------------------------------------------------

class TestGetClustersGuards:
    def test_single_toa(self):
        from pint_tpu.toa import make_single_toa

        t = make_single_toa(55000.0, "gbt")
        assert t.get_clusters().tolist() == [0]

    def test_empty(self):
        m, t = _fake_toas(n=5)
        empty = t[np.zeros(5, dtype=bool)]
        assert len(empty.get_clusters()) == 0

    def test_unsorted_mjds(self):
        m, t = _fake_toas(n=6)
        mjds = np.array([55000.0, 55020.0, 55000.01, 55020.02, 55040.0,
                         55000.02], dtype=np.longdouble)
        t.utc_mjd = mjds
        c = t.get_clusters(gap_limit_hr=2.0)
        # rows at ~55000 share a cluster, ~55020 share one, 55040 is alone
        assert c[0] == c[2] == c[5] == 0
        assert c[1] == c[3] == 1
        assert c[4] == 2

    def test_bad_gap_limit(self):
        m, t = _fake_toas(n=5)
        with pytest.raises(UsageError):
            t.get_clusters(gap_limit_hr=0.0)


# ---------------------------------------------------------------------------
# outlier-robust fitting
# ---------------------------------------------------------------------------

def _contaminated(seed=7, n=60, frac=0.05, mag_s=5e-4):
    """Healthy synthetic TOAs with frac of them shifted by ~500 sigma."""
    m, t = _fake_toas(n=n, seed=seed)
    rng = np.random.default_rng(seed + 100)
    k = max(1, int(frac * n))
    idx = rng.choice(n, size=k, replace=False)
    delta = np.zeros(n)
    delta[idx] = mag_s * rng.choice([1, 1, -1], size=k)
    t.adjust_TOAs(delta)
    return m, t, np.sort(idx)


class TestRobustFitting:
    @pytest.mark.parametrize("fitter_name", ["WLSFitter",
                                             "DownhillWLSFitter"])
    def test_huber_recovers_contaminated_fit(self, fitter_name):
        """5% contamination: plain WLS lands far outside 3 sigma on F0/F1,
        the Huber fit lands inside."""
        import pint_tpu.fitter as fitmod

        cls = getattr(fitmod, fitter_name)
        m, t, idx = _contaminated()
        plain = cls(t, m)
        plain.fit_toas(maxiter=5)
        m2, t2, _ = _contaminated()
        rob = cls(t2, m2)
        rob.fit_toas(maxiter=5, robust="huber")
        for f, ok in ((plain, False), (rob, True)):
            n_f0 = abs(float(f.model.F0.value) - F0_TRUE) / f.errors["F0"]
            n_f1 = abs(float(f.model.F1.value) - F1_TRUE) / f.errors["F1"]
            if ok:
                assert n_f0 < 3.0 and n_f1 < 3.0, (n_f0, n_f1)
            else:
                assert n_f0 > 3.0 and n_f1 > 3.0, (n_f0, n_f1)
        # the final weights expose exactly the injected outliers
        w = np.asarray(rob.robust_weights)
        assert np.array_equal(np.nonzero(w < 0.5)[0], idx)
        assert rob.robust_iterations >= 1
        # plain fits advertise no robust state
        assert plain.robust_weights is None

    def test_healthy_fit_unchanged_by_robust_mode(self):
        """On clean data the Huber weights stay ~1 and the solution
        matches the plain fit to solver precision."""
        from pint_tpu.fitter import WLSFitter

        m, t = _fake_toas(n=40)
        plain = WLSFitter(t, m)
        plain.fit_toas(maxiter=3)
        m2, t2 = _fake_toas(n=40)
        rob = WLSFitter(t2, m2)
        rob.fit_toas(maxiter=3, robust="huber")
        w = np.asarray(rob.robust_weights)
        # a Gaussian sample legitimately has a ~2-3 sigma tail (weight
        # k/|z| ~ 0.5) but no heavy downweighting, and the solution stays
        # within one error bar of the plain fit (Huber is ~95% efficient,
        # not identical, on clean data)
        assert np.mean(w > 0.9) > 0.7
        assert w.min() > 0.3
        assert abs(float(rob.model.F0.value) - float(plain.model.F0.value)) \
            < 1.0 * plain.errors["F0"]

    @pytest.mark.parametrize("fitter_name", ["WLSFitter",
                                             "DownhillWLSFitter"])
    def test_plain_fit_after_robust_drops_weights(self, fitter_name):
        """A plain fit_toas() after a robust one on the same fitter must
        not inherit the IRLS weights — stale weights would silently
        reweight the 'plain' solve."""
        import pint_tpu.fitter as fitmod

        cls = getattr(fitmod, fitter_name)
        m, t, idx = _contaminated()
        f = cls(t, m)
        f.fit_toas(maxiter=5, robust="huber")
        assert f.robust_weights is not None
        f.fit_toas(maxiter=5)
        assert f.robust_weights is None
        # and the plain refit lands back on the contaminated solution
        n_f0 = abs(float(f.model.F0.value) - F0_TRUE) / f.errors["F0"]
        assert n_f0 > 3.0

    def test_garble_never_yields_par_comment_chars(self):
        """The default garbler must not splice '#'/'%' — those would turn
        a corrupted par line into a valid comment-truncated one."""
        from pint_tpu.runtime.faultinject import _default_garble

        rng = np.random.default_rng(0)
        for _ in range(200):
            g = _default_garble("F0 1.234567890123D-15 1\n", rng)
            assert "#" not in g and "%" not in g

    def test_robust_arg_validation(self):
        from pint_tpu.fitter import WLSFitter

        m, t = _fake_toas(n=10)
        f = WLSFitter(t, m)
        with pytest.raises(UsageError, match="robust"):
            f.fit_toas(robust="tukey")

    def test_robust_rejected_on_gls(self):
        from pint_tpu.gls_fitter import DownhillGLSFitter

        m = _model("TNREDAMP -13.0\nTNREDGAM 3.0\nTNREDC 5\n")
        from pint_tpu.simulation import make_fake_toas_uniform

        t = make_fake_toas_uniform(54000, 55500, 20, m, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(3))
        f = DownhillGLSFitter(t, m)
        with pytest.raises(UsageError, match="WLS"):
            f.fit_toas(robust="huber")


# ---------------------------------------------------------------------------
# doctor report
# ---------------------------------------------------------------------------

class TestDoctor:
    def test_doctor_reports_quarantine_and_weights(self):
        from pint_tpu.fitter import WLSFitter

        m, t, idx = _contaminated(n=40)
        t.error_us[2] = 0.0
        t.validate(policy="collect", check_coverage=False)
        f = WLSFitter(t, m)
        f.fit_toas(maxiter=3, robust="huber")
        rep = f.doctor()
        assert "quarantined" in rep
        assert "toa-bad-error" in rep
        assert "certified" in rep
        assert "downweighted" in rep
        assert "Model/TOA compatibility" in rep

    def test_doctor_flags_degenerate_all_toa_jump(self):
        """A free JUMP selecting every TOA is degenerate with the overall
        offset; the doctor names it."""
        from pint_tpu.fitter import DownhillWLSFitter

        m = _model("JUMP MJD 50000 60000 0.0 1\n")
        from pint_tpu.simulation import make_fake_toas_uniform

        t = make_fake_toas_uniform(54000, 55500, 12, m, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(5))
        f = DownhillWLSFitter(t, m)
        rep = f.doctor()
        assert "JUMP1" in rep and "every TOA" in rep

    def test_doctor_clean_fit_is_clean(self):
        from pint_tpu.fitter import WLSFitter

        m, t = _fake_toas(n=20)
        f = WLSFitter(t, m)
        f.fit_toas()
        rep = f.doctor()
        assert "0/20 row(s) quarantined" in rep
        assert "Model/TOA compatibility: clean" in rep
