"""Real NANOGrav 12.5-yr wideband datasets end-to-end (reference
``tests/datafile/*_NANOGrav_12yv3.wb.*``): full component stacks parse, the
wideband pipeline runs, and a simulated refit converges."""

import os

import numpy as np
import pytest

D = "/root/reference/tests/datafile"

pytestmark = pytest.mark.skipif(
    not os.path.exists(f"{D}/B1855+09_NANOGrav_12yv3.wb.tim"),
    reason="reference 12.5-yr datafiles unavailable")


@pytest.mark.parametrize("psr,binary", [
    ("B1855+09", "BinaryELL1"),
    ("J1614-2230", "BinaryELL1"),  # ELL1 with M2/SINI Shapiro
])
def test_12y_wideband_loads_and_fits(psr, binary):
    from pint_tpu.models import get_model_and_toas
    from pint_tpu.wideband import WidebandTOAResiduals

    m, t = get_model_and_toas(f"{D}/{psr}_NANOGrav_12yv3.wb.gls.par",
                              f"{D}/{psr}_NANOGrav_12yv3.wb.tim")
    assert binary in m.components
    assert "DispersionDMX" in m.components
    # wideband TOAs carry DM measurements
    assert all("pp_dm" in fl for fl in t.flags)
    # real TOAs + the built-in analytic ephemeris carry ~ms Earth-position
    # systematics (see bench.py), so assert the prefit pipeline is sane
    # rather than stepping a fit into nonphysical territory (J1614's free
    # SINI sits at 0.9999)
    r = WidebandTOAResiduals(t, m)
    assert np.all(np.isfinite(np.asarray(r.toa.time_resids)))
    assert np.all(np.isfinite(np.asarray(r.dm.resids)))
    chi2 = float(r.calc_chi2())
    assert np.isfinite(chi2) and chi2 > 0


def test_12y_wideband_simulated_refit():
    """On TOAs simulated at the real epochs the full 138-parameter wideband
    GLS fit must sit at chi2/dof ~ 1 (no ephemeris systematics)."""
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromtim, update_fake_dms
    from pint_tpu.wideband import WidebandTOAFitter

    m = get_model(f"{D}/B1855+09_NANOGrav_12yv3.wb.gls.par")
    t = make_fake_toas_fromtim(f"{D}/B1855+09_NANOGrav_12yv3.wb.tim", m)
    update_fake_dms(m, t, dm_error=1e-4)
    f = WidebandTOAFitter(t, m)
    chi2 = float(f.fit_toas(maxiter=2))
    ndata = 2 * len(t)
    assert chi2 < 0.5 * ndata  # noiseless simulation: far below chi2/dof=1
