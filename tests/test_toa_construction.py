"""Programmatic TOA construction (reference ``toa.py``: TOA objects,
get_TOAs_list, get_TOAs_array, get_clusters)."""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def model():
    from pint_tpu.models import get_model

    return get_model(["PSR CONTEST\n", "RAJ 04:00:00\n", "DECJ 10:00:00\n",
                      "F0 200.0 1\n", "PEPOCH 55100\n", "DM 20\n",
                      "UNITS TDB\n"])


class TestTOAObjects:
    def test_single_toa_forms(self):
        from pint_tpu.toa import TOA

        t = TOA(55000.5, error=1.5, obs="gbt", freq=1400.0, fe="Rcvr1_2")
        assert t.error == 1.5 and t.flags["fe"] == "Rcvr1_2"
        assert "55000.5" in str(t)
        line = t.as_line()
        assert "gbt" in line and "-fe Rcvr1_2" in line

    def test_mjd_pair_precision(self):
        from pint_tpu.toa import _split_mjd_value

        hi, lo = _split_mjd_value((55000, 0.123456789012345678))
        total = float(hi) + lo
        assert total == pytest.approx(55000.123456789012, abs=1e-9)
        hi2, _ = _split_mjd_value("55000.12345678901234567890")
        assert float(hi2) == pytest.approx(55000.1234567890123, rel=1e-15)


class TestGetTOAsList:
    def test_pipeline_matches_array(self, model):
        from pint_tpu.toa import TOA, get_TOAs_array, get_TOAs_list

        mjds = np.linspace(55000.0, 55200.0, 7)
        lst = [TOA(m, error=1.0, obs="gbt", freq=1400.0) for m in mjds]
        t1 = get_TOAs_list(lst, model=model)
        t2 = get_TOAs_array(mjds, "gbt", errors=1.0, freqs=1400.0,
                            model=model)
        assert len(t1) == len(t2) == 7
        np.testing.assert_allclose(
            np.asarray(t1.tdb, dtype=np.float64),
            np.asarray(t2.tdb, dtype=np.float64), rtol=0, atol=1e-12)
        np.testing.assert_allclose(t1.ssb_obs_pos_km, t2.ssb_obs_pos_km)
        # residuals computable through the standard stack
        from pint_tpu.residuals import Residuals

        r = Residuals(t2, model)
        assert np.all(np.isfinite(np.asarray(r.time_resids)))

    def test_flags_and_broadcast(self, model):
        from pint_tpu.toa import get_TOAs_array

        t = get_TOAs_array(np.array([55000.0, 55001.0]), "ao",
                           errors=np.array([1.0, 2.0]), freqs=430.0,
                           flags={"be": "puppi"}, model=model, fe="430")
        assert t.error_us.tolist() == [1.0, 2.0]
        assert all(f["be"] == "puppi" and f["fe"] == "430" for f in t.flags)
        with pytest.raises(ValueError):
            get_TOAs_array(np.array([55000.0]), "ao",
                           flags=[{}, {}], model=model)

    def test_mjd_pair_array(self, model):
        from pint_tpu.toa import get_TOAs_array

        hi = np.array([55000.0, 55001.0])
        lo = np.array([0.25, 0.75])
        t = get_TOAs_array((hi, lo), "gbt", model=model)
        np.testing.assert_allclose(
            np.asarray(t.utc_mjd, dtype=np.float64), hi + lo)


class TestClusters:
    def test_gap_clustering(self, model):
        from pint_tpu.toa import get_TOAs_array

        mjds = np.array([55000.0, 55000.01, 55000.02,  # epoch 1
                         55005.0, 55005.03,            # epoch 2
                         55020.0])                     # epoch 3
        t = get_TOAs_array(mjds, "gbt", model=model)
        c = t.get_clusters(gap_limit_hr=2.0)
        assert c.tolist() == [0, 0, 0, 1, 1, 2]
        t.get_clusters(gap_limit_hr=2.0, add_column=True)
        assert t.flags[3]["cluster"] == "1"
        # unsorted input clusters correctly too
        t2 = get_TOAs_array(mjds[::-1].copy(), "gbt", model=model)
        assert t2.get_clusters(gap_limit_hr=2.0).tolist() == [2, 1, 1, 0, 0, 0]


class TestReviewRegressions:
    def test_scale_refused(self):
        from pint_tpu.toa import TOA

        with pytest.raises(NotImplementedError):
            TOA(55000.0, scale="tdb")
        TOA(55000.0, scale="utc")  # fine

    def test_scalar_pair_is_one_toa(self, model):
        from pint_tpu.toa import get_TOAs_array

        t = get_TOAs_array((58000.0, 0.25), "gbt", model=model)
        assert len(t) == 1
        assert float(t.utc_mjd[0]) == pytest.approx(58000.25)

    def test_as_line_day_boundary(self):
        from pint_tpu.toa import TOA

        line = TOA("55000.99999999999999995", obs="gbt",
                   freq=1400.0).as_line()
        assert " 55001.0 " in line  # carried to the next day, not a day early
        # negative fractional part of a pair keeps its sign via the floor
        line2 = TOA((55001, -0.5), obs="gbt", freq=1400.0).as_line()
        assert " 55000.5 " in line2

    def test_slice_flags_isolated(self, model):
        from pint_tpu.toa import get_TOAs_array

        t = get_TOAs_array(np.array([55000.0, 55005.0, 55020.0]), "gbt",
                           model=model)
        sub = t[0:2]
        sub.get_clusters(gap_limit_hr=2.0, add_column=True)
        assert "cluster" in sub.flags[0]
        assert "cluster" not in t.flags[0]  # parent untouched


class TestModulePickle:
    def test_gz_roundtrip_and_search(self, model, tmp_path):
        from pint_tpu.toa import get_TOAs_array, load_pickle, save_pickle

        t = get_TOAs_array(np.array([55000.0, 55001.0]), "gbt", model=model)
        t.filename = str(tmp_path / "x.tim")
        save_pickle(t)  # default: <tim>.pickle.gz
        assert (tmp_path / "x.tim.pickle.gz").exists()
        t2 = load_pickle(str(tmp_path / "x.tim"))
        assert len(t2) == 2
        np.testing.assert_allclose(
            np.asarray(t2.tdb, dtype=np.float64),
            np.asarray(t.tdb, dtype=np.float64))
        with pytest.raises(IOError):
            load_pickle(str(tmp_path / "missing.tim"))

    def test_read_toa_file_alias(self):
        from pint_tpu.toa import read_toa_file

        raw, commands = read_toa_file(
            "/root/reference/src/pint/data/examples/NGC6440E.tim")
        assert len(raw) == 62

    def test_load_pickle_robustness(self, model, tmp_path):
        """Gzip sniffing by content, fall-through past corrupt candidates,
        bare-name candidate."""
        import gzip
        import pickle as pkl

        from pint_tpu.toa import get_TOAs_array, load_pickle

        t = get_TOAs_array(np.array([55000.0]), "gbt", model=model)
        # gzipped content under a non-.gz name still loads
        odd = tmp_path / "cache.pickle"
        with gzip.open(odd, "wb") as f:
            pkl.dump(t, f)
        assert len(load_pickle("x", picklefilename=str(odd))) == 1
        # corrupt .gz next to a valid .pickle: falls through
        base = tmp_path / "y.tim"
        (tmp_path / "y.tim.pickle.gz").write_bytes(b"\x1f\x8b garbage")
        with open(tmp_path / "y.tim.pickle", "wb") as f:
            pkl.dump(t, f)
        assert len(load_pickle(str(base))) == 1
        # bare-name candidate: the pickle path itself
        assert len(load_pickle(str(tmp_path / "y.tim.pickle"))) == 1

    def test_save_pickle_refuses_merged(self, model, tmp_path):
        from pint_tpu.toa import get_TOAs_array, merge_TOAs, save_pickle

        a = get_TOAs_array(np.array([55000.0]), "gbt", model=model)
        b = get_TOAs_array(np.array([55001.0]), "gbt", model=model)
        a.filename = str(tmp_path / "a.tim")
        merged = merge_TOAs([a, b])
        assert merged.filename is None
        with pytest.raises(ValueError, match="picklefilename"):
            save_pickle(merged)
        save_pickle(merged, str(tmp_path / "m.pickle.gz"))  # explicit OK
