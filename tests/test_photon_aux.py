"""Photon-domain completion (VERDICT r2 directive #7): FFTFIT start phase,
energy-dependent template primitives, and MCMC kill-and-resume.

Reference: PRESTO fftfit import at ``scripts/event_optimize.py:119-133``,
``templates/lceprimitives.py``/``lcenorm.py``, emcee HDF5 backend at
``scripts/event_optimize.py:900-910``.
"""

import numpy as np
import pytest


class TestFFTFIT:
    def _template(self, n=256):
        grid = (np.arange(n) + 0.5) / n
        return (np.exp(-0.5 * ((grid - 0.3) / 0.02) ** 2)
                + 0.4 * np.exp(-0.5 * ((grid - 0.7) / 0.05) ** 2))

    def test_noiseless_shift_recovered(self):
        from pint_tpu.fftfit import fftfit_basic, fftfit_full

        n = 256
        tmpl = self._template(n)
        for true in (0.0, 0.123456, 0.5, 0.987):
            prof = np.roll(tmpl, int(round(true * n)))  # integer-bin shift
            shift = fftfit_basic(tmpl, prof)
            err = (shift - round(true * n) / n + 0.5) % 1.0 - 0.5
            assert abs(err) < 1e-10, f"true={true}"
        # sub-bin shift via Fourier rotation
        k = np.fft.rfftfreq(n, d=1 / n)
        true = 0.2345678
        prof = np.fft.irfft(np.fft.rfft(tmpl) * np.exp(-2j * np.pi * k * true * 1.0), n)
        shift, eshift, scale, _ = fftfit_full(tmpl, prof)
        err = (shift - true + 0.5) % 1.0 - 0.5
        assert abs(err) < 1e-9
        assert scale == pytest.approx(1.0, rel=1e-9)

    def test_noisy_shift_within_errors(self):
        from pint_tpu.fftfit import fftfit_full

        n = 256
        tmpl = 5000.0 * self._template(n)
        rng = np.random.default_rng(8)
        true = 0.37
        k = np.fft.rfftfreq(n, d=1 / n)
        base = np.fft.irfft(np.fft.rfft(tmpl) * np.exp(-2j * np.pi * k * true), n)
        errs = []
        sigs = []
        for _ in range(40):
            prof = base + rng.normal(0, 20.0, n)
            shift, eshift, _, _ = fftfit_full(tmpl, prof)
            errs.append((shift - true + 0.5) % 1.0 - 0.5)
            sigs.append(eshift)
        errs = np.array(errs)
        # empirical scatter within a factor ~2 of the claimed uncertainty
        assert np.std(errs) < 2.5 * np.mean(sigs)
        assert np.std(errs) > 0.2 * np.mean(sigs)
        assert np.abs(np.mean(errs)) < 4 * np.std(errs) / np.sqrt(len(errs))

    def test_scale_recovered(self):
        from pint_tpu.fftfit import fftfit_full

        tmpl = self._template()
        prof = 3.7 * np.roll(tmpl, 10)
        _, _, scale, _ = fftfit_full(tmpl, prof)
        assert scale == pytest.approx(3.7, rel=1e-9)


class TestEnergyDependentTemplates:
    def test_lce_gaussian_drifts_with_energy(self):
        from pint_tpu.templates.lceprimitives import LCEGaussian

        g = LCEGaussian(p=[0.03, 0.5], slopes=[0.01, 0.1], e0_mev=1000.0)
        grid = np.linspace(0, 1, 200, endpoint=False)
        # at the pivot: identical to the base Gaussian
        at_pivot = g(grid, np.full(200, 3.0))
        from pint_tpu.templates.lcprimitives import LCGaussian

        base = LCGaussian(p=[0.03, 0.5])
        assert np.allclose(at_pivot, base(grid), rtol=1e-12)
        # a decade above the pivot: location moved by slope, width by slope
        pars = g.parameters_at(np.array([4.0]))[0]
        assert pars[1] == pytest.approx(0.6)
        assert pars[0] == pytest.approx(0.04)
        # each energy's pdf still integrates to 1
        for le in (2.0, 3.0, 4.0):
            vals = g(grid, np.full(200, le))
            assert np.trapezoid(np.append(vals, vals[0]),
                                np.append(grid, 1.0)) == pytest.approx(1.0, abs=1e-6)

    def test_enorm_angles(self):
        from pint_tpu.templates.lcenorm import ENormAngles

        en = ENormAngles([0.4, 0.3], slopes=[0.05, -0.05], e0_mev=1000.0)
        n0 = en(3.0)
        assert np.allclose(n0, [0.4, 0.3], atol=1e-12)
        n1 = en(np.array([4.0]))[0]
        assert not np.allclose(n1, n0)
        assert n1.sum() <= 1.0

    def test_energy_dependent_template(self):
        from pint_tpu.templates.lcenorm import ENormAngles
        from pint_tpu.templates.lceprimitives import LCEGaussian
        from pint_tpu.templates.lctemplate import LCTemplate

        t = LCTemplate([LCEGaussian(p=[0.03, 0.25], slopes=[0.0, 0.2])],
                       ENormAngles([0.6], slopes=[0.0]))
        assert t.is_energy_dependent()
        grid = np.linspace(0, 1, 100, endpoint=False)
        lo = t(grid, log10_ens=np.full(100, 3.0))
        hi = t(grid, log10_ens=np.full(100, 4.0))
        assert np.argmax(lo) != np.argmax(hi)  # peak moved with energy
        # energy-independent call still works
        assert np.all(np.isfinite(t(grid)))


class TestMCMCResume:
    def _gauss_lnpost(self):
        def lnpost(pts):
            pts = np.asarray(pts)
            return -0.5 * np.sum(pts**2, axis=-1)

        lnpost.batched = True
        return lnpost

    def test_kill_and_resume_bit_identical(self, tmp_path):
        """A checkpointed run killed at step 30 and resumed for 20 more must
        reproduce the uninterrupted 50-step chain exactly (RNG state is part
        of the checkpoint)."""
        from pint_tpu.sampler import EnsembleSampler

        path = str(tmp_path / "chain.npz")
        ref = EnsembleSampler(16, seed=42)
        ref.initialize_batched(self._gauss_lnpost(), 3)
        rng = np.random.default_rng(0)
        pos0 = rng.standard_normal((16, 3))
        ref.run_mcmc(pos0.copy(), 50)
        full = ref.get_chain()

        s1 = EnsembleSampler(16, seed=42, backend=path, checkpoint_every=10)
        s1.initialize_batched(self._gauss_lnpost(), 3)
        s1.run_mcmc(pos0.copy(), 30)
        del s1  # "crash"

        s2 = EnsembleSampler(16, seed=999, backend=path)  # seed is overridden
        s2.initialize_batched(self._gauss_lnpost(), 3)
        pos = s2.resume()
        assert len(s2._chain) == 30
        s2.run_mcmc(pos, 20)
        resumed = s2.get_chain()
        assert resumed.shape == full.shape == (50, 16, 3)
        assert np.array_equal(resumed, full)

    def test_photon_fitter_resume(self, tmp_path):
        """End-to-end through the photon MCMC fitter: checkpoint, kill,
        resume with the total step budget."""
        from pint_tpu.event_fitter import MCMCFitterBinnedTemplate
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform
        from pint_tpu.templates import make_twoside_gaussian

        par = ["PSR P\n", "RAJ 05:00:00\n", "DECJ 10:00:00\n",
               "F0 29.946923 1\n", "PEPOCH 55555\n", "UNITS TDB\n"]
        m = get_model(par)
        t = make_fake_toas_uniform(55500, 55600, 60, m, error_us=100.0,
                                   obs="bat", rng=np.random.default_rng(2))
        tmpl = make_twoside_gaussian(0.5, 0.05, 0.05, 0.8)
        path = str(tmp_path / "ck.npz")
        f1 = MCMCFitterBinnedTemplate(t, m, tmpl, nbins=64, nwalkers=8,
                                      backend=path, seed=7)
        f1.sampler.checkpoint_every = 5
        f1.fit_toas(maxiter=15, seed=7, burn_frac=0.2)
        m2 = get_model(par)
        f2 = MCMCFitterBinnedTemplate(t, m2, tmpl, nbins=64, nwalkers=8,
                                      backend=path, seed=7)
        f2.fit_toas(maxiter=40, resume=True, burn_frac=0.2)
        assert len(f2.sampler._chain) == 40


class TestEventToasHelpers:
    def test_timesys_timeref_checks(self):
        from pint_tpu.event_toas import check_timeref, check_timesys

        check_timesys("TT")
        check_timesys("TDB")
        with pytest.raises(ValueError):
            check_timesys("UTC")
        check_timeref("LOCAL")
        with pytest.raises(ValueError):
            check_timeref("TOPOCENTER")

    def test_mission_config(self, monkeypatch, tmp_path):
        from pint_tpu.event_toas import (create_mission_config,
                                         read_mission_info_from_heasoft)

        monkeypatch.delenv("HEADAS", raising=False)
        assert read_mission_info_from_heasoft() == {}
        cfg = create_mission_config()
        assert "nicer" in cfg and cfg["nicer"]["ecol"] == "PI"
        # a fake HEASOFT mdb adds a mission
        (tmp_path / "bin").mkdir()
        (tmp_path / "bin" / "xselect.mdb").write_text(
            "mymission:events MYEVENTS\nmymission:ecol PHA2\n!comment\n")
        monkeypatch.setenv("HEADAS", str(tmp_path))
        cfg2 = create_mission_config()
        assert cfg2["mymission"]["fits_extension"] == "MYEVENTS"
        assert cfg2["mymission"]["ecol"] == "PHA2"


class TestPlotPriors:
    def test_figure_renders(self, tmp_path):
        from pint_tpu.models import get_model
        from pint_tpu.bayesian import apply_prior_info
        from pint_tpu.plot_utils import plot_priors

        m = get_model(["PSR PLT\n", "RAJ 03:00:00\n", "DECJ 3:00:00\n",
                       "F0 99.0 1\n", "PEPOCH 55100\n", "DM 10\n",
                       "UNITS TDB\n"])
        apply_prior_info(m, {"F0": {"distr": "uniform", "pmin": 98.9,
                                    "pmax": 99.1}})
        rng = np.random.default_rng(0)
        chains = {"F0": 99.0 + 1e-3 * rng.standard_normal((300, 8))}
        out = tmp_path / "priors.png"
        fig = plot_priors(m, chains, maxpost_fitvals=[99.0], fitvals=[99.0],
                          burnin=50, plotfile=str(out))
        assert out.exists() and out.stat().st_size > 0

    def test_heasoft_mission_wired_into_loader(self, monkeypatch, tmp_path):
        """create_mission_config feeds load_fits_TOAs: an xselect.mdb
        mission resolves its extension/energy column."""
        from pint_tpu.event_toas import load_fits_TOAs

        (tmp_path / "bin").mkdir()
        (tmp_path / "bin" / "xselect.mdb").write_text(
            "nicer:ecol PHA9\n")
        monkeypatch.setenv("HEADAS", str(tmp_path))
        # the config override is visible even before touching a file
        from pint_tpu.event_toas import create_mission_config

        assert create_mission_config()["nicer"]["ecol"] == "PHA9"


class TestPlotPriorsGuards:
    def test_burnin_too_large(self):
        from pint_tpu.bayesian import apply_prior_info
        from pint_tpu.models import get_model
        from pint_tpu.plot_utils import plot_priors

        m = get_model(["PSR PLT2\n", "RAJ 03:00:00\n", "DECJ 3:00:00\n",
                       "F0 99.0 1\n", "PEPOCH 55100\n", "DM 10\n",
                       "UNITS TDB\n"])
        apply_prior_info(m, {"F0": {"distr": "uniform", "pmin": 98.9,
                                    "pmax": 99.1}})
        chains = {"F0": np.full((50, 4), 99.0)}
        with pytest.raises(ValueError, match="burnin"):
            plot_priors(m, chains, burnin=50)
