"""PTA catalog-engine tests (PR 11).

Pins the load-bearing contracts of ``pint_tpu/catalog``:

* **ingestion gate** — every catalog member passes the
  validate/quarantine gate; corrupt rows never reach a fit and an
  unconstrainable pulsar is excluded with a reason;
* **learned buckets** — shape ladders learned from the catalog's own
  distribution, padding waste bounded, compile budget respected;
* **batched == dedicated** — a >= 16-pulsar ragged catalog fit as one
  vmapped batched program per bucket matches per-pulsar dedicated
  :class:`~pint_tpu.gls_fitter.GLSFitter` fits (parameter values to
  1e-9 relative; steps match the dedicated-shape solve to 1e-9 —
  padding exact by construction), with zero steady-state recompiles
  across buckets after warmup;
* **Hellings-Downs** — analytic curve values pinned at known angular
  separations; the joint lnlikelihood factorizes into the sum of
  per-pulsar lnlikelihoods at zero cross-correlation amplitude, and
  matches a dense-covariance numpy oracle at nonzero amplitude;
* **plans** — the ``catalog`` workload routes over the ``pulsar`` mesh
  axis, and the jitted joint lnlikelihood is sampler-consumable under
  a 2-axis ``(pulsar, walker)`` data-parallel plan.
"""

import copy
import json
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.catalog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pint_tpu.catalog import (  # noqa: E402
    CatalogFitter,
    JointLikelihood,
    angular_separations,
    assign_buckets,
    hd_cholesky,
    hd_curve,
    hd_matrix,
    ingest_catalog,
    learn_ladders,
    make_synthetic_catalog,
    pulsar_directions,
)
from pint_tpu.exceptions import UsageError  # noqa: E402

#: the acceptance catalog: >= 16 pulsars, ragged TOA counts, two
#: members carrying one corrupt row each (quarantine-gate coverage)
N_PULSARS = 16
BAD_MEMBERS = (3, 11)


@pytest.fixture(scope="module")
def catalog16():
    """Ingested 16-pulsar ragged synthetic catalog (module-scoped: the
    host model building dominates this suite's wall time)."""
    pairs = make_synthetic_catalog(n_pulsars=N_PULSARS, seed=7,
                                   ntoa_range=(24, 64),
                                   bad_rows_in=BAD_MEMBERS)
    return ingest_catalog(pairs)


@pytest.fixture(scope="module")
def fitted(catalog16):
    """(CatalogFitter, CatalogFitResult, dedicated GLSFitter fits) —
    the batched fit next to its per-pulsar dedicated twins, computed
    once (each dedicated fit deep-copies the pristine ingest model, so
    both sides start from the identical state)."""
    from pint_tpu.gls_fitter import GLSFitter

    cf = CatalogFitter(catalog16)
    res = cf.fit(maxiter=1)
    dedicated = []
    for p in catalog16.pulsars:
        f = GLSFitter(p.toas, p.model)
        chi2 = f.fit_toas(maxiter=1)
        dedicated.append((f, chi2))
    return cf, res, dedicated


@pytest.fixture
def basic_telemetry():
    from pint_tpu import telemetry

    telemetry.activate("basic")
    yield telemetry
    telemetry.deactivate()


# ---------------------------------------------------------------------------
# Hellings-Downs geometry
# ---------------------------------------------------------------------------

class TestHellingsDowns:
    def test_analytic_pins(self):
        """Curve values at known separations (3/2 x ln x - x/4 + 1/2,
        x = (1-cos g)/2), pinned to 1e-12."""
        pins = {
            np.pi / 3: -0.08236038541995894,
            np.pi / 2: -0.14486038541995894,
            2 * np.pi / 3: -0.011142331508253611,
            np.pi: 0.25,
        }
        for gamma, want in pins.items():
            assert abs(hd_curve(gamma) - want) < 1e-12
        # coincidence limit: x ln x -> 0, distinct-pulsar value 1/2
        assert abs(hd_curve(0.0) - 0.5) < 1e-12
        assert abs(hd_curve(1e-12) - 0.5) < 1e-9

    def test_array_in_array_out(self):
        g = np.array([np.pi / 2, np.pi])
        out = hd_curve(g)
        assert out.shape == (2,)
        assert abs(out[0] - -0.14486038541995894) < 1e-12
        assert abs(out[1] - 0.25) < 1e-12

    def test_matrix_symmetric_unit_diagonal_pd(self, catalog16):
        dirs = pulsar_directions([p.model for p in catalog16.pulsars])
        orf = hd_matrix(dirs)
        assert orf.shape == (len(dirs), len(dirs))
        np.testing.assert_allclose(orf, orf.T, atol=0)
        np.testing.assert_allclose(np.diag(orf), 1.0, atol=0)
        assert np.linalg.eigvalsh(orf).min() > 0
        L = hd_cholesky(dirs)
        np.testing.assert_allclose(L @ L.T, orf, atol=1e-12)

    def test_separations_reject_non_unit_vectors(self):
        with pytest.raises(UsageError):
            angular_separations(np.array([[2.0, 0.0, 0.0],
                                          [0.0, 1.0, 0.0]]))
        with pytest.raises(UsageError):
            angular_separations(np.zeros((3, 2)))


# ---------------------------------------------------------------------------
# learned shape ladders + bucket assignment
# ---------------------------------------------------------------------------

class TestLadders:
    def test_learned_ladder_covers_and_bounds_waste(self):
        shapes = [(24, 8), (30, 8), (61, 10), (64, 10), (40, 9)]
        ntoa, nfree = learn_ladders(shapes, pad_budget=0.25, max_rungs=4)
        assert max(n for n, _ in shapes) in ntoa
        assert max(k for _, k in shapes) in nfree
        assert len(ntoa) <= 4 and len(nfree) <= 4
        # every shape fits under a rung within the budget (no doubling
        # was needed at this spread)
        from pint_tpu.serving.batcher import bucket_of

        for n, _ in shapes:
            b = bucket_of(n, ntoa)
            assert (b - n) / b <= 0.25 + 1e-12

    def test_compile_budget_wins_over_waste(self):
        """A wild spread at max_rungs=1 collapses to one rung (the
        budget doubles until the compile budget is met)."""
        shapes = [(10, 4), (100, 4), (1000, 4)]
        ntoa, _ = learn_ladders(shapes, pad_budget=0.1, max_rungs=1)
        assert ntoa == (1000,)

    def test_assignment_membership_and_waste(self):
        shapes = [(24, 8), (64, 10), (63, 10)]
        plan = assign_buckets(shapes, (24, 64), (10,), emit=False)
        assert plan.n_buckets == 2
        assert sorted(i for idx in plan.buckets.values()
                      for i in idx) == [0, 1, 2]
        assert 0.0 <= plan.pad_waste_frac < 1.0
        assert plan.bucket_of_index(0) == (24, 10)
        assert plan.bucket_of_index(1) == (64, 10)

    def test_oversize_shape_doubles_past_the_top(self):
        plan = assign_buckets([(200, 4)], (64,), (8,), emit=False)
        assert list(plan.buckets) == [(256, 8)]

    def test_usage_errors(self):
        with pytest.raises(UsageError):
            learn_ladders([])
        with pytest.raises(UsageError):
            learn_ladders([(0, 4)])
        with pytest.raises(UsageError):
            learn_ladders([(10, 4)], pad_budget=1.5)
        with pytest.raises(UsageError):
            assign_buckets([], (64,), (8,))


# ---------------------------------------------------------------------------
# ingestion gate
# ---------------------------------------------------------------------------

class TestIngest:
    def test_bad_rows_quarantined(self, catalog16):
        assert catalog16.n_pulsars == N_PULSARS
        assert catalog16.n_quarantined == len(BAD_MEMBERS)
        quarantined = [p for p in catalog16.pulsars
                       if p.n_quarantined > 0]
        assert len(quarantined) == len(BAD_MEMBERS)
        for p in quarantined:
            assert "toa-bad-error" in p.quarantine_codes

    def test_unconstrainable_pulsar_excluded(self):
        pairs = make_synthetic_catalog(n_pulsars=2, seed=5,
                                       ntoa_range=(24, 32))
        # corrupt every row of the second member: zero certified TOAs
        pairs[1][1].error_us[:] = 0.0
        report = ingest_catalog(pairs)
        assert report.n_pulsars == 1
        assert len(report.excluded) == 1
        assert "cannot constrain" in report.excluded[0][1]

    def test_all_excluded_raises_typed(self):
        pairs = make_synthetic_catalog(n_pulsars=1, seed=5,
                                       ntoa_range=(24, 32))
        pairs[0][1].error_us[:] = 0.0
        with pytest.raises(UsageError):
            ingest_catalog(pairs)

    def test_malformed_entry_raises_typed(self):
        with pytest.raises(UsageError):
            ingest_catalog([("only-one-element",)])
        with pytest.raises(UsageError):
            ingest_catalog([])


# ---------------------------------------------------------------------------
# batched fit == dedicated fits (the acceptance pin)
# ---------------------------------------------------------------------------

class TestBatchedParity:
    def test_one_batched_program_per_bucket(self, fitted):
        cf, res, _ = fitted
        assert res.n_pulsars == N_PULSARS
        assert res.n_buckets == cf.bucket_plan.n_buckets
        assert res.n_buckets < N_PULSARS  # batching actually batched
        assert 0.0 <= res.pad_waste_frac < 0.5

    def test_parameters_match_dedicated_to_1e9(self, fitted, catalog16):
        """Parameter values to 1e-9 relative, uncertainties to 1e-6,
        and applied steps to 2e-6 of the natural (error) scale against
        the dedicated Schur-path fit — different f64 factorization
        algebra, same augmented system."""
        _, res, dedicated = fitted
        for p, (ded, _) in zip(catalog16.pulsars, dedicated):
            for name in p.model.free_params:
                base = float(getattr(p.model, name).value or 0.0)
                a = float(getattr(ded.model, name).value)
                b = float(getattr(p.fitted_model, name).value)
                ua = float(getattr(ded.model, name).uncertainty)
                ub = float(getattr(p.fitted_model, name).uncertainty)
                assert abs(a - b) <= 1e-9 * max(abs(a), abs(b)), \
                    (p.name, name, a, b)
                assert abs(a - b) <= 2e-6 * max(abs(a - base), ua), \
                    (p.name, name, a - base, b - base)
                assert abs(ua - ub) <= 1e-6 * ua, (p.name, name, ua, ub)

    def test_steps_match_dedicated_shape_solve_to_1e9(self, catalog16,
                                                      fitted):
        """Padding exactness, promoted from PR 8: each pulsar's batched
        padded step equals the dedicated-shape serve solve of the SAME
        linearized system to 1e-9 (identical kernel, unpadded shape)."""
        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.serving.batcher import FitRequest, ShapeBatcher

        _, res, _ = fitted
        by_name = res.by_name()
        for p in catalog16.pulsars:
            f = GLSFitter(p.toas, p.model)  # pristine state
            req = FitRequest.from_fitter(f)
            rd = ShapeBatcher(ntoa_buckets=(req.n_toas,),
                              nfree_buckets=(req.n_free,)).run([req])[0]
            want = rd.dpars(req)
            norm = req.norm if req.norm is not None \
                else np.ones(req.n_free)
            got = by_name[p.name].dpars
            for j, name in enumerate(req.params):
                err = float(rd.errors[j] / norm[j])
                dv = want[name]
                assert abs(got[name] - dv) <= \
                    1e-9 * max(abs(dv), err), \
                    (p.name, name, got[name], dv, err)

    def test_chi2_matches_dedicated(self, fitted, catalog16):
        _, res, dedicated = fitted
        for pf, (_, chi2) in zip(res.fits, dedicated):
            assert abs(pf.chi2 - chi2) <= 1e-7 * max(1.0, chi2), \
                (pf.name, pf.chi2, chi2)
        assert np.isfinite(res.chi2_total)

    def test_quarantined_members_fit_on_certified_rows(self, fitted):
        _, res, _ = fitted
        q = [f for f in res.fits if f.n_quarantined > 0]
        assert len(q) == len(BAD_MEMBERS)
        for f in q:
            assert np.isfinite(f.chi2)

    def test_zero_steady_state_recompiles(self, basic_telemetry):
        """After a warmup pass, repeat catalog fits dispatch every
        bucket with compiles == 0 (fresh bucket shapes so the first
        pass genuinely compiles)."""
        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=4, seed=13, ntoa_range=(70, 90)))
        cf = CatalogFitter(report)
        first = cf.fit(maxiter=1)
        assert first.compiles > 0
        for _ in range(2):
            again = cf.fit(maxiter=1)
            assert again.compiles == 0

    def test_warm_pool_path_zero_compiles(self, basic_telemetry):
        """warm_catalog pre-compiles every bucket executable into a
        WarmPool; the first real fit then dispatches the held handles
        with zero fresh compiles."""
        from pint_tpu.serving import warm_catalog

        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=4, seed=17, ntoa_range=(91, 120)))
        cf = CatalogFitter(report)
        pool, warm_report = warm_catalog(cf)
        assert warm_report.cold_compiles >= 1
        res = cf.fit(maxiter=1)
        assert res.compiles == 0

    def test_nonfinite_member_raises_typed(self, basic_telemetry):
        from pint_tpu.exceptions import NonFiniteSystemError

        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=2, seed=23, ntoa_range=(24, 32)))
        cf = CatalogFitter(report)
        # poison one member's spin state after ingest: the NaN
        # propagates through its padded lane and the aggregate must
        # refuse, not hide the member
        report.pulsars[0].fitter.model.F0.value = float("nan")
        with pytest.raises(NonFiniteSystemError):
            cf.fit(maxiter=1)


# ---------------------------------------------------------------------------
# joint likelihood
# ---------------------------------------------------------------------------

class TestJointLikelihood:
    def test_factorizes_at_zero_amplitude(self, fitted):
        """The acceptance pin: joint lnlike with the cross-correlation
        amplitude exactly zero == sum of per-pulsar lnlikelihoods (the
        shared per-pulsar block without any cross machinery — the pin
        proves the cross term vanishes identically; the block's own
        formulas are pinned by the dense kernel oracle)."""
        cf, _, _ = fitted
        jl = JointLikelihood(cf, n_modes=3)
        joint0 = jl.lnlike_nocommon()
        parts = jl.per_pulsar_lnlike()
        assert parts.shape == (N_PULSARS,)
        assert abs(joint0 - parts.sum()) <= 1e-9 * abs(parts.sum())

    def test_amplitude_moves_the_likelihood(self, fitted):
        cf, _, _ = fitted
        jl = JointLikelihood(cf, n_modes=3)
        l0 = jl.lnlike_nocommon()
        l1 = jl.lnlike(-13.0, 13.0 / 3.0)
        assert np.isfinite(l1) and l1 != l0

    def test_batch_shape_and_validation(self, fitted):
        cf, _, _ = fitted
        jl = JointLikelihood(cf, n_modes=3)
        pts = np.column_stack([np.linspace(-16, -13, 5),
                               np.full(5, 4.33)])
        out = jl.lnlike_batch(pts)
        assert out.shape == (5,)
        assert np.all(np.isfinite(out))
        with pytest.raises(UsageError):
            jl.lnlike_batch(np.zeros((3, 4)))

    def test_needs_two_pulsars(self, catalog16):
        with pytest.raises(UsageError):
            JointLikelihood(catalog16.pulsars[:1])

    def test_kernel_matches_dense_oracle(self):
        """The block-Woodbury joint kernel == the dense
        stacked-covariance numpy evaluation, on WELL-CONDITIONED
        synthetic operands (moderate priors — the enterprise 1e40
        timing-prior convention pushes cond(P) past 1e20, where a
        dense slogdet/solve is itself meaningless; the Woodbury form
        exists precisely to avoid that regime).  Includes a padded
        member: zero-weight pad rows and a unit-pad-diagonal column
        must contribute exactly nothing."""
        import jax.numpy as jnp
        from scipy.linalg import block_diag

        from pint_tpu.catalog.likelihood import FYR_HZ, _joint_kernel

        rng = np.random.default_rng(2)
        n_p, n, k, m = 3, 12, 3, 2
        M = rng.normal(size=(n_p, n, k))
        r = rng.normal(size=(n_p, n))
        w = rng.uniform(0.5, 2.0, size=(n_p, n))
        phiinv = rng.uniform(0.5, 2.0, size=(n_p, k))
        pad = np.zeros((n_p, k))
        F = rng.normal(size=(n_p, n, 2 * m))
        # member 2 is padded: last column + last two rows are padding
        M[2, :, 2] = 0.0
        phiinv[2, 2] = 0.0
        pad[2, 2] = 1.0
        M[2, -2:, :] = 0.0
        F[2, -2:, :] = 0.0
        r[2, -2:] = 0.0
        w[2, -2:] = 0.0
        dirs = rng.normal(size=(n_p, 3))
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        Lhd = hd_cholesky(dirs)
        freqs = np.array([1.0e-8, 2.0e-8])
        Tspan = 1.0e8
        gamma = 3.0                      # fyr^(gamma-3) == 1
        amp = 1.0e-7                     # phi ~ O(1): same scale as P
        val = float(_joint_kernel(
            amp, gamma, jnp.asarray(M), jnp.asarray(r), jnp.asarray(w),
            jnp.asarray(phiinv), jnp.asarray(pad), jnp.asarray(F),
            jnp.asarray(Lhd), jnp.asarray(freqs), Tspan,
            float(np.log(2 * np.pi))))
        # dense oracle over the UNPADDED slices
        reals = [(M[0], r[0], w[0], phiinv[0], F[0]),
                 (M[1], r[1], w[1], phiinv[1], F[1]),
                 (M[2, :-2, :2], r[2, :-2], w[2, :-2], phiinv[2, :2],
                  F[2, :-2])]
        blocks, Fs, rs = [], [], []
        for Ma, ra, wa, pa, Fa in reals:
            blocks.append(np.diag(1.0 / wa)
                          + Ma @ np.diag(1.0 / pa) @ Ma.T)
            rs.append(ra)
            Fs.append(Fa)
        phi = (amp**2 / (12 * np.pi**2) * FYR_HZ**(gamma - 3.0)
               * freqs**(-gamma) / Tspan)
        C = block_diag(*blocks) + block_diag(*Fs) @ np.kron(
            hd_matrix(dirs), np.diag(np.repeat(phi, 2))
        ) @ block_diag(*Fs).T
        rr = np.concatenate(rs)
        _, lndet = np.linalg.slogdet(C)
        oracle = -0.5 * (rr @ np.linalg.solve(C, rr) + lndet
                         + len(rr) * np.log(2 * np.pi))
        assert abs(val - oracle) <= 1e-9 * max(1.0, abs(oracle)), \
            (val, oracle)


# ---------------------------------------------------------------------------
# execution plans: the pulsar axis
# ---------------------------------------------------------------------------

class TestCatalogPlans:
    def test_select_plan_catalog_workload(self, eight_devices):
        from pint_tpu.runtime.plan import select_plan

        plan = select_plan("catalog", devices=eight_devices)
        assert plan.axes[0] == "pulsar"
        assert plan.kind == "pjit"
        assert plan.rung == 8

    def test_planned_fit_matches_unplanned(self, eight_devices):
        from pint_tpu.runtime.plan import select_plan

        pairs = make_synthetic_catalog(n_pulsars=8, seed=31,
                                       ntoa_range=(24, 48))
        plain = CatalogFitter(ingest_catalog(copy.deepcopy(pairs)))
        res_plain = plain.fit(maxiter=1)
        plan = select_plan("catalog", devices=eight_devices, n_items=8)
        routed = CatalogFitter(ingest_catalog(pairs), plan=plan)
        res_routed = routed.fit(maxiter=1)
        for a, b in zip(res_plain.fits, res_routed.fits):
            assert a.name == b.name
            assert abs(a.chi2 - b.chi2) <= 1e-9 * max(1.0, a.chi2)
            for name, dv in a.dpars.items():
                assert abs(b.dpars[name] - dv) <= \
                    1e-9 * max(abs(dv), 1e-30) + 1e-18, (name, dv)

    def test_two_axis_plan_shards_pulsar_and_walker(self, eight_devices,
                                                    fitted):
        """The acceptance pin: the joint lnlikelihood under a 2-axis
        (pulsar, walker) data-parallel plan matches the unsharded
        evaluation to 1e-9."""
        from pint_tpu.runtime.plan import select_plan

        cf, _, _ = fitted
        plan = select_plan("catalog", devices=eight_devices,
                           axes=("pulsar", "walker"))
        assert plan.mesh is not None
        assert dict(plan.mesh.shape) == {"pulsar": 2, "walker": 4}
        jl_plain = JointLikelihood(cf, n_modes=3)
        jl_routed = JointLikelihood(cf, n_modes=3, plan=plan)
        pts = np.column_stack([np.linspace(-16, -13, 8),
                               np.full(8, 4.33)])
        a = jl_plain.lnlike_batch(pts)
        b = jl_routed.lnlike_batch(pts)
        np.testing.assert_allclose(b, a, rtol=1e-9)

    def test_non_divisible_catalog_pads_the_pulsar_axis(
            self, eight_devices):
        """A catalog whose pulsar count does not divide the mesh's
        pulsar-axis size (the NORMAL outcome of an integrity-gate
        exclusion) pads with all-padding pulsars — lnlike identical to
        the unsharded evaluation, never a device_put shape error."""
        from pint_tpu.runtime.plan import select_plan

        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=3, seed=43, ntoa_range=(20, 28)))
        plan = select_plan("catalog", devices=eight_devices[:4],
                           axes=("pulsar",))
        assert plan.mesh.shape["pulsar"] == 4  # 3 pulsars: not divisible
        jl_plain = JointLikelihood(report.pulsars, n_modes=2)
        jl_routed = JointLikelihood(report.pulsars, n_modes=2,
                                    plan=plan)
        pts = np.column_stack([np.linspace(-15, -13, 4),
                               np.full(4, 4.0)])
        np.testing.assert_allclose(jl_routed.lnlike_batch(pts),
                                   jl_plain.lnlike_batch(pts),
                                   rtol=1e-9)
        assert jl_routed.per_pulsar_lnlike().shape == (3,)

    def test_sampler_consumes_joint_lnlike(self, eight_devices, fitted):
        """EnsembleSampler drives the jitted joint lnlikelihood under
        the (pulsar, walker) plan: a short chain runs, finite
        throughout, with some acceptance."""
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.sampler import EnsembleSampler

        cf, _, _ = fitted
        plan = select_plan("catalog", devices=eight_devices,
                           axes=("pulsar", "walker"))
        jl = JointLikelihood(cf, n_modes=3, plan=plan)
        sampler = EnsembleSampler(nwalkers=8, seed=42)
        sampler.initialize_batched(jl.lnlike_batch, 2)
        rng = np.random.default_rng(1)
        pos = np.column_stack([
            -14.0 + 0.3 * rng.standard_normal(8),
            13.0 / 3.0 + 0.2 * rng.standard_normal(8)])
        sampler.run_mcmc(pos, 3)
        chain = np.asarray(sampler._chain)
        assert chain.shape == (3, 8, 2)
        assert np.all(np.isfinite(np.asarray(sampler._lnprob)))

    def test_wrong_axis_plan_rejected(self, eight_devices):
        from pint_tpu.runtime.plan import select_plan

        plan = select_plan("grid", devices=eight_devices)
        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=2, seed=37, ntoa_range=(20, 28)))
        with pytest.raises(UsageError):
            CatalogFitter(report, plan=plan)
        with pytest.raises(UsageError):
            JointLikelihood(report.pulsars, plan=plan)


# ---------------------------------------------------------------------------
# autotuner: catalog ladder decisions
# ---------------------------------------------------------------------------

class TestCatalogAutotune:
    def test_tune_records_scored_or_excluded_candidates(self, catalog16):
        from pint_tpu import autotune

        shapes = [p.shape() for p in catalog16.pulsars]
        dec = autotune.tune_catalog_ladders(shapes)
        assert dec.name == "catalog.buckets"
        assert dec.basis in ("cost", "static")
        for c in dec.candidates:
            assert c.get("predicted_s") is not None or c.get("excluded")
        # the winning ladders must cover the catalog
        from pint_tpu.serving.batcher import bucket_of

        for n, k in shapes:
            assert bucket_of(n, dec.value["ntoa"]) >= n
            assert bucket_of(k, dec.value["nfree"]) >= k

    def test_resolve_round_trip_through_manifest(self, catalog16,
                                                 tmp_path):
        from pint_tpu import autotune, config

        shapes = [p.shape() for p in catalog16.pulsars]
        config.set_tune_dir(str(tmp_path))
        try:
            autotune.reset_manifest_singleton()
            m = autotune.manifest()
            autotune.tune_catalog_ladders(shapes, tuning_manifest=m)
            tuned = autotune.resolve_catalog_ladders(shapes)
            assert tuned is not None
            assert tuned["ntoa"] and tuned["nfree"]
            # a different shape distribution misses (vkey discipline)
            assert autotune.resolve_catalog_ladders(
                [(999, 99)]) is None
        finally:
            config.set_tune_dir(None)
            autotune.reset_manifest_singleton()

    def test_resolve_none_when_tuning_off(self):
        from pint_tpu import autotune, config

        assert config.tune_dir() is None
        assert autotune.resolve_catalog_ladders([(30, 8)]) is None


# ---------------------------------------------------------------------------
# telemetry events
# ---------------------------------------------------------------------------

class TestCatalogEvents:
    def _validate(self, tmp_path, name, **attrs):
        from tools.telemetry_report import validate_catalog_event

        errors = []
        validate_catalog_event({"name": name, "attrs": attrs},
                               "test", errors)
        return errors

    def test_valid_events_pass(self, tmp_path):
        assert not self._validate(tmp_path, "catalog_ingest",
                                  n_pulsars=16, n_toas=600,
                                  n_quarantined=2,
                                  quarantined_pulsars=0)
        assert not self._validate(tmp_path, "catalog_bucket",
                                  n_pulsars=16, n_buckets=3,
                                  pad_waste_frac=0.04,
                                  ntoa_ladder="24,64",
                                  nfree_ladder="10")

    def test_malformed_events_rejected(self, tmp_path):
        assert self._validate(tmp_path, "catalog_ingest",
                              n_pulsars=0, n_toas=600,
                              n_quarantined=0, quarantined_pulsars=0)
        assert self._validate(tmp_path, "catalog_ingest",
                              n_pulsars=16, n_toas=600,
                              n_quarantined=-1, quarantined_pulsars=0)
        assert self._validate(tmp_path, "catalog_bucket",
                              n_pulsars=16, n_buckets=0,
                              pad_waste_frac=0.04,
                              ntoa_ladder="24", nfree_ladder="10")
        assert self._validate(tmp_path, "catalog_bucket",
                              n_pulsars=16, n_buckets=2,
                              pad_waste_frac=1.5,
                              ntoa_ladder="24", nfree_ladder="10")
        assert self._validate(tmp_path, "catalog_bucket",
                              n_pulsars=16, n_buckets=2,
                              pad_waste_frac="lots",
                              ntoa_ladder="24", nfree_ladder="10")

    def test_full_mode_events_validate_end_to_end(self, tmp_path,
                                                  monkeypatch):
        """A real ingest + bucket assignment in full telemetry mode
        writes catalog_ingest/catalog_bucket records that
        telemetry_report --check accepts."""
        from pint_tpu import config, telemetry
        from pint_tpu.telemetry import runlog
        from tools.telemetry_report import validate_events_file

        monkeypatch.setenv("PINT_TPU_TELEMETRY_DIR", str(tmp_path))
        telemetry.activate("full")
        try:
            pairs = make_synthetic_catalog(n_pulsars=2, seed=41,
                                           ntoa_range=(20, 28),
                                           bad_rows_in=[0])
            report = ingest_catalog(pairs)
            CatalogFitter(report)
            run_dir = runlog.ensure_run().path
        finally:
            telemetry.deactivate()
        errors = []
        n = validate_events_file(os.path.join(run_dir, "events.jsonl"),
                                 errors)
        assert not errors, errors
        body = open(os.path.join(run_dir, "events.jsonl")).read()
        assert "catalog_ingest" in body
        assert "catalog_bucket" in body
        assert n >= 2
        assert config.telemetry_mode() == "off"
