"""Extended light-curve primitive set (reference ``templates/lcprimitives.py``
long tail: two-sided shapes, King, Harmonic, empirical Fourier/KDE profiles,
primitive conversion, gradient checks)."""

import numpy as np
import pytest

from pint_tpu.templates.lcprimitives import (LCEmpiricalFourier, LCGaussian,
                                             LCGaussian2, LCHarmonic,
                                             LCKernelDensity, LCKing,
                                             LCLorentzian, LCLorentzian2,
                                             LCTopHat, LCVonMises,
                                             approx_gradient, check_gradient,
                                             convert_primitive)
from pint_tpu.templates.lctemplate import LCTemplate

GRID = np.linspace(0.0, 1.0, 4001)


def _integral(prim):
    return float(np.trapezoid(np.asarray(prim(GRID)), GRID))


class TestNewPrimitives:
    @pytest.mark.parametrize("prim", [
        LCGaussian2([0.02, 0.05, 0.4]),
        LCLorentzian2([0.02, 0.05, 0.4]),
        LCKing([0.03, 5.0, 0.4]),
        LCHarmonic([0.3], order=2),
        LCGaussian([0.03, 0.5]),
        LCVonMises([0.03, 0.5]),
    ])
    def test_unit_integral(self, prim):
        assert _integral(prim) == pytest.approx(1.0, abs=5e-3)

    def test_two_sided_asymmetry(self):
        g2 = LCGaussian2([0.01, 0.05, 0.5])
        # right side falls slower than the left
        assert float(g2(np.array([0.55]))[0]) > float(g2(np.array([0.45]))[0])
        l2 = LCLorentzian2([0.01, 0.05, 0.5])
        assert float(l2(np.array([0.55]))[0]) > float(l2(np.array([0.45]))[0])
        # peak continuity: values just left/right of the mode agree
        eps = 1e-6
        lo, hi = g2(np.array([0.5 - eps]))[0], g2(np.array([0.5 + eps]))[0]
        assert float(lo) == pytest.approx(float(hi), rel=1e-3)

    def test_hwhm(self):
        g = LCGaussian([0.03, 0.5])
        assert g.hwhm() == pytest.approx(0.03 * np.sqrt(2 * np.log(2)))
        l = LCLorentzian([0.03, 0.5])
        # HWHM of the Lorentzian is gamma by definition
        peak = float(l(np.array([0.5]))[0])
        half = float(l(np.array([0.5 + l.hwhm()]))[0])
        assert half == pytest.approx(peak / 2, rel=5e-2)
        k = LCKing([0.03, 5.0, 0.5])
        peak = float(k(np.array([0.5]))[0])
        half = float(k(np.array([0.5 + k.hwhm()]))[0])
        assert half == pytest.approx(peak / 2, rel=5e-2)

    def test_harmonic_orthonormality(self):
        h = LCHarmonic([0.2], order=3)
        assert _integral(h) == pytest.approx(1.0, abs=1e-6)
        # peak at the location
        assert float(h(np.array([0.2]))[0]) == pytest.approx(3.0)

    def test_gradients_match_autodiff(self):
        for prim in (LCGaussian([0.04, 0.3]), LCGaussian2([0.03, 0.06, 0.3]),
                     LCLorentzian([0.04, 0.3]),
                     LCLorentzian2([0.03, 0.06, 0.3]),
                     LCVonMises([0.04, 0.3])):
            assert check_gradient(prim, n=50), type(prim).__name__

    def test_approx_gradient_shape(self):
        g = LCGaussian2([0.03, 0.06, 0.3])
        J = approx_gradient(g, np.linspace(0, 1, 17))
        assert J.shape == (3, 17)


class TestEmpiricalProfiles:
    def test_empirical_fourier_roundtrip(self, tmp_path):
        rng = np.random.default_rng(3)
        truth = LCGaussian([0.05, 0.6])
        phases = truth.random(20000, rng=rng)
        ef = LCEmpiricalFourier(phases=phases, nharm=16)
        assert _integral(ef) == pytest.approx(1.0, abs=1e-3)
        # reconstructed profile peaks near the truth peak
        assert abs(GRID[np.argmax(np.asarray(ef(GRID)))] - 0.6) < 0.02
        # file round trip
        f = tmp_path / "fourier.txt"
        ef.to_file(f)
        ef2 = LCEmpiricalFourier(input_file=str(f))
        assert np.allclose(ef2.alphas, ef.alphas)
        assert np.allclose(np.asarray(ef2(GRID)), np.asarray(ef(GRID)))
        # shift parameter rotates the profile
        ef.p[0] = 0.25
        assert abs((GRID[np.argmax(np.asarray(ef(GRID)))] - 0.85) % 1.0) < 0.02

    def test_kernel_density(self):
        rng = np.random.default_rng(5)
        truth = LCGaussian([0.04, 0.3])
        kde = LCKernelDensity(phases=truth.random(20000, rng=rng))
        assert _integral(kde) == pytest.approx(1.0, abs=5e-3)
        assert abs(GRID[np.argmax(np.asarray(kde(GRID)))] - 0.3) < 0.03
        # density tracks the truth to a few percent at the peak
        tr = np.asarray(truth(GRID))
        est = np.asarray(kde(GRID))
        assert np.max(np.abs(est - tr)) / np.max(tr) < 0.15


class TestConvertPrimitive:
    def test_location_and_hwhm_preserved(self):
        g = LCGaussian([0.03, 0.4])
        l = convert_primitive(g, LCLorentzian)
        assert isinstance(l, LCLorentzian)
        assert l.get_location() == pytest.approx(0.4)
        assert l.hwhm() == pytest.approx(g.hwhm(), rel=1e-12)
        g2 = convert_primitive(LCLorentzian2([0.02, 0.05, 0.4]), LCGaussian2)
        assert isinstance(g2, LCGaussian2)
        assert g2.hwhm(False) == pytest.approx(0.02 * 0 + LCLorentzian2(
            [0.02, 0.05, 0.4]).hwhm(False), rel=1e-12)
        back = convert_primitive(g2, LCGaussian)
        assert back.get_location() == pytest.approx(0.4)


class TestSampling:
    def test_primitive_random_matches_pdf(self):
        rng = np.random.default_rng(11)
        for prim in (LCGaussian([0.05, 0.5]), LCVonMises([0.05, 0.5]),
                     LCGaussian2([0.03, 0.08, 0.5]), LCTopHat([0.2, 0.5])):
            draws = prim.random(40000, rng=rng)
            assert ((draws >= 0) & (draws < 1)).all()
            hist, edges = np.histogram(draws, bins=50, range=(0, 1),
                                       density=True)
            centers = 0.5 * (edges[:-1] + edges[1:])
            pdf = np.asarray(prim(centers))
            # chi-like agreement: generous 10% of peak
            assert np.max(np.abs(hist - pdf)) < 0.12 * np.max(pdf), \
                type(prim).__name__

    def test_king_is_jit_and_grad_compatible(self):
        assert check_gradient(LCKing([0.03, 5.0, 0.4]), n=40)

    def test_convert_rejects_unsupported_targets(self):
        g = LCGaussian([0.03, 0.4])
        with pytest.raises(ValueError):
            convert_primitive(g, LCKing)
        with pytest.raises(ValueError):
            convert_primitive(g, LCHarmonic)

    def test_kde_bandwidth_reestimated_per_fit(self):
        rng = np.random.default_rng(17)
        kde = LCKernelDensity(phases=rng.random(5000))  # broad -> big bw
        broad_bw = kde.bw_used
        kde.from_phases(LCGaussian([0.01, 0.5]).random(5000, rng=rng))
        assert kde.bw_used < broad_bw / 3  # narrow data -> narrow bandwidth
        assert kde.bw is None  # auto mode preserved

    def test_harmonic_template_sampling_uses_rejection(self):
        rng = np.random.default_rng(19)
        t = LCTemplate([LCHarmonic([0.3], order=1)], [0.5])
        draws = t.random(40000, rng=rng)
        hist, edges = np.histogram(draws, bins=40, range=(0, 1), density=True)
        centers = 0.5 * (edges[:-1] + edges[1:])
        pdf = np.asarray(t(centers))
        assert (pdf >= 0).all()
        assert np.max(np.abs(hist - pdf)) < 0.1 * np.max(pdf)

    def test_template_multinomial_sampling(self):
        rng = np.random.default_rng(13)
        t = LCTemplate([LCGaussian([0.02, 0.25]), LCGaussian([0.04, 0.7])],
                       [0.35, 0.35])
        draws = t.random(60000, rng=rng)
        hist, edges = np.histogram(draws, bins=50, range=(0, 1), density=True)
        centers = 0.5 * (edges[:-1] + edges[1:])
        pdf = np.asarray(t(centers))
        assert np.max(np.abs(hist - pdf)) < 0.12 * np.max(pdf)
