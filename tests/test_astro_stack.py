"""Tests for the host astronomy stack: timescales, earth rotation, ephemerides,
observatories.  Validation is against independent closed-form facts (leap
seconds, equinox geometry, orbital invariants), not against the reference
implementation (which cannot run here)."""

import numpy as np
import pytest

from pint_tpu.earth import gcrs_posvel_from_itrf, itrf_to_gcrs_matrix
from pint_tpu.ephemeris import AnalyticEphemeris, _EPS_J2000, load_ephemeris
from pint_tpu.observatory import get_observatory, list_observatories
from pint_tpu.observatory.clock_file import ClockFile, read_tempo2_clock_file
from pint_tpu.timescales import (
    tai_minus_utc,
    tdb_minus_tt,
    utc_to_tdb_mjd,
    utc_to_tt_mjd,
)

AU_KM = 1.495978707e8


class TestTimescales:
    def test_leap_seconds_known_epochs(self):
        assert tai_minus_utc(41317.0)[0] == 10.0
        assert tai_minus_utc(50000.0)[0] == 29.0  # 1995
        assert tai_minus_utc(53750.0)[0] == 33.0  # 2006
        assert tai_minus_utc(58849.0)[0] == 37.0  # 2020
        assert tai_minus_utc(60000.0)[0] == 37.0  # no leaps since 2017

    def test_pre_1972_raises(self):
        with pytest.raises(ValueError):
            tai_minus_utc(41000.0)

    def test_tt_offset(self):
        tt = utc_to_tt_mjd(np.longdouble(53750.0))
        assert float((tt - np.longdouble(53750.0)) * 86400) == pytest.approx(65.184)

    def test_tdb_tt_bounded_and_annual(self):
        mjds = np.arange(50000.0, 60000.0, 10.0)
        d = tdb_minus_tt(mjds)
        assert np.all(np.abs(d) < 2e-3)  # amplitude ~1.7 ms
        assert np.max(d) > 1.2e-3 and np.min(d) < -1.2e-3

    def test_tdb_precision_longdouble(self):
        tdb = utc_to_tdb_mjd(np.longdouble("53478.2858714192189"))
        # longdouble carries ~1e-13 day precision through the conversion
        assert np.finfo(np.longdouble).eps < 2e-19
        assert abs(float(tdb) - 53478.2866) < 1e-3


class TestEarthRotation:
    def test_matrix_orthonormal(self):
        M = itrf_to_gcrs_matrix(np.array([53750.0, 58849.25]))
        for m in M:
            np.testing.assert_allclose(m @ m.T, np.eye(3), atol=1e-12)

    def test_sidereal_rotation_rate(self):
        # one sidereal day later the matrix should nearly repeat
        M0 = itrf_to_gcrs_matrix(np.array([53750.0]))[0]
        M1 = itrf_to_gcrs_matrix(np.array([53750.0 + 0.9972695663]))[0]
        np.testing.assert_allclose(M0, M1, atol=5e-5)

    def test_site_velocity_magnitude(self):
        # GBT latitude ~38.4 deg: v = omega * R * cos(lat) ~ 0.365 km/s
        itrf = [882589.289, -4924872.368, 3943729.418]
        pos, vel = gcrs_posvel_from_itrf(itrf, np.array([53750.0]))
        assert np.linalg.norm(pos) == pytest.approx(6.37e6, rel=0.01)
        assert np.linalg.norm(vel) == pytest.approx(365.0, rel=0.02)

    def test_precession_direction(self):
        # The mean pole of date, expressed in J2000 coordinates, drifts toward
        # +x at ~2004.3 arcsec/century (the date->J2000 matrix applied to
        # (0,0,1) must have a POSITIVE x component ~ theta).
        from pint_tpu.earth import _precession_matrix

        T = 0.24
        p = _precession_matrix(T) @ np.array([0.0, 0.0, 1.0])
        theta = 2004.3109 * T * np.pi / (180 * 3600)
        assert p[0] == pytest.approx(theta, rel=1e-3)
        assert abs(p[1]) < 1e-4

    def test_pole_stays_polar(self):
        # a vector along the ITRF z-axis maps near the celestial pole
        M = itrf_to_gcrs_matrix(np.array([55000.0]))[0]
        z = M @ np.array([0.0, 0.0, 1.0])
        assert z[2] > 0.99998


class TestAnalyticEphemeris:
    def setup_method(self):
        self.eph = AnalyticEphemeris()

    def test_earth_orbit_scale(self):
        mjd = np.arange(50000.0, 60000.0, 50.0)
        pos, vel = self.eph.posvel_ssb("earth", mjd)
        r = np.linalg.norm(pos, axis=-1) / AU_KM
        assert 0.975 < r.min() < 0.985
        assert 1.012 < r.max() < 1.022
        v = np.linalg.norm(vel, axis=-1)
        assert 29.0 < v.min() and v.max() < 30.8

    def test_equinox_solar_longitude(self):
        # 2020-03-20 03:50 UTC equinox: apparent solar lon (of date) == 0,
        # i.e. J2000 geometric lon == -precession(20.2 yr) ~ -0.2824 deg.
        ep, _ = self.eph.posvel_ssb("earth", [58928.1597])
        sp, _ = self.eph.posvel_ssb("sun", [58928.1597])
        v = (sp - ep)[0]
        c, s = np.cos(_EPS_J2000), np.sin(_EPS_J2000)
        lon = np.degrees(np.arctan2(c * v[1] + s * v[2], v[0])) % 360
        assert lon == pytest.approx(360.0 - 0.2824, abs=0.02)

    def test_velocity_consistent_with_finite_difference(self):
        mjd = np.array([55000.0])
        pos0, vel = self.eph.posvel_ssb("earth", mjd)
        dp = (self.eph.posvel_ssb("earth", mjd + 0.05)[0]
              - self.eph.posvel_ssb("earth", mjd - 0.05)[0]) / (0.1 * 86400.0)
        np.testing.assert_allclose(vel, dp, rtol=2e-3, atol=1e-4)

    def test_moon_distance_range(self):
        mjd = np.arange(53000.0, 54000.0, 1.0)
        em, _ = self.eph.posvel_ssb("earth", mjd)
        mm, _ = self.eph.posvel_ssb("moon", mjd)
        d = np.linalg.norm(mm - em, axis=-1)
        assert 354000 < d.min() < 372000
        assert 398000 < d.max() < 410000

    def test_ssb_is_mass_weighted_origin(self):
        # Sun offset from SSB is dominated by Jupiter: ~ 0.005 AU scale
        sp, _ = self.eph.posvel_ssb("sun", [55000.0])
        r = np.linalg.norm(sp) / AU_KM
        assert 0.001 < r < 0.012


class TestObservatories:
    def test_registry_and_aliases(self):
        gbt = get_observatory("gbt")
        assert get_observatory("1") is gbt  # tempo code
        assert get_observatory("GB") is gbt  # itoa code
        assert get_observatory("ao").name == "arecibo"
        assert get_observatory("@").name == "barycenter"
        assert get_observatory("coe").name == "geocenter"
        assert len(list_observatories()) > 100

    def test_site_posvel_near_earth(self):
        gbt = get_observatory("gbt")
        pv = gbt.posvel(np.array([53750.0]), np.array([53750.0]))
        ep, _ = AnalyticEphemeris().posvel_ssb("earth", [53750.0])
        assert np.linalg.norm(pv.pos - ep) < 7000.0  # within an Earth radius [km]

    def test_barycenter_zero(self):
        b = get_observatory("bat")
        pv = b.posvel([53750.0], [53750.0])
        assert np.all(pv.pos == 0)
        assert np.all(b.clock_corrections([53750.0]) == 0)

    def test_clock_file_tempo2_roundtrip(self, tmp_path):
        p = tmp_path / "test2gps.clk"
        p.write_text("# comment\nUTC(test) UTC\n50000.0 1.0e-6\n51000.0 3.0e-6\n")
        cf = read_tempo2_clock_file(str(p))
        assert cf.evaluate([50500.0])[0] == pytest.approx(2.0e-6)

    def test_clock_file_out_of_range_warns_not_raises(self, tmp_path):
        cf = ClockFile([50000.0, 51000.0], [1.0, 3.0], filename="x")
        cf.evaluate([52000.0], limits="warn")
        with pytest.raises(Exception):
            cf.evaluate([52000.0], limits="error")


class TestVSOPEarth:
    """The truncated-VSOP87 Earth series, validated against independent
    astronomical facts (equinox/perihelion almanac times)."""

    def setup_method(self):
        self.eph = AnalyticEphemeris()

    def test_equinox_2020_of_date_longitude(self):
        # 2020 Mar 20 03:50 UTC: apparent solar lon (of date) == 0.
        # geometric-of-date lon = aberration (+20.5") - nutation dpsi (~ -17")
        # => expect ~ +38" +/- a few arcsec of series truncation
        from pint_tpu.ephemeris import _VSOP_EARTH_L, _vsop_series

        mjd_tdb = 58928.0 + (3 * 3600 + 50 * 60 + 69.2) / 86400.0
        tau = np.atleast_1d((mjd_tdb - 51544.5) / 365250.0)
        lon_sun = (_vsop_series(_VSOP_EARTH_L, tau)[0] + np.pi) % (2 * np.pi)
        arcsec = np.degrees(lon_sun) * 3600
        assert 25 < arcsec < 50

    def test_perihelion_2020_distance(self):
        from pint_tpu.ephemeris import _VSOP_EARTH_R, _vsop_series

        mjd = 58853.0 + (7 * 3600 + 48 * 60) / 86400.0  # 2020 Jan 5 07:48 UTC
        tau = np.atleast_1d((mjd - 51544.5) / 365250.0)
        R = _vsop_series(_VSOP_EARTH_R, tau)[0]
        assert R == pytest.approx(0.9832436, abs=5e-6)

    def test_earth_vs_emb_lunar_wobble(self):
        # earth and emb differ by the ~4670 km barycenter offset
        mjd = np.arange(54000.0, 54060.0, 1.0)
        e, _ = self.eph.posvel_ssb("earth", mjd)
        emb, _ = self.eph.posvel_ssb("emb", mjd)
        d = np.linalg.norm(e - emb, axis=-1)
        assert 4000 < d.mean() < 5300

    def test_precession_consistent_with_earth_module(self):
        # the inline date->J2000 rotation must match earth.py's matrix
        from pint_tpu.earth import _precession_matrix
        from pint_tpu.ephemeris import _roty_vec, _rotz_vec

        T = 0.21
        asec = np.pi / (180.0 * 3600.0)
        zeta = (2306.2181 * T + 0.30188 * T**2 + 0.017998 * T**3) * asec
        z = (2306.2181 * T + 1.09468 * T**2 + 0.018203 * T**3) * asec
        theta = (2004.3109 * T - 0.42665 * T**2 - 0.041833 * T**3) * asec
        rng = np.random.default_rng(0)
        v = rng.standard_normal(3)
        got = _rotz_vec(_roty_vec(_rotz_vec(v[None, :], -z), theta), -zeta)[0]
        want = _precession_matrix(T) @ v
        np.testing.assert_allclose(got, want, atol=1e-12)

    def test_annual_parallax_geometry_sun_earth(self):
        # sun-earth vector should equal minus earth heliocentric: check
        # round-trip closure |earth_ssb - sun_ssb| ~ R series
        mjd = np.array([55000.0, 55100.0, 55200.0])
        e, ev = self.eph.posvel_ssb("earth", mjd)
        s, sv = self.eph.posvel_ssb("sun", mjd)
        r = np.linalg.norm(e - s, axis=-1) / AU_KM
        assert np.all((r > 0.97) & (r < 1.02))
        # radial velocity of earth wrt sun bounded by e*v_orb ~ 0.5 km/s
        rv = np.sum((e - s) * (ev - sv), axis=-1) / np.linalg.norm(e - s, axis=-1)
        assert np.all(np.abs(rv) < 0.6)
