"""grid.py under test: per-point parity with converged fitters, mesh
sharding equivalence, and the sharded GLS solve.

Reference semantics: ``gridutils.py:112 doonefit`` runs a full fitter at
each grid point with the grid parameters frozen; ``grid_chisq``
(``gridutils.py:164``) fans points over an executor.  Here the per-point
refit happens inside one jitted batch, so these tests pin (a) agreement
with an honest per-point fit, (b) that sharding the point axis over a
device mesh changes nothing but the layout.
"""

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"


@pytest.fixture(scope="module")
def ngc_fit():
    import os

    if not os.path.exists(NGC_PAR):
        pytest.skip("reference example par unavailable")
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(NGC_PAR)
    toas = make_fake_toas_uniform(53400, 54800, 60, model, error_us=10.0,
                                  add_noise=True, rng=np.random.default_rng(7))
    f = WLSFitter(toas, model)
    f.fit_toas(maxiter=4)
    return f


def _grids(f, npts):
    dF0 = 4 * f.errors.get("F0", 1e-10)
    dF1 = 4 * f.errors.get("F1", 1e-18)
    g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, npts)
    g1 = np.linspace(f.model.F1.value - dF1, f.model.F1.value + dF1, npts)
    return g0, g1


class TestGridVsPerPointFit:
    def test_grid_matches_converged_wls_per_point(self, ngc_fit):
        """grid_chisq == a converged per-point WLSFitter with grid params
        frozen (reference ``gridutils.py:112`` semantics).

        Pulse numbers are pinned at the best-fit model for the per-point
        fits: with ``nearest`` tracking a frozen-F0 offset lets the fitter
        slide into phase-wrap-aliased minima (e.g. DM shifted by ~1200),
        which the grid's coherent fixed-numbering objective rightly
        excludes — the same distinction the reference draws between its
        track modes (``residuals.py:331``)."""
        import copy

        from pint_tpu.fitter import WLSFitter
        from pint_tpu.grid import grid_chisq

        f = ngc_fit
        toas = copy.deepcopy(f.toas)
        toas.compute_pulse_numbers(f.model)
        g0, g1 = _grids(f, 3)
        chi2_grid, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
        for i, v0 in enumerate(g0):
            for j, v1 in enumerate(g1):
                m = copy.deepcopy(f.model)
                m.F0.value = float(v0)
                m.F0.frozen = True
                m.F1.value = float(v1)
                m.F1.frozen = True
                ff = WLSFitter(toas, m, track_mode="use_pulse_numbers")
                chi2_pt = ff.fit_toas(maxiter=6)
                assert chi2_grid[i, j] == pytest.approx(chi2_pt, rel=1e-6), \
                    f"grid point ({i},{j})"

    def test_tuple_chisq_matches_grid(self, ngc_fit):
        from pint_tpu.grid import grid_chisq, tuple_chisq

        f = ngc_fit
        g0, g1 = _grids(f, 3)
        chi2_grid, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
        pts = [(v0, v1) for v0 in g0 for v1 in g1]
        chi2_t, _ = tuple_chisq(f, ("F0", "F1"), pts)
        assert np.allclose(np.asarray(chi2_t).reshape(3, 3), chi2_grid,
                           rtol=1e-9)

    def test_grid_chisq_derived(self, ngc_fit):
        """Derived-parameter grid: F0 = g/(2pi) style mapping
        (reference ``gridutils.py:390``)."""
        from pint_tpu.grid import grid_chisq, grid_chisq_derived

        f = ngc_fit
        g0, g1 = _grids(f, 3)
        chi2_ref, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
        chi2_d, out_grids, _ = grid_chisq_derived(
            f, ("F0", "F1"),
            (lambda x, y: 2.0 * x, lambda x, y: y),
            (g0 / 2.0, g1))
        assert np.allclose(chi2_d, chi2_ref, rtol=1e-6)
        assert out_grids[0].shape == (3, 3)


class TestMeshSharding:
    def test_grid_chisq_mesh_matches_unsharded(self, ngc_fit, eight_devices):
        """Sharding grid points over a 2x4 mesh must be layout-only
        (SURVEY §2c mechanism 1: the reference's process-pool axis)."""
        from jax.sharding import Mesh

        from pint_tpu.grid import grid_chisq

        f = ngc_fit
        g0, g1 = _grids(f, 4)
        chi2_plain, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
        mesh = Mesh(np.array(eight_devices).reshape(2, 4), ("grid", "aux"))
        chi2_mesh, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), mesh=mesh)
        assert np.allclose(chi2_mesh, chi2_plain, rtol=1e-12, atol=1e-9)

    def test_grid_point_count_not_multiple_of_devices(self, ngc_fit,
                                                      eight_devices):
        """Padding: 3x3=9 points on 8 devices."""
        from jax.sharding import Mesh

        from pint_tpu.grid import grid_chisq

        f = ngc_fit
        g0, g1 = _grids(f, 3)
        chi2_plain, _ = grid_chisq(f, ("F0", "F1"), (g0, g1))
        mesh = Mesh(np.array(eight_devices), ("grid",))
        chi2_mesh, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), mesh=mesh)
        assert np.allclose(chi2_mesh, chi2_plain, rtol=1e-12, atol=1e-9)


@pytest.fixture(scope="module")
def gls_fit():
    """Small correlated-noise workload: EFAC+EQUAD+ECORR+red noise."""
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    par = [
        "PSR TESTGLS\n", "RAJ 05:00:00 1\n", "DECJ 15:00:00 1\n",
        "F0 99.123456789 1\n", "F1 -1.1e-14 1\n", "PEPOCH 55500\n",
        "DM 12.5 1\n",
        "EFAC mjd 53000 58000 1.1\n",
        "EQUAD mjd 53000 58000 0.5\n",
        "ECORR mjd 53000 58000 0.8\n",
        "TNRedAmp -13.5\n", "TNRedGam 3.5\n", "TNRedC 10\n",
        "UNITS TDB\n",
    ]
    model = get_model(par)
    # clustered epochs within the 1 s ECORR quantization threshold so the
    # quantization basis is non-trivial (25 epochs x 2 TOAs)
    rng = np.random.default_rng(3)
    base = np.linspace(55000, 56000, 25)
    mjds = np.sort(np.concatenate([base, base + 0.5 / 86400.0]))
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    toas = make_fake_toas_fromMJDs(mjds, model, error_us=1.0, add_noise=True,
                                   rng=rng)
    f = GLSFitter(toas, model)
    f.fit_toas(maxiter=2)
    return f


class TestGLSGrid:
    def test_gls_grid_matches_per_point_gls(self, gls_fit):
        """The correlated-noise grid path: each point equals a converged
        per-point GLSFitter with the grid params frozen."""
        import copy

        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.grid import grid_chisq

        f = gls_fit
        dF0 = 3 * f.errors.get("F0", 1e-10)
        g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, 3)
        g1 = np.array([f.model.F1.value])
        chi2_grid, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=4)
        for i, v0 in enumerate(g0):
            m = copy.deepcopy(f.model)
            m.F0.value = float(v0)
            m.F0.frozen = True
            m.F1.frozen = True
            ff = GLSFitter(f.toas, m)
            chi2_pt = ff.fit_toas(maxiter=4)
            assert chi2_grid[i, 0] == pytest.approx(chi2_pt, rel=1e-4), \
                f"GLS grid point {i}"

    def test_gls_grid_mesh_matches_unsharded(self, gls_fit, eight_devices):
        """Sharded GLS solve: the chunked Woodbury grid under a device mesh
        equals the single-device result."""
        from jax.sharding import Mesh

        from pint_tpu.grid import grid_chisq

        f = gls_fit
        dF0 = 3 * f.errors.get("F0", 1e-10)
        g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, 4)
        g1 = np.linspace(f.model.F1.value - 1e-16, f.model.F1.value + 1e-16, 4)
        chi2_plain, ex_plain = grid_chisq(f, ("F0", "F1"), (g0, g1),
                                          extraparnames=("DM",))
        mesh = Mesh(np.array(eight_devices), ("grid",))
        chi2_mesh, ex_mesh = grid_chisq(f, ("F0", "F1"), (g0, g1),
                                        extraparnames=("DM",), mesh=mesh)
        assert np.allclose(chi2_mesh, chi2_plain, rtol=1e-10, atol=1e-8)
        # per-point refit extras survive the sharded chunked path too
        assert ex_mesh["DM"].shape == chi2_mesh.shape
        assert np.allclose(ex_mesh["DM"], ex_plain["DM"], rtol=1e-10)


class TestGridExecutableReuse:
    def test_noise_change_uses_fresh_scaling(self, gls_fit):
        """Regression (r4 review): the cached grid executable is reused
        across grid_chisq calls, so every weight-dependent hoisted array
        (including the s_col column scaling) must be a traced argument.
        Changing EFAC between calls must still match per-point fits."""
        import copy

        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.grid import grid_chisq

        f = gls_fit
        dF0 = 3 * f.errors.get("F0", 1e-10)
        g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, 3)
        g1 = np.array([f.model.F1.value])
        grid_chisq(f, ("F0", "F1"), (g0, g1), niter=8)  # seed the cache

        efac_save = f.model.EFAC1.value
        f.model.EFAC1.value = 1.7  # rescales w and therefore s_col
        chi2_grid, ex = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=8,
                                   extraparnames=("DM",))
        for i, v0 in enumerate(g0):
            m = copy.deepcopy(f.model)
            m.F0.value = float(v0)
            m.F0.frozen = True
            m.F1.frozen = True
            ff = GLSFitter(f.toas, m)
            chi2_pt = ff.fit_toas(maxiter=8)
            assert chi2_grid[i, 0] == pytest.approx(chi2_pt, rel=1e-4)
            # DM is the sloppy direction here (single-frequency TOAs):
            # both paths converge toward it from different trajectories, so
            # allow 1e-3 — a stale s_col would miss by the ~1.7x rescale
            assert ex["DM"][i, 0] == pytest.approx(
                float(ff.model.DM.value), rel=1e-3)
        # the fixture is module-scoped: restore the mutated noise param
        f.model.EFAC1.value = efac_save

    def test_hoisted_bundle_cache_hit_and_invalidation(self, gls_fit):
        """The hoisted per-grid constants (Gram blocks, Woodbury Cholesky,
        column scales) are cached by parameter values + TOAs version so
        repeated calls skip the host rebuild and device transfers; an
        identical call must reuse the bundle verbatim, and a TOAs-version
        bump must rebuild it."""
        from pint_tpu.grid import grid_chisq

        f = gls_fit
        dF0 = 3 * f.errors.get("F0", 1e-10)
        g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, 3)
        g1 = np.array([f.model.F1.value])
        c1, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=4)
        slot1 = f.model._cache["grid_gls_bundle"]
        c2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=4)
        assert f.model._cache["grid_gls_bundle"] is slot1  # bundle reused
        np.testing.assert_array_equal(c1, c2)
        nclass0 = sum(1 for k in f.model._cache
                      if isinstance(k, tuple) and k[0] == "grid_classify")
        f.toas._version += 1  # any in-place TOA mutation bumps this
        c3, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=4)
        assert f.model._cache["grid_gls_bundle"] is not slot1  # rebuilt
        # the Jacobian probe must also rerun on the post-mutation TOAs,
        # but by OVERWRITING the single classify entry (version lives in
        # the cached value, not the key) — keying on _version leaked a
        # ~MB-scale Jacobian per in-place edit (ADVICE.md round 5)
        nclass1 = sum(1 for k in f.model._cache
                      if isinstance(k, tuple) and k[0] == "grid_classify")
        assert nclass1 == nclass0
        ckey = next(k for k in f.model._cache
                    if isinstance(k, tuple) and k[0] == "grid_classify")
        assert f.model._cache[ckey][-1] == f.toas._version  # re-probed
        np.testing.assert_array_equal(c1, c3)
        f.toas._version -= 1  # module-scoped fixture: restore

    def test_bundle_key_completeness_under_random_edits(self, gls_fit):
        """Fuzz the bundle-cache key: after ANY parameter edit (timing,
        white noise incl. ECORR, red noise), the cached path must equal
        a rebuild with the value-dependent caches (bundle + classify)
        cleared — a missing key ingredient would serve stale
        weights/bases and diverge.  (The compiled executables are
        value-INdependent by design — values flow in as traced
        arguments — so they are deliberately not cleared.)"""
        from pint_tpu.grid import grid_chisq

        f = gls_fit
        dF0 = 3 * f.errors.get("F0", 1e-10)
        g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, 3)
        g1 = np.array([f.model.F1.value])
        rng = np.random.default_rng(17)
        edits = [("EFAC1", lambda v: v * (1 + 0.3 * rng.random())),
                 ("EQUAD1", lambda v: v + 0.2 * rng.random()),
                 ("ECORR1", lambda v: v * (1 + 0.4 * rng.random())),
                 ("TNREDAMP", lambda v: v + 0.4 * rng.random()),
                 ("TNREDGAM", lambda v: v + 0.5 * rng.random()),
                 ("DM", lambda v: v + 1e-4 * rng.random())]
        saved = {p: getattr(f.model, p).value for p, _ in edits}
        try:
            grid_chisq(f, ("F0", "F1"), (g0, g1), niter=4)  # seed
            for p, fn in edits:
                getattr(f.model, p).value = fn(getattr(f.model, p).value)
                c_cached, _ = grid_chisq(f, ("F0", "F1"), (g0, g1),
                                         niter=4)
                f.model._cache.pop("grid_gls_bundle", None)
                for k in [k for k in f.model._cache
                          if isinstance(k, tuple)
                          and k[0] == "grid_classify"]:
                    del f.model._cache[k]
                c_fresh, _ = grid_chisq(f, ("F0", "F1"), (g0, g1),
                                        niter=4)
                np.testing.assert_array_equal(c_cached, c_fresh,
                                              err_msg=f"stale after {p}")
        finally:
            for p, v in saved.items():
                getattr(f.model, p).value = v

    def test_bundle_not_shared_across_toas_objects(self, gls_fit):
        """Two TOAs objects of equal length and version are different
        data: a model used against both (two fitters sharing the model)
        must not serve the first object's hoisted bundle — weights, noise
        bases, and the phase zero-point all belong to the object."""
        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.grid import grid_chisq
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        f = gls_fit
        base = np.linspace(55000, 56000, 25)
        mjds = np.sort(np.concatenate([base, base + 0.5 / 86400.0]))
        toas_b = make_fake_toas_fromMJDs(
            mjds, f.model, error_us=1.0, add_noise=True,
            rng=np.random.default_rng(99))  # different noise draw
        fb = GLSFitter(toas_b, f.model)
        dF0 = 3 * f.errors.get("F0", 1e-10)
        g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, 3)
        g1 = np.array([f.model.F1.value])
        grid_chisq(f, ("F0", "F1"), (g0, g1), niter=4)  # seeds A's bundle
        cb, _ = grid_chisq(fb, ("F0", "F1"), (g0, g1), niter=4)
        del f.model._cache["grid_gls_bundle"]
        cb_fresh, _ = grid_chisq(fb, ("F0", "F1"), (g0, g1), niter=4)
        np.testing.assert_array_equal(cb, cb_fresh)


class TestLinearColumnClassification:
    def test_probe_scale_keeps_linear_columns_linear(self, gls_fit):
        """Regression: the linearity probe perturbs each parameter by a
        ~1e-3-cycle phase step.  With a naive max(|v|,1)*1e-6 step, F1
        (magnitude 1e-14) gets a catastrophically large perturbation and
        every column misclassifies as nonlinear, killing the constant-column
        speedup."""
        from pint_tpu.grid import build_grid_gls_chi2_fn

        f = gls_fit
        model, toas = f.model, f.toas
        build_grid_gls_chi2_fn(model, toas, ("F0", "F1"), niter=2,
                               grid_spans=[1e-9, 1e-16])
        keys = [k for k in model._cache
                if isinstance(k, tuple) and k and k[0] == "grid_gls_fn"]
        assert keys
        nl = keys[-1][-1]
        fitp = tuple(p for p in model.free_params if p not in ("F0", "F1"))
        # DM enters the phase exactly linearly; it must never classify
        # nonlinear (RAJ/DECJ may legitimately go either way)
        assert fitp.index("DM") not in nl
        assert len(nl) < len(fitp)


class TestGridUtilsParity:
    def test_doonefit_matches_grid_point(self, ngc_fit):
        from pint_tpu.grid import doonefit, tuple_chisq

        f = ngc_fit
        # small enough that "nearest" phase tracking in the fresh fitter and
        # the grid's fixed pulse numbering agree (< a few millicycles)
        v0 = float(f.model.F0.value) + 3e-12
        chi2_one, extras = doonefit(f, ("F0",), (v0,), maxiter=5,
                                    extraparnames=("F1",))
        chi2_t, _ = tuple_chisq(f, ("F0",), [(v0,)], niter=8)
        assert chi2_one == pytest.approx(float(chi2_t[0]), rel=1e-4)
        assert np.isfinite(extras[0])

    def test_batched_extraparnames_match_doonefit(self, ngc_fit):
        """VERDICT r3 #5: the batched grid returns per-point refit values
        (reference gridutils.py:116-160 extraout), matching the scalar
        doonefit path."""
        from pint_tpu.grid import doonefit, grid_chisq

        f = ngc_fit
        F0 = float(f.model.F0.value)
        g0 = np.array([F0 - 3e-12, F0, F0 + 3e-12])
        chi2, extra = grid_chisq(f, ("F0",), (g0,), niter=8,
                                 extraparnames=("F1", "DM", "F0"))
        assert set(extra) == {"F1", "DM", "F0"}
        assert extra["F1"].shape == chi2.shape == (3,)
        # the grid parameter's "extra" is the grid value itself
        np.testing.assert_allclose(extra["F0"], g0, rtol=0)
        for i, v0 in enumerate(g0):
            _, extras = doonefit(f, ("F0",), (v0,), maxiter=8,
                                 extraparnames=("F1", "DM"))
            assert extra["F1"][i] == pytest.approx(extras[0], rel=1e-6), i
            assert extra["DM"][i] == pytest.approx(extras[1], rel=1e-6), i

    def test_extraparnames_positional_reference_order(self, ngc_fit):
        """Reference gridutils.py:164 takes extraparnames as the 4th
        positional parameter; reference-style positional calls must bind
        it there, not to executor."""
        from pint_tpu.grid import grid_chisq

        f = ngc_fit
        F0 = float(f.model.F0.value)
        g0 = np.array([F0, F0 + 3e-12])
        chi2, extra = grid_chisq(f, ("F0",), (g0,), ("F1",))
        assert set(extra) == {"F1"} and extra["F1"].shape == (2,)

    def test_gls_batched_extraparnames(self, gls_fit):
        """Extras ride through the chunked GLS path too."""
        from pint_tpu.grid import grid_chisq

        f = gls_fit
        F0 = float(f.model.F0.value)
        g0 = np.linspace(F0 - 3e-12, F0 + 3e-12, 3)
        from pint_tpu.grid import doonefit

        chi2, extra = grid_chisq(f, ("F0",), (g0,), niter=8,
                                 extraparnames=("F1", "DM"))
        assert extra["DM"].shape == chi2.shape == (3,)
        # per-point parity with the scalar doonefit path (the refit DM
        # legitimately swings point-to-point: single-frequency TOAs leave
        # DM strongly covariant with F0 — both paths must agree on it)
        for i, v in enumerate(g0):
            _, ex = doonefit(f, ("F0",), (v,), maxiter=8,
                             extraparnames=("F1", "DM"))
            assert extra["F1"][i] == pytest.approx(ex[0], rel=1e-5), i
            assert extra["DM"][i] == pytest.approx(ex[1], rel=1e-4), i

    def test_tuple_chisq_derived(self, ngc_fit):
        from pint_tpu.grid import tuple_chisq, tuple_chisq_derived

        f = ngc_fit
        F0 = float(f.model.F0.value)
        # derived quantity: spin period in ms -> F0 (compare at the exact
        # roundtripped values; float inversion loses low bits and chi2 is
        # steep in F0)
        pts = [(1000.0 / F0,), (1000.0 / (F0 + 1e-10),)]
        chi2, vals, _ = tuple_chisq_derived(
            f, ("F0",), [lambda p_ms: 1000.0 / p_ms], pts, niter=8)
        rt = [(1000.0 / p[0],) for p in pts]
        direct, _ = tuple_chisq(f, ("F0",), rt, niter=8)
        np.testing.assert_allclose(chi2, direct, rtol=1e-10)
        assert len(vals) == 1 and len(vals[0]) == 2

    def test_hostinfo_and_set_log(self):
        from pint_tpu.grid import hostinfo, set_log

        assert isinstance(hostinfo(), str) and hostinfo()
        set_log(None)  # parity no-op


class TestDesignmatrixLinearCache:
    def test_cached_matches_exact_after_value_changes(self, gls_fit):
        """Within the probed envelope the linear-cached design matrix (J0
        constants + sub_jac merge) matches the exact recomputation; the
        test asserts the cache entry was actually REUSED so the merge path
        cannot pass vacuously."""
        import copy

        f = gls_fit
        m = copy.deepcopy(f.model)
        toas = f.toas
        m.designmatrix(toas, reuse_linear=True)   # lazy seed
        m.designmatrix(toas, reuse_linear=True)   # classification pass
        free = m.design_param_names()
        entry = m._cache["lincols"][toas][free]
        assert entry["dp"] is not None  # classified
        J0_id = id(entry["J0"])
        # displace every free parameter well WITHIN its probed envelope
        for i, p in enumerate(free):
            dpi = entry["dp"][i]
            step = 0.1 * dpi if np.isfinite(dpi) else 0.0
            par = getattr(m, p)
            par.value = float(par.value or 0.0) + step
        M_cached, _, _ = m.designmatrix(toas, reuse_linear=True)
        # same entry served (no reseed): the sub_jac merge path ran
        assert id(m._cache["lincols"][toas][free]["J0"]) == J0_id
        M_exact, _, _ = m.designmatrix(toas, reuse_linear=False)
        scale = np.abs(M_exact).max(axis=0) + 1e-300
        np.testing.assert_allclose(M_cached / scale, M_exact / scale,
                                   atol=5e-8)

    def test_big_step_reseeds(self, gls_fit):
        """A step beyond the envelope reseeds rather than serving stale
        linear columns."""
        import copy

        f = gls_fit
        m = copy.deepcopy(f.model)
        toas = f.toas
        m.designmatrix(toas, reuse_linear=True)
        m.designmatrix(toas, reuse_linear=True)
        free = m.design_param_names()
        entry = m._cache["lincols"][toas][free]
        i = list(free).index("F0")
        m.F0.value = float(m.F0.value) + 10 * entry["dp"][i]
        M_cached, _, _ = m.designmatrix(toas, reuse_linear=True)
        assert m._cache["lincols"][toas][free]["nl"] is None  # fresh lazy seed
        M_exact, _, _ = m.designmatrix(toas, reuse_linear=False)
        np.testing.assert_allclose(M_cached, M_exact, rtol=0, atol=0)

    def test_frozen_edit_invalidates(self, gls_fit):
        """Editing a frozen parameter reseeds the cache (linear-in-free
        columns can still depend on frozen values)."""
        import copy

        f = gls_fit
        m = copy.deepcopy(f.model)
        toas = f.toas
        m.designmatrix(toas, reuse_linear=True)
        m.designmatrix(toas, reuse_linear=True)
        # TNRedAmp is a frozen noise hyperparameter in this fixture; use a
        # frozen continuous timing value instead: freeze DM and edit it
        m.DM.frozen = True
        free2 = m.design_param_names()
        m.designmatrix(toas, reuse_linear=True)
        m.designmatrix(toas, reuse_linear=True)
        assert m._cache["lincols"][toas][free2]["nl"] is not None
        m.DM.value = float(m.DM.value) + 1.0  # big frozen edit
        m.designmatrix(toas, reuse_linear=True)
        # reseeded: back to the lazy (unclassified) state
        assert m._cache["lincols"][toas][free2]["nl"] is None
        M_cached, _, _ = m.designmatrix(toas, reuse_linear=True)
        M_exact, _, _ = m.designmatrix(toas, reuse_linear=False)
        np.testing.assert_allclose(M_cached, M_exact, rtol=0, atol=0)

    def test_fit_results_unchanged_by_cache(self, gls_fit):
        """A multi-iteration GLS fit lands at the same chi2/parameters with
        and without the linear-column cache."""
        import copy

        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.models.timing_model import TimingModel

        f = gls_fit
        m1 = copy.deepcopy(f.model)
        m2 = copy.deepcopy(f.model)
        # perturb identically so both fits do real work
        for m in (m1, m2):
            m.F0.value = float(m.F0.value) + 3e-10
        fa = GLSFitter(f.toas, m1)
        chi2_a = float(fa.fit_toas(maxiter=3))  # reuse_linear path (default)

        exact = TimingModel.designmatrix

        def exact_dm(self, toas, incfrozen=False, incoffset=True,
                     reuse_linear=False):
            return exact(self, toas, incfrozen=incfrozen,
                         incoffset=incoffset, reuse_linear=False)

        fb = GLSFitter(f.toas, m2)
        try:
            TimingModel.designmatrix = exact_dm
            chi2_b = float(fb.fit_toas(maxiter=3))
        finally:
            TimingModel.designmatrix = exact
        # the classification guarantees columns to 1e-7 relative within the
        # probed envelope; near the minimum chi2 is flat, so agreement far
        # below measurement significance is the contract (observed ~1e-9)
        assert chi2_a == pytest.approx(chi2_b, rel=1e-7)
        for p in fa.model.free_params:
            va = float(getattr(fa.model, p).value)
            vb = float(getattr(fb.model, p).value)
            err = float(getattr(fa.model, p).uncertainty or 0.0)
            tol = max(1e-8 * abs(vb), 1e-4 * err, 1e-20)
            assert abs(va - vb) < tol, p


class TestChunkSizes:
    def test_gls_grid_chunk_sizes_agree(self, gls_fit):
        """chunk= (the tools/tpu_sweep.py knob) changes only the executable
        batch shape: chi2 must agree across chunk sizes, including sizes
        larger than, equal to, and smaller than the point count."""
        from pint_tpu.grid import grid_chisq

        f = gls_fit
        g0 = np.linspace(f.model.F0.value - 2e-10, f.model.F0.value + 2e-10, 3)
        g1 = np.linspace(f.model.F1.value - 2e-17, f.model.F1.value + 2e-17, 3)
        ref, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=2)
        # tolerance: executable shape changes XLA fusion, so the 2-GN-step
        # refit chi2 carries reorder-of-operations noise (~2e-9 relative
        # observed after the no-materialized-B rewrite); an actual chunking
        # or padding bug would be orders of magnitude larger
        for chunk in (4, 9, 32):
            chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=2,
                                 chunk=chunk)
            np.testing.assert_allclose(np.asarray(chi2), np.asarray(ref),
                                       rtol=1e-8, atol=1e-7,
                                       err_msg=f"chunk={chunk}")


class TestKernelMemoryShape:
    def test_no_per_point_design_matrix_scatter(self, tmp_path):
        """The GLS grid kernel must never materialize the per-point design
        matrix: under vmap that is a (chunk, n_toa, n_cols) scatter, which
        was the v5e scoped-vmem compile ceiling (round 5; DESIGN.md
        'no-materialized-B').  Lower the kernel via an XLA dump in a
        subprocess and assert no scatter shape carries the TOA dimension
        (the remaining fix-up scatters are nt x chunk x k — TOA-free)."""
        import os
        import re
        import subprocess
        import sys

        if not os.path.exists(NGC_PAR):
            pytest.skip("reference example par unavailable")
        REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ntoa = 53  # prime and distinctive: no other kernel dim equals it
        script = f"""
import sys; sys.path.insert(0, {repr(REPO)})
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from pint_tpu.models import get_model
from pint_tpu.io.par import parse_parfile
from pint_tpu.simulation import make_fake_toas_uniform
from pint_tpu.gls_fitter import GLSFitter
from pint_tpu.grid import grid_chisq
text = open({repr(NGC_PAR)}).read()
m = get_model(parse_parfile(text + "\\nBINARY BT\\nPB 10.0 1\\nA1 5.0 1\\n"
    "T0 53500.0 1\\nECC 0.01 1\\nOM 10.0 1\\nEFAC mjd 52000 60000 1.2 1\\n"
    "ECORR mjd 52000 60000 2.0 1\\nTNREDAMP -13\\nTNREDGAM 3.0\\nTNREDC 5\\n"))
t = make_fake_toas_uniform(53000, 54800, {ntoa}, m, error_us=2.0,
                           add_noise=True, rng=np.random.default_rng(5))
f = GLSFitter(t, m)
f.fit_toas(maxiter=1)
g0 = np.linspace(m.PB.value - 1e-6, m.PB.value + 1e-6, 2)
g1 = np.linspace(m.ECC.value * 0.99, m.ECC.value * 1.01, 2)
grid_chisq(f, ("PB", "ECC"), (g0, g1), niter=2, chunk=4)
"""
        env = dict(os.environ)
        env["XLA_FLAGS"] = f"--xla_dump_to={tmp_path}"
        env.pop("JAX_PLATFORMS", None)
        subprocess.run([sys.executable, "-c", script], check=True, env=env,
                       cwd=REPO, timeout=500)
        dumps = [p for p in os.listdir(tmp_path)
                 if "chi2_point" in p and p.endswith("after_optimizations.txt")]
        assert dumps, f"no chi2_point HLO dump in {tmp_path}"
        bad, n_scatter_shapes = [], 0
        for p in dumps:
            with open(os.path.join(tmp_path, p)) as fh:
                for line in fh:
                    # scatter result lines: "%name = <shapes> scatter(...)".
                    # Shapes may be variadic tuples and any dtype, so
                    # collect EVERY bracketed dims list on the line.
                    if "scatter(" not in line:
                        continue
                    for shape in re.findall(r"\[([0-9,]+)\]", line):
                        dims = [int(d) for d in shape.split(",")]
                        n_scatter_shapes += 1
                        if ntoa in dims:
                            bad.append((p, line.strip()[:160]))
        # positive control: the kernel's legitimate TOA-free fix-up
        # scatters (A/Y/b_t row-column refreshes) must be visible — zero
        # matches means the scan regex or dump naming broke, and the
        # assertion below would pass vacuously
        assert n_scatter_shapes > 0, \
            "no scatter shapes matched; the HLO scan is no longer seeing ops"
        assert not bad, f"TOA-dimension scatter reappeared: {bad[:3]}"


class TestBundleKeySatellites:
    """Self-contained (no reference datafiles): the two bundle-vkey
    satellite fixes — nfit in the key, and mask-parameter selector ranges
    in the key."""

    PAR = """
PSR  J0000+0000
RAJ  04:37:00.0
DECJ -47:15:00.0
POSEPOCH 55000
F0   173.6879489990983 1
F1   -1.728e-15 1
PEPOCH 55000
DM   2.64476 1
EPHEM DE440
UNITS TDB
TNREDAMP -13.0
TNREDGAM 3.0
TNREDC 5
EFAC mjd 54000 55500 1.3
"""

    @pytest.fixture(scope="class")
    def sim(self):
        import io

        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        m = get_model(io.StringIO(self.PAR))
        t = make_fake_toas_uniform(54000, 55500, 40, m, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(11))
        return m, t

    def test_vkey_includes_nfit(self, sim):
        """Two builds with coinciding all_names but different fit/grid
        partitions must not collide into one bundle (the hoisted basis
        has 1+nfit columns; a collision is a trace-time shape error)."""
        from pint_tpu.grid import build_grid_gls_chi2_fn

        m, t = sim
        fn1, _, _ = build_grid_gls_chi2_fn(m, t, ("F0",),
                                           fit_params=("F1",), niter=1)
        v0 = float(m.F0.value)
        fn1(np.array([[v0]]))
        # same all_names tuple ("F1", "F0"), nfit 0 instead of 1
        fn2, _, _ = build_grid_gls_chi2_fn(m, t, ("F1", "F0"),
                                           fit_params=(), niter=1)
        chi2, _, _ = fn2(np.array([[float(m.F1.value), v0]]))
        assert np.isfinite(np.asarray(chi2)).all()

    def test_vkey_includes_mask_selector(self, sim):
        """Editing an EFAC selector's MJD range at an unchanged VALUE
        changes the weights; the cached Gram/Cholesky bundle must
        invalidate (stale weights would silently skew every chi2)."""
        from pint_tpu.grid import build_grid_gls_chi2_fn

        m, t = sim
        build_grid_gls_chi2_fn(m, t, ("F0", "F1"), niter=1)
        slot1 = m._cache["grid_gls_bundle"]
        build_grid_gls_chi2_fn(m, t, ("F0", "F1"), niter=1)
        assert m._cache["grid_gls_bundle"] is slot1  # stable when unchanged
        efac = m.components["ScaleToaError"]._params_dict["EFAC1"]
        old = list(efac.key_value)
        efac.key_value = ["54000", "54700"]  # same value, new selection
        try:
            build_grid_gls_chi2_fn(m, t, ("F0", "F1"), niter=1)
            assert m._cache["grid_gls_bundle"] is not slot1  # rebuilt
        finally:
            efac.key_value = old
