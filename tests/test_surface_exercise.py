"""Execute the public names a static reference-scan found unexercised.

A sweep of every public def/class in ``pint_tpu`` against the test/
example/tool corpus found ~40 names (mostly reference-parity spellings)
defined but never run by any test.  Parity surface that is never
executed is shipping risk — each test here drives one cluster of them
with a real assertion, not just an import.
"""

import os

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"


def _model_with(extra):
    from pint_tpu.io.par import parse_parfile
    from pint_tpu.models import get_model

    with open(NGC_PAR) as f:
        text = f.read()
    return get_model(parse_parfile(text + "\n" + "\n".join(extra) + "\n"))


@pytest.fixture(scope="module")
def sim():
    m = _model_with([])
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    t = make_fake_toas_fromMJDs(np.linspace(53005, 54795, 60), m,
                                freq=1400.0, error_us=2.0, add_noise=True,
                                rng=np.random.default_rng(5))
    return m, t


class TestExceptionsSurface:
    def test_taxonomy_raisable(self):
        from pint_tpu.exceptions import (ComponentConflict,
                                         MissingBinaryError, ModelError,
                                         PINTPrecisionError, PintError,
                                         TimingModelError)

        with pytest.raises(ModelError):
            raise ComponentConflict("two dispersion components")
        with pytest.raises(TimingModelError):
            raise MissingBinaryError("BINARY missing")
        with pytest.raises((PintError, RuntimeError)):
            raise PINTPrecisionError("longdouble too short")


class TestFitterResidualAccessors:
    def test_correlation_matrix_and_chi2_reduced(self, sim):
        from pint_tpu.fitter import WLSFitter

        m, t = sim
        f = WLSFitter(t, m)
        assert f.get_parameter_correlation_matrix() is None  # pre-fit
        f.fit_toas(maxiter=2)
        corr = np.asarray(f.get_parameter_correlation_matrix().matrix)
        assert np.allclose(np.diag(corr), 1.0, atol=1e-9)
        assert np.all(np.abs(corr) <= 1.0 + 1e-9)
        assert f.resids.chi2_reduced == f.resids.reduced_chi2  # property alias
        # MJDParameter.value_float (float view of the longdouble MJD)
        assert isinstance(m.PEPOCH.value_float, float)
        assert m.PEPOCH.value_float == pytest.approx(float(m.PEPOCH.value))

    def test_pintk_default_fitter(self):
        from pint_tpu.pintk.pulsar import Pulsar

        psr = Pulsar(NGC_PAR, NGC_TIM)
        assert psr.getDefaultFitter() in (
            "downhill WLS", "downhill GLS", "WLS", "GLS", "Wideband")


class TestTimingModelFullMatrices:
    def test_full_designmatrix_and_weights(self, sim):
        m = _model_with(["TNRedAmp -13.0", "TNRedGam 3.0", "TNRedC 5"])
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        t = make_fake_toas_fromMJDs(np.linspace(53005, 54795, 40), m,
                                    freq=1400.0, error_us=2.0,
                                    add_noise=True,
                                    rng=np.random.default_rng(6))
        M, names, units = m.designmatrix(t)
        F, fnames, funits = m.full_designmatrix(t)
        assert F.shape[0] == len(t) and F.shape[1] > M.shape[1]
        w = m.full_basis_weight(t)
        assert w.shape == (F.shape[1],)
        assert np.max(w) >= 1e39  # timing columns get the huge flat prior
        assert np.min(w) > 0

    def test_barycentric_and_total_eval(self, sim):
        m, t = sim
        bary = m.get_barycentric_toas(t)
        # barycentric MJDs stay within light-travel distance of TDB
        assert np.max(np.abs(np.asarray(bary - t.tdb, dtype=float))) \
            < 600.0 / 86400.0
        out = m.total_delay_and_phase(t)
        ph = out[0]
        assert np.asarray(ph.frac).shape == (len(t),)


class TestComponentEditing:
    def test_dmwavex_cmwavex_add_remove(self):
        m = _model_with(["DMWXFREQ_0001 1e-8 0", "DMWXSIN_0001 0 1",
                         "DMWXCOS_0001 0 1"])
        c = m.components["DMWaveX"]
        idx = c.add_dmwavex_components([2e-8, 3e-8], indices=[5, 6])
        assert m.DMWXFREQ_0005.value == pytest.approx(2e-8)
        c.remove_dmwavex_component(6)
        assert getattr(m, "DMWXFREQ_0006", None) is None \
            or m.DMWXFREQ_0006.value is None

        m2 = _model_with(["CM 0.1 1", "TNCHROMIDX 4",
                          "CMWXFREQ_0001 1e-8 0", "CMWXSIN_0001 0 1",
                          "CMWXCOS_0001 0 1"])
        c2 = m2.components["CMWaveX"]
        c2.add_cmwavex_components([2e-8], indices=[7])
        assert m2.CMWXFREQ_0007.value == pytest.approx(2e-8)
        c2.remove_cmwavex_component(7)

    def test_swx_range_removal(self):
        m = _model_with(["NE_SW 5 0", "SWXDM_0001 1e-3 1",
                         "SWXR1_0001 53000", "SWXR2_0001 54000",
                         "SWXDM_0002 2e-3 1", "SWXR1_0002 54000",
                         "SWXR2_0002 55000"])
        c = next(c for c in m.components.values()
                 if hasattr(c, "remove_swx_range"))
        c.remove_swx_range(2)
        assert getattr(m, "SWXDM_0002", None) is None \
            or m.SWXDM_0002.value is None
        assert m.SWXDM_0001.value == pytest.approx(1e-3)

    def test_jump_count(self):
        m = _model_with(["JUMP mjd 53000 54000 1e-5 1",
                         "JUMP mjd 54000 55000 2e-5 1"])
        c = next(c for c in m.components.values()
                 if hasattr(c, "get_number_of_jumps"))
        assert c.get_number_of_jumps() == 2

    def test_absolute_phase_clear_cache(self, sim):
        m = _model_with(["TZRMJD 53800", "TZRSITE @", "TZRFRQ 1400"])
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        t = make_fake_toas_fromMJDs(np.linspace(53005, 54795, 20), m,
                                    freq=1400.0, error_us=2.0,
                                    rng=np.random.default_rng(7))
        c = next(c for c in m.components.values()
                 if hasattr(c, "clear_cache"))
        ph1 = np.asarray(m.phase(t).frac)
        c.clear_cache()
        ph2 = np.asarray(m.phase(t).frac)
        np.testing.assert_array_equal(ph1, ph2)


class TestBinaryHelpers:
    def test_ell1_ecc_om(self):
        m = _model_with(["BINARY ELL1", "PB 10.0 1", "A1 5.0 1",
                         "TASC 53000 1", "EPS1 3e-5 1", "EPS2 4e-5 1"])
        c = m.components["BinaryELL1"]
        assert c.ell1_ecc() == pytest.approx(5e-5)
        om = c.ell1_om_deg()
        assert om == pytest.approx(np.degrees(np.arctan2(3e-5, 4e-5)))


class TestObservatoryHelpers:
    def test_bipm_correction_and_json(self):
        from pint_tpu.observatory import Observatory, get_observatory

        # no BIPM clock file ships in this image, so the correction is
        # the zero fallback — the call path itself is what's exercised
        corr = Observatory.bipm_correction(np.array([55000.0, 56000.0]))
        assert corr.shape == (2,) and np.all(np.abs(corr) < 1e-4)
        site = get_observatory("gbt")
        import json

        d = json.loads(site.get_json())
        assert next(iter(d)).lower() in ("gbt", "green_bank")


class TestNumericHelpers:
    def test_phase_add_dd(self):
        from pint_tpu.dd import dd_from_longdouble
        from pint_tpu.phase import Phase, phase_add_dd

        p = Phase(np.array([100.0]), np.array([0.25]))
        x = dd_from_longdouble(np.longdouble("2.249999999999999"))
        q = phase_add_dd(p, x)
        total = np.asarray(q.int_, dtype=np.longdouble) \
            + np.asarray(q.frac, dtype=np.longdouble)
        assert abs(float(total[0] - np.longdouble("102.5"))) < 1e-12
        assert np.all(np.abs(np.asarray(q.frac)) <= 0.5)

    def test_pint_matrix_helpers(self, sim):
        from pint_tpu.pint_matrix import DesignMatrixMaker

        m, t = sim
        d1 = DesignMatrixMaker("toa", "s")(t, m, ("F0", "F1"))
        d2 = DesignMatrixMaker("toa", "s")(t, m, ("DM",))
        both = d1.append_along_axis(d2, axis=1)
        assert both.shape == (d1.shape[0], d1.shape[1] + d2.shape[1])
        names = both.get_unique_label_names()
        assert "F0" in names and "DM" in names
        units = d1.param_units  # property
        assert len(units) == len(d1.derivative_params)

    def test_toa_select_helpers(self, sim):
        from pint_tpu.toa_select import TOASelect

        sel = TOASelect(is_range=True)
        assert sel.get_has_key("EFAC", 1) == "EFAC1"

        # first sighting -> changed; second identical -> unchanged
        class Named(np.ndarray):
            pass

        arr = np.array([1.0, 2.0]).view(Named)
        arr.name = "mjd"
        assert sel.check_table_column(arr) is False
        assert sel.check_table_column(arr) is True

    def test_sampler_is_initialized(self):
        # is_initialized lives on the EmceeSampler adapter, whose
        # constructor requires the (absent) emcee package: assert the
        # method exists and reflects self.sampler without instantiating
        from pint_tpu.sampler import EmceeSampler

        probe = type("P", (), {"sampler": None})()
        assert EmceeSampler.is_initialized(probe) is False
        probe.sampler = object()
        assert EmceeSampler.is_initialized(probe) is True


class TestPolycosEval:
    def test_evalphase_and_freq_derivative(self, sim):
        m, _ = sim
        from pint_tpu.polycos import Polycos

        p = Polycos.generate_polycos(m, 53800.0, 53801.0, "@", 60, 8, 1400.0)
        ts = np.linspace(53800.1, 53800.9, 5)
        fr = p.eval_phase(ts)
        assert np.all((fr >= 0) & (fr < 1))
        fd = p.eval_spin_freq_derivative(ts)
        # the polynomial's second derivative over a 1-day segment is
        # fit-wiggle-dominated at the 1e-15 Hz/s scale; assert the
        # evaluation works and stays at that physical magnitude
        assert fd.shape == ts.shape and np.all(np.isfinite(fd))
        assert np.all(np.abs(fd) < 1e-12)
        # the per-entry spelling (reference PolycoEntry.evalphase)
        e = p.entries[0]
        span_mid = np.array([e.tmid])
        fe = e.evalphase(span_mid)
        assert fe.shape == (1,) and 0 <= float(fe[0]) < 1


class TestTemplatesSurface:
    def test_lctemplate_helpers(self, tmp_path):
        from pint_tpu.templates.lcprimitives import LCGaussian
        from pint_tpu.templates.lctemplate import LCTemplate

        t = LCTemplate([LCGaussian(p=[0.03, 0.3]),
                        LCGaussian(p=[0.05, 0.7])], [0.4, 0.3])
        assert t.has_bridge() is False
        ph = np.linspace(0, 1, 200, endpoint=False)
        mv = t.mean_value(ph)
        assert mv == pytest.approx(1.0, rel=0.05)  # density integrates to 1
        m0 = t.mean_single_component(0, ph)
        assert m0 > 0
        out = tmp_path / "prof.txt"
        t.write_profile(str(out))
        txt = out.read_text()
        assert "phas" in txt and "fwhm" in txt

    def test_norm_angles_and_fitter_noop(self, sim):
        from pint_tpu.templates.lcnorm import NormAngles
        from pint_tpu.templates.lcfitters import LCFitter
        from pint_tpu.templates.lcprimitives import LCGaussian
        from pint_tpu.templates.lctemplate import LCTemplate

        na = NormAngles([0.4, 0.3])
        bounds = na.get_bounds()
        assert len(bounds) == int(np.sum(na.free))
        assert all(lo == 0.0 and hi == pytest.approx(np.pi / 2)
                   for lo, hi in bounds)
        assert na.sanity_checks() is True

        t = LCTemplate([LCGaussian(p=[0.03, 0.5])], [0.6])
        rng = np.random.default_rng(9)
        ph = (0.5 + 0.03 * rng.standard_normal(200)) % 1.0
        f = LCFitter(t, ph)
        f.remap_errors()  # parity no-op must exist and not raise


class TestPintkAndScriptsSurface:
    def test_colormode_display_info(self):
        from pint_tpu.pintk.colormodes import FreqMode
        from pint_tpu.pintk.pulsar import Pulsar

        psr = Pulsar(NGC_PAR, NGC_TIM)
        info = FreqMode().display_info(psr)
        assert "mode" in info

    def test_zima_plot(self, sim, tmp_path, monkeypatch):
        import matplotlib

        matplotlib.use("Agg")
        m, t = sim
        from pint_tpu.scripts.zima import plot_simulated_toas

        plot_simulated_toas(t, m)  # must draw without a display
