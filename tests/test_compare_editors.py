"""compare() sigma columns + pintk par/tim editors + random overlay data
(VERDICT r2 directive #10; reference ``timing_model.py:2293``,
``pintk/paredit.py``, ``pintk/timedit.py``)."""

import os

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"

pytestmark = pytest.mark.skipif(not os.path.exists(NGC_TIM),
                                reason="reference data unavailable")


@pytest.fixture(scope="module")
def psr():
    from pint_tpu.pintk.pulsar import Pulsar

    return Pulsar(NGC_PAR, NGC_TIM)


class TestCompare:
    def test_sigma_columns(self):
        import copy

        from pint_tpu.models import get_model

        m1 = get_model(NGC_PAR)
        m1.F0.uncertainty = 1e-10
        m2 = copy.deepcopy(m1)
        m2.F0.value = float(m1.F0.value) + 5e-10  # a 5-sigma change
        out = m1.compare(m2)
        assert "Diff_Sigma1" in out and "Diff_Sigma2" in out
        f0_row = next(ln for ln in out.splitlines() if ln.startswith("F0"))
        assert "5.000" in f0_row and f0_row.rstrip().endswith("!")
        assert "parameters changed by >= 3.0 sigma: F0" in out

    def test_verbosity_levels(self):
        import copy

        from pint_tpu.models import get_model

        m1 = get_model(NGC_PAR)
        m1.F0.uncertainty = 1e-10
        m2 = copy.deepcopy(m1)
        m2.F0.value = float(m1.F0.value) + 5e-10
        assert m1.compare(m2, verbosity="check").strip() == "F0"
        out_min = m1.compare(m2, verbosity="min")
        assert "F0" in out_min and "DECJ" not in out_min
        out_med = m1.compare(m2, verbosity="med")
        assert "F0" in out_med


class TestParEditor:
    def test_edit_apply_changes_model(self, psr):
        from pint_tpu.pintk.paredit import ParEditor

        ed = ParEditor(psr)
        assert "F0" in ed.text
        new_f0 = 61.48547
        lines = [(f"F0 {new_f0} 1" if ln.split() and ln.split()[0] == "F0"
                  else ln) for ln in ed.text.splitlines()]
        ed.set_text("\n".join(lines) + "\n")
        ed.apply()
        assert float(psr.model.F0.value) == pytest.approx(new_f0)
        psr.reset_model()

    def test_invalid_par_rejected_without_side_effects(self, psr):
        from pint_tpu.pintk.paredit import ParEditor

        ed = ParEditor(psr)
        before = float(psr.model.F0.value)
        ed.set_text("PSR BROKEN\nRAJ not-an-angle\n")
        with pytest.raises(Exception):
            ed.apply()
        assert float(psr.model.F0.value) == before

    def test_write_and_load(self, psr, tmp_path):
        from pint_tpu.pintk.paredit import ParEditor

        ed = ParEditor(psr)
        p = str(tmp_path / "out.par")
        ed.write(p)
        ed2 = ParEditor(psr)
        assert "F0" in ed2.load(p)


class TestTimEditor:
    def test_edit_apply_changes_toas(self, psr):
        from pint_tpu.pintk.timedit import TimEditor

        ed = TimEditor(psr)
        n0 = len(psr.all_toas)
        # drop the last TOA line
        lines = [ln for ln in ed.text.splitlines() if ln.strip()]
        ed.set_text("\n".join(lines[:-1]) + "\n")
        ed.apply()
        assert len(psr.all_toas) == n0 - 1
        psr.reset_TOAs()
        assert len(psr.all_toas) == n0


class TestRandomOverlayData:
    def test_random_models_shape(self, psr):
        psr.fit()
        dphase, models = psr.random_models(nmodels=5)
        assert dphase.shape == (5, len(psr.all_toas))
        assert np.all(np.isfinite(dphase))
        assert len(models) == 5
        # draws scatter roughly like the parameter covariance: nonzero
        assert np.any(np.abs(dphase) > 0)
