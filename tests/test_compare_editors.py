"""compare() sigma columns + pintk par/tim editors + random overlay data
(VERDICT r2 directive #10; reference ``timing_model.py:2293``,
``pintk/paredit.py``, ``pintk/timedit.py``)."""

import os

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"

pytestmark = pytest.mark.skipif(not os.path.exists(NGC_TIM),
                                reason="reference data unavailable")


@pytest.fixture(scope="module")
def psr():
    from pint_tpu.pintk.pulsar import Pulsar

    return Pulsar(NGC_PAR, NGC_TIM)


class TestCompare:
    def test_sigma_columns(self):
        import copy

        from pint_tpu.models import get_model

        m1 = get_model(NGC_PAR)
        m1.F0.uncertainty = 1e-10
        m2 = copy.deepcopy(m1)
        m2.F0.value = float(m1.F0.value) + 5e-10  # a 5-sigma change
        out = m1.compare(m2)
        assert "Diff_Sigma1" in out and "Diff_Sigma2" in out
        f0_row = next(ln for ln in out.splitlines() if ln.startswith("F0"))
        assert "5.000" in f0_row and f0_row.rstrip().endswith("!")
        assert "parameters changed by >= 3.0 sigma: F0" in out

    def test_verbosity_levels(self):
        import copy

        from pint_tpu.models import get_model

        m1 = get_model(NGC_PAR)
        m1.F0.uncertainty = 1e-10
        m2 = copy.deepcopy(m1)
        m2.F0.value = float(m1.F0.value) + 5e-10
        assert m1.compare(m2, verbosity="check").strip() == "F0"
        out_min = m1.compare(m2, verbosity="min")
        assert "F0" in out_min and "DECJ" not in out_min
        out_med = m1.compare(m2, verbosity="med")
        assert "F0" in out_med


class TestParEditor:
    def test_edit_apply_changes_model(self, psr):
        from pint_tpu.pintk.paredit import ParEditor

        ed = ParEditor(psr)
        assert "F0" in ed.text
        new_f0 = 61.48547
        lines = [(f"F0 {new_f0} 1" if ln.split() and ln.split()[0] == "F0"
                  else ln) for ln in ed.text.splitlines()]
        ed.set_text("\n".join(lines) + "\n")
        ed.apply()
        assert float(psr.model.F0.value) == pytest.approx(new_f0)
        psr.reset_model()

    def test_invalid_par_rejected_without_side_effects(self, psr):
        from pint_tpu.pintk.paredit import ParEditor

        ed = ParEditor(psr)
        before = float(psr.model.F0.value)
        ed.set_text("PSR BROKEN\nRAJ not-an-angle\n")
        with pytest.raises(Exception):
            ed.apply()
        assert float(psr.model.F0.value) == before

    def test_write_and_load(self, psr, tmp_path):
        from pint_tpu.pintk.paredit import ParEditor

        ed = ParEditor(psr)
        p = str(tmp_path / "out.par")
        ed.write(p)
        ed2 = ParEditor(psr)
        assert "F0" in ed2.load(p)


class TestTimEditor:
    def test_edit_apply_changes_toas(self, psr):
        from pint_tpu.pintk.timedit import TimEditor

        ed = TimEditor(psr)
        n0 = len(psr.all_toas)
        # drop the last TOA line
        lines = [ln for ln in ed.text.splitlines() if ln.strip()]
        ed.set_text("\n".join(lines[:-1]) + "\n")
        ed.apply()
        assert len(psr.all_toas) == n0 - 1
        psr.reset_TOAs()
        assert len(psr.all_toas) == n0


class TestRandomOverlayData:
    def test_random_models_shape(self, psr):
        psr.fit()
        dphase, models = psr.random_models(nmodels=5)
        assert dphase.shape == (5, len(psr.all_toas))
        assert np.all(np.isfinite(dphase))
        assert len(models) == 5
        # draws scatter roughly like the parameter covariance: nonzero
        assert np.any(np.abs(dphase) > 0)


class TestColorModes:
    def test_default_and_freq(self, psr):
        from pint_tpu.pintk.colormodes import (DefaultMode, FreqMode,
                                               get_color_mode)

        n = len(psr.all_toas)
        colors, legend = DefaultMode().get_colors(psr)
        assert len(colors) == n and len(set(colors)) == 1
        colors, legend = FreqMode().get_colors(psr)
        assert len(colors) == n
        # every color used appears in the legend
        assert set(colors) <= set(legend.values())
        # NGC6440E is ~1400-2000 MHz: bands restricted to those edges
        freqs = np.asarray(psr.all_toas.freq_mhz)
        assert all(("1000-1800" in l or "1800-3000" in l or "MHz" in l)
                   for l in legend)

    def test_selected_overrides(self, psr):
        from pint_tpu.pintk.colormodes import SELECTED_COLOR, DefaultMode

        sel = np.zeros(len(psr.all_toas), dtype=bool)
        sel[:5] = True
        colors, legend = DefaultMode().get_colors(psr, sel)
        assert (colors[:5] == SELECTED_COLOR).all()
        assert (colors[5:] != SELECTED_COLOR).all()
        assert legend["selected"] == SELECTED_COLOR

    def test_obs_and_name_modes(self, psr):
        from pint_tpu.pintk.colormodes import NameMode, ObsMode

        colors, legend = ObsMode().get_colors(psr)
        # NGC6440E TOAs are all GBT -> single "gb" group, green
        assert set(legend) == {"gb"}
        assert len(set(colors)) == 1
        colors, legend = NameMode().get_colors(psr)
        assert set(colors) <= set(legend.values())

    def test_jump_mode_colors_jumped_toas(self, psr):
        from pint_tpu.pintk.colormodes import JumpMode

        sel = np.zeros(len(psr.all_toas), dtype=bool)
        sel[10:20] = True
        name = psr.add_jump(sel)
        try:
            colors, legend = JumpMode().get_colors(psr)
            assert name in legend
            # the unconfigured placeholder JUMP1 must not appear
            assert "JUMP1" not in legend
            jumped = np.asarray([c == legend[name] for c in colors])
            assert jumped.sum() == 10 and jumped[10:20].all()
        finally:
            psr.reset_model()

    def test_groups_partition_toas(self, psr):
        """get_groups masks are disjoint and cover every TOA even when
        palette colors repeat across labels."""
        from pint_tpu.pintk.colormodes import COLOR_MODES

        n = len(psr.all_toas)
        sel = np.zeros(n, dtype=bool)
        sel[::7] = True
        for name, cls in COLOR_MODES.items():
            total = np.zeros(n, dtype=int)
            for _lbl, _c, m in cls().get_groups(psr, sel):
                total += m.astype(int)
            assert (total == 1).all(), name

    def test_unknown_mode_raises(self):
        from pint_tpu.pintk.colormodes import get_color_mode
        import pytest as _pt

        with _pt.raises(ValueError):
            get_color_mode("nope")


class TestPulsarHelpers:
    def test_axes_helpers(self, psr):
        from pint_tpu.pintk.pulsar import Pulsar

        yr = psr.year()
        assert len(yr) == len(psr.all_toas)
        assert np.all((yr > 1990) & (yr < 2030))
        doy = psr.dayofyear()
        assert np.all((doy >= 0) & (doy < 366.0))
        # NGC6440E is isolated: orbital phase warns and returns zeros
        assert np.all(psr.orbitalphase() == 0.0)

    def test_print_chi2_and_reset(self, psr):
        text = psr.print_chi2()
        assert "Chisq" in text and "d.o.f" in text
        sel = np.zeros(len(psr.all_toas), dtype=bool)
        sel[:10] = True
        assert "d.o.f" in psr.print_chi2(sel)
        psr.fit()
        assert psr.fitted
        psr.resetAll()
        assert not psr.fitted
        assert float(psr.model.F0.value) == float(psr.model_init.F0.value)

    def test_add_model_params_extends_spindown(self, psr):
        before = [p for p in psr.model.params if p.startswith("F")
                  and p[1:].isdigit()]
        psr.add_model_params()
        after = [p for p in psr.model.params if p.startswith("F")
                 and p[1:].isdigit()]
        # F0/F1 free in NGC6440E -> F2 appears, frozen at zero
        assert len(after) == len(before) + 1
        newp = sorted(after, key=lambda p: int(p[1:]))[-1]
        assert getattr(psr.model, newp).frozen
        assert float(getattr(psr.model, newp).value) == 0.0
        psr.resetAll()

    def test_print_chi2_index_array_with_zero(self, psr):
        """Regression: an index array containing 0 is a real selection,
        not 'select nothing'."""
        full = psr.print_chi2()
        one = psr.print_chi2(np.array([0]))
        assert one != full
        assert "for -1 d.o.f" in one or "d.o.f" in one

    def test_add_model_params_par_with_only_f0(self, tmp_path):
        """Regression: a par stopping at F0 offers F1 (value-None F1 exists
        structurally but must not block the offer)."""
        from pint_tpu.pintk.pulsar import Pulsar
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        par = tmp_path / "f0only.par"
        par.write_text("PSR F0ONLY\nRAJ 03:00:00\nDECJ 3:00:00\n"
                       "F0 99.0 1\nPEPOCH 55100\nDM 10\nUNITS TDB\n")
        m = get_model(str(par))
        t = make_fake_toas_uniform(55000, 55100, 8, m, error_us=1.0)
        tim = tmp_path / "f0only.tim"
        t.write_TOA_file(str(tim))
        psr = Pulsar(str(par), str(tim))
        assert psr.model.F1.value is None
        psr.add_model_params()
        assert float(psr.model.F1.value) == 0.0
        assert psr.model.F1.frozen
