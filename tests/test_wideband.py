"""Wideband (TOA+DM) residuals and fitters — simulation-as-fixture tests
mirroring the reference strategy (SURVEY §4; reference tests
test_wideband_dm_data.py / test_widebandTOA_fitting.py)."""

import numpy as np
import pytest

PAR_WB = """
PSR  J1234+5678
RAJ  12:34:00.0
DECJ 56:78:00.0  # parsed as degrees:arcmin (test value)
POSEPOCH 55000
F0   123.456789012345 1
F1   -1.0e-14 1
PEPOCH 55000
DM   12.345 1
DM1  1e-4 1
DMEPOCH 55000
DMJUMP -fe L-wide 0.002 1
EPHEM DE440
CLOCK TT(BIPM2021)
UNITS TDB
"""


def _get_model(text):
    from pint_tpu.models import get_model

    return get_model([ln + "\n" for ln in text.strip().splitlines()])


@pytest.fixture(scope="module")
def wb_model():
    return _get_model(PAR_WB)


@pytest.fixture(scope="module")
def wb_toas(wb_model):
    from pint_tpu.simulation import make_fake_toas, make_fake_toas_uniform

    ts = make_fake_toas_uniform(
        54000, 56000, 60, wb_model, freq=np.array([430.0, 1400.0]),
        error_us=2.0, rng=np.random.default_rng(42))
    # put half the TOAs in the DMJUMP system *before* simulating DM data
    for i, fl in enumerate(ts.flags):
        fl["fe"] = "L-wide" if i % 2 else "430"
    ts._version += 1
    return make_fake_toas(ts, wb_model, add_noise=True, wideband=True,
                          rng=np.random.default_rng(42))


class TestWidebandData:
    def test_flags_roundtrip(self, wb_toas, tmp_path):
        from pint_tpu.toa import get_TOAs

        assert wb_toas.wideband
        p = tmp_path / "wb.tim"
        wb_toas.write_TOA_file(str(p))
        t2 = get_TOAs(str(p))
        assert t2.wideband
        np.testing.assert_allclose(t2.get_dms(), wb_toas.get_dms(), rtol=1e-12)
        np.testing.assert_allclose(t2.get_dm_errors(), wb_toas.get_dm_errors())

    def test_total_dm_matches_injection(self, wb_model, wb_toas):
        # simulated DMs = model DM + noise(1e-4): residual scatter ~ pp_dme
        dm_model = wb_model.total_dm(wb_toas)
        r = wb_toas.get_dms() - dm_model
        assert np.std(r) < 5e-4
        # DMJUMP shifts the *model* DM (negative sign, reference
        # dispersion_model.py:782) on the selected system only
        mask = np.array([fl["fe"] == "L-wide" for fl in wb_toas.flags])
        m2 = _get_model(PAR_WB.replace("DMJUMP -fe L-wide 0.002", "DMJUMP -fe L-wide 0.0"))
        dm_nojump = m2.total_dm(wb_toas)
        d = dm_model - dm_nojump
        np.testing.assert_allclose(d[mask], -0.002, rtol=1e-10)
        np.testing.assert_allclose(d[~mask], 0.0, atol=1e-14)

    def test_dm_jacobian_vs_finite_difference(self, wb_model, wb_toas):
        for par, scale in [("DM", 1e-6), ("DM1", 1e-8), ("DMJUMP1", 1e-6)]:
            a = wb_model.d_dm_d_param(wb_toas, par)
            p = getattr(wb_model, par)
            v0 = float(p.value)
            p.value = v0 + scale
            hi = wb_model.total_dm(wb_toas)
            p.value = v0 - scale
            lo = wb_model.total_dm(wb_toas)
            p.value = v0
            num = (hi - lo) / (2 * scale)
            np.testing.assert_allclose(a, num, atol=1e-6)

    def test_dmjump_no_delay(self, wb_model, wb_toas):
        # DMJUMP must not disperse the TOAs (reference dispersion_model.py:737)
        d1 = wb_model.delay(wb_toas)
        m2 = _get_model(PAR_WB.replace("DMJUMP -fe L-wide 0.002", "DMJUMP -fe L-wide 0.05"))
        d2 = m2.delay(wb_toas)
        np.testing.assert_allclose(d1, d2, atol=1e-12)


class TestWidebandResiduals:
    def test_residual_objects(self, wb_model, wb_toas):
        from pint_tpu.wideband import WidebandTOAResiduals

        r = WidebandTOAResiduals(wb_toas, wb_model)
        assert len(r._combined_resids) == 2 * len(wb_toas)
        assert r.dm.resids.std() < 5e-4
        assert np.isfinite(r.chi2)
        # dm chi2 roughly ~ N for correctly-scaled noise
        assert 0.3 < r.dm.calc_chi2() / len(wb_toas) < 3.0

    def test_dmefac_scaling(self, wb_model, wb_toas):
        m = _get_model(PAR_WB + "\nDMEFAC -fe L-wide 2.0\nDMEQUAD -fe 430 0.001\n")
        base = np.asarray(wb_toas.get_dm_errors())
        scaled = m.scaled_dm_uncertainty(wb_toas)
        mask = np.array([fl["fe"] == "L-wide" for fl in wb_toas.flags])
        np.testing.assert_allclose(scaled[mask], 2.0 * base[mask], rtol=1e-12)
        np.testing.assert_allclose(scaled[~mask],
                                   np.sqrt(base[~mask] ** 2 + 0.001**2), rtol=1e-12)


class TestWidebandFitter:
    def test_recovers_perturbed_params(self, wb_model, wb_toas):
        from pint_tpu.wideband import WidebandTOAFitter

        m = _get_model(PAR_WB)
        m.F0.value = m.F0.value + 2e-10
        m.DM.value = m.DM.value + 5e-3
        m.DMJUMP1.value = 0.0
        f = WidebandTOAFitter(wb_toas, m)
        chi2 = f.fit_toas(maxiter=3)
        assert abs(f.model.F0.value - wb_model.F0.value) < 5 * f.errors["F0"]
        assert abs(f.model.DM.value - wb_model.DM.value) < 5 * f.errors["DM"]
        # DMJUMP is constrained by the DM data block
        assert abs(f.model.DMJUMP1.value - 0.002) < 5 * f.errors["DMJUMP1"]
        assert 0.5 < chi2 / f.resids.dof < 2.0
        # the derived-params report handles the wideband rms dict
        s = f.get_summary()
        assert "Derived Parameters" in s and "nan" not in s.lower()

    def test_downhill_matches_oneshot(self, wb_toas):
        from pint_tpu.wideband import WidebandDownhillFitter, WidebandTOAFitter

        m1 = _get_model(PAR_WB)
        m1.F0.value += 1e-10
        m2 = _get_model(PAR_WB)
        m2.F0.value += 1e-10
        f1 = WidebandTOAFitter(wb_toas, m1)
        c1 = f1.fit_toas(maxiter=4)
        f2 = WidebandDownhillFitter(wb_toas, m2)
        c2 = f2.fit_toas(maxiter=15)
        assert abs(c1 - c2) / c1 < 1e-3
        assert abs(f1.model.F0.value - f2.model.F0.value) < 1e-13

    def test_full_cov_matches_woodbury(self, wb_toas):
        from pint_tpu.wideband import WidebandTOAFitter

        m1 = _get_model(PAR_WB)
        m2 = _get_model(PAR_WB)
        f1 = WidebandTOAFitter(wb_toas, m1)
        c1 = f1.fit_toas(maxiter=2, full_cov=False)
        f2 = WidebandTOAFitter(wb_toas, m2)
        c2 = f2.fit_toas(maxiter=2, full_cov=True)
        assert abs(c1 - c2) / c1 < 1e-6
        assert abs(f1.model.F0.value - f2.model.F0.value) < 1e-14

    def test_auto_dispatch(self, wb_model, wb_toas):
        from pint_tpu.fitter import Fitter
        from pint_tpu.wideband import WidebandDownhillFitter, WidebandTOAFitter

        f = Fitter.auto(wb_toas, wb_model)
        assert isinstance(f, WidebandDownhillFitter)
        f = Fitter.auto(wb_toas, wb_model, downhill=False)
        assert isinstance(f, WidebandTOAFitter)


class TestFDJumpDM:
    def test_fdjumpdm_has_delay_and_dm(self):
        from pint_tpu.simulation import make_fake_toas_uniform

        par = PAR_WB.replace("DMJUMP -fe L-wide 0.002 1",
                             "FDJUMPDM -fe L-wide 0.01 1")
        m = _get_model(par)
        assert "FDJumpDM" in m.components
        ts = make_fake_toas_uniform(54000, 55000, 20, m, freq=1400.0,
                                    error_us=1.0, wideband=True,
                                    rng=np.random.default_rng(0))
        for i, fl in enumerate(ts.flags):
            fl["fe"] = "L-wide" if i % 2 else "430"
        ts._version += 1
        mask = np.array([fl["fe"] == "L-wide" for fl in ts.flags])
        # DM value offset is -FDJUMPDM on selected TOAs
        m0 = _get_model(par.replace("FDJUMPDM -fe L-wide 0.01", "FDJUMPDM -fe L-wide 0.0"))
        ddm = m.total_dm(ts) - m0.total_dm(ts)
        np.testing.assert_allclose(ddm[mask], -0.01, rtol=1e-10)
        # and unlike DMJUMP it does delay the TOAs
        dd = m.delay(ts) - m0.delay(ts)
        assert np.all(np.abs(dd[mask]) > 1e-7)
        np.testing.assert_allclose(dd[~mask], 0.0, atol=1e-12)
