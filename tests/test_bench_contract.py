"""bench.py output contract (PR 5 acceptance pin).

The bench must print EXACTLY ONE JSON line on stdout (the driver tails
output; a duplicate mid-run emit doubled every artifact's tail), with
human-readable stage chatter on stderr only, and the headline must
carry a ``cost`` block with FLOPs/bytes fields (or explicit nulls) for
the grid executable.  The real B1855 datafiles are not present in the
test image, so the headline workload is pointed at a synthetic
DD-binary + correlated-noise stand-in with the same structure (M2/SINI
grid over a GLS model).
"""

import io
import json
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.perfwatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TINY_GLS_PAR = """\
PSR BENCHTINY
RAJ 04:37:15.0
DECJ -47:15:09.0
F0 173.6879 1
F1 -1.7e-15 1
PEPOCH 55000
DM 2.64 1
BINARY DD
PB 5.7410
A1 3.3667
T0 55000.0
OM 1.35
ECC 1.9e-5
M2 0.3 1
SINI 0.95 1
EFAC mjd 50000 60000 1.1
ECORR mjd 50000 60000 0.5
TNRedAmp -13.5
TNRedGam 3.5
TNRedC 5
UNITS TDB
"""


@pytest.fixture
def tiny_headline_files(tmp_path):
    par = tmp_path / "tiny.par"
    par.write_text(TINY_GLS_PAR)
    mjds = np.linspace(54000, 56000, 40)
    lines = ["FORMAT 1\n"]
    # two frequencies so DM is constrained; 0.1 us errors so the
    # Shapiro-range M2/SINI pair is measurable at this TOA count
    for i, m in enumerate(mjds):
        lines.append(f"fakeA{i} 1400.0 {m:.13f} 0.1 gbt\n")
        lines.append(f"fakeB{i} 2300.0 {m + 0.01:.13f} 0.1 gbt\n")
    tim = tmp_path / "tiny.tim"
    tim.write_text("".join(lines))
    return str(par), str(tim)


def test_single_json_line_with_cost(tiny_headline_files, monkeypatch,
                                    capsys):
    import bench

    par, tim = tiny_headline_files
    monkeypatch.setattr(bench, "B1855_PAR", par)
    monkeypatch.setattr(bench, "B1855_TIM", tim)
    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    monkeypatch.setenv("BENCH_SKIP_SECONDARY", "1")
    # small catalog/flow: the contract is the blocks' shape, not scale
    monkeypatch.setenv("BENCH_CATALOG_PULSARS", "4")
    monkeypatch.setenv("BENCH_POSTERIOR_STEPS", "8")
    monkeypatch.setenv("BENCH_SCALING_PULSARS", "3")
    monkeypatch.setenv("BENCH_STREAM_TOAS", "192")
    monkeypatch.setenv("BENCH_STREAM_BLOCK", "8")
    monkeypatch.setenv("BENCH_STREAM_APPENDS", "3")
    monkeypatch.setenv("BENCH_STREAM_REFITS", "1")
    monkeypatch.setenv("BENCH_SLO_TRAIN_STEPS", "4")
    monkeypatch.setenv("BENCH_SLO_REQUESTS", "12")
    monkeypatch.delenv("BENCH_REQUIRE_TPU", raising=False)
    monkeypatch.delenv("PINT_TPU_TELEMETRY", raising=False)
    try:
        bench.main()
    finally:
        from pint_tpu import telemetry

        telemetry.deactivate()
    out = capsys.readouterr().out
    lines = [ln for ln in out.splitlines() if ln.strip()]
    # EXACTLY one stdout line, and it is the headline JSON
    assert len(lines) == 1, f"stdout must be one JSON line, got: {lines}"
    headline = json.loads(lines[0])
    assert headline["metric"] == "gls_chisq_grid_evals_per_sec"
    assert headline["value"] > 0
    # the cost block: FLOPs/bytes fields present (numbers or explicit
    # nulls — never absent) for the grid executable
    cost = headline["cost"]
    assert cost["name"] == "grid.chunk"
    for key in ("flops", "bytes_accessed", "temp_bytes", "peak_bytes",
                "argument_bytes", "output_bytes"):
        assert key in cost
        assert cost[key] is None or isinstance(cost[key], (int, float))
    # on the CPU backend the analysis genuinely reports numbers
    assert cost["flops"] and cost["bytes_accessed"]
    # the telemetry block rode along as before
    assert headline["telemetry"]["jax"]["compiles"] > 0
    # the warm-serving block (PR 8): AOT-cache provenance counters plus
    # steady-state throughput/latency — every key present, and with no
    # AOT cache configured everything was a fresh compile
    warm = headline["warm"]
    for key in ("cache_hits", "cold_compiles", "warm_fits_per_s",
                "p50_ms", "p99_ms"):
        assert key in warm, f"warm block missing {key!r}"
    assert "error" not in warm, f"warm measurement degraded: {warm}"
    assert warm["cache_hits"] == 0
    assert warm["cold_compiles"] >= 1
    assert warm["warm_fits_per_s"] > 0
    assert warm["p50_ms"] > 0 and warm["p99_ms"] >= warm["p50_ms"]
    # the ROADMAP's steady-state proof: the timed serving pass paid no
    # fresh XLA compiles (the bucket executable was pre-warmed)
    assert warm["steady_state_compiles"] == 0
    # the tuned block (PR 10): the cost-model autotuner's chunk search
    # ran next to the headline — every key present, never degraded on
    # CPU, and the never-slower contract holds structurally
    tuned = headline["tuned"]
    for key in ("chunk", "static_chunk", "tuned_fits_per_s",
                "static_fits_per_s", "tuned_vs_static", "basis",
                "decisions"):
        assert key in tuned, f"tuned block missing {key!r}"
    assert "error" not in tuned, f"tuned measurement degraded: {tuned}"
    assert tuned["static_chunk"] == 256
    assert tuned["tuned_fits_per_s"] > 0
    assert tuned["tuned_vs_static"] >= 1.0
    assert tuned["basis"] == "cost+measured"
    assert isinstance(tuned["decisions"], str) and tuned["decisions"]
    # the precision block (PR 12): resolved per-segment policy and the
    # forced-f64 vs active-policy serve comparison — with no manifest
    # configured the active policy IS f64, so the comparison is
    # bit-identical (max_rel_err exactly 0.0, zero reduced segments)
    prec = headline["precision"]
    for key in ("segments", "reduced_count", "f64_count",
                "mixed_fits_per_s", "f64_fits_per_s", "mixed_vs_f64",
                "max_rel_err"):
        assert key in prec, f"precision block missing {key!r}"
    assert "error" not in prec, f"precision measurement degraded: {prec}"
    assert prec["reduced_count"] == 0
    assert prec["f64_count"] == len(prec["segments"])
    assert prec["mixed_fits_per_s"] > 0 and prec["f64_fits_per_s"] > 0
    assert prec["max_rel_err"] == 0.0
    # the catalog block (PR 11): the PTA catalog engine's batched
    # multi-pulsar fit + joint Hellings-Downs lnlikelihood ran next to
    # the headline — every key present, never degraded on CPU, zero
    # steady-state compiles across buckets
    catalog = headline["catalog"]
    for key in ("n_pulsars", "buckets", "pad_waste_frac",
                "catalog_fits_per_s", "joint_lnlike_per_s",
                "steady_state_compiles"):
        assert key in catalog, f"catalog block missing {key!r}"
    assert "error" not in catalog, \
        f"catalog measurement degraded: {catalog}"
    assert catalog["n_pulsars"] >= 4
    assert catalog["buckets"] >= 1
    assert 0.0 <= catalog["pad_waste_frac"] < 1.0
    assert catalog["catalog_fits_per_s"] > 0
    assert catalog["joint_lnlike_per_s"] > 0
    assert catalog["steady_state_compiles"] == 0
    # the scaling block (PR 14): the work-per-byte plans' fused
    # dispatch rate measured live, plus the committed scalewatch
    # series' efficiency / scatter bytes restamped for perfwatch
    scaling = headline["scaling"]
    for key in ("efficiency_at_max", "dispatch_per_s", "scatter_bytes"):
        assert key in scaling, f"scaling block missing {key!r}"
    assert "error" not in scaling, \
        f"scaling measurement degraded: {scaling}"
    assert scaling["dispatch_per_s"] > 0
    assert scaling["efficiency_at_max"] is None \
        or scaling["efficiency_at_max"] > 0
    # the posterior block (PR 13): the amortized engine trained a flow
    # and served draws + log-probs through the posterior door — every
    # key present, never degraded on CPU, zero steady-state compiles
    posterior = headline["posterior"]
    for key in ("train_steps", "elbo_final", "draws_per_s",
                "logprob_per_s", "p50_ms", "p99_ms",
                "steady_state_compiles"):
        assert key in posterior, f"posterior block missing {key!r}"
    assert "error" not in posterior, \
        f"posterior measurement degraded: {posterior}"
    assert posterior["train_steps"] == 8
    assert posterior["draws_per_s"] > 0
    assert posterior["logprob_per_s"] > 0
    assert posterior["p50_ms"] > 0
    assert posterior["p99_ms"] >= posterior["p50_ms"]
    assert posterior["steady_state_compiles"] == 0
    # the streaming block (PR 15): appended TOA blocks served through
    # the update door as rank-k factor updates — every key present,
    # never degraded on CPU, zero steady-state compiles, and the
    # update path measurably faster than the warm full-refit path
    # (the 10x acceptance bar applies to the production-scale
    # workload; this contract-scale stand-in still must win)
    streaming = headline["streaming"]
    for key in ("appends", "update_p50_ms", "update_p99_ms",
                "updates_per_s", "refit_p50_ms", "speedup_vs_refit",
                "steady_state_compiles"):
        assert key in streaming, f"streaming block missing {key!r}"
    assert "error" not in streaming, \
        f"streaming measurement degraded: {streaming}"
    assert streaming["appends"] == 3
    assert streaming["updates_per_s"] > 0
    assert streaming["update_p50_ms"] > 0
    assert streaming["update_p99_ms"] >= streaming["update_p50_ms"]
    assert streaming["refit_p50_ms"] > 0
    assert streaming["speedup_vs_refit"] > 1.0
    assert streaming["steady_state_compiles"] == 0
    # the recovery block (PR 17): journal -> crash -> recover ->
    # drill-under-fault; never degraded on CPU, recovery must land
    # bitwise and the drill must strand nothing
    recovery = headline["recovery"]
    for key in ("ops_journaled", "time_to_recover_s",
                "replay_ops_per_s", "bitwise_match", "rps_under_fault",
                "stranded_futures", "drill_recovery_s", "scenario"):
        assert key in recovery, f"recovery block missing {key!r}"
    assert "error" not in recovery, \
        f"recovery measurement degraded: {recovery}"
    assert recovery["ops_journaled"] > 0
    assert recovery["time_to_recover_s"] > 0
    assert recovery["replay_ops_per_s"] > 0
    assert recovery["bitwise_match"] is True
    assert recovery["stranded_futures"] == 0
    # the predict block (PR 19): the phase-prediction door served a
    # warmed, fully-built predictor cache — never degraded on CPU,
    # all-hit steady state, zero steady-state compiles
    predict = headline["predict"]
    for key in ("windows", "predicts_per_s", "cache_hit_rate",
                "p50_ms", "p99_ms", "steady_state_compiles"):
        assert key in predict, f"predict block missing {key!r}"
    assert "error" not in predict, \
        f"predict measurement degraded: {predict}"
    assert predict["windows"] >= 1
    assert predict["predicts_per_s"] > 0
    assert predict["cache_hit_rate"] == 1.0
    assert predict["p50_ms"] > 0
    assert predict["p99_ms"] >= predict["p50_ms"]
    assert predict["steady_state_compiles"] == 0
    json.dumps(headline)


def test_warm_block_hits_cache_on_second_run(tiny_headline_files,
                                             monkeypatch, capsys,
                                             tmp_path):
    """With PINT_TPU_AOT_CACHE_DIR set, a second bench run (same
    process here; the cache is keyed for cross-process reuse) loads the
    warmed executables from the AOT cache instead of compiling."""
    import bench
    from pint_tpu import config
    from pint_tpu.serving import aotcache

    par, tim = tiny_headline_files
    monkeypatch.setattr(bench, "B1855_PAR", par)
    monkeypatch.setattr(bench, "B1855_TIM", tim)
    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    monkeypatch.setenv("BENCH_SKIP_SECONDARY", "1")
    monkeypatch.setenv("BENCH_CATALOG_PULSARS", "4")
    monkeypatch.setenv("BENCH_POSTERIOR_STEPS", "8")
    monkeypatch.setenv("BENCH_SCALING_PULSARS", "3")
    monkeypatch.setenv("BENCH_STREAM_TOAS", "192")
    monkeypatch.setenv("BENCH_STREAM_BLOCK", "8")
    monkeypatch.setenv("BENCH_STREAM_APPENDS", "3")
    monkeypatch.setenv("BENCH_STREAM_REFITS", "1")
    monkeypatch.setenv("BENCH_SLO_TRAIN_STEPS", "4")
    monkeypatch.setenv("BENCH_SLO_REQUESTS", "12")
    monkeypatch.delenv("BENCH_REQUIRE_TPU", raising=False)
    monkeypatch.delenv("PINT_TPU_TELEMETRY", raising=False)
    cache_dir = str(tmp_path / "aot")
    config.set_aot_cache_dir(cache_dir)
    try:
        bench.main()
        first = json.loads(capsys.readouterr().out.strip())
        import jax

        jax.clear_caches()
        bench.main()
        second = json.loads(capsys.readouterr().out.strip())
    finally:
        from pint_tpu import telemetry

        telemetry.deactivate()
        config.set_aot_cache_dir(None)
        aotcache.reset_cache_singleton()
    assert first["warm"]["cache_hits"] == 0
    assert first["warm"]["cold_compiles"] >= 1
    # every executable the first run stored now loads: zero cold
    # compiles, and the serving pass still pays no steady-state compile
    assert second["warm"]["cold_compiles"] == 0
    assert second["warm"]["cache_hits"] >= first["warm"]["cold_compiles"]
    assert second["warm"]["steady_state_compiles"] == 0
