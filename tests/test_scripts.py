"""CLI entry points invoked in-process (the reference's CLI test strategy,
SURVEY §4: "each script has a test invoking main(argv)")."""

import io
import os

import numpy as np
import pytest

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"

PAR = """
PSR  J0030+0451
RAJ  00:30:27.4 1
DECJ 04:51:39.7 1
POSEPOCH 55000
F0   205.53069 1
F1   -4.3e-16 1
PEPOCH 55000
DM   4.33 1
UNITS TDB
"""


@pytest.fixture(scope="module")
def workdir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    par = d / "sim.par"
    par.write_text(PAR)
    # simulate a tim file via zima
    from pint_tpu.scripts import zima

    tim = d / "sim.tim"
    assert zima.main([str(par), str(tim), "--startMJD", "55000",
                      "--duration", "200", "--ntoa", "40",
                      "--error", "1.5", "--addnoise", "--seed", "42"]) == 0
    assert tim.exists()
    return d


class TestFitAndConvertCLIs:
    def test_pintempo(self, workdir, capsys):
        from pint_tpu.scripts import pintempo

        out = workdir / "post.par"
        assert pintempo.main([str(workdir / "sim.par"),
                              str(workdir / "sim.tim"),
                              "--outfile", str(out)]) == 0
        cap = capsys.readouterr().out
        assert "Postfit residuals" in cap
        assert out.exists()
        from pint_tpu.models import get_model

        m = get_model(str(out))
        assert abs(float(m.F0.value) - 205.53069) < 1e-6

    def test_pintbary(self, capsys):
        from pint_tpu.scripts import pintbary

        assert pintbary.main(["55500.0", "--obs", "gbt",
                              "--ra", "00:30:27.4", "--dec", "04:51:39.7",
                              "--freq", "1400", "--dm", "4.33"]) == 0
        val = float(capsys.readouterr().out.strip())
        # barycentric time within +/-10 min of topocentric (Roemer + TDB)
        assert abs(val - 55500.0) < 0.01

    def test_convert_parfile_binary(self, workdir, tmp_path):
        from pint_tpu.scripts import convert_parfile

        bpar = tmp_path / "bin.par"
        bpar.write_text(PAR + "BINARY ELL1\nPB 4.5\nA1 8.2\nTASC 54999.1\n"
                        "EPS1 2e-6\nEPS2 -1e-6\n")
        out = tmp_path / "dd.par"
        assert convert_parfile.main([str(bpar), "-o", str(out),
                                     "--binary", "DD"]) == 0
        text = out.read_text()
        assert "BINARY" in text and "DD" in text
        assert "ECC" in text and "T0" in text

    def test_compare_parfiles(self, workdir, capsys):
        from pint_tpu.scripts import compare_parfiles

        assert compare_parfiles.main([str(workdir / "sim.par"),
                                      str(workdir / "sim.par")]) == 0
        assert "F0" in capsys.readouterr().out

    def test_tcb2tdb(self, tmp_path, capsys):
        from pint_tpu.scripts import tcb2tdb

        tcb = tmp_path / "tcb.par"
        tcb.write_text(PAR.replace("UNITS TDB", "UNITS TCB"))
        out = tmp_path / "tdb.par"
        assert tcb2tdb.main([str(tcb), str(out)]) == 0
        from pint_tpu.models import get_model

        m = get_model(str(out))
        assert m.UNITS.value == "TDB"
        # F0 scaled by IFTE_K (frequencies grow): relative change +1.55e-8
        assert float(m.F0.value) / 205.53069 == pytest.approx(
            1 + 1.55051979176e-8, rel=1e-12)

    def test_pintpublish(self, workdir, capsys):
        from pint_tpu.scripts import pintpublish

        assert pintpublish.main([str(workdir / "sim.par"),
                                 str(workdir / "sim.tim")]) == 0
        assert r"\begin{table}" in capsys.readouterr().out


class TestPhotonCLIs:
    @pytest.fixture(scope="class")
    def eventfile(self, tmp_path_factory):
        from test_photon_domain import make_event_fits

        d = tmp_path_factory.mktemp("events")
        p = d / "events.fits"
        # pulsed photons for the NGC-like model: uniform MET, phases pulled
        # to a peak by construction below is unnecessary; H-test just runs
        rng = np.random.default_rng(1)
        met = np.sort(rng.random(400)) * 86400 * 10
        make_event_fits(str(p), met, rng.random(400) * 1000)
        par = d / "phot.par"
        par.write_text(PAR)
        gauss = d / "template.gauss"
        gauss.write_text("const = 0.4\nphas1 = 0.5\nfwhm1 = 0.1\nampl1 = 0.6\n")
        return d

    def test_photonphase(self, eventfile, capsys, tmp_path):
        from pint_tpu.scripts import photonphase

        out = tmp_path / "phases.txt"
        assert photonphase.main([str(eventfile / "events.fits"),
                                 str(eventfile / "phot.par"),
                                 "--outfile", str(out)]) == 0
        assert "Htest" in capsys.readouterr().out
        assert out.exists()

    def test_event_optimize(self, eventfile, capsys, tmp_path):
        from pint_tpu.scripts import event_optimize

        os.chdir(tmp_path)
        assert event_optimize.main(
            [str(eventfile / "events.fits"), str(eventfile / "phot.par"),
             str(eventfile / "template.gauss"),
             "--nwalkers", "8", "--nsteps", "12", "--burnin", "4",
             "--seed", "3", "--outbase", str(tmp_path / "eo")]) == 0
        assert (tmp_path / "eo.par").exists()
        assert (tmp_path / "eo_chain.npy").exists()

    def test_event_optimize_mesh(self, eventfile, tmp_path):
        """--mesh N shards the walker axis over N devices (the reference's
        --multicore/--ncores pool axis).  The sharded run goes through the
        jitted SPMD batch path (values fp-close to, not bit-identical
        with, the unsharded executable), so the contract here is: it runs,
        produces a finite chain of the right shape, and lands on the same
        posterior region as the unsharded run."""
        from pint_tpu.scripts import event_optimize

        os.chdir(tmp_path)
        common = [str(eventfile / "events.fits"), str(eventfile / "phot.par"),
                  str(eventfile / "template.gauss"),
                  "--nwalkers", "8", "--nsteps", "12", "--burnin", "4",
                  "--seed", "3"]
        assert event_optimize.main(
            common + ["--mesh", "8", "--outbase", str(tmp_path / "eom")]) == 0
        assert event_optimize.main(
            common + ["--outbase", str(tmp_path / "eou")]) == 0
        a = np.load(tmp_path / "eom_chain.npy")
        b = np.load(tmp_path / "eou_chain.npy")
        assert a.shape == b.shape
        assert np.all(np.isfinite(a))
        # same posterior region: per-parameter chain means agree within the
        # ensemble scatter
        sd = np.maximum(b.reshape(-1, b.shape[-1]).std(0), 1e-12)
        da = np.abs(a.reshape(-1, a.shape[-1]).mean(0)
                    - b.reshape(-1, b.shape[-1]).mean(0))
        assert np.all(da < 5 * sd), (da, sd)
        # negative device counts are a clear CLI error
        with pytest.raises(SystemExit):
            event_optimize.main(common + ["--mesh", "-2", "--outbase", "x"])

    def test_event_optimize_autocorr(self, eventfile, tmp_path):
        """--autocorr runs the convergence-checked sampling path
        (reference event_optimize.py run_sampler_autocorr)."""
        from pint_tpu.scripts import event_optimize

        os.chdir(tmp_path)
        assert event_optimize.main(
            [str(eventfile / "events.fits"), str(eventfile / "phot.par"),
             str(eventfile / "template.gauss"),
             "--nwalkers", "8", "--nsteps", "12", "--burnin", "4",
             "--seed", "3", "--autocorr",
             "--outbase", str(tmp_path / "eoa")]) == 0
        assert (tmp_path / "eoa.par").exists()

    def test_read_gaussfitfile(self, eventfile):
        from pint_tpu.scripts.event_optimize import read_gaussfitfile

        tmpl = read_gaussfitfile(str(eventfile / "template.gauss"), 64)
        assert len(tmpl) == 64
        # peak rotated to phase 0
        assert np.argmax(tmpl) in (0, 63)


class TestPintkCore:
    def test_pulsar_wrapper(self, workdir):
        from pint_tpu.pintk.pulsar import Pulsar

        psr = Pulsar(str(workdir / "sim.par"), str(workdir / "sim.tim"))
        assert psr.name == "J0030+0451"
        assert len(psr.all_toas) == 40
        c0 = psr.resids().chi2
        chi2 = psr.fit()
        assert chi2 <= c0 + 1e-6
        assert psr.fitted
        assert "F0" in psr.write_fit_summary()

    def test_phase_wrap_and_jump(self, workdir):
        from pint_tpu.pintk.pulsar import Pulsar

        psr = Pulsar(str(workdir / "sim.par"), str(workdir / "sim.tim"))
        mask = np.zeros(len(psr.all_toas), dtype=bool)
        mask[:10] = True
        r0 = np.asarray(psr.resids().time_resids)
        psr.add_phase_wrap(mask, 1)
        r1 = np.asarray(psr.resids().time_resids)
        P = 1.0 / 205.53069
        assert np.allclose(np.abs(r1[:10] - r0[:10]).mean(), P, rtol=0.3)
        name = psr.add_jump(mask)
        assert name in psr.model.params
        assert name in psr.model.free_params

    def test_pintk_cli_test_mode(self, workdir, capsys):
        from pint_tpu.scripts import pintk

        assert pintk.main([str(workdir / "sim.par"),
                           str(workdir / "sim.tim"), "--test", "--fit"]) == 0
        assert "pintk --test" in capsys.readouterr().out

    def test_delete_and_select(self, workdir):
        from pint_tpu.pintk.pulsar import Pulsar

        psr = Pulsar(str(workdir / "sim.par"), str(workdir / "sim.tim"))
        psr.select_toas(np.arange(5))
        assert len(psr.selected_toas) == 5
        psr.delete_TOAs([0, 1])
        assert len(psr.all_toas) == 38


class TestReviewRegressions:
    def test_tt_geocentric_events_not_double_converted(self, tmp_path):
        """TIMESYS=TT + TIMEREF=GEOCENTRIC events must not get the UTC->TT
        chain applied twice (~69 s error)."""
        from test_photon_domain import make_event_fits

        from pint_tpu.event_toas import get_fits_TOAs
        from pint_tpu.timescales import utc_to_tt_mjd

        p = str(tmp_path / "geo.fits")
        met = np.array([0.0, 86400.0])
        make_event_fits(p, met, np.zeros(2), timesys="TT",
                        timeref="GEOCENTRIC")
        ts = get_fits_TOAs(p, mission="nicer")
        # TT(utc_mjd) must reproduce the original TT event times
        tt = utc_to_tt_mjd(ts.utc_mjd)
        expect = 56658.000777592592593 + met / 86400.0
        np.testing.assert_allclose(np.asarray(tt, dtype=float), expect,
                                   rtol=0, atol=2e-9)

    def test_fmt_uncertainty_large_error(self):
        from pint_tpu.output.publish import _fmt_uncertainty

        assert _fmt_uncertainty(1234.5, 300.0) == "1234(300)"
        assert _fmt_uncertainty(1.234567, 0.00012) == "1.23457(12)"

    def test_polyco_writer_negative_frac(self, tmp_path):
        from pint_tpu.polycos import PolycoEntry, Polycos

        e = PolycoEntry(55000.5, 60.0, 12345, -0.3, 100.0, 3,
                        [0.0, 0.0, 0.0], obs="gbt")
        f = str(tmp_path / "p.dat")
        Polycos([e]).write_polyco_file(f)
        p2 = Polycos.read_polyco_file(f)
        got = p2.entries[0].rphase_int + p2.entries[0].rphase_frac
        assert got == pytest.approx(12344.7, abs=1e-6)

    def test_gauss_template_overnormalized(self, tmp_path):
        from pint_tpu.templates import gauss_template_from_file

        p = tmp_path / "g.txt"
        p.write_text("phas1 = 0.40181682221254356\nfwhm1 = 0.05\n"
                     "ampl1 = 0.40181682221254356\n"
                     "phas2 = 0.2\nfwhm2 = 0.08\nampl2 = 0.6785150052419683\n")
        t = gauss_template_from_file(str(p))  # must not raise
        assert t.norms().sum() <= 1.0
