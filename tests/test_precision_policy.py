"""Mixed-precision layer under test (pint_tpu/precision/).

The contracts tier-1 (CPU) pins:

* **default bit-identity** — no manifest + no override means EVERY
  consumer (serve kernel, GLS step, grid kernel, catalog fit) runs the
  exact pre-precision f64 path: the policy matmul short-circuits to
  ``a @ b`` and outputs are bitwise identical;
* **compensated primitives** — ``two_sum`` folds are error-free where
  plain summation loses bits, and the ``two_prod`` dd-split matmul
  recovers ~f64-grade accuracy (< 1e-12 rel) from f32 operand pairs
  where a naive f32 matmul sits at ~1e-7;
* **probe discipline** — a segment ships reduced only below its
  budget: unforced probes refuse ill-conditioned f32 Grams, forced
  probes record the measured rel err and still refuse past the forced
  budget;
* **manifest resolution** — ``precision.<segment>`` decisions
  round-trip through the tuning manifest (vkey + fingerprint scheme),
  malformed values and stale vkeys degrade to f64;
* **the forced-f32 acceptance pin** — a forced-f32 CPU run of the
  WLS/GLS fit, grid surface, and catalog batched fit agrees with the
  f64-forced run within each segment's recorded budget, with the
  measured per-segment rel err recorded in the manifest and asserted
  within budget.
"""

import json
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.precision

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the B1855 stand-in of the autotune suite: DD binary (M2/SINI pair) +
# EFAC/ECORR/PL red noise — a real correlated-noise GLS workload
STANDIN_PAR = [
    "PSR TSTPREC\n", "RAJ 04:37:15.0 1\n", "DECJ -47:15:09.0 1\n",
    "F0 173.6879 1\n", "F1 -1.7e-15 1\n", "PEPOCH 55000\n",
    "DM 2.64 1\n", "BINARY DD\n", "PB 5.7410\n", "A1 3.3667\n",
    "T0 55000.0\n", "OM 1.35\n", "ECC 1.9e-5\n", "M2 0.3 1\n",
    "SINI 0.95 1\n", "EFAC mjd 50000 60000 1.1\n",
    "ECORR mjd 50000 60000 0.5\n", "TNRedAmp -13.5\n",
    "TNRedGam 3.5\n", "TNRedC 5\n", "UNITS TDB\n",
]


def _make_fitter(seed=7):
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    model = get_model(list(STANDIN_PAR))
    rng = np.random.default_rng(seed)
    base = np.linspace(54000, 56000, 40)
    mjds = np.sort(np.concatenate([base, base + 0.013]))
    toas = make_fake_toas_fromMJDs(mjds, model, error_us=0.5,
                                   add_noise=True, rng=rng)
    f = GLSFitter(toas, model)
    f.fit_toas(maxiter=2)
    return f


def _grid_axes(model, n=4):
    m2, sini = float(model.M2.value), float(model.SINI.value)
    return (np.linspace(m2 - 0.03, m2 + 0.03, n),
            np.linspace(sini - 0.002, sini + 0.002, n))


def _points(g1, g2):
    return np.stack([g.ravel() for g in
                     np.meshgrid(g1, g2, indexing="ij")], axis=-1)


@pytest.fixture(scope="module")
def ftr():
    return _make_fitter(seed=7)


@pytest.fixture
def tune_dir(tmp_path):
    from pint_tpu import config
    from pint_tpu.autotune import reset_manifest_singleton

    d = str(tmp_path / "tune")
    config.set_tune_dir(d)
    reset_manifest_singleton()
    yield d
    config.set_tune_dir(None)
    reset_manifest_singleton()


@pytest.fixture(autouse=True)
def _no_leftover_policy():
    """Every test starts and ends with no override policy installed."""
    from pint_tpu import precision

    precision.set_policy(None)
    yield
    precision.set_policy(None)


# ---------------------------------------------------------------------------
# compensated primitives
# ---------------------------------------------------------------------------

class TestPrimitives:
    def test_f64_spec_is_the_same_op(self):
        import jax.numpy as jnp

        from pint_tpu import precision as P

        rng = np.random.default_rng(0)
        a = rng.normal(size=(40, 24))
        b = rng.normal(size=(24, 16))
        assert np.array_equal(P.matmul(a, b), a @ b)
        assert np.array_equal(P.matmul(a, b, None), a @ b)
        # same op on the same backend: the jnp path must match jnp's
        # own `a @ b` bitwise (numpy and XLA may round dots differently)
        s64 = P.SegmentSpec(segment="serve.gram")
        aj, bj = jnp.asarray(a), jnp.asarray(b)
        assert np.array_equal(np.asarray(P.matmul(aj, bj, s64)),
                              np.asarray(aj @ bj))

    @pytest.mark.parametrize("host", [True, False])
    def test_two_prod_recovers_f64_grade(self, host):
        """The dd-split matmul: ~ulp(f32)^2 relative accuracy, orders
        beyond a naive f32 product — on BOTH the host-numpy and the
        traced path (same semantics across the boundary)."""
        from pint_tpu import precision as P

        rng = np.random.default_rng(1)
        a = rng.normal(size=(200, 120)) * np.exp(
            rng.normal(size=(200, 120)) * 3)
        b = rng.normal(size=(120, 80))
        if not host:
            import jax.numpy as jnp

            a_in, b_in = jnp.asarray(a), jnp.asarray(b)
        else:
            a_in, b_in = a, b
        ref = a @ b
        scale = np.max(np.abs(ref))
        sp_split = P.SegmentSpec(segment="serve.gram",
                                 compute_dtype="float32",
                                 accumulation="two_prod",
                                 source="forced")
        sp_native = P.SegmentSpec(segment="serve.gram",
                                  compute_dtype="float32",
                                  accumulation="native",
                                  source="forced")
        rel_split = np.max(np.abs(
            np.asarray(P.matmul(a_in, b_in, sp_split)) - ref)) / scale
        rel_native = np.max(np.abs(
            np.asarray(P.matmul(a_in, b_in, sp_native)) - ref)) / scale
        assert rel_split < 1e-12
        assert rel_native > 1e-8        # the gap the split closes
        assert rel_split < rel_native / 1e3

    def test_two_sum_accumulate_is_error_free(self):
        """Partials engineered so plain f64 summation annihilates the
        small term; the two_sum fold keeps it."""
        from pint_tpu.precision import two_sum_accumulate

        parts = [np.array([1e16]), np.array([1.0]), np.array([-1e16])]
        assert float(sum(parts)[0]) == 0.0   # plain summation loses 1.0
        assert float(two_sum_accumulate(parts)[0]) == 1.0

    def test_matvec_and_accumulation_modes(self):
        import jax.numpy as jnp

        from pint_tpu import precision as P

        rng = np.random.default_rng(2)
        a = rng.normal(size=(64, 48))
        v = rng.normal(size=48)
        for acc in P.ACCUMULATIONS:
            sp = P.SegmentSpec(segment="serve.gram",
                               compute_dtype="float32",
                               accumulation=acc, source="forced")
            out = np.asarray(P.matmul(jnp.asarray(a), jnp.asarray(v), sp))
            assert out.shape == (64,)
            assert np.allclose(out, a @ v, rtol=1e-4)

    def test_downcast_is_the_sanctioned_cast(self):
        import jax.numpy as jnp

        from pint_tpu.exceptions import UsageError
        from pint_tpu.precision import downcast

        x = np.linspace(0.0, 1.0, 5)
        assert downcast(x, "float64") is x               # identity
        assert downcast(x, "float32").dtype == np.float32
        xj = jnp.asarray(x)
        assert downcast(xj, "float32").dtype == jnp.float32
        with pytest.raises(UsageError):
            downcast(x, "float16")


# ---------------------------------------------------------------------------
# policy + resolution
# ---------------------------------------------------------------------------

class TestPolicy:
    def test_unknown_segment_and_bad_spec_raise_typed(self):
        from pint_tpu.exceptions import UsageError
        from pint_tpu.precision import SegmentSpec, segment_spec

        with pytest.raises(UsageError):
            segment_spec("no.such.segment")
        with pytest.raises(UsageError):
            SegmentSpec(segment="serve.gram", compute_dtype="float16")
        with pytest.raises(UsageError):
            SegmentSpec(segment="serve.gram", accumulation="kahan")

    def test_forced_policy_and_scoped_install(self):
        from pint_tpu import precision as P

        pol = P.PrecisionPolicy.forced("float32", accumulation="two_prod")
        assert P.active_policy() is None
        with P.use_policy(pol):
            sp = P.segment_spec("serve.gram")
            assert sp.compute_dtype == "float32"
            assert sp.accumulation == "two_prod"
            assert sp.source == "forced"
            assert sp.budget == P.SEGMENTS["serve.gram"].forced_budget
        assert P.active_policy() is None
        # the explicit-f64 override resolves f64 even over a manifest
        with P.use_policy(P.PrecisionPolicy.f64()):
            assert not P.segment_spec("serve.gram").reduced

    def test_spec_from_decision_validation(self):
        from pint_tpu.precision import spec_from_decision

        good = {"compute_dtype": "float32", "accumulation": "two_prod",
                "budget": 1e-3, "rel_err": 2e-10}
        sp = spec_from_decision("serve.gram", good)
        assert sp is not None and sp.reduced and sp.source == "tuned"
        for bad in (
            None, "float32", [],
            {"compute_dtype": "float16", "accumulation": "f64",
             "budget": 1e-3},
            {"compute_dtype": "float32", "accumulation": "kahan",
             "budget": 1e-3},
            {"compute_dtype": "float32", "accumulation": "f64",
             "budget": -1.0},
            {"compute_dtype": "float32", "accumulation": "f64",
             "budget": True},
            {"compute_dtype": "float32", "accumulation": "f64",
             "budget": 1e-3, "rel_err": -2.0},
        ):
            assert spec_from_decision("serve.gram", bad) is None

    def test_default_resolution_is_f64(self, ftr):
        """No manifest, no override: every segment resolves to the
        bit-identical default."""
        from pint_tpu import precision as P

        for name in P.SEGMENTS:
            spec = P.segment_spec(name, model=ftr.model, toas=ftr.toas)
            assert not spec.reduced
            assert spec.source == "default"

    def test_suffix_and_vkeys(self, ftr):
        from pint_tpu import precision as P

        assert P.SegmentSpec(segment="serve.gram").suffix() == ""
        sp = P.SegmentSpec(segment="serve.gram", compute_dtype="float32",
                           accumulation="two_prod", source="forced")
        assert sp.suffix() == "@f32+split"
        # model-bound vkeys need the workload; generic ones do not
        from pint_tpu.exceptions import UsageError

        with pytest.raises(UsageError):
            P.precision_vkey("gls.design")
        assert P.precision_vkey("serve.gram") == \
            ("precision", "serve.gram", 1)
        vk = P.precision_vkey("gls.design", ftr.model, ftr.toas)
        assert vk[0] == "precision" and vk[1] == "gls.design"


# ---------------------------------------------------------------------------
# default bit-identity of the consumers
# ---------------------------------------------------------------------------

class TestDefaultBitIdentity:
    def test_serve_kernel_default_equals_explicit_f64(self, ftr):
        from pint_tpu import precision as P
        from pint_tpu.serving.batcher import FitRequest, pad_request, \
            serve_kernel

        q = FitRequest.from_fitter(ftr)
        ops = pad_request(q, q.n_toas, q.n_free)
        out_def = [np.asarray(o) for o in serve_kernel(*ops)]
        out_f64 = [np.asarray(o) for o in serve_kernel(
            *ops, spec=P.SegmentSpec(segment="serve.gram"))]
        for a, b in zip(out_def, out_f64):
            assert np.array_equal(a, b)

    def test_batcher_under_f64_override_is_bitwise_default(self, ftr):
        from pint_tpu import precision as P
        from pint_tpu.serving.batcher import FitRequest, ShapeBatcher

        reqs = [FitRequest.from_fitter(ftr, request_id=f"r{i}")
                for i in range(3)]
        batcher = ShapeBatcher()
        base = batcher.run(reqs)
        with P.use_policy(P.PrecisionPolicy.f64()):
            forced = batcher.run(reqs)
        for a, b in zip(base, forced):
            assert np.array_equal(a.dx, b.dx)
            assert a.chi2 == b.chi2

    def test_grid_default_equals_explicit_f64_spec(self, ftr):
        import jax.numpy as jnp

        from pint_tpu import precision as P
        from pint_tpu.grid import build_grid_gls_chi2_fn

        g1, g2 = _grid_axes(ftr.model)
        pts = _points(g1, g2)[:4]
        fn_def, _, _ = build_grid_gls_chi2_fn(
            ftr.model, ftr.toas, ("M2", "SINI"), niter=1, chunk=4)
        fn_f64, _, _ = build_grid_gls_chi2_fn(
            ftr.model, ftr.toas, ("M2", "SINI"), niter=1, chunk=4,
            precision=P.SegmentSpec(segment="grid.gram"))
        c_def = np.asarray(fn_def(jnp.asarray(pts))[0])
        c_f64 = np.asarray(fn_f64(jnp.asarray(pts))[0])
        assert np.array_equal(c_def, c_f64)


# ---------------------------------------------------------------------------
# probe discipline + manifest round trip
# ---------------------------------------------------------------------------

class TestProbesAndManifest:
    def test_unforced_probe_refuses_above_the_safe_bar(self, ftr,
                                                       tune_dir):
        """gls.design at plain f32+f64-accumulation sits orders above
        the 1e-12 safe bar on this ill-conditioned system: the probe
        records f64 with the measured margin."""
        from pint_tpu import autotune
        from pint_tpu.precision import tune_precision_segments

        out = tune_precision_segments(
            ftr, segments=("gls.design",), compute_dtype="float32",
            accumulation="f64", tuning_manifest=autotune.manifest())
        dec = out["gls.design"]
        assert dec.value["compute_dtype"] == "float64"
        assert dec.measured["rel_err"] > dec.measured["budget"]

    def test_forced_probe_records_within_budget_and_resolves(
            self, ftr, tune_dir):
        """The forced-f32 run: decisions record the measured rel err,
        the rel err sits INSIDE each segment's forced budget, and the
        resolve layer returns the reduced spec for exactly this
        workload."""
        from pint_tpu import autotune
        from pint_tpu import precision as P
        from pint_tpu.precision import tune_precision_segments

        g1, g2 = _grid_axes(ftr.model)
        out = tune_precision_segments(
            ftr, compute_dtype="float32", accumulation="two_prod",
            force=True, grid_params=("M2", "SINI"),
            points=_points(g1, g2), tuning_manifest=autotune.manifest())
        assert set(out) == {"gls.design", "grid.gram", "serve.gram",
                            "catalog.fit"}
        for segment, dec in out.items():
            assert dec.value["compute_dtype"] == "float32", segment
            assert dec.value["rel_err"] <= dec.value["budget"], segment
            assert dec.basis == "forced"
        sp = P.segment_spec("gls.design", model=ftr.model,
                            toas=ftr.toas)
        assert sp.reduced and sp.source == "tuned"
        assert sp.rel_err <= sp.budget
        assert P.segment_spec("serve.gram").reduced
        # the manifest document itself validates (the pre-commit gate)
        from tools.telemetry_report import validate_tuning_manifest_file

        errors = []
        n = validate_tuning_manifest_file(
            os.path.join(tune_dir, "tuning.json"), errors)
        assert n == 4 and errors == []
        # END-TO-END: with NO override installed, the manifest alone
        # drives the grid kernel reduced — the mixed surface differs
        # from the forced-f64 build (the reduced path genuinely ran)
        # yet stays inside the recorded grid.gram budget
        import jax.numpy as jnp

        from pint_tpu.grid import build_grid_gls_chi2_fn

        pts = _points(g1, g2)[:4]
        fnmix, _, _ = build_grid_gls_chi2_fn(
            ftr.model, ftr.toas, ("M2", "SINI"), niter=1, chunk=4)
        fn64, _, _ = build_grid_gls_chi2_fn(
            ftr.model, ftr.toas, ("M2", "SINI"), niter=1, chunk=4,
            precision=P.SegmentSpec(segment="grid.gram"))
        cmix = np.asarray(fnmix(jnp.asarray(pts))[0])
        c64 = np.asarray(fn64(jnp.asarray(pts))[0])
        assert not np.array_equal(cmix, c64)
        budget = out["grid.gram"].value["budget"]
        assert float(np.max(np.abs(cmix - c64))) \
            / max(float(np.max(np.abs(c64))), 1e-300) <= budget

    def test_forced_probe_refuses_past_the_forced_budget(
            self, ftr, tune_dir, monkeypatch):
        """Even a forced run cannot ship a broken segment: a probe
        measuring past the forced budget records f64 with the
        reason."""
        from pint_tpu import autotune
        from pint_tpu.precision import tune as _tune

        monkeypatch.setitem(_tune._PROBES, "serve.gram",
                            lambda *a, **kw: float("inf"))
        out = _tune.tune_precision_segments(
            ftr, segments=("serve.gram",), compute_dtype="float32",
            accumulation="two_prod", force=True,
            tuning_manifest=autotune.manifest())
        dec = out["serve.gram"]
        assert dec.value["compute_dtype"] == "float64"
        assert "f64 retained" in dec.reason

    def test_stale_vkey_and_tampered_value_degrade_to_f64(
            self, ftr, tune_dir):
        from pint_tpu import autotune
        from pint_tpu import precision as P
        from pint_tpu.autotune.manifest import MANIFEST_BASENAME
        from pint_tpu.precision import tune_precision_segments

        tune_precision_segments(
            ftr, segments=("gls.design", "serve.gram"),
            compute_dtype="float32", accumulation="two_prod",
            force=True, tuning_manifest=autotune.manifest())
        assert P.segment_spec("gls.design", model=ftr.model,
                              toas=ftr.toas).reduced
        # any model-parameter edit invalidates the model-bound vkey
        old = ftr.model.M2.value
        ftr.model.M2.value = old + 1e-6
        try:
            assert not P.segment_spec("gls.design", model=ftr.model,
                                      toas=ftr.toas).reduced
        finally:
            ftr.model.M2.value = old
        # a tampered decision VALUE degrades to f64, never a bad dtype
        mpath = os.path.join(tune_dir, MANIFEST_BASENAME)
        with open(mpath, encoding="utf-8") as f:
            doc = json.load(f)
        for entry in doc["decisions"].values():
            if entry["name"] == "precision.serve.gram":
                entry["decision"]["value"]["compute_dtype"] = "float8"
        with open(mpath, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        autotune.reset_manifest_singleton()
        assert not P.segment_spec("serve.gram").reduced

    def test_probe_events_match_the_validator(self, ftr, monkeypatch):
        """Producer/validator agreement for precision_probe /
        precision_applied, checked on the attrs the REAL emitters
        produce (the runlog wire format is covered by the
        telemetry_report self-test)."""
        from pint_tpu import config, telemetry
        from pint_tpu import precision as P
        from pint_tpu.precision import tune_precision_segments
        from tools.telemetry_report import validate_precision_event

        captured = []
        monkeypatch.setattr(
            telemetry, "lifecycle_event",
            lambda name, **attrs: captured.append(
                {"name": name, "attrs": attrs}))
        prev = config.telemetry_mode()
        config.set_telemetry_mode("basic")
        try:
            tune_precision_segments(ftr, segments=("serve.gram",),
                                    compute_dtype="float32",
                                    accumulation="two_prod", force=True)
            with P.use_policy(P.PrecisionPolicy.forced("float32")):
                P.segment_spec("serve.gram")
        finally:
            config.set_telemetry_mode(prev)
        names = [ev["name"] for ev in captured]
        assert "precision_probe" in names
        assert "precision_applied" in names
        errors = []
        for ev in captured:
            validate_precision_event(ev, "captured", errors)
        assert errors == []
        # the validator rejects malformed twins
        bad = [
            {"name": "precision_probe", "attrs": {
                "segment": "serve.gram", "dtype": "float64",
                "accumulation": "f64", "rel_err": 1e-10,
                "budget": 1e-3, "decision": "float32"}},
            {"name": "precision_probe", "attrs": {
                "segment": "serve.gram", "dtype": "float32",
                "accumulation": "f64", "rel_err": -1.0,
                "budget": 1e-3, "decision": "float64"}},
            {"name": "precision_probe", "attrs": {
                "segment": "serve.gram", "dtype": "float32",
                "accumulation": "f64", "rel_err": 1e-10,
                "budget": 0.0, "decision": "float64"}},
            {"name": "precision_applied", "attrs": {
                "segment": "serve.gram", "compute_dtype": "float32",
                "accumulation": "f64", "source": "default"}},
            {"name": "precision_applied", "attrs": {
                "segment": "serve.gram", "compute_dtype": "float64",
                "accumulation": "f64", "source": "tuned"}},
        ]
        for ev in bad:
            errors = []
            validate_precision_event(ev, "bad", errors)
            assert errors, f"malformed event accepted: {ev}"


# ---------------------------------------------------------------------------
# the forced-f32 acceptance pins
# ---------------------------------------------------------------------------

class TestForcedF32Acceptance:
    def test_gls_fit_within_budget_and_wls_bit_identical(self):
        """f64-forced vs forced-f32 GLS fit: chi2 and fitted parameters
        agree within the gls.design segment's recorded budget; the WLS
        fit (no routed segment) is bit-identical under the same forced
        policy."""
        from pint_tpu import precision as P
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        budget = P.SEGMENTS["gls.design"].forced_budget
        pol = P.PrecisionPolicy.forced("float32",
                                       accumulation="two_prod")
        f64 = _make_fitter(seed=23)
        with P.use_policy(pol):
            fmix = _make_fitter(seed=23)
        chi2_64 = float(f64.resids.calc_chi2())
        chi2_mix = float(fmix.resids.calc_chi2())
        assert abs(chi2_mix - chi2_64) / abs(chi2_64) <= budget
        for p in f64.model.free_params:
            v64 = float(getattr(f64.model, p).value)
            vmix = float(getattr(fmix.model, p).value)
            u = float(getattr(f64.model, p).uncertainty or 0.0)
            scale = max(abs(v64), u, 1e-300)
            assert abs(vmix - v64) <= budget * scale, p
        # WLS: no noise basis, no routed segment — bitwise identical
        par = [ln for ln in STANDIN_PAR
               if not ln.startswith(("EFAC", "ECORR", "TNRed"))]
        rng = np.random.default_rng(5)
        mjds = np.linspace(54000, 56000, 50)

        def _wls():
            model = get_model(list(par))
            toas = make_fake_toas_fromMJDs(
                mjds, model, error_us=1.0, add_noise=True,
                rng=np.random.default_rng(5))
            w = WLSFitter(toas, model)
            return w.fit_toas(maxiter=2)

        chi2_w64 = _wls()
        with P.use_policy(pol):
            chi2_wmix = _wls()
        assert chi2_wmix == chi2_w64
        _ = rng

    def test_grid_surface_within_budget(self, ftr):
        """f64-forced vs forced-f32 chunked grid surface: chi2 within
        the grid.gram forced budget at every point (the correction
        segment rides along under its own override)."""
        import jax.numpy as jnp

        from pint_tpu import precision as P
        from pint_tpu.grid import build_grid_gls_chi2_fn

        budget = P.SEGMENTS["grid.gram"].forced_budget
        g1, g2 = _grid_axes(ftr.model)
        pts = _points(g1, g2)
        fn64, _, _ = build_grid_gls_chi2_fn(
            ftr.model, ftr.toas, ("M2", "SINI"), niter=1, chunk=8,
            correction_dtype="float64",
            precision=P.SegmentSpec(segment="grid.gram"))
        with P.use_policy(P.PrecisionPolicy.forced(
                "float32", accumulation="two_prod")):
            fnmix, _, _ = build_grid_gls_chi2_fn(
                ftr.model, ftr.toas, ("M2", "SINI"), niter=1, chunk=8)
        c64 = np.asarray(fn64(jnp.asarray(pts))[0])
        cmix = np.asarray(fnmix(jnp.asarray(pts))[0])
        assert np.all(np.isfinite(cmix))
        scale = float(np.max(np.abs(c64)))
        assert float(np.max(np.abs(cmix - c64))) / scale <= budget

    def test_catalog_batched_fit_within_budget(self):
        """f64-forced vs forced-f32 catalog batched fit: per-pulsar
        chi2 and parameter steps within the catalog.fit forced
        budget."""
        from pint_tpu import precision as P
        from pint_tpu.catalog import CatalogFitter
        from pint_tpu.catalog.ingest import (
            ingest_catalog,
            make_synthetic_catalog,
        )

        budget = P.SEGMENTS["catalog.fit"].forced_budget

        def _fit():
            report = ingest_catalog(make_synthetic_catalog(
                n_pulsars=4, seed=42, ntoa_range=(24, 40)))
            cf = CatalogFitter(report)
            return cf.fit(maxiter=1)

        res64 = _fit()
        with P.use_policy(P.PrecisionPolicy.forced(
                "float32", accumulation="two_prod")):
            resmix = _fit()
        by64 = res64.by_name()
        for fit in resmix.fits:
            ref = by64[fit.name]
            assert abs(fit.chi2 - ref.chi2) \
                <= budget * max(abs(ref.chi2), 1.0)
            for par, dv in fit.dpars.items():
                scale = max(abs(ref.dpars[par]),
                            abs(ref.errors.get(par, 0.0)), 1e-300)
                assert abs(dv - ref.dpars[par]) <= budget * scale, par

    def test_joint_lnlike_within_budget_and_factorization_holds(self):
        """The joint HD lnlikelihood under forced f32: within the
        catalog.lnlike budget of the f64 kernel, and the amp->0
        factorization pin HOLDS AT REDUCED PRECISION (both sides trace
        the same spec)."""
        from pint_tpu import precision as P
        from pint_tpu.catalog.ingest import (
            ingest_catalog,
            make_synthetic_catalog,
        )
        from pint_tpu.catalog.likelihood import JointLikelihood

        budget = P.SEGMENTS["catalog.lnlike"].forced_budget
        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=4, seed=42, ntoa_range=(24, 40)))
        spec = P.SegmentSpec(segment="catalog.lnlike",
                             compute_dtype="float32",
                             accumulation="two_prod", source="forced")
        jl64 = JointLikelihood(report, n_modes=3)
        jlmix = JointLikelihood(report, n_modes=3, precision=spec)
        l64 = jl64.lnlike(-14.5, 13.0 / 3.0)
        lmix = jlmix.lnlike(-14.5, 13.0 / 3.0)
        assert abs(lmix - l64) / max(abs(l64), 1.0) <= budget
        # factorization: joint at amp==0 == sum of per-pulsar blocks,
        # both evaluated under the SAME reduced spec
        assert np.isclose(jlmix.lnlike_nocommon(),
                          float(np.sum(jlmix.per_pulsar_lnlike())),
                          rtol=1e-9, atol=1e-6)


# ---------------------------------------------------------------------------
# the CPU stand-in check suite (mirrors the TPU_PRECISION check names)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestPrecisionCheckSuiteStandin:
    def test_standin_suite_mirrors_tpu_precision_names(self, tmp_path):
        """A CPU stand-in of the TPU_PRECISION_r* check suite: the
        forced-f32 mixed path plays the role of the device, the forced-
        f64 path the reference, and the named checks reuse the
        artifact's spelling (``b_la_chi2_rel``-family) so the same
        perfwatch gate reads both.  Every value must sit inside its
        bound, and the resulting artifact must gate cleanly through
        ``tools/perfwatch.py``."""
        import jax.numpy as jnp

        from pint_tpu import precision as P
        from pint_tpu.grid import build_grid_gls_chi2_fn
        from pint_tpu.serving.batcher import FitRequest, pad_request, \
            serve_kernel
        from tools.perfwatch import check_precision_artifacts, collect

        f = _make_fitter(seed=31)
        pol = P.PrecisionPolicy.forced("float32",
                                       accumulation="two_prod")
        checks = {}

        # b_la_chi2_rel: the linearized-solve chi2, mixed vs f64
        q = FitRequest.from_fitter(f)
        ops = pad_request(q, q.n_toas, q.n_free)
        out64 = [np.asarray(o) for o in serve_kernel(*ops)]
        spec = P.SegmentSpec(segment="serve.gram",
                             compute_dtype="float32",
                             accumulation="two_prod", source="forced")
        outmix = [np.asarray(o) for o in serve_kernel(*ops, spec=spec)]
        checks["b_la_chi2_rel"] = {
            "value": abs(float(outmix[2]) - float(out64[2]))
            / max(abs(float(out64[2])), 1e-300),
            "bound": P.SEGMENTS["serve.gram"].forced_budget}

        # b_gls_step_explained: step deviation over the step scale
        step_scale = max(float(np.linalg.norm(out64[0])), 1e-300)
        checks["b_gls_step_explained"] = {
            "value": float(np.linalg.norm(outmix[0] - out64[0]))
            / step_scale,
            "bound": P.SEGMENTS["gls.design"].forced_budget}

        # b_grid_chi2_explained: the chunked grid surface, mixed vs f64
        g1, g2 = _grid_axes(f.model)
        pts = _points(g1, g2)[:4]
        fn64, _, _ = build_grid_gls_chi2_fn(
            f.model, f.toas, ("M2", "SINI"), niter=1, chunk=4,
            correction_dtype="float64",
            precision=P.SegmentSpec(segment="grid.gram"))
        with P.use_policy(pol):
            fnmix, _, _ = build_grid_gls_chi2_fn(
                f.model, f.toas, ("M2", "SINI"), niter=1, chunk=4)
        c64 = np.asarray(fn64(jnp.asarray(pts))[0])
        cmix = np.asarray(fnmix(jnp.asarray(pts))[0])
        checks["b_grid_chi2_explained"] = {
            "value": float(np.max(np.abs(cmix - c64)))
            / max(float(np.max(np.abs(c64))), 1e-300),
            "bound": P.SEGMENTS["grid.gram"].forced_budget}

        for name, c in checks.items():
            c["ok"] = bool(c["value"] <= c["bound"])
            assert c["ok"], f"{name}: {c['value']} > {c['bound']}"

        # the artifact shape the TPU runner commits; perfwatch must
        # ingest and gate it cleanly
        artifact = {"metric": "tpu_precision", "platform": "cpu",
                    "ok": all(c["ok"] for c in checks.values()),
                    "checks": checks}
        path = tmp_path / "TPU_PRECISION_r99.json"
        path.write_text(json.dumps(artifact))
        errors = []
        records = collect([str(path)], None, errors)
        assert errors == []
        verdicts = check_precision_artifacts(records, threshold=0.30)
        assert len(verdicts) == len(checks)
        assert not any(v.failed for v in verdicts)
