"""Synthetic SPK kernels: the test writes its own DAF/SPK type-2/3 files
from the public format spec and asserts :class:`SPKEphemeris` reproduces the
Chebyshev polynomials exactly (VERDICT r2 directive #4a — unblocks the SPK
path in a kernel-less image; reference ``solar_system_ephemerides.py:201``).
"""

import os
import struct

import numpy as np
import pytest

DAY_S = 86400.0
J2000_MJD = 51544.5


def _cheb_records(rng, n_rec, ncoef, init, intlen, ncomp=3, scale=1e8):
    """Random smooth Chebyshev records: (n_rec, 2 + ncomp*ncoef) doubles."""
    recs = np.zeros((n_rec, 2 + ncomp * ncoef))
    for i in range(n_rec):
        mid = init + (i + 0.5) * intlen
        radius = intlen / 2.0
        recs[i, 0] = mid
        recs[i, 1] = radius
        # decaying coefficients so the polynomial is smooth
        decay = scale * 0.5 ** np.arange(ncoef)
        recs[i, 2:] = (rng.standard_normal(ncomp * ncoef)
                       * np.tile(decay, ncomp))
    return recs


def _write_spk(path, segments, little_endian=True):
    """Minimal DAF/SPK writer: one summary record, data after it.

    ``segments``: list of dicts with target/center/dtype/records/init/intlen.
    """
    endian = "<" if little_endian else ">"
    nd, ni = 2, 6
    ss = nd + (ni + 1) // 2  # 5 doubles per summary

    # layout: record 1 = file record, record 2 = summary record,
    # record 3 = name record (required spacing by the spec), data from rec 4
    data_words = []  # doubles
    seg_meta = []
    word_ptr = 3 * 128 + 1  # first data word (1-based), after 3 records
    for seg in segments:
        recs = seg["records"]
        n_rec, rsize = recs.shape
        arr = list(recs.ravel())
        trailer = [seg["init"], seg["intlen"], float(rsize), float(n_rec)]
        start = word_ptr
        end = start + len(arr) + 4 - 1
        et0 = seg["init"]
        et1 = seg["init"] + n_rec * seg["intlen"]
        seg_meta.append((et0, et1, seg["target"], seg["center"], 1,
                         seg["dtype"], start, end))
        data_words += arr + trailer
        word_ptr = end + 1

    nrec_total = (word_ptr - 1 + 127) // 128 + 1
    buf = bytearray(1024 * max(4, nrec_total))
    # file record
    buf[0:8] = b"DAF/SPK "
    struct.pack_into(endian + "ii", buf, 8, nd, ni)
    buf[16:76] = b"synthetic test kernel".ljust(60)
    struct.pack_into(endian + "iii", buf, 76, 2, 2, word_ptr)  # fward bward free
    buf[88:96] = b"LTL-IEEE" if little_endian else b"BIG-IEEE"
    # summary record (record 2)
    base = 1024
    struct.pack_into(endian + "ddd", buf, base, 0.0, 0.0, float(len(seg_meta)))
    for i, (et0, et1, tgt, ctr, frame, dtype, start, end) in enumerate(seg_meta):
        off = base + 24 + i * ss * 8
        struct.pack_into(endian + "dd", buf, off, et0, et1)
        struct.pack_into(endian + "6i", buf, off + nd * 8, tgt, ctr, frame,
                         dtype, start, end)
    # data
    for i, w in enumerate(data_words):
        struct.pack_into(endian + "d", buf, (3 * 128 + i) * 8, w)
    with open(path, "wb") as f:
        f.write(bytes(buf))


def _cheb_eval(recs, et, ncomp=3):
    """Oracle: evaluate the Chebyshev records with numpy.polynomial."""
    from numpy.polynomial import chebyshev as C

    et = np.atleast_1d(et)
    mids, radii = recs[:, 0], recs[:, 1]
    ncoef = (recs.shape[1] - 2) // ncomp
    pos = np.zeros(et.shape + (ncomp,))
    dpos = np.zeros(et.shape + (ncomp,))
    for j, t in enumerate(et):
        i = int(np.argmin(np.abs(mids - t)))
        x = (t - mids[i]) / radii[i]
        for c in range(ncomp):
            coef = recs[i, 2 + c * ncoef:2 + (c + 1) * ncoef]
            pos[j, c] = C.chebval(x, coef)
            dpos[j, c] = C.chebval(x, C.chebder(coef)) / radii[i]
    return pos, dpos


@pytest.fixture
def kernel(tmp_path):
    rng = np.random.default_rng(42)
    init = (55000.0 - J2000_MJD) * DAY_S
    intlen = 16.0 * DAY_S
    n_rec = 32  # covers 512 days
    segs = []
    recs = {}
    # EMB wrt SSB (3/0), Earth wrt EMB (399/3), Sun wrt SSB (10/0): type 2
    for tgt, ctr, scale in ((3, 0, 1.5e8), (399, 3, 4.5e5), (10, 0, 1e6)):
        r = _cheb_records(rng, n_rec, 8, init, intlen, ncomp=3, scale=scale)
        recs[(tgt, ctr)] = r
        segs.append(dict(target=tgt, center=ctr, dtype=2, records=r,
                         init=init, intlen=intlen))
    # Jupiter barycenter wrt SSB: type 3 (position+velocity coefficients)
    r5 = _cheb_records(rng, n_rec, 6, init, intlen, ncomp=6, scale=7.5e8)
    recs[(5, 0)] = r5
    segs.append(dict(target=5, center=0, dtype=3, records=r5,
                     init=init, intlen=intlen))
    path = str(tmp_path / "de999.bsp")
    _write_spk(path, segs)
    return path, recs, init, intlen


class TestSyntheticSPK:
    def test_type2_exact(self, kernel):
        from pint_tpu.ephemeris import SPKEphemeris

        path, recs, init, intlen = kernel
        eph = SPKEphemeris(path)
        et = init + np.linspace(0.25, 31.75, 40) * intlen
        mjd = et / DAY_S + J2000_MJD
        pos, vel = eph.posvel_ssb("sun", mjd)
        want_p, want_v = _cheb_eval(recs[(10, 0)], et)
        assert np.allclose(pos, want_p, rtol=1e-14, atol=1e-6)
        assert np.allclose(vel, want_v, rtol=1e-12, atol=1e-10)

    def test_chained_pairs(self, kernel):
        """Earth = EMB/SSB + Earth/EMB via the BFS chain."""
        from pint_tpu.ephemeris import SPKEphemeris

        path, recs, init, intlen = kernel
        eph = SPKEphemeris(path)
        et = init + np.array([3.3, 17.9, 30.1]) * intlen
        mjd = et / DAY_S + J2000_MJD
        pos, vel = eph.posvel_ssb("earth", mjd)
        p1, v1 = _cheb_eval(recs[(3, 0)], et)
        p2, v2 = _cheb_eval(recs[(399, 3)], et)
        # forward-recurrence vs Clenshaw rounding on ~1.5e8-scale random
        # coefficients: allow ~1e-12 relative (real kernels are smoother)
        assert np.allclose(pos, p1 + p2, rtol=1e-11, atol=1e-4)
        assert np.allclose(vel, v1 + v2, rtol=1e-9, atol=1e-8)

    def test_type3_posvel(self, kernel):
        from pint_tpu.ephemeris import SPKEphemeris

        path, recs, init, intlen = kernel
        eph = SPKEphemeris(path)
        et = init + np.array([8.5]) * intlen
        mjd = et / DAY_S + J2000_MJD
        pos, vel = eph.posvel_ssb("jupiter", mjd)
        full, _ = _cheb_eval(recs[(5, 0)], et, ncomp=6)
        assert np.allclose(pos, full[..., :3], rtol=1e-14, atol=1e-6)
        assert np.allclose(vel, full[..., 3:], rtol=1e-14, atol=1e-10)

    def test_out_of_coverage_raises(self, kernel):
        from pint_tpu.ephemeris import SPKEphemeris

        path, _, init, intlen = kernel
        eph = SPKEphemeris(path)
        with pytest.raises(ValueError, match="coverage"):
            eph.posvel_ssb("sun", np.array([40000.0]))

    def test_pipeline_uses_kernel(self, kernel, tmp_path, monkeypatch):
        """End-to-end: get_TOAs resolves the kernel through PINT_EPHEM_DIR
        and the posvel columns match the kernel's polynomials."""
        import pint_tpu.ephemeris as em
        from pint_tpu.toa import get_TOAs

        path, recs, init, intlen = kernel
        monkeypatch.setenv("PINT_EPHEM_DIR", os.path.dirname(path))
        monkeypatch.setitem(em._loaded, "de999", em.SPKEphemeris(path))
        lines = ["FORMAT 1\n"]
        mjds = 55100.0 + np.array([0.125, 40.375, 200.625])
        for i, m in enumerate(mjds):
            lines.append(f"s{i} 1400.0 {m:.13f} 1.0 bat\n")  # barycenter site
        timf = tmp_path / "bat.tim"
        timf.write_text("".join(lines))
        t = get_TOAs(str(timf), ephem="DE999", include_gps=False,
                     include_bipm=False)
        et = (np.asarray(t.tdb, np.float64) - J2000_MJD) * DAY_S
        sun_p, _ = _cheb_eval(recs[(10, 0)], et)
        # barycentric observer: obs->sun == sun(SSB)
        assert np.allclose(t.obs_sun_pos_km, sun_p, rtol=1e-12, atol=1e-3)
