"""CI wiring + fixture tests for tools/jaxlint.

Each rule is exercised on a minimal bad snippet and its good twin, then
the pragma and baseline layers round-trip, and finally the whole of
``pint_tpu/`` must lint clean against the committed
``jaxlint_baseline.txt`` — a trace-safety regression in the hot path
fails the suite, not just a style check.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.jaxlint.engine import (  # noqa: E402
    ConfigError,
    Engine,
    load_baseline,
    parse_file,
    write_baseline,
)
from tools.jaxlint.rules import RULES, default_rules  # noqa: E402
from tools.jaxlint.rules.dtype_literals import (  # noqa: E402
    F32UnsafeLiteralRule,
    ImplicitDtypeRule,
)
from tools.jaxlint.rules.host_jit import HostCallInJitRule  # noqa: E402
from tools.jaxlint.rules.static_args import StaticArgsRule  # noqa: E402
from tools.jaxlint.rules.traced_branch import TracedBranchRule  # noqa: E402
from tools.jaxlint.rules.typed_raises import TypedRaiseRule  # noqa: E402


def lint_snippet(tmp_path, source, rules):
    p = tmp_path / "snippet.py"
    p.write_text(source)
    return Engine(rules=rules, repo=str(tmp_path)).lint_file(str(p))


def rule_names(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# traced-function discovery (the engine core the rules stand on)
# ---------------------------------------------------------------------------

class TestTracedDiscovery:
    def test_decorator_wrap_scan_and_nested(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def decorated(x):\n"
            "    return x\n"
            "def wrapped(x):\n"
            "    def nested(y):\n"
            "        return y\n"
            "    return nested(x)\n"
            "fn = jax.jit(jax.vmap(wrapped))\n"
            "def scan_body(c, x):\n"
            "    return c, x\n"
            "def host(x):\n"
            "    return jax.lax.scan(scan_body, 0.0, x)\n")
        info = parse_file(str(p), repo=str(tmp_path))
        names = {getattr(td.node, "name", "<lambda>")
                 for td in info.traced_defs}
        assert names == {"decorated", "wrapped", "nested", "scan_body"}

    def test_lax_data_operands_not_marked(self, tmp_path):
        """Only function *positions* of lax combinators mark defs: a
        cond predicate or scan carry sharing a def's name must not."""
        p = tmp_path / "t.py"
        p.write_text(
            "import jax\n"
            "import numpy as np\n"
            "def pred(a):\n"
            "    return np.sum(a) > 0\n"   # host-only helper
            "def tfn(o):\n"
            "    return o\n"
            "def ffn(o):\n"
            "    return o\n"
            "def host(x):\n"
            "    return jax.lax.cond(pred, tfn, ffn, x)\n")
        info = parse_file(str(p), repo=str(tmp_path))
        names = {getattr(td.node, "name", "<lambda>")
                 for td in info.traced_defs}
        assert names == {"tfn", "ffn"}
        assert lint_snippet(tmp_path, p.read_text(),
                            [HostCallInJitRule()]) == []

    def test_non_jax_jit_attribute_not_marked(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.sum(x)\n"
            "class C:\n"
            "    def jit(self, fn):\n"
            "        return fn\n"
            "c = C()\n"
            "g = c.jit(f)\n")   # not jax.jit: f stays a host function
        info = parse_file(str(p), repo=str(tmp_path))
        assert info.traced_defs == []

    def test_dotted_jax_numpy_import_covered(self, tmp_path):
        src = (
            "import jax.numpy\n"
            "a = jax.numpy.zeros(3)\n"
            "b = jax.numpy.array([1.0])\n"
        )
        findings = lint_snippet(tmp_path, src,
                                [ImplicitDtypeRule(files=None)])
        assert rule_names(findings) == ["implicit-dtype"] * 2

    def test_partial_jit_static_argnums(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, n):\n"
            "    return x\n")
        info = parse_file(str(p), repo=str(tmp_path))
        (td,) = info.traced_defs
        assert td.static_params == {"n"}

    def test_aliased_from_import_still_entry(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "from jax import jit as jjit\n"
            "import numpy as np\n"
            "@jjit\n"
            "def f(x):\n"
            "    return np.sin(x)\n")
        info = parse_file(str(p), repo=str(tmp_path))
        assert {td.node.name for td in info.traced_defs} == {"f"}
        findings = lint_snippet(tmp_path, p.read_text(),
                                [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"]


# ---------------------------------------------------------------------------
# rule fixtures: each fires on the bad snippet, stays silent on the twin
# ---------------------------------------------------------------------------

class TestHostCallInJit:
    BAD = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = np.sum(x)\n"
        "    print(y)\n"
        "    z = float(x)\n"
        "    return y + x.item()\n"
    )
    GOOD = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = jnp.sum(x)\n"
        "    z = float(2.0)\n"   # literal coercion: trace-time constant
        "    return y + z\n"
        "def host(x):\n"
        "    print(np.sum(x))\n"  # host code may use numpy freely
        "    return float(x)\n"
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 4
        msgs = " ".join(f.message for f in findings)
        assert "np.sum" in msgs and "print" in msgs
        assert "float" in msgs and ".item()" in msgs

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [HostCallInJitRule()]) == []

    def test_telemetry_call_in_jit_flagged(self, tmp_path):
        """A span/metric/event call accidentally placed inside a traced
        function is a host-side contextvar/lock/file operation that fires
        once per TRACE — the rule must flag every telemetry spelling."""
        bad = (
            "import jax\n"
            "from pint_tpu import telemetry\n"
            "from pint_tpu.telemetry import span, event as _tevent\n"
            "from pint_tpu.telemetry import metrics as _metrics\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    with span('inner'):\n"             # bare imported name
            "        _tevent('tick', n=1)\n"        # aliased import
            "    telemetry.event('tock')\n"         # package alias
            "    _metrics.counter('c').inc()\n"     # submodule alias
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 4
        msgs = " ".join(f.message for f in findings)
        assert "telemetry call" in msgs and "once per TRACE" in msgs

    def test_telemetry_call_on_host_not_flagged(self, tmp_path):
        """The good twin: the same telemetry calls AROUND the jitted
        function (the documented pattern) are host code and stay silent."""
        good = (
            "import jax\n"
            "from pint_tpu import telemetry\n"
            "from pint_tpu.telemetry import span, event\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    with span('fit', n=3) as sp:\n"
            "        y = sp.sync(f(x))\n"
            "        event('done')\n"
            "        telemetry.metrics.counter('fits').inc()\n"
            "    return y\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_costs_call_in_jit_flagged(self, tmp_path):
        """telemetry.costs AOT analysis (lower/compile) inside a traced
        function would re-enter tracing once per TRACE — the rule's
        target set must cover the costs submodule like every other
        telemetry spelling."""
        bad = (
            "import jax\n"
            "from pint_tpu.telemetry import costs as _costs\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    _costs.record_cost_profile(_costs.analyze_jitted(f, x))\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2
        assert "telemetry call" in findings[0].message

    def test_costs_call_on_host_not_flagged(self, tmp_path):
        """Good twin: cost attribution of a jitted fn FROM host code is
        exactly the documented pattern and stays silent."""
        good = (
            "import jax\n"
            "from pint_tpu.telemetry import costs\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    prof = costs.analyze_jitted(f, x, name='f')\n"
            "    return costs.record_cost_profile(prof)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_distview_call_in_jit_flagged(self, tmp_path):
        """telemetry.distview's HLO scrape is AOT lower/compile + host
        parsing — inside a traced function it would re-enter tracing per
        TRACE; the rule's target set must cover the distview submodule
        like costs and every other telemetry spelling."""
        bad = (
            "import jax\n"
            "from pint_tpu.telemetry import distview\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    distview.analyze_jitted_collectives(f, x, name='f')\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"]
        assert "telemetry call" in findings[0].message

    def test_distview_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — observe the executable
        from host code around the jitted function — stays silent."""
        good = (
            "import jax\n"
            "from pint_tpu.telemetry import distview as _dv\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    prof = _dv.analyze_jitted_collectives(f, x, name='f')\n"
            "    _dv.record_sharding_plan(_dv.sharding_plan_of_jitted(\n"
            "        f, x, name='f'))\n"
            "    return _dv.record_collective_profile(prof)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_elastic_event_in_shard_map_flagged(self, tmp_path):
        """The elastic supervisor's lifecycle events (plan_selected /
        device_evicted / mesh_degraded) are host-side runlog writes; a
        shard_map-traced body that emits one (or canary-checks through
        numpy) would fire once per TRACE per device — the runtime/plan +
        runtime/elastic idiom the rule must police."""
        bad = (
            "import jax\n"
            "import numpy as np\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from pint_tpu import telemetry\n"
            "def block_body(pts):\n"
            "    telemetry.event('device_evicted', device_id=0)\n"
            "    return np.sum(pts ** 2, axis=-1)\n"
            "def dispatch(mesh, spec, pts):\n"
            "    return jax.jit(shard_map(block_body, mesh=mesh,\n"
            "                             in_specs=spec,\n"
            "                             out_specs=spec))(pts)\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2
        msgs = " ".join(f.message for f in findings)
        assert "telemetry call" in msgs and "np.sum" in msgs

    def test_elastic_supervisor_host_emit_not_flagged(self, tmp_path):
        """Good twin: the shipped pattern — the supervisor emits events
        and runs the numpy canary check AROUND the sharded dispatch
        (host code), the traced body stays pure jnp."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "import numpy as np\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from pint_tpu import telemetry\n"
            "def block_body(pts):\n"
            "    return jnp.sum(pts ** 2, axis=-1)\n"
            "def supervise(mesh, spec, pts, canary_rows):\n"
            "    telemetry.event('plan_selected', kind='shard_map',\n"
            "                    rung=mesh.devices.size)\n"
            "    out = jax.jit(shard_map(block_body, mesh=mesh,\n"
            "                            in_specs=spec,\n"
            "                            out_specs=spec))(pts)\n"
            "    vals = np.asarray(out)[canary_rows]\n"
            "    if not np.all(np.isfinite(vals)):\n"
            "        telemetry.event('device_evicted', device_id=0)\n"
            "    return out\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_serving_call_in_jit_flagged(self, tmp_path):
        """The warm-serving layer is pure host machinery (filesystem
        cache I/O, export serialization, asyncio, metrics) — an
        aotcache get/put or a pool warm inside a traced function would
        run per TRACE and hang the compile on cache I/O; the serving
        submodules are policed like the telemetry ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.serving import aotcache\n"
            "from pint_tpu.serving.warmup import WarmPool\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    aotcache.cache().get('grid.chunk', (x,))\n"
            "    WarmPool().warm('f', f, (x,))\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_serving_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — warm the pool and consult
        the cache from host code AROUND the jitted function."""
        good = (
            "import jax\n"
            "from pint_tpu.serving import aotcache, warmup\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    pool = warmup.WarmPool()\n"
            "    entry = pool.warm('f', f, (x,))\n"
            "    aotcache.cache()\n"
            "    return entry(x)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_serving_is_clean_target(self):
        """pint_tpu/serving/ itself lints clean under the host-call rule
        (its one traced function — the serve kernel — touches only
        jax/jnp) without pragmas or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/serving/aotcache.py",
                    "pint_tpu/serving/warmup.py",
                    "pint_tpu/serving/batcher.py",
                    "pint_tpu/serving/service.py",
                    "pint_tpu/serving/admission.py",
                    "pint_tpu/serving/scheduler.py",
                    "pint_tpu/serving/loadgen.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_traffic_submodules_tracked(self, tmp_path):
        """The PR 16 traffic-engineering submodules (admission /
        scheduler / loadgen) are host-side the same way the original
        four are: a shed check or a scheduler quantum inside a traced
        function would run per TRACE.  Bad twin fires per call, good
        twin (host-side arbitration around the jit) is clean."""
        from tools.jaxlint.engine import _SERVING_SUBMODULES

        assert {"admission", "scheduler", "loadgen"} <= \
            _SERVING_SUBMODULES
        bad = (
            "import jax\n"
            "from pint_tpu.serving import admission\n"
            "from pint_tpu.serving.scheduler import Scheduler\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    admission.AdmissionController().check('fit', 0)\n"
            "    Scheduler().quantum('fit')\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2
        good = (
            "import jax\n"
            "from pint_tpu.serving import admission\n"
            "from pint_tpu.serving.scheduler import Scheduler\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    shed = admission.AdmissionController().check('fit', 0)\n"
            "    Scheduler().note_dispatch('fit', 1)\n"
            "    return f(x) if shed is None else shed\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_serving_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/serving/ is a typed-raise target: a planted bare
        ValueError in a serving module fires, its UsageError twin does
        not."""
        from tools.jaxlint.rules.typed_raises import (
            DEFAULT_TARGETS,
            TypedRaiseRule,
        )

        assert "pint_tpu/serving/" in DEFAULT_TARGETS
        d = tmp_path / "pint_tpu" / "serving"
        d.mkdir(parents=True)
        bad = d / "bad.py"
        bad.write_text("def f():\n    raise ValueError('bare')\n")
        good = d / "good.py"
        good.write_text(
            "from pint_tpu.exceptions import UsageError\n"
            "def f():\n    raise UsageError('typed')\n")
        eng = Engine(rules=[TypedRaiseRule()], repo=str(tmp_path))
        assert rule_names(eng.lint_file(str(bad))) == ["typed-raise"]
        assert eng.lint_file(str(good)) == []

    def test_runtime_plan_and_elastic_are_clean_targets(self):
        """runtime/plan.py + runtime/elastic.py are lint targets of the
        host-call rule (they orchestrate traced dispatches from host
        code) and must stay clean without pragmas or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/runtime/plan.py",
                    "pint_tpu/runtime/elastic.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_autotune_call_in_jit_flagged(self, tmp_path):
        """The autotune layer is pure host machinery (manifest
        filesystem I/O, AOT lower/compile analyses, timed runs) — a
        resolve or search call inside a traced function would run per
        TRACE and recursively re-enter tracing through its own AOT
        analyses; the autotune submodules are policed like the
        telemetry/serving ones."""
        bad = (
            "import jax\n"
            "from pint_tpu import autotune\n"
            "from pint_tpu.autotune import search\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    autotune.resolve_grid_chunk(None, None)\n"
            "    search.tune_solve_rung(None)\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_autotune_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — resolve the tuned value
        on the host, close over the result in traced code."""
        good = (
            "import jax\n"
            "from pint_tpu import autotune\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(model, toas, x):\n"
            "    chunk = autotune.resolve_grid_chunk(model, toas)\n"
            "    return f(x[:chunk])\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_autotune_is_clean_target(self):
        """pint_tpu/autotune/ itself lints clean under the host-call
        rule (it defines no traced functions) without pragmas or
        baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/autotune/__init__.py",
                    "pint_tpu/autotune/search.py",
                    "pint_tpu/autotune/manifest.py",
                    "pint_tpu/autotune/records.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_autotune_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/autotune/ is a typed-raise target: a planted bare
        ValueError in an autotune module fires, its UsageError twin
        does not."""
        from tools.jaxlint.rules.typed_raises import DEFAULT_TARGETS

        assert "pint_tpu/autotune/" in DEFAULT_TARGETS
        d = tmp_path / "pint_tpu" / "autotune"
        d.mkdir(parents=True)
        bad = d / "bad.py"
        bad.write_text("def f():\n    raise ValueError('bare')\n")
        good = d / "good.py"
        good.write_text(
            "from pint_tpu.exceptions import UsageError\n"
            "def f():\n    raise UsageError('typed')\n")
        eng = Engine(rules=[TypedRaiseRule()], repo=str(tmp_path))
        assert rule_names(eng.lint_file(str(bad))) == ["typed-raise"]
        assert eng.lint_file(str(good)) == []

    def test_catalog_call_in_jit_flagged(self, tmp_path):
        """The catalog package is host orchestration (par/tim ingest +
        quarantine I/O, padding bookkeeping, HD geometry built once per
        catalog) — an ingest/fit/likelihood call inside a traced
        function would re-run the whole catalog build per TRACE; the
        catalog submodules are policed like the serving/autotune
        ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.catalog import ingest\n"
            "from pint_tpu.catalog.crosscorr import hd_matrix\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    ingest.ingest_catalog([])\n"
            "    hd_matrix(x)\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_catalog_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — ingest, bucket, and
        build the HD factor on the host; traced code touches only the
        padded operands the host prepared."""
        good = (
            "import jax\n"
            "from pint_tpu.catalog import batchfit, crosscorr\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    return x * 2\n"
            "def host(pairs, dirs):\n"
            "    L = crosscorr.hd_cholesky(dirs)\n"
            "    fn = batchfit.catalog_batched()\n"
            "    return fn, L\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_catalog_is_clean_target(self):
        """pint_tpu/catalog/ itself lints clean under the host-call
        rule (its traced kernels touch only jax/jnp) without pragmas
        or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/catalog/__init__.py",
                    "pint_tpu/catalog/ingest.py",
                    "pint_tpu/catalog/buckets.py",
                    "pint_tpu/catalog/batchfit.py",
                    "pint_tpu/catalog/crosscorr.py",
                    "pint_tpu/catalog/likelihood.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_catalog_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/catalog/ is a typed-raise target: a planted bare
        ValueError in a catalog module fires, its UsageError twin does
        not."""
        from tools.jaxlint.rules.typed_raises import DEFAULT_TARGETS

        assert "pint_tpu/catalog/" in DEFAULT_TARGETS
        d = tmp_path / "pint_tpu" / "catalog"
        d.mkdir(parents=True)
        bad = d / "bad.py"
        bad.write_text("def f():\n    raise ValueError('bare')\n")
        good = d / "good.py"
        good.write_text(
            "from pint_tpu.exceptions import UsageError\n"
            "def f():\n    raise UsageError('typed')\n")
        eng = Engine(rules=[TypedRaiseRule()], repo=str(tmp_path))
        assert rule_names(eng.lint_file(str(bad))) == ["typed-raise"]
        assert eng.lint_file(str(good)) == []

    def test_amortized_call_in_jit_flagged(self, tmp_path):
        """The amortized package is host orchestration (flow
        construction + training loops with checkpoint I/O, npz
        persistence, pool warming) — a train/load call inside a traced
        function would re-run the whole optimization per TRACE; the
        amortized submodules are policed like the serving/catalog
        ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.amortized import train\n"
            "from pint_tpu.amortized.train import train_flow\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    train.train_flow(x)\n"
            "    train_flow(x)\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_amortized_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — train/register on the
        host; traced code touches only Flow-instance methods (the
        traced maps are object attributes, not the modules' function
        surface)."""
        good = (
            "import jax\n"
            "from pint_tpu.amortized import elbo, train\n"
            "@jax.jit\n"
            "def kernel(flow, params, z):\n"
            "    u, logdet = flow.forward(params, z)\n"
            "    return u, logdet\n"
            "def host(lnpost, specs):\n"
            "    vi = elbo.AmortizedVI(lnpost, specs)\n"
            "    return train.train_flow(vi)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_amortized_is_clean_target(self):
        """pint_tpu/amortized/ itself lints clean under the host-call
        rule (its traced kernels touch only jax/jnp + the precision
        matmul) without pragmas or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/amortized/__init__.py",
                    "pint_tpu/amortized/flows.py",
                    "pint_tpu/amortized/elbo.py",
                    "pint_tpu/amortized/train.py",
                    "pint_tpu/amortized/posterior.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_amortized_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/amortized/ is a typed-raise target: a planted bare
        ValueError in an amortized module fires, its UsageError twin
        does not."""
        from tools.jaxlint.rules.typed_raises import DEFAULT_TARGETS

        assert "pint_tpu/amortized/" in DEFAULT_TARGETS
        d = tmp_path / "pint_tpu" / "amortized"
        d.mkdir(parents=True)
        bad = d / "bad.py"
        bad.write_text("def f():\n    raise ValueError('bare')\n")
        good = d / "good.py"
        good.write_text(
            "from pint_tpu.exceptions import UsageError\n"
            "def f():\n    raise UsageError('typed')\n")
        eng = Engine(rules=[TypedRaiseRule()], repo=str(tmp_path))
        assert rule_names(eng.lint_file(str(bad))) == ["typed-raise"]
        assert eng.lint_file(str(good)) == []

    def test_amortized_in_downcast_scope(self):
        """The unguarded-downcast rule covers the flow layers: a bare
        reduced cast in pint_tpu/amortized/ would bypass the
        flow.coupling segment budget."""
        from tools.jaxlint.rules.downcast import DOWNCAST_SCOPE

        assert "pint_tpu/amortized/" in DOWNCAST_SCOPE

    def test_streaming_call_in_jit_flagged(self, tmp_path):
        """The streaming package is host orchestration (factor-state
        bookkeeping, TOA merging/validation, checkpoint I/O, warm-pool
        registration) — an append/update call inside a traced function
        would re-enter the whole ingestion pipeline per TRACE; the
        streaming submodules are policed like the serving/catalog
        ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.streaming import cache\n"
            "from pint_tpu.streaming.lowrank import apply_rank_update\n"
            "@jax.jit\n"
            "def f(L, V):\n"
            "    cache.StreamCache(None, None)\n"
            "    apply_rank_update(L, V)\n"
            "    return L\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_streaming_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — the engine appends and
        warm-steps on the host; traced code touches only jnp math (the
        rank-k/warm-step kernels are module-level jit objects the
        cache dispatches, not the packages' function surface)."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from pint_tpu.streaming import update\n"
            "@jax.jit\n"
            "def kernel(L, b):\n"
            "    return jax.scipy.linalg.cho_solve((L, True), b)\n"
            "def host(ftr, blocks):\n"
            "    eng = update.StreamingGLS(ftr)\n"
            "    return [eng.update_toas(b) for b in blocks]\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_streaming_is_clean_target(self):
        """pint_tpu/streaming/ itself lints clean under the host-call
        rule (its traced kernels touch only jax/jnp; the one sanctioned
        cross-module traced call — the lowrank kernel core — carries
        its pragma)."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/streaming/__init__.py",
                    "pint_tpu/streaming/lowrank.py",
                    "pint_tpu/streaming/cache.py",
                    "pint_tpu/streaming/update.py",
                    "pint_tpu/streaming/door.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_streaming_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/streaming/ is a typed-raise target: a planted bare
        ValueError in a streaming module fires, its UsageError twin
        does not."""
        from tools.jaxlint.rules.typed_raises import DEFAULT_TARGETS

        assert "pint_tpu/streaming/" in DEFAULT_TARGETS
        d = tmp_path / "pint_tpu" / "streaming"
        d.mkdir(parents=True)
        bad = d / "bad.py"
        bad.write_text("def f():\n    raise ValueError('bare')\n")
        good = d / "good.py"
        good.write_text(
            "from pint_tpu.exceptions import UsageError\n"
            "def f():\n    raise UsageError('typed')\n")
        eng = Engine(rules=[TypedRaiseRule()], repo=str(tmp_path))
        assert rule_names(eng.lint_file(str(bad))) == ["typed-raise"]
        assert eng.lint_file(str(good)) == []

    def test_streaming_in_downcast_scope(self):
        """The unguarded-downcast rule covers the stream kernels: a
        bare reduced cast in pint_tpu/streaming/ would silently drop
        the factor state below the dd-split error budget."""
        from tools.jaxlint.rules.downcast import DOWNCAST_SCOPE

        assert "pint_tpu/streaming/" in DOWNCAST_SCOPE

    def test_durability_call_in_jit_flagged(self, tmp_path):
        """The durability layer is host I/O and orchestration: a
        journal commit (fsync!) or a chaos drill inside a traced
        function would block the trace on disk/asyncio per TRACE; both
        new submodules are policed like the rest of serving/runtime."""
        bad = (
            "import jax\n"
            "from pint_tpu.serving import journal\n"
            "from pint_tpu.runtime.chaos import run_drill\n"
            "@jax.jit\n"
            "def f(x, svc, reqs):\n"
            "    journal.UpdateJournal('/tmp/j', ['vk']).commit(reqs)\n"
            "    run_drill(svc, 'device_loss')\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_durability_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — the service journals
        and drills on the host; traced code touches only jnp math."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from pint_tpu.serving.journal import UpdateJournal\n"
            "from pint_tpu.runtime import chaos\n"
            "@jax.jit\n"
            "def kernel(M, r):\n"
            "    return jnp.dot(M.T, r)\n"
            "def host(svc, jdir, reqs):\n"
            "    with UpdateJournal(jdir, ['vk']) as j:\n"
            "        j.commit(reqs)\n"
            "    return chaos.run_drill(svc, 'device_loss')\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_durability_modules_are_clean_targets(self):
        """journal.py and chaos.py themselves lint clean under the
        full default rule set (the injected-fault raise sites carry
        their typed-raise pragmas)."""
        from tools.jaxlint.engine import (
            _RUNTIME_SUBMODULES,
            _SERVING_SUBMODULES,
        )

        assert "journal" in _SERVING_SUBMODULES
        assert "chaos" in _RUNTIME_SUBMODULES
        eng = Engine(rules=default_rules(), repo=REPO)
        for rel in ("pint_tpu/serving/journal.py",
                    "pint_tpu/runtime/chaos.py"):
            # run() applies the pragma layer (the chaos raise-factory
            # site carries a justified typed-raise pragma)
            res = eng.run([os.path.join(REPO, rel)])
            assert res.findings == [], "\n".join(
                f.render() for f in res.findings)

    def test_durability_in_typed_raise_targets(self, tmp_path):
        """Both new modules sit inside typed-raise target trees: a
        planted bare ValueError fires, the typed twin does not."""
        from tools.jaxlint.rules.typed_raises import DEFAULT_TARGETS

        assert "pint_tpu/serving/" in DEFAULT_TARGETS
        assert "pint_tpu/runtime/" in DEFAULT_TARGETS
        for pkg in ("serving", "runtime"):
            d = tmp_path / "pint_tpu" / pkg
            d.mkdir(parents=True)
            bad = d / "bad.py"
            bad.write_text("def f():\n    raise ValueError('bare')\n")
            good = d / "good.py"
            good.write_text(
                "from pint_tpu.exceptions import UsageError\n"
                "def f():\n    raise UsageError('typed')\n")
            eng = Engine(rules=[TypedRaiseRule()], repo=str(tmp_path))
            assert rule_names(eng.lint_file(str(bad))) == ["typed-raise"]
            assert eng.lint_file(str(good)) == []

    def test_static_shape_coercions_not_flagged(self, tmp_path):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    n = int(x.shape[0])\n"    # static at trace time
            "    m = int(len(x) * 2)\n"    # ditto
            "    return x * n * m\n"
        )
        assert lint_snippet(tmp_path, src, [HostCallInJitRule()]) == []


class TestImplicitDtype:
    BAD = (
        "import jax.numpy as jnp\n"
        "a = jnp.array([1.0, 2.0])\n"
        "b = jnp.zeros(3)\n"
        "c = jnp.asarray(1.5)\n"
    )
    GOOD = (
        "import jax.numpy as jnp\n"
        "a = jnp.array([1.0, 2.0], dtype=jnp.float64)\n"
        "b = jnp.zeros(3, dtype=jnp.float64)\n"
        "def convert(x):\n"
        "    return jnp.asarray(x)\n"  # pass-through keeps x's dtype
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD,
                                [ImplicitDtypeRule(files=None)])
        assert rule_names(findings) == ["implicit-dtype"] * 3

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD,
                            [ImplicitDtypeRule(files=None)]) == []

    def test_scoped_to_precision_core_by_default(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD,
                                [ImplicitDtypeRule(files=...)])
        assert findings == []  # snippet.py is not a precision-core file


class TestUnguardedDowncast:
    BAD = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x, U):\n"
        "    a = x.astype(jnp.float32)\n"
        "    b = U.astype(np.bfloat16)\n"
        "    c = x.astype('float32')\n"
        "    d = jnp.zeros(3, dtype=jnp.float32)\n"
        "    return a, b, c, d\n"
    )
    GOOD = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from pint_tpu.precision import downcast, matmul\n"
        "def f(x, U, spec):\n"
        "    a = x.astype(jnp.float64)\n"          # upcasts are free
        "    b = U.astype(np.float64)\n"
        "    c = downcast(x, 'float32')\n"         # the sanctioned route
        "    d = matmul(U, x, spec)\n"
        "    e = jnp.zeros(3, dtype=jnp.float64)\n"
        "    return a, b, c, d, e\n"
    )

    def test_fires_on_bad(self, tmp_path):
        from tools.jaxlint.rules.downcast import UnguardedDowncastRule

        findings = lint_snippet(tmp_path, self.BAD,
                                [UnguardedDowncastRule(files=None)])
        assert rule_names(findings) == ["unguarded-downcast"] * 4

    def test_silent_on_good(self, tmp_path):
        from tools.jaxlint.rules.downcast import UnguardedDowncastRule

        assert lint_snippet(tmp_path, self.GOOD,
                            [UnguardedDowncastRule(files=None)]) == []

    def test_scoped_to_downcast_scope_by_default(self, tmp_path):
        from tools.jaxlint.rules.downcast import UnguardedDowncastRule

        findings = lint_snippet(tmp_path, self.BAD,
                                [UnguardedDowncastRule(files=...)])
        assert findings == []  # snippet.py is outside the scoped set

    def test_precision_core_is_clean_target(self):
        """The scoped file set lints clean TODAY with zero baseline
        entries for this rule: every reduced cast in the core routes
        through pint_tpu.precision (grid.py's PR 10 correction casts
        included)."""
        from tools.jaxlint.rules.downcast import (
            DOWNCAST_SCOPE,
            UnguardedDowncastRule,
        )

        targets = [p for p in DOWNCAST_SCOPE
                   if os.path.exists(os.path.join(REPO, p))]
        assert targets
        result = Engine(rules=[UnguardedDowncastRule(files=...)],
                        repo=REPO).run(targets)
        assert result.findings == [], "\n".join(
            f.render() for f in result.findings)


class TestF32UnsafeLiteral:
    BAD = (
        "SPLIT = 134217729.0\n"     # 2**27+1: loses integer exactness
        "PRIOR = 1e40\n"            # overflows f32
        "TINY = 1e-300\n"           # flushes to zero
    )
    GOOD = (
        "HALF = 0.5\n"
        "DAY = 86400.0\n"
        "POW2 = 33554432.0\n"       # 2**25: exact in f32
        "EPS = 1e-3\n"              # a few ulps of drift is not value-class change
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD,
                                [F32UnsafeLiteralRule(files=None)])
        assert rule_names(findings) == ["f32-unsafe-literal"] * 3

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD,
                            [F32UnsafeLiteralRule(files=None)]) == []


class TestTracedBranch:
    BAD = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, lo):\n"
        "    y = x * 2\n"
        "    if y > lo:\n"          # traced-derived local in an `if`
        "        return y\n"
        "    while x > 0:\n"        # traced parameter in a `while`
        "        x = x - 1\n"
        "    return x\n"
    )
    GOOD = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "LIMIT = 3\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if len(x) > 2:\n"          # shape: static under tracing
        "        x = x + 1\n"
        "    if x.shape[0] > 1:\n"      # ditto\n"
        "        x = x * 2\n"
        "    if LIMIT > 2:\n"           # closure constant
        "        x = x - 1\n"
        "    return jnp.where(x > 0, x, -x)\n"
        "@partial(jax.jit, static_argnums=(1,))\n"
        "def g(x, n):\n"
        "    if n > 0:\n"               # static argument: host branch is fine
        "        return x\n"
        "    return -x\n"
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [TracedBranchRule()])
        assert rule_names(findings) == ["traced-branch"] * 2
        assert "`if`" in findings[0].message
        assert "`while`" in findings[1].message

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [TracedBranchRule()]) == []


class TestStaticArgs:
    BAD = (
        "import jax\n"
        "def f(x, opts=[]):\n"
        "    return x\n"
        "g = jax.jit(f, static_argnums=(1,))\n"
        "def key_of(d):\n"
        "    return tuple(d.items())\n"
    )
    GOOD = (
        "import jax\n"
        "def f(x, opts=()):\n"
        "    return x\n"
        "g = jax.jit(f, static_argnums=(1,))\n"
        "def key_of(d):\n"
        "    return tuple(sorted(d.items()))\n"
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [StaticArgsRule()])
        assert rule_names(findings) == ["static-args"] * 2
        msgs = " ".join(f.message for f in findings)
        assert "mutable" in msgs and "insertion order" in msgs

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [StaticArgsRule()]) == []

    def test_bare_dict_name_is_function_scoped(self, tmp_path):
        src = (
            "def a():\n"
            "    d = {}\n"
            "    return tuple(sorted(d.items()))\n"
            "def b():\n"
            "    d = []\n"          # same name, different type: no finding
            "    return tuple(d)\n"
        )
        assert lint_snippet(tmp_path, src, [StaticArgsRule()]) == []


class TestTypedRaise:
    def test_fires_on_bad_and_allows_typed(self, tmp_path):
        src = (
            "class MyError(Exception):\n"
            "    pass\n"
            "def f():\n"
            "    raise ValueError('bare')\n"
            "def g():\n"
            "    raise AllowedError('typed')\n"
            "def h():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception as e:\n"
            "        raise e\n"
        )
        rule = TypedRaiseRule(files=None, allowed={"AllowedError"})
        findings = lint_snippet(tmp_path, src, [rule])
        # ValueError flagged; MyError(Exception) is a local class NOT
        # rooted in an allowed name... but it is never raised, so only
        # the bare ValueError fires
        assert rule_names(findings) == ["typed-raise"]
        assert "ValueError" in findings[0].message

    def test_local_subclass_of_allowed_is_allowed(self, tmp_path):
        src = (
            "class Derived(AllowedError):\n"
            "    pass\n"
            "def f():\n"
            "    raise Derived('ok')\n"
            "def g():\n"
            "    raise Rogue('not ok')\n"
        )
        rule = TypedRaiseRule(files=None, allowed={"AllowedError"})
        findings = lint_snippet(tmp_path, src, [rule])
        assert rule_names(findings) == ["typed-raise"]
        assert "Rogue" in findings[0].message


# ---------------------------------------------------------------------------
# pragma + baseline round trips
# ---------------------------------------------------------------------------

class TestPragmaAndBaseline:
    SRC = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = np.sum(x)  # jaxlint: disable=host-call-in-jit -- fixture\n"
        "    b = np.mean(x)  # jaxlint: disable=all\n"
        "    return a + b + np.max(x)\n"
    )

    def test_pragma_suppresses_by_rule_and_all(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(self.SRC)
        result = Engine(rules=[HostCallInJitRule()],
                        repo=str(tmp_path)).run([str(p)])
        assert len(result.findings) == 1          # only the np.max line
        assert result.findings[0].lineno == 7
        assert result.suppressed == 2

    def test_unknown_pragma_rule_is_config_error(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(
            "import jax\nimport numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)  # jaxlint: disable=no-such-rule\n")
        with pytest.raises(ConfigError):
            Engine(rules=[HostCallInJitRule()],
                   repo=str(tmp_path)).run([str(p)])

    def test_baseline_round_trip(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(self.SRC)
        engine = Engine(rules=[HostCallInJitRule()], repo=str(tmp_path))
        findings = engine.collect([str(p)])
        assert len(findings) == 1
        bl_path = tmp_path / "baseline.txt"
        write_baseline(str(bl_path), findings)
        baseline = load_baseline(str(bl_path))
        result = engine.run([str(p)], baseline=baseline)
        assert result.findings == []
        assert result.baselined == 1
        assert result.stale_baseline == []

    def test_baseline_survives_line_drift_but_not_edits(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(self.SRC)
        engine = Engine(rules=[HostCallInJitRule()], repo=str(tmp_path))
        bl_path = tmp_path / "baseline.txt"
        write_baseline(str(bl_path), engine.collect([str(p)]))
        # unrelated lines added above: same entry still matches
        p.write_text("# a new leading comment\n" + self.SRC)
        result = engine.run([str(p)], baseline=load_baseline(str(bl_path)))
        assert result.findings == [] and result.baselined == 1
        # the flagged line itself changes: entry goes stale, finding is new
        p.write_text(self.SRC.replace("np.max(x)", "np.max(x) + 0"))
        result = engine.run([str(p)], baseline=load_baseline(str(bl_path)))
        assert len(result.findings) == 1
        assert len(result.stale_baseline) == 1

    def test_stale_is_scoped_to_linted_paths(self, tmp_path):
        """A partial-path run must not report other files' baseline
        entries as stale — they were simply not linted."""
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        for p in (a, b):
            p.write_text(
                "import jax\nimport numpy as np\n"
                "@jax.jit\n"
                "def f(x):\n"
                "    return np.sum(x)\n")
        engine = Engine(rules=[HostCallInJitRule()], repo=str(tmp_path))
        bl_path = tmp_path / "baseline.txt"
        write_baseline(str(bl_path), engine.collect([str(a), str(b)]))
        result = engine.run([str(a)], baseline=load_baseline(str(bl_path)))
        assert result.findings == []
        assert result.stale_baseline == []  # b.py's entry is NOT stale

    def test_update_baseline_preserves_justifications_and_scope(
            self, tmp_path, capsys):
        """--update-baseline keeps hand-written justifications of
        unchanged entries and retains entries for files outside the
        linted path set."""
        from tools.jaxlint.cli import main

        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        for p in (a, b):
            p.write_text(
                "import jax\nimport numpy as np\n"
                "@jax.jit\n"
                "def f(x):\n"
                "    return np.sum(x)\n")
        bl = tmp_path / "bl.txt"
        assert main([str(a), str(b), "--baseline", str(bl),
                     "--update-baseline"]) == 0
        # hand-edit the justifications
        text = bl.read_text()
        assert "TODO: justify" in text
        bl.write_text(text.replace("# TODO: justify",
                                   "# REVIEWED: fixture rationale", 1))
        # partial-path regeneration: a.py relinted, b.py out of scope
        assert main([str(a), "--baseline", str(bl),
                     "--update-baseline"]) == 0
        text = bl.read_text()
        assert "b.py" in text                      # out-of-scope retained
        assert "REVIEWED: fixture rationale" in text  # justification kept
        assert main([str(a), str(b), "--baseline", str(bl)]) == 0
        capsys.readouterr()

    def test_malformed_baseline_is_config_error(self, tmp_path):
        bl = tmp_path / "b.txt"
        bl.write_text("not a valid entry line\n")
        with pytest.raises(ConfigError):
            load_baseline(str(bl))


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------

class TestCli:
    def test_exit_codes(self, tmp_path, capsys):
        from tools.jaxlint.cli import main

        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\nimport numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n")
        assert main([str(clean), "--no-baseline"]) == 0
        assert main([str(bad), "--no-baseline"]) == 1
        assert main([str(bad), "--select", "no-such-rule"]) == 2
        assert main([str(tmp_path / "missing.py")]) == 2
        # unwritable baseline destination is a config error, not a crash
        assert main([str(bad), "--baseline",
                     str(tmp_path / "no-such-dir" / "bl.txt"),
                     "--update-baseline"]) == 2
        capsys.readouterr()

    def test_update_baseline_then_clean(self, tmp_path, capsys):
        from tools.jaxlint.cli import main

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\nimport numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n")
        bl = tmp_path / "bl.txt"
        assert main([str(bad), "--baseline", str(bl),
                     "--update-baseline"]) == 0
        assert main([str(bad), "--baseline", str(bl)]) == 0
        # a rule-subset rewrite would drop other rules' entries: refused
        assert main([str(bad), "--baseline", str(bl), "--update-baseline",
                     "--select", "host-call-in-jit"]) == 2
        capsys.readouterr()

    def test_list_rules(self, capsys):
        from tools.jaxlint.cli import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for name in RULES:
            assert name in out


class TestCollectiveAxisContext:
    """ISSUE 14 satellite: psum_scatter outside a shard_map axis
    context is a silent full-replication footgun under the SPMD
    partitioner."""

    BAD = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def gram(M, w):\n"
        "    pm = M.T @ (w[:, None] * M)\n"
        "    return jax.lax.psum_scatter(pm, 'toa',\n"
        "                                scatter_dimension=0, tiled=True)\n"
    )
    GOOD = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def build(mesh):\n"
        "    def gram(M, w):\n"
        "        pm = M.T @ (w[:, None] * M)\n"
        "        return jax.lax.psum_scatter(pm, 'toa',\n"
        "                                    scatter_dimension=0,\n"
        "                                    tiled=True)\n"
        "    return jax.jit(shard_map(gram, mesh=mesh,\n"
        "                             in_specs=(P('toa', None), P('toa')),\n"
        "                             out_specs=P('toa', None)))\n"
    )

    def _rule(self):
        from tools.jaxlint.rules.collective_context import (
            CollectiveAxisContextRule)

        return CollectiveAxisContextRule()

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [self._rule()])
        assert rule_names(findings) == ["collective-axis-context"]
        assert "shard_map" in findings[0].message
        assert "replicat" in findings[0].message

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [self._rule()]) == []

    def test_scan_inside_shard_map_body_not_flagged(self, tmp_path):
        """The row-chunked production shape: psum_scatter inside a
        lax.scan step that is NESTED in the shard_map body inherits the
        axis context (exactly workperbyte's chunked accumulation)."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from jax.sharding import PartitionSpec as P\n"
            "def build(mesh):\n"
            "    def scattered(M, w):\n"
            "        def step(carry, xs):\n"
            "            Mc, wc = xs\n"
            "            pm = Mc.T @ (wc[:, None] * Mc)\n"
            "            sm = jax.lax.psum_scatter(pm, 'toa',\n"
            "                                      scatter_dimension=0,\n"
            "                                      tiled=True)\n"
            "            return carry + sm, ()\n"
            "        init = jnp.zeros((4, 8))\n"
            "        out, _ = jax.lax.scan(step, init, (M, w))\n"
            "        return out\n"
            "    return jax.jit(shard_map(scattered, mesh=mesh,\n"
            "                             in_specs=P('toa', None),\n"
            "                             out_specs=P('toa', None)))\n"
        )
        assert lint_snippet(tmp_path, good, [self._rule()]) == []

    def test_module_level_scatter_flagged(self, tmp_path):
        bad = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "x = jax.lax.psum_scatter(jnp.ones((4, 4)), 'toa')\n"
        )
        findings = lint_snippet(tmp_path, bad, [self._rule()])
        assert rule_names(findings) == ["collective-axis-context"]

    def test_registered_by_default(self):
        assert "collective-axis-context" in RULES
        assert any(type(r).name == "collective-axis-context"
                   for r in default_rules())

    def test_workperbyte_kernel_is_clean(self):
        """The shipped scattered-Gram kernel passes its own rule (the
        scatters live inside the shard_map body)."""
        info = parse_file(os.path.join(
            REPO, "pint_tpu", "runtime", "workperbyte.py"), repo=REPO)
        assert list(self._rule().check(info)) == []


class TestWorkperbyteHostTarget:
    def test_workperbyte_call_in_jit_flagged(self, tmp_path):
        """The scan-fused era's host-call targets (ISSUE 14 satellite):
        workperbyte's scatter orchestration called inside a traced
        function re-enters tracing per TRACE — the host-call-in-jit
        target set must cover the runtime.workperbyte module."""
        bad = (
            "import jax\n"
            "from pint_tpu.runtime import workperbyte as _wpb\n"
            "from pint_tpu.runtime.workperbyte import "
            "verify_scatter_contract\n"
            "@jax.jit\n"
            "def f(M, r, Nvec, phiinv, plan):\n"
            "    m, y = _wpb.scattered_normal_equations(M, r, Nvec,\n"
            "                                           phiinv, plan)\n"
            "    verify_scatter_contract(f, M)\n"
            "    return m\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_workperbyte_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — build/verify on host,
        dispatch the jitted kernel."""
        good = (
            "import jax\n"
            "from pint_tpu.runtime.workperbyte import (\n"
            "    scattered_normal_equations, verify_scatter_contract)\n"
            "@jax.jit\n"
            "def solve(mtcm, mtcy):\n"
            "    return mtcm @ mtcy\n"
            "def host(M, r, Nvec, phiinv, plan):\n"
            "    m, y = scattered_normal_equations(M, r, Nvec, phiinv,\n"
            "                                      plan)\n"
            "    return solve(m, y)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []


# ---------------------------------------------------------------------------
# the contract: pint_tpu lints clean against the committed baseline
# ---------------------------------------------------------------------------

class TestRepoContract:
    def test_pint_tpu_clean_against_committed_baseline(self):
        baseline = load_baseline(os.path.join(REPO, "jaxlint_baseline.txt"))
        result = Engine(rules=default_rules(),
                        repo=REPO).run(["pint_tpu"], baseline=baseline)
        assert result.findings == [], "\n".join(
            f.render() for f in result.findings)

    def test_committed_baseline_has_no_stale_entries(self):
        baseline = load_baseline(os.path.join(REPO, "jaxlint_baseline.txt"))
        result = Engine(rules=default_rules(),
                        repo=REPO).run(["pint_tpu"], baseline=baseline)
        assert result.stale_baseline == []

    def test_every_baseline_entry_is_justified(self):
        """The baseline grandfathers, it does not hide: each entry must
        carry a justification comment line directly above it."""
        path = os.path.join(REPO, "jaxlint_baseline.txt")
        with open(path) as f:
            lines = [ln.rstrip() for ln in f]
        prev = ""
        for ln in lines:
            if ln and not ln.startswith("#"):
                assert prev.startswith("#") and len(prev) > 2, (
                    f"baseline entry lacks a justification comment: {ln!r}")
            prev = ln
