"""CI wiring + fixture tests for tools/jaxlint.

Each rule is exercised on a minimal bad snippet and its good twin, then
the pragma and baseline layers round-trip, and finally the whole of
``pint_tpu/`` must lint clean against the committed
``jaxlint_baseline.txt`` — a trace-safety regression in the hot path
fails the suite, not just a style check.
"""

import ast
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.jaxlint.engine import (  # noqa: E402
    ConfigError,
    Engine,
    load_baseline,
    parse_file,
    write_baseline,
)
from tools.jaxlint.rules import RULES, default_rules  # noqa: E402
from tools.jaxlint.rules.dtype_literals import (  # noqa: E402
    F32UnsafeLiteralRule,
    ImplicitDtypeRule,
)
from tools.jaxlint.rules.host_jit import HostCallInJitRule  # noqa: E402
from tools.jaxlint.rules.static_args import StaticArgsRule  # noqa: E402
from tools.jaxlint.rules.traced_branch import TracedBranchRule  # noqa: E402
from tools.jaxlint.rules.typed_raises import TypedRaiseRule  # noqa: E402
from tools.jaxlint.rules.async_discipline import (  # noqa: E402
    ASYNC_SCOPE,
    AwaitUnderLockRule,
    BlockingInCoroutineRule,
    StrandedFutureRule,
)


def lint_snippet(tmp_path, source, rules):
    p = tmp_path / "snippet.py"
    p.write_text(source)
    return Engine(rules=rules, repo=str(tmp_path)).lint_file(str(p))


def rule_names(findings):
    return [f.rule for f in findings]


def assert_twins(tmp_path, rules, bad, good, expected):
    """The shared twin-runner: the rule set reports exactly
    ``expected`` on the bad snippet and stays silent on the good twin.
    Returns the bad-twin findings for message assertions."""
    findings = lint_snippet(tmp_path, bad, rules)
    assert rule_names(findings) == expected, "\n".join(
        f.render() for f in findings)
    clean = lint_snippet(tmp_path, good, rules)
    assert clean == [], "\n".join(f.render() for f in clean)
    return findings


def assert_typed_raise_twins(tmp_path, pkg):
    """Twin-runner for typed-raise target coverage: ``pint_tpu/<pkg>/``
    must sit in DEFAULT_TARGETS, a planted bare ValueError there fires,
    and its UsageError twin stays silent."""
    from tools.jaxlint.rules.typed_raises import DEFAULT_TARGETS

    assert f"pint_tpu/{pkg}/" in DEFAULT_TARGETS
    d = tmp_path / "pint_tpu" / pkg
    d.mkdir(parents=True, exist_ok=True)
    bad = d / "bad.py"
    bad.write_text("def f():\n    raise ValueError('bare')\n")
    good = d / "good.py"
    good.write_text(
        "from pint_tpu.exceptions import UsageError\n"
        "def f():\n    raise UsageError('typed')\n")
    eng = Engine(rules=[TypedRaiseRule()], repo=str(tmp_path))
    assert rule_names(eng.lint_file(str(bad))) == ["typed-raise"]
    assert eng.lint_file(str(good)) == []


# ---------------------------------------------------------------------------
# traced-function discovery (the engine core the rules stand on)
# ---------------------------------------------------------------------------

class TestTracedDiscovery:
    def test_decorator_wrap_scan_and_nested(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.jit\n"
            "def decorated(x):\n"
            "    return x\n"
            "def wrapped(x):\n"
            "    def nested(y):\n"
            "        return y\n"
            "    return nested(x)\n"
            "fn = jax.jit(jax.vmap(wrapped))\n"
            "def scan_body(c, x):\n"
            "    return c, x\n"
            "def host(x):\n"
            "    return jax.lax.scan(scan_body, 0.0, x)\n")
        info = parse_file(str(p), repo=str(tmp_path))
        names = {getattr(td.node, "name", "<lambda>")
                 for td in info.traced_defs}
        assert names == {"decorated", "wrapped", "nested", "scan_body"}

    def test_lax_data_operands_not_marked(self, tmp_path):
        """Only function *positions* of lax combinators mark defs: a
        cond predicate or scan carry sharing a def's name must not."""
        p = tmp_path / "t.py"
        p.write_text(
            "import jax\n"
            "import numpy as np\n"
            "def pred(a):\n"
            "    return np.sum(a) > 0\n"   # host-only helper
            "def tfn(o):\n"
            "    return o\n"
            "def ffn(o):\n"
            "    return o\n"
            "def host(x):\n"
            "    return jax.lax.cond(pred, tfn, ffn, x)\n")
        info = parse_file(str(p), repo=str(tmp_path))
        names = {getattr(td.node, "name", "<lambda>")
                 for td in info.traced_defs}
        assert names == {"tfn", "ffn"}
        assert lint_snippet(tmp_path, p.read_text(),
                            [HostCallInJitRule()]) == []

    def test_non_jax_jit_attribute_not_marked(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "import numpy as np\n"
            "def f(x):\n"
            "    return np.sum(x)\n"
            "class C:\n"
            "    def jit(self, fn):\n"
            "        return fn\n"
            "c = C()\n"
            "g = c.jit(f)\n")   # not jax.jit: f stays a host function
        info = parse_file(str(p), repo=str(tmp_path))
        assert info.traced_defs == []

    def test_dotted_jax_numpy_import_covered(self, tmp_path):
        src = (
            "import jax.numpy\n"
            "a = jax.numpy.zeros(3)\n"
            "b = jax.numpy.array([1.0])\n"
        )
        findings = lint_snippet(tmp_path, src,
                                [ImplicitDtypeRule(files=None)])
        assert rule_names(findings) == ["implicit-dtype"] * 2

    def test_partial_jit_static_argnums(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "import jax\n"
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, n):\n"
            "    return x\n")
        info = parse_file(str(p), repo=str(tmp_path))
        (td,) = info.traced_defs
        assert td.static_params == {"n"}

    def test_aliased_from_import_still_entry(self, tmp_path):
        p = tmp_path / "t.py"
        p.write_text(
            "from jax import jit as jjit\n"
            "import numpy as np\n"
            "@jjit\n"
            "def f(x):\n"
            "    return np.sin(x)\n")
        info = parse_file(str(p), repo=str(tmp_path))
        assert {td.node.name for td in info.traced_defs} == {"f"}
        findings = lint_snippet(tmp_path, p.read_text(),
                                [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"]


# ---------------------------------------------------------------------------
# rule fixtures: each fires on the bad snippet, stays silent on the twin
# ---------------------------------------------------------------------------

class TestHostCallInJit:
    BAD = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = np.sum(x)\n"
        "    print(y)\n"
        "    z = float(x)\n"
        "    return y + x.item()\n"
    )
    GOOD = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    y = jnp.sum(x)\n"
        "    z = float(2.0)\n"   # literal coercion: trace-time constant
        "    return y + z\n"
        "def host(x):\n"
        "    print(np.sum(x))\n"  # host code may use numpy freely
        "    return float(x)\n"
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 4
        msgs = " ".join(f.message for f in findings)
        assert "np.sum" in msgs and "print" in msgs
        assert "float" in msgs and ".item()" in msgs

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [HostCallInJitRule()]) == []

    def test_telemetry_call_in_jit_flagged(self, tmp_path):
        """A span/metric/event call accidentally placed inside a traced
        function is a host-side contextvar/lock/file operation that fires
        once per TRACE — the rule must flag every telemetry spelling."""
        bad = (
            "import jax\n"
            "from pint_tpu import telemetry\n"
            "from pint_tpu.telemetry import span, event as _tevent\n"
            "from pint_tpu.telemetry import metrics as _metrics\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    with span('inner'):\n"             # bare imported name
            "        _tevent('tick', n=1)\n"        # aliased import
            "    telemetry.event('tock')\n"         # package alias
            "    _metrics.counter('c').inc()\n"     # submodule alias
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 4
        msgs = " ".join(f.message for f in findings)
        assert "telemetry call" in msgs and "once per TRACE" in msgs

    def test_telemetry_call_on_host_not_flagged(self, tmp_path):
        """The good twin: the same telemetry calls AROUND the jitted
        function (the documented pattern) are host code and stay silent."""
        good = (
            "import jax\n"
            "from pint_tpu import telemetry\n"
            "from pint_tpu.telemetry import span, event\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    with span('fit', n=3) as sp:\n"
            "        y = sp.sync(f(x))\n"
            "        event('done')\n"
            "        telemetry.metrics.counter('fits').inc()\n"
            "    return y\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_costs_call_in_jit_flagged(self, tmp_path):
        """telemetry.costs AOT analysis (lower/compile) inside a traced
        function would re-enter tracing once per TRACE — the rule's
        target set must cover the costs submodule like every other
        telemetry spelling."""
        bad = (
            "import jax\n"
            "from pint_tpu.telemetry import costs as _costs\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    _costs.record_cost_profile(_costs.analyze_jitted(f, x))\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2
        assert "telemetry call" in findings[0].message

    def test_costs_call_on_host_not_flagged(self, tmp_path):
        """Good twin: cost attribution of a jitted fn FROM host code is
        exactly the documented pattern and stays silent."""
        good = (
            "import jax\n"
            "from pint_tpu.telemetry import costs\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    prof = costs.analyze_jitted(f, x, name='f')\n"
            "    return costs.record_cost_profile(prof)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_distview_call_in_jit_flagged(self, tmp_path):
        """telemetry.distview's HLO scrape is AOT lower/compile + host
        parsing — inside a traced function it would re-enter tracing per
        TRACE; the rule's target set must cover the distview submodule
        like costs and every other telemetry spelling."""
        bad = (
            "import jax\n"
            "from pint_tpu.telemetry import distview\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    distview.analyze_jitted_collectives(f, x, name='f')\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"]
        assert "telemetry call" in findings[0].message

    def test_distview_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — observe the executable
        from host code around the jitted function — stays silent."""
        good = (
            "import jax\n"
            "from pint_tpu.telemetry import distview as _dv\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    prof = _dv.analyze_jitted_collectives(f, x, name='f')\n"
            "    _dv.record_sharding_plan(_dv.sharding_plan_of_jitted(\n"
            "        f, x, name='f'))\n"
            "    return _dv.record_collective_profile(prof)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_elastic_event_in_shard_map_flagged(self, tmp_path):
        """The elastic supervisor's lifecycle events (plan_selected /
        device_evicted / mesh_degraded) are host-side runlog writes; a
        shard_map-traced body that emits one (or canary-checks through
        numpy) would fire once per TRACE per device — the runtime/plan +
        runtime/elastic idiom the rule must police."""
        bad = (
            "import jax\n"
            "import numpy as np\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from pint_tpu import telemetry\n"
            "def block_body(pts):\n"
            "    telemetry.event('device_evicted', device_id=0)\n"
            "    return np.sum(pts ** 2, axis=-1)\n"
            "def dispatch(mesh, spec, pts):\n"
            "    return jax.jit(shard_map(block_body, mesh=mesh,\n"
            "                             in_specs=spec,\n"
            "                             out_specs=spec))(pts)\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2
        msgs = " ".join(f.message for f in findings)
        assert "telemetry call" in msgs and "np.sum" in msgs

    def test_elastic_supervisor_host_emit_not_flagged(self, tmp_path):
        """Good twin: the shipped pattern — the supervisor emits events
        and runs the numpy canary check AROUND the sharded dispatch
        (host code), the traced body stays pure jnp."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "import numpy as np\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from pint_tpu import telemetry\n"
            "def block_body(pts):\n"
            "    return jnp.sum(pts ** 2, axis=-1)\n"
            "def supervise(mesh, spec, pts, canary_rows):\n"
            "    telemetry.event('plan_selected', kind='shard_map',\n"
            "                    rung=mesh.devices.size)\n"
            "    out = jax.jit(shard_map(block_body, mesh=mesh,\n"
            "                            in_specs=spec,\n"
            "                            out_specs=spec))(pts)\n"
            "    vals = np.asarray(out)[canary_rows]\n"
            "    if not np.all(np.isfinite(vals)):\n"
            "        telemetry.event('device_evicted', device_id=0)\n"
            "    return out\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_serving_call_in_jit_flagged(self, tmp_path):
        """The warm-serving layer is pure host machinery (filesystem
        cache I/O, export serialization, asyncio, metrics) — an
        aotcache get/put or a pool warm inside a traced function would
        run per TRACE and hang the compile on cache I/O; the serving
        submodules are policed like the telemetry ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.serving import aotcache\n"
            "from pint_tpu.serving.warmup import WarmPool\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    aotcache.cache().get('grid.chunk', (x,))\n"
            "    WarmPool().warm('f', f, (x,))\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_serving_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — warm the pool and consult
        the cache from host code AROUND the jitted function."""
        good = (
            "import jax\n"
            "from pint_tpu.serving import aotcache, warmup\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    pool = warmup.WarmPool()\n"
            "    entry = pool.warm('f', f, (x,))\n"
            "    aotcache.cache()\n"
            "    return entry(x)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_serving_is_clean_target(self):
        """pint_tpu/serving/ itself lints clean under the host-call rule
        (its one traced function — the serve kernel — touches only
        jax/jnp) without pragmas or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/serving/aotcache.py",
                    "pint_tpu/serving/warmup.py",
                    "pint_tpu/serving/batcher.py",
                    "pint_tpu/serving/service.py",
                    "pint_tpu/serving/admission.py",
                    "pint_tpu/serving/scheduler.py",
                    "pint_tpu/serving/loadgen.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_traffic_submodules_tracked(self, tmp_path):
        """The PR 16 traffic-engineering submodules (admission /
        scheduler / loadgen) are host-side the same way the original
        four are: a shed check or a scheduler quantum inside a traced
        function would run per TRACE.  Bad twin fires per call, good
        twin (host-side arbitration around the jit) is clean."""
        from tools.jaxlint.engine import _SERVING_SUBMODULES

        assert {"admission", "scheduler", "loadgen"} <= \
            _SERVING_SUBMODULES
        bad = (
            "import jax\n"
            "from pint_tpu.serving import admission\n"
            "from pint_tpu.serving.scheduler import Scheduler\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    admission.AdmissionController().check('fit', 0)\n"
            "    Scheduler().quantum('fit')\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2
        good = (
            "import jax\n"
            "from pint_tpu.serving import admission\n"
            "from pint_tpu.serving.scheduler import Scheduler\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(x):\n"
            "    shed = admission.AdmissionController().check('fit', 0)\n"
            "    Scheduler().note_dispatch('fit', 1)\n"
            "    return f(x) if shed is None else shed\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_serving_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/serving/ is a typed-raise target: a planted bare
        ValueError in a serving module fires, its UsageError twin does
        not."""
        assert_typed_raise_twins(tmp_path, "serving")

    def test_runtime_plan_and_elastic_are_clean_targets(self):
        """runtime/plan.py + runtime/elastic.py are lint targets of the
        host-call rule (they orchestrate traced dispatches from host
        code) and must stay clean without pragmas or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/runtime/plan.py",
                    "pint_tpu/runtime/elastic.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_autotune_call_in_jit_flagged(self, tmp_path):
        """The autotune layer is pure host machinery (manifest
        filesystem I/O, AOT lower/compile analyses, timed runs) — a
        resolve or search call inside a traced function would run per
        TRACE and recursively re-enter tracing through its own AOT
        analyses; the autotune submodules are policed like the
        telemetry/serving ones."""
        bad = (
            "import jax\n"
            "from pint_tpu import autotune\n"
            "from pint_tpu.autotune import search\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    autotune.resolve_grid_chunk(None, None)\n"
            "    search.tune_solve_rung(None)\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_autotune_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — resolve the tuned value
        on the host, close over the result in traced code."""
        good = (
            "import jax\n"
            "from pint_tpu import autotune\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
            "def host(model, toas, x):\n"
            "    chunk = autotune.resolve_grid_chunk(model, toas)\n"
            "    return f(x[:chunk])\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_autotune_is_clean_target(self):
        """pint_tpu/autotune/ itself lints clean under the host-call
        rule (it defines no traced functions) without pragmas or
        baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/autotune/__init__.py",
                    "pint_tpu/autotune/search.py",
                    "pint_tpu/autotune/manifest.py",
                    "pint_tpu/autotune/records.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_autotune_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/autotune/ is a typed-raise target: a planted bare
        ValueError in an autotune module fires, its UsageError twin
        does not."""
        assert_typed_raise_twins(tmp_path, "autotune")

    def test_catalog_call_in_jit_flagged(self, tmp_path):
        """The catalog package is host orchestration (par/tim ingest +
        quarantine I/O, padding bookkeeping, HD geometry built once per
        catalog) — an ingest/fit/likelihood call inside a traced
        function would re-run the whole catalog build per TRACE; the
        catalog submodules are policed like the serving/autotune
        ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.catalog import ingest\n"
            "from pint_tpu.catalog.crosscorr import hd_matrix\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    ingest.ingest_catalog([])\n"
            "    hd_matrix(x)\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_catalog_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — ingest, bucket, and
        build the HD factor on the host; traced code touches only the
        padded operands the host prepared."""
        good = (
            "import jax\n"
            "from pint_tpu.catalog import batchfit, crosscorr\n"
            "@jax.jit\n"
            "def kernel(x):\n"
            "    return x * 2\n"
            "def host(pairs, dirs):\n"
            "    L = crosscorr.hd_cholesky(dirs)\n"
            "    fn = batchfit.catalog_batched()\n"
            "    return fn, L\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_catalog_is_clean_target(self):
        """pint_tpu/catalog/ itself lints clean under the host-call
        rule (its traced kernels touch only jax/jnp) without pragmas
        or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/catalog/__init__.py",
                    "pint_tpu/catalog/ingest.py",
                    "pint_tpu/catalog/buckets.py",
                    "pint_tpu/catalog/batchfit.py",
                    "pint_tpu/catalog/crosscorr.py",
                    "pint_tpu/catalog/likelihood.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_catalog_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/catalog/ is a typed-raise target: a planted bare
        ValueError in a catalog module fires, its UsageError twin does
        not."""
        assert_typed_raise_twins(tmp_path, "catalog")

    def test_amortized_call_in_jit_flagged(self, tmp_path):
        """The amortized package is host orchestration (flow
        construction + training loops with checkpoint I/O, npz
        persistence, pool warming) — a train/load call inside a traced
        function would re-run the whole optimization per TRACE; the
        amortized submodules are policed like the serving/catalog
        ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.amortized import train\n"
            "from pint_tpu.amortized.train import train_flow\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    train.train_flow(x)\n"
            "    train_flow(x)\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_amortized_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — train/register on the
        host; traced code touches only Flow-instance methods (the
        traced maps are object attributes, not the modules' function
        surface)."""
        good = (
            "import jax\n"
            "from pint_tpu.amortized import elbo, train\n"
            "@jax.jit\n"
            "def kernel(flow, params, z):\n"
            "    u, logdet = flow.forward(params, z)\n"
            "    return u, logdet\n"
            "def host(lnpost, specs):\n"
            "    vi = elbo.AmortizedVI(lnpost, specs)\n"
            "    return train.train_flow(vi)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_amortized_is_clean_target(self):
        """pint_tpu/amortized/ itself lints clean under the host-call
        rule (its traced kernels touch only jax/jnp + the precision
        matmul) without pragmas or baseline entries."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/amortized/__init__.py",
                    "pint_tpu/amortized/flows.py",
                    "pint_tpu/amortized/elbo.py",
                    "pint_tpu/amortized/train.py",
                    "pint_tpu/amortized/posterior.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_amortized_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/amortized/ is a typed-raise target: a planted bare
        ValueError in an amortized module fires, its UsageError twin
        does not."""
        assert_typed_raise_twins(tmp_path, "amortized")

    def test_amortized_in_downcast_scope(self):
        """The unguarded-downcast rule covers the flow layers: a bare
        reduced cast in pint_tpu/amortized/ would bypass the
        flow.coupling segment budget."""
        from tools.jaxlint.rules.downcast import DOWNCAST_SCOPE

        assert "pint_tpu/amortized/" in DOWNCAST_SCOPE

    def test_streaming_call_in_jit_flagged(self, tmp_path):
        """The streaming package is host orchestration (factor-state
        bookkeeping, TOA merging/validation, checkpoint I/O, warm-pool
        registration) — an append/update call inside a traced function
        would re-enter the whole ingestion pipeline per TRACE; the
        streaming submodules are policed like the serving/catalog
        ones."""
        bad = (
            "import jax\n"
            "from pint_tpu.streaming import cache\n"
            "from pint_tpu.streaming.lowrank import apply_rank_update\n"
            "@jax.jit\n"
            "def f(L, V):\n"
            "    cache.StreamCache(None, None)\n"
            "    apply_rank_update(L, V)\n"
            "    return L\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_streaming_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — the engine appends and
        warm-steps on the host; traced code touches only jnp math (the
        rank-k/warm-step kernels are module-level jit objects the
        cache dispatches, not the packages' function surface)."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from pint_tpu.streaming import update\n"
            "@jax.jit\n"
            "def kernel(L, b):\n"
            "    return jax.scipy.linalg.cho_solve((L, True), b)\n"
            "def host(ftr, blocks):\n"
            "    eng = update.StreamingGLS(ftr)\n"
            "    return [eng.update_toas(b) for b in blocks]\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_streaming_is_clean_target(self):
        """pint_tpu/streaming/ itself lints clean under the host-call
        rule (its traced kernels touch only jax/jnp; the one sanctioned
        cross-module traced call — the lowrank kernel core — carries
        its pragma)."""
        eng = Engine(rules=[HostCallInJitRule()], repo=REPO)
        for rel in ("pint_tpu/streaming/__init__.py",
                    "pint_tpu/streaming/lowrank.py",
                    "pint_tpu/streaming/cache.py",
                    "pint_tpu/streaming/update.py",
                    "pint_tpu/streaming/door.py"):
            findings = eng.lint_file(os.path.join(REPO, rel))
            assert findings == [], "\n".join(f.render() for f in findings)

    def test_streaming_in_typed_raise_targets(self, tmp_path):
        """pint_tpu/streaming/ is a typed-raise target: a planted bare
        ValueError in a streaming module fires, its UsageError twin
        does not."""
        assert_typed_raise_twins(tmp_path, "streaming")

    def test_streaming_in_downcast_scope(self):
        """The unguarded-downcast rule covers the stream kernels: a
        bare reduced cast in pint_tpu/streaming/ would silently drop
        the factor state below the dd-split error budget."""
        from tools.jaxlint.rules.downcast import DOWNCAST_SCOPE

        assert "pint_tpu/streaming/" in DOWNCAST_SCOPE

    def test_durability_call_in_jit_flagged(self, tmp_path):
        """The durability layer is host I/O and orchestration: a
        journal commit (fsync!) or a chaos drill inside a traced
        function would block the trace on disk/asyncio per TRACE; both
        new submodules are policed like the rest of serving/runtime."""
        bad = (
            "import jax\n"
            "from pint_tpu.serving import journal\n"
            "from pint_tpu.runtime.chaos import run_drill\n"
            "@jax.jit\n"
            "def f(x, svc, reqs):\n"
            "    journal.UpdateJournal('/tmp/j', ['vk']).commit(reqs)\n"
            "    run_drill(svc, 'device_loss')\n"
            "    return x\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_durability_call_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — the service journals
        and drills on the host; traced code touches only jnp math."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from pint_tpu.serving.journal import UpdateJournal\n"
            "from pint_tpu.runtime import chaos\n"
            "@jax.jit\n"
            "def kernel(M, r):\n"
            "    return jnp.dot(M.T, r)\n"
            "def host(svc, jdir, reqs):\n"
            "    with UpdateJournal(jdir, ['vk']) as j:\n"
            "        j.commit(reqs)\n"
            "    return chaos.run_drill(svc, 'device_loss')\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []

    def test_durability_modules_are_clean_targets(self):
        """journal.py and chaos.py themselves lint clean under the
        full default rule set (the injected-fault raise sites carry
        their typed-raise pragmas)."""
        from tools.jaxlint.engine import (
            _RUNTIME_SUBMODULES,
            _SERVING_SUBMODULES,
        )

        assert "journal" in _SERVING_SUBMODULES
        assert "chaos" in _RUNTIME_SUBMODULES
        eng = Engine(rules=default_rules(), repo=REPO)
        for rel in ("pint_tpu/serving/journal.py",
                    "pint_tpu/runtime/chaos.py"):
            # run() applies the pragma layer (the chaos raise-factory
            # site carries a justified typed-raise pragma)
            res = eng.run([os.path.join(REPO, rel)])
            assert res.findings == [], "\n".join(
                f.render() for f in res.findings)

    def test_durability_in_typed_raise_targets(self, tmp_path):
        """Both new modules sit inside typed-raise target trees: a
        planted bare ValueError fires, the typed twin does not."""
        for pkg in ("serving", "runtime"):
            assert_typed_raise_twins(tmp_path, pkg)

    def test_observatory_modules_are_clean_targets(self):
        """The request-lifecycle-observatory modules (reqtrace /
        flightrec in telemetry, slo in serving) are auto-tracked by the
        package view and lint clean under the full default rule set —
        a trace mark or a ring note inside a traced function would run
        per TRACE like any other host call."""
        from tools.jaxlint.engine import (
            _SERVING_SUBMODULES,
            _TELEMETRY_SUBMODULES,
        )

        assert "slo" in _SERVING_SUBMODULES
        assert "reqtrace" in _TELEMETRY_SUBMODULES
        assert "flightrec" in _TELEMETRY_SUBMODULES
        eng = Engine(rules=default_rules(), repo=REPO)
        for rel in ("pint_tpu/telemetry/reqtrace.py",
                    "pint_tpu/telemetry/flightrec.py",
                    "pint_tpu/serving/slo.py"):
            res = eng.run([os.path.join(REPO, rel)])
            assert res.findings == [], "\n".join(
                f.render() for f in res.findings)

    def test_static_shape_coercions_not_flagged(self, tmp_path):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    n = int(x.shape[0])\n"    # static at trace time
            "    m = int(len(x) * 2)\n"    # ditto
            "    return x * n * m\n"
        )
        assert lint_snippet(tmp_path, src, [HostCallInJitRule()]) == []


class TestImplicitDtype:
    BAD = (
        "import jax.numpy as jnp\n"
        "a = jnp.array([1.0, 2.0])\n"
        "b = jnp.zeros(3)\n"
        "c = jnp.asarray(1.5)\n"
    )
    GOOD = (
        "import jax.numpy as jnp\n"
        "a = jnp.array([1.0, 2.0], dtype=jnp.float64)\n"
        "b = jnp.zeros(3, dtype=jnp.float64)\n"
        "def convert(x):\n"
        "    return jnp.asarray(x)\n"  # pass-through keeps x's dtype
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD,
                                [ImplicitDtypeRule(files=None)])
        assert rule_names(findings) == ["implicit-dtype"] * 3

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD,
                            [ImplicitDtypeRule(files=None)]) == []

    def test_scoped_to_precision_core_by_default(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD,
                                [ImplicitDtypeRule(files=...)])
        assert findings == []  # snippet.py is not a precision-core file


class TestUnguardedDowncast:
    BAD = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x, U):\n"
        "    a = x.astype(jnp.float32)\n"
        "    b = U.astype(np.bfloat16)\n"
        "    c = x.astype('float32')\n"
        "    d = jnp.zeros(3, dtype=jnp.float32)\n"
        "    return a, b, c, d\n"
    )
    GOOD = (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "from pint_tpu.precision import downcast, matmul\n"
        "def f(x, U, spec):\n"
        "    a = x.astype(jnp.float64)\n"          # upcasts are free
        "    b = U.astype(np.float64)\n"
        "    c = downcast(x, 'float32')\n"         # the sanctioned route
        "    d = matmul(U, x, spec)\n"
        "    e = jnp.zeros(3, dtype=jnp.float64)\n"
        "    return a, b, c, d, e\n"
    )

    def test_fires_on_bad(self, tmp_path):
        from tools.jaxlint.rules.downcast import UnguardedDowncastRule

        findings = lint_snippet(tmp_path, self.BAD,
                                [UnguardedDowncastRule(files=None)])
        assert rule_names(findings) == ["unguarded-downcast"] * 4

    def test_silent_on_good(self, tmp_path):
        from tools.jaxlint.rules.downcast import UnguardedDowncastRule

        assert lint_snippet(tmp_path, self.GOOD,
                            [UnguardedDowncastRule(files=None)]) == []

    def test_scoped_to_downcast_scope_by_default(self, tmp_path):
        from tools.jaxlint.rules.downcast import UnguardedDowncastRule

        findings = lint_snippet(tmp_path, self.BAD,
                                [UnguardedDowncastRule(files=...)])
        assert findings == []  # snippet.py is outside the scoped set

    def test_precision_core_is_clean_target(self):
        """The scoped file set lints clean TODAY with zero baseline
        entries for this rule: every reduced cast in the core routes
        through pint_tpu.precision (grid.py's PR 10 correction casts
        included)."""
        from tools.jaxlint.rules.downcast import (
            DOWNCAST_SCOPE,
            UnguardedDowncastRule,
        )

        targets = [p for p in DOWNCAST_SCOPE
                   if os.path.exists(os.path.join(REPO, p))]
        assert targets
        result = Engine(rules=[UnguardedDowncastRule(files=...)],
                        repo=REPO).run(targets)
        assert result.findings == [], "\n".join(
            f.render() for f in result.findings)


class TestF32UnsafeLiteral:
    BAD = (
        "SPLIT = 134217729.0\n"     # 2**27+1: loses integer exactness
        "PRIOR = 1e40\n"            # overflows f32
        "TINY = 1e-300\n"           # flushes to zero
    )
    GOOD = (
        "HALF = 0.5\n"
        "DAY = 86400.0\n"
        "POW2 = 33554432.0\n"       # 2**25: exact in f32
        "EPS = 1e-3\n"              # a few ulps of drift is not value-class change
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD,
                                [F32UnsafeLiteralRule(files=None)])
        assert rule_names(findings) == ["f32-unsafe-literal"] * 3

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD,
                            [F32UnsafeLiteralRule(files=None)]) == []


class TestTracedBranch:
    BAD = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, lo):\n"
        "    y = x * 2\n"
        "    if y > lo:\n"          # traced-derived local in an `if`
        "        return y\n"
        "    while x > 0:\n"        # traced parameter in a `while`
        "        x = x - 1\n"
        "    return x\n"
    )
    GOOD = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "LIMIT = 3\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if len(x) > 2:\n"          # shape: static under tracing
        "        x = x + 1\n"
        "    if x.shape[0] > 1:\n"      # ditto\n"
        "        x = x * 2\n"
        "    if LIMIT > 2:\n"           # closure constant
        "        x = x - 1\n"
        "    return jnp.where(x > 0, x, -x)\n"
        "@partial(jax.jit, static_argnums=(1,))\n"
        "def g(x, n):\n"
        "    if n > 0:\n"               # static argument: host branch is fine
        "        return x\n"
        "    return -x\n"
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [TracedBranchRule()])
        assert rule_names(findings) == ["traced-branch"] * 2
        assert "`if`" in findings[0].message
        assert "`while`" in findings[1].message

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [TracedBranchRule()]) == []


class TestStaticArgs:
    BAD = (
        "import jax\n"
        "def f(x, opts=[]):\n"
        "    return x\n"
        "g = jax.jit(f, static_argnums=(1,))\n"
        "def key_of(d):\n"
        "    return tuple(d.items())\n"
    )
    GOOD = (
        "import jax\n"
        "def f(x, opts=()):\n"
        "    return x\n"
        "g = jax.jit(f, static_argnums=(1,))\n"
        "def key_of(d):\n"
        "    return tuple(sorted(d.items()))\n"
    )

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [StaticArgsRule()])
        assert rule_names(findings) == ["static-args"] * 2
        msgs = " ".join(f.message for f in findings)
        assert "mutable" in msgs and "insertion order" in msgs

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [StaticArgsRule()]) == []

    def test_bare_dict_name_is_function_scoped(self, tmp_path):
        src = (
            "def a():\n"
            "    d = {}\n"
            "    return tuple(sorted(d.items()))\n"
            "def b():\n"
            "    d = []\n"          # same name, different type: no finding
            "    return tuple(d)\n"
        )
        assert lint_snippet(tmp_path, src, [StaticArgsRule()]) == []


class TestTypedRaise:
    def test_fires_on_bad_and_allows_typed(self, tmp_path):
        src = (
            "class MyError(Exception):\n"
            "    pass\n"
            "def f():\n"
            "    raise ValueError('bare')\n"
            "def g():\n"
            "    raise AllowedError('typed')\n"
            "def h():\n"
            "    try:\n"
            "        g()\n"
            "    except Exception as e:\n"
            "        raise e\n"
        )
        rule = TypedRaiseRule(files=None, allowed={"AllowedError"})
        findings = lint_snippet(tmp_path, src, [rule])
        # ValueError flagged; MyError(Exception) is a local class NOT
        # rooted in an allowed name... but it is never raised, so only
        # the bare ValueError fires
        assert rule_names(findings) == ["typed-raise"]
        assert "ValueError" in findings[0].message

    def test_local_subclass_of_allowed_is_allowed(self, tmp_path):
        src = (
            "class Derived(AllowedError):\n"
            "    pass\n"
            "def f():\n"
            "    raise Derived('ok')\n"
            "def g():\n"
            "    raise Rogue('not ok')\n"
        )
        rule = TypedRaiseRule(files=None, allowed={"AllowedError"})
        findings = lint_snippet(tmp_path, src, [rule])
        assert rule_names(findings) == ["typed-raise"]
        assert "Rogue" in findings[0].message


# ---------------------------------------------------------------------------
# pragma + baseline round trips
# ---------------------------------------------------------------------------

class TestPragmaAndBaseline:
    SRC = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    a = np.sum(x)  # jaxlint: disable=host-call-in-jit -- fixture\n"
        "    b = np.mean(x)  # jaxlint: disable=all\n"
        "    return a + b + np.max(x)\n"
    )

    def test_pragma_suppresses_by_rule_and_all(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(self.SRC)
        result = Engine(rules=[HostCallInJitRule()],
                        repo=str(tmp_path)).run([str(p)])
        assert len(result.findings) == 1          # only the np.max line
        assert result.findings[0].lineno == 7
        assert result.suppressed == 2

    def test_unknown_pragma_rule_is_config_error(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(
            "import jax\nimport numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)  # jaxlint: disable=no-such-rule\n")
        with pytest.raises(ConfigError):
            Engine(rules=[HostCallInJitRule()],
                   repo=str(tmp_path)).run([str(p)])

    def test_baseline_round_trip(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(self.SRC)
        engine = Engine(rules=[HostCallInJitRule()], repo=str(tmp_path))
        findings = engine.collect([str(p)])
        assert len(findings) == 1
        bl_path = tmp_path / "baseline.txt"
        write_baseline(str(bl_path), findings)
        baseline = load_baseline(str(bl_path))
        result = engine.run([str(p)], baseline=baseline)
        assert result.findings == []
        assert result.baselined == 1
        assert result.stale_baseline == []

    def test_baseline_survives_line_drift_but_not_edits(self, tmp_path):
        p = tmp_path / "s.py"
        p.write_text(self.SRC)
        engine = Engine(rules=[HostCallInJitRule()], repo=str(tmp_path))
        bl_path = tmp_path / "baseline.txt"
        write_baseline(str(bl_path), engine.collect([str(p)]))
        # unrelated lines added above: same entry still matches
        p.write_text("# a new leading comment\n" + self.SRC)
        result = engine.run([str(p)], baseline=load_baseline(str(bl_path)))
        assert result.findings == [] and result.baselined == 1
        # the flagged line itself changes: entry goes stale, finding is new
        p.write_text(self.SRC.replace("np.max(x)", "np.max(x) + 0"))
        result = engine.run([str(p)], baseline=load_baseline(str(bl_path)))
        assert len(result.findings) == 1
        assert len(result.stale_baseline) == 1

    def test_stale_is_scoped_to_linted_paths(self, tmp_path):
        """A partial-path run must not report other files' baseline
        entries as stale — they were simply not linted."""
        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        for p in (a, b):
            p.write_text(
                "import jax\nimport numpy as np\n"
                "@jax.jit\n"
                "def f(x):\n"
                "    return np.sum(x)\n")
        engine = Engine(rules=[HostCallInJitRule()], repo=str(tmp_path))
        bl_path = tmp_path / "baseline.txt"
        write_baseline(str(bl_path), engine.collect([str(a), str(b)]))
        result = engine.run([str(a)], baseline=load_baseline(str(bl_path)))
        assert result.findings == []
        assert result.stale_baseline == []  # b.py's entry is NOT stale

    def test_update_baseline_preserves_justifications_and_scope(
            self, tmp_path, capsys):
        """--update-baseline keeps hand-written justifications of
        unchanged entries and retains entries for files outside the
        linted path set."""
        from tools.jaxlint.cli import main

        a = tmp_path / "a.py"
        b = tmp_path / "b.py"
        for p in (a, b):
            p.write_text(
                "import jax\nimport numpy as np\n"
                "@jax.jit\n"
                "def f(x):\n"
                "    return np.sum(x)\n")
        bl = tmp_path / "bl.txt"
        assert main([str(a), str(b), "--baseline", str(bl),
                     "--update-baseline"]) == 0
        # hand-edit the justifications
        text = bl.read_text()
        assert "TODO: justify" in text
        bl.write_text(text.replace("# TODO: justify",
                                   "# REVIEWED: fixture rationale", 1))
        # partial-path regeneration: a.py relinted, b.py out of scope
        assert main([str(a), "--baseline", str(bl),
                     "--update-baseline"]) == 0
        text = bl.read_text()
        assert "b.py" in text                      # out-of-scope retained
        assert "REVIEWED: fixture rationale" in text  # justification kept
        assert main([str(a), str(b), "--baseline", str(bl)]) == 0
        capsys.readouterr()

    def test_malformed_baseline_is_config_error(self, tmp_path):
        bl = tmp_path / "b.txt"
        bl.write_text("not a valid entry line\n")
        with pytest.raises(ConfigError):
            load_baseline(str(bl))


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------

class TestCli:
    def test_exit_codes(self, tmp_path, capsys):
        from tools.jaxlint.cli import main

        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\nimport numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n")
        assert main([str(clean), "--no-baseline"]) == 0
        assert main([str(bad), "--no-baseline"]) == 1
        assert main([str(bad), "--select", "no-such-rule"]) == 2
        assert main([str(tmp_path / "missing.py")]) == 2
        # unwritable baseline destination is a config error, not a crash
        assert main([str(bad), "--baseline",
                     str(tmp_path / "no-such-dir" / "bl.txt"),
                     "--update-baseline"]) == 2
        capsys.readouterr()

    def test_update_baseline_then_clean(self, tmp_path, capsys):
        from tools.jaxlint.cli import main

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\nimport numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n")
        bl = tmp_path / "bl.txt"
        assert main([str(bad), "--baseline", str(bl),
                     "--update-baseline"]) == 0
        assert main([str(bad), "--baseline", str(bl)]) == 0
        # a rule-subset rewrite would drop other rules' entries: refused
        assert main([str(bad), "--baseline", str(bl), "--update-baseline",
                     "--select", "host-call-in-jit"]) == 2
        capsys.readouterr()

    def test_list_rules(self, capsys):
        from tools.jaxlint.cli import main

        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for name in RULES:
            assert name in out


class TestCollectiveAxisContext:
    """ISSUE 14 satellite: psum_scatter outside a shard_map axis
    context is a silent full-replication footgun under the SPMD
    partitioner."""

    BAD = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def gram(M, w):\n"
        "    pm = M.T @ (w[:, None] * M)\n"
        "    return jax.lax.psum_scatter(pm, 'toa',\n"
        "                                scatter_dimension=0, tiled=True)\n"
    )
    GOOD = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def build(mesh):\n"
        "    def gram(M, w):\n"
        "        pm = M.T @ (w[:, None] * M)\n"
        "        return jax.lax.psum_scatter(pm, 'toa',\n"
        "                                    scatter_dimension=0,\n"
        "                                    tiled=True)\n"
        "    return jax.jit(shard_map(gram, mesh=mesh,\n"
        "                             in_specs=(P('toa', None), P('toa')),\n"
        "                             out_specs=P('toa', None)))\n"
    )

    def _rule(self):
        from tools.jaxlint.rules.collective_context import (
            CollectiveAxisContextRule)

        return CollectiveAxisContextRule()

    def test_fires_on_bad(self, tmp_path):
        findings = lint_snippet(tmp_path, self.BAD, [self._rule()])
        assert rule_names(findings) == ["collective-axis-context"]
        assert "shard_map" in findings[0].message
        assert "replicat" in findings[0].message

    def test_silent_on_good(self, tmp_path):
        assert lint_snippet(tmp_path, self.GOOD, [self._rule()]) == []

    def test_scan_inside_shard_map_body_not_flagged(self, tmp_path):
        """The row-chunked production shape: psum_scatter inside a
        lax.scan step that is NESTED in the shard_map body inherits the
        axis context (exactly workperbyte's chunked accumulation)."""
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from jax.experimental.shard_map import shard_map\n"
            "from jax.sharding import PartitionSpec as P\n"
            "def build(mesh):\n"
            "    def scattered(M, w):\n"
            "        def step(carry, xs):\n"
            "            Mc, wc = xs\n"
            "            pm = Mc.T @ (wc[:, None] * Mc)\n"
            "            sm = jax.lax.psum_scatter(pm, 'toa',\n"
            "                                      scatter_dimension=0,\n"
            "                                      tiled=True)\n"
            "            return carry + sm, ()\n"
            "        init = jnp.zeros((4, 8))\n"
            "        out, _ = jax.lax.scan(step, init, (M, w))\n"
            "        return out\n"
            "    return jax.jit(shard_map(scattered, mesh=mesh,\n"
            "                             in_specs=P('toa', None),\n"
            "                             out_specs=P('toa', None)))\n"
        )
        assert lint_snippet(tmp_path, good, [self._rule()]) == []

    def test_module_level_scatter_flagged(self, tmp_path):
        bad = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "x = jax.lax.psum_scatter(jnp.ones((4, 4)), 'toa')\n"
        )
        findings = lint_snippet(tmp_path, bad, [self._rule()])
        assert rule_names(findings) == ["collective-axis-context"]

    def test_registered_by_default(self):
        assert "collective-axis-context" in RULES
        assert any(type(r).name == "collective-axis-context"
                   for r in default_rules())

    def test_workperbyte_kernel_is_clean(self):
        """The shipped scattered-Gram kernel passes its own rule (the
        scatters live inside the shard_map body)."""
        info = parse_file(os.path.join(
            REPO, "pint_tpu", "runtime", "workperbyte.py"), repo=REPO)
        assert list(self._rule().check(info)) == []


class TestWorkperbyteHostTarget:
    def test_workperbyte_call_in_jit_flagged(self, tmp_path):
        """The scan-fused era's host-call targets (ISSUE 14 satellite):
        workperbyte's scatter orchestration called inside a traced
        function re-enters tracing per TRACE — the host-call-in-jit
        target set must cover the runtime.workperbyte module."""
        bad = (
            "import jax\n"
            "from pint_tpu.runtime import workperbyte as _wpb\n"
            "from pint_tpu.runtime.workperbyte import "
            "verify_scatter_contract\n"
            "@jax.jit\n"
            "def f(M, r, Nvec, phiinv, plan):\n"
            "    m, y = _wpb.scattered_normal_equations(M, r, Nvec,\n"
            "                                           phiinv, plan)\n"
            "    verify_scatter_contract(f, M)\n"
            "    return m\n"
        )
        findings = lint_snippet(tmp_path, bad, [HostCallInJitRule()])
        assert rule_names(findings) == ["host-call-in-jit"] * 2

    def test_workperbyte_on_host_not_flagged(self, tmp_path):
        """Good twin: the documented pattern — build/verify on host,
        dispatch the jitted kernel."""
        good = (
            "import jax\n"
            "from pint_tpu.runtime.workperbyte import (\n"
            "    scattered_normal_equations, verify_scatter_contract)\n"
            "@jax.jit\n"
            "def solve(mtcm, mtcy):\n"
            "    return mtcm @ mtcy\n"
            "def host(M, r, Nvec, phiinv, plan):\n"
            "    m, y = scattered_normal_equations(M, r, Nvec, phiinv,\n"
            "                                      plan)\n"
            "    return solve(m, y)\n"
        )
        assert lint_snippet(tmp_path, good, [HostCallInJitRule()]) == []


# ---------------------------------------------------------------------------
# the flow engine: CFG + exception edges + reaching defs + call summaries
# ---------------------------------------------------------------------------

@pytest.mark.asynclint
class TestFlowEngine:
    """The flow-aware substrate under the async rules
    (tools/jaxlint/flow.py): per-function CFGs with exception edges,
    reaching definitions, and the module call-summary fixpoint."""

    def test_no_raise_body_cannot_reach_raise_exit(self):
        from tools.jaxlint import flow

        fn = ast.parse(
            "def f(xs):\n"
            "    n = len(xs)\n"
            "    xs.append(n)\n"
            "    return n\n").body[0]
        assert not flow.build_cfg(fn).raise_reachable()

    def test_unsummarized_call_grows_exception_edge(self):
        from tools.jaxlint import flow

        fn = ast.parse(
            "def f(x):\n"
            "    y = frobnicate(x)\n"
            "    return y\n").body[0]
        assert flow.build_cfg(fn).raise_reachable()

    def test_broad_handler_fences_narrow_does_not(self):
        from tools.jaxlint import flow

        fenced = ast.parse(
            "def f(x):\n"
            "    try:\n"
            "        y = frobnicate(x)\n"
            "    except Exception:\n"
            "        y = None\n"
            "    return y\n").body[0]
        assert not flow.build_cfg(fenced).raise_reachable()
        narrow = ast.parse(
            "def f(x):\n"
            "    try:\n"
            "        y = frobnicate(x)\n"
            "    except ValueError:\n"
            "        y = None\n"
            "    return y\n").body[0]
        assert flow.build_cfg(narrow).raise_reachable()

    def test_reaching_definitions_merge_at_join(self):
        from tools.jaxlint import flow

        fn = ast.parse(
            "def f(c):\n"
            "    x = 1\n"
            "    if c:\n"
            "        x = 2\n"
            "    return x\n").body[0]
        cfg = flow.build_cfg(fn)
        defs = flow.reaching_definitions(cfg)[cfg.exit].get("x", set())
        assert len(defs) == 2

    def test_summary_resolution_and_fixpoint(self):
        from tools.jaxlint import flow

        tree = ast.parse(
            "def fail_all(pending, exc):\n"
            "    for _, fut in pending:\n"
            "        if fut.done():\n"
            "            continue\n"
            "        fut.set_exception(exc)\n"
            "def drain(pending, exc):\n"
            "    fail_all(pending, exc)\n")
        s = flow.module_summaries(tree)
        assert s["fail_all"].resolves_params == frozenset({"pending"})
        assert s["fail_all"].cannot_raise
        # fixpoint: drain only calls the summarized no-raise helper
        assert s["drain"].cannot_raise

    def test_shipped_flush_door_summary(self):
        """The real serving dispatch: the summary pass proves
        _flush_door resolves its `pending` parameter and cannot raise
        (the contract _drain_door's hand-off rests on)."""
        from tools.jaxlint import flow

        with open(os.path.join(REPO, "pint_tpu", "serving",
                               "service.py")) as f:
            s = flow.module_summaries(ast.parse(f.read()))
        assert "pending" in s["_flush_door"].resolves_params
        assert s["_flush_door"].cannot_raise


# ---------------------------------------------------------------------------
# async-discipline rules (stranded-future / await-under-lock / blocking)
# ---------------------------------------------------------------------------

@pytest.mark.asynclint
class TestStrandedFuture:
    """The static form of the chaos-drill zero-stranded-futures
    contract, pinned by the seeded _flush_door mutant: the exception
    branch returns without failing the popped batch."""

    MUTANT = (
        "import time\n"
        "class Service:\n"
        "    async def _flush_door(self, door, pending, run, record,\n"
        "                          what):\n"
        "        if not pending:\n"
        "            return\n"
        "        try:\n"
        "            results = run([p[0] for p in pending])\n"
        "        except Exception as e:\n"
        "            door.breaker.record_failure()\n"
        "            return\n"
        "        door.breaker.record_success()\n"
        "        now = time.perf_counter()\n"
        "        for (req, fut, t0), res in zip(pending, results):\n"
        "            res.latency_ms = 1e3 * (now - t0)\n"
        "            if fut.done():\n"
        "                continue\n"
        "            fut.set_result(res)\n"
        "            try:\n"
        "                record(req, res, res.latency_ms)\n"
        "            except Exception:\n"
        "                pass\n"
    )
    FIXED = MUTANT.replace(
        "            door.breaker.record_failure()\n"
        "            return\n",
        "            door.breaker.record_failure()\n"
        "            for _, fut, _ in pending:\n"
        "                if not fut.done():\n"
        "                    fut.set_exception(e)\n"
        "            return\n")

    def test_seeded_flush_door_mutant_caught(self, tmp_path):
        findings = assert_twins(
            tmp_path, [StrandedFutureRule(files=None)],
            self.MUTANT, self.FIXED, ["stranded-future"])
        assert "'pending'" in findings[0].message
        assert "_flush_door" in findings[0].message

    def test_created_future_stranded_by_raising_bookkeeping(
            self, tmp_path):
        bad = (
            "import asyncio\n"
            "async def submit(door, req):\n"
            "    loop = asyncio.get_running_loop()\n"
            "    fut = loop.create_future()\n"
            "    door.validate(req)\n"
            "    door.pending.append((req, fut))\n"
            "    return await fut\n")
        good = (
            "import asyncio\n"
            "async def submit(door, req):\n"
            "    loop = asyncio.get_running_loop()\n"
            "    fut = loop.create_future()\n"
            "    try:\n"
            "        door.validate(req)\n"
            "    except Exception as e:\n"
            "        fut.set_exception(e)\n"
            "        return await fut\n"
            "    door.pending.append((req, fut))\n"
            "    return await fut\n")
        assert_twins(tmp_path, [StrandedFutureRule(files=None)],
                     bad, good, ["stranded-future"])

    def test_popped_batch_is_tainted(self, tmp_path):
        bad = (
            "async def drain(door):\n"
            "    batch, door.pending = door.pending[:4], "
            "door.pending[4:]\n"
            "    door.gauge()\n"
            "    for _, fut in batch:\n"
            "        fut.set_result(None)\n")
        good = (
            "async def drain(door):\n"
            "    batch, door.pending = door.pending[:4], "
            "door.pending[4:]\n"
            "    try:\n"
            "        door.gauge()\n"
            "    except Exception as e:\n"
            "        for _, fut in batch:\n"
            "            fut.set_exception(e)\n"
            "        return\n"
            "    for _, fut in batch:\n"
            "        fut.set_result(None)\n")
        findings = assert_twins(
            tmp_path, [StrandedFutureRule(files=None)],
            bad, good, ["stranded-future"])
        assert "'batch'" in findings[0].message

    def test_handoff_to_resolving_callee_kills(self, tmp_path):
        """Interprocedural: passing the futures to a module-local
        helper counts as resolution exactly when the helper's summary
        resolves that parameter."""
        bad = (
            "def log_all(futs):\n"
            "    for fut in futs:\n"
            "        print(fut)\n"
            "async def drain(pending):\n"
            "    log_all(pending)\n")
        good = (
            "def cancel_all(futs):\n"
            "    for fut in futs:\n"
            "        fut.cancel()\n"
            "async def drain(pending):\n"
            "    cancel_all(pending)\n")
        assert_twins(tmp_path, [StrandedFutureRule(files=None)],
                     bad, good, ["stranded-future"])

    def test_default_scope_is_the_async_layer(self, tmp_path):
        assert "pint_tpu/serving/" in ASYNC_SCOPE
        assert "pint_tpu/streaming/door.py" in ASYNC_SCOPE
        # out of the scoped set, the default-scope instance is silent
        assert lint_snippet(tmp_path, self.MUTANT,
                            [StrandedFutureRule(files=...)]) == []

    def test_shipped_serving_layer_is_clean(self):
        """The acceptance contract: the live serving layer + the
        streaming door pass all three async rules with no pragmas and
        no baseline entries."""
        rules = [StrandedFutureRule(files=...),
                 AwaitUnderLockRule(files=...),
                 BlockingInCoroutineRule(files=...)]
        result = Engine(rules=rules, repo=REPO).run(
            ["pint_tpu/serving", "pint_tpu/streaming/door.py"])
        assert result.findings == [], "\n".join(
            f.render() for f in result.findings)
        assert result.suppressed == 0 and result.baselined == 0


@pytest.mark.asynclint
class TestAwaitUnderLock:
    BAD_WITH = (
        "import asyncio\n"
        "class Door:\n"
        "    async def flush(self):\n"
        "        with self._lock:\n"
        "            await asyncio.sleep(0)\n"
    )
    GOOD_WITH = (
        "import asyncio\n"
        "class Door:\n"
        "    async def flush(self):\n"
        "        async with self._lock:\n"
        "            await asyncio.sleep(0)\n"
        "    def snapshot(self):\n"
        "        with self._lock:\n"
        "            return list(self._q)\n"
    )

    def test_plain_with_over_lock(self, tmp_path):
        assert_twins(tmp_path, [AwaitUnderLockRule(files=None)],
                     self.BAD_WITH, self.GOOD_WITH,
                     ["await-under-lock"])

    BAD_ACQ = (
        "class Door:\n"
        "    async def flush(self, batch):\n"
        "        self._door_lock.acquire()\n"
        "        await self.run(batch)\n"
        "        self._door_lock.release()\n"
    )
    GOOD_ACQ = (
        "class Door:\n"
        "    async def flush(self, batch):\n"
        "        self._door_lock.acquire()\n"
        "        take = self.quantum()\n"
        "        self._door_lock.release()\n"
        "        await self.run(batch[:take])\n"
    )

    def test_bare_acquire_release_span(self, tmp_path):
        findings = assert_twins(
            tmp_path, [AwaitUnderLockRule(files=None)],
            self.BAD_ACQ, self.GOOD_ACQ, ["await-under-lock"])
        assert "acquire" in findings[0].message

    def test_inline_threading_primitive(self, tmp_path):
        bad = (
            "import threading\n"
            "async def f(x):\n"
            "    with threading.Lock():\n"
            "        await x\n")
        findings = lint_snippet(tmp_path, bad,
                                [AwaitUnderLockRule(files=None)])
        assert rule_names(findings) == ["await-under-lock"]


@pytest.mark.asynclint
class TestBlockingInCoroutine:
    BAD = (
        "import os\n"
        "import time\n"
        "class Service:\n"
        "    async def _dispatch(self, door, fh, x):\n"
        "        os.fsync(fh)\n"
        "        time.sleep(0.01)\n"
        "        with open('audit.log', 'a') as f:\n"
        "            f.write('x')\n"
        "        self._journal.commit([x])\n"
        "        x.block_until_ready()\n"
        "        return x\n"
    )
    GOOD = (
        "import asyncio\n"
        "import os\n"
        "class Service:\n"
        "    def _run_sync(self, door, fh, x):\n"
        "        os.fsync(fh)\n"
        "        with open('audit.log', 'a') as f:\n"
        "            f.write('x')\n"
        "        self._journal.commit([x])\n"
        "        return x.block_until_ready()\n"
        "    async def _dispatch(self, door, fh, x):\n"
        "        await asyncio.sleep(0.01)\n"
        "        loop = asyncio.get_running_loop()\n"
        "        return await loop.run_in_executor(\n"
        "            None, self._run_sync, door, fh, x)\n"
    )

    def test_twins(self, tmp_path):
        findings = assert_twins(
            tmp_path, [BlockingInCoroutineRule(files=None)],
            self.BAD, self.GOOD, ["blocking-in-coroutine"] * 5)
        msgs = " ".join(f.message for f in findings)
        assert "fsync" in msgs and "sleep" in msgs and "open" in msgs
        assert "commit" in msgs and "block_until_ready" in msgs


# ---------------------------------------------------------------------------
# the telemetry event-schema cross-checker
# ---------------------------------------------------------------------------

@pytest.mark.asynclint
class TestEventContract:
    """Producer/validator drift twins: emit sites are diffed against
    the *_EVENT_ATTRS contract tables parsed from the repo's
    tools/telemetry_report.py SOURCE (never imported)."""

    CONTRACTS = (
        "DOOR_EVENT_ATTRS = {\n"
        "    'door.flush': {'klass': str, 'n': int,\n"
        "                   'latency_ms': (int, float)},\n"
        "    'door.shed': {'klass': str},\n"
        "}\n"
    )

    def _repo(self, tmp_path, producer_src):
        (tmp_path / "tools").mkdir()
        (tmp_path / "tools" / "telemetry_report.py").write_text(
            self.CONTRACTS)
        pkg = tmp_path / "pint_tpu" / "serving"
        pkg.mkdir(parents=True)
        tele = tmp_path / "pint_tpu" / "telemetry"
        tele.mkdir()
        (tele / "__init__.py").write_text("SEAM = 1\n")
        p = pkg / "door.py"
        p.write_text(producer_src)
        return p

    def _lint(self, tmp_path, path):
        from tools.jaxlint.rules.event_contract import EventContractRule

        eng = Engine(rules=[EventContractRule(files=...)],
                     repo=str(tmp_path))
        return eng.lint_file(str(path))

    def test_conforming_producer_is_clean(self, tmp_path):
        p = self._repo(
            tmp_path,
            "def flush(run, n, dt):\n"
            "    run.record_event('door.flush', klass='fit', n=n,\n"
            "                     latency_ms=dt)\n"
            "def shed(run, **attrs):\n"
            "    run.record_event('door.shed', **attrs)\n")
        assert self._lint(tmp_path, p) == []

    def test_unknown_event_name(self, tmp_path):
        p = self._repo(
            tmp_path,
            "def flush(run):\n"
            "    run.record_event('door.flsh', klass='fit')\n")
        findings = self._lint(tmp_path, p)
        assert rule_names(findings) == ["event-contract"]
        assert "no validator contract" in findings[0].message

    def test_missing_required_attr(self, tmp_path):
        p = self._repo(
            tmp_path,
            "def flush(run):\n"
            "    run.record_event('door.flush', klass='fit')\n")
        findings = self._lint(tmp_path, p)
        assert rule_names(findings) == ["event-contract"] * 2
        msgs = " ".join(f.message for f in findings)
        assert "'n'" in msgs and "'latency_ms'" in msgs

    def test_rejected_attr_type_and_bool_exclusion(self, tmp_path):
        p = self._repo(
            tmp_path,
            "def flush(run):\n"
            "    run.record_event('door.flush', klass='fit', n=True,\n"
            "                     latency_ms=3)\n")
        findings = self._lint(tmp_path, p)
        # n=True is bool (the validator rejects bools for int attrs);
        # latency_ms=3 is accepted because the contract spells
        # (int, float)
        assert rule_names(findings) == ["event-contract"]
        assert "bool" in findings[0].message

    def test_dead_contract_anchored_on_telemetry_seam(self, tmp_path):
        self._repo(
            tmp_path,
            "def flush(run, n, dt):\n"
            "    run.record_event('door.flush', klass='fit', n=n,\n"
            "                     latency_ms=dt)\n")
        anchor = tmp_path / "pint_tpu" / "telemetry" / "__init__.py"
        findings = self._lint(tmp_path, anchor)
        assert rule_names(findings) == ["event-contract"]
        assert "dead contract" in findings[0].message
        assert "door.shed" in findings[0].message

    def test_producer_validator_drift_twin(self, tmp_path):
        """The drift twin: rename the emitted event and the checker
        reports BOTH directions — unknown producer at the emit site,
        dead contract at the telemetry seam."""
        p = self._repo(
            tmp_path,
            "def shed(run):\n"
            "    run.record_event('door.dropped', klass='fit')\n")
        emit = self._lint(tmp_path, p)
        assert rule_names(emit) == ["event-contract"]
        assert "door.dropped" in emit[0].message
        anchor = tmp_path / "pint_tpu" / "telemetry" / "__init__.py"
        dead = self._lint(tmp_path, anchor)
        assert rule_names(dead) == ["event-contract"] * 2

    def test_repo_contracts_and_producers_agree(self):
        """Acceptance pin: over the real repo the static extractor and
        the validator tables cover exactly the same event set — zero
        unknown producers, zero dead contracts."""
        from tools.jaxlint.rules.event_contract import (
            load_contract_table,
            repo_producers,
        )

        table = load_contract_table(REPO)
        produced = repo_producers(REPO)
        assert table and produced
        assert set(produced) - set(table) == set(), (
            f"producers without contracts: "
            f"{sorted(set(produced) - set(table))}")
        dead = {n for n in table if produced.get(n, 0) == 0}
        assert dead == set(), f"dead contracts: {sorted(dead)}"


# ---------------------------------------------------------------------------
# the auto-discovered target map
# ---------------------------------------------------------------------------

class TestTargetMapContract:
    """Every discovered pint_tpu subpackage is analyzed or excluded
    WITH a written justification, per rule family — a new package
    cannot silently fall outside the lint surface."""

    def test_discovery_finds_the_known_packages(self):
        from tools.jaxlint.engine import pint_tpu_subpackages

        pkgs = pint_tpu_subpackages(REPO)
        assert {"serving", "streaming", "telemetry", "runtime",
                "catalog", "amortized", "autotune"} <= set(pkgs)
        assert "journal" in pkgs["serving"]
        assert "door" in pkgs["streaming"]

    def test_host_call_map_is_total(self):
        from tools.jaxlint.engine import (
            HOST_CALL_EXCLUSIONS,
            _PKG_VIEW,
            pint_tpu_subpackages,
        )

        for pkg, subs in pint_tpu_subpackages(REPO).items():
            tracked = _PKG_VIEW.get(f"pint_tpu.{pkg}")
            if pkg in HOST_CALL_EXCLUSIONS:
                assert tracked is None
                continue
            assert tracked is not None, (
                f"{pkg} neither host-tracked nor excluded")
            for s in subs - tracked:
                assert f"{pkg}.{s}" in HOST_CALL_EXCLUSIONS, (
                    f"{pkg}.{s} dropped without a justification")

    def test_typed_raise_map_is_total(self):
        from tools.jaxlint.engine import pint_tpu_subpackages
        from tools.jaxlint.rules.typed_raises import (
            DEFAULT_TARGETS,
            TYPED_RAISE_EXCLUSIONS,
        )

        for pkg in pint_tpu_subpackages(REPO):
            covered = f"pint_tpu/{pkg}/" in DEFAULT_TARGETS
            excluded = pkg in TYPED_RAISE_EXCLUSIONS
            assert covered != excluded, (
                f"{pkg} must be exactly one of covered/excluded")

    def test_downcast_map_is_total(self):
        from tools.jaxlint.engine import pint_tpu_subpackages
        from tools.jaxlint.rules.downcast import (
            DOWNCAST_EXCLUSIONS,
            DOWNCAST_SCOPE,
        )

        for pkg in pint_tpu_subpackages(REPO):
            covered = f"pint_tpu/{pkg}/" in DOWNCAST_SCOPE
            excluded = pkg in DOWNCAST_EXCLUSIONS
            assert covered != excluded, (
                f"{pkg} must be exactly one of covered/excluded")

    def test_every_exclusion_is_justified_and_real(self):
        from tools.jaxlint.engine import (
            HOST_CALL_EXCLUSIONS,
            pint_tpu_subpackages,
        )
        from tools.jaxlint.rules.downcast import DOWNCAST_EXCLUSIONS
        from tools.jaxlint.rules.typed_raises import (
            TYPED_RAISE_EXCLUSIONS)

        pkgs = pint_tpu_subpackages(REPO)
        for table in (HOST_CALL_EXCLUSIONS, TYPED_RAISE_EXCLUSIONS,
                      DOWNCAST_EXCLUSIONS):
            for key, why in table.items():
                assert isinstance(why, str) and len(why.split()) >= 3, (
                    f"exclusion {key!r} lacks a written justification")
                if "." in key:
                    pkg, sub = key.split(".", 1)
                    assert sub in pkgs.get(pkg, set()), (
                        f"exclusion {key!r} names a module that no "
                        "longer exists")
                else:
                    assert key in pkgs, (
                        f"exclusion {key!r} names a package that no "
                        "longer exists")

    def test_async_and_contract_scopes_cover_the_issue_targets(self):
        from tools.jaxlint.rules.event_contract import EventContractRule

        assert "pint_tpu/serving/" in ASYNC_SCOPE
        assert "pint_tpu/streaming/door.py" in ASYNC_SCOPE
        assert EventContractRule.default_files == ("pint_tpu/",)


# ---------------------------------------------------------------------------
# normalized baseline keys + --format json
# ---------------------------------------------------------------------------

class TestNormalizedBaseline:
    """Satellite: baseline keys are (path, rule, normalized snippet) —
    reformatting and comment edits keep an entry matching; editing the
    flagged code itself stales it."""

    def test_normalize_snippet(self):
        from tools.jaxlint.engine import normalize_snippet

        assert normalize_snippet("  y =   np.sum(x)\t# note") \
            == "y = np.sum(x)"
        # a '#' inside a string literal is code, not a comment
        assert normalize_snippet("x = 'a # b'  # trailing") \
            == "x = 'a # b'"
        assert normalize_snippet('m = "esc \\" # q"  # c') \
            == 'm = "esc \\" # q"'

    def test_baseline_survives_reformat_and_comment_edits(
            self, tmp_path):
        src = (
            "import jax\nimport numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return np.sum(x)\n")
        p = tmp_path / "s.py"
        p.write_text(src)
        engine = Engine(rules=[HostCallInJitRule()], repo=str(tmp_path))
        bl = tmp_path / "bl.txt"
        write_baseline(str(bl), engine.collect([str(p)]))
        # the rename-survives case: a refactor pass re-spaces the
        # flagged line and hangs a comment on it
        p.write_text(src.replace(
            "    return np.sum(x)\n",
            "    return  np.sum(x)   # kept: host reduction\n"))
        result = engine.run([str(p)], baseline=load_baseline(str(bl)))
        assert result.findings == [] and result.baselined == 1
        assert result.stale_baseline == []
        # editing the code itself still stales the entry
        p.write_text(src.replace("np.sum(x)", "np.sum(x * 2)"))
        result = engine.run([str(p)], baseline=load_baseline(str(bl)))
        assert len(result.findings) == 1
        assert len(result.stale_baseline) == 1

    def test_committed_baseline_is_normalized(self):
        """Idempotence pin for the migrated entries: every committed
        key equals its own normalization."""
        from tools.jaxlint.engine import (
            normalize_snippet,
            read_baseline_entries,
        )

        entries = read_baseline_entries(
            os.path.join(REPO, "jaxlint_baseline.txt"))
        assert len(entries) >= 5
        for _, key in entries:
            assert key[2] == normalize_snippet(key[2])


class TestJsonFormat:
    """Satellite: `--format json` machine-readable findings on stdout;
    text mode stays byte-identical."""

    BAD = (
        "import jax\nimport numpy as np\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return np.sum(x)\n")

    def test_json_records(self, tmp_path, capsys):
        import json

        from tools.jaxlint.cli import main

        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        assert main([str(bad), "--no-baseline",
                     "--format", "json"]) == 1
        cap = capsys.readouterr()
        records = json.loads(cap.out)   # stdout is pure JSON
        assert len(records) == 1
        r = records[0]
        assert set(r) == {"file", "line", "col", "rule", "message",
                          "severity"}
        assert r["rule"] == "host-call-in-jit"
        assert r["severity"] == "error"
        assert r["line"] == 5 and r["file"].endswith("bad.py")
        assert "violation" in cap.err    # summary moved to stderr

    def test_json_clean_is_empty_array(self, tmp_path, capsys):
        import json

        from tools.jaxlint.cli import main

        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        assert main([str(clean), "--no-baseline",
                     "--format", "json"]) == 0
        cap = capsys.readouterr()
        assert json.loads(cap.out) == []
        assert "OK" in cap.err

    def test_text_output_unchanged(self, tmp_path, capsys):
        from tools.jaxlint.cli import main

        bad = tmp_path / "bad.py"
        bad.write_text(self.BAD)
        assert main([str(bad), "--no-baseline"]) == 1
        cap = capsys.readouterr()
        assert cap.err == ""
        lines = cap.out.strip().splitlines()
        assert "host-call-in-jit" in lines[0] and ":5:" in lines[0]
        assert lines[-1].startswith("1 violation(s)")


# ---------------------------------------------------------------------------
# the contract: pint_tpu lints clean against the committed baseline
# ---------------------------------------------------------------------------

class TestRepoContract:
    def test_pint_tpu_clean_against_committed_baseline(self):
        baseline = load_baseline(os.path.join(REPO, "jaxlint_baseline.txt"))
        result = Engine(rules=default_rules(),
                        repo=REPO).run(["pint_tpu"], baseline=baseline)
        assert result.findings == [], "\n".join(
            f.render() for f in result.findings)

    def test_committed_baseline_has_no_stale_entries(self):
        baseline = load_baseline(os.path.join(REPO, "jaxlint_baseline.txt"))
        result = Engine(rules=default_rules(),
                        repo=REPO).run(["pint_tpu"], baseline=baseline)
        assert result.stale_baseline == []

    def test_every_baseline_entry_is_justified(self):
        """The baseline grandfathers, it does not hide: each entry must
        carry a justification comment line directly above it."""
        path = os.path.join(REPO, "jaxlint_baseline.txt")
        with open(path) as f:
            lines = [ln.rstrip() for ln in f]
        prev = ""
        for ln in lines:
            if ln and not ln.startswith("#"):
                assert prev.startswith("#") and len(prev) > 2, (
                    f"baseline entry lacks a justification comment: {ln!r}")
            prev = ln
