"""Long-tail timing-model components: glitch, waves, FD/FDJUMP, solar wind,
chromatic, IFUNC, piecewise spindown, troposphere.

Strategy mirrors the reference suite (SURVEY §4): build each model from a par
string, check behavior against closed-form expectations, and check the
autodiff design-matrix column against finite differences
(reference ``tests/test_model_derivatives.py``)."""

import io

import numpy as np
import pytest

BASE_PAR = """
PSR  J0000+0000
RAJ  05:00:00
DECJ 15:00:00
F0   100.0  1
F1   -1e-14 1
PEPOCH 55000
DM   10.0
TZRMJD 55000
TZRFRQ 1400
TZRSITE gbt
"""


def _model(extra: str):
    from pint_tpu.models import get_model

    return get_model(io.StringIO(BASE_PAR + extra))


@pytest.fixture(scope="module")
def toas():
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    m = _model("")
    return make_fake_toas_uniform(54500, 55500, 60, m, error_us=1.0, obs="gbt",
                                  freq=(400.0, 1400.0))


def _check_deriv(model, toas, param, step=1e-2, rtol=1e-4, atol=1e-10):
    model.free_params = [param]
    analytic = model.d_phase_d_param(toas, None, param)
    numeric = model.d_phase_d_param_num(toas, param, step=step)
    scale = max(float(np.max(np.abs(numeric))), atol)
    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=rtol * scale)


class TestGlitch:
    def test_phase_step(self, toas):
        m0 = _model("")
        m1 = _model("GLEP_1 55000\nGLF0_1 1e-7\nGLPH_1 0.1\n")
        r0 = m1.phase(toas) - m0.phase(toas)
        d = np.asarray(r0.int_) + np.asarray(r0.frac)
        mjd = np.asarray(toas.get_mjds(), dtype=float)
        assert np.all(d[mjd < 54999.9] == 0)
        on = mjd > 55001
        delay = np.asarray(m1.delay(toas))
        tdb = np.asarray(toas.tdb, dtype=float)
        dt = (tdb[on] - 55000.0) * 86400.0 - delay[on]
        np.testing.assert_allclose(d[on], 0.1 + 1e-7 * dt, rtol=1e-6)

    def test_decay_term(self, toas):
        m = _model("GLEP_1 55000\nGLF0D_1 1e-8\nGLTD_1 50\n")
        ph = m.phase(toas) - _model("").phase(toas)
        d = np.asarray(ph.int_) + np.asarray(ph.frac)
        mjd = np.asarray(toas.get_mjds(), dtype=float)
        on = mjd > 55300  # ~6 decay times: saturated
        np.testing.assert_allclose(d[on], 1e-8 * 50 * 86400, rtol=1e-2)

    def test_derivatives(self, toas):
        m = _model("GLEP_1 55000\nGLF0_1 1e-7\nGLF1_1 1e-15\n"
                   "GLF0D_1 1e-8\nGLTD_1 50\n")
        for p in ["GLF0_1", "GLF1_1", "GLPH_1", "GLF0D_1", "GLTD_1"]:
            _check_deriv(m, toas, p)

    def test_missing_epoch_raises(self):
        from pint_tpu.exceptions import MissingParameter

        with pytest.raises(MissingParameter):
            _model("GLF0_1 1e-7\n")


class TestWave:
    def test_wave_phase(self, toas):
        m = _model("WAVEEPOCH 55000\nWAVE_OM 0.005\nWAVE1 0.01 -0.02\n"
                   "WAVE2 0.003 0.001\n")
        ph = m.phase(toas) - _model("").phase(toas)
        d = np.asarray(ph.int_) + np.asarray(ph.frac)
        delay = np.asarray(m.delay(toas))
        dt = np.asarray(toas.tdb, dtype=float) - 55000.0 - delay / 86400.0
        expect = 100.0 * (0.01 * np.sin(0.005 * dt) - 0.02 * np.cos(0.005 * dt)
                          + 0.003 * np.sin(0.01 * dt) + 0.001 * np.cos(0.01 * dt))
        np.testing.assert_allclose(d, expect, rtol=1e-6, atol=1e-9)


class TestWaveX:
    def test_wavex_delay(self, toas):
        m = _model("WXEPOCH 55000\nWXFREQ_0001 0.005\nWXSIN_0001 1e-5\n"
                   "WXCOS_0001 2e-5\n")
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        assert np.max(np.abs(d)) > 5e-6
        assert np.max(np.abs(d)) <= np.hypot(1e-5, 2e-5) * 1.001

    def test_wavex_derivs(self, toas):
        m = _model("WXEPOCH 55000\nWXFREQ_0001 0.005\nWXSIN_0001 1e-5\n"
                   "WXCOS_0001 2e-5\n")
        for p in ["WXSIN_0001", "WXCOS_0001"]:
            _check_deriv(m, toas, p)
        # frequency enters through sin(2 pi f dt): small FD step needed
        _check_deriv(m, toas, "WXFREQ_0001", step=1e-5, rtol=1e-3)

    def test_dmwavex(self, toas):
        from pint_tpu import DMconst

        m = _model("DMWXEPOCH 55000\nDMWXFREQ_0001 0.01\nDMWXSIN_0001 1e-4\n"
                   "DMWXCOS_0001 0\n")
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        freq = np.asarray(toas.get_freqs())
        # frequency-squared scaling of the DM series
        lo, hi = freq < 500, freq > 1000
        ratio = np.max(np.abs(d[lo])) / np.max(np.abs(d[hi]))
        assert ratio == pytest.approx((1400 / 400) ** 2, rel=0.15)
        assert np.max(np.abs(d)) <= 1e-4 * DMconst / 400**2 * 1.01

    def test_cmwavex(self, toas):
        m = _model("TNCHROMIDX 4\nCM 0\nCMWXEPOCH 55000\nCMWXFREQ_0001 0.01\n"
                   "CMWXSIN_0001 1e-4\nCMWXCOS_0001 0\n")
        assert "CMWaveX" in m.components
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        freq = np.asarray(toas.get_freqs())
        lo, hi = freq < 500, freq > 1000
        ratio = np.max(np.abs(d[lo])) / np.max(np.abs(d[hi]))
        assert ratio == pytest.approx((1400 / 400) ** 4, rel=0.2)


class TestFD:
    def test_fd_delay(self, toas):
        m = _model("FD1 1e-4\nFD2 -2e-5\n")
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        # barycentric freq differs from topocentric by ~1e-4 relative; loose tol
        logf = np.log(np.asarray(toas.get_freqs()) / 1000.0)
        expect = 1e-4 * logf - 2e-5 * logf**2
        np.testing.assert_allclose(d, expect, rtol=2e-3, atol=1e-9)

    def test_fd_derivs(self, toas):
        m = _model("FD1 1e-4\nFD2 -2e-5\n")
        for p in ["FD1", "FD2"]:
            _check_deriv(m, toas, p)

    def test_fd_contiguity(self):
        from pint_tpu.exceptions import MissingParameter

        with pytest.raises(MissingParameter):
            _model("FD1 1e-4\nFD3 1e-5\n")


class TestFDJump:
    def test_masked_delay(self, toas):
        m = _model("FD1JUMP -fe 430 1e-4\nFDJUMPLOG N\n")
        assert "FDJump" in m.components
        # no TOAs carry -fe 430 here: delay must be zero
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        np.testing.assert_allclose(d, 0.0, atol=1e-15)

    def test_mjd_masked_delay(self, toas):
        m = _model("FD1JUMP MJD 54500 55000 1e-4\nFDJUMPLOG N\n")
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        mjd = np.asarray(toas.get_mjds(), dtype=float)
        sel = (mjd >= 54500) & (mjd <= 55000)
        f_ghz = np.asarray(toas.get_freqs()) / 1000.0
        np.testing.assert_allclose(d[sel], 1e-4 * f_ghz[sel], rtol=1e-9)
        np.testing.assert_allclose(d[~sel], 0.0, atol=1e-15)


class TestSolarWind:
    def test_spherical_dm_positive(self, toas):
        m = _model("NE_SW 10\n")
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        assert np.all(d > 0)
        # low frequencies delayed more
        freq = np.asarray(toas.get_freqs())
        assert np.median(d[freq < 500]) > np.median(d[freq > 1000])

    def test_powerlaw_p2_close_to_spherical(self, toas):
        """At p=2 the Hazboun geometry reduces to the spherical model up to
        the half-path (the spherical model integrates past the Sun)."""
        m0 = _model("NE_SW 10\nSWM 0\n")
        m1 = _model("NE_SW 10\nSWM 1\nSWP 2\n")
        d0 = np.asarray(m0.delay(toas)) - np.asarray(_model("").delay(toas))
        d1 = np.asarray(m1.delay(toas)) - np.asarray(_model("").delay(toas))
        np.testing.assert_allclose(d1, d0, rtol=1e-4)

    def test_ne_sw_deriv(self, toas):
        m = _model("NE_SW 10\n")
        _check_deriv(m, toas, "NE_SW")

    def test_swx(self, toas):
        m = _model("SWXDM_0001 1e-3\nSWXP_0001 2\nSWXR1_0001 54500\n"
                   "SWXR2_0001 55000\n")
        assert "SolarWindDispersionX" in m.components
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        mjd = np.asarray(toas.get_mjds(), dtype=float)
        out = (mjd < 54500) | (mjd > 55000)
        np.testing.assert_allclose(d[out], 0.0, atol=1e-15)
        assert np.max(np.abs(d[~out])) > 0


class TestChromatic:
    def test_cm_taylor(self, toas):
        from pint_tpu import DMconst

        m = _model("CM 1e-2\nTNCHROMIDX 4\n")
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        freq = np.asarray(toas.get_freqs())
        expect = 1e-2 * DMconst * freq**-4.0
        np.testing.assert_allclose(d, expect, rtol=5e-3)

    def test_cm_deriv(self, toas):
        m = _model("CM 1e-2\nCM1 1e-4\nCMEPOCH 55000\n")
        # delay is linear in CM terms: a large FD step avoids phase-quantization
        # noise without truncation error
        for p in ["CM", "CM1"]:
            _check_deriv(m, toas, p, step=10.0, rtol=5e-4)

    def test_cmx(self, toas):
        m = _model("CMX_0001 1e-2\nCMXR1_0001 54500\nCMXR2_0001 55000\n")
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        mjd = np.asarray(toas.get_mjds(), dtype=float)
        assert np.all(d[(mjd >= 54500) & (mjd <= 55000)] > 0)
        np.testing.assert_allclose(d[(mjd < 54500) | (mjd > 55000)], 0, atol=1e-16)


class TestIFunc:
    def test_linear_interp(self, toas):
        m = _model("SIFUNC 2 0\nIFUNC1 54400 1e-4 0\nIFUNC2 55600 3e-4 0\n")
        ph = m.phase(toas) - _model("").phase(toas)
        d = (np.asarray(ph.int_) + np.asarray(ph.frac)) / 100.0  # /F0 -> seconds
        mjd = np.asarray(toas.get_mjds(), dtype=float)
        expect = np.interp(mjd, [54400, 55600], [1e-4, 3e-4])
        np.testing.assert_allclose(d, expect, rtol=1e-5)

    def test_constant_interp(self, toas):
        m = _model("SIFUNC 0 0\nIFUNC1 54400 1e-4 0\nIFUNC2 55000 3e-4 0\n")
        ph = m.phase(toas) - _model("").phase(toas)
        d = (np.asarray(ph.int_) + np.asarray(ph.frac)) / 100.0
        mjd = np.asarray(toas.get_mjds(), dtype=float)
        np.testing.assert_allclose(d[mjd < 54999], 1e-4, rtol=1e-9)
        np.testing.assert_allclose(d[mjd > 55001], 3e-4, rtol=1e-9)


class TestPiecewise:
    def test_range_phase(self, toas):
        m = _model("PWEP_1 54750\nPWSTART_1 54500\nPWSTOP_1 55000\n"
                   "PWF0_1 1e-7\n")
        ph = m.phase(toas) - _model("").phase(toas)
        d = np.asarray(ph.int_) + np.asarray(ph.frac)
        tdb = np.asarray(toas.tdb, dtype=float)
        delay = np.asarray(m.delay(toas))
        t_bary = tdb - delay / 86400.0
        inr = (t_bary >= 54500) & (t_bary <= 55000)
        dt = (tdb - 54750.0) * 86400.0 - delay
        np.testing.assert_allclose(d[inr], 1e-7 * dt[inr], rtol=1e-4, atol=1e-7)
        np.testing.assert_allclose(d[~inr], 0.0, atol=1e-12)


class TestTroposphere:
    def test_delay_scale(self, toas):
        m = _model("CORRECT_TROPOSPHERE Y\n")
        assert "TroposphereDelay" in m.components
        d = np.asarray(m.delay(toas)) - np.asarray(_model("").delay(toas))
        # zenith hydrostatic delay ~7-8 ns; mapped delays larger, below 200 ns
        assert np.all(d >= 0)
        assert np.all(d < 2e-7)
        assert np.max(d) > 5e-9


class TestParfileRoundtrip:
    def test_longtail_roundtrip(self):
        from pint_tpu.models import get_model

        m = _model("GLEP_1 55000\nGLF0_1 1e-7\nWXEPOCH 55000\nWXFREQ_0001 0.005\n"
                   "WXSIN_0001 1e-5\nWXCOS_0001 2e-5\nFD1 1e-4\nNE_SW 10\n")
        m2 = get_model(m.as_parfile().splitlines(keepends=True))
        assert m2.GLF0_1.value == 1e-7
        assert m2.WXSIN_0001.value == 1e-5
        assert m2.FD1.value == 1e-4
        assert m2.NE_SW.value == 10.0


class TestReviewRegressions:
    """Regressions for review findings: gap detection, index >= 2 families,
    unset exemplars."""

    def test_glitch_index_2_only(self, toas):
        m = _model("GLEP_2 55000\nGLF0_2 1e-7\n")
        assert m.components["Glitch"].glitch_indices == [2]
        d = np.asarray(m.phase(toas).frac) - np.asarray(_model("").phase(toas).frac)
        assert np.any(np.abs(d) > 0)

    def test_wave_without_pairs_evaluates(self, toas):
        m = _model("WAVEEPOCH 55000\nWAVE_OM 0.005\n")
        m.phase(toas)  # must not crash on the unset WAVE1 exemplar

    def test_cm_taylor_gap_raises(self):
        from pint_tpu.exceptions import MissingParameter

        with pytest.raises(MissingParameter):
            _model("CM 0.01\nCM3 1e-4\nCMEPOCH 55000\n")

    def test_dm_taylor_gap_raises(self):
        from pint_tpu.exceptions import MissingParameter

        with pytest.raises(MissingParameter):
            _model("DM3 1e-4\n")

    def test_fdjump_parfile_no_spurious_lines(self):
        m = _model("FD1JUMP -fe 430 1e-4\n")
        text = m.as_parfile()
        assert "FD2JUMP" not in text
        assert "FD1JUMP" in text


class TestPhaseOffset:
    """PHOFF semantics (reference ``phase_offset.py:37``): applies to
    physical TOAs, zero at the TZR TOA — so it survives into the absolute
    phase instead of cancelling against the TZR reference."""

    def test_phoff_shifts_absolute_phase(self, toas):
        m0 = _model("")
        m = _model("PHOFF 0.2\n")
        assert "PhaseOffset" in m.components
        d = np.asarray(m.phase(toas, abs_phase=True).frac) \
            - np.asarray(m0.phase(toas, abs_phase=True).frac)
        np.testing.assert_allclose(d, -0.2, atol=1e-9)

    def test_phoff_disables_mean_subtraction_and_is_fittable(self, toas):
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.residuals import Residuals

        m = _model("PHOFF 0.01 1\n")
        r = Residuals(toas, m)
        assert not r.subtract_mean
        f = WLSFitter(toas, m)
        f.fit_toas()
        # the data were simulated with PHOFF=0 -> the fit must pull it back
        assert abs(f.model.PHOFF.value) < 4 * f.model.PHOFF.uncertainty + 1e-4
        assert "PHOFF" in f.fitted_params

    def test_phoff_derivative_column(self, toas):
        m = _model("PHOFF 0.0 1\n")
        M, names, units = m.designmatrix(toas)
        j = names.index("PHOFF")
        col = np.asarray(M)[:, j]
        # d resid_seconds / d PHOFF = +1/F0 on every physical TOA
        np.testing.assert_allclose(col, 1.0 / float(m.F0.value), rtol=1e-9)
