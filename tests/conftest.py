"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on host-platform virtual devices (the same mechanism the driver's
``dryrun_multichip`` uses).  Must run before jax initializes a backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# The container's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (the single-chip TPU tunnel), so env vars alone are too
# late — override via jax.config before any backend is touched.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("virtual 8-device mesh unavailable")
    return devs[:8]
