"""Long-tail infrastructure: TOASelect caching, satellite observatories,
global clock corrections, BT_piecewise binary, TCB conversion."""

import io
import struct

import numpy as np
import pytest


class TestTOASelect:
    def test_range_and_nonrange(self):
        from pint_tpu.toa_select import TOASelect

        mjds = np.array([100.0, 200.0, 300.0, 400.0])
        sel = TOASelect(is_range=True)
        r = sel.get_select_index({"DMX_0001": (150, 350)}, mjds)
        np.testing.assert_array_equal(r["DMX_0001"], [1, 2])
        names = np.array(["430", "Lband", "430", "820"], dtype=object)
        sel2 = TOASelect(is_range=False)
        r2 = sel2.get_select_index({"JUMP1": "430"}, names)
        np.testing.assert_array_equal(r2["JUMP1"], [0, 2])

    def test_cache_hits(self):
        from pint_tpu.toa_select import TOASelect

        mjds = np.arange(1000.0)
        sel = TOASelect(is_range=True)
        r1 = sel.get_select_index({"a": (10, 20)}, mjds)
        r2 = sel.get_select_index({"a": (10, 20)}, mjds)
        assert r1 is r2  # cached object returned
        r3 = sel.get_select_index({"a": (10, 30)}, mjds)
        assert len(r3["a"]) > len(r1["a"])


def _orbit_fits(path, mjds_tt, pos_km):
    """Minimal FPorbit-style FITS (TIME, X, Y, Z in meters)."""
    from test_photon_domain import _card, _pad

    met = (np.asarray(mjds_tt) - 50000.0) * 86400.0
    hdr0 = b"".join([_card("SIMPLE", True), _card("BITPIX", 8),
                     _card("NAXIS", 0), b"END".ljust(80)])
    rows = b"".join(struct.pack(">dddd", t, *(p * 1e3))
                    for t, p in zip(met, pos_km))
    hdr1 = b"".join([
        _card("XTENSION", "BINTABLE"), _card("BITPIX", 8), _card("NAXIS", 2),
        _card("NAXIS1", 32), _card("NAXIS2", len(met)), _card("PCOUNT", 0),
        _card("GCOUNT", 1), _card("TFIELDS", 4),
        _card("TTYPE1", "TIME"), _card("TFORM1", "D"),
        _card("TTYPE2", "X"), _card("TFORM2", "D"),
        _card("TTYPE3", "Y"), _card("TFORM3", "D"),
        _card("TTYPE4", "Z"), _card("TFORM4", "D"),
        _card("EXTNAME", "ORBIT"), _card("MJDREFI", 50000),
        _card("MJDREFF", 0.0), _card("TIMESYS", "TT"), b"END".ljust(80),
    ])
    data = rows + b"\0" * ((len(rows) + 2879) // 2880 * 2880 - len(rows))
    with open(path, "wb") as f:
        f.write(_pad(hdr0).replace(b"\0", b" "))
        f.write(_pad(hdr1).replace(b"\0", b" "))
        f.write(data)


class TestSatelliteObs:
    def test_orbit_interpolation(self, tmp_path):
        from pint_tpu.observatory.satellite_obs import get_satellite_observatory

        # circular LEO: 7000 km radius, 98-min period
        t = 55000.0 + np.linspace(0, 0.2, 200)
        w = 2 * np.pi / (98.0 / 1440.0)
        pos = 7000.0 * np.column_stack([
            np.cos(w * (t - t[0])), np.sin(w * (t - t[0])), np.zeros_like(t)])
        f = str(tmp_path / "orbit.fits")
        _orbit_fits(f, t, pos)
        obs = get_satellite_observatory("TESTSAT", f, fmt="FPORBIT")
        tq = np.array([55000.05, 55000.1])
        p_m, v_ms = obs.get_gcrs(tq, tt_mjd=tq)
        # radius preserved by the spline
        np.testing.assert_allclose(np.linalg.norm(p_m, axis=1), 7.0e6,
                                   rtol=1e-4)
        # orbital speed = w * r
        np.testing.assert_allclose(np.linalg.norm(v_ms, axis=1),
                                   w * 7.0e6 / 86400.0, rtol=1e-3)
        # ssb posvel composes the Earth position
        pv = obs.posvel(tq, tq)
        assert np.all(np.linalg.norm(pv.pos, axis=1) > 1e8)  # ~1 AU in km
        with pytest.raises(ValueError, match="outside orbit"):
            obs.get_gcrs(np.array([55010.0]))

    def test_registry(self, tmp_path):
        from pint_tpu.observatory import get_observatory
        from pint_tpu.observatory.satellite_obs import get_satellite_observatory

        t = 55000.0 + np.linspace(0, 0.1, 50)
        pos = np.tile([7000.0, 0, 0], (50, 1))
        f = str(tmp_path / "o.fits")
        _orbit_fits(f, t, pos)
        get_satellite_observatory("TESTSAT2", f, fmt="FPORBIT")
        assert get_observatory("testsat2").name == "testsat2"


class TestGlobalClock:
    def test_local_mirror(self, tmp_path, monkeypatch):
        from pint_tpu.observatory.global_clock_corrections import (
            Index, clock_search_dirs, get_clock_correction_file)

        d = tmp_path / "mirror"
        d.mkdir()
        (d / "time_gbt.dat").write_text("# clock\n")
        (d / "index.txt").write_text(
            "# file interval invalid\ntime_gbt.dat 7.0\n")
        monkeypatch.setenv("PINT_CLOCK_DIR", str(d))
        monkeypatch.delenv("PINT_CLOCK_REPO", raising=False)
        monkeypatch.setenv("PINT_CLOCK_CACHE", str(tmp_path / "cache"))
        assert str(d) in clock_search_dirs()
        assert get_clock_correction_file("time_gbt.dat") is not None
        assert get_clock_correction_file("missing.dat") is None
        idx = Index(url_base=str(d))
        assert idx.files["time_gbt.dat"].update_interval_days == 7.0


class TestBTPiecewise:
    PAR = """
PSR  J1023+0038
RAJ  10:23:47.68 1
DECJ 00:38:40.8
POSEPOCH 55000
F0   592.42145 1
PEPOCH 55000
DM   14.325
BINARY BT_piecewise
PB   0.1980963 1
A1   0.343356 1
T0   55000.02 1
ECC  0.0
OM   0.0
T0X_0001 55000.0200002
A1X_0001 0.343360
XR1_0001 55010.0
XR2_0001 55020.0
UNITS TDB
"""

    def test_piecewise_applies_in_range(self):
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        m = get_model(io.StringIO(self.PAR))
        assert "BinaryBT_piecewise" in m.components
        ts = make_fake_toas_uniform(55005, 55025, 40, m, error_us=1.0)
        # same par without the piece
        base = self.PAR
        for ln in ("T0X_0001 55000.0200002\n", "A1X_0001 0.343360\n",
                   "XR1_0001 55010.0\n", "XR2_0001 55020.0\n"):
            base = base.replace(ln, "")
        m0 = get_model(io.StringIO(base.replace("BT_piecewise", "BT")))
        d1 = np.asarray(m.delay(ts))
        d0 = np.asarray(m0.delay(ts))
        mjds = np.asarray(ts.get_mjds(), dtype=float)
        inr = (mjds >= 55010.0) & (mjds < 55020.0)
        # outside the piece the two models agree exactly
        np.testing.assert_allclose(d1[~inr], d0[~inr], atol=1e-12)
        # inside, the A1/T0 overrides shift the delay (the shift oscillates
        # with orbital phase, so test the aggregate, not every epoch)
        dd = np.abs(d1[inr] - d0[inr])
        assert dd.max() > 1e-6
        assert dd.mean() > 3e-7

    def test_fit_recovers_piece_a1(self):
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform

        m = get_model(io.StringIO(self.PAR))
        ts = make_fake_toas_uniform(55005, 55025, 60, m, error_us=1.0,
                                    add_noise=True,
                                    rng=np.random.default_rng(0))
        m2 = get_model(io.StringIO(self.PAR))
        m2.A1X_0001.value = 0.343356  # forget the override
        m2.free_params = ["A1X_0001"]
        f = WLSFitter(ts, m2)
        f.fit_toas(maxiter=3)
        assert float(f.model.A1X_0001.value) == pytest.approx(0.343360,
                                                              abs=3e-6)


class TestTCBConversion:
    def test_roundtrip(self):
        from pint_tpu.models import get_model
        from pint_tpu.models.tcb_conversion import IFTE_K, convert_tcb_tdb

        par = ("PSR J0\nRAJ 10:00:00\nDECJ 10:00:00\nPOSEPOCH 55000\n"
               "F0 100.0 1\nF1 -1e-14\nPEPOCH 55000\nDM 10.0\nUNITS TCB\n")
        # "raw" keeps the TCB model untouched (allow_tcb=True now converts
        # on load, reference model_builder.py:139 semantics)
        m = get_model(io.StringIO(par), allow_tcb="raw")
        f0_tcb = float(m.F0.value)
        pepoch_tcb = float(m.PEPOCH.value)
        convert_tcb_tdb(m)
        assert m.UNITS.value == "TDB"
        assert float(m.F0.value) == pytest.approx(f0_tcb * float(IFTE_K),
                                                  rel=1e-14)
        assert float(m.PEPOCH.value) < pepoch_tcb  # pulled toward IFTE_MJD0
        # F1 scales by K^2
        assert float(m.F1.value) == pytest.approx(-1e-14 * float(IFTE_K) ** 2,
                                                  rel=1e-12)
        # DM scales by K
        assert float(m.DM.value) == pytest.approx(10.0 * float(IFTE_K),
                                                  rel=1e-14)
        convert_tcb_tdb(m, backwards=True)
        assert float(m.F0.value) == pytest.approx(f0_tcb, rel=1e-14)
        assert float(m.PEPOCH.value) == pytest.approx(pepoch_tcb, abs=1e-9)


class TestLoadObservatories:
    def test_json_loader_and_override(self, tmp_path, monkeypatch):
        """Custom observatory JSON + $PINT_OBS_OVERRIDE (reference
        topo_obs.py:457,491 schema)."""
        import json

        import numpy as np

        from pint_tpu.observatory import (Observatory, get_observatory,
                                          load_observatories,
                                          load_observatories_from_usual_locations)

        defs = {
            "mytelescope": {
                "itrf_xyz": [882589.289, -4924872.368, 3943729.418],
                "tempo_code": "z",
                "aliases": ["myt"],
                "clock_file": "time_myt.dat",
                "apply_gps2utc": False,
                "fullname": "My Telescope",
                "origin": ["line one", "line two"],
            }
        }
        p = tmp_path / "obs.json"
        p.write_text(json.dumps(defs))
        added = load_observatories(str(p))
        assert added == ["mytelescope"]
        o = get_observatory("myt")
        assert o.name == "mytelescope"
        assert o.include_gps is False
        assert o.origin == "line one\nline two"
        assert np.allclose(o.itrf_xyz, defs["mytelescope"]["itrf_xyz"])
        # redefinition without overwrite raises; with overwrite succeeds
        import pytest as _pt

        with _pt.raises(ValueError):
            load_observatories(str(p))
        defs["mytelescope"]["itrf_xyz"][0] += 1.0
        p.write_text(json.dumps(defs))
        load_observatories(str(p), overwrite=True)
        assert get_observatory("mytelescope").itrf_xyz[0] == \
            882589.289 + 1.0
        # override an existing builtin via the env var
        gbt_xyz = list(get_observatory("gbt").itrf_xyz)
        defs2 = {"gbt": {"itrf_xyz": [gbt_xyz[0] + 0.5, gbt_xyz[1],
                                      gbt_xyz[2]],
                         "clock_file": "time_gbt.dat"}}
        p2 = tmp_path / "override.json"
        p2.write_text(json.dumps(defs2))
        monkeypatch.setenv("PINT_OBS_OVERRIDE", str(p2))
        load_observatories_from_usual_locations(clear=True)
        assert get_observatory("gbt").itrf_xyz[0] == gbt_xyz[0] + 0.5
        # restore pristine registry for other tests
        monkeypatch.delenv("PINT_OBS_OVERRIDE")
        Observatory.clear_registry()
        assert np.allclose(get_observatory("gbt").itrf_xyz, gbt_xyz)

    def test_malformed_override_leaves_registry_intact(self, tmp_path):
        """Regression: a bad entry must not delete the builtin site."""
        import json

        import pytest as _pt

        from pint_tpu.observatory import get_observatory, load_observatories

        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"gbt": {"overwrite": True}}))  # no itrf_xyz
        before = list(get_observatory("gbt").itrf_xyz)
        with _pt.raises(ValueError):
            load_observatories(str(p))
        assert list(get_observatory("gbt").itrf_xyz) == before
        # partial load: first entry valid, second invalid -> nothing applied
        p.write_text(json.dumps({
            "newsite": {"itrf_xyz": [1.0, 2.0, 3.0]},
            "badsite": {"itrf_xyz": [1.0]},
        }))
        with _pt.raises(ValueError):
            load_observatories(str(p))
        with _pt.raises(KeyError):
            get_observatory("newsite")

    def test_constructor_failure_rolls_back_registry(self, tmp_path):
        """A failure DURING the mutation loop (past pre-validation) must
        restore the registry snapshot, not leave earlier sites replaced."""
        import json

        import pytest as _pt

        from pint_tpu.observatory import get_observatory, load_observatories

        before = list(get_observatory("gbt").itrf_xyz)
        p = tmp_path / "bad2.json"
        # entry 1 passes pre-validation and would replace gbt; entry 2
        # passes pre-validation but its constructor raises (aliases not
        # iterable)
        p.write_text(json.dumps({
            "gbt": {"itrf_xyz": [before[0] + 9.0, before[1], before[2]],
                    "overwrite": True},
            "badsite": {"itrf_xyz": [1.0, 2.0, 3.0], "aliases": 42},
        }))
        with _pt.raises(TypeError):
            load_observatories(str(p))
        assert list(get_observatory("gbt").itrf_xyz) == before
