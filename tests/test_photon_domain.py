"""Photon-domain stack: templates, event statistics, FITS event reading,
template MCMC fitting (reference tests: test_eventstats.py,
test_templates.py, test_event_toas.py, test_event_optimize.py)."""

import io
import struct

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# templates
# ---------------------------------------------------------------------------

class TestTemplates:
    def test_gaussian_normalized(self):
        from pint_tpu.templates import LCGaussian

        g = LCGaussian([0.03, 0.4])
        assert g.integrate(0, 1) == pytest.approx(1.0, abs=1e-6)
        # peak at the location
        grid = np.linspace(0, 1, 1001)
        assert abs(grid[np.argmax(g(grid))] - 0.4) < 2e-3

    def test_vonmises_lorentzian_normalized(self):
        from pint_tpu.templates import LCLorentzian, LCVonMises

        for prim in (LCVonMises([0.05, 0.7]), LCLorentzian([0.04, 0.2])):
            assert prim.integrate(0, 1) == pytest.approx(1.0, abs=1e-3)

    def test_template_mixture_and_background(self):
        from pint_tpu.templates import LCGaussian, LCTemplate

        t = LCTemplate([LCGaussian([0.02, 0.3]), LCGaussian([0.05, 0.7])],
                       [0.35, 0.25])
        assert t.integrate(0, 1) == pytest.approx(1.0, abs=1e-5)
        # background level: 1 - 0.6
        assert np.asarray(t(np.array([0.05])))[0] == pytest.approx(0.4, abs=0.01)
        assert t.get_location() == pytest.approx(0.3)

    def test_parameter_roundtrip(self):
        from pint_tpu.templates import LCGaussian, LCTemplate

        t = LCTemplate([LCGaussian([0.02, 0.3])], [0.5])
        p = t.get_parameters()
        p2 = p.copy()
        p2[0] = 0.04
        t.set_parameters(p2)
        assert t.primitives[0].get_width() == pytest.approx(0.04)
        np.testing.assert_allclose(t.get_parameters(), p2)

    def test_norm_angles_simplex(self):
        from pint_tpu.templates import NormAngles

        n = NormAngles([0.2, 0.5, 0.1])
        np.testing.assert_allclose(n(), [0.2, 0.5, 0.1], atol=1e-12)
        with pytest.raises(ValueError):
            NormAngles([0.7, 0.5])

    def test_random_draws_match_template(self):
        from pint_tpu.templates import LCGaussian, LCTemplate

        t = LCTemplate([LCGaussian([0.03, 0.5])], [0.9])
        ph = t.random(20000, rng=np.random.default_rng(0))
        # histogram peak should be near 0.5
        h, edges = np.histogram(ph, bins=50, range=(0, 1))
        assert abs(edges[np.argmax(h)] - 0.5) < 0.05

    def test_gaussfile_io(self, tmp_path):
        from pint_tpu.templates import LCTemplate, gauss_template_from_file

        p = tmp_path / "gauss.txt"
        p.write_text("const = 0.4\nphas1 = 0.30 0.01\nfwhm1 = 0.047 0.002\n"
                     "ampl1 = 0.6 0.05\n")
        t = gauss_template_from_file(str(p))
        assert isinstance(t, LCTemplate)
        assert t.primitives[0].get_location() == pytest.approx(0.30)
        assert t.norms()[0] == pytest.approx(0.6)

    def test_lcfitter_recovers_location(self):
        from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate

        truth = LCTemplate([LCGaussian([0.03, 0.55])], [0.8])
        phases = truth.random(4000, rng=np.random.default_rng(1))
        start = LCTemplate([LCGaussian([0.04, 0.50])], [0.7])
        f = LCFitter(start, phases)
        f.fit(quiet=True)
        assert start.primitives[0].get_location() == pytest.approx(0.55, abs=0.01)
        assert start.norms()[0] == pytest.approx(0.8, abs=0.08)

    def test_fit_position(self):
        from pint_tpu.templates import LCFitter, LCGaussian, LCTemplate

        truth = LCTemplate([LCGaussian([0.03, 0.62])], [0.9])
        phases = truth.random(3000, rng=np.random.default_rng(2))
        shifted = LCTemplate([LCGaussian([0.03, 0.52])], [0.9])
        f = LCFitter(shifted, phases)
        shift, err = f.fit_position()
        assert shift == pytest.approx(0.10, abs=0.01)
        assert 0 < err < 0.01


# ---------------------------------------------------------------------------
# event statistics
# ---------------------------------------------------------------------------

class TestEventStats:
    def test_uniform_phases_low_significance(self):
        from pint_tpu.eventstats import hm, sf_hm, z2m

        rng = np.random.default_rng(3)
        ph = rng.random(2000)
        h = hm(ph)
        assert sf_hm(h) > 1e-3  # not significant
        zs = z2m(ph, m=2)
        assert zs[-1] < 30

    def test_pulsed_phases_high_significance(self):
        from pint_tpu.eventstats import h2sig, hm, hmw, sf_hm, z2m, sf_z2m

        from pint_tpu.templates import LCGaussian, LCTemplate

        t = LCTemplate([LCGaussian([0.05, 0.5])], [0.5])
        ph = t.random(2000, rng=np.random.default_rng(4))
        h = hm(ph)
        assert sf_hm(h) < 1e-10
        assert h2sig(h) > 6
        z = z2m(ph, m=2)[-1]
        assert sf_z2m(z) < 1e-10
        # weights: all-ones equals unweighted
        assert hmw(ph, np.ones_like(ph)) == pytest.approx(h)

    def test_sig_conversions(self):
        from pint_tpu.eventstats import sig2sigma, sigma2sig

        assert sig2sigma(sigma2sig(3.0)) == pytest.approx(3.0)
        assert sig2sigma(0.5) == pytest.approx(0.0, abs=1e-10)


# ---------------------------------------------------------------------------
# FITS event reading
# ---------------------------------------------------------------------------

def _card(key, value, comment=""):
    if isinstance(value, str):
        v = f"'{value:<8}'"
    elif isinstance(value, bool):
        v = "T" if value else "F"
    else:
        v = repr(value)
    return f"{key:<8}= {v:>20} / {comment}"[:80].ljust(80).encode()


def _pad(b):
    n = (len(b) + 2879) // 2880 * 2880
    return b + b" " * (n - len(b)) if b and b[-1:] != b"\0" else b + b"\0" * (n - len(b))


def make_event_fits(path, met, energies, mjdrefi=56658,
                    mjdreff=0.000777592592592593, timesys="TDB",
                    timeref="SOLARSYSTEM"):
    """Write a minimal FITS file with an EVENTS BINTABLE (TIME, PI)."""
    hdr0 = b"".join([
        _card("SIMPLE", True), _card("BITPIX", 8), _card("NAXIS", 0),
        b"END".ljust(80),
    ])
    rows = b"".join(struct.pack(">d f", t, e) for t, e in zip(met, energies))
    hdr1 = b"".join([
        _card("XTENSION", "BINTABLE"), _card("BITPIX", 8), _card("NAXIS", 2),
        _card("NAXIS1", 12), _card("NAXIS2", len(met)), _card("PCOUNT", 0),
        _card("GCOUNT", 1), _card("TFIELDS", 2),
        _card("TTYPE1", "TIME"), _card("TFORM1", "D"),
        _card("TTYPE2", "PI"), _card("TFORM2", "E"),
        _card("EXTNAME", "EVENTS"),
        _card("MJDREFI", mjdrefi), _card("MJDREFF", mjdreff),
        _card("TIMESYS", timesys), _card("TIMEREF", timeref),
        _card("TIMEZERO", 0.0),
        b"END".ljust(80),
    ])
    data = rows + b"\0" * ((len(rows) + 2879) // 2880 * 2880 - len(rows))
    with open(path, "wb") as f:
        f.write(_pad(hdr0).replace(b"\0", b" "))
        f.write(_pad(hdr1).replace(b"\0", b" "))
        f.write(data)


class TestEventTOAs:
    def test_fits_roundtrip(self, tmp_path):
        from pint_tpu.fits_utils import get_hdu, read_fits

        p = str(tmp_path / "evt.fits")
        met = np.array([1000.0, 2000.0, 86400.0 * 3 + 10.0])
        make_event_fits(p, met, np.array([500., 700., 900.]))
        hdus = read_fits(p)
        hdu = get_hdu(hdus, "EVENTS")
        d = hdu.data()
        np.testing.assert_allclose(d["TIME"], met)
        np.testing.assert_allclose(d["PI"], [500., 700., 900.], rtol=1e-6)

    def test_event_mjds(self, tmp_path):
        from pint_tpu.fits_utils import get_hdu, read_fits, read_fits_event_mjds

        p = str(tmp_path / "evt.fits")
        met = np.array([0.0, 86400.0])
        make_event_fits(p, met, np.zeros(2))
        hdu = get_hdu(read_fits(p), "EVENTS")
        mjds = read_fits_event_mjds(hdu)
        assert float(mjds[1] - mjds[0]) == pytest.approx(1.0, abs=1e-12)
        assert float(mjds[0]) == pytest.approx(56658.000777592, abs=1e-9)

    def test_get_fits_toas_barycentered(self, tmp_path):
        from pint_tpu.event_toas import get_fits_TOAs

        p = str(tmp_path / "evt.fits")
        rng = np.random.default_rng(5)
        met = np.sort(rng.random(20)) * 86400 * 30
        make_event_fits(p, met, rng.random(20) * 1000)
        ts = get_fits_TOAs(p, mission="nicer")
        assert len(ts) == 20
        assert set(ts.obs) == {"barycenter"}
        # TDB equals the event MJDs for barycentered data
        np.testing.assert_allclose(
            np.asarray(ts.tdb, dtype=float),
            56658.000777592592 + met / 86400, rtol=0, atol=1e-9)
        # energy flags attached
        assert "energy" in ts.flags[0]

    def test_local_events_need_orbit(self, tmp_path):
        from pint_tpu.event_toas import get_fits_TOAs

        p = str(tmp_path / "evt.fits")
        make_event_fits(p, np.array([100.0]), np.array([1.0]),
                        timesys="TT", timeref="LOCAL")
        with pytest.raises(ValueError, match="satellite"):
            get_fits_TOAs(p, mission="nicer")

    def test_fermi_weights_calc(self):
        from pint_tpu.fermi_toas import calc_lat_weights

        w = calc_lat_weights(np.array([100.0, 1000.0, 10000.0]),
                             np.array([0.0, 0.0, 0.0]))
        assert np.all((w > 0) & (w <= 1.0))
        # off-source photons get lower weight
        w2 = calc_lat_weights(np.array([1000.0]), np.array([5.0]))
        assert w2[0] < calc_lat_weights(np.array([1000.0]), np.array([0.0]))[0]


# ---------------------------------------------------------------------------
# photon-template MCMC
# ---------------------------------------------------------------------------

class TestPhotonMCMC:
    @pytest.fixture(scope="class")
    def photon_setup(self):
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_uniform
        from pint_tpu.templates import LCGaussian, LCTemplate

        par = ("PSR J0030+0451\nRAJ 00:30:27.4\nDECJ 04:51:39.7\n"
               "POSEPOCH 55000\nF0 205.53069 1\nF1 -4.3e-16\nPEPOCH 55000\n"
               "DM 4.33\nUNITS TDB\n")
        m = get_model(io.StringIO(par))
        # photon arrival times: uniform epochs; phases drawn from template
        t = make_fake_toas_uniform(54990, 55010, 300, m, error_us=1.0,
                                   obs="barycenter", freq=np.inf,
                                   rng=np.random.default_rng(6))
        template = LCTemplate([LCGaussian([0.04, 0.5])], [0.6])
        # shift each TOA so its phase is a draw from the template
        ph_now = np.asarray(m.phase(t).frac) % 1.0
        ph_want = template.random(len(t), rng=np.random.default_rng(7))
        dt = ((ph_want - ph_now + 0.5) % 1.0 - 0.5) / float(m.F0.value)
        t.adjust_TOAs(dt)
        return m, t, template

    def test_binned_template_fit(self, photon_setup):
        from pint_tpu.event_fitter import MCMCFitterBinnedTemplate

        m, t, template = photon_setup
        m2 = __import__("copy").deepcopy(m)
        truth = float(m.F0.value)
        # 3e-8 Hz offset smears phase by ~0.026 cycles over the 20-day span:
        # clearly detectable against the 0.04-wide peak with 300 photons
        m2.F0.value = truth + 3e-8
        m2.F0.uncertainty = 1e-8
        f = MCMCFitterBinnedTemplate(
            t, m2, template, nwalkers=16,
            prior_info={"F0": {"distr": "uniform", "pmin": truth - 2e-7,
                               "pmax": truth + 2e-7}})
        f.fit_toas(maxiter=150, seed=8)
        assert abs(float(f.model.F0.value) - truth) < 2e-8
        assert f.sampler.acceptance_fraction > 0.1

    def test_empty_chain_raises_clear_error(self, photon_setup):
        """maxiter=0 with no resumed chain must raise a clear ValueError,
        not an opaque argmax/slice failure (advisor r3)."""
        import pytest as _pt

        from pint_tpu.event_fitter import MCMCFitterBinnedTemplate

        m, t, template = photon_setup
        f = MCMCFitterBinnedTemplate(t, __import__("copy").deepcopy(m),
                                     template, nwalkers=16)
        with _pt.raises(ValueError, match="empty chain"):
            f.fit_toas(maxiter=0, seed=1)
        with _pt.raises(ValueError, match="empty chain"):
            f.fit_toas(maxiter=0, seed=1, autocorr=True)

    def test_analytic_template_matches_binned(self, photon_setup):
        from pint_tpu.event_fitter import (MCMCFitterAnalyticTemplate,
                                           MCMCFitterBinnedTemplate)

        m, t, template = photon_setup
        x = np.array([[float(m.F0.value)], [float(m.F0.value) + 1e-7]])
        m1 = __import__("copy").deepcopy(m)
        fa = MCMCFitterAnalyticTemplate(t, m1, template, nwalkers=16)
        fb = MCMCFitterBinnedTemplate(t, __import__("copy").deepcopy(m),
                                      template, nbins=2048, nwalkers=16)
        la = fa.lnposterior_batch(x)
        lb = fb.lnposterior_batch(x)
        # binned lookup approximates the analytic density
        np.testing.assert_allclose(la, lb, rtol=2e-3)
        # higher posterior at the true F0
        assert la[0] > la[1]

    def test_marginalize_over_phase(self, photon_setup):
        from pint_tpu.event_fitter import marginalize_over_phase

        m, t, template = photon_setup
        ph = (np.asarray(m.phase(t).frac) + 0.3) % 1.0  # rotated
        grid = (np.arange(128) + 0.5) / 128
        tb = np.asarray(template(grid))
        dphis, lnls = marginalize_over_phase(ph, tb)
        best = dphis[np.argmax(lnls)]
        # shifting by ~0.7 realigns the rotation
        assert min(abs(best - 0.7), abs(best - 0.7 + 1), abs(best - 0.7 - 1)) < 0.03


class TestTemplateLongTail:
    """Skew/wrapped/MC template long tail (VERDICT r4 missing #2)."""

    def test_skew_gaussian_reduces_to_gaussian(self):
        from pint_tpu.templates.lcprimitives import (LCGaussian,
                                                     LCSkewGaussian,
                                                     LCWrappedFunction)

        s = LCSkewGaussian([0.04, 0.0, 0.45])
        assert isinstance(s, LCWrappedFunction)
        g = LCGaussian([0.04, 0.45])
        grid = np.linspace(0, 1, 257)
        np.testing.assert_allclose(np.asarray(s(grid)), np.asarray(g(grid)),
                                   atol=1e-9)

    def test_skew_gaussian_normalized_and_skewed(self):
        from pint_tpu.templates.lcprimitives import LCSkewGaussian

        s = LCSkewGaussian([0.05, 4.0, 0.4])
        assert s.integrate(0, 1) == pytest.approx(1.0, abs=1e-3)
        # positive shape skews right: right HWHM wider than left
        assert s.hwhm(True) > s.hwhm(False)
        # wide peak exercises the wrapped-truncation remainder term
        wide = LCSkewGaussian([0.8, 2.0, 0.5])
        assert wide.integrate(0, 1) == pytest.approx(1.0, abs=2e-3)

    def test_skew_gaussian_sampling_matches_pdf(self):
        from pint_tpu.templates.lcprimitives import LCSkewGaussian

        s = LCSkewGaussian([0.05, 4.0, 0.4])
        rng = np.random.default_rng(1)
        ph = s.random(100_000, rng=rng)
        grid = np.linspace(0, 1, 201)
        mids = 0.5 * (grid[:-1] + grid[1:])
        pdf = np.asarray(s(mids))
        pdf = pdf / pdf.sum()
        hist = np.histogram(ph, bins=grid)[0] / len(ph)
        assert np.abs(np.sum(mids * pdf) - ph.mean()) < 3e-3
        assert np.max(np.abs(np.cumsum(pdf) - np.cumsum(hist))) < 5e-3

    def test_two_comp_mc(self):
        from scipy.stats import norm

        from pint_tpu.templates.lcprimitives import two_comp_mc

        d = two_comp_mc(100_000, 0.02, 0.06, 0.5, norm.rvs,
                        rng=np.random.default_rng(2))
        assert d.shape == (100_000,)
        assert np.all((0 <= d) & (d < 1))
        left = int(((d > 0.3) & (d < 0.5)).sum())
        right = int(((d >= 0.5) & (d < 0.8)).sum())
        # side fractions follow w1/(w1+w2) = 0.25
        assert left / (left + right) == pytest.approx(0.25, abs=0.01)

    def test_energy_dependent_skew(self):
        from pint_tpu.templates.lceprimitives import LCESkewGaussian

        es = LCESkewGaussian([0.04, 2.0, 0.5], slopes=[0.01, -0.5, 0.0])
        v = es(np.array([0.45, 0.55]), np.array([2.5, 3.5]))
        assert v.shape == (2,) and np.all(np.isfinite(v)) and np.all(v >= 0)
        # energy-independent call falls back to the base parameters
        v0 = es(np.array([0.45]))
        assert np.isfinite(np.asarray(v0)).all()
        # the sign-free Shape column survives the energy track (only the
        # width is clamped positive): left- and right-skewed variants must
        # differ at identical energies
        neg = LCESkewGaussian([0.04, -3.0, 0.5])
        pos = LCESkewGaussian([0.04, 3.0, 0.5])
        ph = np.array([0.42, 0.58])
        en = np.array([3.0, 3.0])
        vn, vp = neg(ph, en), pos(ph, en)
        assert not np.allclose(vn, vp)
        assert vn[0] > vn[1] and vp[1] > vp[0]  # skew directions opposite

    def test_mc_round_trip_refit(self):
        """Draw photons from a skew template -> refit from a perturbed
        start -> recover the parameters (the VERDICT's MC round trip)."""
        from pint_tpu.templates import LCFitter, LCSkewGaussian, LCTemplate

        rng = np.random.default_rng(5)
        truth = LCTemplate([LCSkewGaussian([0.03, 3.0, 0.5])], [0.85])
        ph = truth.random(20_000, rng=rng)
        start = LCTemplate([LCSkewGaussian([0.05, 1.0, 0.45])], [0.7])
        f = LCFitter(start, ph)
        f.fit(quiet=True)
        got = start.primitives[0].p
        assert got[2] == pytest.approx(0.5, abs=0.01)       # location
        assert got[0] == pytest.approx(0.03, rel=0.25)      # width
        assert got[1] > 1.0                                 # right-skewed
        assert start.get_amplitudes()[0] == pytest.approx(0.85, abs=0.05)

    def test_get_errors_and_err_plot(self):
        from pint_tpu.templates import (LCSkewGaussian, LCTemplate,
                                        get_errors, make_err_plot)

        t = LCTemplate([LCSkewGaussian([0.03, 3.0, 0.5])], [0.9])
        fv, e1, e2 = get_errors(t, 300, n=6, rng=np.random.default_rng(3))
        assert fv.shape == e1.shape == e2.shape == (6,)
        assert np.all(np.isfinite(e1)) and np.all(e1 > 0)
        assert np.all(np.isfinite(e2)) and np.all(e2 > 0)
        # most realizations recover phase within a few estimated errors
        assert np.median(np.abs(fv) / e1) < 5.0
        fig = make_err_plot(t, totals=(50,), n=4,
                            rng=np.random.default_rng(4))
        assert fig is not None
        import matplotlib.pyplot as plt

        plt.close(fig)
