"""ecorr_average vs the stored output of NANOGrav's tempo ``res_avg`` tool
(reference ``tests/test_ecorr_average.py`` — which is skipped upstream for
needing the res_avg binary + a DE436 kernel; here we compare the
kernel-INDEPENDENT columns: segment structure, weighted epoch MJDs, and
averaged uncertainties, all of which depend only on the TOAs and the
EFAC/EQUAD/ECORR noise model).
"""

import os

import numpy as np
import pytest

DATADIR = "/root/reference/tests/datafile"
PAR = f"{DATADIR}/J0023+0923_NANOGrav_11yv0.gls.par"
TIM = f"{DATADIR}/J0023+0923_NANOGrav_11yv0.tim"
RESAVG = f"{PAR}.resavg"

pytestmark = pytest.mark.skipif(not os.path.exists(RESAVG),
                                reason="resavg datafile unavailable")


@pytest.fixture(scope="module")
def avg_and_golden():
    from pint_tpu.models import get_model_and_toas
    from pint_tpu.residuals import Residuals

    model, toas = get_model_and_toas(PAR, TIM)
    avg = Residuals(toas, model).ecorr_average()
    golden = np.genfromtxt(RESAVG, usecols=(0, 1, 2, 3))
    order = np.argsort(np.asarray(avg["mjds"]))
    return avg, order, golden


class TestResavgGolden:
    def test_segment_count_matches(self, avg_and_golden):
        avg, order, golden = avg_and_golden
        assert len(avg["mjds"]) == len(golden)

    def test_epoch_mjds_match(self, avg_and_golden):
        """Weighted segment epochs agree with res_avg at <1e-9 d (the
        reference test's own tolerance)."""
        avg, order, golden = avg_and_golden
        diff = np.abs(np.asarray(avg["mjds"])[order] - golden[:, 0])
        assert diff.max() < 1e-9

    def test_frequencies_match(self, avg_and_golden):
        avg, order, golden = avg_and_golden
        diff = np.abs(np.asarray(avg["freqs"])[order] - golden[:, 1])
        assert diff.max() < 0.5  # MHz; res_avg rounds to 1e-4 MHz

    def test_errors_match(self, avg_and_golden):
        """Averaged uncertainties (incl. the ECORR variance) agree with
        res_avg to 5e-4 relative (reference tolerance)."""
        avg, order, golden = avg_and_golden
        ratio = np.asarray(avg["errors"])[order] * 1e6 / golden[:, 3]
        assert np.abs(ratio - 1.0).max() < 5e-4

    def test_indices_partition_the_toas(self, avg_and_golden):
        avg, order, golden = avg_and_golden
        seen = np.concatenate([np.asarray(i) for i in avg["indices"]])
        assert len(seen) == len(np.unique(seen))  # disjoint segments
