"""Regression observatory under test (tools/perfwatch.py).

Pins the acceptance contract: ingestion of every historical artifact
shape, ``--check`` exiting 0 over the committed history and 1 when a
synthetic run drops fits/s by >30%, sanity_ok=false exclusion, the
per-(metric, platform) series split, and the MAD noise floor.
"""

import json
import os
import sys

import pytest

pytestmark = pytest.mark.perfwatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.perfwatch import (  # noqa: E402
    HISTORY_SCHEMA,
    build_history,
    collect,
    ingest_file,
    main,
    render_report,
)


def _bench(path, round_, value, platform="cpu", sanity=True, wrap=True,
           compile_s=10.0, error=None, extra=None):
    headline = {"metric": "gls_chisq_grid_evals_per_sec", "value": value,
                "unit": "fits/s", "platform": platform,
                "sanity_ok": sanity, "compile_s": compile_s}
    if error is not None:
        headline["error"] = error
    if extra:
        headline.update(extra)
    doc = {"n": 1, "rc": 0, "parsed": headline,
           "tail": "# chatter\n" + json.dumps(headline) + "\n"} \
        if wrap else headline
    fn = os.path.join(path, f"BENCH_r{round_:02d}.json")
    with open(fn, "w") as f:
        json.dump(doc, f)
    return fn


class TestIngestion:
    def test_wrapper_and_bare_shapes(self, tmp_path):
        errors = []
        f1 = _bench(str(tmp_path), 1, 100.0, wrap=True)
        f2 = _bench(str(tmp_path), 2, 105.0, wrap=False)
        r1 = ingest_file(f1, errors)
        r2 = ingest_file(f2, errors)
        assert not errors
        assert r1.round == 1 and r1.value == 100.0 and r1.platform == "cpu"
        assert r2.round == 2 and r2.value == 105.0
        assert r1.usable and r2.usable

    def test_tail_headline_recovers_null_parsed(self, tmp_path):
        """Rounds whose driver 'parsed' is null (r03) recover the
        headline from the JSON line in the captured tail; the FINAL tail
        line wins (the bench's exactly-once emit contract)."""
        doc = {"n": 1, "rc": 0, "parsed": None,
               "tail": 'noise\n{"metric": "m", "value": 50.0, '
                       '"platform": "tpu"}\n'}
        fn = tmp_path / "BENCH_r03.json"
        fn.write_text(json.dumps(doc))
        errors = []
        r = ingest_file(str(fn), errors)
        assert not errors
        assert r.value == 50.0 and r.platform == "tpu"

    def test_headline_less_wrapper_excluded_not_fatal(self, tmp_path):
        doc = {"n": 1, "rc": 1, "parsed": None, "tail": "SIGILL noise\n"}
        fn = tmp_path / "BENCH_r03.json"
        fn.write_text(json.dumps(doc))
        errors = []
        r = ingest_file(str(fn), errors)
        assert not errors
        assert not r.usable and r.error

    def test_unreadable_is_fatal(self, tmp_path):
        fn = tmp_path / "BENCH_r01.json"
        fn.write_text("{not json")
        errors = []
        assert ingest_file(str(fn), errors) is None
        assert errors

    def test_telemetry_and_cost_blocks(self, tmp_path):
        fn = _bench(str(tmp_path), 6, 300.0, extra={
            "telemetry": {"jax": {"compiles": 12, "compile_seconds": 30.5},
                          "memory": {"peak_bytes_in_use": 2 ** 30}},
            "cost": {"name": "grid.chunk", "flops": 1e9,
                     "bytes_accessed": 2e9}})
        r = ingest_file(fn, [])
        assert r.compiles == 12 and r.compile_seconds == 30.5
        assert r.hbm_peak_bytes == 2 ** 30
        assert r.cost["flops"] == 1e9

    def test_multichip_with_cost_line(self, tmp_path):
        cost = {"name": "grid.chunk.sharded", "flops": 5.0,
                "num_devices": 4,
                "per_device": {"0": {"flops": 5.0}, "1": {"flops": 5.0}}}
        doc = {"n_devices": 4, "rc": 0, "ok": True, "skipped": False,
               "tail": "dryrun OK\n"
                       + json.dumps({"multichip_cost": cost}) + "\n"}
        fn = tmp_path / "MULTICHIP_r06.json"
        fn.write_text(json.dumps(doc))
        r = ingest_file(str(fn), [])
        assert r.kind == "multichip" and r.n_devices == 4
        assert r.multichip_cost["per_device"]["1"]["flops"] == 5.0

    def test_multichip_schema_tagged_tail_records(self, tmp_path):
        """Round-6+ dryrun tails carry pint_tpu.telemetry.multichip/1
        records: mesh shape, collective profile, scaling ratio and
        sharding plans all land on the RunRecord (and render)."""
        schema = "pint_tpu.telemetry.multichip/1"
        coll = {"schema": "pint_tpu.telemetry.collective_profile/1",
                "name": "grid.chunk.sharded", "collective_count": 6,
                "collective_bytes": 111616.0, "comm_compute_ratio": 0.1,
                "compute_bytes": 1113983.0, "flops": 1.0,
                "mesh_axes": {"grid": 8}, "num_devices": 8,
                "group_sizes": [8],
                "ops": {"all-gather": {"count": 6, "bytes": 111616.0}}}
        plan = {"schema": "pint_tpu.telemetry.sharding_plan/1",
                "name": "grid.chunk.sharded", "mesh": {"grid": 8},
                "num_devices": 8, "backend": "cpu",
                "inputs": ["PartitionSpec('grid',)"], "outputs": [],
                "error": None}
        cost = {"schema": "pint_tpu.telemetry.cost_profile/1",
                "name": "multichip.fit_step", "flops": 9.0,
                "num_devices": 8}
        tail = "\n".join([
            "dryrun_multichip OK: mesh stuff",
            json.dumps({"schema": schema, "record": "correctness",
                        "n_devices": 8, "mesh": {"grid": 2, "toa": 4},
                        "chi2_spread": 5e-6}),
            json.dumps({"schema": schema, "record": "cost",
                        "cost": cost}),
            json.dumps({"schema": schema, "record": "collective",
                        "collective": coll}),
            json.dumps({"schema": schema, "record": "sharding_plan",
                        "sharding_plan": plan}),
            json.dumps({"schema": schema, "record": "scaling",
                        "n_devices": 8, "speedup": 0.9,
                        "efficiency": 0.1125}),
        ]) + "\n"
        doc = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
               "tail": tail}
        fn = tmp_path / "MULTICHIP_r06.json"
        fn.write_text(json.dumps(doc))
        r = ingest_file(str(fn), [])
        assert r.mesh_shape == {"grid": 2, "toa": 4}
        assert r.multichip_cost["flops"] == 9.0  # cost record filled it
        assert r.multichip_collective["collective_bytes"] == 111616.0
        assert r.multichip_scaling["speedup"] == 0.9
        assert r.sharding_plans[0]["mesh"] == {"grid": 8}
        # and the report renders the enrichment
        import io

        out = io.StringIO()
        render_report([r], out=out)
        text = out.getvalue()
        assert "mesh={'grid': 2, 'toa': 4}" in text
        assert "collectives[grid.chunk.sharded]" in text
        assert "scaling: speedup 0.9" in text

    def test_history_schema(self, tmp_path):
        _bench(str(tmp_path), 1, 100.0)
        _bench(str(tmp_path), 2, 101.0)
        recs = collect([], str(tmp_path), [])
        h = build_history(recs)
        assert h["schema"] == HISTORY_SCHEMA
        assert [r["round"] for r in h["runs"]] == [1, 2]
        json.dumps(h)


class TestCheckGating:
    def test_committed_history_passes(self, capsys):
        """The acceptance pin: --check over the repo's own committed
        artifact history exits 0 on the current tree."""
        assert main(["--check", "--dir", REPO]) == 0
        assert "no meaningful regression" in capsys.readouterr().out

    def test_thirty_percent_drop_fails(self, tmp_path, capsys):
        """The acceptance pin: a synthetic run with a >30% fits/s drop
        against the same (metric, platform) series exits 1."""
        d = str(tmp_path)
        for i, v in enumerate([100.0, 102.0, 98.0], start=1):
            _bench(d, i, v)
        _bench(d, 4, 60.0)  # 40% below the 100.0 median
        assert main(["--check", "--dir", d]) == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_small_drop_passes(self, tmp_path):
        d = str(tmp_path)
        for i, v in enumerate([100.0, 102.0, 98.0], start=1):
            _bench(d, i, v)
        _bench(d, 4, 95.0)  # 5% drop: under the 30% bar
        assert main(["--check", "--dir", d]) == 0

    def test_sanity_false_runs_excluded(self, tmp_path):
        """A sanity_ok=false run neither fails the gate as the latest
        run nor poisons the baseline."""
        d = str(tmp_path)
        _bench(d, 1, 100.0)
        _bench(d, 2, 101.0)
        _bench(d, 3, 10.0, sanity=False)  # broken measurement, excluded
        assert main(["--check", "--dir", d]) == 0

    def test_errored_zero_run_excluded(self, tmp_path):
        d = str(tmp_path)
        _bench(d, 1, 100.0)
        _bench(d, 2, 0.0, error="TPU unavailable")
        assert main(["--check", "--dir", d]) == 0

    def test_platform_split_is_not_a_regression(self, tmp_path):
        """A CPU round after TPU rounds is a hardware change: the series
        split by platform must keep the 20x drop out of the gate."""
        d = str(tmp_path)
        _bench(d, 1, 360.0, platform="tpu")
        _bench(d, 2, 365.0, platform="tpu")
        _bench(d, 3, 18.0, platform="cpu")
        assert main(["--check", "--dir", d]) == 0

    def test_noise_floor_raises_the_bar(self, tmp_path):
        """A series whose own scatter exceeds the threshold only fails
        beyond its noise floor (MAD-scaled)."""
        d = str(tmp_path)
        # scatter ~40% around median 100: MAD = 40 -> floor ~178%
        for i, v in enumerate([60.0, 100.0, 140.0, 58.0, 142.0], start=1):
            _bench(d, i, v)
        _bench(d, 6, 55.0)  # 45% drop: over threshold, under noise floor
        assert main(["--check", "--dir", d]) == 0

    def test_compile_time_rise_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, compile_s=10.0)
        _bench(d, 4, 100.0, compile_s=20.0)  # 2x compile rise
        assert main(["--check", "--dir", d]) == 1
        assert "compile_s" in capsys.readouterr().out

    def test_zero_compile_baseline_is_skipped_not_infinite(self, tmp_path):
        """A compile_s history of 0.0 (warm persistent-compile-cache
        rounds) must not make the first cold-cache run an ungateable
        infinite regression — zero_baseline_fails stays off for
        compile_s; only ratio-like quantities opt in."""
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, compile_s=0.0)
        _bench(d, 4, 100.0, compile_s=25.0)  # first cold compile
        assert main(["--check", "--dir", d]) == 0

    def test_threshold_configurable(self, tmp_path):
        d = str(tmp_path)
        for i, v in enumerate([100.0, 100.0, 100.0], start=1):
            _bench(d, i, v)
        _bench(d, 4, 90.0)  # 10% drop
        assert main(["--check", "--dir", d]) == 0
        assert main(["--check", "--threshold", "0.05", "--dir", d]) == 1

    def test_newest_run_missing_quantity_not_regated(self, tmp_path,
                                                     capsys):
        """When the newest run lacks compile_s, that quantity is simply
        not gated — an older run must NOT be re-gated and presented as
        the latest verdict (which would mask the newest round)."""
        d = str(tmp_path)
        _bench(d, 1, 100.0, compile_s=10.0)
        _bench(d, 2, 100.0, compile_s=30.0)  # would fail if (re)gated
        fn = os.path.join(d, "BENCH_r03.json")
        headline = {"metric": "gls_chisq_grid_evals_per_sec",
                    "value": 100.0, "platform": "cpu", "sanity_ok": True}
        with open(fn, "w") as f:
            json.dump({"n": 1, "rc": 0, "parsed": headline, "tail": ""}, f)
        assert main(["--check", "--dir", d]) == 0
        assert "compile_s" not in capsys.readouterr().out

    def test_single_run_series_passes(self, tmp_path):
        d = str(tmp_path)
        _bench(d, 1, 100.0)
        assert main(["--check", "--dir", d]) == 0

    def test_empty_dir(self, tmp_path):
        assert main(["--check", "--dir", str(tmp_path)]) == 0
        assert main(["--dir", str(tmp_path)]) == 2


class TestReportAndJson:
    def test_report_renders_series_and_multichip(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0)
        _bench(d, 2, 120.0, extra={
            "cost": {"name": "grid.chunk", "flops": 1e9,
                     "bytes_accessed": 2e9, "peak_bytes": 3e6,
                     "num_devices": 1}})
        (tmp_path / "MULTICHIP_r02.json").write_text(json.dumps(
            {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
             "tail": ""}))
        assert main(["--dir", d]) == 0
        out = capsys.readouterr().out
        assert "gls_chisq_grid_evals_per_sec @ cpu" in out
        assert "+20.0" in out          # round-over-round delta
        assert "flops=1000000000" in out or "flops=1e+09" in out
        assert "multichip" in out and "8 devices" in out

    def test_json_history(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0)
        assert main(["--json", "--dir", d]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["schema"] == HISTORY_SCHEMA
        assert doc["runs"][0]["value"] == 100.0

    def test_bad_args(self):
        with pytest.raises(SystemExit):
            main(["--check", "--threshold", "0"])


def _warm(hits=2, cold=0, fps=900.0, p50=4.0, p99=6.0):
    return {"warm": {"cache_hits": hits, "cold_compiles": cold,
                     "warm_fits_per_s": fps, "p50_ms": p50,
                     "p99_ms": p99, "steady_state_compiles": 0}}


class TestWarmSeries:
    """The round-8 warm{} block: ingestion + gating of the warm-serving
    series (warm_fits_per_s gates drops, p99_ms gates rises) under the
    same max(30%, 3xMAD) bar as the headline."""

    def test_warm_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 8, 100.0,
                    extra=_warm(hits=3, cold=1, fps=850.5))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.warm_fits_per_s == 850.5
        assert r.warm_p50_ms == 4.0 and r.warm_p99_ms == 6.0
        assert r.warm_cache_hits == 3 and r.warm_cold_compiles == 1
        # and it survives the history document round trip
        doc = build_history([r])
        assert doc["runs"][0]["warm_fits_per_s"] == 850.5

    def test_runs_without_warm_block_stay_valid(self, tmp_path):
        """Pre-round-8 artifacts have no warm{}: ingestion leaves the
        fields None and the gate skips the series (nothing to compare)."""
        errors = []
        r = ingest_file(_bench(str(tmp_path), 5, 100.0), errors)
        assert not errors and r.usable
        assert r.warm_fits_per_s is None and r.warm_p99_ms is None
        d = str(tmp_path)
        _bench(d, 6, 100.0, extra=_warm())
        assert main(["--check", "--dir", d]) == 0

    def test_warm_fits_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([900.0, 920.0, 880.0], start=1):
            _bench(d, i, 100.0, extra=_warm(fps=v))
        _bench(d, 4, 100.0, extra=_warm(fps=500.0))  # 44% below median
        assert main(["--check", "--dir", d]) == 1
        assert "warm_fits_per_s" in capsys.readouterr().out

    def test_warm_p99_rise_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_warm(p99=5.0))
        _bench(d, 4, 100.0, extra=_warm(p99=12.0))  # 2.4x tail latency
        assert main(["--check", "--dir", d]) == 1
        assert "warm_p99_ms" in capsys.readouterr().out

    def test_small_warm_changes_pass(self, tmp_path):
        d = str(tmp_path)
        for i, (v, p) in enumerate([(900.0, 5.0), (920.0, 5.2),
                                    (880.0, 4.9)], start=1):
            _bench(d, i, 100.0, extra=_warm(fps=v, p99=p))
        _bench(d, 4, 100.0, extra=_warm(fps=860.0, p99=5.5))
        assert main(["--check", "--dir", d]) == 0

    def test_warm_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0, extra=_warm(fps=850.0))
        assert main(["--dir", d]) == 0
        out = capsys.readouterr().out
        assert "warm: 850.0 fits/s" in out
        assert "cache_hits=2" in out

    def test_malformed_warm_block_ignored(self, tmp_path):
        """A warm block with garbage types must not crash ingestion or
        fabricate a gated number."""
        errors = []
        fn = _bench(str(tmp_path), 9, 100.0,
                    extra={"warm": {"cache_hits": "many",
                                    "warm_fits_per_s": True,
                                    "p99_ms": None}})
        r = ingest_file(fn, errors)
        assert not errors
        assert r.warm_fits_per_s is None
        assert r.warm_cache_hits is None and r.warm_p99_ms is None

    def test_errored_warm_block_fails_when_history_had_warm(
            self, tmp_path, capsys):
        """A degraded warm{} (present but errored) on the newest run is
        a total warm-serving regression when prior runs measured warm
        serving — the missing-quantity skip must not swallow it."""
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_warm())
        _bench(d, 3, 100.0, extra={"warm": {
            "cache_hits": 0, "cold_compiles": 0, "warm_fits_per_s": None,
            "p50_ms": None, "p99_ms": None, "steady_state_compiles": None,
            "bucket": None, "chi2": None, "aot_cache": None,
            "error": "ImportError: serving broken"}})
        assert main(["--check", "--dir", d]) == 1
        assert "warm block degraded" in capsys.readouterr().out

    def test_errored_warm_block_clean_without_warm_history(self,
                                                           tmp_path):
        """Same degraded block with NO warm history (pre-round-8
        series) stays clean — there was nothing to regress from."""
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0, extra={"warm": {
            "warm_fits_per_s": None, "error": "ImportError: broken"}})
        assert main(["--check", "--dir", d]) == 0


def _tuned(fps=350.0, static=350.0, chunk=256, error=None):
    block = {"chunk": chunk, "static_chunk": 256,
             "tuned_fits_per_s": fps, "static_fits_per_s": static,
             "tuned_vs_static": (round(fps / static, 4)
                                 if fps is not None and static else None),
             "basis": "cost+measured", "decisions": "abc123def456"}
    if error is not None:
        block.update({"tuned_fits_per_s": None, "static_fits_per_s": None,
                      "tuned_vs_static": None, "chunk": None,
                      "basis": None, "decisions": None, "error": error})
    return {"tuned": block}


class TestTunedSeries:
    """The round-10 tuned{} block: ingestion + gating of the autotuner
    series.  tuned_fits_per_s gates drops like the headline; the
    tuned/static ratio gates DIRECTLY (within the newest run) because
    the autotuner's contract is "never slower than static"."""

    def test_tuned_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 10, 100.0,
                    extra=_tuned(fps=360.5, static=350.0, chunk=128))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.tuned_fits_per_s == 360.5
        assert r.tuned_vs_static == round(360.5 / 350.0, 4)
        assert r.tuned_chunk == 128
        assert r.tuned_decisions == "abc123def456"
        doc = build_history([r])
        assert doc["runs"][0]["tuned_fits_per_s"] == 360.5

    def test_tuned_at_parity_passes(self, tmp_path):
        d = str(tmp_path)
        _bench(d, 9, 100.0)
        _bench(d, 10, 100.0, extra=_tuned(fps=350.0, static=350.0))
        assert main(["--check", "--dir", d]) == 0

    def test_tuned_below_static_fails(self, tmp_path, capsys):
        """A tuned configuration measurably slower than the static
        default fails even with NO tuned history — the ratio gate is
        within-run."""
        d = str(tmp_path)
        _bench(d, 10, 100.0, extra=_tuned(fps=200.0, static=350.0))
        assert main(["--check", "--dir", d]) == 1
        assert "tuned_vs_static" in capsys.readouterr().out

    def test_tuned_fits_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([350.0, 360.0, 345.0], start=1):
            _bench(d, i, 100.0, extra=_tuned(fps=v, static=v))
        _bench(d, 4, 100.0, extra=_tuned(fps=200.0, static=200.0))
        assert main(["--check", "--dir", d]) == 1
        assert "tuned_fits_per_s" in capsys.readouterr().out

    def test_errored_tuned_block_fails_when_history_had_tuned(
            self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_tuned())
        _bench(d, 3, 100.0, extra=_tuned(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 1
        assert "tuned block degraded" in capsys.readouterr().out

    def test_errored_tuned_block_clean_without_tuned_history(
            self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0, extra=_tuned(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 0

    def test_malformed_tuned_block_ignored(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 11, 100.0,
                    extra={"tuned": {"tuned_fits_per_s": "fast",
                                     "tuned_vs_static": True,
                                     "chunk": "auto"}})
        r = ingest_file(fn, errors)
        assert not errors
        assert r.tuned_fits_per_s is None
        assert r.tuned_vs_static is None and r.tuned_chunk is None

    def test_tuned_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0, extra=_tuned(fps=360.0, static=350.0,
                                         chunk=128))
        assert main(["--dir", d]) == 0
        out = capsys.readouterr().out
        assert "tuned: 360.0 fits/s (chunk 128)" in out


def _catalog(fps=24.0, waste=0.05, lnl=900.0, n=16, error=None):
    block = {"n_pulsars": n, "buckets": 3, "pad_waste_frac": waste,
             "catalog_fits_per_s": fps, "joint_lnlike_per_s": lnl,
             "steady_state_compiles": 0}
    if error is not None:
        block.update({"n_pulsars": None, "buckets": None,
                      "pad_waste_frac": None, "catalog_fits_per_s": None,
                      "joint_lnlike_per_s": None,
                      "steady_state_compiles": None, "error": error})
    return {"catalog": block}


class TestCatalogSeries:
    """The round-11 catalog{} block: ingestion + gating of the PTA
    catalog-engine series (catalog_fits_per_s gates drops,
    pad_waste_frac gates rises, joint_lnlike_per_s gates drops) under
    the same max(30%, 3xMAD) bar as the headline."""

    def test_catalog_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 11, 100.0,
                    extra=_catalog(fps=25.5, waste=0.041, lnl=880.0))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.catalog_fits_per_s == 25.5
        assert r.catalog_pad_waste_frac == 0.041
        assert r.catalog_joint_lnlike_per_s == 880.0
        assert r.catalog_n_pulsars == 16
        # and it survives the history document round trip
        doc = build_history([r])
        assert doc["runs"][0]["catalog_fits_per_s"] == 25.5

    def test_catalog_fits_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([24.0, 25.0, 23.5], start=1):
            _bench(d, i, 100.0, extra=_catalog(fps=v))
        _bench(d, 4, 100.0, extra=_catalog(fps=12.0))  # 50% below
        assert main(["--check", "--dir", d]) == 1
        assert "catalog_fits_per_s" in capsys.readouterr().out

    def test_pad_waste_rise_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_catalog(waste=0.05))
        _bench(d, 4, 100.0, extra=_catalog(waste=0.20))  # 4x padding
        assert main(["--check", "--dir", d]) == 1
        assert "catalog_pad_waste_frac" in capsys.readouterr().out

    def test_small_catalog_changes_pass(self, tmp_path):
        d = str(tmp_path)
        for i, (v, pw) in enumerate([(24.0, 0.050), (25.0, 0.052),
                                     (23.5, 0.048)], start=1):
            _bench(d, i, 100.0, extra=_catalog(fps=v, waste=pw))
        _bench(d, 4, 100.0, extra=_catalog(fps=22.8, waste=0.055))
        assert main(["--check", "--dir", d]) == 0

    def test_errored_catalog_block_fails_when_history_had_catalog(
            self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_catalog())
        _bench(d, 3, 100.0,
               extra=_catalog(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 1
        assert "catalog block degraded" in capsys.readouterr().out

    def test_errored_catalog_block_clean_without_catalog_history(
            self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0,
               extra=_catalog(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 0

    def test_malformed_catalog_block_ignored(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 11, 100.0,
                    extra={"catalog": {"catalog_fits_per_s": "fast",
                                       "pad_waste_frac": True,
                                       "n_pulsars": "many"}})
        r = ingest_file(fn, errors)
        assert not errors
        assert r.catalog_fits_per_s is None
        assert r.catalog_pad_waste_frac is None
        assert r.catalog_n_pulsars is None

    def test_catalog_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0, extra=_catalog(fps=25.5, waste=0.04))
        assert main(["--dir", d]) == 0
        out = capsys.readouterr().out
        assert "catalog: 25.5 fits/s (16 pulsars)" in out


def _posterior(draws=9500.0, logprob=12000.0, p50=2.0, p99=4.0,
               steps=80, error=None):
    block = {"train_steps": steps, "elbo_final": -4.3,
             "draws_per_s": draws, "logprob_per_s": logprob,
             "p50_ms": p50, "p99_ms": p99, "steady_state_compiles": 0}
    if error is not None:
        block = {"train_steps": None, "elbo_final": None,
                 "draws_per_s": None, "logprob_per_s": None,
                 "p50_ms": None, "p99_ms": None,
                 "steady_state_compiles": None, "error": error}
    return {"posterior": block}


class TestPosteriorSeries:
    """The bench's posterior{} block (round 13+): amortized draw /
    log-prob throughput gate drops, the posterior door's p99 gates
    rises, and an errored block after measured rounds fails."""

    def test_posterior_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 13, 100.0,
                    extra=_posterior(draws=9500.0, logprob=12000.0,
                                     p99=4.5, steps=80))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.posterior_draws_per_s == 9500.0
        assert r.posterior_logprob_per_s == 12000.0
        assert r.posterior_p99_ms == 4.5
        assert r.posterior_train_steps == 80
        doc = build_history([r])
        assert doc["runs"][0]["posterior_draws_per_s"] == 9500.0

    def test_draws_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([9500.0, 9800.0, 9300.0], start=1):
            _bench(d, i, 100.0, extra=_posterior(draws=v))
        _bench(d, 4, 100.0, extra=_posterior(draws=4000.0))  # ~58% drop
        assert main(["--check", "--dir", d]) == 1
        assert "posterior_draws_per_s" in capsys.readouterr().out

    def test_logprob_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_posterior(logprob=12000.0))
        _bench(d, 4, 100.0, extra=_posterior(logprob=5000.0))
        assert main(["--check", "--dir", d]) == 1
        assert "posterior_logprob_per_s" in capsys.readouterr().out

    def test_p99_rise_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_posterior(p99=4.0))
        _bench(d, 4, 100.0, extra=_posterior(p99=9.0))  # >2x the tail
        assert main(["--check", "--dir", d]) == 1
        assert "posterior_p99_ms" in capsys.readouterr().out

    def test_small_posterior_changes_pass(self, tmp_path):
        d = str(tmp_path)
        for i, (v, p) in enumerate([(9500.0, 4.0), (9800.0, 4.2),
                                    (9300.0, 3.9)], start=1):
            _bench(d, i, 100.0, extra=_posterior(draws=v, p99=p))
        _bench(d, 4, 100.0, extra=_posterior(draws=9100.0, p99=4.3))
        assert main(["--check", "--dir", d]) == 0

    def test_errored_posterior_block_fails_when_history_had_it(
            self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_posterior())
        _bench(d, 3, 100.0,
               extra=_posterior(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 1
        assert "posterior block degraded" in capsys.readouterr().out

    def test_errored_posterior_block_clean_without_history(
            self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0,
               extra=_posterior(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 0

    def test_posterior_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0,
               extra=_posterior(draws=9500.0, logprob=12000.0))
        assert main(["--dir", d]) == 0
        assert "posterior: 9500.0 draws/s" in capsys.readouterr().out


def _predict(pps=250000.0, hit=1.0, p50=1.2, p99=2.5, windows=48,
             error=None):
    block = {"windows": windows, "predicts_per_s": pps,
             "cache_hit_rate": hit, "p50_ms": p50, "p99_ms": p99,
             "steady_state_compiles": 0}
    if error is not None:
        block = {"windows": None, "predicts_per_s": None,
                 "cache_hit_rate": None, "p50_ms": None, "p99_ms": None,
                 "steady_state_compiles": None, "error": error}
    return {"predict": block}


class TestPredictSeries:
    """The bench's predict{} block (round 19+): warm-served epoch
    throughput gates drops, the predict door's p99 gates rises, the
    steady-state cache-hit rate gates drops, and an errored block
    after measured rounds fails."""

    def test_predict_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 19, 100.0,
                    extra=_predict(pps=250000.0, hit=1.0, p99=2.5,
                                   windows=48))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.predict_predicts_per_s == 250000.0
        assert r.predict_cache_hit_rate == 1.0
        assert r.predict_p99_ms == 2.5
        assert r.predict_windows == 48
        assert r.predict_steady_compiles == 0
        doc = build_history([r])
        assert doc["runs"][0]["predict_predicts_per_s"] == 250000.0

    def test_predicts_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([250000.0, 260000.0, 245000.0], start=1):
            _bench(d, i, 100.0, extra=_predict(pps=v))
        _bench(d, 4, 100.0, extra=_predict(pps=100000.0))  # ~60% drop
        assert main(["--check", "--dir", d]) == 1
        assert "predict_predicts_per_s" in capsys.readouterr().out

    def test_p99_rise_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_predict(p99=2.5))
        _bench(d, 4, 100.0, extra=_predict(p99=6.0))  # >2x the tail
        assert main(["--check", "--dir", d]) == 1
        assert "predict_p99_ms" in capsys.readouterr().out

    def test_hit_rate_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_predict(hit=1.0))
        _bench(d, 4, 100.0, extra=_predict(hit=0.6))  # cache went cold
        assert main(["--check", "--dir", d]) == 1
        assert "predict_cache_hit_rate" in capsys.readouterr().out

    def test_small_predict_changes_pass(self, tmp_path):
        d = str(tmp_path)
        for i, (v, p) in enumerate([(250000.0, 2.5), (258000.0, 2.6),
                                    (246000.0, 2.4)], start=1):
            _bench(d, i, 100.0, extra=_predict(pps=v, p99=p))
        _bench(d, 4, 100.0, extra=_predict(pps=242000.0, p99=2.7))
        assert main(["--check", "--dir", d]) == 0

    def test_errored_predict_block_fails_when_history_had_it(
            self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_predict())
        _bench(d, 3, 100.0,
               extra=_predict(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 1
        assert "predict block degraded" in capsys.readouterr().out

    def test_errored_predict_block_clean_without_history(
            self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0,
               extra=_predict(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 0

    def test_predict_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0, extra=_predict(pps=250000.0, windows=48))
        assert main(["--dir", d]) == 0
        assert "predict: 250000.0 epochs/s (48 windows)" \
            in capsys.readouterr().out


def _streaming(ups=180.0, p50=5.5, p99=6.5, speedup=45.0, error=None):
    block = {"appends": 8, "update_p50_ms": p50, "update_p99_ms": p99,
             "updates_per_s": ups, "refit_p50_ms": p50 * speedup,
             "speedup_vs_refit": speedup, "steady_state_compiles": 0}
    if error is not None:
        block = {"appends": None, "update_p50_ms": None,
                 "update_p99_ms": None, "updates_per_s": None,
                 "refit_p50_ms": None, "speedup_vs_refit": None,
                 "steady_state_compiles": None, "error": error}
    return {"streaming": block}


class TestStreamingSeries:
    """The bench's streaming{} block (round 15+): update throughput
    gates drops, the update door's p99 gates rises, the speedup over
    the warm full-refit path gates drops, and an errored block after
    measured rounds fails."""

    def test_streaming_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 15, 100.0,
                    extra=_streaming(ups=189.3, p50=5.2, p99=5.8,
                                     speedup=47.8))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.streaming_updates_per_s == 189.3
        assert r.streaming_update_p50_ms == 5.2
        assert r.streaming_update_p99_ms == 5.8
        assert r.streaming_speedup_vs_refit == 47.8
        assert r.streaming_steady_compiles == 0
        doc = build_history([r])
        assert doc["runs"][0]["streaming_updates_per_s"] == 189.3

    def test_updates_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([180.0, 195.0, 185.0], start=1):
            _bench(d, i, 100.0, extra=_streaming(ups=v))
        _bench(d, 4, 100.0, extra=_streaming(ups=70.0))  # ~62% drop
        assert main(["--check", "--dir", d]) == 1
        assert "streaming_updates_per_s" in capsys.readouterr().out

    def test_p99_rise_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_streaming(p99=6.0))
        _bench(d, 4, 100.0, extra=_streaming(p99=14.0))  # >2x the tail
        assert main(["--check", "--dir", d]) == 1
        assert "streaming_update_p99_ms" in capsys.readouterr().out

    def test_speedup_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_streaming(speedup=45.0))
        # the rank-k win eroding back toward refit cost is the
        # structural regression this series exists to catch
        _bench(d, 4, 100.0, extra=_streaming(speedup=8.0))
        assert main(["--check", "--dir", d]) == 1
        assert "streaming_speedup_vs_refit" in capsys.readouterr().out

    def test_small_streaming_changes_pass(self, tmp_path):
        d = str(tmp_path)
        for i, (v, p) in enumerate([(180.0, 6.0), (195.0, 6.2),
                                    (185.0, 5.9)], start=1):
            _bench(d, i, 100.0, extra=_streaming(ups=v, p99=p))
        _bench(d, 4, 100.0, extra=_streaming(ups=176.0, p99=6.4))
        assert main(["--check", "--dir", d]) == 0

    def test_errored_streaming_block_fails_when_history_had_it(
            self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_streaming())
        _bench(d, 3, 100.0,
               extra=_streaming(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 1
        assert "streaming block degraded" in capsys.readouterr().out

    def test_errored_streaming_block_clean_without_history(
            self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0,
               extra=_streaming(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 0

    def test_malformed_streaming_types_ignored(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 15, 100.0,
                    extra={"streaming": {"updates_per_s": "fast",
                                         "update_p99_ms": True,
                                         "steady_state_compiles": "0"}})
        r = ingest_file(fn, errors)
        assert not errors
        assert r.streaming_updates_per_s is None
        assert r.streaming_update_p99_ms is None
        assert r.streaming_steady_compiles is None

    def test_streaming_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0,
               extra=_streaming(ups=189.3, p50=5.2, speedup=47.8))
        assert main(["--dir", d]) == 0
        assert "streaming: 189.3 updates/s" in capsys.readouterr().out


def _slo(overhead=0.01, fitc=1.0, postc=1.0, burn=0.0, pm=1,
         error=None):
    block = {"untraced_fits_per_s": 1800.0,
             "traced_fits_per_s": 1800.0 * (1.0 - overhead),
             "trace_overhead_frac": overhead,
             "fit_compliance": fitc, "posterior_compliance": postc,
             "worst_burn_rate": burn, "postmortems_emitted": pm,
             "steady_state_compiles": 0}
    if error is not None:
        block = {"untraced_fits_per_s": None, "traced_fits_per_s": None,
                 "trace_overhead_frac": None, "fit_compliance": None,
                 "posterior_compliance": None, "worst_burn_rate": None,
                 "postmortems_emitted": None,
                 "steady_state_compiles": None, "error": error}
    return {"slo": block}


class TestSLOSeries:
    """The bench's slo{} block (round 20+): the tracer's throughput
    tax gates rises (zero-baseline opt-in), per-class deadline
    compliance gates drops, and an errored block after measured
    rounds fails."""

    def test_slo_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 20, 100.0,
                    extra=_slo(overhead=0.012, fitc=0.99, postc=0.97,
                               burn=0.4, pm=2))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.slo_trace_overhead_frac == 0.012
        assert r.slo_fit_compliance == 0.99
        assert r.slo_posterior_compliance == 0.97
        assert r.slo_worst_burn_rate == 0.4
        assert r.slo_postmortems == 2
        assert r.slo_steady_compiles == 0
        doc = build_history([r])
        assert doc["runs"][0]["slo_trace_overhead_frac"] == 0.012

    def test_overhead_rise_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([0.010, 0.012, 0.011], start=1):
            _bench(d, i, 100.0, extra=_slo(overhead=v))
        _bench(d, 4, 100.0, extra=_slo(overhead=0.25))  # >20x the tax
        assert main(["--check", "--dir", d]) == 1
        assert "slo_trace_overhead_frac" in capsys.readouterr().out

    def test_overhead_from_zero_baseline_fails(self, tmp_path, capsys):
        # a free-tracing history (0.0) must gate the FIRST nonzero tax
        # — the zero-baseline opt-in, same contract as load_shed_rate
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_slo(overhead=0.0))
        _bench(d, 4, 100.0, extra=_slo(overhead=0.08))
        assert main(["--check", "--dir", d]) == 1
        assert "slo_trace_overhead_frac" in capsys.readouterr().out

    def test_compliance_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_slo(fitc=1.0))
        # an all-compliant history has zero MAD scatter: a 40% miss
        # past the base threshold is the deadline contract breaking
        _bench(d, 4, 100.0, extra=_slo(fitc=0.6))
        assert main(["--check", "--dir", d]) == 1
        assert "slo_fit_compliance" in capsys.readouterr().out

    def test_small_slo_changes_pass(self, tmp_path):
        d = str(tmp_path)
        for i, (v, c) in enumerate([(0.010, 1.0), (0.013, 0.99),
                                    (0.011, 1.0)], start=1):
            _bench(d, i, 100.0, extra=_slo(overhead=v, fitc=c))
        _bench(d, 4, 100.0, extra=_slo(overhead=0.012, fitc=0.98))
        assert main(["--check", "--dir", d]) == 0

    def test_errored_slo_block_fails_when_history_had_it(
            self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_slo())
        _bench(d, 3, 100.0, extra=_slo(error="RuntimeError: broken"))
        assert main(["--check", "--dir", d]) == 1
        assert "slo block degraded" in capsys.readouterr().out

    def test_errored_slo_block_clean_without_history(self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0, extra=_slo(error="RuntimeError: broken"))
        assert main(["--check", "--dir", d]) == 0

    def test_malformed_slo_types_ignored(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 20, 100.0,
                    extra={"slo": {"trace_overhead_frac": "cheap",
                                   "fit_compliance": True,
                                   "postmortems_emitted": "1",
                                   "steady_state_compiles": None}})
        r = ingest_file(fn, errors)
        assert not errors
        assert r.slo_trace_overhead_frac is None
        assert r.slo_fit_compliance is None
        assert r.slo_postmortems is None
        assert r.slo_steady_compiles is None

    def test_slo_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0, extra=_slo(overhead=0.012, fitc=0.99))
        assert main(["--dir", d]) == 0
        assert "slo: trace_overhead=0.012" in capsys.readouterr().out


def _precision(mixed=50.0, f64=50.0, rel=0.0, reduced=0, error=None):
    block = {"segments": {"serve.gram": "f64"}, "reduced_count": reduced,
             "f64_count": 6 - reduced, "mixed_fits_per_s": mixed,
             "f64_fits_per_s": f64,
             "mixed_vs_f64": (mixed / f64) if f64 else None,
             "max_rel_err": rel}
    if error is not None:
        block = {"segments": None, "reduced_count": None,
                 "f64_count": None, "mixed_fits_per_s": None,
                 "f64_fits_per_s": None, "mixed_vs_f64": None,
                 "max_rel_err": None, "error": error}
    return {"precision": block}


def _precision_artifact(path, round_, checks, platform="tpu",
                        error=None):
    doc = {"metric": "tpu_precision", "platform": platform,
           "ok": all(c.get("ok", True) for c in checks.values()),
           "checks": checks}
    if error is not None:
        doc = {"metric": "tpu_precision", "platform": platform,
               "error": error}
    fn = os.path.join(path, f"TPU_PRECISION_r{round_:02d}.json")
    with open(fn, "w") as f:
        json.dump(doc, f)
    return fn


class TestPrecisionSeries:
    """The bench's precision{} block (round 12+): policy-path
    throughput gates drops, and max_rel_err gates rises off a
    zero baseline (the bit-identical default contract)."""

    def test_precision_block_ingested(self, tmp_path):
        errors = []
        fn = _bench(str(tmp_path), 12, 100.0,
                    extra=_precision(mixed=55.0, f64=50.0, rel=1.5e-10,
                                     reduced=2))
        r = ingest_file(fn, errors)
        assert not errors
        assert r.precision_mixed_fits_per_s == 55.0
        assert r.precision_max_rel_err == 1.5e-10
        assert r.precision_reduced_count == 2
        assert r.precision_mixed_vs_f64 == 1.1
        doc = build_history([r])
        assert doc["runs"][0]["precision_mixed_fits_per_s"] == 55.0

    def test_mixed_fits_drop_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        for i, v in enumerate([50.0, 52.0, 49.0], start=1):
            _bench(d, i, 100.0, extra=_precision(mixed=v))
        _bench(d, 4, 100.0, extra=_precision(mixed=25.0))  # 50% drop
        assert main(["--check", "--dir", d]) == 1
        assert "precision_mixed_fits_per_s" in capsys.readouterr().out

    def test_rel_err_rise_off_zero_baseline_fails(self, tmp_path,
                                                  capsys):
        """A bit-identical history (max_rel_err exactly 0.0) gates a
        newly nonzero disagreement — the zero-baseline opt-in, so a
        silently flipped segment cannot slip into a clean history."""
        d = str(tmp_path)
        for i in (1, 2, 3):
            _bench(d, i, 100.0, extra=_precision(rel=0.0))
        _bench(d, 4, 100.0, extra=_precision(rel=2.0e-6))
        assert main(["--check", "--dir", d]) == 1
        assert "precision_max_rel_err" in capsys.readouterr().out

    def test_steady_zero_rel_err_passes(self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2, 3, 4):
            _bench(d, i, 100.0, extra=_precision(rel=0.0))
        assert main(["--check", "--dir", d]) == 0

    def test_errored_precision_block_fails_when_history_had_it(
            self, tmp_path, capsys):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0, extra=_precision())
        _bench(d, 3, 100.0,
               extra=_precision(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 1
        assert "precision block degraded" in capsys.readouterr().out

    def test_errored_precision_block_clean_without_history(
            self, tmp_path):
        d = str(tmp_path)
        for i in (1, 2):
            _bench(d, i, 100.0)
        _bench(d, 3, 100.0,
               extra=_precision(error="UsageError: broken"))
        assert main(["--check", "--dir", d]) == 0

    def test_precision_line_rendered_in_report(self, tmp_path, capsys):
        d = str(tmp_path)
        _bench(d, 1, 100.0,
               extra=_precision(mixed=55.0, f64=50.0, reduced=2))
        assert main(["--dir", d]) == 0
        assert "precision: mixed 55.0 fits/s" in capsys.readouterr().out


class TestPrecisionArtifacts:
    """TPU_PRECISION_r* check-suite gating: each named check's value
    against its committed bound, within the newest artifact."""

    def test_in_bound_checks_pass(self, tmp_path, capsys):
        d = str(tmp_path)
        _precision_artifact(d, 5, {
            "b_frac_cycles": {"value": 5.2e-5, "bound": 1e-4,
                              "ok": True},
            "b_la_chi2_rel": {"value": 4.8e-14, "bound": 1e-9,
                              "ok": True}})
        assert main(["--check", "--dir", d]) == 0
        out = capsys.readouterr().out
        assert "tpu_precision" in out and "b_frac_cycles" in out

    def test_over_bound_check_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        _precision_artifact(d, 6, {
            "b_frac_cycles": {"value": 3.0e-4, "bound": 1e-4,
                              "ok": False}})
        assert main(["--check", "--dir", d]) == 1
        assert "b_frac_cycles" in capsys.readouterr().out

    def test_only_newest_artifact_gates(self, tmp_path):
        """An old over-bound artifact is history, not a verdict — the
        newest round superseded it."""
        d = str(tmp_path)
        _precision_artifact(d, 5, {
            "b_frac_cycles": {"value": 3.0e-4, "bound": 1e-4,
                              "ok": False}})
        _precision_artifact(d, 6, {
            "b_frac_cycles": {"value": 5.0e-5, "bound": 1e-4,
                              "ok": True}})
        assert main(["--check", "--dir", d]) == 0

    def test_errored_artifact_fails_after_measured_history(
            self, tmp_path, capsys):
        d = str(tmp_path)
        _precision_artifact(d, 5, {
            "b_frac_cycles": {"value": 5.0e-5, "bound": 1e-4,
                              "ok": True}})
        _precision_artifact(d, 6, {}, error="tunnel wedged")
        assert main(["--check", "--dir", d]) == 1
        assert "errored/check-less" in capsys.readouterr().out

    def test_errored_artifact_clean_without_history(self, tmp_path):
        d = str(tmp_path)
        _precision_artifact(d, 5, {}, error="tunnel wedged")
        assert main(["--check", "--dir", d]) == 0

    def test_malformed_check_fails(self, tmp_path, capsys):
        d = str(tmp_path)
        _precision_artifact(d, 5, {
            "b_frac_cycles": {"value": "tiny", "bound": 1e-4}})
        assert main(["--check", "--dir", d]) == 1
        assert "malformed" in capsys.readouterr().out

    def test_artifact_never_joins_the_bench_series(self, tmp_path):
        """The value-less precision artifact must not appear as an
        errored bench run (it is its own kind)."""
        errors = []
        fn = _precision_artifact(str(tmp_path), 5, {
            "b_frac_cycles": {"value": 5.0e-5, "bound": 1e-4,
                              "ok": True}})
        r = ingest_file(fn, errors)
        assert not errors
        assert r.kind == "precision"
        assert r.error is None
        assert r.precision_checks is not None

    def test_committed_r05_artifact_ingests_and_gates_clean(self):
        """The repo's own TPU_PRECISION_r05.json: 12 named checks, all
        within their committed bounds."""
        errors = []
        r = ingest_file(os.path.join(REPO, "TPU_PRECISION_r05.json"),
                        errors)
        assert not errors and r is not None
        assert r.kind == "precision" and r.platform == "tpu"
        assert len(r.precision_checks) == 12
        from tools.perfwatch import check_precision_artifacts

        verdicts = check_precision_artifacts([r], threshold=0.30)
        assert len(verdicts) == 12
        assert not any(v.failed for v in verdicts)
