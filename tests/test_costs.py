"""AOT cost attribution under test (pint_tpu/telemetry/costs.py).

The contract tier-1 (CPU) pins: whatever a backend reports — op-level
dict lists, flat dicts, ``None``, exceptions — the cost module produces
a schema-valid profile whose absent numbers are explicit nulls, and it
NEVER raises into the fit path.  Plus the end-to-end wiring: grid_chisq
records the executable handle, full mode streams ``cost_profile``
records the report CLI validates, and the profiling trace summary
degrades gracefully.
"""

import json
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.perfwatch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


@pytest.fixture
def fresh_telemetry():
    from pint_tpu import telemetry
    from pint_tpu.telemetry import metrics, runlog, spans

    telemetry.deactivate()
    metrics.reset_registry()
    spans.clear_finished()
    yield telemetry
    runlog.end_run()
    telemetry.deactivate()
    metrics.reset_registry()
    spans.clear_finished()


def _tiny_gls_fitter(seed=3):
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = ["PSR TSTCOST\n", "RAJ 05:00:00 1\n", "DECJ 15:00:00 1\n",
           "F0 99.123456789 1\n", "F1 -1.1e-14 1\n", "PEPOCH 55500\n",
           "DM 12.5 1\n",
           "EFAC mjd 53000 58000 1.1\n",
           "EQUAD mjd 53000 58000 0.5\n",
           "ECORR mjd 53000 58000 0.8\n",
           "TNRedAmp -13.5\n", "TNRedGam 3.5\n", "TNRedC 10\n",
           "UNITS TDB\n"]
    model = get_model(par)
    rng = np.random.default_rng(seed)
    base = np.linspace(55000, 56000, 20)
    mjds = np.sort(np.concatenate([base, base + 0.5 / 86400.0]))
    toas = make_fake_toas_fromMJDs(mjds, model, error_us=1.0,
                                   add_noise=True, rng=rng)
    return GLSFitter(toas, model)


# ---------------------------------------------------------------------------
# normalization: every backend shape folds into the one schema
# ---------------------------------------------------------------------------

class TestNormalization:
    def test_none_is_all_nulls(self):
        from pint_tpu.telemetry.costs import (normalize_cost_analysis,
                                              normalize_memory_analysis)

        c = normalize_cost_analysis(None)
        assert c["flops"] is None and c["bytes_accessed"] is None
        m = normalize_memory_analysis(None)
        assert m["temp_bytes"] is None and m["argument_bytes"] is None

    def test_dict_and_list_shapes(self):
        from pint_tpu.telemetry.costs import normalize_cost_analysis

        flat = normalize_cost_analysis({"flops": 10.0, "bytes accessed": 4})
        assert flat["flops"] == 10.0 and flat["bytes_accessed"] == 4.0
        # CPU wraps in a list; multiple entries sum
        listed = normalize_cost_analysis([{"flops": 10.0}, {"flops": 5.0}])
        assert listed["flops"] == 15.0
        # per-operand suffixed keys are backend noise, dropped
        noisy = normalize_cost_analysis({"flops": 1.0,
                                         "bytes accessed0{}": 99.0,
                                         "utilization1{}": 3.0})
        assert noisy["flops"] == 1.0 and noisy["bytes_accessed"] is None

    def test_negative_sentinels_are_nulls(self):
        """CPU reports optimal_seconds=-4: costs are nonnegative by
        definition, so sentinels normalize to null, never propagate."""
        from pint_tpu.telemetry.costs import normalize_cost_analysis

        c = normalize_cost_analysis({"optimal_seconds": -4.0, "flops": 2.0})
        assert c["optimal_seconds"] is None and c["flops"] == 2.0

    def test_garbage_values_skipped(self):
        from pint_tpu.telemetry.costs import normalize_cost_analysis

        c = normalize_cost_analysis([{"flops": "not-a-number"}, 42, None])
        assert c["flops"] is None

    def test_profile_schema_always_complete(self):
        """to_dict() carries EVERY numeric field — the schema the runlog
        validator and the bench cost{} block rely on — even for a fully
        degraded profile."""
        from pint_tpu.telemetry.costs import NUMERIC_FIELDS, CostProfile

        d = CostProfile(name="empty", error="synthetic").to_dict()
        for f in NUMERIC_FIELDS:
            assert f in d and d[f] is None
        assert d["peak_bytes"] is None
        json.dumps(d)  # strict-JSON serializable

    def test_peak_bytes_partial_sum(self):
        from pint_tpu.telemetry.costs import CostProfile

        p = CostProfile(name="x", argument_bytes=10, temp_bytes=5)
        assert p.peak_bytes == 15  # output_bytes None: summed as absent


# ---------------------------------------------------------------------------
# analysis entry points degrade, never raise
# ---------------------------------------------------------------------------

class TestAnalyze:
    def test_analyze_jitted_on_cpu(self):
        import jax
        import jax.numpy as jnp

        from pint_tpu.telemetry import costs

        f = jax.jit(lambda x, y: (x @ y).sum())
        x = jnp.ones((16, 16))
        prof = costs.analyze_jitted(f, x, x, name="matmul")
        d = prof.to_dict()
        assert d["name"] == "matmul"
        assert prof.error is None
        # CPU reports flops/bytes; memory analysis reports buffer sizes
        assert d["flops"] > 0 and d["bytes_accessed"] > 0
        assert d["argument_bytes"] == 2 * 16 * 16 * 8

    def test_analyze_unjitted_degrades(self):
        from pint_tpu.telemetry import costs

        prof = costs.analyze_jitted(lambda z: z, 1.0, name="plain")
        assert prof.error is not None
        assert prof.to_dict()["flops"] is None

    def test_analyze_compiled_refusals_degrade(self):
        """A backend whose cost_analysis/memory_analysis RAISE still
        yields a schema-valid profile carrying the error string."""
        from pint_tpu.telemetry import costs

        class Hostile:
            def cost_analysis(self):
                raise RuntimeError("backend says no")

            def memory_analysis(self):
                raise NotImplementedError("nor this")

        prof = costs.analyze_compiled(Hostile(), name="hostile")
        assert "backend says no" in prof.error
        assert "nor this" in prof.error
        d = prof.to_dict()
        assert d["flops"] is None and d["temp_bytes"] is None
        json.dumps(d)

    def test_profile_grid_before_any_grid(self):
        from pint_tpu.telemetry import costs

        class Bare:
            pass

        prof = costs.profile_grid(Bare())
        assert prof.error and "grid_chisq" in prof.error

    def test_analysis_compile_not_counted(self, fresh_telemetry):
        """The analysis' own deliberate lower/compile must not skew the
        workload compile counters it exists to contextualize — AOT
        compile runs with the jaxevents accounting paused."""
        import jax
        import jax.numpy as jnp

        from pint_tpu.telemetry import costs, jaxevents

        fresh_telemetry.activate("basic")
        f = jax.jit(lambda x: jnp.cos(x).sum() * 3)
        x = jnp.arange(33.0)
        with jaxevents.watch() as w:
            prof = costs.analyze_jitted(f, x, name="uncounted")
        assert prof.error is None and prof.flops
        assert w.delta.compiles == 0, (
            "the AOT analysis compile leaked into the workload counters")
        # and the accounting itself is restored afterwards
        with jaxevents.watch() as w2:
            jax.jit(lambda x: x - 5)(x)
        assert w2.delta.compiles >= 1

    def test_cache_hit_restamps_name(self, fresh_telemetry):
        """A memoized analysis returned under a different caller label
        must carry THAT label (the MULTICHIP artifact's
        grid.chunk.sharded line, not the first caller's name)."""
        import jax
        import jax.numpy as jnp

        from pint_tpu.telemetry import costs

        f = jax.jit(lambda x: x * 2 + 1)
        x = jnp.arange(17.0)
        p1 = costs.analyze_jitted(f, x, name="first")
        p2 = costs.analyze_jitted(f, x, name="second")
        assert p1.name == "first" and p2.name == "second"
        assert p2.flops == p1.flops

    def test_record_off_mode_is_noop(self, fresh_telemetry):
        from pint_tpu.telemetry import costs, spans

        prof = costs.CostProfile(name="off", flops=1.0)
        assert costs.record_cost_profile(prof) is prof
        assert spans.finished_roots() == []


# ---------------------------------------------------------------------------
# end to end: fit/grid executables on the CPU tier-1 backend
# ---------------------------------------------------------------------------

class TestWorkloadProfiles:
    def test_grid_fit_gls_profiles(self, fresh_telemetry, tmp_path):
        """The full path: grid_chisq records the executable, the three
        workload profilers produce schema-valid profiles, full mode
        streams a validated cost_profile record with cost.* span attrs."""
        from tools.telemetry_report import main as report_main

        from pint_tpu.grid import grid_chisq
        from pint_tpu.telemetry import costs, runlog

        f = _tiny_gls_fitter()
        fresh_telemetry.activate("full")
        run_dir = str(tmp_path / "run")
        runlog.start_run(run_dir, name="cost-e2e", probe_device=False)
        f.fit_toas(maxiter=1)
        g0 = np.linspace(f.model.F0.value - 1e-9, f.model.F0.value + 1e-9, 3)
        g1 = np.linspace(f.model.F1.value - 1e-17,
                         f.model.F1.value + 1e-17, 3)
        chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=1)
        assert np.all(np.isfinite(chi2))

        assert hasattr(f, "last_grid_executable")
        workload = costs.profile_workload(f)
        assert set(workload) == {"fit.eval", "fit.jac", "gls.solve",
                                 "grid.chunk"}
        for name, d in workload.items():
            assert d["name"] == name
            json.dumps(d)
        # on the CPU backend these must be real numbers, not nulls
        assert workload["grid.chunk"]["flops"] > 0
        assert workload["gls.solve"]["flops"] > 0
        assert workload["fit.eval"]["bytes_accessed"] > 0

        runlog.end_run()
        records = [json.loads(ln) for ln in
                   open(os.path.join(run_dir, "events.jsonl"))]
        cps = [r["cost_profile"] for r in records
               if r["type"] == "cost_profile"]
        assert any(c["name"] == "grid.chunk" and c["flops"] for c in cps)
        grid_spans = [r["span"] for r in records if r["type"] == "span"
                      and r["span"]["name"] == "grid_chisq"]
        assert grid_spans and any(k.startswith("cost.")
                                  for k in grid_spans[0].get("attrs", {}))
        assert report_main(["--check", run_dir]) == 0

    def test_check_rejects_malformed_cost_profile(self, fresh_telemetry,
                                                  tmp_path, capsys):
        """The report CLI's --check enforces the cost_profile schema:
        a record missing the numeric fields (producer drift) fails."""
        from tools.telemetry_report import main as report_main

        from pint_tpu.telemetry import runlog

        fresh_telemetry.activate("full")
        run_dir = str(tmp_path / "bad")
        run = runlog.start_run(run_dir, name="bad", probe_device=False)
        run.record_cost_profile({"name": "drifted"})  # no schema, no fields
        runlog.end_run()
        assert report_main(["--check", run_dir]) == 1
        err = capsys.readouterr().err
        assert "cost_profile" in err and "missing field" in err

    def test_repeat_grid_reuses_cached_profile(self, fresh_telemetry):
        """Full-mode cost analysis runs ONCE per executable: a repeat
        sweep must reuse the model-cached profile, not re-lower."""
        from pint_tpu.grid import grid_chisq
        from pint_tpu.telemetry import costs

        f = _tiny_gls_fitter()
        fresh_telemetry.activate("full")
        f.fit_toas(maxiter=1)
        g0 = np.linspace(f.model.F0.value - 1e-9, f.model.F0.value + 1e-9, 3)
        g1 = np.linspace(f.model.F1.value - 1e-17,
                         f.model.F1.value + 1e-17, 3)
        calls = []
        orig = costs.analyze_jitted

        def counting(*a, **kw):
            calls.append(kw.get("name"))
            return orig(*a, **kw)

        costs.analyze_jitted = counting
        try:
            grid_chisq(f, ("F0", "F1"), (g0, g1), niter=1)
            grid_chisq(f, ("F0", "F1"), (g0, g1), niter=1)
        finally:
            costs.analyze_jitted = orig
        assert calls.count("grid.chunk") == 1

    def test_cost_never_blocks_fit_path(self, fresh_telemetry,
                                        monkeypatch):
        """A hostile analysis path must not take grid_chisq down: the
        full-mode attachment swallows even an unexpectedly-raising
        analyze and the sweep's chi2 surface is unaffected."""
        from pint_tpu.grid import grid_chisq
        from pint_tpu.telemetry import costs

        f = _tiny_gls_fitter()
        fresh_telemetry.activate("full")
        f.fit_toas(maxiter=1)

        def explode(*a, **kw):
            raise RuntimeError("analysis backend down")

        monkeypatch.setattr(costs, "analyze_jitted", explode)
        g0 = np.linspace(f.model.F0.value - 1e-9, f.model.F0.value + 1e-9, 2)
        g1 = np.linspace(f.model.F1.value - 1e-17,
                         f.model.F1.value + 1e-17, 2)
        chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=1)
        assert np.all(np.isfinite(np.asarray(chi2)))
        # and analyze_jitted's own contract: lower/compile failures are
        # swallowed into an errored profile, never raised
        monkeypatch.undo()
        prof = costs.analyze_jitted(object(), name="junk")
        assert prof.error is not None


# ---------------------------------------------------------------------------
# trace summary (profiling.py)
# ---------------------------------------------------------------------------

class TestTraceSummary:
    def test_summarize_missing_dir_degrades(self, tmp_path):
        from pint_tpu.profiling import summarize_trace

        rep = summarize_trace(str(tmp_path / "nowhere"))
        assert rep.error and "no .xplane.pb" in rep.error
        assert rep.ops == {}
        assert "nowhere" in rep.table()

    @pytest.mark.slow
    def test_device_trace_summarizes_ops(self, tmp_path):
        import jax
        import jax.numpy as jnp

        from pint_tpu.profiling import device_trace

        with device_trace(str(tmp_path)) as rep:
            g = jax.jit(lambda x: jnp.sin(x @ x).sum())
            g(jnp.ones((64, 64))).block_until_ready()
        if rep.error:  # parser genuinely unavailable: listing fallback
            assert rep.files or "no .xplane.pb" in rep.error
        else:
            assert rep.ops
            top = rep.top(5)
            assert top and top[0][1] >= top[-1][1]
            d = rep.to_dict()
            json.dumps(d)
            assert d["top_ops"]

    def test_self_time_nesting(self):
        """A parent event's self-time excludes its nested child."""
        from pint_tpu.profiling import TraceReport

        class Meta:
            def __init__(self, name):
                self.name = name

        class Ev:
            def __init__(self, off, dur, mid):
                self.offset_ps = off
                self.duration_ps = dur
                self.metadata_id = mid

        class Line:
            name = "ops"
            events = [Ev(0, 100, 1), Ev(10, 40, 2)]

        class Plane:
            event_metadata = {1: Meta("parent"), 2: Meta("child")}

        rep = TraceReport("unused")
        ops = {}
        rep._accumulate_line(Plane(), Line(), ops)
        assert ops["parent"] == pytest.approx(60e-12)
        assert ops["child"] == pytest.approx(40e-12)

    def test_self_time_child_shares_parent_start(self):
        """A child starting at the SAME ps as its parent (a region event
        and its first sub-event) must still nest under it — a plain
        (start, end) sort would process the shorter child first and
        drive its self-time negative."""
        from pint_tpu.profiling import TraceReport

        class Meta:
            def __init__(self, name):
                self.name = name

        class Ev:
            def __init__(self, off, dur, mid):
                self.offset_ps = off
                self.duration_ps = dur
                self.metadata_id = mid

        class Line:
            name = "ops"
            events = [Ev(0, 5, 2), Ev(0, 10, 1)]  # child listed first

        class Plane:
            event_metadata = {1: Meta("parent"), 2: Meta("child")}

        rep = TraceReport("unused")
        ops = {}
        rep._accumulate_line(Plane(), Line(), ops)
        assert ops["parent"] == pytest.approx(5e-12)
        assert ops["child"] == pytest.approx(5e-12)
        assert all(v >= 0 for v in ops.values())
