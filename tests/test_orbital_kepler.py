"""Keplerian orbit utilities (reference ``orbital/kepler.py``): forward
propagation with jacfwd partials, inverse (state -> elements) round trips,
physics invariants, and numeric-difference checks on every jacobian."""

import numpy as np
import pytest

from pint_tpu.orbital.kepler import (G, Kepler2DParameters,
                                     Kepler3DParameters,
                                     KeplerTwoBodyParameters,
                                     btx_parameters, eccentric_from_mean,
                                     inverse_kepler_2d, inverse_kepler_3d,
                                     inverse_kepler_two_body, kepler_2d,
                                     kepler_3d, kepler_two_body, mass,
                                     mass_partials, true_from_eccentric)

P2 = Kepler2DParameters(a=8.0, pb=12.3, eps1=0.02, eps2=0.05, t0=1.5)
P3 = Kepler3DParameters(a=8.0, pb=12.3, eps1=0.02, eps2=0.05,
                        i=0.7, lan=1.1, t0=1.5)
PT = KeplerTwoBodyParameters(a=8.0, pb=12.3, eps1=0.02, eps2=0.05, i=0.7,
                             lan=1.1, q=0.2, x_cm=3.0, y_cm=-2.0, z_cm=1.0,
                             vx_cm=0.01, vy_cm=-0.02, vz_cm=0.003, tasc=1.5)


def _numeric_jac(fn, vec, eps=1e-6):
    vec = np.asarray(vec, dtype=np.float64)
    cols = []
    for i in range(len(vec)):
        hi = vec.copy()
        lo = vec.copy()
        h = eps * max(abs(vec[i]), 1.0)
        hi[i] += h
        lo[i] -= h
        cols.append((fn(hi) - fn(lo)) / (2 * h))
    return np.stack(cols, axis=-1)


class TestAnomalies:
    def test_true_from_eccentric_derivs(self):
        e, E = 0.3, 1.2
        nu, de, dE = true_from_eccentric(e, E)
        h = 1e-7
        assert de == pytest.approx(
            (true_from_eccentric(e + h, E)[0]
             - true_from_eccentric(e - h, E)[0]) / (2 * h), rel=1e-5)
        assert dE == pytest.approx(
            (true_from_eccentric(e, E + h)[0]
             - true_from_eccentric(e, E - h)[0]) / (2 * h), rel=1e-5)

    def test_eccentric_from_mean(self):
        e, M = 0.4, 2.1
        E, (de, dM) = eccentric_from_mean(e, M)
        assert E - e * np.sin(E) == pytest.approx(M, abs=1e-12)
        h = 1e-7
        assert de == pytest.approx(
            (eccentric_from_mean(e + h, M)[0]
             - eccentric_from_mean(e - h, M)[0]) / (2 * h), rel=1e-5)
        assert dM == pytest.approx(
            (eccentric_from_mean(e, M + h)[0]
             - eccentric_from_mean(e, M - h)[0]) / (2 * h), rel=1e-5)

    def test_mass_partials(self):
        m, dm = mass_partials(8.0, 12.3 * 86400.0)
        h = 1e-5
        assert dm[0] == pytest.approx(
            (mass(8 + h, 12.3 * 86400) - mass(8 - h, 12.3 * 86400)) / (2 * h),
            rel=1e-6)

    def test_btx_parameters(self):
        asini, pb, e, om, t0 = btx_parameters(8.0, 12.3, 0.02, 0.05, 100.0)
        assert e == pytest.approx(np.hypot(0.02, 0.05))
        assert om == pytest.approx(np.arctan2(0.02, 0.05))
        # defining identity: propagating mean anomaly from periastron t0 to
        # tasc reproduces the anomaly of the ascending node (nu = -om)
        M_at_tasc = 2 * np.pi * (100.0 - t0) / pb
        E0, _ = eccentric_from_mean(e, M_at_tasc)
        nu0, _, _ = true_from_eccentric(e, E0)
        wrapped = np.remainder(nu0 + om + np.pi, 2 * np.pi) - np.pi
        assert wrapped == pytest.approx(0.0, abs=1e-10)


class TestKepler2D:
    def test_energy_and_momentum(self):
        """Specific orbital energy and angular momentum are conserved and
        match -mu/2a and sqrt(mu p)."""
        m = mass(P2.a, P2.pb)
        mu = G * m
        for t in (2.0, 5.5, 11.9):
            xv, _ = kepler_2d(P2, t)
            r = np.hypot(xv[0], xv[1])
            v2 = xv[2] ** 2 + xv[3] ** 2
            energy = v2 / 2 - mu / r
            assert energy == pytest.approx(-mu / (2 * P2.a), rel=1e-10)
            h = xv[0] * xv[3] - xv[1] * xv[2]
            e = np.hypot(P2.eps1, P2.eps2)
            assert abs(h) == pytest.approx(np.sqrt(mu * P2.a * (1 - e**2)),
                                           rel=1e-10)

    def test_at_t0_on_ascending_node(self):
        """t0 is the ascending-node time: the particle sits on the +x axis."""
        xv, _ = kepler_2d(P2, P2.t0)
        assert xv[1] == pytest.approx(0.0, abs=1e-10)
        assert xv[0] > 0

    def test_partials_match_numeric(self):
        from pint_tpu.orbital.kepler import _kepler_2d_core

        vec = [P2.a, P2.pb, P2.eps1, P2.eps2, P2.t0, 4.2]
        xv, jac = kepler_2d(P2, 4.2)
        njac = _numeric_jac(lambda v: np.asarray(_kepler_2d_core(v)), vec)
        np.testing.assert_allclose(jac, njac, rtol=2e-5, atol=1e-8)

    def test_roundtrip_inverse(self):
        m = mass(P2.a, P2.pb)
        t = 4.2
        xv, _ = kepler_2d(P2, t)
        p = inverse_kepler_2d(xv, m, t)
        assert p.a == pytest.approx(P2.a, rel=1e-9)
        assert p.pb == pytest.approx(P2.pb, rel=1e-9)
        assert p.eps1 == pytest.approx(P2.eps1, abs=1e-9)
        assert p.eps2 == pytest.approx(P2.eps2, abs=1e-9)
        assert (p.t0 - P2.t0) % P2.pb == pytest.approx(0.0, abs=1e-7) or \
            (p.t0 - P2.t0) % P2.pb == pytest.approx(P2.pb, abs=1e-7)

    def test_circular_orbit_no_nans(self):
        p = Kepler2DParameters(a=8.0, pb=12.3, eps1=0.0, eps2=0.0, t0=0.0)
        xv, jac = kepler_2d(p, 3.0)
        assert np.all(np.isfinite(xv)) and np.all(np.isfinite(jac))
        assert np.hypot(xv[0], xv[1]) == pytest.approx(8.0, rel=1e-9)


class TestKepler3D:
    def test_reduces_to_2d_at_zero_angles(self):
        p3 = Kepler3DParameters(a=P2.a, pb=P2.pb, eps1=P2.eps1,
                                eps2=P2.eps2, i=0.0, lan=0.0, t0=P2.t0)
        xyv, _ = kepler_3d(p3, 4.2)
        xv, _ = kepler_2d(P2, 4.2)
        np.testing.assert_allclose(xyv[[0, 1, 3, 4]], xv, rtol=1e-12)
        assert xyv[2] == xyv[5] == 0.0

    def test_partials_match_numeric(self):
        from pint_tpu.orbital.kepler import _kepler_3d_core

        vec = [P3.a, P3.pb, P3.eps1, P3.eps2, P3.i, P3.lan, P3.t0, 4.2]
        xyv, jac = kepler_3d(P3, 4.2)
        njac = _numeric_jac(lambda v: np.asarray(_kepler_3d_core(v)), vec)
        np.testing.assert_allclose(jac, njac, rtol=2e-5, atol=1e-8)

    def test_roundtrip_inverse(self):
        m = mass(P3.a, P3.pb)
        t = 4.2
        xyv, _ = kepler_3d(P3, t)
        p = inverse_kepler_3d(xyv, m, t)
        assert p.a == pytest.approx(P3.a, rel=1e-9)
        assert p.i == pytest.approx(P3.i, rel=1e-9)
        assert p.lan == pytest.approx(P3.lan, rel=1e-9)
        assert p.eps1 == pytest.approx(P3.eps1, abs=1e-9)


class TestKeplerTwoBody:
    def test_center_of_mass_and_masses(self):
        state, _ = kepler_two_body(PT, 4.2)
        xv_p, m_p = state[:6], state[6]
        xv_c, m_c = state[7:13], state[13]
        assert m_c / m_p == pytest.approx(PT.q, rel=1e-12)
        cm = (m_p * xv_p[:3] + m_c * xv_c[:3]) / (m_p + m_c)
        np.testing.assert_allclose(cm, [PT.x_cm, PT.y_cm, PT.z_cm],
                                   rtol=1e-10, atol=1e-10)

    def test_partials_match_numeric(self):
        from pint_tpu.orbital.kepler import _kepler_two_body_core

        vec = [PT.a, PT.pb, PT.eps1, PT.eps2, PT.i, PT.lan, PT.q,
               PT.x_cm, PT.y_cm, PT.z_cm, PT.vx_cm, PT.vy_cm, PT.vz_cm,
               PT.tasc, 4.2]
        state, jac = kepler_two_body(PT, 4.2)
        njac = _numeric_jac(lambda v: np.asarray(_kepler_two_body_core(v)),
                            vec)
        np.testing.assert_allclose(jac, njac, rtol=5e-5, atol=1e-7)

    def test_roundtrip_inverse(self):
        t = 4.2
        state, _ = kepler_two_body(PT, t)
        p = inverse_kepler_two_body(state, t)
        for name in ("a", "pb", "eps1", "eps2", "i", "lan", "q",
                     "x_cm", "y_cm", "z_cm", "vx_cm", "vy_cm", "vz_cm"):
            assert getattr(p, name) == pytest.approx(
                getattr(PT, name), rel=1e-7, abs=1e-9), name


class TestSolverRobustness:
    def test_high_eccentricity_converges(self):
        """Regression: step-clamped Newton handles e -> 1 where raw Newton
        overshoots catastrophically."""
        for e in (0.99, 0.999, 0.9999):
            for M in np.linspace(0.01, 2 * np.pi - 0.01, 50):
                E, _ = eccentric_from_mean(e, M)
                assert abs(E - e * np.sin(E) - M) < 1e-10
        p = Kepler2DParameters(a=8.0, pb=12.3, eps1=0.0, eps2=0.9999, t0=0.0)
        xv, jac = kepler_2d(p, 0.11)
        assert np.all(np.isfinite(xv)) and np.all(np.isfinite(jac))

    def test_random_models_recentered(self):
        """Each overlay curve's mean over the fitted span sits at rs_mean."""
        import jax
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.models import get_model
        from pint_tpu.random_models import random_models
        from pint_tpu.simulation import make_fake_toas_uniform

        m = get_model(["PSR RM\n", "RAJ 03:00:00\n", "DECJ 3:00:00\n",
                       "F0 99.0 1\n", "F1 -1e-14 1\n", "PEPOCH 55100\n",
                       "DM 10\n", "UNITS TDB\n"])
        t = make_fake_toas_uniform(55000, 55200, 25, m, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(2))
        f = WLSFitter(t, m)
        f.fit_toas(maxiter=2)
        fake, rss = random_models(f, rs_mean=1e-5, iter=4, npoints=60,
                                  rng=np.random.default_rng(5))
        assert len(rss) == 4
        # within the fitted span the curves center near rs_mean
        mjf = np.asarray(fake.get_mjds(), dtype=float)
        inspan = (mjf >= 55000) & (mjf <= 55200)
        for rs in rss:
            assert abs(np.mean(rs[inspan]) - 1e-5) < 5e-4
