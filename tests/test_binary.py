"""Binary-model tests: engine physics checks, cross-model consistency,
autodiff-vs-finite-difference derivatives, end-to-end fits on simulated data
(the reference's strategy: tests/test_model_derivatives.py + simulation
fixtures, SURVEY §4)."""

import copy

import numpy as np
import pytest

DD_PAR = "/root/reference/tests/datafile/B1855+09_NANOGrav_dfg+12_modified_DD.par"
ELL1_PAR = "/root/reference/tests/datafile/J0023+0923_ell1_simple.par"


def _fake(model, n=50, seed=1, start=53000, end=54000):
    from pint_tpu.simulation import make_fake_toas_uniform

    return make_fake_toas_uniform(start, end, n, model, error_us=1.0,
                                  add_noise=True, rng=np.random.default_rng(seed))


class TestEngines:
    def test_kepler_solver(self):
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import solve_kepler

        M = jnp.linspace(0, 2 * np.pi, 100)
        for e in (0.0, 0.1, 0.6, 0.9):
            E = solve_kepler(M, e)
            assert np.allclose(np.asarray(E - e * jnp.sin(E)), np.asarray(M),
                               atol=1e-13)

    def test_bt_circular_limit(self):
        """At e=0, BT Roemer delay = x sin(M + om) to first order."""
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import bt_delay

        pv = {"PB": 10.0, "A1": 5.0, "ECC": 0.0, "OM": 30.0, "GAMMA": 0.0}
        tt0 = jnp.linspace(0, 86400.0 * 30, 200)
        d = np.asarray(bt_delay(pv, tt0))
        M = 2 * np.pi * np.asarray(tt0) / (10 * 86400.0)
        om = np.radians(30.0)
        expect = 5.0 * np.sin(M + om)
        # inverse-timing correction is O(x * 2pi x/PB) ~ 2e-4 s
        assert np.allclose(d, expect, atol=3e-4)

    def test_dd_matches_bt_at_low_order(self):
        """DD and BT agree for a mildly relativistic orbit to O((v/c)^2)."""
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import bt_delay, dd_delay

        pv = {"PB": 8.0, "A1": 12.0, "ECC": 0.3, "OM": 120.0, "GAMMA": 0.0,
              "SINI": 0.0, "M2": 0.0}
        tt0 = jnp.linspace(0, 86400.0 * 40, 400)
        db = np.asarray(bt_delay(pv, tt0))
        dd = np.asarray(dd_delay(pv, tt0))
        assert np.allclose(db, dd, atol=5e-5)

    def test_ell1_matches_dd_small_ecc(self):
        """ELL1 (3rd-order expansion) matches DD at small eccentricity.

        TASC/T0 relation: T0 = TASC + PB/(2pi) * atan2(eps1, eps2).
        """
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import dd_delay, ell1_delay

        pb, a1, ecc, om_deg = 5.0, 8.0, 3e-4, 70.0
        om = np.radians(om_deg)
        eps1, eps2 = ecc * np.sin(om), ecc * np.cos(om)
        pv_ell1 = {"PB": pb, "A1": a1, "EPS1": eps1, "EPS2": eps2,
                   "SINI": 0.6, "M2": 0.3}
        pv_dd = {"PB": pb, "A1": a1, "ECC": ecc, "OM": om_deg,
                 "SINI": 0.6, "M2": 0.3, "GAMMA": 0.0}
        ttasc = jnp.linspace(0, 86400.0 * 20, 300)
        # DD time argument is relative to T0 = TASC + PB/(2pi)*om
        dt0 = pb * 86400.0 / (2 * np.pi) * om
        d_ell1 = np.asarray(ell1_delay(pv_ell1, ttasc))
        d_dd = np.asarray(dd_delay(pv_dd, ttasc - dt0))
        # ELL1 drops the constant (3/2) x eps1 Roemer term (absorbed into
        # TASC/phase; Lange et al. 2001) — remove it before comparing.  The
        # T0<->TASC epoch relation is itself O(e)-accurate, so residual
        # disagreement is bounded by x e^2.
        assert np.allclose(d_ell1 - 1.5 * a1 * eps1, d_dd, atol=a1 * ecc**2)

    def test_dds_equals_dd(self):
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import dd_delay, dds_delay

        sini = 0.95
        shapmax = -np.log(1 - sini)
        base = {"PB": 8.0, "A1": 12.0, "ECC": 0.3, "OM": 120.0,
                "GAMMA": 0.002, "M2": 0.4}
        tt0 = jnp.linspace(0, 86400.0 * 40, 300)
        d1 = np.asarray(dd_delay({**base, "SINI": sini}, tt0))
        d2 = np.asarray(dds_delay({**base, "SHAPMAX": shapmax}, tt0))
        assert np.allclose(d1, d2, atol=1e-14)

    def test_ddh_equals_dd(self):
        """H3/STIGMA <-> M2/SINI mapping (Freire & Wex 2010 eq 20, 22)."""
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import TSUN, dd_delay, ddh_delay

        sini = 0.9
        cosi = np.sqrt(1 - sini**2)
        stig = sini / (1 + cosi)
        m2 = 0.35
        h3 = TSUN * m2 * stig**3
        base = {"PB": 8.0, "A1": 12.0, "ECC": 0.1, "OM": 45.0, "GAMMA": 0.0}
        tt0 = jnp.linspace(0, 86400.0 * 40, 300)
        d1 = np.asarray(dd_delay({**base, "SINI": sini, "M2": m2}, tt0))
        d2 = np.asarray(ddh_delay({**base, "H3": h3, "STIGMA": stig}, tt0))
        assert np.allclose(d1, d2, atol=1e-13)

    def test_ell1h_harmonics_match_exact_form(self):
        """The truncated harmonic sum converges to the exact H3/STIGMA
        bracket (Freire & Wex 2010 eq 19 vs 28) — catches sign/parity
        errors in the Fourier coefficients."""
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import _h3_fourier_harms

        phi = jnp.linspace(0, 2 * np.pi, 97)
        stig = 0.1
        exact = (jnp.log(1 + stig**2 - 2 * stig * jnp.sin(phi))
                 + 2 * stig * jnp.sin(phi)
                 - stig**2 * jnp.cos(2 * phi)) / stig**3
        approx = _h3_fourier_harms(phi, stig, 30)
        assert np.allclose(np.asarray(approx), np.asarray(exact), atol=1e-10)

    def test_fbx_freq_factorials(self):
        """pbprime from FBX must equal 1/(d orbits/dt) incl. 1/n! factors."""
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import orbits_fbx

        fbs = [1e-4, 1e-12, 1e-20, 3e-28]
        t0 = 1e6
        orbits_fn = lambda t: orbits_fbx(fbs, t)[0]
        import jax

        freq_ad = jax.grad(lambda t: orbits_fn(t))(t0)
        _, pbprime = orbits_fbx(fbs, jnp.asarray([t0]))
        assert float(pbprime[0]) == pytest.approx(1.0 / float(freq_ad), rel=1e-12)

    def test_fbx_equals_pb(self):
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import orbits_fbx, orbits_pb

        pb_days = 3.21
        pv = {"PB": pb_days, "PBDOT": 0.0}
        tt0 = jnp.linspace(0, 86400.0 * 30, 100)
        o1, p1 = orbits_pb(pv, tt0)
        o2, p2 = orbits_fbx([1.0 / (pb_days * 86400.0)], tt0)
        assert np.allclose(np.asarray(o1), np.asarray(o2), rtol=1e-12)
        assert np.allclose(np.asarray(p1), np.asarray(p2), rtol=1e-12)

    def test_ddgr_pk_values(self):
        """DDGR-derived SINI approximates the mass function expectation."""
        import jax.numpy as jnp
        from pint_tpu.models.binary.engines import TSUN, _ddgr_arr

        mtot, m2 = 2.8 * TSUN, 1.4 * TSUN
        pb_s = 8.0 * 86400.0
        n = 2 * np.pi / pb_s
        arr0, arr = _ddgr_arr(mtot, mtot - m2, m2, n)
        # Newtonian limit: arr0 = (G Mtot / n^2)^(1/3) in seconds
        assert np.isclose(float(arr0), (mtot / n**2) ** (1 / 3), rtol=1e-12)
        # relativistic correction is small but nonzero
        assert 0 < abs(float(arr - arr0) / float(arr0)) < 1e-4


class TestComponents:
    def test_dd_model_build_and_residuals(self):
        from pint_tpu.models import get_model

        m = get_model(DD_PAR)
        assert "BinaryDD" in m.components
        toas = _fake(m, 60, start=49000, end=50000)
        from pint_tpu.residuals import Residuals

        r = Residuals(toas, m)
        # simulation zeroed the residuals: binary model round-trips
        assert np.max(np.abs(r.time_resids)) < 5e-6

    def test_ell1_fbx_model(self):
        from pint_tpu.models import get_model

        m = get_model(ELL1_PAR)
        assert "BinaryELL1" in m.components
        assert m.components["BinaryELL1"]._nfb == 3
        toas = _fake(m, 60, start=56000, end=57000)
        from pint_tpu.residuals import Residuals

        r = Residuals(toas, m)
        assert np.max(np.abs(r.time_resids)) < 5e-6

    def test_binary_designmatrix_autodiff_vs_fd(self):
        """jacfwd binary-parameter derivatives match finite differences."""
        from pint_tpu.models import get_model

        m = get_model(DD_PAR)
        toas = _fake(m, 40, start=49000, end=50000)
        m.free_params = ["PB", "A1", "ECC", "OM", "SINI", "M2"]
        M, names, units = m.designmatrix(toas)
        F0 = float(m.F0.value)
        for p in ("A1", "ECC", "OM", "M2"):
            i = names.index(p)
            num = m.d_phase_d_param_num(toas, p, step=1e-6)
            col = -num / F0
            # FD is noise-limited (phase differencing); compare to 1% of the
            # column scale
            assert np.max(np.abs(M[:, i] - col)) < 1e-2 * np.max(np.abs(col)), p

    def test_binary_fit_recovers_perturbation(self):
        from pint_tpu.fitter import DownhillWLSFitter
        from pint_tpu.models import get_model

        m = get_model(DD_PAR)
        toas = _fake(m, 80, start=49000, end=50500)
        m2 = copy.deepcopy(m)
        a1_true = m.A1.value
        m2.A1.value = a1_true + 3e-6
        m2.free_params = ["A1", "OM", "F0"]
        f = DownhillWLSFitter(toas, m2)
        f.fit_toas()
        assert abs(f.model.A1.value - a1_true) < 5 * f.errors["A1"]

    def test_ddk_builds_and_evaluates(self):
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.models import get_model
        from pint_tpu.residuals import Residuals

        with open(DD_PAR) as fh:
            text = fh.read().replace("BINARY         DD", "BINARY         DDK")
        text = text.replace("SINI           0.99741717335200923866    1  0.00182023515130851988", "")
        text += "\nKIN 85.0\nKOM 30.0\nPX 0.5\n"
        m = get_model(parse_parfile(text))
        assert "BinaryDDK" in m.components
        toas = _fake(m, 40, start=49000, end=50000)
        r = Residuals(toas, m)
        assert np.all(np.isfinite(r.time_resids))

    def test_ddgr_component(self):
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.models import get_model
        from pint_tpu.residuals import Residuals

        import re

        with open(DD_PAR) as fh:
            text = fh.read().replace("BINARY         DD", "BINARY         DDGR")
        # masses must be consistent with A1 (sin i <= 1): raise M2
        text = re.sub(r"M2 .*", "", text)
        text += "\nMTOT 1.65\nM2 0.4\n"
        m = get_model(parse_parfile(text))
        assert "BinaryDDGR" in m.components
        toas = _fake(m, 40, start=49000, end=50000)
        r = Residuals(toas, m)
        assert np.all(np.isfinite(r.time_resids))

    def test_t2_guess(self):
        from pint_tpu.models.model_builder import ModelBuilder

        b = ModelBuilder()
        assert b.guess_t2_model({"TASC", "EPS1"}) == "BinaryELL1"
        assert b.guess_t2_model({"TASC", "H3"}) == "BinaryELL1H"
        assert b.guess_t2_model({"T0", "KIN", "KOM"}) == "BinaryDDK"
        assert b.guess_t2_model({"T0", "SHAPMAX"}) == "BinaryDDS"
        assert b.guess_t2_model({"T0", "OM"}) == "BinaryBT"

    def test_t2_requires_opt_in(self):
        from pint_tpu.exceptions import UnknownBinaryModel
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.models import get_model

        with open(DD_PAR) as fh:
            text = fh.read().replace("BINARY         DD", "BINARY         T2")
        with pytest.raises(UnknownBinaryModel):
            get_model(parse_parfile(text))
        m = get_model(parse_parfile(text), allow_T2=True)
        assert "BinaryDD" in m.components

    def test_xdot_unit_scaling(self):
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.models import get_model

        with open(DD_PAR) as fh:
            text = fh.read()
        m = get_model(parse_parfile(text + "\nXDOT 1.3\n"))
        # tempo convention: bare XDOT > 1e-7 is in units of 1e-12
        assert m.A1DOT.value == pytest.approx(1.3e-12)


class TestGuessBinaryModel:
    def test_priority_list_from_parfile_dict(self):
        from pint_tpu.io.par import parse_parfile
        from pint_tpu.models.model_builder import guess_binary_model

        d = parse_parfile(["PSR X\n", "BINARY T2\n", "PB 1.0\n", "A1 2.0\n",
                           "TASC 55000\n", "EPS1 1e-5\n", "H3 1e-7\n"])
        guesses = guess_binary_model(d)
        assert guesses[0] == "ELL1H"
        assert "BT" in guesses and len(set(guesses)) == len(guesses)
        d2 = parse_parfile(["PSR Y\n", "KIN 70\n", "KOM 90\n", "PB 1\n"])
        assert guess_binary_model(d2)[0] == "DDK"
