"""Durability & chaos-drill tests (PR 17).

Pins the load-bearing contracts of the durable-service layer:

* **write-ahead journal** — every accepted ``append | quarantine |
  release`` op is durably logged (checksummed, schema-tagged,
  seq-contiguous records, identity-bound segments) before the submit
  future resolves; a torn FINAL record is dropped with a typed
  ``journal_truncated`` event, interior corruption / a foreign
  journal refuse with the typed ``CheckpointError``;
* **crash-consistent recovery** — ``SimulatedCrash`` at EVERY op
  index: :meth:`~pint_tpu.serving.service.TimingService.recover`
  lands **bitwise** (``array_equal`` on every ``state_dict`` array)
  on the last-acknowledged pre-crash state, snapshot + tail-replay
  reconstructs the quarantine pen, and a warm fit after recovery
  matches the uncrashed run at 1e-9;
* **circuit breakers & deadlines** — N consecutive dispatch failures
  open the breaker (closed → open → half_open → closed, pinned
  transition counts); submits resolve as typed
  ``ShedResponse(reason="circuit_open")`` data, a request past its
  class deadline budget resolves as ``reason="deadline"`` instead of
  hanging its awaiter;
* **the drill contract** — every scripted chaos scenario under
  open-loop load resolves every admitted request (ZERO stranded
  futures), bounds untyped failure, returns to steady state, and
  leaves served results matching a dedicated dense solve at 1e-9;
* **event contracts** — ``journal_replay`` / ``journal_truncated`` /
  ``circuit_transition`` / ``chaos_drill`` records validate through
  ``telemetry_report --check`` and malformed twins are rejected.
"""

import asyncio
import copy
import os
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pint_tpu.exceptions import CheckpointError, UsageError  # noqa: E402
from pint_tpu.runtime import chaos  # noqa: E402
from pint_tpu.runtime.faultinject import (  # noqa: E402
    SimulatedCrash,
    corrupt_record,
    crash_at_op,
    torn_tail,
)
from pint_tpu.serving import service  # noqa: E402
from pint_tpu.serving.admission import (  # noqa: E402
    BreakerConfig,
    CircuitBreaker,
    SHED_REASONS,
    ShedResponse,
)
from pint_tpu.serving.batcher import FitRequest  # noqa: E402
from pint_tpu.serving.journal import (  # noqa: E402
    UpdateJournal,
    decode_request,
    scan_journal,
)
from pint_tpu.serving.scheduler import SchedulerConfig  # noqa: E402
from pint_tpu.streaming.door import UpdateRequest  # noqa: E402

STREAM_PAR = """PSR J9999+9999
RAJ 9:59:59.0
DECJ 9:59:59.0
F0 300.0 1 0.0
F1 -1e-14 1 0.0
PEPOCH 54000
POSEPOCH 54000
DM 2.64
EFAC mjd 50000 60000 1.1
TNRedAmp -13.5
TNRedGam 3.5
TNRedC 5
TNREDTSPAN 6.0
UNITS TDB
"""

N_TOAS = 140
N_BASE = 100
BLOCK = 8
N_BLOCKS = 5


@pytest.fixture(scope="module")
def workload():
    """(model, full toas, base slice, append blocks) — read-only;
    tests that mutate TOAs deep-copy what they touch."""
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model([ln + "\n" for ln in STREAM_PAR.splitlines()])
    rng = np.random.default_rng(7)
    toas = make_fake_toas_uniform(
        53400, 54800, N_TOAS, model, freq=np.array([800.0, 1400.0]),
        error_us=1.0, add_noise=True, rng=rng)
    base = toas[np.arange(N_BASE)]
    blocks = [toas[np.arange(N_BASE + BLOCK * i, N_BASE + BLOCK * (i + 1))]
              for i in range(N_BLOCKS)]
    return model, toas, base, blocks


def _fresh_service(workload, **cfg):
    """A TimingService over a FRESH engine from the same converged
    base fit — the recovery precondition."""
    from pint_tpu.gls_fitter import GLSFitter

    model, _, base, _ = workload
    f = GLSFitter(base, copy.deepcopy(model))
    f.fit_toas(maxiter=2)
    svc = service.TimingService(service.ServeConfig(**cfg)) \
        if cfg else service.TimingService()
    svc.register_stream(f, warm=False)
    return svc


#: the acceptance-pin op script: >= 5 epoch blocks interleaved with
#: quarantine/release row ops, one op per journal record
def _op_script(blocks):
    return [
        UpdateRequest(new_toas=copy.deepcopy(blocks[0]),
                      request_id="a0"),
        UpdateRequest(kind="quarantine", block_id=0, rows=[0, 2],
                      request_id="q0"),
        UpdateRequest(new_toas=copy.deepcopy(blocks[1]),
                      request_id="a1"),
        UpdateRequest(kind="release", block_id=0, rows=[2],
                      request_id="r0"),
        UpdateRequest(new_toas=copy.deepcopy(blocks[2]),
                      request_id="a2"),
        UpdateRequest(kind="quarantine", block_id=1, rows=[1],
                      request_id="q1"),
        UpdateRequest(new_toas=copy.deepcopy(blocks[3]),
                      request_id="a3"),
        UpdateRequest(new_toas=copy.deepcopy(blocks[4]),
                      request_id="a4"),
    ]


def _state_of(svc):
    return {k: np.asarray(v).copy()
            for k, v in svc.stream.cache.state_dict().items()}


def _assert_bitwise(ref, got, what=""):
    assert set(ref) == set(got), (what, set(ref) ^ set(got))
    for k in ref:
        assert ref[k].dtype == got[k].dtype \
            and ref[k].shape == got[k].shape \
            and np.array_equal(ref[k], got[k], equal_nan=True), \
            f"{what}: state array {k!r} differs"


# ---------------------------------------------------------------------------
# the load-harness stub service (drill + breaker/deadline tests)
# ---------------------------------------------------------------------------

def _fit_request(rng, n=48, k=6, request_id=None):
    M = rng.standard_normal((n, k))
    r = 1e-6 * rng.standard_normal(n)
    w = 1.0 / (1e-12 + 1e-13 * rng.random(n))
    return FitRequest(M=M, r=r, w=w, phiinv=np.zeros(k),
                      request_id=request_id)


def _stub_service(**over):
    cfg = dict(ntoa_buckets=(64,), nfree_buckets=(8,),
               batch_buckets=(1, 4, 16), draw_buckets=(32,),
               window_ms=1.0, max_queue=256,
               breaker=BreakerConfig(failures=2, reset_s=0.2))
    cfg.update(over)
    return service.TimingService(service.ServeConfig(**cfg))


# ---------------------------------------------------------------------------
# write-ahead journal
# ---------------------------------------------------------------------------

class TestJournal:
    def _requests(self):
        return [UpdateRequest(kind="quarantine", block_id=0, rows=[0],
                              request_id="q"),
                UpdateRequest(kind="release", block_id=0, rows=[0],
                              request_id="r")]

    def test_commit_scan_round_trip(self, tmp_path):
        path = str(tmp_path / "j")
        with UpdateJournal(path, ["vk0", "vk1"]) as j:
            gid, last = j.commit(self._requests())
            assert (gid, last) == (0, 1)
            gid2, last2 = j.commit([self._requests()[0]])
            assert (gid2, last2) == (2, 2)
            assert j.ops_journaled == 3 and j.next_seq == 3
        scan = scan_journal(path)
        assert scan.ident == ["vk0", "vk1"]
        assert scan.dropped is None and scan.last_seq == 2
        batches = scan.batches()
        assert [len(b) for b in batches] == [2, 1]
        req = decode_request(batches[0][0])
        assert req.kind == "quarantine" and req.rows == [0] \
            and req.request_id == "q"

    def test_reopen_continues_seq_in_fresh_segment(self, tmp_path):
        path = str(tmp_path / "j")
        with UpdateJournal(path, ["vk"]) as j:
            j.commit([self._requests()[0]])
        with UpdateJournal(path, ["vk"]) as j2:
            assert j2.next_seq == 1
            j2.commit([self._requests()[1]])
        scan = scan_journal(path)
        assert scan.last_seq == 1 and len(scan.segments) == 2

    def test_torn_tail_dropped(self, tmp_path):
        path = str(tmp_path / "j")
        with UpdateJournal(path, ["vk"]) as j:
            j.commit([self._requests()[0]])
            with torn_tail() as state:
                j.commit([self._requests()[1]])
        assert state["torn"] == 1
        scan = scan_journal(path)
        # the torn FINAL record is dropped, never replayed as garbage
        assert scan.dropped is not None
        assert len(scan.records) == 1 and scan.last_seq == 0

    def test_interior_corruption_refused(self, tmp_path):
        path = str(tmp_path / "j")
        with UpdateJournal(path, ["vk"]) as j:
            with corrupt_record():
                j.commit([self._requests()[0]])
            j.commit([self._requests()[1]])
        with pytest.raises(CheckpointError):
            scan_journal(path)

    def test_crash_at_op_leaves_clean_prefix(self, tmp_path):
        path = str(tmp_path / "j")
        j = UpdateJournal(path, ["vk"])
        j.commit([self._requests()[0]])
        with pytest.raises(SimulatedCrash):
            with crash_at_op(1):
                j.commit(self._requests())
        # group commit: the crashed batch flushed NOTHING (its futures
        # never resolved, so losing it whole is the WAL contract) and
        # the durable prefix scans as a VALID journal
        scan = scan_journal(path)
        assert scan.dropped is None and scan.last_seq == 0
        # a fresh handle (the restarted process) resumes cleanly
        with UpdateJournal(path, ["vk"]) as j2:
            assert j2.next_seq == 1
            j2.commit([self._requests()[1]])
        assert scan_journal(path).last_seq == 1

    def test_foreign_ident_refused(self, tmp_path):
        path = str(tmp_path / "j")
        with UpdateJournal(path, ["vk-a"]) as j:
            j.commit([self._requests()[0]])
        with pytest.raises(CheckpointError):
            UpdateJournal(path, ["vk-b"])

    def test_config_validation_typed(self, tmp_path):
        path = str(tmp_path / "j")
        with pytest.raises(UsageError):
            UpdateJournal(path, ["vk"], fsync="sometimes")
        with pytest.raises(UsageError):
            UpdateJournal(path, ["vk"], segment_bytes=16)
        with pytest.raises(UsageError):
            UpdateJournal(path, [])
        with UpdateJournal(path, ["vk"]) as j:
            with pytest.raises(UsageError):
                j.commit(["not a request"])

    def test_segment_rotation_preserves_scan(self, tmp_path):
        path = str(tmp_path / "j")
        with UpdateJournal(path, ["vk"], segment_bytes=256) as j:
            for i in range(6):
                j.commit([UpdateRequest(kind="quarantine", block_id=0,
                                        rows=[i], request_id=f"q{i}")])
        scan = scan_journal(path)
        assert len(scan.segments) > 1
        assert scan.last_seq == 5 and scan.dropped is None
        assert [r["seq"] for r in scan.records] == list(range(6))


# ---------------------------------------------------------------------------
# circuit breakers & deadlines
# ---------------------------------------------------------------------------

class TestBreaker:
    def test_state_machine_transition_counts(self):
        b = CircuitBreaker("fit", BreakerConfig(failures=3,
                                                reset_s=0.05))
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed"          # under threshold
        b.record_failure()
        assert b.state == "open" and b.transitions == 1
        assert not b.allow()                # open refuses
        time.sleep(0.06)
        assert b.allow()                    # reset elapsed: half-open
        assert b.state == "half_open" and b.transitions == 2
        assert not b.allow()                # ONE probe only
        b.record_success()
        assert b.state == "closed" and b.transitions == 3
        # a half-open probe failure re-opens instantly
        for _ in range(3):
            b.record_failure()
        time.sleep(0.06)
        assert b.allow()
        b.record_failure()
        assert b.state == "open"

    def test_config_validation_typed(self):
        with pytest.raises(UsageError):
            BreakerConfig(failures=0)
        with pytest.raises(UsageError):
            BreakerConfig(reset_s=0.0)
        with pytest.raises(UsageError):
            CircuitBreaker("grid")

    def test_open_breaker_sheds_typed(self):
        """After `failures` consecutive dispatch failures the door
        answers with ShedResponse(reason='circuit_open') DATA — never
        an exception through a coalescing window."""
        rng = np.random.default_rng(0)
        svc = _stub_service()

        async def go():
            out = []
            with chaos.door_fault(svc, "raise", times=10):
                for i in range(4):
                    try:
                        out.append(await svc.submit(
                            _fit_request(rng, request_id=f"r{i}")))
                    except Exception as exc:   # pre-trip: typed raise
                        out.append(exc)
            return out

        results = asyncio.run(go())
        raised = [r for r in results if isinstance(r, Exception)]
        # the first `failures` submits raise the injected typed fault
        assert 0 < len(raised) <= 2
        sheds = [r for r in results if isinstance(r, ShedResponse)]
        # breaker trips after 2 failures; later submits shed typed
        assert sheds and all(s.reason == "circuit_open" for s in sheds)
        assert all(s.retry_after_ms > 0 for s in sheds)
        assert svc.breakers()["fit"]["state"] == "open"

    def test_half_open_probe_recloses(self):
        rng = np.random.default_rng(1)
        svc = _stub_service()

        async def go():
            with chaos.door_fault(svc, "raise", times=2):
                for i in range(2):
                    try:
                        await svc.submit(_fit_request(rng))
                    except Exception:
                        pass
            assert svc.breakers()["fit"]["state"] == "open"
            await asyncio.sleep(0.25)      # past reset_s
            res = await svc.submit(_fit_request(rng))
            return res

        res = asyncio.run(go())
        assert not isinstance(res, ShedResponse)
        assert svc.breakers()["fit"]["state"] == "closed"
        assert svc.breakers()["fit"]["transitions"] == 3

    def test_deadline_timeout_sheds_typed(self):
        """A request still coalescing past its class deadline budget
        resolves as ShedResponse(reason='deadline') instead of hanging
        its awaiter on the window."""
        rng = np.random.default_rng(2)
        svc = _stub_service(
            window_ms=5000.0,
            sched=SchedulerConfig(deadlines_ms={"fit": 40.0}))

        async def go():
            t0 = time.perf_counter()
            res = await svc.submit(_fit_request(rng, request_id="late"))
            return res, time.perf_counter() - t0

        res, dt = asyncio.run(go())
        assert isinstance(res, ShedResponse)
        assert res.reason == "deadline" and res.request_id == "late"
        assert dt < 2.0                    # never waited out the window
        assert "deadline" in SHED_REASONS and "circuit_open" in SHED_REASONS

    def test_deadlines_opt_out(self):
        rng = np.random.default_rng(3)
        svc = _stub_service(
            window_ms=60.0, enforce_deadlines=False,
            sched=SchedulerConfig(deadlines_ms={"fit": 1.0}))

        async def go():
            return await svc.submit(_fit_request(rng))

        res = asyncio.run(go())
        assert not isinstance(res, ShedResponse)


# ---------------------------------------------------------------------------
# crash-consistent recovery
# ---------------------------------------------------------------------------

class TestRecovery:
    def _journal_full_run(self, workload, jdir):
        """Apply + journal the full op script, capturing the reference
        state after every op; returns (ref_states, svc)."""
        svc = _fresh_service(workload)
        svc.attach_journal(jdir)
        refs = []
        for op in _op_script(workload[3]):
            svc.serve_updates([op])
            refs.append(_state_of(svc))
        return refs, svc

    def _truncate_journal(self, src, dst, k):
        """A copy of the single-segment journal holding only the first
        k op records — the on-disk state after a crash at op index k."""
        os.makedirs(dst, exist_ok=True)
        seg = sorted(os.listdir(src))[0]
        with open(os.path.join(src, seg), "rb") as fh:
            lines = fh.readlines()
        with open(os.path.join(dst, seg), "wb") as fh:
            fh.writelines(lines[:1 + k])   # header + k ops

    def test_crash_at_every_op_lands_bitwise(self, workload, tmp_path):
        """The acceptance pin: for EVERY op index k, recovery from the
        journal's first k ops lands bitwise on the state after op k-1
        — and at the full prefix, a warm fit on the recovered engine
        matches the uncrashed engine at 1e-9."""
        jdir = str(tmp_path / "journal")
        refs, svc_ref = self._journal_full_run(workload, jdir)
        svc_ref.journal.close()
        n_ops = len(refs)
        for k in range(1, n_ops + 1):
            jcut = str(tmp_path / f"cut{k}")
            self._truncate_journal(jdir, jcut, k)
            svc = _fresh_service(workload)
            rep = svc.recover(jcut)
            assert rep["ops_replayed"] == k and rep["truncated"] is None
            _assert_bitwise(refs[k - 1], _state_of(svc), what=f"k={k}")
        # warm-fit agreement on the full prefix (svc still recovered
        # state == refs[-1]): one more identical append on both
        _, toas, _, _ = workload
        probe = toas[np.arange(0, 6)]
        o_ref = svc_ref.stream.update_toas(copy.deepcopy(probe))
        o_rec = svc.stream.update_toas(copy.deepcopy(probe))
        for p in o_ref.params:
            a, b = o_ref.params[p], o_rec.params[p]
            assert abs(a - b) <= 1e-9 * max(abs(a), 1.0), (p, a, b)
        assert abs(o_ref.chi2 - o_rec.chi2) <= 1e-9 * abs(o_ref.chi2)

    def test_simulated_crash_mid_stream(self, workload, tmp_path):
        """The real seam: SimulatedCrash between the factor apply and
        the journal ack loses ONLY the unacknowledged op."""
        jdir = str(tmp_path / "journal")
        svc = _fresh_service(workload)
        svc.attach_journal(jdir)
        ops = _op_script(workload[3])
        applied = 0
        with pytest.raises(SimulatedCrash):
            with crash_at_op(4):
                for op in ops:
                    svc.serve_updates([op])
                    applied += 1
        assert applied == 4                # op 4 applied but never acked
        svc2 = _fresh_service(workload)
        rep = svc2.recover(jdir)
        assert rep["ops_replayed"] == 4
        # re-driving the crashed-op replayable tail converges with the
        # journaled prefix: the service continues from the recovery
        out = svc2.serve_updates([ops[4]])
        assert out[0].kind in ("append", "quarantine", "release")
        assert svc2.journal.ops_journaled == 1   # fresh segment, acked

    def test_snapshot_plus_tail_replay_rebuilds_pen(self, workload,
                                                    tmp_path):
        """Snapshot mid-stream + journal-tail replay: bitwise landing
        AND the quarantine pen re-derived from the journaled appends
        the snapshot covers (the inspect/repair/release workflow
        survives a crash)."""
        jdir = str(tmp_path / "journal")
        snap = str(tmp_path / "snap")
        _, _, _, blocks = workload
        svc = _fresh_service(workload)
        svc.attach_journal(jdir)
        bad = copy.deepcopy(blocks[0])
        bad.error_us[2] = -1.0             # one penned row
        svc.serve_updates([UpdateRequest(new_toas=bad,
                                         request_id="bad")])
        assert len(svc.stream.pen) == 1
        svc.snapshot(snap)
        svc.serve_updates([UpdateRequest(
            new_toas=copy.deepcopy(blocks[1]), request_id="b1")])
        ref = _state_of(svc)
        svc.journal.close()

        svc2 = _fresh_service(workload)
        rep = svc2.recover(jdir, snapshot=snap)
        assert rep["snapshot_seq"] == 0 and rep["ops_replayed"] == 1
        _assert_bitwise(ref, _state_of(svc2), what="snapshot+tail")
        assert len(svc2.stream.pen) == 1
        penned, reasons = next(iter(svc2.stream.pen.values()))
        assert len(penned) == 1 and reasons

    def test_torn_tail_recovery_flags_truncation(self, workload,
                                                 tmp_path):
        jdir = str(tmp_path / "journal")
        _, _, _, blocks = workload
        svc = _fresh_service(workload)
        svc.attach_journal(jdir)
        svc.serve_updates([UpdateRequest(
            new_toas=copy.deepcopy(blocks[0]), request_id="a0")])
        ref = _state_of(svc)
        with torn_tail():
            svc.serve_updates([UpdateRequest(
                new_toas=copy.deepcopy(blocks[1]), request_id="a1")])
        svc.journal.close()
        svc2 = _fresh_service(workload)
        rep = svc2.recover(jdir)
        assert rep["truncated"] is not None   # typed truncation report
        assert rep["ops_replayed"] == 1
        _assert_bitwise(ref, _state_of(svc2), what="torn-tail")

    def test_foreign_journal_refused(self, workload, tmp_path):
        jdir = str(tmp_path / "journal")
        with UpdateJournal(jdir, ["some-other-stream"]) as j:
            j.commit([UpdateRequest(kind="quarantine", block_id=0,
                                    rows=[0], request_id="q")])
        svc = _fresh_service(workload)
        with pytest.raises(CheckpointError):
            svc.recover(jdir)

    def test_recover_requires_stream(self, tmp_path):
        svc = service.TimingService()
        with pytest.raises(UsageError):
            svc.recover(str(tmp_path / "journal"))


# ---------------------------------------------------------------------------
# chaos drills under live load — the drill contract
# ---------------------------------------------------------------------------

class TestDrills:
    @pytest.mark.parametrize("scenario", [
        "device_loss", "nan_shard", "straggler", "failed_collective",
        "crash_mid_coalesce", "corrupt_aot"])
    def test_drill_contract_per_fault_class(self, scenario):
        """Every injected fault class: zero stranded futures, typed
        sheds, bounded untyped failure, recovery to steady state, and
        post-drill results at 1e-9 vs the dedicated solve."""
        svc = _stub_service()
        rep = chaos.run_drill(svc, scenario, rps=300.0, n_requests=16,
                              times=2, delay_s=0.02, seed=5,
                              recovery_timeout_s=15.0)
        assert rep.contract_ok, rep.violations
        assert rep.stranded == 0
        assert rep.offered == rep.completed + rep.shed + rep.errored
        assert rep.errored <= rep.errors_bound
        assert rep.recovery_s is not None
        assert rep.spot_check_rel_err <= chaos.SPOT_CHECK_RTOL
        # the flight-recorder side of the contract: every drill dumps
        # at least one postmortem bundle and each bundle validates
        assert rep.postmortems >= 1
        assert rep.postmortem_ok
        d = rep.to_dict()
        assert d["scenario"] == scenario and d["contract_ok"] is True

    def test_quarantine_storm_journals_under_load(self, workload):
        import shutil
        import tempfile

        svc = _fresh_service(workload)
        _, _, _, blocks = workload
        svc.serve_updates([UpdateRequest(
            new_toas=copy.deepcopy(blocks[0]), request_id="seed")])
        tmp = tempfile.mkdtemp(prefix="pint_tpu_storm_")
        try:
            svc.attach_journal(os.path.join(tmp, "journal"))
            rep = chaos.run_drill(svc, "quarantine_storm", rps=200.0,
                                  n_requests=16, seed=6,
                                  recovery_timeout_s=15.0)
            assert rep.contract_ok, rep.violations
            assert rep.stranded == 0
            # the storm's accepted ops were all journaled before ack
            assert svc.journal.ops_journaled > 0
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def test_unknown_scenario_typed(self):
        svc = _stub_service()
        with pytest.raises(UsageError):
            chaos.run_drill(svc, "squirrels")
        with pytest.raises(UsageError):
            chaos.scenario_context(svc, "squirrels")
        with pytest.raises(UsageError):
            chaos.door_fault(svc, "maybe").__enter__()


# ---------------------------------------------------------------------------
# event contracts (telemetry_report --check)
# ---------------------------------------------------------------------------

class TestDurabilityEventValidation:
    def _validate(self, tmp_path, **attrs):
        from pint_tpu import telemetry
        from pint_tpu.telemetry import runlog
        from tools.telemetry_report import validate_run_dir

        run_dir = str(tmp_path / "run")
        telemetry.activate("full")
        try:
            run = runlog.start_run(run_dir, name="durability-events",
                                   probe_device=False)
            run.record_event(attrs.pop("_name"), **attrs)
            runlog.end_run()
        finally:
            telemetry.deactivate()
        errors = []
        validate_run_dir(run_dir, errors)
        return errors

    def test_valid_journal_replay_passes(self, tmp_path):
        assert not self._validate(
            tmp_path, _name="journal_replay", ops_replayed=5,
            ops_total=8, time_to_recover_s=0.4, snapshot=True,
            truncated=False)

    def test_replay_exceeding_total_rejected(self, tmp_path):
        errors = self._validate(
            tmp_path, _name="journal_replay", ops_replayed=9,
            ops_total=8, time_to_recover_s=0.4, snapshot=False,
            truncated=False)
        assert any("ops_total" in e for e in errors)

    def test_truncation_requires_reason(self, tmp_path):
        errors = self._validate(
            tmp_path, _name="journal_truncated",
            segment="seg_000000.wal", reason="  ", dropped=1)
        assert any("reason" in e for e in errors)

    def test_truncation_drops_exactly_one(self, tmp_path):
        errors = self._validate(
            tmp_path, _name="journal_truncated",
            segment="seg_000000.wal", reason="crc mismatch", dropped=3)
        assert any("dropped" in e for e in errors)

    def test_transition_state_enum_enforced(self, tmp_path):
        errors = self._validate(
            tmp_path, _name="circuit_transition", door="fit",
            from_state="closed", to_state="ajar", failures=2)
        assert any("ajar" in e for e in errors)
        errors = self._validate(
            tmp_path, _name="circuit_transition", door="fit",
            from_state="open", to_state="open", failures=2)
        assert any("must change state" in e for e in errors)

    def test_chaos_drill_counts_validated(self, tmp_path):
        assert not self._validate(
            tmp_path, _name="chaos_drill", scenario="device_loss",
            offered=32, completed=20, shed=10, errored=2, stranded=0,
            duration_s=1.1, recovery_s=0.2, contract_ok=True,
            postmortems=1, postmortem_ok=True)
        errors = self._validate(
            tmp_path, _name="chaos_drill", scenario="device_loss",
            offered=-1, completed=20, shed=10, errored=2, stranded=-2,
            duration_s=1.1, recovery_s=0.2, contract_ok=False,
            postmortems=-1, postmortem_ok=False)
        assert any("offered" in e for e in errors)
        assert any("stranded" in e for e in errors)
        assert any("postmortems" in e for e in errors)

    def test_breaker_and_deadline_shed_reasons_accepted(self,
                                                        tmp_path):
        for reason in ("circuit_open", "deadline"):
            assert not self._validate(
                tmp_path, _name="request_shed", request_class="fit",
                reason=reason, retry_after_ms=5.0, queue_depth=0)
