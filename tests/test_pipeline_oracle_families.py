"""40-digit pipeline-oracle rows for the delay/phase families the original
harness did not cover (VERDICT r4 missing #1 / next-round item 4): every
binary family (BT, DDS, DDH, DDGR, DDK, ELL1, ELL1H, ELL1k — DD is in the
original harness), glitch recoveries, troposphere (Niell mapping),
chromatic CM/CMX, wave, ifunc, piecewise spindown, SWX.

Same philosophy as ``test_pipeline_oracle.py``: both sides get IDENTICAL
fabricated TDB times and observer/sun vectors; the framework computes
residuals through its full jitted stack, while the oracle recomputes every
delay/phase term from the published formulas in 40-digit mpmath — with the
binary delays supplied by the *reference's own engines* executed in-process
through the r2 unit shim (use-as-oracle, not copying) — and the residual
vectors must agree at the nanosecond level.

Reference formulas: ``glitch.py:12``, ``troposphere_delay.py:16`` (Davis
1985 zenith + Niell 1996 mapping), ``chromatic_model.py:118,313``,
``wave.py:11,148``, ``ifunc.py:128``, ``piecewise.py:12``,
``solar_wind_dispersion.py:608`` (Hazboun et al. 2022 eq. 11 geometry);
engine oracles ``ELL1H_model.py``, ``DDK_model.py``, ``DDGR_model.py``.
"""

import math
import os
import sys
import tempfile

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _refshim  # noqa: E402

pytestmark = pytest.mark.skipif(
    not os.path.isdir(_refshim.REF), reason="reference tree not present")

mp = pytest.importorskip("mpmath")
mp.mp.dps = 40

N = 32
SECPERDAY = 86400.0
C_KM_S = 299792.458
DMK = 1.0 / 2.41e-4
AU_KM = 149597870.7
AU_LS = AU_KM / C_KM_S
PC_LS = 3.0856775814913673e16 / 299792458.0
KPC_LS = 3.0856775814913673e19 / 299792458.0
T_SUN = 4.925490947641267e-06

BASE_ECL = """\
PSR FABFAM
LAMBDA 123.456
BETA 17.3
POSEPOCH 55300
F0 218.8118437960826 1
F1 -4.08D-16 1
PEPOCH 55300
DM 11.5 1
UNITS TDB
"""

BASE_EQ = """\
PSR FABK
RAJ 17:48:52.75
DECJ -20:21:29.0
PMRA 3.1
PMDEC -2.4
PX 0.9
POSEPOCH 55300
F0 218.8118437960826 1
F1 -4.08D-16 1
PEPOCH 55300
DM 11.5 1
UNITS TDB
"""


def _fab(par_text, n=N, obs="bat", seed=11, mjd_lo=54200.0, mjd_hi=56400.0):
    """Model + TOAs with fabricated, smooth, reproducible tdb/posvel inputs."""
    import jax

    jax.config.update("jax_enable_x64", True)
    from pint_tpu.models import get_model
    from pint_tpu.toa import get_TOAs

    rng = np.random.default_rng(seed)
    model = get_model([ln + "\n" for ln in par_text.splitlines()])
    mjds = np.sort(rng.uniform(mjd_lo, mjd_hi, n))
    freqs = np.where(rng.random(n) < 0.5, 430.0, 1410.0) + rng.uniform(0, 40, n)
    lines = ["FORMAT 1\n"]
    for i in range(n):
        lines.append(f"f{i} {freqs[i]:.4f} {mjds[i]:.13f} "
                     f"{1.0 + rng.random():.3f} {obs}\n")
    with tempfile.NamedTemporaryFile("w", suffix=".tim", delete=False) as f:
        f.write("".join(lines))
        timf = f.name
    t = get_TOAs(timf, include_gps=False, include_bipm=False)
    os.unlink(timf)

    ph = 2 * np.pi * (mjds - 54000.0) / 365.25
    obs_v = np.stack([AU_KM * np.cos(ph), AU_KM * 0.9 * np.sin(ph),
                      AU_KM * 0.39 * np.sin(ph)], axis=1)
    vel = np.stack([-30.0 * np.sin(ph), 27.0 * np.cos(ph),
                    11.7 * np.cos(ph)], axis=1)
    sun = -obs_v * (1.0 + 0.01 * np.sin(3 * ph))[:, None]
    t.ssb_obs_pos_km = obs_v
    t.ssb_obs_vel_kms = vel
    t.obs_sun_pos_km = sun
    t._version += 1
    return model, t


# ---------------------------------------------------------------------------
# oracle building blocks (mpmath)
# ---------------------------------------------------------------------------

def _mp_tdb(t):
    hi64 = np.asarray(t.tdb, np.float64)
    if t.tdb_lo is not None:
        lo64 = np.asarray(t.tdb_lo, np.float64)
    else:
        lo64 = np.asarray(t.tdb - hi64.astype(np.longdouble), np.float64)
    return [mp.mpf(float(h)) + mp.mpf(float(l)) for h, l in zip(hi64, lo64)]


def _lhats(model, tdb):
    """Equatorial unit vectors per TOA, PM applied linearly in angle (the
    same approximation the timing model uses, ``astrometry.py:181-196``)."""
    from pint_tpu import OBL_IERS2010_RAD

    masyr = mp.pi / 180 / 3600 / 1000 / mp.mpf("365.25")
    pe = mp.mpf(repr(float(model.POSEPOCH.value)))
    out = []
    if "AstrometryEcliptic" in model.components:
        lam0 = mp.mpf(repr(float(model.ELONG.value)))
        bet0 = mp.mpf(repr(float(model.ELAT.value)))
        pml = mp.mpf(repr(float(model.PMELONG.value or 0.0)))
        pmb = mp.mpf(repr(float(model.PMELAT.value or 0.0)))
        cob = mp.cos(mp.mpf(float(OBL_IERS2010_RAD)))
        sob = mp.sin(mp.mpf(float(OBL_IERS2010_RAD)))
        for ti in tdb:
            dt = ti - pe
            lat = bet0 + pmb * masyr * dt
            lon = lam0 + pml * masyr * dt / mp.cos(bet0)
            cb = mp.cos(lat)
            xe, ye, ze = cb * mp.cos(lon), cb * mp.sin(lon), mp.sin(lat)
            out.append((xe, cob * ye - sob * ze, sob * ye + cob * ze))
    else:
        ra0 = mp.mpf(repr(float(model.RAJ.value)))
        dec0 = mp.mpf(repr(float(model.DECJ.value)))
        pmra = mp.mpf(repr(float(model.PMRA.value or 0.0)))
        pmdec = mp.mpf(repr(float(model.PMDEC.value or 0.0)))
        for ti in tdb:
            dt = ti - pe
            dec = dec0 + pmdec * masyr * dt
            ra = ra0 + pmra * masyr * dt / mp.cos(dec0)
            cd = mp.cos(dec)
            out.append((cd * mp.cos(ra), cd * mp.sin(ra), mp.sin(dec)))
    return out


def _base_delays(model, t, tdb, Lhats):
    """Roemer (+PX) + sun Shapiro + DM/f^2 dispersion, and the barycentric
    frequencies (doppler) shared by every chromatic term."""
    obs_ls = np.asarray(t.ssb_obs_pos_km) / C_KM_S
    sun_ls = np.asarray(t.obs_sun_pos_km) / C_KM_S
    vel_ls = np.asarray(t.ssb_obs_vel_kms) / C_KM_S
    px = mp.mpf(repr(float(model.PX.value))) if (
        "PX" in model and model.PX.value) else None
    dmv = mp.mpf(repr(float(model.DM.value)))
    pepoch = mp.mpf(repr(float(model.PEPOCH.value)))
    parsed_freq = np.asarray(t.freq_mhz)
    delays, bfreq = [], []
    AU_LS_f = mp.mpf(repr(AU_LS))
    for i in range(len(t)):
        L = Lhats[i]
        r = [mp.mpf(float(v)) for v in obs_ls[i]]
        rdL = sum(a * b for a, b in zip(r, L))
        r2 = sum(a * a for a in r)
        d = -rdL
        if px is not None:
            d += mp.mpf("0.5") * r2 * (px / mp.mpf(repr(KPC_LS))) \
                * (1 - rdL**2 / r2)
        s = [mp.mpf(float(v)) for v in sun_ls[i]]
        smag = mp.sqrt(sum(a * a for a in s))
        rdn = sum(a * b for a, b in zip(s, L))
        d += -2 * mp.mpf(repr(T_SUN)) * mp.log((smag - rdn) / AU_LS_f)
        v = [mp.mpf(float(x)) for x in vel_ls[i]]
        vdL = sum(a * b for a, b in zip(v, L))
        bf = mp.mpf(repr(float(parsed_freq[i]))) * (1 - vdL)
        bfreq.append(bf)
        d += dmv * mp.mpf(repr(DMK)) / bf**2
        delays.append(d)
    return delays, bfreq, pepoch


def _resids(model, t, delays, tdb, pepoch, phase_extra=None):
    """frac phase (spindown + optional extra phase terms) -> time residuals,
    weighted-mean subtracted with the RAW TOA errors, / F0."""
    F0 = mp.mpf(repr(float(model.F0.value)))
    F1 = mp.mpf(repr(float(model.F1.value)))
    fracs = []
    for i in range(len(t)):
        dt = (tdb[i] - pepoch) * SECPERDAY - delays[i]
        phase = F0 * dt + F1 * dt * dt / 2
        if phase_extra is not None:
            phase += phase_extra(i, dt, delays[i])
        fracs.append(phase - mp.nint(phase))
    err = np.asarray(t.get_errors()) * 1e-6
    w = 1.0 / err**2
    fr = np.array([float(f) for f in fracs])
    fr -= np.sum(fr * w) / np.sum(w)
    return fr / float(F0)


def _assert_parity(model, t, theirs, tol=2e-9, label=""):
    from pint_tpu.residuals import Residuals

    r = Residuals(t, model, track_mode="nearest")
    mine = np.asarray(r.time_resids)
    ph = model.phase(t)
    assert np.all(np.abs(np.abs(np.asarray(ph.frac)) - 0.5) > 1e-3), \
        f"{label}: fabricated phase near wrap boundary, re-seed"
    err = np.abs(mine - theirs)
    assert err.max() < tol, (
        f"{label} pipeline parity: max |delta| = {err.max():.3e} s "
        f"at i={int(err.argmax())}")


@pytest.fixture(scope="module")
def ref():
    return _refshim.install_and_import()


# ---------------------------------------------------------------------------
# phase-family rows (glitch / wave / ifunc / piecewise)
# ---------------------------------------------------------------------------

class TestPhaseFamilies:
    def test_glitch(self):
        """Two glitches, one with an exponential recovery (ref glitch.py:12):
        dphi = GLPH + GLF0*dt + GLF1*dt^2/2 + GLF0D*tau*(1-exp(-dt/tau))."""
        model, t = _fab(BASE_ECL + (
            "GLEP_1 55100\nGLPH_1 0.3\nGLF0_1 2e-8 1\nGLF1_1 -1e-17\n"
            "GLF0D_1 1.5e-8\nGLTD_1 80\nGLEP_2 55900\nGLF0_2 -1e-8\n"))
        tdb = _mp_tdb(t)
        L = _lhats(model, tdb)
        delays, _, pepoch = _base_delays(model, t, tdb, L)

        g = []
        for i in (1, 2):
            g.append({k: mp.mpf(repr(float(
                getattr(model, f"{k}_{i}").value or 0.0)))
                for k in ("GLEP", "GLPH", "GLF0", "GLF1", "GLF0D", "GLTD")})

        def extra(i, dt, delay):
            ph = mp.mpf(0)
            for gl in g:
                dtg = (tdb[i] - gl["GLEP"]) * SECPERDAY - delay
                if dtg > 0:
                    ph += gl["GLPH"] + dtg * (gl["GLF0"] + dtg * gl["GLF1"] / 2)
                    if gl["GLTD"] > 0:
                        tau = gl["GLTD"] * SECPERDAY
                        ph += gl["GLF0D"] * tau * (1 - mp.exp(-dtg / tau))
            return ph

        _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch, extra),
                       label="glitch")

    def test_wave(self):
        """Tempo WAVE sinusoids (ref wave.py:148): phase = F0 * sum_k
        a_k sin(k om dt) + b_k cos(k om dt), dt days from WAVEEPOCH."""
        model, t = _fab(BASE_ECL + (
            "WAVEEPOCH 55300\nWAVE_OM 0.004\nWAVE1 0.01 -0.02\n"
            "WAVE2 -0.004 0.003\nWAVE3 0.001 0.002\n"))
        tdb = _mp_tdb(t)
        L = _lhats(model, tdb)
        delays, _, pepoch = _base_delays(model, t, tdb, L)
        om = mp.mpf(repr(float(model.WAVE_OM.value)))
        wep = mp.mpf(repr(float(model.WAVEEPOCH.value)))
        F0 = mp.mpf(repr(float(model.F0.value)))
        ab = [tuple(mp.mpf(repr(float(x)))
                    for x in getattr(model, f"WAVE{k}").value)
              for k in (1, 2, 3)]

        def extra(i, dt, delay):
            dt_day = tdb[i] - wep - delay / SECPERDAY
            base = om * dt_day
            s = mp.mpf(0)
            for k, (a, b) in enumerate(ab, start=1):
                s += a * mp.sin(k * base) + b * mp.cos(k * base)
            return s * F0

        _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch, extra),
                       label="wave")

    def test_ifunc_linear(self):
        """SIFUNC 2 linear interpolation with flat extrapolation (ref
        ifunc.py:128): phase += F0 * interp(t_bary)."""
        model, t = _fab(BASE_ECL + (
            "SIFUNC 2 0\nIFUNC1 54400 1e-4 0\nIFUNC2 55300 3e-4 0\n"
            "IFUNC3 56200 -2e-4 0\n"))
        tdb = _mp_tdb(t)
        L = _lhats(model, tdb)
        delays, _, pepoch = _base_delays(model, t, tdb, L)
        F0 = mp.mpf(repr(float(model.F0.value)))
        xs = [mp.mpf("54400"), mp.mpf("55300"), mp.mpf("56200")]
        ys = [mp.mpf("1e-4"), mp.mpf("3e-4"), mp.mpf("-2e-4")]

        def extra(i, dt, delay):
            ts = tdb[i] - delay / SECPERDAY
            if ts <= xs[0]:
                y = ys[0]
            elif ts >= xs[-1]:
                y = ys[-1]
            else:
                j = max(k for k in range(len(xs)) if xs[k] <= ts)
                frac = (ts - xs[j]) / (xs[j + 1] - xs[j])
                y = ys[j] + frac * (ys[j + 1] - ys[j])
            return y * F0

        _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch, extra),
                       label="ifunc")

    def test_piecewise_spindown(self):
        """PWF0/PWF1 range solution (ref piecewise.py:12): in
        [PWSTART, PWSTOP], phase += PWPH + dt*(PWF0 + dt*PWF1/2)."""
        model, t = _fab(BASE_ECL + (
            "PWEP_1 55300\nPWSTART_1 55000\nPWSTOP_1 55600\nPWPH_1 0.1\n"
            "PWF0_1 1e-8 1\nPWF1_1 -2e-18\n"))
        tdb = _mp_tdb(t)
        L = _lhats(model, tdb)
        delays, _, pepoch = _base_delays(model, t, tdb, L)
        ep = mp.mpf("55300")
        pwph, pwf0, pwf1 = (mp.mpf("0.1"), mp.mpf("1e-8"), mp.mpf("-2e-18"))

        def extra(i, dt, delay):
            t_mjd = tdb[i] - delay / SECPERDAY
            if not (mp.mpf("55000") <= t_mjd <= mp.mpf("55600")):
                return mp.mpf(0)
            dtp = (tdb[i] - ep) * SECPERDAY - delay
            return pwph + dtp * (pwf0 + dtp * pwf1 / 2)

        _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch, extra),
                       label="piecewise")


# ---------------------------------------------------------------------------
# chromatic / solar-wind delay rows
# ---------------------------------------------------------------------------

class TestChromaticAndSolarWind:
    def test_chromatic_cm_cmx(self):
        """CM Taylor series + CMX window offsets at nu^-TNCHROMIDX (ref
        chromatic_model.py:118,313), on the doppler-shifted frequency."""
        model, t = _fab(BASE_ECL + (
            "CM 0.02 1\nCM1 0.003\nCMEPOCH 55300\nTNCHROMIDX 4\n"
            "CMX_0001 0.01 1\nCMXR1_0001 54800\nCMXR2_0001 55500\n"))
        tdb = _mp_tdb(t)
        L = _lhats(model, tdb)
        delays, bfreq, pepoch = _base_delays(model, t, tdb, L)
        mjd_utc = np.asarray(t.get_mjds(), np.float64)
        cm0, cm1 = mp.mpf("0.02"), mp.mpf("0.003")
        cmx = mp.mpf("0.01")
        dmk = mp.mpf(repr(DMK))
        hi64 = np.asarray(t.tdb, np.float64)
        for i in range(len(t)):
            # CM Taylor is evaluated at tdb (float64 hi), not t_bary
            # (chromatic.py:78 dt_yr uses batch.tdb.hi)
            dt_yr = mp.mpf(repr(float(hi64[i]))) - mp.mpf("55300")
            dt_yr = dt_yr / mp.mpf("365.25")
            cm = cm0 + cm1 * dt_yr
            if 54800.0 <= mjd_utc[i] <= 55500.0:
                cm += cmx
            delays[i] += cm * dmk / bfreq[i]**4
        _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch),
                       label="chromatic")

    def test_swx(self):
        """SWX window solar wind (ref solar_wind_dispersion.py:608): DM =
        SWXDM * (geom - geom_opp)/(geom_conj - geom_opp), geometry from
        Hazboun et al. (2022) eq. 11 with the exact integral computed by
        mp.quad (the framework uses 64-pt Gauss-Legendre)."""
        model, t = _fab(BASE_ECL + (
            "SWXDM_0001 5e-4 1\nSWXP_0001 2.0\nSWXR1_0001 54500\n"
            "SWXR2_0001 55500\nSWXDM_0002 3e-4\nSWXP_0002 2.5\n"
            "SWXR1_0002 55500.001\nSWXR2_0002 56500\n"))
        tdb = _mp_tdb(t)
        L = _lhats(model, tdb)
        delays, bfreq, pepoch = _base_delays(model, t, tdb, L)
        sun_ls = np.asarray(t.obs_sun_pos_km) / C_KM_S
        mjd_utc = np.asarray(t.get_mjds(), np.float64)

        def geom(r, theta, p):
            b = r * mp.sin(theta)
            z = r * mp.cos(theta)
            I_inf = mp.sqrt(mp.pi) / 2 * mp.gamma((p - 1) / 2) / mp.gamma(p / 2)
            I_u = mp.quad(lambda ph: mp.cos(ph)**(p - 2),
                          [0, mp.atan(z / b)])
            return (mp.mpf(repr(AU_LS)) / b)**p * (b / mp.mpf(repr(PC_LS))) \
                * (I_inf + I_u)

        # theta0: minimum elongation from the ecliptic latitude
        # (solar_wind.py:96 _theta0, the reference's 'simplified model')
        beta = mp.mpf(repr(float(model.ELAT.value)))
        theta0 = abs(beta)
        r0 = mp.mpf(repr(AU_LS))
        wins = [(mp.mpf("5e-4"), mp.mpf(2), 54500.0, 55500.0),
                (mp.mpf("3e-4"), mp.mpf("2.5"), 55500.001, 56500.0)]
        dmk = mp.mpf(repr(DMK))
        for i in range(len(t)):
            s = [mp.mpf(float(v)) for v in sun_ls[i]]
            smag = mp.sqrt(sum(a * a for a in s))
            cost = sum(a * b for a, b in zip(s, L[i])) / smag
            theta = mp.acos(cost)
            dm = mp.mpf(0)
            for swxdm, p, r1, r2 in wins:
                if r1 <= mjd_utc[i] <= r2:
                    g = geom(smag, theta, p)
                    g_conj = geom(r0, theta0, p)
                    g_opp = geom(r0, mp.pi - theta0, p)
                    dm += swxdm * (g - g_opp) / (g_conj - g_opp)
            delays[i] += dm * dmk / bfreq[i]**2
        _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch),
                       label="SWX")


# ---------------------------------------------------------------------------
# troposphere row (real gbt site; Niell tables are published data)
# ---------------------------------------------------------------------------

# Niell (1996) hydrostatic table + height correction and Davis (1985) zenith
# delay constants, as published (same data the implementation bakes in)
_LATS = [0.0, 15.0, 30.0, 45.0, 60.0, 75.0, 90.0]
_NA = [0.0, 1.2769934e-3, 1.2683230e-3, 1.2465397e-3, 1.2196049e-3,
       1.2045996e-3, 0.0]
_NB = [0.0, 2.9153695e-3, 2.9152299e-3, 2.9288445e-3, 2.9022565e-3,
       2.9024912e-3, 0.0]
_NC = [0.0, 62.610505e-3, 62.837393e-3, 63.721774e-3, 63.824265e-3,
       64.258455e-3, 0.0]
_NA_AMP = [0.0, 0.0, 1.2709626e-5, 2.6523662e-5, 3.4000452e-5, 4.1202191e-5,
           0.0]
_NB_AMP = [0.0, 0.0, 2.1414979e-5, 3.0160779e-5, 7.2562722e-5, 11.723375e-5,
           0.0]
_NC_AMP = [0.0, 0.0, 9.0128400e-5, 4.3497037e-5, 84.795348e-5, 170.37206e-5,
           0.0]


def _interp1(x, xs, ys):
    if x <= xs[0]:
        return mp.mpf(repr(ys[0]))
    if x >= xs[-1]:
        return mp.mpf(repr(ys[-1]))
    j = max(k for k in range(len(xs)) if xs[k] <= x)
    f = (mp.mpf(repr(x)) - mp.mpf(repr(xs[j]))) / (
        mp.mpf(repr(xs[j + 1])) - mp.mpf(repr(xs[j])))
    return mp.mpf(repr(ys[j])) + f * (mp.mpf(repr(ys[j + 1]))
                                      - mp.mpf(repr(ys[j])))


def _herring(alt, a, b, c):
    se = mp.sin(alt)
    top = 1 + a / (1 + b / (1 + c))
    bot = se + a / (se + b / (se + c))
    return top / bot


class TestTroposphere:
    def test_troposphere_niell(self):
        """Davis zenith delay x Niell hydrostatic mapping at a real ground
        site (ref troposphere_delay.py:16).  The source altitude (Earth
        orientation) is a shared input — the oracle independently recomputes
        everything downstream of it: geodetic lat/height (WGS84), US-std
        pressure, zenith delay, annual Niell coefficients, Herring continued
        fraction, and the height correction."""
        model, t = _fab(BASE_ECL + "CORRECT_TROPOSPHERE Y\n", obs="gbt")
        tdb = _mp_tdb(t)
        L = _lhats(model, tdb)
        delays, _, pepoch = _base_delays(model, t, tdb, L)

        from pint_tpu.earth import itrf_to_gcrs_matrix
        from pint_tpu.observatory import get_observatory

        xyz = np.asarray(get_observatory("gbt").itrf_xyz, np.float64)
        # -- geodetic lat/height: closed Bowring iteration in mpmath -------
        a_e, f_e = mp.mpf("6378137.0"), 1 / mp.mpf("298.257223563")
        e2 = f_e * (2 - f_e)
        x, y, z = (mp.mpf(repr(float(v))) for v in xyz)
        p = mp.sqrt(x * x + y * y)
        lat = mp.atan2(z, p * (1 - e2))
        for _ in range(8):
            Nn = a_e / mp.sqrt(1 - e2 * mp.sin(lat)**2)
            h = p / mp.cos(lat) - Nn
            lat = mp.atan2(z, p * (1 - e2 * Nn / (Nn + h)))
        Nn = a_e / mp.sqrt(1 - e2 * mp.sin(lat)**2)
        h = p / mp.cos(lat) - Nn
        lon = mp.atan2(y, x)
        up = np.array([float(mp.cos(lat) * mp.cos(lon)),
                       float(mp.cos(lat) * mp.sin(lon)), float(mp.sin(lat))])

        # -- altitude: shared input (framework Earth rotation) -------------
        utc = np.asarray(t.get_mjds(), np.float64)
        R = itrf_to_gcrs_matrix(utc)
        zen = np.einsum("nij,j->ni", R, up)
        astro = model.components["AstrometryEcliptic"]
        ra, dec = astro.coords_as_ICRS()
        psr = np.array([np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra),
                        np.sin(dec)])
        alt = np.pi / 2 - np.arccos(np.clip(zen @ psr, -1.0, 1.0))

        # -- US standard atmosphere pressure -> Davis zenith delay ---------
        h_km = h / 1000
        gph = mp.mpf("6356.766") * h_km / (mp.mpf("6356.766") + h_km)
        T = mp.mpf("288.15") - mp.mpf("0.0065") * gph * 1000
        p_kpa = mp.mpf("101.325") * (mp.mpf("288.15") / T) ** mp.mpf("-5.25575")
        c_light = mp.mpf("299792458.0")
        zd = (p_kpa / mp.mpf("43.921")) / (
            c_light * (1 - mp.mpf("0.00266") * mp.cos(2 * lat)
                       - mp.mpf("0.00028") * h_km))

        abs_lat = abs(float(lat) * 180 / np.pi)
        for i in range(len(t)):
            if alt[i] < np.radians(5.0):
                continue  # zeroed by the implementation too
            yf = ((utc[i] - 28.0) % 365.25) / 365.25
            if float(lat) < 0:
                yf = (yf + 0.5) % 1.0
            cosyf = mp.cos(2 * mp.pi * mp.mpf(repr(float(yf))))
            a_c = _interp1(abs_lat, _LATS, _NA) + cosyf * _interp1(
                abs_lat, _LATS, _NA_AMP)
            b_c = _interp1(abs_lat, _LATS, _NB) + cosyf * _interp1(
                abs_lat, _LATS, _NB_AMP)
            c_c = _interp1(abs_lat, _LATS, _NC) + cosyf * _interp1(
                abs_lat, _LATS, _NC_AMP)
            altm = mp.mpf(repr(float(alt[i])))
            base = _herring(altm, a_c, b_c, c_c)
            fcorr = _herring(altm, mp.mpf("2.53e-5"), mp.mpf("5.49e-3"),
                             mp.mpf("1.14e-3"))
            hmap = base + (1 / mp.sin(altm) - fcorr) * (h_km)
            delays[i] += zd * hmap
        _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch),
                       label="troposphere")


# ---------------------------------------------------------------------------
# binary rows: reference engines as oracles (BT/DDS/DDH/DDGR/DDK/ELL1/ELL1H/ELL1k)
# ---------------------------------------------------------------------------

def _engine_delay(ref, mod_cls, pars, bary, fit_params=None, psr_pos=None,
                  obs_pos_km=None):
    import warnings

    mod_name, cls_name = mod_cls
    cls = getattr(getattr(ref, mod_name), cls_name)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        m = cls()
        m.update_input(barycentric_toa=bary, **pars)
        if fit_params is not None:
            m.fit_params = fit_params
        if psr_pos is not None:
            m.psr_pos = psr_pos
        if obs_pos_km is not None:
            m.obs_pos = _refshim.Quantity(obs_pos_km, _refshim.km)
        return np.asarray(m.binary_delay().to("second").value,
                          dtype=np.float64)


def _binary_parity(ref, par_text, mod_cls, parnames, fit_params=None,
                   ddk=False, label="", tol=2e-9):
    model, t = _fab(par_text)
    tdb = _mp_tdb(t)
    L = _lhats(model, tdb)
    delays, _, pepoch = _base_delays(model, t, tdb, L)
    bary = np.array([float(tdb[i] - delays[i] / SECPERDAY)
                     for i in range(len(t))])
    pars = {k: float(getattr(model, k).value) for k in parnames}
    kw = {}
    if ddk:
        pars["PMLONG_DDK"] = float(model.PMRA.value)
        pars["PMLAT_DDK"] = float(model.PMDEC.value)
        pars["PX"] = float(model.PX.value)
        pars["K96"] = bool(model.K96.value)
        # psr_pos exactly as the component feeds the engine: PM-propagated
        # unit vector at tdb.hi (components.py:575)
        kw["psr_pos"] = np.array([[float(c) for c in Li] for Li in L])
        kw["obs_pos_km"] = np.asarray(t.ssb_obs_pos_km, np.float64)
    bdel = _engine_delay(ref, mod_cls, pars, bary, fit_params=fit_params, **kw)
    for i in range(len(t)):
        delays[i] += mp.mpf(float(bdel[i]))
    _assert_parity(model, t, _resids(model, t, delays, tdb, pepoch),
                   label=label, tol=tol)


class TestBinaryFamilies:
    def test_ell1h(self, ref):
        """ELL1H orthometric (H3/STIGMA) Shapiro harmonics through the full
        pipeline; oracle = reference ELL1Hmodel engine."""
        _binary_parity(
            ref,
            BASE_ECL + ("BINARY ELL1H\nPB 4.07\nA1 3.37\nTASC 55250.1\n"
                        "EPS1 1.2e-5\nEPS2 -3.1e-5\nH3 2.8e-7\n"
                        "STIGMA 0.31\nNHARMS 7\n"),
            ("ELL1H_model", "ELL1Hmodel"),
            ("PB", "A1", "TASC", "EPS1", "EPS2", "H3", "STIGMA", "NHARMS"),
            fit_params=["H3", "STIGMA"], label="ELL1H")

    def test_ddgr(self, ref):
        """DDGR: PK parameters derived from (MTOT, M2) under GR; oracle =
        reference DDGRmodel engine."""
        _binary_parity(
            ref,
            BASE_ECL + ("BINARY DDGR\nPB 0.323\nA1 2.34\nECC 0.617\n"
                        "OM 226.0\nT0 55245.4\nM2 1.39\nMTOT 2.83\n"),
            ("DDGR_model", "DDGRmodel"),
            ("PB", "A1", "ECC", "OM", "T0", "M2", "MTOT"), label="DDGR")

    def test_bt(self, ref):
        """BT through the full pipeline; oracle = reference BTmodel."""
        _binary_parity(
            ref,
            BASE_ECL + ("BINARY BT\nPB 0.3\nA1 2.0\nECC 0.1\nOM 30.0\n"
                        "T0 55245.4\nGAMMA 1e-4\n"),
            ("BT_model", "BTmodel"),
            ("PB", "A1", "ECC", "OM", "T0", "GAMMA"), label="BT")

    def test_dds(self, ref):
        """DDS (SHAPMAX Shapiro parameterization); oracle = DDSmodel."""
        _binary_parity(
            ref,
            BASE_ECL + ("BINARY DDS\nPB 8.7\nA1 14.0\nECC 0.18\nOM 310.0\n"
                        "T0 55245.4\nM2 1.0\nSHAPMAX 3.5\nGAMMA 1e-3\n"),
            ("DDS_model", "DDSmodel"),
            ("PB", "A1", "ECC", "OM", "T0", "M2", "SHAPMAX", "GAMMA"),
            label="DDS")

    def test_ddh(self, ref):
        """DDH (orthometric H3/STIGMA in a DD orbit); oracle = DDHmodel."""
        _binary_parity(
            ref,
            BASE_ECL + ("BINARY DDH\nPB 5.0\nA1 9.0\nECC 0.4\nOM 77.0\n"
                        "T0 55245.4\nH3 4e-7\nSTIGMA 0.3\n"),
            ("DDH_model", "DDHmodel"),
            ("PB", "A1", "ECC", "OM", "T0", "H3", "STIGMA"), label="DDH")

    def test_ell1(self, ref):
        """ELL1 small-eccentricity model; oracle = ELL1model."""
        _binary_parity(
            ref,
            BASE_ECL + ("BINARY ELL1\nPB 12.3\nA1 21.0\nTASC 55245.4\n"
                        "EPS1 4e-4\nEPS2 3e-4\nM2 0.25\nSINI 0.97\n"),
            ("ELL1_model", "ELL1model"),
            ("PB", "A1", "TASC", "EPS1", "EPS2", "M2", "SINI"),
            label="ELL1")

    def test_ell1k(self, ref):
        """ELL1k (periastron advance + eccentricity evolution); oracle =
        ELL1kmodel."""
        _binary_parity(
            ref,
            BASE_ECL + ("BINARY ELL1k\nPB 0.3\nA1 2.0\nTASC 55245.4\n"
                        "EPS1 1e-4\nEPS2 -2e-4\nOMDOT 10.0\nLNEDOT 1e-10\n"),
            ("ELL1k_model", "ELL1kmodel"),
            ("PB", "A1", "TASC", "EPS1", "EPS2", "OMDOT", "LNEDOT"),
            label="ELL1k")

    def test_ddk(self, ref):
        """DDK Kopeikin annual/secular parallax + proper-motion terms
        (K96), equatorial astrometry; oracle = reference DDKmodel engine
        fed the same PM-propagated psr_pos and fabricated observatory
        positions the component uses."""
        _binary_parity(
            ref,
            BASE_EQ + ("BINARY DDK\nPB 8.634\nA1 11.7\nECC 0.249\n"
                       "OM 110.8\nT0 55245.4\nM2 0.35\nKIN 71.3\n"
                       "KOM 42.0\nK96 1\n"),
            ("DDK_model", "DDKmodel"),
            ("PB", "A1", "ECC", "OM", "T0", "M2", "KIN", "KOM"),
            ddk=True, label="DDK")
