"""Independent oracle for the correlated-noise (GLS/Woodbury) chi2.

VERDICT r3 #4: the Woodbury chi2 + logdet path (reference
``residuals.py:584,608`` -> ``utils.py:3069 woodbury_dot``) was previously
validated only self-consistently (grid-vs-fitter).  Here a clean-room
oracle builds the DENSE TOA covariance

    C = diag(Nvec) + U_ecorr W U_ecorr^T + F phi F^T + w_off * 1 1^T

entirely from published formulas in 40-digit mpmath — white-noise scaling
(sigma' = EFAC * sqrt(sigma^2 + EQUAD^2)), ECORR epoch grouping (TOAs
within 1 s of the group start, >= 2 members), the Fourier GP basis
(sin/cos pairs at k/Tspan) with the enterprise power-law PSD
(A^2/(12 pi^2) fyr^(gamma-3) f^-gamma * df), and the marginalized phase
offset — then evaluates r^T C^-1 r and logdet C by dense LU.  The
framework must match through its Woodbury path at ~1e-9 relative.

The wideband combined chi2 (reference ``residuals.py:1240``) is covered
the same way: the stacked system separates into the TOA GLS chi2 plus the
diagonal DM chi2, both recomputed independently.
"""

import numpy as np
import pytest

mp = pytest.importorskip("mpmath")
# C spans ~22 decades (1e10 offset block against ~1e-12 s^2 white noise);
# 70 digits keeps the dense LU comfortably nonsingular.  mp.mp.dps is a
# GLOBAL other test modules also set at import time (test_pipeline_oracle
# uses 40), so the precision is scoped per-call with mp.workdps instead.
ORACLE_DPS = 70

NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
DAY_S = 86400.0
FYR = 1.0 / (365.25 * DAY_S)

# two disjoint mjd ranges with their own white-noise parameters, so the
# oracle can recompute every mask straight from the epochs
R1 = (52000.0, 53900.0)
R2 = (53900.0, 60000.0)
NOISE_LINES = [
    f"EFAC mjd {R1[0]:.0f} {R1[1]:.0f} 1.3 1",
    f"EQUAD mjd {R1[0]:.0f} {R1[1]:.0f} 2.0 1",
    f"EFAC mjd {R2[0]:.0f} {R2[1]:.0f} 0.9 1",
    f"EQUAD mjd {R2[0]:.0f} {R2[1]:.0f} 0.7 1",
    f"ECORR mjd {R1[0]:.0f} {R2[1]:.0f} 3.0 1",
    "TNREDAMP -12.6", "TNREDGAM 3.1", "TNREDC 5",
]


def _model_with_lines(extra_lines):
    from pint_tpu.io.par import parse_parfile
    from pint_tpu.models import get_model

    with open(NGC_PAR) as f:
        text = f.read()
    return get_model(parse_parfile(text + "\n" + "\n".join(extra_lines) + "\n"))


@pytest.fixture(scope="module")
def dataset():
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    m = _model_with_lines(NOISE_LINES)
    epochs = np.linspace(53005.0, 54795.0, 20)
    mjds = (epochs[:, None] + np.arange(3)[None, :] * 0.4 / 86400.0).ravel()
    t = make_fake_toas_fromMJDs(mjds, m, error_us=2.0, add_noise=True,
                                add_correlated_noise=True,
                                rng=np.random.default_rng(31))
    return m, t


def _oracle_cov(model, toas):
    """Dense covariance in mpmath, every term from first principles."""
    with mp.workdps(ORACLE_DPS):
        return _oracle_cov_inner(model, toas)


def _oracle_cov_inner(model, toas):
    n = len(toas)
    mjd = np.asarray(toas.get_mjds(), dtype=np.float64)
    raw_s = np.asarray(toas.get_errors(), dtype=np.float64) * 1e-6
    t_s = np.asarray(toas.tdb, dtype=np.float64) * DAY_S

    # white scaling: sigma' = EFAC * sqrt(sigma^2 + EQUAD^2) per mjd range
    var = []
    for i in range(n):
        if R1[0] <= mjd[i] <= R1[1]:
            efac, equad = 1.3, 2.0e-6
        else:
            efac, equad = 0.9, 0.7e-6
        var.append(mp.mpf(efac) ** 2
                   * (mp.mpf(raw_s[i]) ** 2 + mp.mpf(equad) ** 2))

    C = mp.zeros(n)
    for i in range(n):
        C[i, i] = var[i]

    # ECORR: group by "within 1 s of the group start", keep >=2 members
    order = np.argsort(t_s)
    groups, cur = [], [int(order[0])]
    ref = t_s[order[0]]
    for i in order[1:]:
        if t_s[i] - ref < 1.0:
            cur.append(int(i))
        else:
            groups.append(cur)
            cur, ref = [int(i)], t_s[i]
    groups.append(cur)
    w_ec = mp.mpf(3.0e-6) ** 2
    for g in groups:
        if len(g) < 2:
            continue
        for i in g:
            for j in g:
                C[i, j] += w_ec

    # power-law red noise: sin/cos pairs at f_k = k/Tspan,
    # phi = A^2/(12 pi^2) fyr^(gamma-3) f^-gamma * df per column
    amp = mp.mpf(10.0) ** mp.mpf(-12.6)
    gam = mp.mpf(3.1)
    Tspan = mp.mpf(float(t_s.max() - t_s.min()))
    nmodes = 5
    fs = [mp.mpf(k) / Tspan for k in range(1, nmodes + 1)]
    dfs = [fs[0]] + [fs[k] - fs[k - 1] for k in range(1, nmodes)]
    fyr = mp.mpf(repr(FYR))
    cols, phis = [], []
    for k in range(nmodes):
        arg = [2 * mp.pi * mp.mpf(float(ts)) * fs[k] for ts in t_s]
        cols.append([mp.sin(a) for a in arg])
        cols.append([mp.cos(a) for a in arg])
        pk = amp**2 / 12 / mp.pi**2 * fyr**(gam - 3) * fs[k]**(-gam) * dfs[k]
        phis += [pk, pk]
    for c, pk in zip(cols, phis):
        for i in range(n):
            ci = c[i] * pk
            for j in range(n):
                C[i, j] += ci * c[j]

    # marginalized overall offset: the oracle must add the SAME improper
    # prior variance the framework marginalizes with — the lnlikelihood
    # carries an additive log(weight)/2 normalization constant, so the
    # value is part of the definition being checked, not a free choice
    from pint_tpu.models.timing_model import OFFSET_PRIOR_WEIGHT

    big = mp.mpf(repr(OFFSET_PRIOR_WEIGHT))
    for i in range(n):
        for j in range(n):
            C[i, j] += big
    return C


def _dense_chi2_logdet(C, r):
    with mp.workdps(ORACLE_DPS):
        n = len(r)
        rv = mp.matrix([mp.mpf(float(x)) for x in r])
        x = mp.lu_solve(C, rv)
        chi2 = sum(rv[i] * x[i] for i in range(n))
        # logdet via LU (mp.det underflows fixed-precision floats less
        # gracefully; LU diagonal keeps it in log space)
        P, L, U = mp.lu(C)
        logdet = sum(mp.log(abs(U[i, i])) for i in range(n))
        return chi2, logdet


class TestGLSOracle:
    def test_woodbury_chi2_matches_dense_oracle(self, dataset):
        from pint_tpu.residuals import Residuals

        m, t = dataset
        res = Residuals(t, m)
        r = np.asarray(res.time_resids)
        C = _oracle_cov(m, t)
        chi2_o, logdet_o = _dense_chi2_logdet(C, r)
        chi2_fw = res.calc_chi2()
        assert abs(chi2_fw - float(chi2_o)) < 1e-9 * float(chi2_o), \
            (chi2_fw, float(chi2_o))

    def test_lnlikelihood_matches_dense_oracle(self, dataset):
        from pint_tpu.residuals import Residuals

        m, t = dataset
        res = Residuals(t, m)
        r = np.asarray(res.time_resids)
        C = _oracle_cov(m, t)
        chi2_o, logdet_o = _dense_chi2_logdet(C, r)
        n = len(t)
        lnl_o = -(chi2_o / 2 + logdet_o / 2 + n * mp.log(2 * mp.pi) / 2)
        lnl_fw = res.lnlikelihood()
        assert abs(lnl_fw - float(lnl_o)) < 1e-9 * abs(float(lnl_o)), \
            (lnl_fw, float(lnl_o))

    def test_noisefit_lnlike_matches_dense_oracle(self, dataset):
        """The jitted noise likelihood (autodiff path) against the same
        dense oracle, at the current parameter values."""
        import copy

        from pint_tpu.noisefit import build_noise_lnlikelihood
        from pint_tpu.residuals import Residuals

        m, t = dataset
        m2 = copy.deepcopy(m)
        for p in ("EFAC1", "EQUAD1", "ECORR1"):
            getattr(m2, p).frozen = False
        res = Residuals(t, m2)
        r = np.asarray(res.time_resids)
        lnl, x0, names = build_noise_lnlikelihood(m2, t)
        C = _oracle_cov(m2, t)
        chi2_o, logdet_o = _dense_chi2_logdet(C, r)
        n = len(t)
        lnl_o = float(-(chi2_o / 2 + logdet_o / 2 + n * mp.log(2 * mp.pi) / 2))
        assert abs(float(lnl(x0, r)) - lnl_o) < 1e-9 * abs(lnl_o)


class TestWidebandOracle:
    def test_combined_chi2_matches_oracle(self, dataset):
        """Wideband combined chi2 = TOA GLS chi2 (dense oracle) + diagonal
        DM chi2 (reference ``residuals.py:1240`` separation)."""
        from pint_tpu.wideband import WidebandTOAResiduals

        m, t = dataset
        rng = np.random.default_rng(5)
        dm_model = float(m.DM.value)
        dme = np.full(len(t), 1e-3)
        dms = dm_model + rng.standard_normal(len(t)) * dme
        t.update_dms(dms, dme)
        wr = WidebandTOAResiduals(t, m)
        chi2_fw = wr.calc_chi2()
        r = np.asarray(wr.toa.time_resids)
        C = _oracle_cov(m, t)
        chi2_toa_o, _ = _dense_chi2_logdet(C, r)
        # DM residuals: measured - model DM against the measurement errors
        chi2_dm_o = float(np.sum(((dms - dm_model) / dme) ** 2))
        total_o = float(chi2_toa_o) + chi2_dm_o
        assert abs(chi2_fw - total_o) < 1e-9 * total_o, (chi2_fw, total_o)
