"""ECORR-averaged residuals + DMX tooling (VERDICT r2 directive #8).

Reference: ``residuals.py:859 ecorr_average``, ``utils.py:778 dmx_ranges``,
``utils.py:1075 dmxparse``.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def ecorr_fit():
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_fromMJDs

    par = [
        "PSR TESTECORR\n", "RAJ 06:30:00 1\n", "DECJ -05:00:00 1\n",
        "F0 250.0123456 1\n", "F1 -3e-15 1\n", "PEPOCH 55500\n",
        "DM 21.0 1\n",
        "EFAC mjd 50000 59000 1.2\n",
        "ECORR mjd 50000 59000 0.9\n",
        "TNRedAmp -13.8\n", "TNRedGam 2.9\n", "TNRedC 8\n",
        "UNITS TDB\n",
    ]
    model = get_model(par)
    rng = np.random.default_rng(5)
    # clustered epochs: 20 epochs x 3 TOAs within 1 s (the ECORR
    # quantization threshold) => 20 ECORR segments
    base = np.linspace(55000, 55900, 20)
    mjds = np.sort(np.concatenate([base, base + 0.3 / 86400.0,
                                   base + 0.7 / 86400.0]))
    freqs = np.resize([430.0, 1410.0, 1410.0], len(mjds))
    toas = make_fake_toas_fromMJDs(mjds, model, freq=freqs, error_us=1.0,
                                   add_noise=True, rng=rng)
    f = GLSFitter(toas, model)
    f.fit_toas(maxiter=2)
    return f


class TestEcorrAverage:
    def test_segments_and_weighted_average(self, ecorr_fit):
        f = ecorr_fit
        avg = f.resids.ecorr_average()
        n_seg = len(avg["mjds"])
        assert n_seg == 20
        # manual check of one segment: weighted average with scaled errors
        idx = avg["indices"][3]
        assert len(idx) == 3
        err = np.asarray(f.model.scaled_toa_uncertainty(f.toas))[idx]
        w = 1.0 / err**2
        r = np.asarray(f.resids.time_resids)[idx]
        assert avg["time_resids"][3] == pytest.approx(np.sum(w * r) / np.sum(w),
                                                      rel=1e-12)
        # errors include the ECORR variance: bigger than pure white average
        white = np.sqrt(1.0 / np.sum(w))
        assert avg["errors"][3] > white
        # raw-error variant drops ECORR
        avg0 = f.resids.ecorr_average(use_noise_model=False)
        assert np.all(avg0["errors"] <= avg["errors"])

    def test_noise_resids_projected(self, ecorr_fit):
        f = ecorr_fit
        nr = f.resids.noise_resids()
        assert set(nr) == {"EcorrNoise", "PLRedNoise"}
        for v in nr.values():
            assert v.shape == (len(f.toas),)
            assert np.all(np.isfinite(v))
        avg = f.resids.ecorr_average()
        assert set(avg["noise_resids"]) == set(nr)

    def test_requires_ecorr(self):
        from pint_tpu.models import get_model
        from pint_tpu.residuals import Residuals
        from pint_tpu.simulation import make_fake_toas_uniform

        par = ["PSR X\n", "RAJ 01:00:00\n", "DECJ 10:00:00\n",
               "F0 100.0 1\n", "PEPOCH 55000\n", "DM 10\n", "UNITS TDB\n"]
        m = get_model(par)
        t = make_fake_toas_uniform(54000, 56000, 10, m, error_us=1.0)
        with pytest.raises(ValueError, match="ECORR"):
            Residuals(t, m).ecorr_average()


class TestDMXTools:
    def test_dmx_ranges_bins(self):
        from pint_tpu.dmx import dmx_ranges
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        par = ["PSR Y\n", "RAJ 02:00:00\n", "DECJ 20:00:00\n",
               "F0 150.0 1\n", "PEPOCH 55200\n", "DM 15\n", "UNITS TDB\n"]
        m = get_model(par)
        # 6 observing epochs, each with a low- and a high-frequency TOA
        base = np.linspace(55000, 55400, 6)
        mjds = np.sort(np.concatenate([base, base + 0.3]))
        freqs = np.resize([430.0, 1410.0], len(mjds))
        t = make_fake_toas_fromMJDs(mjds, m, freq=freqs, error_us=1.0)
        mask, comp = dmx_ranges(t, divide_freq=1000.0, binwidth=15.0)
        assert mask.all()  # every epoch has both bands -> all covered
        assert comp.dmx_indices == list(range(1, 7))
        for i in comp.dmx_indices:
            r1 = getattr(comp, f"DMXR1_{i:04d}").value
            r2 = getattr(comp, f"DMXR2_{i:04d}").value
            assert r2 > r1
            inbin = (mjds >= r1) & (mjds <= r2)
            assert np.any(freqs[inbin] < 1000) and np.any(freqs[inbin] >= 1000)

    def test_dmx_ranges_skips_single_band_epochs(self):
        from pint_tpu.dmx import dmx_ranges
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        par = ["PSR Z\n", "RAJ 03:00:00\n", "DECJ -10:00:00\n",
               "F0 120.0 1\n", "PEPOCH 55200\n", "DM 11\n", "UNITS TDB\n"]
        m = get_model(par)
        mjds = np.array([55000.0, 55000.2, 55100.0, 55100.1])
        freqs = np.array([430.0, 1410.0, 1410.0, 1420.0])  # 2nd epoch hi-only
        t = make_fake_toas_fromMJDs(mjds, m, freq=freqs, error_us=1.0)
        mask, comp = dmx_ranges(t, divide_freq=1000.0, binwidth=15.0)
        assert comp.dmx_indices == [1]
        assert mask.tolist() == [True, True, False, False]

    def test_dmxparse_covariance_projection(self, tmp_path):
        """dmxparse on a fitted DMX model: mean-subtracted values, projected
        variance errors, TEMPO-format save file."""
        from pint_tpu.dmx import dmxparse
        from pint_tpu.fitter import WLSFitter
        from pint_tpu.models import get_model
        from pint_tpu.simulation import make_fake_toas_fromMJDs

        par = [
            "PSR W\n", "RAJ 04:00:00 1\n", "DECJ 25:00:00 1\n",
            "F0 180.0 1\n", "F1 -2e-15 1\n", "PEPOCH 55250\n", "DM 18 1\n",
            "DMX_0001 0.001 1\n", "DMXR1_0001 54990\n", "DMXR2_0001 55010\n",
            "DMX_0002 -0.002 1\n", "DMXR1_0002 55490\n", "DMXR2_0002 55510\n",
            "UNITS TDB\n",
        ]
        m = get_model(par)
        base = np.array([55000.0, 55000.1, 55000.2, 55500.0, 55500.1, 55500.2])
        mjds = np.concatenate([base, base + 0.01])
        freqs = np.resize([430.0, 1410.0], len(mjds))
        t = make_fake_toas_fromMJDs(np.sort(mjds), m, freq=freqs,
                                    error_us=1.0, add_noise=True,
                                    rng=np.random.default_rng(9))
        f = WLSFitter(t, m)
        f.fit_toas(maxiter=3)
        out = dmxparse(f, save=str(tmp_path / "dmxparse.out"))
        assert out["bins"] == ["DMX_0001", "DMX_0002"]
        assert out["dmxs"] == pytest.approx(
            np.array([float(f.model.DMX_0001.value),
                      float(f.model.DMX_0002.value)]) - out["mean_dmx"])
        assert np.all(np.isfinite(out["dmx_verrs"]))
        assert np.all(out["dmx_verrs"] > 0)
        text = (tmp_path / "dmxparse.out").read_text()
        assert "Mean DMX value" in text and "DMX_0002" in text
