"""CI wiring for the typed-raises AST lint (tools/check_typed_raises.py):
the ingestion/fitting core must raise only PintError subclasses — a bare
``raise ValueError`` regression in io/toa/fitter/gls_fitter/residuals
fails the suite, not just a style check."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_linter():
    spec = importlib.util.spec_from_file_location(
        "check_typed_raises",
        os.path.join(REPO, "tools", "check_typed_raises.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTypedRaisesLint:
    def test_core_modules_raise_only_typed(self):
        linter = _load_linter()
        violations = linter.run()
        assert violations == [], "\n".join(violations)

    def test_lint_actually_fires(self, tmp_path):
        """The lint is not vacuous: a planted bare ValueError is caught,
        and a typed raise plus a re-raise are not."""
        linter = _load_linter()
        bad = tmp_path / "planted.py"
        bad.write_text(
            "def f():\n"
            "    raise ValueError('bare')\n"
            "def g():\n"
            "    raise RuntimeError('also bare')\n"
            "def h():\n"
            "    from pint_tpu.exceptions import PintFileError\n"
            "    try:\n"
            "        raise PintFileError('typed')\n"
            "    except PintFileError as e:\n"
            "        raise e\n")
        allowed = linter._pint_exception_names()
        findings = linter.check_file(str(bad), allowed)
        msgs = [m for _, m in findings]
        assert len(findings) == 2
        assert any("ValueError" in m for m in msgs)
        assert any("RuntimeError" in m for m in msgs)
