"""Work-per-byte execution plans under test (ISSUE 14).

The three contracts of the scaling fix:

* **scattered Gram** — the TOA-sharded normal-equation build compiles
  to a real ``reduce-scatter`` (and ZERO full-Gram ``all-reduce``) and
  matches the host build to 1e-9, zero-weight padding included;
* **fused dispatch** — the scan-fused executables retire K chunks /
  steps per dispatch (dispatch counters), reach zero steady-state
  recompiles, and agree with their unfused siblings;
* **elastic compatibility** — a fused sweep still degrades 8->4 and
  resumes from :class:`SweepCheckpoint` with results matching an
  unfaulted run to 1e-7.
"""

import numpy as np
import pytest

pytestmark = [pytest.mark.distview]


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

_NOISE_PAR = """\
PSR WPB
RAJ 05:00:00
DECJ 20:00:00
F0 100.0 1
F1 -1e-15 1
PEPOCH 55000
DM 10.0 1
EFAC mjd 50000 60000 1.1
ECORR mjd 50000 60000 0.5
TNRedAmp -13.5
TNRedGam 3.5
TNRedC 3
UNITS TDB
"""


def _gls_fitter(ntoas=46, seed=3):
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    m = get_model([ln + "\n" for ln in _NOISE_PAR.splitlines()])
    t = make_fake_toas_uniform(53400, 54800, 2 * (ntoas // 2), m,
                               freq=np.array([1400.0, 2300.0]),
                               error_us=1.0, add_noise=True,
                               rng=np.random.default_rng(seed))
    f = GLSFitter(t, m)
    f.update_resids()
    return f


# ---------------------------------------------------------------------------
# scattered Gram: exactness + the HLO collective contract
# ---------------------------------------------------------------------------

class TestScatteredGram:
    def test_scattered_matches_host_build(self, eight_devices):
        """Zero-weight-padded scattered build == host build to 1e-9,
        at a ragged TOA count (46 over 8 shards: padding exercised on
        both the row and the Gram-column axis)."""
        from pint_tpu.gls_fitter import (build_augmented_system,
                                         gls_normal_equations)
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.runtime.workperbyte import (
            scattered_normal_equations)

        f = _gls_fitter()
        r = np.asarray(f.resids.time_resids)
        M, _, _, phiinv, Nvec, _ = build_augmented_system(f.model, f.toas)
        host_m, host_y = gls_normal_equations(M, r, Nvec=Nvec,
                                              phiinv=phiinv)
        plan = select_plan("gls_normal_eq", devices=eight_devices,
                           n_items=len(f.toas))
        assert plan.rung == 8
        for row_chunks in (1, 4):
            mtcm, mtcy = scattered_normal_equations(
                M, r, Nvec, phiinv, plan, row_chunks=row_chunks)
            scale = max(1.0, np.abs(host_m).max())
            assert np.abs(mtcm - host_m).max() / scale < 1e-9
            assert np.abs(mtcy - host_y).max() \
                / max(1.0, np.abs(host_y).max()) < 1e-9

    def test_scatter_contract_reduce_scatter_no_allreduce(
            self, eight_devices):
        """ISSUE 14 acceptance (a): the compiled scattered-Gram HLO
        contains reduce-scatter and ZERO full-Gram all-reduces."""
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.runtime.workperbyte import verify_scatter_contract

        f = _gls_fitter()
        plan = select_plan("gls_normal_eq", devices=eight_devices,
                           n_items=len(f.toas))
        fn, args = f.gls_normal_equations_executable(plan=plan)
        prof, violations = verify_scatter_contract(fn, *args)
        assert violations == []
        rs = prof.ops.get("reduce-scatter")
        assert rs is not None and rs["count"] >= 1 and rs["bytes"] > 0
        assert "all-reduce" not in prof.ops
        assert prof.mesh_axes == {"toa": 8}
        # and the executable actually runs to a finite system
        mtcm, mtcy = fn(*args)
        assert np.all(np.isfinite(np.asarray(mtcm)))
        assert np.all(np.isfinite(np.asarray(mtcy)))

    def test_row_chunked_scatter_keeps_contract(self, eight_devices):
        """The row-chunked (scan-of-scatters) form — the structure XLA
        can bracket in async reduce-scatter-start/done pairs — still
        satisfies the contract: distview folds async spellings into the
        base kind, and no all-reduce appears."""
        from pint_tpu.gls_fitter import build_augmented_system
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.runtime.workperbyte import (
            scattered_gram_operands, scattered_normal_equations_fn,
            verify_scatter_contract)

        f = _gls_fitter(ntoas=64)
        r = np.asarray(f.resids.time_resids)
        M, _, _, phiinv, Nvec, _ = build_augmented_system(f.model, f.toas)
        plan = select_plan("gls_normal_eq", devices=eight_devices,
                           n_items=len(f.toas))
        fn = scattered_normal_equations_fn(plan.mesh, row_chunks=4)
        args, _ = scattered_gram_operands(M, r, Nvec, phiinv, plan.mesh,
                                          row_chunks=4)
        prof, violations = verify_scatter_contract(
            fn, *args, name="gls.scattered_gram.chunked")
        assert violations == []
        assert prof.ops["reduce-scatter"]["count"] >= 1

    def test_legacy_allreduce_build_violates_contract(
            self, eight_devices):
        """The contract check CONVICTS the legacy all-reduce build (the
        SCALING_r06 shape) — strict mode raises the typed error."""
        from pint_tpu.exceptions import CollectiveContractError
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.runtime.workperbyte import verify_scatter_contract

        f = _gls_fitter()
        plan = select_plan("gls_normal_eq", devices=eight_devices,
                           n_items=len(f.toas))
        fn, args = f.gls_normal_equations_executable(plan=plan,
                                                     scatter=False)
        prof, violations = verify_scatter_contract(fn, *args)
        assert violations and "all-reduce" in " ".join(violations)
        with pytest.raises(CollectiveContractError) as ei:
            verify_scatter_contract(fn, *args, strict=True)
        assert ei.value.violations

    def test_executable_pads_instead_of_trims(self, eight_devices):
        """ISSUE 14 satellite: the analyzed sharded executable computes
        the SAME system as the unsharded build — zero-weight pad rows,
        never a trim that silently drops TOAs from the solve.  Pinned
        for both the scattered and the legacy form at a TOA count that
        does NOT divide the shard count."""
        import jax
        from jax.sharding import Mesh

        f = _gls_fitter(ntoas=46)       # 46 % 8 == 6: trim would drop 6
        fn0, args0 = f.gls_normal_equations_executable()
        ref_m, ref_y = (np.asarray(a) for a in fn0(*args0))
        mesh = Mesh(np.array(eight_devices), ("toa",))
        for scatter in (True, False):
            fn, args = f.gls_normal_equations_executable(
                mesh=mesh, scatter=scatter)
            mtcm, mtcy = (np.asarray(a) for a in fn(*args))
            k = ref_m.shape[0]
            scale = max(1.0, np.abs(ref_m).max())
            assert np.abs(mtcm[:k, :k] - ref_m).max() / scale < 1e-9, \
                f"scatter={scatter} dropped TOAs from the solve"
            assert np.abs(mtcy[:k] - ref_y).max() \
                / max(1.0, np.abs(ref_y).max()) < 1e-9


# ---------------------------------------------------------------------------
# fused dispatch: one executable per K chunks / steps
# ---------------------------------------------------------------------------

class TestFusedDispatch:
    def _padded_batch(self, lanes=2, n=64, k=8, seed=0):
        from pint_tpu.serving.batcher import FitRequest, pad_request

        rng = np.random.default_rng(seed)
        ops = []
        for i in range(lanes):
            req = FitRequest(M=rng.normal(size=(48, 7)),
                             r=rng.normal(size=48) * 1e-6,
                             w=rng.uniform(0.5, 2.0, size=48) * 1e12,
                             phiinv=np.zeros(7))
            ops.append(pad_request(req, n, k))
        return tuple(np.stack([o[i] for o in ops]) for i in range(5))

    def test_fused_step0_matches_serve_kernel(self):
        from pint_tpu.serving.batcher import serve_batched, serve_fused

        operands = self._padded_batch()
        base = [np.asarray(o) for o in serve_batched()(*operands)]
        dxs, err, chi2s, chi2i = (np.asarray(o) for o in
                                  serve_fused(steps=3)(*operands))
        assert np.abs(dxs[:, 0, :] - base[0]).max() \
            / max(np.abs(base[0]).max(), 1e-30) < 1e-9
        assert np.allclose(err, base[1], rtol=1e-9)
        assert np.allclose(chi2s[:, 0], base[2], rtol=1e-9)
        assert np.allclose(chi2i, base[3], rtol=1e-12)

    def test_fused_equals_sequential_refinement(self):
        """K fused steps == K single-step dispatches carrying residuals
        by hand (the dispatch-fusion exactness contract)."""
        from pint_tpu.serving.batcher import serve_fused

        operands = self._padded_batch()
        K = 4
        chi2s = np.asarray(serve_fused(steps=K)(*operands)[2])
        M, r, w, phiinv, padf = (np.asarray(o) for o in operands)
        single = serve_fused(steps=1)
        rc, seq = r.copy(), []
        for _ in range(K):
            d1, _, c1, _ = (np.asarray(o) for o in
                            single(M, rc, w, phiinv, padf))
            seq.append(c1[:, 0])
            rc = rc - np.einsum("bnk,bk->bn", M, d1[:, 0, :])
        assert np.abs(np.stack(seq, axis=1) - chi2s).max() \
            / max(chi2s.max(), 1e-30) < 1e-9

    def test_huber_reweighted_steps_finite_and_weighted(self):
        """The robust variant runs, stays finite, and actually
        down-weights an outlier-poisoned lane (its robust chi2 falls
        below the plain step-0 chi2)."""
        from pint_tpu.serving.batcher import serve_fused

        operands = list(self._padded_batch())
        r = operands[1].copy()
        r[0, 5] *= 1e3                          # one gross outlier
        operands[1] = r
        dxs, err, chi2s, chi2i = (
            np.asarray(o) for o in
            serve_fused(steps=4, reweight="huber")(*tuple(operands)))
        assert np.all(np.isfinite(chi2s))
        plain = np.asarray(serve_fused(steps=1)(*tuple(operands))[2])
        assert chi2s[0, -1] < plain[0, 0]

    def test_grid_fused_dispatches_once_per_k_chunks(self):
        """ISSUE 14 acceptance (b): the fused-scan grid path reduces
        dispatches >= K-fold at identical results, with zero
        steady-state recompiles on the repeat call."""
        from pint_tpu.grid import build_grid_chi2_fn
        from pint_tpu.telemetry import jaxevents

        f = _gls_fitter(ntoas=32)
        f.fit_toas(maxiter=1)
        g0 = np.linspace(f.model.F0.value - 3e-11,
                         f.model.F0.value + 3e-11, 4)
        g1 = np.linspace(f.model.F1.value - 3e-18,
                         f.model.F1.value + 3e-18, 4)
        pts = np.stack([g.ravel() for g in
                        np.meshgrid(g0, g1, indexing="ij")], axis=-1)
        fn, _, _ = build_grid_chi2_fn(f.model, f.toas, ("F0", "F1"),
                                      niter=2, chunk=4)
        c_plain, _, _ = fn(pts)
        assert fn.dispatch_count() == 4          # 16 points / chunk 4
        jaxevents.install()
        c_fused, _, _ = fn.fused(pts, fuse=4)
        assert fn.dispatch_count() == 1          # 4 chunks / fuse 4
        before = jaxevents.counts()
        c_fused2, _, _ = fn.fused(pts, fuse=4)
        assert (jaxevents.counts() - before).compiles == 0
        scale = max(1.0, np.abs(c_plain).max())
        assert np.abs(c_plain - c_fused).max() / scale < 1e-7
        assert np.abs(c_fused - c_fused2).max() == 0.0

    def test_catalog_refine_dispatches_once_per_bucket(self):
        from pint_tpu.catalog import CatalogFitter, ingest_catalog
        from pint_tpu.catalog.ingest import make_synthetic_catalog
        from pint_tpu.telemetry import jaxevents

        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=4, seed=7, ntoa_range=(24, 48)))
        cf = CatalogFitter(report)
        res = cf.refine(steps=5)
        assert res.dispatches == res.n_buckets
        assert res.steps == 5
        assert len(res.chi2_steps) == 4
        for traj in res.chi2_steps.values():
            assert traj.shape == (5,) and np.all(np.isfinite(traj))
        # steady state: a repeat refine pays zero fresh compiles
        jaxevents.install()
        before = jaxevents.counts()
        res2 = cf.refine(steps=5)
        assert (jaxevents.counts() - before).compiles == 0
        assert res2.dispatches == res.n_buckets

    def test_catalog_refine_step0_matches_fit_step(self):
        """reweight=None step 0 IS the batched fit's linearized step:
        the refine dpars agree with CatalogFitter.fit's dpars to 1e-9
        (same state, same kernel, solve via the factored inverse)."""
        from pint_tpu.catalog import CatalogFitter, ingest_catalog
        from pint_tpu.catalog.ingest import make_synthetic_catalog

        report = ingest_catalog(make_synthetic_catalog(
            n_pulsars=3, seed=9, ntoa_range=(24, 48)))
        ref = CatalogFitter(report).refine(steps=2)
        report2 = ingest_catalog(make_synthetic_catalog(
            n_pulsars=3, seed=9, ntoa_range=(24, 48)))
        fit = CatalogFitter(report2).fit(maxiter=1)
        for pf in fit.fits:
            mine = ref.dpars_first[pf.name]
            for par, step in pf.dpars.items():
                assert abs(mine[par] - step) \
                    <= 1e-9 * max(1.0, abs(step)), (pf.name, par)


# ---------------------------------------------------------------------------
# elastic compatibility: fused sweeps degrade and resume
# ---------------------------------------------------------------------------

class TestElasticFused:
    def _grid_setup(self):
        f = _gls_fitter(ntoas=32, seed=5)
        f.fit_toas(maxiter=1)
        g0 = np.linspace(f.model.F0.value - 3e-11,
                         f.model.F0.value + 3e-11, 4)
        g1 = np.linspace(f.model.F1.value - 3e-18,
                         f.model.F1.value + 3e-18, 4)
        return f, ("F0", "F1"), (g0, g1)

    def test_fused_elastic_degrades_and_matches_unfaulted(
            self, eight_devices, tmp_path, monkeypatch):
        """ISSUE 14 acceptance (c): a device lost mid-fused-sweep
        degrades 8->4 and the resumed scanned sweep matches the
        unfaulted surface to 1e-7."""
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime import elastic
        from pint_tpu.runtime.faultinject import SimulatedDeviceLoss
        from pint_tpu.runtime.plan import select_plan

        f, params, axes = self._grid_setup()
        plan = select_plan("grid", devices=eight_devices)
        clean, _ = grid_chisq(f, params, axes, niter=2, chunk=4,
                              plan=plan,
                              checkpoint=str(tmp_path / "clean"),
                              fuse=2)
        rep = f.last_elastic_report
        assert rep.chunks_computed == 4
        assert rep.steady_state_recompiles == 0

        state = {"calls": 0}
        orig = elastic._invoke_fused

        def failing(eval_fn, blocks, group, plan_):
            state["calls"] += 1
            if state["calls"] == 1:
                raise SimulatedDeviceLoss(
                    "injected: device lost in fused dispatch",
                    device_id=int(plan_.devices[1].id))
            return orig(eval_fn, blocks, group, plan_)

        monkeypatch.setattr(elastic, "_invoke_fused", failing)
        plan2 = select_plan("grid", devices=eight_devices)
        faulted, _ = grid_chisq(f, params, axes, niter=2, chunk=4,
                                plan=plan2,
                                checkpoint=str(tmp_path / "faulted"),
                                fuse=2)
        rep2 = f.last_elastic_report
        assert rep2.degradations == 1
        assert rep2.final_plan["rung"] == 4
        assert len(rep2.evicted) == 1
        scale = max(1.0, np.abs(clean).max())
        assert np.abs(np.asarray(clean) - np.asarray(faulted)).max() \
            / scale < 1e-7

    def test_fused_sweep_resumes_from_checkpoint(self, eight_devices,
                                                 tmp_path):
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime.plan import select_plan

        f, params, axes = self._grid_setup()
        ck = str(tmp_path / "resume")
        plan = select_plan("grid", devices=eight_devices)
        first, _ = grid_chisq(f, params, axes, niter=2, chunk=4,
                              plan=plan, checkpoint=ck, fuse=2)
        plan2 = select_plan("grid", devices=eight_devices)
        again, _ = grid_chisq(f, params, axes, niter=2, chunk=4,
                              plan=plan2, checkpoint=ck, fuse=2)
        rep = f.last_elastic_report
        assert rep.chunks_resumed == 4 and rep.chunks_computed == 0
        assert np.array_equal(np.asarray(first), np.asarray(again))


    def test_scatter_fn_cache_keys_on_device_identity(self,
                                                      eight_devices):
        """Two 4-device meshes with DIFFERENT survivor sets must not
        share a cached shard_map executable — it closes over the mesh,
        and after an eviction the stale one names a dead device."""
        from jax.sharding import Mesh

        from pint_tpu.runtime.workperbyte import (
            scattered_normal_equations_fn)

        mesh_a = Mesh(np.array(eight_devices[:4]), ("toa",))
        mesh_b = Mesh(np.array(eight_devices[4:8]), ("toa",))
        fn_a = scattered_normal_equations_fn(mesh_a)
        fn_b = scattered_normal_equations_fn(mesh_b)
        assert fn_a is not fn_b
        assert fn_a is scattered_normal_equations_fn(mesh_a)

    def test_fuse_with_checkpoint_needs_plan(self, tmp_path):
        """fuse= on the plain checkpointed path would be a silent
        no-op; it must refuse loudly and name the fix."""
        from pint_tpu.exceptions import UsageError
        from pint_tpu.grid import grid_chisq

        f = _gls_fitter(ntoas=32)
        f.fit_toas(maxiter=1)
        g0 = np.linspace(f.model.F0.value - 3e-11,
                         f.model.F0.value + 3e-11, 4)
        g1 = np.linspace(f.model.F1.value - 3e-18,
                         f.model.F1.value + 3e-18, 4)
        with pytest.raises(UsageError, match="plan"):
            grid_chisq(f, ("F0", "F1"), (g0, g1), niter=2, chunk=4,
                       checkpoint=str(tmp_path / "ck"), fuse=4)


# ---------------------------------------------------------------------------
# plan strategy: data-parallel-first + the tunable
# ---------------------------------------------------------------------------

class TestPlanStrategy:
    def test_select_plan_data_parallel_first(self, eight_devices):
        """A caller holding a batch routes data-parallel: n_batch >= 2
        flips the TOA-reduction workload onto the pulsar axis; without
        a batch the TOA sharding stands."""
        from pint_tpu.runtime.plan import select_plan

        single = select_plan("gls_normal_eq", devices=eight_devices,
                             n_items=64)
        assert single.axes[0] == "toa"
        batched = select_plan("gls_normal_eq", devices=eight_devices,
                              n_batch=16)
        assert batched.axes[0] == "pulsar"
        assert batched.kind == "pjit"
        assert batched.rung == 8
        # a 1-item "batch" is no batch at all
        not_batched = select_plan("gls_normal_eq",
                                  devices=eight_devices, n_batch=1,
                                  n_items=64)
        assert not_batched.axes[0] == "toa"

    def test_tune_plan_strategy_ranks_real_executables(
            self, eight_devices):
        """The strategy tunable analyzes all three candidates on real
        compiled executables: the scatter candidate carries
        reduce-scatter ops, the all-reduce candidate carries more
        collective bytes than the scatter one, and the decision value
        is a (axes, kind) dict the resolve layer accepts."""
        from pint_tpu.autotune import tune_plan_strategy

        f = _gls_fitter(ntoas=48)
        decision = tune_plan_strategy(f, measure_reps=1)
        assert decision.basis in ("measured", "static")
        assert isinstance(decision.value, dict)
        assert decision.value.get("kind") in ("pjit", "shard_map")
        assert decision.value.get("axes")
        by_build = {c["value"]["build"]: c for c in decision.candidates}
        assert set(by_build) == {"scatter", "allreduce", "dataparallel"}
        sc = by_build["scatter"]
        ar = by_build["allreduce"]
        if sc["excluded"] is None and ar["excluded"] is None:
            # predicted_s IS the measured collective bytes (the cost-
            # ranking signal): the scattered build must move less
            assert sc["predicted_s"] < ar["predicted_s"]
            assert sc["measured_fits_per_s"] is not None


# ---------------------------------------------------------------------------
# scalewatch calibration
# ---------------------------------------------------------------------------

class TestCalibration:
    def test_calibrated_repeats_respect_floor(self):
        """ISSUE 14 satellite: repeats scale until the timed region
        reaches the floor (r11 measured ~5 ms walls — pure dispatch
        floor)."""
        import time as _time

        from tools.scalewatch import _calibrated_repeats

        repeats, probe = _calibrated_repeats(
            lambda: _time.sleep(0.002), floor_s=0.02)
        assert probe >= 0.002
        assert repeats * probe >= 0.02
        # an already-slow workload needs no repeats
        repeats2, _ = _calibrated_repeats(
            lambda: _time.sleep(0.03), floor_s=0.02)
        assert repeats2 == 1
