"""Elastic multichip execution: plan selection, canary, degradation, resume.

Runs on the conftest's virtual 8-CPU-device mesh.  The contract under
test (ISSUE 7 / DESIGN.md "Elastic multichip execution"):

* plan selection starts from the per-device preflight probes — a sick
  chip never joins a mesh;
* a fault mid-sweep (device loss, NaN-on-one-shard, straggler, failed
  collective) evicts the offender, degrades the mesh down the 8→4→2→1
  ladder, resumes from the last checkpoint, and the final surface
  matches the unfaulted single-plan run to 1e-7;
* checkpoints are mesh-portable: fingerprints never bind the device
  count (mesh identity lives in the sidecar), so a sweep checkpointed
  on 8 devices resumes on 4;
* the lifecycle events (plan_selected / device_evicted / mesh_degraded)
  land in events.jsonl and satisfy ``telemetry_report --check``;
* zero steady-state recompiles once degradation settles (one recompile
  per rung change is allowed and counted).
"""

import io
import json
import os
import signal

import numpy as np
import pytest

pytestmark = pytest.mark.elastic

PAR = """
PSR  J0000+0000
RAJ  04:37:00.0
DECJ -47:15:00.0
POSEPOCH 55000
F0   173.6879489990983 1
F1   -1.728e-15 1
PEPOCH 55000
DM   2.64476 1
EPHEM DE440
UNITS TDB
"""

NOISE = "TNREDAMP -13.0\nTNREDGAM 3.0\nTNREDC 5\n"


@pytest.fixture(autouse=True)
def _per_test_timeout():
    """POSIX-alarm wall-clock limit, same discipline as the
    fault-injection suite (a wedged supervisor must not stall tier-1)."""

    def _fire(signum, frame):
        raise TimeoutError("elastic test exceeded 300 s")

    old = signal.signal(signal.SIGALRM, _fire)
    signal.alarm(300)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _model(extra=""):
    from pint_tpu.models import get_model

    return get_model(io.StringIO(PAR + extra))


@pytest.fixture(scope="module")
def gls_fit(eight_devices):
    """Correlated-noise B1855-shaped stand-in: GLS fitter + a 64-point
    F0xF1 grid, fitted once (module scope keeps compile cost paid once)."""
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.simulation import make_fake_toas_uniform

    m = _model(NOISE)
    t = make_fake_toas_uniform(54000, 55500, 40, m, error_us=1.0,
                               add_noise=True,
                               rng=np.random.default_rng(3))
    f = GLSFitter(t, m)
    f.fit_toas(maxiter=1)
    dF0, dF1 = 3e-11, 3e-18
    g0 = np.linspace(m.F0.value - dF0, m.F0.value + dF0, 8)
    g1 = np.linspace(m.F1.value - dF1, m.F1.value + dF1, 8)
    return f, ("F0", "F1"), (g0, g1)


@pytest.fixture(scope="module")
def wls_fit(eight_devices):
    """White-noise twin (exercises the vmapped non-GLS grid builder)."""
    from pint_tpu.fitter import WLSFitter
    from pint_tpu.simulation import make_fake_toas_uniform

    m = _model()
    t = make_fake_toas_uniform(54000, 55500, 40, m, error_us=1.0,
                               add_noise=True,
                               rng=np.random.default_rng(3))
    f = WLSFitter(t, m)
    f.fit_toas()
    dF0, dF1 = 3e-11, 3e-18
    g0 = np.linspace(f.model.F0.value - dF0, f.model.F0.value + dF0, 8)
    g1 = np.linspace(f.model.F1.value - dF1, f.model.F1.value + dF1, 8)
    return f, ("F0", "F1"), (g0, g1)


@pytest.fixture()
def telemetry_run(tmp_path):
    """Full telemetry into a known run dir; deactivated afterwards."""
    from pint_tpu import telemetry
    from pint_tpu.telemetry import runlog

    telemetry.activate("full")
    run = runlog.start_run(str(tmp_path / "run"), name="elastic-test")
    yield run
    telemetry.deactivate()


def _events(run_dir):
    out = []
    with open(os.path.join(run_dir, "events.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("type") == "event":
                out.append(rec["event"])
    return out


# ---------------------------------------------------------------------------
# plan layer
# ---------------------------------------------------------------------------

class TestPlanSelection:
    def test_ladder_rungs(self):
        from pint_tpu.exceptions import MeshExhaustedError
        from pint_tpu.runtime.plan import ladder

        assert ladder(8) == (8, 4, 2, 1)
        assert ladder(7) == (4, 2, 1)
        assert ladder(1) == (1,)
        with pytest.raises(MeshExhaustedError):
            ladder(0)

    def test_select_plan_kinds_and_rungs(self, eight_devices):
        from pint_tpu.runtime.plan import select_plan

        grid = select_plan("grid")
        assert grid.kind == "pjit" and grid.rung == 8
        assert grid.mesh.axis_names == ("grid",)
        walker = select_plan("walker")
        assert walker.kind == "shard_map"
        assert walker.axes == ("walker",)
        ne = select_plan("gls_normal_eq")
        assert ne.axes == ("toa",)
        single = select_plan("grid", devices=eight_devices[:1])
        assert single.kind == "single" and single.mesh is None
        # n_items caps the rung: 3 points never mesh 8 devices
        small = select_plan("grid", n_items=3)
        assert small.rung == 2

    def test_two_axis_mesh(self, eight_devices):
        from pint_tpu.runtime.plan import select_plan

        p = select_plan("grid", axes=("grid", "toa"))
        assert dict(zip(p.mesh.axis_names, p.mesh.devices.shape)) \
            == {"grid": 2, "toa": 4}

    def test_degraded_descends_and_exhausts(self, eight_devices):
        from pint_tpu.exceptions import MeshExhaustedError
        from pint_tpu.runtime.plan import select_plan

        p = select_plan("grid")
        p4 = p.degraded(evict_ids=[eight_devices[3].id])
        assert p4.rung == 4
        assert eight_devices[3].id not in [d.id for d in p4.devices]
        assert p4.evicted == (eight_devices[3].id,)
        p2 = p4.degraded()
        p1 = p2.degraded()
        assert (p2.rung, p1.rung) == (2, 1) and p1.kind == "single"
        with pytest.raises(MeshExhaustedError):
            p1.degraded()

    def test_unknown_axis_rejected(self):
        from pint_tpu.exceptions import UsageError
        from pint_tpu.runtime.plan import select_plan

        with pytest.raises(UsageError):
            select_plan("grid", axes=("chip",))

    def test_sick_device_excluded_from_mesh(self, eight_devices):
        """The per-device probe gates membership: a sick chip drops the
        plan a rung and never appears in device_ids."""
        from pint_tpu.runtime import faultinject as fi
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.runtime.preflight import healthy_devices

        with fi.sick_device(eight_devices[5].id):
            assert len(healthy_devices()) == 7
            p = select_plan("grid")
            assert p.rung == 4
            assert eight_devices[5].id not in p.device_ids
        assert len(healthy_devices()) == 8
        assert select_plan("grid").rung == 8

    def test_plan_selected_event_validates(self, telemetry_run):
        from pint_tpu.runtime.plan import select_plan
        from tools.telemetry_report import validate_events_file

        select_plan("grid")
        evs = [e for e in _events(telemetry_run.path)
               if e["name"] == "plan_selected"]
        assert evs and evs[0]["attrs"]["kind"] == "pjit"
        assert evs[0]["attrs"]["rung"] == 8
        errors = []
        validate_events_file(
            os.path.join(telemetry_run.path, "events.jsonl"), errors)
        assert errors == []


# ---------------------------------------------------------------------------
# preflight per-device probes
# ---------------------------------------------------------------------------

class TestDeviceHealth:
    def test_all_virtual_devices_probe_healthy(self, eight_devices):
        from pint_tpu.runtime.preflight import device_health

        hs = device_health(refresh=True)
        assert len(hs) == 8
        assert all(h.healthy for h in hs)
        assert {h.device_id for h in hs} == {d.id for d in eight_devices}

    def test_probe_failure_marks_unhealthy(self, eight_devices):
        """A probe that raises IS the verdict — the device is out."""
        from pint_tpu.runtime import preflight as pf

        orig = pf._probe_one

        def exploding(dev):
            if dev.id == eight_devices[2].id:
                raise RuntimeError("injected: probe cannot reach device")
            return orig(dev)

        pf._probe_one = exploding
        try:
            hs = pf.device_health(refresh=True)
            bad = [h for h in hs if not h.healthy]
            assert [h.device_id for h in bad] == [eight_devices[2].id]
            assert "probe cannot reach" in bad[0].error
        finally:
            pf._probe_one = orig
            pf.device_health(refresh=True)


# ---------------------------------------------------------------------------
# elastic supervisor: the degradation ladder end to end
# ---------------------------------------------------------------------------

class TestElasticGrid:
    def test_device_loss_mid_sweep_degrades_resumes_and_matches(
            self, gls_fit, tmp_path, telemetry_run):
        """THE acceptance scenario: a GLS grid sweep on the 8-device
        mesh loses a device at chunk 1, degrades to 4 devices, resumes
        from the checkpoint, and the chi2 surface matches the unfaulted
        run to 1e-7 — with the lifecycle events in events.jsonl and
        zero steady-state recompiles after degradation settles."""
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime import faultinject as fi
        from pint_tpu.runtime.plan import select_plan
        from tools.telemetry_report import validate_run_dir

        f, params, axes = gls_fit
        chi2_ref, _ = grid_chisq(f, params, axes, niter=2)
        plan = select_plan("grid", n_items=64)
        with fi.shard_device_loss(at_chunk=1, device_index=3) as st:
            chi2_el, _ = grid_chisq(f, params, axes, niter=2, plan=plan,
                                    checkpoint=str(tmp_path / "ck"),
                                    chunk=16)
        assert st["calls"] == 1
        rep = f.last_elastic_report
        assert rep.rungs == [8, 4]
        assert len(rep.evicted) == 1
        rel = np.max(np.abs(np.asarray(chi2_el) - np.asarray(chi2_ref))
                     / np.maximum(np.abs(np.asarray(chi2_ref)), 1.0))
        assert rel < 1e-7, f"degraded sweep diverged: rel {rel:.3g}"
        # one recompile budget per rung, zero at steady state
        assert rep.steady_state_recompiles == 0
        assert set(rep.recompiles_by_rung) == {8, 4}
        # lifecycle events present and schema-valid
        names = [e["name"] for e in _events(telemetry_run.path)]
        assert "plan_selected" in names
        assert "device_evicted" in names
        assert "mesh_degraded" in names
        assert "elastic.sweep_done" in names
        errors = []
        validate_run_dir(telemetry_run.path, errors)
        assert errors == []
        # the checkpoint sidecar recorded the degradation trail
        meta = json.load(open(tmp_path / "ck" / "meta.json"))
        assert meta["sidecar"]["plan"]["rung"] == 4
        assert [s["plan"]["rung"] for s in meta["sidecar_history"]] == [8]

    def test_canary_catches_nan_shard(self, wls_fit, tmp_path,
                                      telemetry_run):
        """Silent corruption: one shard's outputs are NaN with no
        exception raised — only the cross-replica canary can notice.
        The offender is evicted and the surface stays correct."""
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime import faultinject as fi
        from pint_tpu.runtime.plan import select_plan

        f, params, axes = wls_fit
        chi2_ref, _ = grid_chisq(f, params, axes, niter=2)
        plan = select_plan("grid", n_items=64)
        with fi.shard_nan(device_index=2, at_chunk=0):
            chi2_el, _ = grid_chisq(f, params, axes, niter=2, plan=plan,
                                    checkpoint=str(tmp_path / "ck"),
                                    chunk=16)
        rep = f.last_elastic_report
        assert rep.rungs == [8, 4]
        assert len(rep.evicted) == 1
        assert np.all(np.isfinite(np.asarray(chi2_el)))
        rel = np.max(np.abs(np.asarray(chi2_el) - np.asarray(chi2_ref))
                     / np.maximum(np.abs(np.asarray(chi2_ref)), 1.0))
        assert rel < 1e-7
        evicted = [e for e in _events(telemetry_run.path)
                   if e["name"] == "device_evicted"]
        assert evicted and evicted[-1]["attrs"]["reason"] \
            == "canary_mismatch"

    def test_straggler_times_out_and_degrades(self, wls_fit, tmp_path):
        """A wedged chip stalls a dispatch past the per-attempt timeout:
        one same-rung retry, then a rung down (no device identified, so
        nothing is evicted)."""
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime import faultinject as fi
        from pint_tpu.runtime.checkpoint import RetryPolicy
        from pint_tpu.runtime.plan import select_plan

        f, params, axes = wls_fit
        chi2_ref, _ = grid_chisq(f, params, axes, niter=2)
        plan = select_plan("grid", n_items=64)
        with fi.straggler(delay_s=8.0, at_chunk=0, times=2):
            chi2_el, _ = grid_chisq(
                f, params, axes, niter=2, plan=plan,
                checkpoint=str(tmp_path / "ck"), chunk=16,
                retry=RetryPolicy(timeout=2.0, backoff_base=0.0))
        rep = f.last_elastic_report
        assert rep.rungs == [8, 4]
        assert rep.evicted == []
        rel = np.max(np.abs(np.asarray(chi2_el) - np.asarray(chi2_ref))
                     / np.maximum(np.abs(np.asarray(chi2_ref)), 1.0))
        assert rel < 1e-7

    def test_failed_collective_degrades_without_eviction(
            self, wls_fit, tmp_path):
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime import faultinject as fi
        from pint_tpu.runtime.plan import select_plan

        f, params, axes = wls_fit
        chi2_ref, _ = grid_chisq(f, params, axes, niter=2)
        plan = select_plan("grid", n_items=64)
        with fi.failed_collective(at_chunk=0, times=2):
            chi2_el, _ = grid_chisq(f, params, axes, niter=2, plan=plan,
                                    checkpoint=str(tmp_path / "ck"),
                                    chunk=16)
        rep = f.last_elastic_report
        assert rep.rungs == [8, 4] and rep.evicted == []
        rel = np.max(np.abs(np.asarray(chi2_el) - np.asarray(chi2_ref))
                     / np.maximum(np.abs(np.asarray(chi2_ref)), 1.0))
        assert rel < 1e-7

    def test_ladder_exhaustion_raises_typed(self, eight_devices):
        """Every rung failing ends in SweepChunkFailure, never a silent
        partial surface."""
        from pint_tpu.exceptions import SweepChunkFailure
        from pint_tpu.runtime import elastic, faultinject as fi
        from pint_tpu.runtime.plan import select_plan

        def make_eval(block_size, p):
            def ev(block):
                return {"chi2": np.sum(np.asarray(block) ** 2, axis=1)}
            return ev

        pts = np.arange(32.0).reshape(16, 2)
        plan = select_plan("grid", devices=eight_devices[:2])
        with fi.shard_device_loss(at_chunk=0, device_index=0, times=99):
            with pytest.raises(SweepChunkFailure):
                elastic.elastic_map(make_eval, pts, plan=plan, chunk=8)

    def test_canary_all_nan_is_agreement(self, eight_devices):
        """A NaN chi2 is a legitimate grid outcome (unsolvable point);
        when EVERY shard returns NaN for the canary they agree, and
        nobody may be convicted — only a divergent shard is corrupt."""
        from pint_tpu.exceptions import CanaryMismatchError
        from pint_tpu.runtime.elastic import check_canary
        from pint_tpu.runtime.plan import select_plan

        plan = select_plan("grid", devices=eight_devices[:4])
        check_canary(np.full(4, np.nan), plan)  # unanimous: no eviction
        with pytest.raises(CanaryMismatchError):  # divergent: convicted
            check_canary(np.array([1.0, 1.0, np.nan, 1.0]), plan)

    def test_unclassified_failure_propagates(self, eight_devices):
        """A typed solve failure must NOT be retried down the ladder —
        it would fail identically on every rung."""
        from pint_tpu.exceptions import UsageError
        from pint_tpu.runtime import elastic
        from pint_tpu.runtime.plan import select_plan

        def make_eval(block_size, p):
            def ev(block):
                raise UsageError("not an elastic failure")
            return ev

        pts = np.arange(32.0).reshape(16, 2)
        plan = select_plan("grid", devices=eight_devices[:4])
        with pytest.raises(UsageError):
            elastic.elastic_map(make_eval, pts, plan=plan, chunk=8)


# ---------------------------------------------------------------------------
# mesh-portable checkpoints (satellite: fingerprint must not bind mesh)
# ---------------------------------------------------------------------------

class TestMeshPortableResume:
    def test_sidecar_not_part_of_fingerprint(self, tmp_path):
        from pint_tpu.exceptions import CheckpointError
        from pint_tpu.runtime.checkpoint import SweepCheckpoint

        path = str(tmp_path / "ck")
        a = SweepCheckpoint(path, "fp", 4, sidecar={"plan": {"rung": 8}})
        a.save(0, x=np.arange(3.0))
        # same fingerprint, different mesh: opens fine, sidecar updates
        b = SweepCheckpoint(path, "fp", 4, sidecar={"plan": {"rung": 4}})
        assert b.has(0)
        assert b.meta["sidecar"]["plan"]["rung"] == 4
        assert b.meta["sidecar_history"][0]["plan"]["rung"] == 8
        # a different SWEEP still refuses
        with pytest.raises(CheckpointError):
            SweepCheckpoint(path, "other-fp", 4)

    def test_crash_on_8_resumes_on_4(self, gls_fit, tmp_path):
        """The cross-device-count resume regression: sweep crashes after
        2 chunks on an 8-device plan; a fresh run on a 4-device plan
        resumes the SAME checkpoint (fingerprint is mesh-free), reuses
        the completed chunks, and matches the unfaulted surface."""
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime import faultinject as fi
        from pint_tpu.runtime.faultinject import SimulatedCrash
        from pint_tpu.runtime.plan import select_plan

        f, params, axes = gls_fit
        chi2_ref, _ = grid_chisq(f, params, axes, niter=2)
        ck = str(tmp_path / "ck")
        plan8 = select_plan("grid", n_items=64)
        assert plan8.rung == 8
        with fi.shard_crash_after_chunks(2):
            with pytest.raises(SimulatedCrash):
                grid_chisq(f, params, axes, niter=2, plan=plan8,
                           checkpoint=ck, chunk=16)
        meta = json.load(open(os.path.join(ck, "meta.json")))
        assert meta["sidecar"]["plan"]["rung"] == 8
        # "new process", half the devices
        import jax

        plan4 = select_plan("grid", devices=jax.devices()[:4])
        assert plan4.rung == 4
        chi2_el, _ = grid_chisq(f, params, axes, niter=2, plan=plan4,
                                checkpoint=ck, chunk=16)
        rep = f.last_elastic_report
        assert rep.chunks_resumed == 2
        assert rep.chunks_computed == 2
        rel = np.max(np.abs(np.asarray(chi2_el) - np.asarray(chi2_ref))
                     / np.maximum(np.abs(np.asarray(chi2_ref)), 1.0))
        assert rel < 1e-7
        meta = json.load(open(os.path.join(ck, "meta.json")))
        assert meta["sidecar"]["plan"]["rung"] == 4

    def test_mesh_plus_checkpoint_still_guided_to_plan(self, gls_fit,
                                                       tmp_path):
        from jax.sharding import Mesh
        import jax

        from pint_tpu.exceptions import UsageError
        from pint_tpu.grid import grid_chisq
        from pint_tpu.runtime.plan import select_plan

        f, params, axes = gls_fit
        mesh = Mesh(np.array(jax.devices()[:2]), ("grid",))
        with pytest.raises(UsageError, match="plan="):
            grid_chisq(f, params, axes, checkpoint=str(tmp_path / "x"),
                       mesh=mesh)
        with pytest.raises(UsageError, match="cannot be combined"):
            grid_chisq(f, params, axes, mesh=mesh,
                       plan=select_plan("grid"))


# ---------------------------------------------------------------------------
# routed GLS normal equations + sampler walkers
# ---------------------------------------------------------------------------

class TestRoutedSolvesAndWalkers:
    def test_gls_fit_with_plan_matches_host(self, eight_devices):
        """The TOA-sharded normal-equation build is algebraically the
        host build (zero-padded rows contribute nothing): chi2 and
        parameter steps agree to fp noise, and the plan survives on the
        fitter."""
        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.simulation import make_fake_toas_uniform

        m1, m2 = _model(NOISE), _model(NOISE)
        t = make_fake_toas_uniform(54000, 55500, 40, m1, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(7))
        f_host = GLSFitter(t, m1)
        chi2_host = f_host.fit_toas(maxiter=1)
        f_plan = GLSFitter(t, m2)
        chi2_plan = f_plan.fit_toas(
            maxiter=1, plan=select_plan("gls_normal_eq",
                                        devices=eight_devices))
        assert abs(chi2_plan - chi2_host) <= 1e-7 * max(chi2_host, 1.0)
        assert f_plan.plan is not None and f_plan.plan.rung == 8
        # host path solves via the Schur fast path, plan path via the
        # sharded dense build: same system, different factorization
        # order — agreement to solver precision, not bit equality
        for p in ("F0", "F1", "DM"):
            a = float(getattr(f_host.model, p).value)
            b = float(getattr(f_plan.model, p).value)
            assert np.isclose(a, b, rtol=1e-6, atol=0), (p, a, b)

    def test_gls_plan_degrades_on_device_loss(self, eight_devices):
        """A device lost during the sharded build degrades the plan and
        the fit completes on the smaller mesh."""
        import pint_tpu.gls_fitter as gf
        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.runtime.faultinject import SimulatedDeviceLoss
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.simulation import make_fake_toas_uniform

        m = _model(NOISE)
        t = make_fake_toas_uniform(54000, 55500, 40, m, error_us=1.0,
                                   add_noise=True,
                                   rng=np.random.default_rng(7))
        f = GLSFitter(t, m)
        orig = gf._sharded_normal_equations
        state = {"calls": 0}

        def failing(M, r, Nvec, phiinv, plan):
            state["calls"] += 1
            if state["calls"] == 1:
                raise SimulatedDeviceLoss(
                    "injected: device lost in normal-eq build",
                    device_id=int(plan.devices[1].id))
            return orig(M, r, Nvec, phiinv, plan)

        gf._sharded_normal_equations = failing
        try:
            chi2 = f.fit_toas(maxiter=1,
                              plan=select_plan("gls_normal_eq",
                                               devices=eight_devices))
        finally:
            gf._sharded_normal_equations = orig
        assert np.isfinite(chi2)
        assert f.plan.rung == 4
        assert len(f.plan.evicted) == 1

    def test_sampler_walker_plan_matches_unsharded(self, eight_devices):
        """shard_map walker routing is bit-compatible with the plain
        path: same seed, same chain (per-walker math has no cross-item
        reduction)."""
        import jax
        import jax.numpy as jnp

        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.sampler import EnsembleSampler

        lnp = jax.jit(lambda pts: -0.5 * jnp.sum(pts ** 2, axis=-1))

        def run(plan):
            s = EnsembleSampler(16, seed=11, plan=plan)
            s.initialize_batched(lnp, 3)
            pos = np.random.default_rng(5).standard_normal((16, 3))
            s.run_mcmc(pos, 8)
            return s

        s_plan = run(select_plan("walker", devices=eight_devices))
        s_plain = run(None)
        assert s_plan._shard_map_ok is True
        np.testing.assert_allclose(s_plan.get_chain(), s_plain.get_chain(),
                                   rtol=1e-12, atol=0)

    def test_sampler_plan_degrades_on_device_loss(self, eight_devices):
        """Retry exhaustion on the walker batch degrades the plan
        instead of killing the chain."""
        import jax
        import jax.numpy as jnp

        from pint_tpu.runtime.faultinject import SimulatedDeviceLoss
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.sampler import EnsembleSampler

        base = jax.jit(lambda pts: -0.5 * jnp.sum(pts ** 2, axis=-1))
        state = {"calls": 0}

        def flaky_lnp(pts):
            state["calls"] += 1
            if state["calls"] <= 2:
                raise SimulatedDeviceLoss("injected: walker batch lost",
                                          device_id=1)
            return base(pts)

        s = EnsembleSampler(16, seed=11,
                            plan=select_plan("walker",
                                             devices=eight_devices),
                            retries=0)
        s.initialize_batched(flaky_lnp, 3)
        pos = np.random.default_rng(5).standard_normal((16, 3))
        s.run_mcmc(pos, 4)
        assert s.plan.rung <= 4
        assert 1 in s.plan.evicted
        assert np.all(np.isfinite(s.get_log_prob()))

    def test_sampler_unclassified_failure_propagates(self, eight_devices):
        """The sampler's elastic supervision obeys the same contract as
        elastic_map: a typed non-device failure must NOT burn rungs —
        it would fail identically on every device count."""
        from pint_tpu.exceptions import UsageError
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.sampler import EnsembleSampler

        def bad_lnp(pts):
            raise UsageError("not an elastic failure")

        plan = select_plan("walker", devices=eight_devices)
        s = EnsembleSampler(16, seed=11, plan=plan, retries=0)
        s.initialize_batched(bad_lnp, 3)
        pos = np.random.default_rng(5).standard_normal((16, 3))
        with pytest.raises(UsageError):
            s.run_mcmc(pos, 2)
        assert s.plan.rung == plan.rung  # no rung was burned

    def test_custom_posterior_falls_back_from_shard_map(
            self, eight_devices):
        """A non-traceable Python batch posterior cannot shard_map; the
        sampler falls back to the sharded-dispatch path once and
        remembers."""
        from pint_tpu.runtime.plan import select_plan
        from pint_tpu.sampler import EnsembleSampler

        def py_lnp(pts):
            return np.array([-0.5 * float(np.sum(np.asarray(p) ** 2))
                             for p in np.asarray(pts)])

        s = EnsembleSampler(16, seed=11,
                            plan=select_plan("walker",
                                             devices=eight_devices))
        s.initialize_batched(py_lnp, 3)
        pos = np.random.default_rng(5).standard_normal((16, 3))
        s.run_mcmc(pos, 2)
        assert s._shard_map_ok is False
        assert np.all(np.isfinite(s.get_log_prob()))


# ---------------------------------------------------------------------------
# telemetry contract
# ---------------------------------------------------------------------------

class TestElasticEventContract:
    def test_malformed_elastic_events_rejected(self, tmp_path):
        """--check refuses drifted lifecycle events (missing attrs, an
        'ascending' degradation, an unknown plan kind)."""
        import time as _time

        from pint_tpu.telemetry.runlog import EVENT_SCHEMA
        from tools.telemetry_report import validate_events_file

        def line(name, attrs):
            return json.dumps({"schema": EVENT_SCHEMA, "t": _time.time(),
                               "type": "event",
                               "event": {"name": name, "attrs": attrs}})

        p = tmp_path / "events.jsonl"
        p.write_text("\n".join([
            line("mesh_degraded", {"from_rung": 4, "to_rung": 8,
                                   "reason": "x"}),
            line("device_evicted", {"reason": "canary_mismatch"}),
            line("plan_selected", {"workload": "grid", "kind": "mpi",
                                   "rung": 8, "n_devices": 8}),
        ]) + "\n")
        errors = []
        validate_events_file(str(p), errors)
        assert len(errors) == 3
        assert any("strictly descend" in e for e in errors)
        assert any("device_id" in e for e in errors)
        assert any("not in" in e for e in errors)

    def test_wellformed_elastic_events_pass(self, tmp_path):
        import time as _time

        from pint_tpu.telemetry.runlog import EVENT_SCHEMA
        from tools.telemetry_report import validate_events_file

        recs = [
            {"name": "plan_selected",
             "attrs": {"workload": "grid", "kind": "shard_map", "rung": 4,
                       "n_devices": 8}},
            {"name": "device_evicted",
             "attrs": {"device_id": 3, "reason": "device_loss"}},
            {"name": "mesh_degraded",
             "attrs": {"from_rung": 8, "to_rung": 4,
                       "reason": "collective_timeout"}},
        ]
        p = tmp_path / "events.jsonl"
        p.write_text("\n".join(
            json.dumps({"schema": EVENT_SCHEMA, "t": _time.time(),
                        "type": "event", "event": r}) for r in recs) + "\n")
        errors = []
        validate_events_file(str(p), errors)
        assert errors == []


# ---------------------------------------------------------------------------
# acceptance-scale sweep (slow: full 256-point grid)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_full_scale_sweep_with_device_loss(eight_devices, tmp_path):
    """256-point GLS grid (synthetic B1855-shaped correlated-noise
    workload), device lost mid-sweep on the 8-device mesh: degrade to
    4, resume, match the unfaulted single-plan run to 1e-7."""
    from pint_tpu.gls_fitter import GLSFitter
    from pint_tpu.grid import grid_chisq
    from pint_tpu.runtime import faultinject as fi
    from pint_tpu.runtime.plan import select_plan
    from pint_tpu.simulation import make_fake_toas_uniform

    m = _model(NOISE)
    t = make_fake_toas_uniform(54000, 55500, 64, m, error_us=1.0,
                               add_noise=True,
                               rng=np.random.default_rng(3))
    f = GLSFitter(t, m)
    f.fit_toas(maxiter=1)
    dF0, dF1 = 3e-11, 3e-18
    g0 = np.linspace(m.F0.value - dF0, m.F0.value + dF0, 16)
    g1 = np.linspace(m.F1.value - dF1, m.F1.value + dF1, 16)
    chi2_ref, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=2)
    plan = select_plan("grid", n_items=256)
    with fi.shard_device_loss(at_chunk=2, device_index=5):
        chi2_el, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=2,
                                plan=plan,
                                checkpoint=str(tmp_path / "ck"), chunk=64)
    rep = f.last_elastic_report
    assert rep.rungs == [8, 4]
    assert rep.steady_state_recompiles == 0
    rel = np.max(np.abs(np.asarray(chi2_el) - np.asarray(chi2_ref))
                 / np.maximum(np.abs(np.asarray(chi2_ref)), 1.0))
    assert rel < 1e-7
