"""Packaging metadata + formerly-dead arguments (VERDICT r2 directive #6).

- pyproject.toml declares the same 14 console scripts as the reference
  (``pyproject.toml:60-74``) and every entry point resolves.
- ``get_TOAs(usepickle=True)`` is a real hash-invalidated cache
  (reference ``toa.py:333,373,1856``).
- ``TimingModel.delay(cutoff_component=...)`` truncates the ordered delay
  accumulation (reference ``timing_model.py:1565``).
- ``Residuals.dof`` counts the implicit offset only when one is fitted.
"""

import importlib
import os
import tomllib

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NGC_PAR = "/root/reference/src/pint/data/examples/NGC6440E.par"
NGC_TIM = "/root/reference/src/pint/data/examples/NGC6440E.tim"


class TestPackaging:
    def test_console_scripts_resolve(self):
        with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
            meta = tomllib.load(f)
        scripts = meta["project"]["scripts"]
        assert len(scripts) == 14
        for name, target in scripts.items():
            mod, func = target.split(":")
            m = importlib.import_module(mod)
            assert callable(getattr(m, func)), name

    def test_package_metadata(self):
        with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
            meta = tomllib.load(f)
        assert meta["project"]["name"] == "pint-tpu"
        assert "jax" in meta["project"]["dependencies"]


@pytest.mark.skipif(not os.path.exists(NGC_TIM), reason="no reference data")
class TestUsepickle:
    def test_pickle_roundtrip_and_invalidation(self, tmp_path):
        import shutil

        from pint_tpu.toa import PICKLE_SUFFIX, get_TOAs

        timf = str(tmp_path / "t.tim")
        shutil.copy(NGC_TIM, timf)
        t1 = get_TOAs(timf, usepickle=True)
        cache = timf + PICKLE_SUFFIX
        assert os.path.exists(cache)
        t2 = get_TOAs(timf, usepickle=True)
        assert np.array_equal(np.asarray(t2.tdb, np.float64),
                              np.asarray(t1.tdb, np.float64))
        # different settings -> cache miss (not wrong data)
        t3 = get_TOAs(timf, usepickle=True, planets=True)
        assert "jupiter" in {k.lower() for k in (t3.planet_pos_km or {})}
        # edit the tim file -> hash invalidation (append a copy of the last
        # TOA line with a shifted MJD, preserving the file's own format)
        with open(timf) as f:
            last = [ln for ln in f if ln.strip()][-1]
        old_mjd = last.split()[2]
        new_mjd = str(float(old_mjd) + 1.0).ljust(len(old_mjd), "0")[:len(old_mjd)]
        with open(timf, "a") as f:
            f.write(last.rstrip("\n").replace(old_mjd, new_mjd) + "\n")
        t4 = get_TOAs(timf, usepickle=True)
        assert len(t4) == len(t1) + 1


@pytest.mark.skipif(not os.path.exists(NGC_TIM), reason="no reference data")
class TestCutoffDelay:
    def test_cutoff_component(self):
        from pint_tpu.models import get_model_and_toas

        m, t = get_model_and_toas(NGC_PAR, NGC_TIM)
        full = m.delay(t)
        # delay up to (excluding) the dispersion component = astrometry+shapiro
        part = m.delay(t, cutoff_component="DispersionDM", include_last=False)
        withdm = m.delay(t, cutoff_component="DispersionDM", include_last=True)
        dm_delay = withdm - part
        assert np.all(dm_delay > 0)  # dispersion always delays
        assert not np.allclose(part, full)
        # last delay component (in EVALUATION order) inclusive == full delay
        by_id = {id(c): n for n, c in m.components.items()}
        names = [by_id[id(c)] for c in m.delay_components]
        again = m.delay(t, cutoff_component=names[-1], include_last=True)
        assert np.allclose(again, full, atol=1e-12)
        with pytest.raises(ValueError):
            m.delay(t, cutoff_component="NoSuchComponent")


@pytest.mark.skipif(not os.path.exists(NGC_TIM), reason="no reference data")
class TestDofAccounting:
    def test_dof_counts_offset_only_when_subtracted(self):
        from pint_tpu.models import get_model_and_toas
        from pint_tpu.residuals import Residuals

        m, t = get_model_and_toas(NGC_PAR, NGC_TIM)
        r_mean = Residuals(t, m, subtract_mean=True)
        r_nomean = Residuals(t, m, subtract_mean=False)
        nfree = len(m.free_params)
        assert r_mean.dof == len(t) - nfree - 1
        assert r_nomean.dof == len(t) - nfree


class TestImportNeverTouchesDevices:
    def test_model_build_without_backend(self):
        """Importing the package and building a model must not initialize
        a jax backend: a module-scope jnp.asarray once hung every import
        while the TPU tunnel was wedged (r4 regression).  Run in a
        subprocess with backend init poisoned."""
        import subprocess
        import sys

        code = (
            "import jax._src.xla_bridge as xb\n"
            "def _boom(*a, **k):\n"
            "    raise SystemExit('backend init during import/model build')\n"
            "xb.backends = _boom\n"
            "import pint_tpu\n"
            "from pint_tpu.models import get_model\n"
            "m = get_model(['PSR X\\n','RAJ 1:0:0\\n','DECJ 1:0:0\\n',"
            "'F0 100.0\\n','PEPOCH 55000\\n','DM 10\\n','UNITS TDB\\n'])\n"
            "print('no backend touched')\n"
        )
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, timeout=300)
        assert "no backend touched" in out.stdout, (out.stdout, out.stderr)
