"""Scaling-efficiency gate under test (tools/scalewatch.py).

Unit level: artifact ingestion/validation and the MAD-floored gate
(ISSUE 6 acceptance: --check exits 1 on a synthetic >30% efficiency
drop, 0 on the committed history).  Integration level: one in-process
worker measurement on the virtual CPU mesh produces the schema-tagged
record set (measurement with per-device busy fractions, the GLS
normal-equation all-reduce, a sharding plan) that the sweep assembles
into the SCALING artifact.
"""

import json
import os
import sys

import pytest

pytestmark = [pytest.mark.distview, pytest.mark.perfwatch]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.scalewatch import (  # noqa: E402
    SCALING_SCHEMA,
    _records_from_output,
    check_history,
    collect_history,
    ingest_artifact,
    main,
)


def _artifact(round_n, efficiency, ratio=0.1, tmp_path=None, **extra):
    doc = {"schema": SCALING_SCHEMA, "created_unix": 0.0,
           "platform": "cpu", "workload": "synthetic_gls_grid",
           "device_counts": [1, 8], "max_devices": 8,
           "efficiency_at_max": efficiency,
           "comm_compute_ratio_at_max": ratio,
           "series": [{"n_devices": 1, "wall_s": 1.0, "fits_per_sec": 64.0,
                       "speedup": 1.0, "efficiency": 1.0,
                       "comm_compute_ratio": 0.0, "busy_fractions": {}},
                      {"n_devices": 8, "wall_s": 1.0, "fits_per_sec": 64.0,
                       "speedup": 8 * efficiency,
                       "efficiency": efficiency,
                       "comm_compute_ratio": ratio,
                       "busy_fractions": {}}]}
    doc.update(extra)
    path = tmp_path / f"SCALING_r{round_n:02d}.json"
    path.write_text(json.dumps(doc))
    return str(path)


class TestIngest:
    def test_valid_artifact_round_trips(self, tmp_path):
        p = _artifact(1, 0.8, tmp_path=tmp_path)
        errors = []
        doc = ingest_artifact(p, errors)
        assert errors == []
        assert doc["_round"] == 1
        assert doc["efficiency_at_max"] == 0.8

    def test_malformed_artifacts_error(self, tmp_path):
        bad1 = tmp_path / "SCALING_r01.json"
        bad1.write_text("{not json")
        bad2 = tmp_path / "SCALING_r02.json"
        bad2.write_text(json.dumps({"schema": "wrong/1"}))
        bad3 = tmp_path / "SCALING_r03.json"
        bad3.write_text(json.dumps({"schema": SCALING_SCHEMA,
                                    "series": []}))
        errors = []
        for p in (bad1, bad2, bad3):
            assert ingest_artifact(str(p), errors) is None
        assert len(errors) == 3

    def test_collect_orders_by_round(self, tmp_path):
        _artifact(3, 0.5, tmp_path=tmp_path)
        _artifact(1, 0.9, tmp_path=tmp_path)
        errors = []
        docs = collect_history([], str(tmp_path), errors)
        assert [d["_round"] for d in docs] == [1, 3]

    def test_records_from_output(self):
        text = ("prose line\n"
                '{"schema": "pint_tpu.telemetry.multichip/1", '
                '"record": "measurement", "n_devices": 2, "wall_s": 1.0, '
                '"fits_per_sec": 8.0}\n'
                '{"untagged": true}\n')
        recs = _records_from_output(text)
        assert len(recs) == 1 and recs[0]["record"] == "measurement"


class TestGate:
    def test_synthetic_efficiency_drop_fails(self, tmp_path, capsys):
        """The ISSUE 6 acceptance pin: a >30% efficiency drop between
        the newest artifact and its history exits 1."""
        _artifact(1, 0.80, tmp_path=tmp_path)
        _artifact(2, 0.50, tmp_path=tmp_path)  # -37.5%
        assert main(["--check", "--dir", str(tmp_path)]) == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_flat_history_passes(self, tmp_path, capsys):
        _artifact(1, 0.80, tmp_path=tmp_path)
        _artifact(2, 0.78, tmp_path=tmp_path)
        assert main(["--check", "--dir", str(tmp_path)]) == 0
        assert "no meaningful scaling regression" in \
            capsys.readouterr().out

    def test_comm_ratio_rise_fails(self, tmp_path, capsys):
        _artifact(1, 0.80, ratio=0.10, tmp_path=tmp_path)
        _artifact(2, 0.80, ratio=0.20, tmp_path=tmp_path)  # +100%
        assert main(["--check", "--dir", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "comm_compute_ratio_at_max" in out

    def test_zero_ratio_baseline_still_gates_new_comms(self, tmp_path):
        """An all-zero comm-ratio history is a measurement ("this plan
        moves nothing"): a newly nonzero ratio is an infinite relative
        rise and must fail, zero baseline or not."""
        _artifact(1, 0.80, ratio=0.0, tmp_path=tmp_path)
        _artifact(2, 0.80, ratio=0.0, tmp_path=tmp_path)
        _artifact(3, 0.80, ratio=0.05, tmp_path=tmp_path)
        assert main(["--check", "--dir", str(tmp_path)]) == 1

    def test_single_artifact_passes(self, tmp_path, capsys):
        _artifact(1, 0.80, tmp_path=tmp_path)
        assert main(["--check", "--dir", str(tmp_path)]) == 0
        assert "no history to gate" in capsys.readouterr().out

    def test_noisy_history_raises_the_bar(self, capsys):
        """A drop inside the history's own MAD noise floor passes."""
        history = []
        for i, eff in enumerate((1.0, 0.5, 1.5, 0.55)):
            history.append({"schema": SCALING_SCHEMA,
                            "efficiency_at_max": eff,
                            "comm_compute_ratio_at_max": 0.1,
                            "max_devices": 8, "series": [{}],
                            "_source": f"r{i}", "_round": i})
        assert check_history(history, threshold=0.30, noise_mult=3.0) == 0

    def test_malformed_history_fails_check(self, tmp_path):
        (tmp_path / "SCALING_r01.json").write_text("{broken")
        assert main(["--check", "--dir", str(tmp_path)]) == 1

    def test_committed_history_passes(self, capsys):
        """The repo's own committed SCALING_r* history gates clean —
        exactly what the pre-commit hook runs."""
        assert main(["--check"]) == 0


class TestWorkerIntegration:
    def test_worker_emits_full_record_set(self, eight_devices, capsys,
                                          monkeypatch):
        """One in-process worker measurement at 2 devices: the stdout
        record set carries a measurement with per-device busy fractions
        and the workload calibration stamp, a non-empty GLS
        normal-equation CollectiveProfile (reduce-scatter bytes > 0, NO
        full-Gram all-reduce — the ISSUE 14 contract), and a sharding
        plan — every record schema-valid per the telemetry_report
        validators."""
        from tools.scalewatch import run_worker
        from tools.telemetry_report import validate_multichip_record

        monkeypatch.setattr("tools.scalewatch._CAL_FLOOR_S", 0.02)
        assert run_worker(2) == 0
        recs = _records_from_output(capsys.readouterr().out)
        errors = []
        for rec in recs:
            validate_multichip_record(rec, "worker", errors)
        assert errors == []
        by_kind = {}
        for rec in recs:
            by_kind.setdefault(rec["record"], []).append(rec)
        meas = by_kind["measurement"][0]
        assert meas["n_devices"] == 2
        assert meas["fits_per_sec"] > 0
        assert len(meas["busy_fractions"]) >= 1
        cal = meas["calibration"]
        assert cal["repeats"] >= 1
        assert meas["wall_s"] >= cal["floor_s"] * 0.5  # probe-based
        assert meas["fused"]["dispatches"] >= 1
        colls = {c["collective"]["name"]: c["collective"]
                 for c in by_kind["collective"]}
        ne = colls["gls.normal_eq"]
        assert ne["ops"]["reduce-scatter"]["bytes"] > 0
        assert "all-reduce" not in ne["ops"]
        assert ne["comm_compute_ratio"] > 0
        assert by_kind["sharding_plan"]

    @pytest.mark.slow
    def test_sweep_subprocess_end_to_end(self, tmp_path):
        """The full parent path: subprocess workers at 1 and 2 devices,
        artifact assembly, --emit, and the emitted artifact re-ingests
        cleanly."""
        from tools.scalewatch import run_sweep

        errors = []
        doc = run_sweep([1, 2], errors, timeout_s=600.0)
        assert errors == []
        assert doc is not None and doc["schema"] == SCALING_SCHEMA
        assert [s["n_devices"] for s in doc["series"]] == [1, 2]
        assert doc["series"][0]["efficiency"] == 1.0
        assert doc["efficiency_at_max"] is not None
        assert doc["comm_compute_ratio_at_max"] > 0
        out = tmp_path / "SCALING_r99.json"
        out.write_text(json.dumps(doc))
        errs = []
        assert ingest_artifact(str(out), errs) is not None and errs == []


class TestCatalogWorkload:
    """The --workload catalog mode (PR 11): the pulsar-data-parallel
    batched catalog fit swept as its own scaling series, gated against
    its own history."""

    def test_catalog_worker_emits_full_record_set(self, eight_devices,
                                                  capsys, monkeypatch):
        """One in-process catalog worker at 2 devices: the measurement
        carries the catalog workload tag, the calibration stamp, the
        fused-dispatch accounting, and a pulsar-axis sharding plan; the
        scan-fused bucket executable's CollectiveProfile shows the
        data-parallel story (no all-reduce contractions — any
        collective bytes are resharding overhead, tiny next to
        compute)."""
        import tools.scalewatch as sw
        from tools.telemetry_report import validate_multichip_record

        monkeypatch.setattr(sw, "_CATALOG_PULSARS", 4)
        monkeypatch.setattr(sw, "_CATALOG_NTOA_RANGE", (48, 96))
        monkeypatch.setattr(sw, "_CATALOG_NTOA_LADDER", (96,))
        monkeypatch.setattr(sw, "_CATALOG_STEPS", 4)
        monkeypatch.setattr(sw, "_CAL_FLOOR_S", 0.02)
        assert sw.run_worker(2, workload="catalog") == 0
        recs = _records_from_output(capsys.readouterr().out)
        errors = []
        for rec in recs:
            validate_multichip_record(rec, "catalog worker", errors)
        assert errors == []
        by_kind = {}
        for rec in recs:
            by_kind.setdefault(rec["record"], []).append(rec)
        meas = by_kind["measurement"][0]
        assert meas["workload"] == "catalog_batched_fit"
        assert meas["n_devices"] == 2
        assert meas["fits_per_sec"] > 0
        assert meas["n_pulsars"] == 4
        assert meas["plan"]["axes"][0] == "pulsar"
        plan = by_kind["sharding_plan"][0]["sharding_plan"]
        assert plan["mesh"] == {"pulsar": 2}
        coll = by_kind["collective"][0]["collective"]
        assert "all-reduce" not in (coll.get("ops") or {})

    def test_workloads_gate_against_their_own_series(self, tmp_path,
                                                     capsys):
        """A catalog artifact entering a grid history must not be
        cross-gated: each workload trends its own series."""
        _artifact(1, 0.80, tmp_path=tmp_path)
        _artifact(2, 0.78, tmp_path=tmp_path)
        # a first catalog artifact at a very different efficiency: with
        # cross-gating this would be a fake regression of the grid
        _artifact(3, 0.30, ratio=0.01, tmp_path=tmp_path,
                  workload="catalog_batched_fit")
        assert main(["--check", "--dir", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "catalog_batched_fit: 1 artifact(s)" in out

    def test_catalog_series_regression_fails(self, tmp_path, capsys):
        _artifact(1, 0.80, tmp_path=tmp_path,
                  workload="catalog_batched_fit")
        _artifact(2, 0.40, tmp_path=tmp_path,
                  workload="catalog_batched_fit")  # -50%
        assert main(["--check", "--dir", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "catalog_batched_fit/efficiency_at_max" in out

    def test_mixed_series_gate_independently(self, tmp_path, capsys):
        """Interleaved grid and catalog artifacts: a catalog regression
        fails even when the grid series is flat (and names the right
        series)."""
        _artifact(1, 0.80, tmp_path=tmp_path)
        _artifact(2, 0.80, tmp_path=tmp_path,
                  workload="catalog_batched_fit")
        _artifact(3, 0.79, tmp_path=tmp_path)
        _artifact(4, 0.35, tmp_path=tmp_path,
                  workload="catalog_batched_fit")  # -56%
        assert main(["--check", "--dir", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "catalog_batched_fit/efficiency_at_max" in out
        assert "[ok] synthetic_gls_grid/efficiency_at_max" in out
