"""Global clock-correction repository semantics (reference
``observatory/global_clock_corrections.py``): index parsing, download
policies against a local mirror, expiry, invalid-if-older-than, export."""

import os
import time

import pytest

INDEX = """\
# File                    Update (days)  Invalid if older than
index.txt                 1.0            ---
gps2utc.clk               7.0            ---  GPS to UTC
T2runtime/clock/wsrt2gps.clk  30.0       2021-09-14  WSRT
time_gbt.dat              0.5            ---  GBT
"""


@pytest.fixture
def repo(tmp_path, monkeypatch):
    r = tmp_path / "repo"
    (r / "T2runtime" / "clock").mkdir(parents=True)
    (r / "index.txt").write_text(INDEX)
    (r / "gps2utc.clk").write_text("# UTC(GPS) UTC\n50000.0 0.0\n51000.0 1e-8\n")
    (r / "T2runtime" / "clock" / "wsrt2gps.clk").write_text(
        "# UTC(WSRT) UTC(GPS)\n50000.0 0.0\n")
    (r / "time_gbt.dat").write_text("   50000.00 0.00\n")
    cache = tmp_path / "cache"
    monkeypatch.setenv("PINT_CLOCK_REPO", str(r))
    monkeypatch.setenv("PINT_CLOCK_CACHE", str(cache))
    monkeypatch.delenv("PINT_CLOCK_DIR", raising=False)
    return r, cache


class TestIndex:
    def test_parse(self, repo):
        from pint_tpu.observatory.global_clock_corrections import Index

        idx = Index()
        assert set(idx.files) == {"index.txt", "gps2utc.clk", "wsrt2gps.clk",
                                  "time_gbt.dat"}
        e = idx.files["wsrt2gps.clk"]
        assert e.file == "T2runtime/clock/wsrt2gps.clk"
        assert e.update_interval_days == 30.0
        assert e.invalid_if_older_than is not None  # 2021-09-14 stamp
        assert idx.files["gps2utc.clk"].invalid_if_older_than is None


class TestPolicies:
    def test_if_missing_copies_once(self, repo):
        from pint_tpu.observatory.global_clock_corrections import get_file

        r, cache = repo
        p = get_file("gps2utc.clk", download_policy="if_missing")
        assert p.exists() and p.parent == cache
        mtime = p.stat().st_mtime
        # repo copy changes, but if_missing keeps the cached one
        (r / "gps2utc.clk").write_text("changed\n")
        p2 = get_file("gps2utc.clk", download_policy="if_missing")
        assert p2.read_text().startswith("# UTC(GPS)")
        assert p2.stat().st_mtime == mtime

    def test_never_requires_cache(self, repo):
        from pint_tpu.observatory.global_clock_corrections import get_file

        with pytest.raises(FileNotFoundError):
            get_file("gps2utc.clk", download_policy="never")
        get_file("gps2utc.clk", download_policy="if_missing")
        assert get_file("gps2utc.clk", download_policy="never").exists()

    def test_if_expired_refreshes_old_copy(self, repo):
        from pint_tpu.observatory.global_clock_corrections import get_file

        r, cache = repo
        p = get_file("gps2utc.clk")  # if_expired, fresh copy
        (r / "gps2utc.clk").write_text("v2\n")
        # young copy: not refreshed
        assert get_file("gps2utc.clk").read_text().startswith("# UTC")
        # age the cache copy past the interval: refreshed
        old = time.time() - 8 * 86400
        os.utime(p, (old, old))
        assert get_file("gps2utc.clk", update_interval_days=7.0
                        ).read_text() == "v2\n"

    def test_invalid_if_older_than(self, repo):
        from pint_tpu.observatory.global_clock_corrections import get_file

        r, cache = repo
        name = "T2runtime/clock/wsrt2gps.clk"
        p = get_file(name, update_interval_days=1e9)
        (r / name).write_text("v2\n")
        # fresh enough for the interval, but force-invalidate via stamp
        assert get_file(name, update_interval_days=1e9).read_text() != "v2\n"
        assert get_file(name, update_interval_days=1e9,
                        invalid_if_older_than=time.time() + 10
                        ).read_text() == "v2\n"

    def test_always_refreshes(self, repo):
        from pint_tpu.observatory.global_clock_corrections import get_file

        r, _ = repo
        get_file("time_gbt.dat", download_policy="always")
        (r / "time_gbt.dat").write_text("v2\n")
        assert get_file("time_gbt.dat", download_policy="always"
                        ).read_text() == "v2\n"

    def test_unknown_policy(self, repo):
        from pint_tpu.observatory.global_clock_corrections import get_file

        with pytest.raises(ValueError):
            get_file("gps2utc.clk", download_policy="sometimes")

    def test_stale_cache_survives_missing_repo_file(self, repo):
        from pint_tpu.observatory.global_clock_corrections import get_file

        r, _ = repo
        p = get_file("time_gbt.dat")
        (r / "time_gbt.dat").unlink()
        old = time.time() - 86400
        os.utime(p, (old, old))
        # due for refresh but repo copy is gone: stale cache returned
        assert get_file("time_gbt.dat", update_interval_days=0.5).exists()

    def test_always_raises_without_repo_source(self, repo):
        """policy='always' promises a refresh; a stale cache copy must not
        silently satisfy it when the repository copy is gone."""
        from pint_tpu.observatory.global_clock_corrections import get_file

        r, _ = repo
        get_file("time_gbt.dat")
        (r / "time_gbt.dat").unlink()
        with pytest.raises(FileNotFoundError):
            get_file("time_gbt.dat", download_policy="always")
        # non-'always' policies still fall back to the stale copy, even when
        # the copy is due for refresh (exercises the src-is-None branch)
        import os as _os
        import time as _time

        p = get_file("time_gbt.dat", download_policy="never")
        old = _time.time() - 86400
        _os.utime(p, (old, old))
        assert get_file("time_gbt.dat", download_policy="if_expired",
                        update_interval_days=0.5).exists()


class TestLookupAndUpdateAll:
    def test_lookup_via_index(self, repo):
        from pint_tpu.observatory.global_clock_corrections import (
            get_clock_correction_file)

        p = get_clock_correction_file("wsrt2gps.clk")
        assert p is not None and p.endswith("wsrt2gps.clk")
        with pytest.raises(KeyError):
            get_clock_correction_file("unknown.clk")

    def test_lookup_without_repo_falls_back(self, tmp_path, monkeypatch):
        from pint_tpu.observatory.global_clock_corrections import (
            get_clock_correction_file)

        monkeypatch.delenv("PINT_CLOCK_REPO", raising=False)
        d = tmp_path / "plain"
        d.mkdir()
        (d / "x.clk").write_text("data\n")
        monkeypatch.setenv("PINT_CLOCK_DIR", str(d))
        assert get_clock_correction_file("x.clk") == str(d / "x.clk")
        assert get_clock_correction_file("y.clk") is None

    def test_update_all_exports(self, repo, tmp_path):
        from pint_tpu.observatory.global_clock_corrections import update_all

        out = tmp_path / "export"
        done = update_all(export_to=str(out))
        assert set(done) == {"index.txt", "gps2utc.clk", "wsrt2gps.clk",
                             "time_gbt.dat"}
        assert (out / "wsrt2gps.clk").exists()

    def test_http_repo_rejected(self, repo, monkeypatch):
        from pint_tpu.observatory.global_clock_corrections import (
            get_clock_correction_file)

        monkeypatch.setenv("PINT_CLOCK_REPO", "https://example.com/repo")
        monkeypatch.delenv("PINT_CLOCK_DIR", raising=False)
        # network repos are refused in zero-egress; falls back to None
        assert get_clock_correction_file("gps2utc.clk") is None


class TestObservatoryIntegration:
    def test_update_and_export_clock_files(self, repo, tmp_path, monkeypatch):
        """Repository -> cache -> live clock chain -> export round trip
        (reference observatory/__init__.py:802, topo_obs.py:425)."""
        import numpy as np

        from pint_tpu.observatory import (export_all_clock_files,
                                          get_observatory,
                                          update_clock_files)
        from pint_tpu.observatory import clock_file as _cf

        r, cache = repo
        # a GBT site file the repo provides in tempo format
        (r / "time_gbt.dat").write_text(
            "   50000.00000 0.00\n   51000.00000 2.00\n")
        _cf._cache.clear()
        done = update_clock_files(bipm_versions=["BIPM2019"])
        assert "time_gbt.dat" in done and "gps2utc.clk" in done
        # the chain now finds the cached copies: nonzero corrections
        gbt = get_observatory("gbt")
        corr = gbt.clock_corrections(np.array([50500.0]), include_bipm=False)
        # site file contributes 1 us at the midpoint + gps2utc 1.5e-6ish
        assert corr[0] != 0.0
        out = export_all_clock_files(tmp_path / "exported")
        assert any(p.endswith("time_gbt.dat") for p in out)
        assert any(p.endswith("gps2utc.clk") for p in out)
        _cf._cache.clear()

    def test_update_skips_files_missing_from_repo(self, repo):
        """Regression: a file listed in index.txt but absent from the
        repository is skipped with a warning, not a crash."""
        from pint_tpu.observatory import update_clock_files
        from pint_tpu.observatory import clock_file as _cf

        r, _ = repo
        (r / "gps2utc.clk").unlink()  # listed in the index, now absent
        _cf._cache.clear()
        done = update_clock_files()
        assert "gps2utc.clk" not in done
        assert "time_gbt.dat" in done
        _cf._cache.clear()


class TestGlobalClockFile:
    def test_auto_refresh_past_end(self, repo):
        """Evaluating beyond the loaded span re-checks the repository and
        picks up extended data (reference clock_file.py:781 behavior)."""
        import numpy as np

        from pint_tpu.observatory.clock_file import GlobalClockFile

        r, cache = repo
        (r / "gps2utc.clk").write_text(
            "# UTC(GPS) UTC\n50000.00000 1.0e-6\n51000.00000 1.0e-6\n")
        gcf = GlobalClockFile("gps2utc.clk", fmt="tempo2")
        assert gcf.last_correction_mjd() == 51000.0
        assert gcf.evaluate(np.array([50500.0]))[0] == pytest.approx(1e-6)
        # repository gains newer data; age the cache copy past its interval
        (r / "gps2utc.clk").write_text(
            "# UTC(GPS) UTC\n50000.00000 1.0e-6\n52000.00000 3.0e-6\n")
        old = time.time() - 8 * 86400
        os.utime(gcf._path, (old, old))
        val = gcf.evaluate(np.array([51500.0]))[0]
        assert gcf.last_correction_mjd() == 52000.0
        assert val == pytest.approx(2.5e-6)

    def test_update_reports_changes(self, repo):
        from pint_tpu.observatory.clock_file import GlobalClockFile

        r, _ = repo
        gcf = GlobalClockFile("time_gbt.dat", fmt="tempo")
        assert gcf.update() is False  # fresh copy, nothing new
        (r / "time_gbt.dat").write_text("   50000.00 1.00\n")
        old = time.time() - 86400
        os.utime(gcf._path, (old, old))
        assert gcf.update() is True

    def test_missing_raises_no_clock_corrections(self, repo, monkeypatch):
        from pint_tpu.exceptions import NoClockCorrections
        from pint_tpu.observatory.clock_file import GlobalClockFile

        with pytest.raises(NoClockCorrections):
            GlobalClockFile("nope.clk")

    def test_empty_eval_and_failed_refresh(self, repo):
        """Empty MJD arrays pass through; a failed refresh warns and serves
        the loaded data instead of raising."""
        import numpy as np

        from pint_tpu.observatory.clock_file import GlobalClockFile

        r, _ = repo
        gcf = GlobalClockFile("gps2utc.clk", fmt="tempo2")
        assert gcf.evaluate(np.array([])).size == 0
        # repository disappears; evaluation past the end must still work
        (r / "gps2utc.clk").unlink()
        (r / "index.txt").write_text("time_gbt.dat 0.5 ---\n")
        old = time.time() - 8 * 86400
        os.utime(gcf._path, (old, old))
        vals = gcf.evaluate(np.array([60000.0]))  # past end, refresh fails
        assert np.isfinite(vals).all()

    def test_reference_views_and_export(self, repo, tmp_path):
        """time/clock/leading_comment/comments/export on the repository
        wrapper (reference ``clock_file.py:903`` surface)."""
        import numpy as np

        from pint_tpu.observatory.clock_file import (ClockFile,
                                                     GlobalClockFile)

        r, cache = repo
        (r / "gps2utc.clk").write_text(
            "# UTC(GPS) UTC\n50000.00000 1.0e-6\n51000.00000 2.0e-6\n")
        gcf = GlobalClockFile("gps2utc.clk", fmt="tempo2")
        np.testing.assert_array_equal(gcf.time, [50000.0, 51000.0])
        np.testing.assert_allclose(gcf.clock, [1.0, 2.0])  # microseconds
        assert "UTC(GPS)" in gcf.leading_comment
        assert len(gcf.comments) == 2
        out = tmp_path / "exported.clk"
        gcf.export(str(out))
        re_read = ClockFile.read(str(out), fmt="tempo2")
        np.testing.assert_allclose(
            re_read.evaluate(np.array([50500.0]))[0],
            gcf.evaluate(np.array([50500.0]))[0])
