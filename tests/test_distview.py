"""Distributed-execution observatory under test (telemetry/distview.py).

The contract tier-1 (8 virtual CPU devices) pins: the HLO collective
scrape turns a sharded executable into a schema-valid CollectiveProfile
(all-reduce count/bytes, comm/compute ratio, mesh axes) and NEVER raises
into the fit path; a deliberately unsharded executable yields an EMPTY
profile (ratio exactly 0.0 — a measurement, not a degradation); sharding
plans land in the runlog event stream AND the run manifest; and the grid
attach path records all three observatory documents in full mode.
"""

import json
import os
import sys

import numpy as np
import pytest

pytestmark = [pytest.mark.distview, pytest.mark.perfwatch]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tests.test_costs import _tiny_gls_fitter, fresh_telemetry  # noqa: E402,F401


# ---------------------------------------------------------------------------
# HLO text parsing (no backend required)
# ---------------------------------------------------------------------------

class TestHLOParse:
    def test_basic_all_reduce(self):
        from pint_tpu.telemetry.distview import parse_hlo_collectives

        hlo = ('ROOT %all-reduce = f32[5,5]{1,0} all-reduce(f32[5,5]{1,0} '
               '%dot), channel_id=1, replica_groups=[1,8]<=[8], '
               'use_global_device_ids=true, to_apply=%add.clone')
        out = parse_hlo_collectives(hlo)
        assert out == [("all-reduce", 100.0, 8)]

    def test_f64_and_explicit_groups(self):
        from pint_tpu.telemetry.distview import parse_hlo_collectives

        hlo = ('%ag = f64[16,3]{1,0} all-gather(f64[4,3]{1,0} %p), '
               'replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}')
        (kind, nbytes, group), = parse_hlo_collectives(hlo)
        assert kind == "all-gather"
        assert nbytes == 16 * 3 * 8
        assert group == 4

    def test_tuple_shape_and_async_start(self):
        """Async `-start` halves carry the payload; their tuple result
        repeats the OPERAND next to the result, so the payload is the
        largest member — the async spelling must report the SAME bytes
        as the sync spelling of the collective (the scaling gate
        compares the number across plans/backends).  `-done` halves
        carry none and are skipped."""
        from pint_tpu.telemetry.distview import parse_hlo_collectives

        hlo = ("%ars = (f32[4]{0}, f32[4]{0}) all-reduce-start(f32[4]{0} "
               "%x), replica_groups=[2,4]<=[8], to_apply=%add\n"
               "%ard = f32[4]{0} all-reduce-done((f32[4]{0}, f32[4]{0}) "
               "%ars)")
        out = parse_hlo_collectives(hlo)
        assert len(out) == 1
        kind, nbytes, group = out[0]
        assert kind == "all-reduce" and nbytes == 4 * 4 and group == 4
        sync = parse_hlo_collectives(
            "%ar = f32[4]{0} all-reduce(f32[4]{0} %x), "
            "replica_groups=[2,4]<=[8], to_apply=%add")
        assert sync[0][1] == nbytes  # async == sync bytes

    def test_async_all_gather_start_counts_result_not_operand(self):
        """all-gather-start's tuple is (operand, result): bytes must be
        the gathered RESULT, with collective-permute-start's u32 context
        members ignored too."""
        from pint_tpu.telemetry.distview import parse_hlo_collectives

        hlo = ("%ags = (f32[4,3]{1,0}, f32[32,3]{1,0}) all-gather-start("
               "f32[4,3]{1,0} %p), replica_groups=[1,8]<=[8], "
               "dimensions={0}\n"
               "%cps = (f32[8]{0}, f32[8]{0}, u32[], u32[]) "
               "collective-permute-start(f32[8]{0} %q), "
               "source_target_pairs={{0,1},{1,0}}")
        out = parse_hlo_collectives(hlo)
        assert out[0] == ("all-gather", 32 * 3 * 4, 8)
        assert out[1][0] == "collective-permute" and out[1][1] == 8 * 4

    def test_async_reduce_scatter_start_counts_scattered_result(self):
        """reduce-scatter-start's tuple is (operand, result) with the
        result 1/N of the operand: bytes must be the scattered RESULT
        (matching the sync spelling), not the max() tuple member — or a
        backend flipping sync<->async emission would shift the comm-
        ratio gate by ~N x with no real plan change."""
        from pint_tpu.telemetry.distview import parse_hlo_collectives

        hlo = ("%rss = (f32[1024]{0}, f32[128]{0}) reduce-scatter-start("
               "f32[1024]{0} %x), replica_groups=[1,8]<=[8], "
               "dimensions={0}, to_apply=%add")
        out = parse_hlo_collectives(hlo)
        assert out == [("reduce-scatter", 128 * 4, 8)]
        sync = parse_hlo_collectives(
            "%rs = f32[128]{0} reduce-scatter(f32[1024]{0} %x), "
            "replica_groups=[1,8]<=[8], dimensions={0}, to_apply=%add")
        assert sync[0][1] == out[0][1]  # async == sync bytes

    def test_every_kind_and_no_collectives(self):
        from pint_tpu.telemetry.distview import (COLLECTIVE_KINDS,
                                                 parse_hlo_collectives)

        lines = [f"%c{i} = f32[2]{{0}} {kind}(f32[2]{{0}} %x)"
                 for i, kind in enumerate(COLLECTIVE_KINDS)]
        out = parse_hlo_collectives("\n".join(lines))
        assert [k for k, _, _ in out] == list(COLLECTIVE_KINDS)
        assert parse_hlo_collectives(
            "%fusion = f32[8]{0} fusion(f32[8]{0} %p), kind=kLoop") == []

    def test_metadata_mentions_do_not_match(self):
        """op_name metadata strings mentioning reductions must not be
        scraped as collectives."""
        from pint_tpu.telemetry.distview import parse_hlo_collectives

        hlo = ('%f = f32[8]{0} fusion(f32[8]{0} %p), metadata='
               '{op_name="jit(f)/all_reduce_sum" source_file="x.py"}')
        assert parse_hlo_collectives(hlo) == []


class TestCollectiveProfileSchema:
    def test_to_dict_complete_and_json(self):
        from pint_tpu.telemetry.distview import (COLLECTIVE_PROFILE_SCHEMA,
                                                 CollectiveProfile)

        p = CollectiveProfile(name="x", num_devices=8,
                              mesh_axes={"toa": 8}, compute_bytes=100.0)
        p.add("all-reduce", 25.0, 8)
        p.add("all-reduce", 25.0, 8)
        d = p.to_dict()
        assert d["schema"] == COLLECTIVE_PROFILE_SCHEMA
        assert d["ops"]["all-reduce"] == {"count": 2, "bytes": 50.0}
        assert d["collective_count"] == 2
        assert d["collective_bytes"] == 50.0
        assert d["comm_compute_ratio"] == 0.5
        assert d["group_sizes"] == [8]
        json.dumps(d)

    def test_degraded_profile_schema_valid(self):
        from pint_tpu.telemetry.distview import CollectiveProfile

        d = CollectiveProfile(name="broken", error="synthetic").to_dict()
        assert d["error"] == "synthetic"
        assert d["ops"] == {} and d["collective_bytes"] == 0.0
        assert d["comm_compute_ratio"] is None  # compute unknown: no 0
        json.dumps(d)

    def test_ratio_null_without_compute_bytes(self):
        from pint_tpu.telemetry.distview import CollectiveProfile

        p = CollectiveProfile(name="x")
        p.add("all-gather", 10.0, 2)
        assert p.comm_compute_ratio is None


# ---------------------------------------------------------------------------
# analysis entry points on the 8-virtual-device CPU backend
# ---------------------------------------------------------------------------

class TestAnalyze:
    def test_sharded_normal_equations_show_reduce_scatter(
            self, eight_devices):
        """The GLS normal-equation reduction over a TOA-sharded mesh
        compiles to the reduce-scatter kernel (ISSUE 14: each device
        materializes only its Gram slice) — >= 1 reduce-scatter with
        non-zero bytes and NO full-Gram all-reduce; the legacy
        ``scatter=False`` spelling still shows the all-reduce the
        plan-strategy tunable ranks against."""
        import jax
        from jax.sharding import Mesh

        from pint_tpu.telemetry import distview

        f = _tiny_gls_fitter()
        f.fit_toas(maxiter=1)
        mesh = Mesh(np.array(eight_devices), ("toa",))
        fn, args = f.gls_normal_equations_executable(mesh=mesh)
        prof = distview.analyze_jitted_collectives(
            fn, *args, name="gls.normal_eq")
        assert prof.error is None
        rs = prof.ops.get("reduce-scatter")
        assert rs is not None and rs["count"] >= 1 and rs["bytes"] > 0
        assert "all-reduce" not in prof.ops
        assert prof.mesh_axes == {"toa": 8}
        assert prof.num_devices == 8
        assert prof.comm_compute_ratio is not None \
            and prof.comm_compute_ratio > 0
        # and the executable actually runs to a finite system
        mtcm, mtcy = fn(*args)
        assert np.all(np.isfinite(np.asarray(mtcm)))
        assert np.all(np.isfinite(np.asarray(mtcy)))
        # legacy comparison form: the full-Gram all-reduce
        fn_ar, args_ar = f.gls_normal_equations_executable(
            mesh=mesh, scatter=False)
        prof_ar = distview.analyze_jitted_collectives(
            fn_ar, *args_ar, name="gls.normal_eq.allreduce")
        ar = prof_ar.ops.get("all-reduce")
        assert ar is not None and ar["count"] >= 1 and ar["bytes"] > 0

    def test_unsharded_executable_empty_profile(self):
        """Degrade-never-raise twin: an unsharded executable yields an
        EMPTY CollectiveProfile (ratio exactly 0.0, no error)."""
        from pint_tpu.telemetry import distview

        f = _tiny_gls_fitter()
        f.fit_toas(maxiter=1)
        fn, args = f.gls_normal_equations_executable()
        prof = distview.analyze_jitted_collectives(fn, *args, name="plain")
        assert prof.error is None
        assert prof.ops == {}
        assert prof.collective_bytes == 0.0
        assert prof.comm_compute_ratio == 0.0

    def test_uncompilable_degrades_never_raises(self):
        from pint_tpu.telemetry import distview

        prof = distview.analyze_jitted_collectives(
            lambda z: z, 1.0, name="notjitted")
        assert prof.error is not None and "lower/compile" in prof.error
        json.dumps(prof.to_dict())

    def test_hostile_compiled_degrades(self):
        """A backend whose as_text/cost_analysis RAISE still yields a
        schema-valid profile carrying the error string."""
        from pint_tpu.telemetry import distview

        class Hostile:
            def as_text(self):
                raise RuntimeError("no HLO for you")

            def cost_analysis(self):
                raise NotImplementedError

        prof = distview.analyze_compiled_collectives(Hostile(), "hostile")
        assert "no HLO for you" in prof.error
        assert prof.ops == {}
        json.dumps(prof.to_dict())

    def test_shared_compile_cache_with_costs(self, eight_devices):
        """Cost + collective + plan analysis of one executable pays ONE
        AOT compile (the shared compiled_for cache) and the deliberate
        compile stays out of the workload counters."""
        import jax
        import jax.numpy as jnp

        from pint_tpu.telemetry import distview

        class CountingJit:
            """Duck-typed jitted fn: counts lower() calls."""

            def __init__(self, fn):
                self.fn = fn
                self.lowers = 0

            def lower(self, *a, **k):
                self.lowers += 1
                return self.fn.lower(*a, **k)

        f = CountingJit(jax.jit(lambda x: (x * 3).sum()))
        x = jnp.arange(23.0)
        obs = distview.observe_jitted(f, x, name="shared")
        assert f.lowers == 1, "observe_jitted must compile exactly once"
        assert obs["cost"]["flops"] is not None
        assert obs["collectives"]["ops"] == {}
        assert obs["sharding_plan"]["name"] == "shared"


class TestShardingPlan:
    def test_plan_of_sharded_executable(self, eight_devices):
        from jax.sharding import Mesh

        from pint_tpu.telemetry import distview
        from pint_tpu.telemetry.distview import SHARDING_PLAN_SCHEMA

        f = _tiny_gls_fitter()
        f.fit_toas(maxiter=1)
        mesh = Mesh(np.array(eight_devices), ("toa",))
        fn, args = f.gls_normal_equations_executable(mesh=mesh)
        plan = distview.sharding_plan_of_jitted(fn, *args, name="ne")
        assert plan["schema"] == SHARDING_PLAN_SCHEMA
        assert plan["mesh"] == {"toa": 8}
        assert plan["num_devices"] == 8
        assert any("toa" in s for s in plan["inputs"])
        assert plan["error"] is None
        json.dumps(plan)

    def test_plan_degrades_on_garbage(self):
        from pint_tpu.telemetry import distview

        plan = distview.sharding_plan_of(object(), "junk")
        assert plan["mesh"] is None and plan["inputs"] == []
        json.dumps(plan)


# ---------------------------------------------------------------------------
# recording: runlog events + manifest fold-in, end to end through grid
# ---------------------------------------------------------------------------

def _read_events(run_dir):
    out = []
    with open(os.path.join(run_dir, "events.jsonl"), encoding="utf-8") as f:
        for line in f:
            out.append(json.loads(line))
    return out


class TestRecording:
    def test_records_land_in_runlog_and_manifest(self, fresh_telemetry,
                                                 tmp_path):
        from pint_tpu.telemetry import distview, runlog
        from pint_tpu.telemetry.distview import CollectiveProfile

        fresh_telemetry.activate("full")
        run_dir = str(tmp_path / "run")
        runlog.start_run(run_dir, name="distview-e2e", probe_device=False)
        prof = CollectiveProfile(name="synthetic", num_devices=4,
                                 mesh_axes={"grid": 4},
                                 compute_bytes=10.0)
        prof.add("all-reduce", 5.0, 4)
        distview.record_collective_profile(prof)
        distview.record_sharding_plan(
            {"schema": distview.SHARDING_PLAN_SCHEMA, "name": "synthetic",
             "mesh": {"grid": 4}, "num_devices": 4, "backend": "cpu",
             "inputs": ["PartitionSpec('grid',)"], "outputs": [],
             "error": None})
        runlog.end_run()
        events = _read_events(run_dir)
        types = [e["type"] for e in events]
        assert "collective_profile" in types
        assert "sharding_plan" in types
        with open(os.path.join(run_dir, "manifest.json"),
                  encoding="utf-8") as f:
            manifest = json.load(f)
        assert "synthetic" in manifest["sharding_plans"]
        # and the report CLI accepts the whole run
        from tools.telemetry_report import main as report_main

        assert report_main(["--check", run_dir]) == 0

    def test_record_off_mode_is_noop(self, fresh_telemetry):
        from pint_tpu.telemetry import distview
        from pint_tpu.telemetry.distview import CollectiveProfile

        prof = CollectiveProfile(name="off")
        assert distview.record_collective_profile(prof) is prof
        plan = {"name": "off"}
        assert distview.record_sharding_plan(plan) is plan

    def test_grid_full_mode_streams_all_three(self, fresh_telemetry,
                                              tmp_path, eight_devices):
        """grid_chisq on a mesh, under full telemetry: the runlog gains
        cost_profile + collective_profile + sharding_plan records for
        the sharded chunk executable, and the manifest knows the mesh."""
        from jax.sharding import Mesh

        from pint_tpu.grid import grid_chisq
        from pint_tpu.telemetry import runlog

        f = _tiny_gls_fitter()
        fresh_telemetry.activate("full")
        run_dir = str(tmp_path / "run")
        runlog.start_run(run_dir, name="grid-dist", probe_device=False)
        f.fit_toas(maxiter=1)
        g0 = np.linspace(f.model.F0.value - 1e-9, f.model.F0.value + 1e-9, 4)
        g1 = np.linspace(f.model.F1.value - 1e-17,
                         f.model.F1.value + 1e-17, 4)
        mesh = Mesh(np.array(eight_devices), ("grid",))
        chi2, _ = grid_chisq(f, ("F0", "F1"), (g0, g1), niter=1, mesh=mesh)
        assert np.all(np.isfinite(chi2))
        runlog.end_run()
        events = _read_events(run_dir)
        by_type = {}
        for e in events:
            by_type.setdefault(e["type"], []).append(e)
        assert "cost_profile" in by_type
        colls = [e["collective_profile"]
                 for e in by_type.get("collective_profile", [])]
        assert any(c["name"] == "grid.chunk" for c in colls)
        plans = [e["sharding_plan"]
                 for e in by_type.get("sharding_plan", [])]
        grid_plans = [p for p in plans if p["name"] == "grid.chunk"]
        assert grid_plans and grid_plans[0]["mesh"] == {"grid": 8}
        with open(os.path.join(run_dir, "manifest.json"),
                  encoding="utf-8") as f_:
            manifest = json.load(f_)
        assert manifest["sharding_plans"]["grid.chunk"]["mesh"] == \
            {"grid": 8}

    def test_observe_grid_before_any_grid_degrades(self):
        from pint_tpu.telemetry import distview

        class Bare:
            pass

        obs = distview.observe_grid(Bare())
        assert "grid_chisq" in obs["collectives"]["error"]
        assert "grid_chisq" in obs["sharding_plan"]["error"]
        json.dumps(obs)
