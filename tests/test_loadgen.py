"""Traffic-engineering tests (PR 16).

Pins the load-bearing contracts of the serving traffic layer:

* **shed semantics** — admission control resolves the shed caller's
  future with a typed :class:`~pint_tpu.serving.admission.
  ShedResponse` (``strict=True``: the old ``UsageError``), and a shed
  NEVER fails a coalesced batch-mate;
* **hysteresis** — shedding engages at the high watermark and
  disengages only below the low watermark: a square-wave queue depth
  oscillating between the watermarks produces exactly the pinned
  engage/disengage transition count, no flapping;
* **starvation protection** — a fit flood concurrent with posterior
  traffic keeps posterior p99 under its deadline budget while the fit
  backlog drains in weighted-fair quanta (pinned fairness bound
  through the load harness);
* **determinism** — the load generator's full schedule is a pure
  function of its seed;
* **escalation** — sustained shedding runs the degradation ladder in
  reverse, one rung at a time, capped by the healthy device set;
* **event contracts** — ``load_run`` / ``request_shed`` /
  ``mesh_escalated`` records validate through ``telemetry_report
  --check`` and malformed twins are rejected.
"""

import asyncio
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.loadgen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pint_tpu.exceptions import UsageError  # noqa: E402
from pint_tpu.serving import service  # noqa: E402
from pint_tpu.serving.admission import (  # noqa: E402
    AdmissionConfig,
    AdmissionController,
    ShedResponse,
)
from pint_tpu.serving.batcher import FitRequest  # noqa: E402
from pint_tpu.serving.loadgen import (  # noqa: E402
    LoadConfig,
    LoadGenerator,
    ShapePopulation,
)
from pint_tpu.serving.scheduler import (  # noqa: E402
    PressureEscalator,
    Scheduler,
    SchedulerConfig,
)


def _fit_request(rng, n=48, k=6, request_id=None):
    M = rng.standard_normal((n, k))
    r = 1e-6 * rng.standard_normal(n)
    w = 1.0 / (1e-12 + 1e-13 * rng.random(n))
    return FitRequest(M=M, r=r, w=w, phiinv=np.zeros(k),
                      request_id=request_id)


class _StubFlowSpec:
    def suffix(self):
        return ""


class _StubFlow:
    spec = _StubFlowSpec()


class _StubPosterior:
    """The minimal surface the posterior door's dispatch path touches
    (pool lookups miss, so the kernels run directly as host numpy) —
    contention tests need the door's scheduling, not a trained flow."""

    ndim = 2
    params = np.zeros(1)
    flow = _StubFlow()

    def ident(self):
        return "stub"

    def serve_vkey(self):
        return ("stub",)

    def draw_kernel(self, n):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(params, keys):
            return jnp.zeros((keys.shape[0], n, self.ndim))

        return fn

    def logprob_kernel(self, n):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(params, pts):
            return jnp.zeros(pts.shape[:2])

        return fn


def _stub_service(max_queue=256, admission=None, window_ms=1.0):
    svc = service.TimingService(service.ServeConfig(
        ntoa_buckets=(64,), nfree_buckets=(8,), batch_buckets=(1, 4, 16),
        draw_buckets=(32,), window_ms=window_ms, max_queue=max_queue,
        admission=admission))
    svc.register_posterior(_StubPosterior(), seed=0)
    return svc


# ---------------------------------------------------------------------------
# shed semantics
# ---------------------------------------------------------------------------

class TestShedSemantics:
    def test_shed_response_enums_validated(self):
        with pytest.raises(UsageError):
            ShedResponse(request_class="grid", reason="queue_full",
                         retry_after_ms=1.0)
        with pytest.raises(UsageError):
            ShedResponse(request_class="fit", reason="tired",
                         retry_after_ms=1.0)
        assert ShedResponse(request_class="fit", reason="queue_full",
                            retry_after_ms=1.0).shed is True

    def test_admission_config_validated(self):
        with pytest.raises(UsageError):
            AdmissionConfig(high_watermark=0.0)
        with pytest.raises(UsageError):
            AdmissionConfig(high_watermark=0.5, low_watermark=0.8)
        with pytest.raises(UsageError):
            AdmissionConfig(latency_high_ms=10.0, latency_low_ms=None)

    def test_shed_never_fails_batch_mates(self):
        """The acceptance criterion: the overflow request resolves with
        its OWN ShedResponse while every admitted batch-mate in the
        same coalescing window completes normally."""
        rng = np.random.default_rng(0)
        svc = _stub_service(max_queue=3)

        async def go():
            admitted = [asyncio.ensure_future(
                svc.submit(_fit_request(rng, request_id=f"ok-{i}")))
                for i in range(3)]
            await asyncio.sleep(0)   # enqueue all three (depth = cap)
            shed = await svc.submit(_fit_request(rng, request_id="over"))
            return await asyncio.gather(*admitted), shed

        results, shed = asyncio.run(go())
        assert isinstance(shed, ShedResponse)
        assert shed.reason == "queue_full"
        assert shed.request_id == "over"
        assert len(results) == 3
        for res in results:
            assert not getattr(res, "shed", False)
            assert np.isfinite(res.chi2)

    def test_posterior_and_update_doors_shed_typed(self):
        """All three doors speak ShedResponse (the fit door is pinned
        in test_serving); posterior here, and strict=True restores the
        exception on the same door."""
        svc = _stub_service(max_queue=1)

        async def go():
            t1 = asyncio.ensure_future(svc.submit_posterior(
                service.PosteriorRequest(n_draws=8)))
            await asyncio.sleep(0)
            shed = await svc.submit_posterior(
                service.PosteriorRequest(n_draws=8))
            assert isinstance(shed, ShedResponse)
            assert shed.request_class == "posterior"
            with pytest.raises(UsageError):
                await svc.submit_posterior(
                    service.PosteriorRequest(n_draws=8), strict=True)
            return await t1

        res = asyncio.run(go())
        assert res.kind == "draw"


# ---------------------------------------------------------------------------
# hysteresis
# ---------------------------------------------------------------------------

class TestHysteresis:
    def test_square_wave_no_flapping(self):
        """Depth oscillating between the watermarks must not flap the
        controller: one engage on the way up, one disengage after the
        drain below LOW — exactly two transitions for the whole wave."""
        ctl = AdmissionController(
            AdmissionConfig(high_watermark=0.8, low_watermark=0.4),
            max_queue=100)
        assert ctl.check("fit", 10) is None
        assert not ctl.shedding("fit")
        # rising edge: engage at >= 80
        assert ctl.check("fit", 85) is not None
        assert ctl.shedding("fit")
        # square wave BETWEEN the watermarks: stays engaged throughout
        for depth in (75, 85, 60, 85, 45, 79) * 4:
            shed = ctl.check("fit", depth)
            assert shed is not None, f"disengaged at depth {depth}"
            assert shed.reason == "queue_depth"
        assert ctl.transitions("fit") == 1
        # drain below LOW: disengage, and stay admitted between the
        # watermarks on the way back up
        assert ctl.check("fit", 30) is None
        assert not ctl.shedding("fit")
        for depth in (45, 70, 79, 60) * 4:
            assert ctl.check("fit", depth) is None
        assert ctl.transitions("fit") == 2

    def test_hard_cap_sheds_regardless(self):
        """max_queue is a hard cap: full depth sheds queue_full even
        when hysteresis would otherwise admit."""
        ctl = AdmissionController(AdmissionConfig(), max_queue=10)
        shed = ctl.check("update", 10)
        assert shed is not None and shed.reason == "queue_full"

    def test_latency_watermarks(self):
        ctl = AdmissionController(
            AdmissionConfig(high_watermark=1.0, low_watermark=0.5,
                            latency_high_ms=100.0, latency_low_ms=50.0),
            max_queue=1000)
        assert ctl.check("posterior", 1, p99_ms=80.0) is None
        shed = ctl.check("posterior", 1, p99_ms=150.0)
        assert shed is not None and shed.reason == "latency"
        # hysteresis: 80 ms is above the LOW watermark — still shedding
        assert ctl.check("posterior", 1, p99_ms=80.0) is not None
        assert ctl.check("posterior", 1, p99_ms=40.0) is None

    def test_unknown_class_rejected(self):
        with pytest.raises(UsageError):
            AdmissionController().check("grid", 0)


# ---------------------------------------------------------------------------
# scheduler arbitration
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_config_validated(self):
        with pytest.raises(UsageError):
            SchedulerConfig(weights={"grid": 1})
        with pytest.raises(UsageError):
            SchedulerConfig(weights={"fit": 0})
        with pytest.raises(UsageError):
            SchedulerConfig(deadlines_ms={"fit": -1.0})

    def test_priority_weights_and_quanta(self):
        s = Scheduler()
        assert s.weight("posterior") > s.weight("update") > s.weight("fit")
        assert s.quantum("posterior") == 4 * s.quantum("fit")

    def test_deadline_aware_window(self):
        s = Scheduler(SchedulerConfig(deadlines_ms={"posterior": 100.0}))
        # plenty of slack: the configured window stands
        assert s.window_s("posterior", 2.0, p99_ms=10.0) == 2.0 / 1e3
        # p99 eats the budget: the window shrinks to the slack
        assert s.window_s("posterior", 2.0, p99_ms=99.5) == 0.5 / 1e3
        # budget exhausted: never negative
        assert s.window_s("posterior", 2.0, p99_ms=500.0) == 0.0
        # no deadline configured: full window
        assert s.window_s("fit", 2.0, p99_ms=1e9) == 2.0 / 1e3

    def test_at_risk(self):
        s = Scheduler(SchedulerConfig(deadlines_ms={"posterior": 100.0}))
        assert not s.at_risk("posterior", oldest_wait_ms=10.0,
                             p99_ms=20.0)
        assert s.at_risk("posterior", oldest_wait_ms=90.0, p99_ms=20.0)
        assert not s.at_risk("fit", oldest_wait_ms=1e9, p99_ms=1e9)

    def test_fit_flood_does_not_starve_posterior(self):
        """The starvation pin: a 120-request fit flood concurrent with
        posterior traffic — every posterior request completes under its
        deadline budget while the fit backlog drains in quanta (many
        dispatches, not one mega-batch), and the harness fairness index
        holds the pinned bound."""
        svc = _stub_service(max_queue=512)
        # steady state: pre-compile every bucket the flood will hit, so
        # the p99 measures arbitration, not first-call compiles
        svc.warm([(b, 64, 8) for b in (1, 4, 16)])
        svc.warm_posterior([(b, 32) for b in (1, 4, 16)])
        rng = np.random.default_rng(1)

        async def go():
            flood = [asyncio.ensure_future(svc.submit(
                _fit_request(rng, request_id=f"flood-{i}")))
                for i in range(120)]
            await asyncio.sleep(0)
            post = [asyncio.ensure_future(svc.submit_posterior(
                service.PosteriorRequest(n_draws=8,
                                         request_id=f"p-{i}")))
                for i in range(8)]
            return await asyncio.gather(*flood), \
                await asyncio.gather(*post)

        fits, posts = asyncio.run(go())
        assert all(not getattr(r, "shed", False) for r in fits + posts)
        budget = svc.scheduler.deadline_ms("posterior")
        p99 = svc.posterior_latency_summary()["p99_ms"]
        assert p99 < budget, f"posterior p99 {p99} past {budget} ms"
        sched = svc.scheduler.to_dict()
        # weighted-fair dispatch: the flood split into >= quantum-sized
        # chunks (120 / 16 -> >= 8 dispatch passes)
        assert sched["fit"]["dispatches"] >= 8
        assert sched["fit"]["served"] == 120
        assert sched["posterior"]["served"] == 8

    def test_load_harness_fairness_bound(self):
        """The pinned fairness bound through the real harness: a 4:1
        fit:posterior closed-loop mix on an uncontended service keeps
        Jain's index at 1.0-ish (>= 0.9) — both classes get their
        offered load through."""
        svc = _stub_service(max_queue=256)
        shapes = ShapePopulation.synthetic(n=4, seed=2,
                                           ntoa_range=(24, 64),
                                           nfree_range=(3, 8))
        cfg = LoadConfig(arrival="closed", concurrency=4, n_requests=40,
                         mix={"fit": 4.0, "posterior": 1.0}, seed=3,
                         posterior_draws=8)
        rep = LoadGenerator(svc, cfg, shapes=shapes).run()
        assert rep.offered == 40
        assert rep.completed + rep.shed == rep.offered
        assert rep.fairness >= 0.9, rep.to_dict()


# ---------------------------------------------------------------------------
# load-generator determinism
# ---------------------------------------------------------------------------

class TestLoadGenDeterminism:
    def test_same_seed_same_schedule(self):
        svc = _stub_service()
        shapes = ShapePopulation.synthetic(n=5, seed=4)
        cfg = LoadConfig(arrival="open", rps=100.0, n_requests=64,
                         mix={"fit": 3.0, "posterior": 1.0}, seed=7)
        a = LoadGenerator(svc, cfg, shapes=shapes).schedule()
        b = LoadGenerator(svc, cfg, shapes=shapes).schedule()
        assert a == b
        assert len(a) == 64
        assert any(k == "posterior" for _, k, _ in a)
        assert all(t >= 0 for t, _, _ in a)
        # open arrivals are strictly ordered (cumulative gaps)
        ts = [t for t, _, _ in a]
        assert ts == sorted(ts)

    def test_different_seed_different_schedule(self):
        svc = _stub_service()
        shapes = ShapePopulation.synthetic(n=5, seed=4)
        a = LoadGenerator(svc, LoadConfig(seed=1, n_requests=32),
                          shapes=shapes).schedule()
        b = LoadGenerator(svc, LoadConfig(seed=2, n_requests=32),
                          shapes=shapes).schedule()
        assert a != b

    def test_config_validated(self):
        with pytest.raises(UsageError):
            LoadConfig(arrival="bursty")
        with pytest.raises(UsageError):
            LoadConfig(mix={})
        with pytest.raises(UsageError):
            LoadConfig(mix={"grid": 1.0})
        with pytest.raises(UsageError):
            LoadConfig(mix={"fit": 0.0})
        with pytest.raises(UsageError):
            ShapePopulation([])
        with pytest.raises(UsageError):
            ShapePopulation([(4, 8)])   # n_free > n_toas

    def test_mix_requires_registered_doors(self):
        svc = service.TimingService(service.ServeConfig(
            ntoa_buckets=(64,), nfree_buckets=(8,)))
        with pytest.raises(UsageError):
            LoadGenerator(svc, LoadConfig(mix={"posterior": 1.0}))
        with pytest.raises(UsageError):
            LoadGenerator(svc, LoadConfig(mix={"update": 1.0}))

    def test_open_loop_accounting(self):
        svc = _stub_service(max_queue=128)
        shapes = ShapePopulation.synthetic(n=4, seed=5)
        rep = LoadGenerator(svc, LoadConfig(
            arrival="open", rps=2000.0, n_requests=48,
            mix={"fit": 1.0}, seed=6), shapes=shapes).run()
        assert rep.completed + rep.shed == rep.offered == 48
        assert rep.per_class["fit"]["offered"] == 48
        assert 0.0 <= rep.shed_rate <= 1.0


# ---------------------------------------------------------------------------
# pressure escalation (the ladder in reverse)
# ---------------------------------------------------------------------------

class _Dev:
    """ExecutionPlan only touches .id/.platform until .mesh is built —
    a test rung never builds the mesh."""

    def __init__(self, i):
        self.id = i
        self.platform = "cpu"


class TestPressureEscalation:
    def test_sustained_shedding_escalates_one_rung(self):
        devs = [_Dev(i) for i in range(4)]
        esc = PressureEscalator(devices=devs, sustain=3, start_rung=1)
        assert esc.rung == 1
        assert esc.observe(True) is None
        assert esc.observe(True) is None
        plan = esc.observe(True)       # third consecutive: escalate
        assert plan is not None and esc.rung == 2
        # pressure persists: another sustained episode doubles again
        for _ in range(2):
            assert esc.observe(True) is None
        assert esc.observe(True) is not None
        assert esc.rung == 4

    def test_calm_resets_the_streak(self):
        devs = [_Dev(i) for i in range(4)]
        esc = PressureEscalator(devices=devs, sustain=3)
        esc.observe(True)
        esc.observe(True)
        assert esc.observe(False) is None   # streak broken
        esc.observe(True)
        esc.observe(True)
        assert esc.rung == 1                # never reached sustain

    def test_capped_at_healthy_ladder(self):
        devs = [_Dev(i) for i in range(2)]
        esc = PressureEscalator(devices=devs, sustain=1, start_rung=2)
        assert esc.rung == 2
        # rung already at the 2-device ladder top: capped, no event,
        # and the cap latches until pressure clears
        assert esc.observe(True) is None
        assert esc.observe(True) is None
        assert esc.rung == 2
        esc.observe(False)
        assert esc.observe(True) is None    # still capped at the top
        assert esc.rung == 2

    def test_sustain_validated(self):
        with pytest.raises(UsageError):
            PressureEscalator(devices=[_Dev(0)], sustain=0)

    def test_service_opt_in(self):
        svc = _stub_service(max_queue=2)
        esc = svc.enable_escalation(devices=[_Dev(i) for i in range(4)],
                                    sustain=2)
        assert svc.escalator is esc
        rng = np.random.default_rng(9)

        async def go():
            t = asyncio.ensure_future(svc.submit(_fit_request(rng)))
            t2 = asyncio.ensure_future(svc.submit(_fit_request(rng)))
            await asyncio.sleep(0)
            # two consecutive shed observations trip the escalator
            s1 = await svc.submit(_fit_request(rng))
            s2 = await svc.submit(_fit_request(rng))
            return await t, await t2, s1, s2

        _, _, s1, s2 = asyncio.run(go())
        assert isinstance(s1, ShedResponse)
        assert isinstance(s2, ShedResponse)
        assert esc.rung == 2


# ---------------------------------------------------------------------------
# event contracts (telemetry_report --check)
# ---------------------------------------------------------------------------

class TestLoadEventValidation:
    def _validate(self, tmp_path, **attrs):
        from pint_tpu import telemetry
        from pint_tpu.telemetry import runlog
        from tools.telemetry_report import validate_run_dir

        run_dir = str(tmp_path / "run")
        telemetry.activate("full")
        try:
            run = runlog.start_run(run_dir, name="load-events",
                                   probe_device=False)
            run.record_event(attrs.pop("_name"), **attrs)
            runlog.end_run()
        finally:
            telemetry.deactivate()
        errors = []
        validate_run_dir(run_dir, errors)
        return errors

    def _load_run_attrs(self, **over):
        attrs = dict(_name="load_run", arrival="open", duration_s=2.0,
                     offered=100, completed=90, shed=10, shed_rate=0.1,
                     fairness=0.95, fit_rps=40.0, posterior_rps=10.0,
                     update_rps=0.0, predict_rps=0.0, fit_p99_ms=80.0,
                     posterior_p99_ms=30.0, update_p99_ms=0.0,
                     predict_p99_ms=0.0)
        attrs.update(over)
        return attrs

    def test_valid_load_run_passes(self, tmp_path):
        assert not self._validate(tmp_path, **self._load_run_attrs())

    def test_unknown_arrival_rejected(self, tmp_path):
        errors = self._validate(
            tmp_path, **self._load_run_attrs(arrival="bursty"))
        assert any("arrival" in e for e in errors)

    def test_unbalanced_accounting_rejected(self, tmp_path):
        errors = self._validate(
            tmp_path, **self._load_run_attrs(completed=50, shed=10))
        assert any("offered" in e for e in errors)

    def test_shed_rate_out_of_range_rejected(self, tmp_path):
        errors = self._validate(
            tmp_path, **self._load_run_attrs(shed_rate=1.5))
        assert any("shed_rate" in e for e in errors)

    def test_valid_request_shed_passes(self, tmp_path):
        assert not self._validate(
            tmp_path, _name="request_shed", request_class="fit",
            reason="queue_depth", retry_after_ms=5.0, queue_depth=40)

    def test_bad_shed_reason_rejected(self, tmp_path):
        errors = self._validate(
            tmp_path, _name="request_shed", request_class="fit",
            reason="tired", retry_after_ms=5.0, queue_depth=40)
        assert any("reason" in e for e in errors)

    def test_nonpositive_retry_hint_rejected(self, tmp_path):
        errors = self._validate(
            tmp_path, _name="request_shed", request_class="fit",
            reason="queue_full", retry_after_ms=0.0, queue_depth=40)
        assert any("retry_after_ms" in e for e in errors)

    def test_valid_mesh_escalated_passes(self, tmp_path):
        assert not self._validate(
            tmp_path, _name="mesh_escalated", from_rung=1, to_rung=2,
            reason="sustained_shedding", workload="gls_normal_eq",
            n_healthy=4)

    def test_downward_escalation_rejected(self, tmp_path):
        errors = self._validate(
            tmp_path, _name="mesh_escalated", from_rung=4, to_rung=2,
            reason="sustained_shedding", workload="gls_normal_eq",
            n_healthy=4)
        assert any("to_rung" in e for e in errors)

    def test_live_shed_event_validates(self, tmp_path):
        """End to end: the admission controller's OWN emission passes
        the --check contract."""
        from pint_tpu import telemetry
        from pint_tpu.telemetry import runlog
        from tools.telemetry_report import validate_run_dir

        run_dir = str(tmp_path / "run")
        telemetry.activate("full")
        try:
            runlog.start_run(run_dir, name="live-shed",
                             probe_device=False)
            ctl = AdmissionController(AdmissionConfig(), max_queue=4)
            assert ctl.check("fit", 4, window_ms=2.0) is not None
            runlog.end_run()
        finally:
            telemetry.deactivate()
        errors = []
        validate_run_dir(run_dir, errors)
        assert not errors, errors


# ---------------------------------------------------------------------------
# selftest entry point
# ---------------------------------------------------------------------------

class TestSelftest:
    def test_selftest_passes(self):
        """The pre-commit hook's exact entry point returns 0."""
        from pint_tpu.serving import loadgen

        assert loadgen.main(["--selftest"]) == 0
