"""Phase-prediction subsystem tests (PR 19).

Pins the load-bearing contracts of ``pint_tpu/predict``:

* **generation parity** — batched on-device predictor phases match the
  full ``TimingModel`` phase evaluation to < 1e-9 cycles across every
  window of a multi-window, multi-pulsar grid, AND match host
  ``Polycos`` evaluation on the same coefficients;
* **polyco boundary exactness** — ``find_entry``/``valid`` at window
  edges: a shared edge resolves to exactly one entry (no gap, no
  double-cover), and the TEMPO write -> read round trip holds the
  format's quantization precision;
* **incremental invalidation** — an accepted streaming append
  regenerates ONLY the windows whose validity spans the appended
  epochs (``regen_count`` witness), a quarantine-only batch
  regenerates zero, and post-invalidation predictions match a
  from-scratch cache bitwise;
* **warm path** — populate the AOT cache -> ``jax.clear_caches()`` ->
  fresh pool -> all-hit re-warm -> a coalesced predict batch serves at
  ``compiles == 0``;
* **traffic** — the predict door sheds typed, validates before
  enqueue (a bad request never fails its batch-mates), and a loadgen
  mixed run including the ``predict`` class passes its SLO with
  balanced shed accounting.
"""

import asyncio
import copy
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.predict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from pint_tpu import config  # noqa: E402
from pint_tpu.exceptions import UsageError  # noqa: E402
from pint_tpu.polycos import (  # noqa: E402
    MIN_PER_DAY,
    PolycoEntry,
    Polycos,
)
from pint_tpu.predict import (  # noqa: E402
    PredictorCache,
    PredictRequest,
    generate_predictor_sets,
)
from pint_tpu.predict.door import (  # noqa: E402
    run_predict_requests,
    update_epoch_span,
)
from pint_tpu.predict.generate import fit_windows, window_tmids  # noqa: E402
from pint_tpu.serving import aotcache, service  # noqa: E402
from pint_tpu.serving.admission import ShedResponse  # noqa: E402

#: isolated-pulsar stand-ins (barycentric so the pins need no clock
#: chain): same scale as the NGC6440E walkthrough, two distinct
#: solutions for the multi-pulsar generation pin
PAR_A = """\
PSR PREDTESTA
RAJ 17:48:52.75
DECJ -20:21:29.0
F0 61.485476554
F1 -1.181e-15
PEPOCH 53750
DM 223.9
EPHEM DE421
UNITS TDB
"""

PAR_B = """\
PSR PREDTESTB
RAJ 17:13:49.53
DECJ +07:47:37.5
F0 218.8118438
F1 -4.08e-16
PEPOCH 53750
DM 15.99
EPHEM DE421
UNITS TDB
"""

T0 = 53750.0


def _get_model(par):
    from pint_tpu.models import get_model

    return get_model([ln + "\n" for ln in par.splitlines()])


@pytest.fixture(scope="module")
def model_a():
    return _get_model(PAR_A)


@pytest.fixture(scope="module")
def model_b():
    return _get_model(PAR_B)


@pytest.fixture
def aot_dir(tmp_path):
    """An enabled AOT cache rooted in tmp, torn down afterwards."""
    d = str(tmp_path / "aot")
    config.set_aot_cache_dir(d)
    yield d
    config.set_aot_cache_dir(None)
    aotcache.reset_cache_singleton()


def _model_phase(model, mjds):
    """The full TimingModel absolute phase at barycentric UTC epochs —
    the same host pipeline predictor generation fits against."""
    from pint_tpu.toa import TOAs

    mjds = np.asarray(mjds, dtype=np.float64)
    n = len(mjds)
    ts = TOAs(
        utc_mjd=np.asarray(mjds, dtype=np.longdouble),
        error_us=np.ones(n), freq_mhz=np.full(n, 1400.0),
        obs=np.array(["barycenter"] * n, dtype=object),
        flags=[{} for _ in range(n)],
    )
    ts.clock_corr_s = np.zeros(n)
    ephem = model.EPHEM.value or "DE440"
    ts.compute_TDBs(ephem=ephem)
    ts.compute_posvels(ephem=ephem,
                       planets=bool(model.PLANET_SHAPIRO.value))
    ph = model.phase(ts, abs_phase="AbsPhase" in model.components)
    return np.asarray(ph.int_), np.asarray(ph.frac)


def _window_probes(pset):
    """Interior sample epochs hitting EVERY window of a predictor
    set's grid (4 per window, none on an edge)."""
    offs = np.array([-0.9, -0.35, 0.4, 0.85])
    half_d = pset.segLength / (2 * MIN_PER_DAY)
    return (pset.tmid[:, None] + offs[None, :] * half_d).ravel()


# ---------------------------------------------------------------------------
# polyco boundary exactness + TEMPO round trip (satellite hardening)
# ---------------------------------------------------------------------------

class TestPolycoBoundaries:
    #: 45 min = 0.03125 d = 2^-5: the span is exact in binary, so
    #: handcrafted window edges align bitwise and the half-open
    #: dispatch rule is tested at EXACT shared edges, not near them
    SPAN_MIN = 45.0
    NWIN = 4

    def _grid(self, base=55000.0):
        span_d = self.SPAN_MIN / MIN_PER_DAY
        assert span_d == 0.03125  # exact binary, by construction
        return Polycos([
            PolycoEntry(base + (k + 0.5) * span_d, self.SPAN_MIN,
                        0, 0.0, 100.0, 3, np.zeros(3),
                        psrname="EDGETEST")
            for k in range(self.NWIN)])

    def test_edges_bitwise_aligned(self):
        pol = self._grid()
        for a, b in zip(pol.entries[:-1], pol.entries[1:]):
            assert a.tstop == b.tstart  # bitwise: no gap, no overlap

    def test_shared_edge_single_cover(self):
        """t exactly ON an interior edge is valid for exactly ONE
        entry (the half-open ``tstart <= t < tstop`` rule) and
        find_entry returns that entry — no gap, no double-cover."""
        pol = self._grid()
        for k in range(self.NWIN - 1):
            t = pol.entries[k].tstop
            covering = [e for e in pol.entries if bool(e.valid(t))]
            assert covering == [pol.entries[k + 1]]
            assert pol.find_entry(t) is pol.entries[k + 1]

    def test_grid_start_and_end(self):
        """The opening edge belongs to the first entry; the closing
        edge is outside every half-open span but dispatches to the
        last entry through EDGE_TOL (distance exactly 0) — the grid
        answers for its full advertised coverage."""
        pol = self._grid()
        t_start = pol.entries[0].tstart
        assert bool(pol.entries[0].valid(t_start))
        assert pol.find_entry(t_start) is pol.entries[0]
        t_end = pol.entries[-1].tstop
        assert not any(bool(e.valid(t_end)) for e in pol.entries)
        assert pol.find_entry(t_end) is pol.entries[-1]

    def test_interior_dispatch(self):
        pol = self._grid()
        for k, e in enumerate(pol.entries):
            assert pol.find_entry(e.tmid) is e

    def test_outside_coverage_raises(self):
        pol = self._grid()
        with pytest.raises(ValueError):
            pol.find_entry(pol.entries[0].tstart - 1.0)
        with pytest.raises(ValueError):
            pol.find_entry(pol.entries[-1].tstop + 1.0)

    def test_tempo_round_trip_precision(self, tmp_path, model_a):
        """TEMPO write -> read: tmid and coefficients survive exactly
        (%.11f pre-quantized; %25.17e covers float64), the reference
        phase to its %.6f quantization — so round-tripped phases agree
        to < 2e-6 cycles and frequencies to < 1e-9 Hz."""
        pol = Polycos.generate_polycos(model_a, T0, T0 + 0.25, "@",
                                       30, 12, 1400.0)
        path = str(tmp_path / "polyco_rt.dat")
        pol.write_polyco_file(path)
        back = Polycos.read_polyco_file(path)
        assert len(back.entries) == len(pol.entries)
        for a, b in zip(pol.entries, back.entries):
            assert b.tmid == a.tmid
            assert b.mjdspan == a.mjdspan
            assert np.array_equal(b.coeffs, a.coeffs)
            assert abs(b.f0 - a.f0) <= 5e-13
            da = a.rphase_int + a.rphase_frac
            db = b.rphase_int + b.rphase_frac
            assert abs(db - da) <= 5.1e-7  # %.6f quantization
        rng = np.random.default_rng(3)
        t = np.sort(rng.uniform(T0 + 1e-6, T0 + 0.25 - 1e-6, 64))
        pa, pb = pol.eval_abs_phase(t), back.eval_abs_phase(t)
        dphase = (np.asarray(pb.int_) - np.asarray(pa.int_)
                  + np.asarray(pb.frac) - np.asarray(pa.frac))
        assert np.max(np.abs(dphase)) < 2e-6
        dfreq = back.eval_spin_freq(t) - pol.eval_spin_freq(t)
        assert np.max(np.abs(dfreq)) < 1e-9


# ---------------------------------------------------------------------------
# batched on-device generation (the tentpole parity pin)
# ---------------------------------------------------------------------------

class TestBatchedGeneration:
    def test_multi_pulsar_multi_window_parity(self, model_a, model_b):
        """The acceptance pin: one vmapped device fit over BOTH
        pulsars' windows; the resulting predictors match the full
        TimingModel phase to < 1e-9 cycles at probes in EVERY window,
        and match the host generator (``Polycos.generate_polycos``)
        evaluated on its own coefficients to the same bar."""
        sets = generate_predictor_sets([model_a, model_b], T0,
                                       T0 + 0.5, "@", segLength=30.0,
                                       ncoeff=12)
        assert len(sets) == 2
        for model, pset in zip((model_a, model_b), sets):
            assert pset.n_windows == 24
            assert np.all(pset.fit_rms < 1e-8)
            t = _window_probes(pset)
            dev = pset.to_polycos().eval_abs_phase(t)
            mi, mf = _model_phase(model, t)
            dphase = (np.asarray(dev.int_) - mi
                      + np.asarray(dev.frac) - mf)
            worst = float(np.max(np.abs(dphase)))
            assert worst < 1e-9, \
                f"{pset.psrname}: device vs model {worst:.2e} cycles"
            host = Polycos.generate_polycos(model, T0, T0 + 0.5, "@",
                                            30, 12, 1400.0)
            hp = host.eval_abs_phase(t)
            dhost = (np.asarray(dev.int_) - np.asarray(hp.int_)
                     + np.asarray(dev.frac) - np.asarray(hp.frac))
            assert float(np.max(np.abs(dhost))) < 1e-9

    def test_device_eval_kernel_parity(self, model_a):
        """The door's batched EVAL kernel (not just the host Horner)
        against the full model phase and the host polyco frequency,
        across every window of the grid."""
        cache = PredictorCache(model_a, T0, T0 + 0.25, obs="@",
                               segLength=30.0, ncoeff=12)
        pset = cache.to_predictor_set()
        t = _window_probes(pset)
        out = run_predict_requests(cache, None, [PredictRequest(t)])
        assert len(out) == 1 and out[0].windows == cache.n_windows
        mi, mf = _model_phase(model_a, t)
        dphase = (out[0].phase_int - mi + out[0].phase_frac - mf)
        assert float(np.max(np.abs(dphase))) < 1e-9
        fhost = pset.to_polycos().eval_spin_freq(t)
        assert float(np.max(np.abs(out[0].freq - fhost))) < 1e-9

    def test_window_bucket_shares_executable(self):
        """Grids of nearby window counts pad onto the same ladder rung:
        the second fit at a different W but the same rung pays zero
        fresh compiles (the ShapeBatcher discipline)."""
        from pint_tpu.telemetry import jaxevents

        rng = np.random.default_rng(0)
        ncoeff, nnode, half = 5, 14, 2.0
        c_true = rng.normal(size=(1, ncoeff))

        def fit(W):
            x = np.tile(np.linspace(-1.0, 1.0, nnode), (W, 1))
            dt = x * half
            y = sum(c_true[0, j] * dt ** j for j in range(ncoeff))
            return fit_windows(x, y, ncoeff, half)

        coeffs, rms = fit(5)                       # may compile
        assert coeffs.shape == (5, ncoeff)
        assert np.allclose(coeffs, c_true, atol=1e-9)
        assert np.all(rms < 1e-9)
        before = jaxevents.counts()
        coeffs2, _ = fit(9)                        # same rung (16)
        assert (jaxevents.counts() - before).compiles == 0
        assert coeffs2.shape == (9, ncoeff)
        assert np.allclose(coeffs2, c_true, atol=1e-9)

    def test_input_validation(self, model_a):
        with pytest.raises(UsageError):
            fit_windows(np.zeros((2, 8)), np.zeros((3, 8)), 4, 1.0)
        with pytest.raises(UsageError):
            window_tmids(55000.0, 55000.0, 60.0)
        with pytest.raises(UsageError):
            generate_predictor_sets([], 55000.0, 55001.0, "@")
        with pytest.raises(UsageError):
            PredictorCache(model_a, T0, T0 + 1.0, ncoeff=1)
        with pytest.raises(UsageError):
            PredictRequest(times_mjd=np.zeros((2, 2)))


# ---------------------------------------------------------------------------
# incremental invalidation (cache unit + streaming integration)
# ---------------------------------------------------------------------------

class TestCacheInvalidation:
    def _mids(self, cache):
        """Every window's midpoint, from the public coverage."""
        lo, hi = cache.coverage()
        W = cache.n_windows
        return lo + (np.arange(W) + 0.5) * (hi - lo) / W

    def test_lazy_build_and_hit_accounting(self, model_a):
        cache = PredictorCache(model_a, T0, T0 + 0.25, obs="@",
                               segLength=60.0, ncoeff=6)
        assert cache.n_windows == 6
        mids = self._mids(cache)
        cache.predict(mids[:2])               # builds 2 windows
        st = cache.stats()
        assert st["misses"] == 2 and st["regenerated"] == 2
        cache.predict(mids[:2])               # pure hits
        st = cache.stats()
        assert st["hits"] == 2 and st["misses"] == 2
        assert np.array_equal(cache.regen_count,
                              np.array([1, 1, 0, 0, 0, 0]))

    def test_invalidate_all_and_span(self, model_a):
        cache = PredictorCache(model_a, T0, T0 + 0.25, obs="@",
                               segLength=60.0, ncoeff=6)
        cache.build()
        lo, hi = cache.coverage()
        # a span over windows 2-3 only
        n = cache.invalidate_span(lo + 0.105, lo + 0.14)
        assert n == 2
        cache.predict(self._mids(cache))
        assert np.array_equal(cache.regen_count,
                              np.array([1, 1, 2, 2, 1, 1]))
        assert cache.invalidate_all() == cache.n_windows
        # a second invalidation of already-stale windows is a no-op
        assert cache.invalidate_span(lo, hi) == 0

    def test_model_mutation_safety_net(self, model_a):
        """A parameter moved OUTSIDE the streaming hook still stales
        the grid: the vkey signature check on the gather path."""
        model = _get_model(PAR_A)
        cache = PredictorCache(model, T0, T0 + 0.25, obs="@",
                               segLength=120.0, ncoeff=6)
        t = self._mids(cache)[:1]
        p0 = cache.predict(t)
        rc = cache.regen_count.copy()
        model.F0.value = model.F0.value + 1e-7
        p1 = cache.predict(t)
        assert cache.regen_count[0] == rc[0] + 1
        assert cache.stats()["invalidated"] >= 1
        d0 = p0[0] + p0[1]
        d1 = p1[0] + p1[1]
        assert not np.array_equal(d0, d1)  # the moved F0 shows up

    def test_outside_coverage_refused(self, model_a):
        cache = PredictorCache(model_a, T0, T0 + 0.25, obs="@",
                               segLength=60.0, ncoeff=6)
        with pytest.raises(UsageError):
            cache.window_of(T0 + 2.0)
        with pytest.raises(UsageError):
            cache.predict(np.array([T0 - 1.0]))

    def test_update_epoch_span(self):
        from types import SimpleNamespace as NS

        reqs = [
            NS(kind="append",
               new_toas=NS(utc_mjd=np.array([55010.0, 55012.5]))),
            NS(kind="quarantine", new_toas=None),
            NS(kind="append",
               new_toas=NS(utc_mjd=np.array([55001.25]))),
        ]
        assert update_epoch_span(reqs) == (55001.25, 55012.5)
        assert update_epoch_span(reqs[1:2]) == (None, None)
        assert update_epoch_span([]) == (None, None)


class TestStreamingInvalidation:
    """The service-level incremental pin on a live streaming engine."""

    #: the streaming test workload's B1855 stand-in (spin + red noise,
    #: DM frozen — the rank-k engine's own acceptance configuration)
    STREAM_PAR = """\
PSR STREAMPRED
RAJ 04:37:15.0
DECJ -47:15:09.0
F0 173.6879 1
F1 -1.7e-15 1
PEPOCH 55000
DM 2.64
EFAC mjd 50000 60000 1.1
TNRedAmp -13.5
TNRedGam 3.5
TNRedC 5
TNREDTSPAN 6.0
UNITS TDB
"""

    @pytest.fixture(scope="class")
    def workload(self):
        from pint_tpu.simulation import make_fake_toas_uniform

        model = _get_model(self.STREAM_PAR)
        rng = np.random.default_rng(7)
        toas = make_fake_toas_uniform(
            53400, 54800, 116, model, freq=np.array([800.0, 1400.0]),
            error_us=1.0, add_noise=True, rng=rng)
        base = toas[np.arange(100)]
        blocks = [toas[np.arange(100 + 8 * i, 100 + 8 * (i + 1))]
                  for i in range(2)]
        return model, base, blocks

    def test_streaming_invalidation_scenario(self, workload):
        """The full acceptance scenario on one engine: an accepted
        append stales EXACTLY the windows spanning its epochs (and the
        regen_count witness shows only those regenerate); a
        quarantined-only batch regenerates zero; the post-invalidation
        prediction matches a from-scratch cache bitwise."""
        from pint_tpu.gls_fitter import GLSFitter
        from pint_tpu.streaming import UpdateRequest

        model, base, blocks = workload
        f = GLSFitter(base, copy.deepcopy(model))
        f.fit_toas(maxiter=3)
        svc = service.TimingService(service.ServeConfig(
            time_buckets=(16,), batch_buckets=(1, 4)))
        svc.register_stream(f, warm=False)

        block = blocks[0]
        b_mjd = np.asarray(block.utc_mjd, dtype=np.float64)
        lo_b, hi_b = float(b_mjd.min()), float(b_mjd.max())
        cache = PredictorCache(f.model, lo_b - 6.0, hi_b + 6.0,
                               obs="@", segLength=2880.0, ncoeff=6)
        svc.register_predictor(cache, warm=False)
        cache.build()
        assert np.all(cache.regen_count == 1)
        # the windows whose validity spans the appended epochs — a
        # contiguous run, located through the public dispatch
        w_lo = int(cache.window_of(np.array([lo_b]))[0])
        w_hi = int(cache.window_of(np.array([hi_b]))[0])
        spanned = np.arange(w_lo, w_hi + 1)
        assert 0 < len(spanned) < cache.n_windows

        # --- accepted append: the solution moves ----------------------
        out = svc.serve_updates(
            [UpdateRequest(new_toas=copy.deepcopy(block))])
        assert len(out) == 1
        assert cache.invalidated == len(spanned), \
            "append must stale exactly the spanned windows"
        lo_c, hi_c = cache.coverage()
        W = cache.n_windows
        mids = lo_c + (np.arange(W) + 0.5) * (hi_c - lo_c) / W
        cache.predict(mids)                   # touch EVERY window
        expect = np.ones(W, dtype=np.int64)
        expect[spanned] += 1
        assert np.array_equal(cache.regen_count, expect), \
            "only the spanned windows may regenerate"

        # --- quarantine-only batch: solution untouched ----------------
        bad = copy.deepcopy(blocks[1])
        bad.error_us[:] = -1.0                # every row quarantined
        inv0, reg0 = cache.invalidated, cache.regenerated
        svc.serve_updates([UpdateRequest(new_toas=bad)])
        assert cache.invalidated == inv0
        assert cache.regenerated == reg0
        cache.predict(mids)
        assert np.array_equal(cache.regen_count, expect), \
            "a quarantine-only batch must regenerate nothing"

        # --- bitwise from-scratch agreement ---------------------------
        probes = mids[spanned]
        p1 = cache.predict(probes)
        scratch = PredictorCache(svc.stream.fitter.model,
                                 lo_b - 6.0, hi_b + 6.0, obs="@",
                                 segLength=2880.0, ncoeff=6)
        p2 = scratch.predict(probes)
        for a, b, what in zip(p1, p2, ("int", "frac", "freq")):
            assert np.array_equal(a, b), \
                f"post-invalidation {what} != from-scratch (bitwise)"


# ---------------------------------------------------------------------------
# the predict door
# ---------------------------------------------------------------------------

def _small_cache(model, span=0.25, seg=60.0, ncoeff=6):
    return PredictorCache(model, T0, T0 + span, obs="@",
                          segLength=seg, ncoeff=ncoeff)


class TestPredictDoor:
    def test_unregistered_door_refuses(self):
        svc = service.TimingService(service.ServeConfig())
        with pytest.raises(UsageError):
            svc.serve_predicts([PredictRequest(np.array([T0]))])
        with pytest.raises(UsageError):
            svc.register_predictor(object())

    def test_request_order_and_buckets(self, model_a):
        """Mixed-size requests group by time-ladder rung and chunk at
        the batch top, but results come back in REQUEST order and
        match the cache's host evaluation."""
        cache = _small_cache(model_a)
        svc = service.TimingService(service.ServeConfig(
            time_buckets=(8, 32), batch_buckets=(1, 2)))
        svc.register_predictor(cache, warm=False)
        lo, hi = cache.coverage()
        rng = np.random.default_rng(5)
        sizes = [20, 4, 25, 6]
        reqs = [PredictRequest(
            np.sort(rng.uniform(lo + 1e-6, hi - 1e-6, n)),
            request_id=f"q{i}") for i, n in enumerate(sizes)]
        out = svc.serve_predicts(reqs)
        assert [r.request_id for r in out] == [q.request_id
                                              for q in reqs]
        assert [r.bucket for r in out] == [32, 8, 32, 8]
        for q, r in zip(reqs, out):
            assert len(r.phase_frac) == q.n
            hi_, hf, hfreq = cache.predict(q.times_mjd)
            d = (r.phase_int - hi_) + (r.phase_frac - hf)
            assert float(np.max(np.abs(d))) < 1e-9
            assert float(np.max(np.abs(r.freq - hfreq))) < 1e-9
        assert svc.predicts_served == 4
        assert svc.predict_latency_summary()["n"] == 4

    def test_submit_validates_before_enqueue(self, model_a):
        """A malformed or out-of-coverage request fails its OWN
        awaiter immediately — the admitted batch-mate still serves."""
        cache = _small_cache(model_a)
        svc = service.TimingService(service.ServeConfig(
            time_buckets=(16,), batch_buckets=(1, 2), window_ms=1.0))
        svc.register_predictor(cache, warm=False)
        lo, hi = cache.coverage()
        good = PredictRequest(np.linspace(lo + 1e-4, hi - 1e-4, 8))

        async def go():
            mate = asyncio.ensure_future(svc.submit_predict(good))
            await asyncio.sleep(0)
            with pytest.raises(UsageError):
                await svc.submit_predict(
                    PredictRequest(np.array([hi + 5.0])))
            with pytest.raises(UsageError):
                await svc.submit_predict("phase please")
            return await mate

        res = asyncio.run(go())
        assert not getattr(res, "shed", False)
        assert np.all(np.isfinite(res.phase_frac))

    def test_shed_is_typed_and_strict_raises(self, model_a):
        cache = _small_cache(model_a)
        svc = service.TimingService(service.ServeConfig(
            time_buckets=(16,), batch_buckets=(1, 2), window_ms=1.0,
            max_queue=1))
        svc.register_predictor(cache, warm=False)
        lo, hi = cache.coverage()

        def req():
            return PredictRequest(np.linspace(lo + 1e-4, hi - 1e-4, 8))

        async def go():
            t1 = asyncio.ensure_future(svc.submit_predict(req()))
            await asyncio.sleep(0)
            shed = await svc.submit_predict(req())
            assert isinstance(shed, ShedResponse)
            assert shed.request_class == "predict"
            with pytest.raises(UsageError):
                await svc.submit_predict(req(), strict=True)
            return await t1

        res = asyncio.run(go())
        assert not getattr(res, "shed", False)

    def test_coalesced_batch_compile_attribution(self, model_a):
        """Batch-mates share one dispatch: every member reports the
        shared batch size, and compiles land on the FIRST member only
        (the fit door's accounting discipline)."""
        cache = _small_cache(model_a)
        svc = service.TimingService(service.ServeConfig(
            time_buckets=(16,), batch_buckets=(1, 4), window_ms=5.0))
        svc.register_predictor(cache, warm=False)
        lo, hi = cache.coverage()

        async def go():
            ts = [asyncio.ensure_future(svc.submit_predict(
                PredictRequest(
                    np.linspace(lo + 1e-4, hi - 1e-4, 8),
                    request_id=f"c{i}")))
                for i in range(3)]
            return await asyncio.gather(*ts)

        out = asyncio.run(go())
        assert [r.batch for r in out] == [3, 3, 3]
        assert all(r.compiles == 0 for r in out[1:])

    def test_predict_events_validate_live(self, tmp_path, model_a):
        """End to end: the door's predict_serve emission AND the
        cache's predictor_cache hit/miss/regenerate emissions pass the
        telemetry_report --check contracts."""
        from pint_tpu import telemetry
        from pint_tpu.telemetry import runlog
        from tools.telemetry_report import validate_run_dir

        cache = _small_cache(model_a)
        svc = service.TimingService(service.ServeConfig(
            time_buckets=(16,), batch_buckets=(1, 2)))
        svc.register_predictor(cache, warm=False)
        lo, hi = cache.coverage()
        t = np.linspace(lo + 1e-4, hi - 1e-4, 8)
        run_dir = str(tmp_path / "run")
        telemetry.activate("full")
        try:
            runlog.start_run(run_dir, name="predict-events",
                             probe_device=False)
            svc.serve_predicts([PredictRequest(t)])   # miss+regen
            svc.serve_predicts([PredictRequest(t)])   # hit
            cache.invalidate_span(lo, hi)             # invalidate
            runlog.end_run()
        finally:
            telemetry.deactivate()
        errors = []
        validate_run_dir(run_dir, errors)
        assert not errors, errors


# ---------------------------------------------------------------------------
# warm path: AOT cache -> clear_caches -> all-hit re-warm -> compiles == 0
# ---------------------------------------------------------------------------

class TestWarmPath:
    def test_clear_caches_all_hit_rewarm_zero_compiles(self, aot_dir,
                                                       model_a):
        """The acceptance pin: the first service's warm populates the
        AOT cache cold; after ``jax.clear_caches()`` a FRESH pool
        re-warms all-hit, and a coalesced predict batch through the
        re-warmed service pays zero fresh XLA compiles."""
        import jax

        from pint_tpu.telemetry import jaxevents

        cfg = service.ServeConfig(time_buckets=(16,),
                                  batch_buckets=(1, 2), window_ms=1.0)
        svc1 = service.TimingService(cfg)
        c1 = _small_cache(model_a)
        svc1.register_predictor(c1, warm=False)
        rep1 = svc1.warm_predict()
        assert rep1.entries, "warm_predict must register executables"
        assert rep1.cold_compiles == len(rep1.entries)
        c1.build()
        lo, hi = c1.coverage()
        reqs = [PredictRequest(
            np.linspace(lo + 1e-4, hi - 1e-4, 10),
            request_id=f"w{i}") for i in range(4)]
        out1 = svc1.serve_predicts(reqs)

        jax.clear_caches()
        svc2 = service.TimingService(cfg)     # fresh WarmPool
        c2 = _small_cache(model_a)
        svc2.register_predictor(c2, warm=False)
        rep2 = svc2.warm_predict()
        assert rep2.cache_hits == len(rep2.entries), \
            f"expected all-hit re-warm, got {rep2.to_dict()}"
        assert rep2.cold_compiles == 0
        c2.build()                            # pooled fit dispatch
        before = jaxevents.counts()
        out2 = svc2.serve_predicts(reqs)
        delta = jaxevents.counts() - before
        assert delta.compiles == 0, \
            "steady-state predict batch must pay zero fresh compiles"
        assert all(r.compiles == 0 for r in out2)
        for a, b in zip(out1, out2):
            assert np.array_equal(a.phase_int, b.phase_int)
            assert np.array_equal(a.phase_frac, b.phase_frac)
            assert np.array_equal(a.freq, b.freq)

    def test_schema_only_vkey_shared_across_pulsars(self, aot_dir,
                                                    model_a, model_b):
        """The predict executables are parameter-independent, so one
        pulsar's AOT population re-warms ALL-HIT for a different
        pulsar (the schema-only vkey discipline)."""
        import jax

        cfg = service.ServeConfig(time_buckets=(16,),
                                  batch_buckets=(1, 2))
        svc1 = service.TimingService(cfg)
        svc1.register_predictor(_small_cache(model_a), warm=False)
        rep1 = svc1.warm_predict()
        assert rep1.cold_compiles == len(rep1.entries)
        jax.clear_caches()
        svc2 = service.TimingService(cfg)
        svc2.register_predictor(_small_cache(model_b), warm=False)
        rep2 = svc2.warm_predict()
        assert rep2.cache_hits == len(rep2.entries)
        assert rep2.cold_compiles == 0


# ---------------------------------------------------------------------------
# loadgen: the read class in a mixed traffic run
# ---------------------------------------------------------------------------

class _StubFlowSpec:
    def suffix(self):
        return ""


class _StubFlow:
    spec = _StubFlowSpec()


class _StubPosterior:
    """The minimal posterior-door surface (test_loadgen's stub): the
    mixed run needs the door's scheduling, not a trained flow."""

    ndim = 2
    params = np.zeros(1)
    flow = _StubFlow()

    def ident(self):
        return "stub"

    def serve_vkey(self):
        return ("stub",)

    def draw_kernel(self, n):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(params, keys):
            return jnp.zeros((keys.shape[0], n, self.ndim))

        return fn

    def logprob_kernel(self, n):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def fn(params, pts):
            return jnp.zeros(pts.shape[:2])

        return fn


class TestLoadMixWithPredict:
    def test_predict_mix_requires_registered_predictor(self):
        from pint_tpu.serving.loadgen import LoadConfig, LoadGenerator

        svc = service.TimingService(service.ServeConfig(
            ntoa_buckets=(64,), nfree_buckets=(8,)))
        with pytest.raises(UsageError):
            LoadGenerator(svc, LoadConfig(mix={"predict": 1.0}))

    def test_mixed_run_meets_predict_slo(self, model_a):
        """The acceptance pin: a closed-loop mixed run including the
        ``predict`` class completes with balanced per-class shed
        accounting, zero stranded futures, and predict p99 inside the
        scheduler's deadline budget."""
        from pint_tpu.serving.loadgen import (
            LoadConfig,
            LoadGenerator,
            ShapePopulation,
        )

        svc = service.TimingService(service.ServeConfig(
            ntoa_buckets=(64,), nfree_buckets=(8,),
            batch_buckets=(1, 4, 16), draw_buckets=(32,),
            time_buckets=(16, 64), window_ms=1.0, max_queue=256))
        svc.register_posterior(_StubPosterior(), seed=0)
        cache = _small_cache(model_a)
        svc.register_predictor(cache, warm=True)
        cache.build()
        # steady state: pre-compile the write-class buckets too, so
        # predict p99 measures arbitration, not first-call compiles
        # blocking the loop (the fairness test's discipline)
        svc.warm([(b, 64, 8) for b in (1, 4, 16)])
        svc.warm_posterior([(b, 32) for b in (1, 4, 16)])
        shapes = ShapePopulation.synthetic(n=4, seed=2, n_predict=3)
        cfg = LoadConfig(arrival="closed", concurrency=4,
                         n_requests=48,
                         mix={"fit": 2.0, "posterior": 1.0,
                              "predict": 3.0},
                         seed=11, posterior_draws=8)
        rep = LoadGenerator(svc, cfg, shapes=shapes).run()
        assert rep.offered == 48
        assert rep.completed + rep.shed == rep.offered
        assert rep.stranded == 0
        for klass, c in rep.per_class.items():
            assert c["completed"] + c["shed"] == c["offered"], \
                f"{klass} accounting unbalanced: {c}"
        pc = rep.per_class["predict"]
        assert pc["offered"] > 0 and pc["completed"] > 0
        budget = svc.scheduler.deadline_ms("predict")
        p99 = svc.predict_latency_summary()["p99_ms"]
        assert p99 < budget, \
            f"predict p99 {p99:.1f} ms past the {budget} ms budget"
