"""Epoch-move surgery and the phase/time derivative: ``change_pepoch``,
``change_posepoch``, ``change_dmepoch``, ``change_binary_epoch`` keep the
physical model invariant while re-referencing its Taylor expansions
(reference ``tests/test_change_epoch.py``), and ``d_phase_d_toa`` returns
the topocentric spin frequency (reference ``timing_model.py:1962``).
"""

import copy
import io

import numpy as np
import pytest

DD_PAR = """
PSR  J1234+5678
RAJ  12:34:00
DECJ 56:47:00
PMRA  5.0
PMDEC -3.0
POSEPOCH 55000
F0   218.8 1
F1   -4.0e-16 1
PEPOCH 55000
DM   10.5
DM1  3.0e-4
DM2  -1.0e-5
DMEPOCH 55000
BINARY DD
PB   12.327 1
PBDOT 2.0e-12
A1   9.2 1
A1DOT 1.0e-14
T0   55000.1 1
ECC  0.17
EDOT 3.0e-16
OM   100.0
OMDOT 0.01
UNITS TDB
"""

ELL1_FB_PAR = """
PSR  J2222-3333
RAJ  22:22:00
DECJ -33:33:00
F0   400.0 1
PEPOCH 55000
DM   5.0
BINARY ELL1
FB0  2.1e-5 1
FB1  -3.0e-19
A1   1.9 1
TASC 55000.05 1
EPS1 1.0e-5
EPS2 -2.0e-5
EPS1DOT 1.0e-17
EPS2DOT -2.0e-17
UNITS TDB
"""


def _get(par):
    from pint_tpu.models import get_model

    return get_model(io.StringIO(par))


@pytest.fixture(scope="module")
def dd_model():
    return _get(DD_PAR)


@pytest.fixture(scope="module")
def fake_toas(dd_model):
    from pint_tpu.simulation import make_fake_toas_uniform

    return make_fake_toas_uniform(54800, 55400, 24, dd_model, obs="gbt")


class TestChangePepoch:
    def test_f0_advances_along_f1(self, dd_model):
        m = copy.deepcopy(dd_model)
        dt_s = (56000.0 - 55000.0) * 86400.0
        expect_f0 = float(m.F0.value) + float(m.F1.value) * dt_s
        m.change_pepoch(56000.0)
        assert float(m.PEPOCH.value) == 56000.0
        assert float(m.F0.value) == pytest.approx(expect_f0, rel=0, abs=1e-13)

    def test_phase_invariant(self, dd_model, fake_toas):
        m = copy.deepcopy(dd_model)
        ph0 = dd_model.phase(fake_toas)
        m.change_pepoch(55200.0)
        ph1 = m.phase(fake_toas)
        d = (np.asarray(ph1.int_) - np.asarray(ph0.int_)) \
            + (np.asarray(ph1.frac) - np.asarray(ph0.frac))
        # the F-ladder re-referencing is exact up to float64 roundoff of
        # ~1e10-cycle intermediates
        assert np.all(np.abs(d - d[0]) < 1e-4)


class TestChangePosepoch:
    def test_position_advances_along_pm(self, dd_model):
        m = copy.deepcopy(dd_model)
        ra0, dec0 = float(m.RAJ.value), float(m.DECJ.value)
        m.change_posepoch(56000.0)
        masyr_rad = np.pi / 180.0 / 3.6e6
        dt_yr = 1000.0 / 365.25
        assert float(m.POSEPOCH.value) == 56000.0
        assert float(m.DECJ.value) - dec0 == pytest.approx(
            -3.0 * masyr_rad * dt_yr, rel=1e-9)
        assert float(m.RAJ.value) - ra0 == pytest.approx(
            5.0 * masyr_rad * dt_yr / np.cos(dec0), rel=1e-9)

    def test_direction_invariant(self, dd_model):
        m = copy.deepcopy(dd_model)
        comp = m.components["AstrometryEquatorial"]
        pv0 = m._const_pv()
        v_before = np.asarray(comp.ssb_to_psb_xyz(pv0, np.array([56321.0])))
        m.change_posepoch(55900.0)
        pv1 = m._const_pv()
        v_after = np.asarray(comp.ssb_to_psb_xyz(pv1, np.array([56321.0])))
        # moving the reference point along the model's own linearization
        # keeps the evaluated direction fixed to second order in PM*dt
        assert np.all(np.abs(v_after - v_before) < 1e-11)

    def test_unset_raises(self):
        m = _get("PSR X\nRAJ 1:00:00\nDECJ 2:00:00\nF0 1\nPEPOCH 55000\n"
                 "UNITS TDB\n")
        with pytest.raises(ValueError):
            m.change_posepoch(56000.0)


class TestChangeDmepoch:
    def test_dm_advances(self, dd_model):
        m = copy.deepcopy(dd_model)
        dt_yr = 1000.0 / 365.25
        expect = 10.5 + 3.0e-4 * dt_yr - 0.5e-5 * dt_yr**2
        m.change_dmepoch(56000.0)
        assert float(m.DMEPOCH.value) == 56000.0
        assert float(m.DM.value) == pytest.approx(expect, rel=0, abs=1e-9)

    def test_dm_curve_invariant(self, dd_model, fake_toas):
        m = copy.deepcopy(dd_model)
        dm0 = dd_model.total_dm(fake_toas)
        m.change_dmepoch(55321.0)
        assert np.all(np.abs(m.total_dm(fake_toas) - dm0) < 1e-8)

    def test_unset_with_derivs_raises(self):
        m = _get("PSR X\nRAJ 1:00:00\nDECJ 2:00:00\nF0 1\nPEPOCH 55000\n"
                 "DM 10\nUNITS TDB\n")
        m.DM1.value = 7.0
        with pytest.raises(ValueError):
            m.change_dmepoch(56000.0)

    def test_unset_without_derivs_sets(self):
        m = _get("PSR X\nRAJ 1:00:00\nDECJ 2:00:00\nF0 1\nPEPOCH 55000\n"
                 "DM 10\nUNITS TDB\n")
        m.change_dmepoch(56000.0)
        assert float(m.DMEPOCH.value) == 56000.0
        assert float(m.DM.value) == 10.0


class TestChangeBinaryEpoch:
    @pytest.mark.parametrize("par,epoch_name", [(DD_PAR, "T0"),
                                                (ELL1_FB_PAR, "TASC")])
    def test_epoch_moves_by_integer_orbits(self, par, epoch_name):
        m = _get(par)
        old_epoch = float(getattr(m, epoch_name).value)
        if m.PB.value is not None:
            pb = float(m.PB.value)
            pbdot = float(m.PBDOT.value or 0.0)
        else:
            pb = 1.0 / float(m.FB0.value) / 86400.0
            pbdot = -float(m.FB1.value) / float(m.FB0.value) ** 2
        m.change_binary_epoch(56000.0)
        new_epoch = float(getattr(m, epoch_name).value)
        elapsed = new_epoch - old_epoch
        periods = elapsed / (pb + pbdot * elapsed / 2.0)
        assert abs(periods - round(periods)) < 1e-6
        assert round(periods) != 0
        # the new epoch is the orbit boundary closest to the request
        assert abs(new_epoch - 56000.0) <= pb / 2 * (1 + 1e-8)

    def test_secular_parameters_advance(self, dd_model):
        m = copy.deepcopy(dd_model)
        t0_before = float(m.T0.value)
        ecc_b, om_b, a1_b, pb_b = (float(m.ECC.value), float(m.OM.value),
                                   float(m.A1.value), float(m.PB.value))
        m.change_binary_epoch(56000.0)
        dt_d = float(m.T0.value) - t0_before
        # stored rate values are plain SI (tempo 1e-12 unit_scale only
        # rescales suspiciously-large par-file entries at parse time)
        assert float(m.ECC.value) - ecc_b == pytest.approx(
            3.0e-16 * dt_d * 86400.0, rel=1e-9)
        assert float(m.OM.value) - om_b == pytest.approx(
            0.01 * dt_d / 365.25, rel=1e-9)
        assert float(m.A1.value) - a1_b == pytest.approx(
            1.0e-14 * dt_d * 86400.0, rel=1e-9)
        assert float(m.PB.value) - pb_b == pytest.approx(
            2.0e-12 * dt_d, rel=1e-9)

    def test_fb_ladder_advances(self):
        m = _get(ELL1_FB_PAR)
        tasc_before = float(m.TASC.value)
        fb0_b, fb1_b = float(m.FB0.value), float(m.FB1.value)
        m.change_binary_epoch(56000.0)
        dt_s = (float(m.TASC.value) - tasc_before) * 86400.0
        assert float(m.FB0.value) == pytest.approx(fb0_b + fb1_b * dt_s,
                                                   rel=0, abs=1e-24)
        assert float(m.FB1.value) == fb1_b

    def test_noop_when_within_half_orbit(self, dd_model):
        m = copy.deepcopy(dd_model)
        t0 = float(m.T0.value)
        m.change_binary_epoch(t0 + 0.3 * float(m.PB.value))
        assert float(m.T0.value) == t0

    def test_delay_invariant(self, dd_model, fake_toas):
        m = copy.deepcopy(dd_model)
        d0 = np.asarray(dd_model.delay(fake_toas))
        m.change_binary_epoch(55150.0)
        d1 = np.asarray(m.delay(fake_toas))
        # exact up to the second-order PBDOT/EDOT/OMDOT cross terms the
        # reference also drops (~rate * dt^2 / PB)
        assert np.max(np.abs(d1 - d0)) < 1e-7


class TestPb:
    def test_pb_path_with_pbdot(self, dd_model):
        import copy

        m = copy.deepcopy(dd_model)
        m.PB.uncertainty = 1e-6
        m.PBDOT.uncertainty = 1e-13
        v, e = m.pb()
        assert v == pytest.approx(12.327, rel=1e-12)
        assert e == pytest.approx(1e-6, rel=1e-9)  # dt=0: only sigma_PB
        dt = 1000.0
        v2, e2 = m.pb(55000.1 + dt)
        assert v2 == pytest.approx(12.327 + 2.0e-12 * dt, rel=1e-12)
        assert e2 == pytest.approx(np.hypot(1e-6, 1e-13 * dt), rel=1e-9)

    def test_fb_path(self):
        m = _get(ELL1_FB_PAR)
        v, e = m.pb()
        assert v == pytest.approx(1.0 / 2.1e-5 / 86400.0, rel=1e-12)
        assert e is None
        dt_d = 500.0
        v2, _ = m.pb(float(m.TASC.value) + dt_d)
        f = 2.1e-5 + (-3.0e-19) * dt_d * 86400.0
        assert v2 == pytest.approx(1.0 / f / 86400.0, rel=1e-12)

    def test_vector_times(self, dd_model):
        t = np.array([55000.1, 55100.1, 55200.1])
        v, _ = dd_model.pb(t)
        assert v.shape == (3,)
        assert np.all(np.diff(v) > 0)  # PBDOT > 0


class TestDPhaseDToa:
    def test_matches_f0_scale(self, dd_model, fake_toas):
        f = dd_model.d_phase_d_toa(fake_toas)
        assert f.shape == (fake_toas.ntoas,)
        # topocentric frequency = F0 modulated by binary (~a1/pb*c ~ 1e-4)
        # and Earth Doppler (~1e-4) terms
        assert np.all(np.abs(f / 218.8 - 1.0) < 1e-3)
        assert np.std(f) > 0  # the modulation is really there

    def test_step_insensitive(self, dd_model, fake_toas):
        f1 = dd_model.d_phase_d_toa(fake_toas)
        f2 = dd_model.d_phase_d_toa(fake_toas, sample_step=5.0 / 218.8)
        assert np.all(np.abs(f2 - f1) < 1e-6 * np.abs(f1))

    def test_isolated_pulsar_is_doppler_only(self):
        m = _get("PSR X\nRAJ 6:00:00\nDECJ 10:00:00\nF0 100.0\n"
                 "PEPOCH 55100\nDM 10\nUNITS TDB\n")
        from pint_tpu.simulation import make_fake_toas_uniform

        t = make_fake_toas_uniform(55000, 55365, 12, m, obs="gbt")
        f = m.d_phase_d_toa(t)
        # Earth orbital velocity: |v.n|/c <= ~1.07e-4
        frac = f / 100.0 - 1.0
        assert np.all(np.abs(frac) < 1.2e-4)
        # annual modulation should cross a decent fraction of that range
        assert np.ptp(frac) > 2e-5
