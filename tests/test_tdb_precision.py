"""TDB precision (VERDICT r2 directive #3): kernel time-ephemeris segments
and the topocentric TDB term.

No ERFA exists in this image to generate true dtdb values, so precision is
pinned differentially: (a) a synthetic SPK 't' kernel with a KNOWN TDB-TT
function must round-trip through ``SPKEphemeris.tdb_minus_tt`` and the full
``get_TDBs`` chain at the ns level (this is the ns-exact production path —
DE430t/DE440t kernels carry the integrated time ephemeris, better than the
reference's ERFA analytic series); (b) the observatory topocentric term
(v_earth . r_site / c^2, ~2.1 us diurnal — reference gets it inside ERFA
dtdb, ``observatory/__init__.py:443``) must match an independent evaluation
and show the right amplitude/diurnal signature.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from test_synthetic_spk import _write_spk  # noqa: E402

DAY_S = 86400.0
J2000 = 51544.5


def _tdbtt_truth(et):
    """A known smooth TDB-TT-like function [s] of TDB seconds past J2000."""
    w = 2 * np.pi / (365.25 * DAY_S)
    return (1.657e-3 * np.sin(w * et + 1.2)
            + 2.2e-5 * np.sin(2 * w * et + 0.4) - 7.3e-5)


@pytest.fixture
def t_kernel(tmp_path):
    """Synthetic kernel: planets (type 2) + a TDB-TT segment fitted to the
    known truth function with degree-12 Chebyshev records."""
    from numpy.polynomial import chebyshev as C

    init = (54000.0 - J2000) * DAY_S
    intlen = 32.0 * DAY_S
    n_rec, ncoef = 40, 13
    recs = np.zeros((n_rec, 2 + ncoef))
    for i in range(n_rec):
        mid = init + (i + 0.5) * intlen
        radius = intlen / 2.0
        recs[i, 0], recs[i, 1] = mid, radius
        xs = np.cos(np.pi * (np.arange(2 * ncoef) + 0.5) / (2 * ncoef))
        recs[i, 2:] = C.chebfit(xs, _tdbtt_truth(mid + radius * xs), ncoef - 1)
    # a minimal earth/sun set so the kernel also serves posvel
    rng = np.random.default_rng(1)
    from test_synthetic_spk import _cheb_records

    segs = [
        dict(target=3, center=0, dtype=2, init=init, intlen=intlen,
             records=_cheb_records(rng, n_rec, 8, init, intlen, scale=1.5e8)),
        dict(target=399, center=3, dtype=2, init=init, intlen=intlen,
             records=_cheb_records(rng, n_rec, 8, init, intlen, scale=4.5e5)),
        dict(target=10, center=0, dtype=2, init=init, intlen=intlen,
             records=_cheb_records(rng, n_rec, 8, init, intlen, scale=1e6)),
        dict(target=1000000001, center=1000000000, dtype=2, init=init,
             intlen=intlen, records=recs),
    ]
    path = str(tmp_path / "de998t.bsp")
    _write_spk(path, segs)
    return path


class TestKernelTDB:
    def test_segment_roundtrip_ns(self, t_kernel):
        from pint_tpu.ephemeris import SPKEphemeris

        eph = SPKEphemeris(t_kernel)
        assert eph.has_tdb_tt()
        tt = 54100.0 + np.linspace(0, 1000, 300)
        got = eph.tdb_minus_tt(tt)
        want = _tdbtt_truth((tt - J2000) * DAY_S)
        assert np.max(np.abs(got - want)) < 1e-9  # ns-level round trip

    def test_timescales_prefers_kernel(self, t_kernel, monkeypatch):
        import pint_tpu.ephemeris as em
        from pint_tpu.timescales import tdb_minus_tt, tdb_minus_tt_series

        monkeypatch.setitem(em._loaded, "de998t", em.SPKEphemeris(t_kernel))
        tt = np.array([54321.0, 54700.5])
        got = tdb_minus_tt(tt, ephem="DE998T")
        want = _tdbtt_truth((tt - J2000) * DAY_S)
        assert np.allclose(got, want, atol=1e-9)
        # and it really is a different source than the series
        assert not np.allclose(got, tdb_minus_tt_series(tt), atol=1e-6)

    def test_full_chain_uses_kernel(self, t_kernel, monkeypatch, tmp_path):
        """get_TOAs -> compute_TDBs picks up the kernel's time ephemeris."""
        import pint_tpu.ephemeris as em
        from pint_tpu.timescales import tt_minus_utc, utc_to_tt_mjd
        from pint_tpu.toa import get_TOAs

        monkeypatch.setitem(em._loaded, "de998t", em.SPKEphemeris(t_kernel))
        mjds = np.array([54200.3, 54800.7])
        lines = ["FORMAT 1\n"] + [
            f"k{i} 1400.0 {m:.13f} 1.0 geocenter\n" for i, m in enumerate(mjds)]
        p = tmp_path / "k.tim"
        p.write_text("".join(lines))
        t = get_TOAs(str(p), ephem="DE998T", include_gps=False,
                     include_bipm=False)
        tt = np.asarray(utc_to_tt_mjd(mjds), np.float64)
        want = _tdbtt_truth((tt - J2000) * DAY_S)
        got = (np.asarray(t.tdb, np.longdouble)
               - np.asarray(utc_to_tt_mjd(mjds), np.longdouble)) * 86400.0
        assert np.allclose(np.asarray(got, np.float64), want, atol=1e-8)

    def test_tt_minus_tdb_convention_autodetected(self, tmp_path):
        """A kernel storing TT-TDB (the opposite convention) must come out
        sign-corrected: the annual-term correlation against the analytic
        series disambiguates (kernels agree with the series at ~10 us)."""
        from numpy.polynomial import chebyshev as C

        from pint_tpu.ephemeris import SPKEphemeris
        from pint_tpu.timescales import tdb_minus_tt_series

        init = (54000.0 - J2000) * DAY_S
        intlen = 32.0 * DAY_S
        n_rec, ncoef = 40, 13
        recs = np.zeros((n_rec, 2 + ncoef))
        for i in range(n_rec):
            mid = init + (i + 0.5) * intlen
            recs[i, 0], recs[i, 1] = mid, intlen / 2.0
            xs = np.cos(np.pi * (np.arange(2 * ncoef) + 0.5) / (2 * ncoef))
            # store MINUS the true TDB-TT (i.e. TT-TDB); use the real series
            # so the annual phase matches physical kernels
            recs[i, 2:] = C.chebfit(
                xs, -tdb_minus_tt_series(
                    (mid + intlen / 2.0 * xs) / DAY_S + J2000), ncoef - 1)
        path = str(tmp_path / "flip.bsp")
        _write_spk(path, [dict(target=1000000001, center=1000000000, dtype=2,
                               init=init, intlen=intlen, records=recs)])
        eph = SPKEphemeris(path)
        tt = 54100.0 + np.linspace(0, 800, 50)
        got = eph.tdb_minus_tt(tt)
        want = tdb_minus_tt_series(tt)
        assert np.allclose(got, want, atol=1e-7)  # sign came out corrected

    def test_explicit_provider_wins(self, t_kernel, monkeypatch):
        import pint_tpu.ephemeris as em
        from pint_tpu.timescales import set_tdb_provider, tdb_minus_tt

        monkeypatch.setitem(em._loaded, "de998t", em.SPKEphemeris(t_kernel))
        set_tdb_provider(lambda tt: np.full(np.shape(tt), 42.0))
        try:
            assert tdb_minus_tt(np.array([54300.0]), ephem="DE998T")[0] == 42.0
        finally:
            set_tdb_provider(None)


class TestTopocentricTDB:
    def test_matches_independent_formula(self):
        from pint_tpu.ephemeris import load_ephemeris
        from pint_tpu.observatory import get_observatory

        gbt = get_observatory("gbt")
        utc = np.linspace(55000.0, 55001.0, 25)  # one day, hourly
        topo = gbt._topocentric_tdb_seconds(utc)
        # independent evaluation
        eph = load_ephemeris("DE440")
        _, evel = eph.posvel_ssb("earth", utc + 69.184 / 86400.0)
        gpos_m, _ = gbt.get_gcrs(utc)
        want = np.sum(evel * gpos_m / 1e3, axis=1) / 299792.458**2
        assert np.allclose(topo, want, rtol=0, atol=1e-12)
        # ~2.1 us amplitude, diurnal sign change
        assert 1e-6 < np.max(np.abs(topo)) < 2.3e-6
        assert np.min(topo) < 0 < np.max(topo)

    def test_get_tdbs_includes_topo(self):
        from pint_tpu.observatory import get_observatory
        from pint_tpu.timescales import utc_to_tdb_mjd

        gbt = get_observatory("gbt")
        utc = np.array([55123.25, 55123.75])
        with_topo = gbt.get_TDBs(utc)
        base = utc_to_tdb_mjd(utc)
        diff_s = np.asarray((with_topo - base) * 86400.0, np.float64)
        want = gbt._topocentric_tdb_seconds(utc)
        assert np.allclose(diff_s, want, atol=2e-10)  # longdouble MJD ulp ~5e-10 s
        # offset-seconds (pair pipeline) path carries the same term
        off = gbt.get_TDB_offset_seconds(utc)
        from pint_tpu.timescales import utc_to_tdb_offset_seconds

        assert np.allclose(off - utc_to_tdb_offset_seconds(utc), want,
                           atol=1e-12)

    def test_geocenter_and_barycenter_have_no_topo(self):
        from pint_tpu.observatory import get_observatory
        from pint_tpu.timescales import utc_to_tdb_mjd

        utc = np.array([55123.3])
        ob = get_observatory("geocenter")
        assert np.all(ob.get_TDBs(utc) == utc_to_tdb_mjd(utc))
        # barycentric TOAs are already TDB: identity, no conversion, no topo
        bat = get_observatory("barycenter")
        assert np.all(np.asarray(bat.get_TDBs(utc), np.float64) == utc)


class TestIntegratedTDB:
    def test_close_to_series_but_sharper(self):
        """The integral tracks the 14-term series within its ~10 us
        truncation error, and the anchored offset+rate are ~zero."""
        from pint_tpu.tdb_integrated import IntegratedTDB
        from pint_tpu.timescales import tdb_minus_tt_series

        integ = IntegratedTDB()
        tt = np.linspace(54000.0, 56000.0, 400)
        got = integ(tt)
        d = got - tdb_minus_tt_series(tt)
        assert np.max(np.abs(d)) < 3e-5  # series truncation scale
        # the offset+rate anchor is fit at the FIXED J2000 anchor range
        # (determinism contract); a remote window sees the residual
        # ephemeris-vs-series rate bias accumulating from there —
        # unobservable in timing (absorbed by F0), bounded loosely here
        assert abs(np.mean(d)) < 1e-5
        slope = np.polyfit(tt - tt.mean(), d, 1)[0]
        assert abs(slope * 2000) < 5e-6  # linear drift across the window

    def test_quadrature_converged(self):
        """Halving the integration step changes nothing at the ns level."""
        from pint_tpu.tdb_integrated import IntegratedTDB

        a = IntegratedTDB()
        b = IntegratedTDB()
        b.STEP = 0.0625
        tt = np.linspace(55000.0, 55400.0, 60)
        assert np.max(np.abs(a(tt) - b(tt))) < 1e-9

    def test_window_extension_consistent(self):
        """Extending the window must (a) keep previously served values
        unchanged (a re-anchored offset would act like an inter-site jump)
        and (b) agree with a fresh wide-window integrator up to the
        unobservable offset+rate ambiguity."""
        from pint_tpu.tdb_integrated import IntegratedTDB

        a = IntegratedTDB()
        narrow = np.linspace(55000.0, 55100.0, 21)
        before = a(narrow)
        wide = np.linspace(54500.0, 55600.0, 50)
        got = a(wide)
        assert np.max(np.abs(a(narrow) - before)) < 1e-10  # continuity
        fresh = IntegratedTDB()(wide)
        d = got - fresh
        resid = d - np.polyval(np.polyfit(wide - wide.mean(), d, 1),
                               wide - wide.mean())
        assert np.max(np.abs(resid)) < 2e-9  # equal modulo offset+rate

    def test_history_independence_bit_exact(self):
        """DETERMINISM CONTRACT: the value served for an epoch depends only
        on (ephemeris, epoch), never on the process's query history.  The
        fixed absolute anchor range + absolutely-aligned sample grid make
        extension rebuilds reproduce prior values exactly — without this,
        polycos/TZR phases written by one process disagreed with another
        by tens of us (caught by the polyco walkthrough, r4)."""
        from pint_tpu.tdb_integrated import IntegratedTDB

        t = np.linspace(53800.0, 53801.0, 11)
        # fresh build straight at the target epochs
        direct = IntegratedTDB()(t)
        # build far away first, then extend down to the target epochs
        b = IntegratedTDB()
        b(np.linspace(55000.0, 55001.0, 5))
        via_extension = b(t)
        np.testing.assert_array_equal(direct, via_extension)
        # and a third ordering: target first, then far, then target again
        c = IntegratedTDB()
        first = c(t)
        c(np.linspace(55000.0, 55001.0, 5))
        np.testing.assert_array_equal(c(t), first)
        # epochs BELOW the J2000 anchor: downward extensions must also
        # reproduce prior values bit-for-bit (outward accumulation)
        t_lo = np.linspace(48000.0, 48001.0, 11)
        d1 = IntegratedTDB()(t_lo)
        e = IntegratedTDB()
        e(t_lo)
        e(np.linspace(45000.0, 45001.0, 5))  # extend further down
        np.testing.assert_array_equal(e(t_lo), d1)

    def test_default_chain_uses_integrator(self):
        from pint_tpu.timescales import tdb_minus_tt, tdb_minus_tt_series

        tt = np.array([55200.25])
        got = tdb_minus_tt(tt)
        from pint_tpu.tdb_integrated import integrated_tdb_minus_tt

        assert got[0] == integrated_tdb_minus_tt(tt)[0]
        # and that differs (sub-series-error but nonzero) from the series
        assert got[0] != tdb_minus_tt_series(tt)[0]
