"""On-device TPU precision regression (tunnel-gated) + harness self-test.

VERDICT r4 item 3: the DESIGN.md v5e precision measurements (mul_mod1 phase
agreement, delay bounds, grid-chi2 parity) must be re-assertable, not
measured-once.  The real assertion runs ``tools/tpu_precision_check.py
--auto`` on the live tunnel; it is opt-in via ``PINT_TPU_TESTS=1`` because a
wedged tunnel HANGS ``jax.devices()`` for ~25 min (BENCH_NOTES.md) — a
default test run must never gamble on that, and the tunnel lease is
exclusive (concurrent TPU clients wedge it).

The CPU self-test below always runs: it exercises the full two-pass dump/
compare machinery with both passes pinned to the host CPU, where every
deviation must be exactly zero.  A bug in the harness (array mismatch, key
drift, JSON contract) fails here without needing hardware.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "tpu_precision_check.py")


def _clean_env():
    """Subprocess env without the conftest's CPU-forcing knobs."""
    env = dict(os.environ)
    for k in ("JAX_PLATFORMS", "XLA_FLAGS"):
        env.pop(k, None)
    return env


def test_precision_harness_cpu_self_consistent(tmp_path):
    """CPU vs CPU through the real dump/compare path: all deviations 0."""
    ref = tmp_path / "ref.npz"
    env = dict(os.environ)  # CPU pass: keep the conftest forcing
    subprocess.run(
        [sys.executable, TOOL, "--cpu", "--dump", str(ref), "--skip-b1855"],
        check=True, env=env, cwd=REPO, timeout=900)
    p = subprocess.run(
        [sys.executable, TOOL, "--cpu", "--compare", str(ref),
         "--skip-b1855"],
        check=True, env=env, cwd=REPO, timeout=900, capture_output=True,
        text=True)
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["ok"], out
    for name, c in out["checks"].items():
        assert c["value"] == 0.0, (name, c)


@pytest.mark.skipif(
    not os.environ.get("PINT_TPU_TESTS"),
    reason="opt-in (PINT_TPU_TESTS=1): needs exclusive live axon tunnel; "
           "a wedged tunnel hangs jax.devices() ~25 min",
)
def test_tpu_precision_bounds(tmp_path):
    """The DESIGN.md bounds, asserted on the live TPU behind the tunnel."""
    p = subprocess.run(
        [sys.executable, TOOL, "--auto",
         "--dump", str(tmp_path / "ref.npz")],
        env=_clean_env(), cwd=REPO, timeout=3000, capture_output=True,
        text=True)
    sys.stderr.write(p.stderr[-2000:])
    assert p.returncode == 0, p.stdout + p.stderr[-2000:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["platform"] in ("tpu", "axon")
    assert out["ok"], out["checks"]
